import sys
import time

from spark_rapids_tpu.session import TpuSession
from spark_rapids_tpu.workloads import tpch

tables = tpch.gen_tables(1 << 20, seed=42)
tpu = TpuSession({'spark.rapids.sql.enabled': True,
                  'spark.rapids.sql.variableFloatAgg.enabled': True})
t0 = time.perf_counter()
tpu_t = tpch.load(tpu, tables)
print('load+upload: %.1fs' % (time.perf_counter() - t0), flush=True)
names = sys.argv[1:] or sorted(tpch.QUERIES)
for name in names:
    q = tpch.QUERIES[name]
    t0 = time.perf_counter()
    r = q(tpu_t).collect()
    print(name, 'warmup %.1fs' % (time.perf_counter() - t0), r.num_rows,
          'rows', flush=True)
    t0 = time.perf_counter()
    q(tpu_t).collect()
    print(name, 'run %.2fs' % (time.perf_counter() - t0), flush=True)
