"""Benchmark: TPC-H-like suite + TPCxBB-like scoring query, device vs the
CPU oracle — BASELINE.md configs 1-3 (the reference's own harnesses are
TpchLikeSpark / TpcxbbLikeSpark; its headline chart is the TPCxBB-like
suite). The metric is the suite GEOMEAN, matching BASELINE.md's stated
"geomean query time" metric.

Prints exactly one JSON line:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}

Resilience contract (the driver parses stdout's last JSON line): this
script ALWAYS emits a valid JSON line and exits 0. If the TPU backend is
unreachable (probed in a short subprocess so a hanging backend init can't
wedge this process — the reference likewise fails fast on executor init,
Plugin.scala:130-137), the whole benchmark re-runs on the CPU XLA backend
and the JSON carries an "error" field saying so.

Methodology (TPC practice + the reference's CPU-vs-accelerator compare):
tables load once per engine — ``df.cache()`` pins them host-side for the
CPU oracle and HBM-resident for the TPU. Each query runs once for compile
warmup WITH a full-row correctness gate against the oracle, then is timed
end-to-end (plan -> execute -> result download), median of 3.
value = geomean TPU time; vs_baseline = geomean(CPU time / TPU time),
>1 = TPU wins.
"""

import argparse
import contextlib
import json
import math
import os
import signal
import subprocess
import sys
import time

PROBE_TIMEOUT_S = 240

#: Suite wall-clock budget (seconds) when --budget is not given: BENCH_r05
#: was killed by an external timeout (rc=124, bb_q01 spent 646s in
#: warmup+compile); the budget makes the suite skip over-budget work and
#: ALWAYS emit its JSON instead.
DEFAULT_BUDGET_S = 2400.0
#: Per-query ceiling (seconds) on warmup+correctness+timing for one query.
DEFAULT_QUERY_BUDGET_S = 600.0


class QueryBudgetExceeded(Exception):
    """Raised by the SIGALRM guard when one query overruns its budget."""


@contextlib.contextmanager
def query_budget(seconds):
    """Bound one query's warmup+timing with a SIGALRM (main thread only;
    no-op where unavailable). A query that overruns raises
    QueryBudgetExceeded at the next Python bytecode, is recorded as
    skipped, and the suite moves on — the always-complete contract."""
    if seconds is None or seconds <= 0 or not hasattr(signal, "SIGALRM") \
            or threading_main() is False:
        yield
        return

    def on_alarm(signum, frame):
        raise QueryBudgetExceeded(f"query budget {seconds:.0f}s exceeded")
    old = signal.signal(signal.SIGALRM, on_alarm)
    signal.setitimer(signal.ITIMER_REAL, seconds)
    try:
        yield
    finally:
        signal.setitimer(signal.ITIMER_REAL, 0)
        signal.signal(signal.SIGALRM, old)


def threading_main() -> bool:
    import threading
    return threading.current_thread() is threading.main_thread()


def timed(fn, reps=3):
    import numpy as np
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


def probe_backend() -> str:
    """Check in a throwaway subprocess whether the default JAX backend
    initializes and runs one op. Returns '' on success, else a reason.

    A flapping tunnel must not forfeit the TPU measurement (VERDICT r3
    item 1c): three probes with backoff spread over ~10 minutes before
    falling back to the CPU backend."""
    code = ("import jax, jax.numpy as jnp;"
            "print(jax.devices());"
            "print(int(jnp.arange(8).sum()))")
    reason = ""
    for attempt, backoff_s in enumerate((0, 60, 120)):
        if backoff_s:
            print(f"[bench] tpu probe retry in {backoff_s}s "
                  f"(attempt {attempt + 1}/3): {reason}", file=sys.stderr)
            time.sleep(backoff_s)
        try:
            proc = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True, text=True, timeout=PROBE_TIMEOUT_S,
                cwd=os.path.dirname(os.path.abspath(__file__)))
        except subprocess.TimeoutExpired:
            reason = f"backend probe timed out after {PROBE_TIMEOUT_S}s"
            continue
        if proc.returncode != 0:
            tail = (proc.stderr or proc.stdout).strip().splitlines()[-1:]
            reason = f"backend probe failed (rc={proc.returncode}): " \
                     f"{tail[0] if tail else 'no output'}"
            continue
        return ""
    return reason + " (after 3 probes over ~10min)"


def _geo(xs):
    return math.exp(sum(math.log(x) for x in xs) / len(xs))


def tunnel_diagnostics() -> dict:
    """Measured link characteristics, reported so the artifact is
    interpretable: on the axon tunnel every collect pays one dispatch+
    download round trip, and bandwidth has been observed anywhere from
    2 to 20 MB/s — numbers a colocated deployment would not pay."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    jax.device_get(jnp.arange(8).sum())      # settle/compile
    t0 = time.perf_counter()
    for _ in range(3):
        jax.device_get(jnp.arange(8).sum())
    rt = (time.perf_counter() - t0) / 3
    buf = jnp.zeros((1 << 21,), jnp.int64)   # 16 MB
    jax.device_get(buf)
    t0 = time.perf_counter()
    jax.device_get(buf)
    dl = time.perf_counter() - t0
    return {"backend": jax.default_backend(),
            "tunnel_rt_ms": round(rt * 1e3, 1),
            "tunnel_download_mbps": round(16 / max(dl - rt, 1e-3), 1)}


def run_large_scale(n_rows: int = 1 << 22):
    """Cached-only supplement at 4M lineitem rows: the reference's claim
    is accelerator wins AT SCALE — at 1M rows the per-query round-trip
    floor (~100-200ms on the tunnel) dwarfs compute, at 4M the CPU
    oracle's compute grows 4x while the device pays the same floor.
    Returns the geomean CPU/TPU ratio over q1/q6/q19."""
    from spark_rapids_tpu.session import TpuSession
    from spark_rapids_tpu.workloads import tpch
    tables = tpch.gen_tables(n_rows, seed=42)
    cpu = TpuSession({"spark.rapids.sql.enabled": False})
    tpu = TpuSession({"spark.rapids.sql.enabled": True,
                      "spark.rapids.sql.variableFloatAgg.enabled": True})
    cpu_t = tpch.load(cpu, tables)
    tpu_t = tpch.load(tpu, tables)
    ratios = []
    for name in ("q1", "q6", "q19"):
        q = tpch.QUERIES[name]
        q(tpu_t).collect()                   # warmup + compile
        cpu_time = timed(lambda: q(cpu_t).collect())
        tpu_time = timed(lambda: q(tpu_t).collect())
        ratios.append(cpu_time / tpu_time)
        print(f"[bench] 4M {name}: cpu={cpu_time*1e3:.0f}ms "
              f"tpu={tpu_time*1e3:.0f}ms ratio={cpu_time/tpu_time:.2f}",
              file=sys.stderr)
    return _geo(ratios)


def measure_pipeline_overlap(tpch, tables, timed_fn):
    """ISSUE-5 acceptance probe: cold uncached wall time of the
    multi-boundary join queries q3/q5 with the pipeline layer enabled
    (default) vs spark.rapids.tpu.pipeline.enabled=false, on this bench
    backend. >1 = the pipeline wins; the target deployment (high-latency
    tunnel, where uploads are mostly link waits) is where the overlap
    pays most — a host-saturated CPU backend has little idle to harvest."""
    from spark_rapids_tpu.data import upload_cache
    from spark_rapids_tpu.session import TpuSession
    out = {}
    on = TpuSession({"spark.rapids.sql.enabled": True,
                     "spark.rapids.sql.variableFloatAgg.enabled": True})
    off = on.with_conf(**{"spark.rapids.tpu.pipeline.enabled": False})
    t_on = tpch.load(on, tables, cache=False)
    t_off = tpch.load(off, tables, cache=False)
    for name in ("q3", "q5"):
        q = tpch.QUERIES[name]
        q(t_on).collect()  # shared warmup (same plan shape both modes)
        q(t_off).collect()

        def cold(t):
            upload_cache.clear()
            return q(t).collect()
        t_pipe = timed_fn(lambda: cold(t_on))
        t_serial = timed_fn(lambda: cold(t_off))
        out[f"pipeline_cold_speedup_{name}"] = round(t_serial / t_pipe, 3)
        print(f"[bench] pipeline A/B {name}: on={t_pipe*1e3:.1f}ms "
              f"off={t_serial*1e3:.1f}ms "
              f"speedup={t_serial/t_pipe:.2f}", file=sys.stderr)
    return out


def run_suite(budget_s=DEFAULT_BUDGET_S,
              query_budget_s=DEFAULT_QUERY_BUDGET_S):
    # NOTE: do not enable the persistent executable cache here
    # (spark.rapids.tpu.compileCache.enabled / jax_compilation_cache_dir) —
    # it deadlocks the axon remote-compile helper (observed: queries hang
    # indefinitely), and its XLA-level executable replay can SIGILL on
    # cross-machine AOT artifacts (see spark_rapids_tpu/__init__.py and
    # docs/compile-cache.md).
    from spark_rapids_tpu.session import TpuSession
    from spark_rapids_tpu.utils import kernel_cache as KC
    from spark_rapids_tpu.workloads import tpch
    from spark_rapids_tpu.workloads.compare import tables_match
    suite_t0 = time.perf_counter()
    diag = tunnel_diagnostics()
    print(f"[bench] backend={diag['backend']} rt={diag['tunnel_rt_ms']}ms "
          f"download={diag['tunnel_download_mbps']}MB/s", file=sys.stderr)

    n_li = 1 << 20
    tables = tpch.gen_tables(n_li, seed=42)

    cpu = TpuSession({"spark.rapids.sql.enabled": False})
    # variableFloatAgg: same stance as the reference's benchmarks — float
    # aggregation order differs from CPU (documented incompat,
    # docs/compatibility.md); the correctness gate compares with tolerance.
    # ESSENTIAL metrics so every timed query leaves a QueryProfile
    # (emitted next to the BENCH_*.json artifacts; docs/monitoring.md).
    tpu = TpuSession({"spark.rapids.sql.enabled": True,
                      "spark.rapids.sql.variableFloatAgg.enabled": True,
                      "spark.rapids.tpu.metrics.level": "ESSENTIAL"})
    cpu_t = tpch.load(cpu, tables)
    tpu_t = tpch.load(tpu, tables)
    # UNCACHED variants re-upload per run, so scan+transfer is inside the
    # timed region (the reference's benchmarks pay file scans; VERDICT r3
    # weak-9) — reported alongside the HBM-resident numbers.
    cpu_u = tpch.load(cpu, tables, cache=False)
    tpu_u = tpch.load(tpu, tables, cache=False)

    from spark_rapids_tpu.data import upload_cache

    ratios, tpu_times, uncached_ratios, cold_ratios = [], [], [], []
    # Subset: every operator shape (scan/filter/project/agg, 1-4 joins,
    # semi join, disjunctive band join, conditional sums, float scoring)
    # without double-paying remote-compile time for shapes q5/q3 already
    # cover (q10/q18 re-run under pytest, tests/test_tpch.py).
    bench_queries = ["q1", "q3", "q4", "q5", "q6", "q12", "q14", "q19",
                     "xbb_score"]
    # TPCxBB suite entries (the reference's headline chart is TPCxBB;
    # round-5 adds the basket self-join, ML feature build, and
    # clickstream sessionization shapes from workloads/tpcxbb.py)
    from spark_rapids_tpu.workloads import tpcxbb
    xbb_tables = tpcxbb.gen_tables(1 << 17, seed=42)
    xbb_specs = [("bb_q01", tpcxbb.q01), ("bb_q05", tpcxbb.q05),
                 ("bb_q30", tpcxbb.q30)]
    runs = [(name, tpch.QUERIES[name], cpu_t, tpu_t, cpu_u, tpu_u)
            for name in bench_queries]
    bb_cpu = tpcxbb.load(cpu, xbb_tables)
    bb_tpu = tpcxbb.load(tpu, xbb_tables)
    bb_cpu_u = tpcxbb.load(cpu, xbb_tables, cache=False)
    bb_tpu_u = tpcxbb.load(tpu, xbb_tables, cache=False)
    runs += [(name, q, bb_cpu, bb_tpu, bb_cpu_u, bb_tpu_u)
             for name, q in xbb_specs]
    from spark_rapids_tpu.compile import executables as _executables
    from spark_rapids_tpu.exec import fusion
    profiles = {}
    skipped = {}
    # Per-query compile breakdown (ISSUE 6): compile_seconds,
    # kernels_compiled, executables_reused, cold_vs_cached_ratio land in
    # the BENCH JSON so the win curve is machine-readable (the ROADMAP
    # success metric is cold within 2x of cached, per query).
    query_compile = {}
    for name, q, cpu_t, tpu_t, cpu_u, tpu_u in runs:
        elapsed = time.perf_counter() - suite_t0
        if budget_s and elapsed > budget_s:
            # Wall-clock budget exhausted (rc=124 class of failure in
            # BENCH_r05): record the skip and keep the JSON contract.
            skipped[name] = (f"suite budget {budget_s:.0f}s exhausted "
                             f"after {elapsed:.0f}s; warmup skipped")
            print(f"[bench] SKIP {name}: {skipped[name]}", file=sys.stderr)
            continue
        per_query = query_budget_s
        if budget_s:
            per_query = min(per_query or budget_s, budget_s - elapsed)
        t0 = time.perf_counter()
        try:
            with query_budget(per_query):
                stats0 = KC.cache_stats()
                exe0 = _executables.stats()
                cpu_result = q(cpu_t).collect()       # oracle
                tpu_result = q(tpu_t).collect()       # warmup + compile
                assert tables_match(tpu_result, cpu_result), \
                    f"{name}: TPU result != CPU oracle result"
                stats1 = KC.cache_stats()
                exe1 = _executables.stats()
                cpu_time = timed(lambda: q(cpu_t).collect())
                tpu_time = timed(lambda: q(tpu_t).collect())
                # Per-query QueryProfile of the last timed device run,
                # emitted next to BENCH_*.json (tools/profile_bench.py
                # --compare diffs two bundles for >20% regressions).
                profiles[name] = tpu.last_query_profile()
                # uncached: re-collect over the same (immutable) host
                # tables — the upload memo legally skips re-encoding/
                # re-uploading bytes the device has already seen
                ucpu = timed(lambda: q(cpu_u).collect(), reps=1)
                utpu = timed(lambda: q(tpu_u).collect(), reps=1)
                # cold: upload memo dropped first, so host-side prep +
                # transfer land fully inside the timed region

                def cold_run():
                    upload_cache.clear()
                    return q(tpu_u).collect()
                ctpu = timed(cold_run, reps=1)
        except QueryBudgetExceeded as e:
            skipped[name] = f"{e} (started at {t0 - suite_t0:.0f}s)"
            print(f"[bench] SKIP {name}: {skipped[name]}", file=sys.stderr)
            continue
        ratios.append(cpu_time / tpu_time)
        uncached_ratios.append(ucpu / utpu)
        cold_ratios.append(ucpu / ctpu)
        tpu_times.append(tpu_time)
        reused0 = exe0["aot_hits"] + exe0["jit_calls"] - exe0["jit_compiles"]
        reused1 = exe1["aot_hits"] + exe1["jit_calls"] - exe1["jit_compiles"]
        query_compile[name] = {
            # Fused-program compile time plus host kernel-build time paid
            # by this query's warmup run.
            "compile_seconds": round(
                exe1["compile_seconds"] - exe0["compile_seconds"]
                + (stats1["build_ns"] - stats0["build_ns"]) / 1e9, 3),
            "kernels_compiled": stats1["misses"] - stats0["misses"],
            "fused_compiles": exe1["jit_compiles"] - exe0["jit_compiles"],
            "executables_reused": reused1 - reused0,
            # ROADMAP success metric: cold within 2x of cached (<= 2.0).
            "cold_vs_cached_ratio": round(ctpu / tpu_time, 3),
        }
        # Perf evidence (VERDICT r3 item 1b): kernels compiled for this
        # query's warmup, fused-program count, and steady-state dispatch
        # counts — "compiles and matches" AND "how it runs".
        print(f"[bench] {name}: cpu={cpu_time*1e3:.1f}ms "
              f"tpu={tpu_time*1e3:.1f}ms ratio={cpu_time/tpu_time:.2f} "
              f"uncached_ratio={ucpu/utpu:.2f} cold_ratio={ucpu/ctpu:.2f} "
              f"kernels_compiled={stats1['misses'] - stats0['misses']} "
              f"compile_s={query_compile[name]['compile_seconds']:.1f} "
              f"cold_vs_cached={ctpu/tpu_time:.2f} "
              f"fused_programs={len(fusion._FUSED_CACHE)} "
              f"(warmup+compile {time.perf_counter()-t0:.0f}s)",
              file=sys.stderr)

    # Per-query QueryProfile bundle next to the BENCH_*.json artifacts
    # (best-effort: profiles must never fail the bench contract).
    try:
        from spark_rapids_tpu.metrics.profile import dump_profiles
        prof_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "BENCH_profiles.json")
        dump_profiles(prof_path, profiles)
        print(f"[bench] wrote {len([p for p in profiles.values() if p])} "
              f"query profiles to {prof_path}", file=sys.stderr)
    except Exception as e:  # noqa: BLE001 - observability is best-effort
        print(f"[bench] profile dump failed: {e}", file=sys.stderr)

    # Compile-once layer counters (docs/compile-cache.md): how many fused
    # programs exist, how many AOT executables warm-up built, and how the
    # steady-state dispatches split between the AOT table and jit.
    from spark_rapids_tpu.compile import budget as _compile_budget
    from spark_rapids_tpu.compile import warmup as _compile_warmup
    _aot = _executables.stats()
    print(f"[bench] compile-once: programs={_aot['programs']} "
          f"aot_executables={_aot['aot_executables']} "
          f"aot_hits={_aot['aot_hits']} jit_calls={_aot['jit_calls']} "
          f"fused_compiles={_aot['jit_compiles']} "
          f"compile_seconds={_aot['compile_seconds']:.1f} "
          f"budget={_compile_budget.stats()} "
          f"warmup={_compile_warmup.stats()}", file=sys.stderr)

    if not tpu_times:
        return {
            "metric": "tpch_tpcxbb_geomean_device_time",
            "value": 0.0, "unit": "ms", "vs_baseline": 0.0,
            "skipped": skipped,
            "queries": query_compile,
            "error": "every query skipped by the wall-clock budget",
            **diag,
        }
    geo_t = _geo(tpu_times)
    geo_r = _geo(ratios)
    print(f"[bench] geomean ratio cached={geo_r:.3f} "
          f"uncached={_geo(uncached_ratios):.3f} "
          f"cold={_geo(cold_ratios):.3f} "
          f"(>1 = device wins; cached pins tables HBM-resident, uncached "
          f"re-collects over the same host tables with the upload memo "
          f"warm, cold clears the memo so prep+transfer are fully timed)",
          file=sys.stderr)
    out = {
        "metric": f"tpch_tpcxbb_{len(tpu_times)}q_1Mrow_geomean_device_time",
        "value": round(geo_t * 1000, 2),
        "unit": "ms",
        "vs_baseline": round(geo_r, 3),
        "uncached_vs_baseline": round(_geo(uncached_ratios), 3),
        "cold_vs_baseline": round(_geo(cold_ratios), 3),
        # Per-query compile breakdown + suite compile totals (ISSUE 6):
        # the machine-readable compile win curve.
        "queries": query_compile,
        "compile": {
            "fused_programs": _aot["programs"],
            "fused_compiles": _aot["jit_compiles"],
            "compile_seconds": round(_aot["compile_seconds"], 1),
            "executables_reused": _aot["aot_hits"] + _aot["jit_calls"]
            - _aot["jit_compiles"],
            "cold_vs_cached_geomean": round(_geo(
                [q["cold_vs_cached_ratio"] for q in query_compile.values()
                 if q.get("cold_vs_cached_ratio", 0) > 0] or [1.0]), 3),
        },
        # Durability evidence (ISSUE 7, docs/fault-tolerance.md): the
        # per-query recovery counters from the QueryProfiles. All-zero
        # totals PROVE the run was clean (no silent corruption was
        # retried through); non-zero counters under fault injection prove
        # the recovery machinery actually ran.
        "faults": _fault_section(profiles),
        # Pallas kernel evidence (ISSUE 8, docs/tuning-guide.md): which
        # hand-written kernels served each query (staged launches,
        # compiled pallas programs, fallback reasons). With the gate off
        # (the default) this records {enabled: false} — the per-kernel
        # win curve comes from tools/kernel_bench.py's BENCH_kernels.json.
        "pallas": _pallas_bench_section(profiles),
        **diag,
    }
    if skipped:
        out["skipped"] = skipped
    # Pipelined-execution A/B (ISSUE-5 acceptance): cold q3/q5 with the
    # pipeline on vs off, budget-guarded like everything else.
    if not budget_s or time.perf_counter() - suite_t0 < budget_s:
        try:
            with query_budget(query_budget_s):
                out.update(measure_pipeline_overlap(tpch, tables, timed))
        except Exception as e:  # noqa: BLE001 — incl. QueryBudgetExceeded
            print(f"[bench] pipeline A/B skipped: {e}", file=sys.stderr)
    # Large-scale supplement (skipped if the main suite already consumed
    # the budget — compile time on a cold remote helper can be minutes).
    if time.perf_counter() - suite_t0 < min(1800, budget_s or 1800):
        try:
            with query_budget(query_budget_s):
                out["vs_baseline_4m_cached"] = round(run_large_scale(), 3)
        except Exception as e:  # noqa: BLE001 — incl. QueryBudgetExceeded
            print(f"[bench] 4M supplement failed: {e}", file=sys.stderr)
    return out


def _fault_section(profiles) -> dict:
    """The BENCH JSON ``faults`` section: suite totals + per-query
    durability counters (only queries with any non-zero counter are
    listed — the common all-clean case stays one small totals dict)."""
    totals = {"checksumFailures": 0, "shuffleBlocksRefetched": 0,
              "mapTasksRecomputed": 0, "deadlineCancels": 0,
              "peersBlacklisted": 0}
    per_query = {}
    for qname, p in profiles.items():
        engine = getattr(p, "engine", None) or {}
        dur = engine.get("durability")
        if not dur:
            continue
        counters = {k: int(dur.get(k, 0)) for k in totals}
        for k, v in counters.items():
            totals[k] += v
        if any(counters.values()):
            per_query[qname] = counters
    out = {"totals": totals}
    if per_query:
        out["queries"] = per_query
    return out


def _pallas_bench_section(profiles) -> dict:
    """The BENCH JSON ``pallas`` section: per-kernel suite totals
    (staged launches, compiled programs, fallback reasons) plus the
    per-query kernel breakdown for queries where any Pallas kernel ran
    or fell back — all zeros / empty with the gate off (the default)."""
    totals: dict = {}
    per_query: dict = {}
    enabled = False
    for qname, p in profiles.items():
        engine = getattr(p, "engine", None) or {}
        pal = engine.get("pallas") or {}
        enabled = enabled or bool(pal.get("enabled"))
        kernels = pal.get("kernels") or {}
        if not kernels:
            continue
        per_query[qname] = kernels
        for k, m in kernels.items():
            t = totals.setdefault(k, {"staged": 0, "programsCompiled": 0,
                                      "fallbacks": {}})
            t["staged"] += int(m.get("staged", 0))
            t["programsCompiled"] += int(m.get("programsCompiled", 0))
            for r, n in (m.get("fallbacks") or {}).items():
                t["fallbacks"][r] = t["fallbacks"].get(r, 0) + int(n)
    out = {"enabled": enabled, "totals": totals}
    if per_query:
        out["queries"] = per_query
    return out


def parse_args(argv=None):
    ap = argparse.ArgumentParser(
        description="TPC-H/TPCxBB-like bench (always emits one JSON line, "
                    "always exits 0)")
    ap.add_argument(
        "--budget", type=float,
        default=float(os.environ.get("SPARK_RAPIDS_TPU_BENCH_BUDGET",
                                     DEFAULT_BUDGET_S)),
        help="suite wall-clock budget in seconds; queries whose warmup "
             "would start past it are skipped (recorded per query in the "
             "output JSON). 0 disables.")
    ap.add_argument(
        "--query-budget", type=float,
        default=float(os.environ.get("SPARK_RAPIDS_TPU_BENCH_QUERY_BUDGET",
                                     DEFAULT_QUERY_BUDGET_S)),
        help="per-query ceiling in seconds (SIGALRM-guarded warmup+timing; "
             "an over-budget query is recorded as skipped and the suite "
             "continues). 0 disables.")
    return ap.parse_args(argv)


def main():
    args = parse_args()
    if os.environ.get("SPARK_RAPIDS_TPU_BENCH_CHILD") != "1":
        reason = probe_backend()
        if reason:
            # Accelerator unreachable: rerun this script on the CPU XLA
            # backend in a scrubbed env so a number still lands, and say so.
            # The child gets a hard timeout too — the always-emit-JSON
            # contract must survive a wedged child as well.
            env = dict(os.environ)
            env["JAX_PLATFORMS"] = "cpu"
            env.pop("PALLAS_AXON_POOL_IPS", None)
            # The remote-compile helper can serve XLA:CPU AOT executables
            # built for CPU features this host lacks (SIGILL risk) — the
            # CPU fallback must compile locally.
            env.pop("PALLAS_AXON_REMOTE_COMPILE", None)
            env["JAX_ENABLE_COMPILATION_CACHE"] = "false"
            env["SPARK_RAPIDS_TPU_BENCH_CHILD"] = "1"
            env["SPARK_RAPIDS_TPU_BENCH_BUDGET"] = str(args.budget)
            env["SPARK_RAPIDS_TPU_BENCH_QUERY_BUDGET"] = \
                str(args.query_budget)
            stdout, stderr = "", ""
            try:
                proc = subprocess.run(
                    [sys.executable, os.path.abspath(__file__)], env=env,
                    capture_output=True, text=True, timeout=3000,
                    cwd=os.path.dirname(os.path.abspath(__file__)))
                stdout, stderr = proc.stdout or "", proc.stderr or ""
            except subprocess.TimeoutExpired as te:
                stdout = (te.stdout or b"").decode(errors="replace") \
                    if isinstance(te.stdout, bytes) else (te.stdout or "")
                stderr = f"cpu-fallback child timed out after {te.timeout}s"
            sys.stderr.write(stderr)
            line = None
            for ln in stdout.strip().splitlines():
                try:
                    parsed = json.loads(ln)
                except (json.JSONDecodeError, ValueError):
                    continue
                # json.loads accepts bare scalars; only a dict payload can
                # take the "error" key without breaking the exit-0 contract
                if isinstance(parsed, dict):
                    line = parsed
            if line is None:
                line = {"metric": "tpchlike_geomean_device_time",
                        "value": 0.0, "unit": "ms", "vs_baseline": 0.0}
            line["error"] = (f"tpu backend unreachable ({reason}); "
                             "measured on cpu XLA backend instead")
            print(json.dumps(line))
            return
    try:
        result = run_suite(budget_s=args.budget,
                           query_budget_s=args.query_budget)
    except Exception as e:  # noqa: BLE001 — the JSON line must always land
        import traceback
        traceback.print_exc()
        result = {"metric": "tpchlike_geomean_device_time", "value": 0.0,
                  "unit": "ms", "vs_baseline": 0.0,
                  "error": f"{type(e).__name__}: {e}"}
    print(json.dumps(result))


if __name__ == "__main__":
    main()
