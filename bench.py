"""Benchmark: TPC-DS-q5-shaped query (scan -> join -> group-by aggregate) on
the device vs the CPU oracle — BASELINE.md config 1.

Prints exactly one JSON line:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}

value = device wall time for the query (post-compile, median of 3);
vs_baseline = CPU-oracle time / device time (speedup; >1 means the TPU path
beats the pyarrow CPU path on the same machine). The reference publishes no
machine-readable numbers (BASELINE.md), so the CPU oracle is the baseline we
measure against, exactly like the reference's CPU-Spark-vs-GPU methodology.
"""

import json
import time

import numpy as np


def build_tables(session, n_fact: int, n_dim: int):
    rng = np.random.default_rng(42)
    fact = {
        "k": rng.integers(0, n_dim, n_fact).astype(np.int64).tolist(),
        "q": rng.integers(1, 100, n_fact).astype(np.int64).tolist(),
        "p": rng.integers(1, 1000, n_fact).astype(np.int64).tolist(),
    }
    dim = {
        "k": list(range(n_dim)),
        "cat": rng.integers(0, 20, n_dim).astype(np.int64).tolist(),
    }
    return session.create_dataframe(fact), session.create_dataframe(dim)


def q5_like(session, n_fact: int, n_dim: int):
    from spark_rapids_tpu.ops import aggregates as AGG
    from spark_rapids_tpu.ops import predicates as P
    from spark_rapids_tpu.ops.arithmetic import Multiply
    from spark_rapids_tpu.ops.expression import col, lit

    fact, dim = build_tables(session, n_fact, n_dim)
    return (fact
            .where(P.LessThan(col("q"), lit(95)))
            .with_column("rev", Multiply(col("q"), col("p")))
            .join(dim, on="k", how="inner")
            .group_by(col("cat"))
            .agg(AGG.AggregateExpression(AGG.Sum(col("rev")), "total_rev"),
                 AGG.AggregateExpression(AGG.Count(), "cnt"),
                 AGG.AggregateExpression(AGG.Min(col("p")), "min_p"),
                 AGG.AggregateExpression(AGG.Max(col("q")), "max_q")))


def timed(fn, reps=3):
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


def main():
    import spark_rapids_tpu  # noqa: F401
    from spark_rapids_tpu.session import TpuSession

    n_fact = 1 << 20
    n_dim = 1000

    cpu = TpuSession({"spark.rapids.sql.enabled": False})
    tpu = TpuSession({"spark.rapids.sql.enabled": True})

    cpu_result = q5_like(cpu, n_fact, n_dim).collect()
    tpu_result = q5_like(tpu, n_fact, n_dim).collect()  # warmup + compile
    # Correctness gate: bench numbers are meaningless if results differ.
    c = {tuple(r): None for r in zip(
        *[cpu_result.column(i).to_pylist() for i in range(4)])}
    t = {tuple(r): None for r in zip(
        *[tpu_result.column(i).to_pylist() for i in range(4)])}
    assert c.keys() == t.keys(), "TPU result != CPU oracle result"

    cpu_time = timed(lambda: q5_like(cpu, n_fact, n_dim).collect())
    tpu_time = timed(lambda: q5_like(tpu, n_fact, n_dim).collect())

    print(json.dumps({
        "metric": "q5like_1Mrows_device_time",
        "value": round(tpu_time * 1000, 2),
        "unit": "ms",
        "vs_baseline": round(cpu_time / tpu_time, 3),
    }))


if __name__ == "__main__":
    main()
