"""Benchmark: TPC-H-like suite + TPCxBB-like scoring query, device vs the
CPU oracle — BASELINE.md configs 1-3 (the reference's own harnesses are
TpchLikeSpark / TpcxbbLikeSpark; its headline chart is the TPCxBB-like
suite). The metric is the suite GEOMEAN, matching BASELINE.md's stated
"geomean query time" metric.

Prints one cumulative JSON line after EVERY query plus the final line;
the driver takes stdout's LAST parsed line:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}

Resilience contract: this script ALWAYS leaves a valid JSON line behind
— the per-query checkpoint lines mean even a SIGKILL mid-suite yields
the cumulative totals up to the last completed query (the BENCH_r05
rc=124 parsed:null failure class), and a SIGTERM/normal-exit mid-suite
additionally dumps a final partial line via the installed handlers. If
the TPU backend is unreachable (probed in a short subprocess so a
hanging backend init can't wedge this process — the reference likewise
fails fast on executor init, Plugin.scala:130-137), the whole benchmark
re-runs on the CPU XLA backend and the JSON carries an "error" field
saying so.

Methodology (TPC practice + the reference's CPU-vs-accelerator compare):
generated tables are written to PARQUET once per run and every timed run
SCANS them — the device parquet decoder is inside the headline number
(ISSUE 11 / ROADMAP item 1; BASELINE's configs say "SF=N parquet").
Headline scale is 4M lineitem rows (--rows), where the CPU oracle's
compute grows past the device's fixed round-trip floor. Each query runs
once for compile warmup WITH a full-row correctness gate against the
oracle, then is timed end-to-end (scan -> plan -> execute -> result
download), median of 3. value = geomean TPU time; vs_baseline =
geomean(CPU time / TPU time), >1 = TPU wins; cold_vs_baseline clears the
upload memo first so host prep + transfer are fully timed too.
"""

import argparse
import atexit
import contextlib
import json
import math
import os
import signal
import subprocess
import sys
import tempfile
import time

#: Default headline scale: 4M lineitem rows — at 1M the per-query
#: round-trip floor (~100-200ms on the tunnel) dwarfs compute and the
#: 1M CPU oracle finishes under it; at 4M the device can legitimately win.
DEFAULT_ROWS = 1 << 22

# -- cumulative checkpointing (VERDICT round-5 ask) -------------------------
#: The last cumulative payload emitted; the SIGTERM/atexit dumpers re-emit
#: it with an error note so an external kill can never yield parsed:null.
_CHECKPOINT = {"payload": None, "done": False}

#: cleanups the signal-exit path must run itself: os._exit skips atexit,
#: so anything registered only there (the parquet staging dir rmtree)
#: would leak on every external SIGTERM/timeout kill — the exact rc=124
#: class the kill-dump exists for.
_KILL_CLEANUPS: list = []


def emit_checkpoint(payload: dict) -> None:
    """Print one cumulative JSON line NOW (the driver takes the last
    parsed line, so each checkpoint supersedes the previous one)."""
    payload = dict(payload)
    payload["partial"] = True
    _CHECKPOINT["payload"] = payload
    print(json.dumps(payload), flush=True)


def emit_final(payload: dict) -> None:
    _CHECKPOINT["done"] = True
    print(json.dumps(payload), flush=True)


def install_kill_dump() -> None:
    """SIGTERM/SIGINT + atexit dumpers: re-emit the last cumulative
    checkpoint with an error note, flush, and (for signals) exit — the
    always-emit-JSON contract survives external timeouts."""
    def dump(note: str) -> None:
        if not _CHECKPOINT["done"]:
            # Before the first per-query checkpoint (table gen + parquet
            # write + first warmup can take minutes at 4M rows) there is
            # no cumulative payload yet — a kill there must still leave a
            # parseable line, not rc=0 with no JSON.
            p = dict(_CHECKPOINT["payload"] or
                     {"metric": "tpchlike_geomean_device_time",
                      "value": 0.0, "unit": "ms", "vs_baseline": 0.0,
                      "partial": True})
            p["error"] = note
            print(json.dumps(p), flush=True)
        sys.stdout.flush()

    def on_signal(signum, frame):
        dump(f"killed by signal {signum} mid-suite; cumulative totals up "
             "to the last completed query")
        for fn in list(_KILL_CLEANUPS):  # os._exit skips atexit
            try:
                fn()
            except Exception:
                pass
        os._exit(0)  # exit-0 contract: the JSON just printed is valid
    try:
        signal.signal(signal.SIGTERM, on_signal)
        signal.signal(signal.SIGINT, on_signal)
    except (ValueError, OSError):
        pass  # not the main thread / restricted platform
    atexit.register(
        lambda: dump("process exited mid-suite; cumulative totals up to "
                     "the last completed query"))

PROBE_TIMEOUT_S = 240

#: Suite wall-clock budget (seconds) when --budget is not given: BENCH_r05
#: was killed by an external timeout (rc=124, bb_q01 spent 646s in
#: warmup+compile); the budget makes the suite skip over-budget work and
#: ALWAYS emit its JSON instead.
DEFAULT_BUDGET_S = 2400.0
#: Per-query ceiling (seconds) on warmup+correctness+timing for one query.
DEFAULT_QUERY_BUDGET_S = 600.0


class QueryBudgetExceeded(Exception):
    """Raised by the SIGALRM guard when one query overruns its budget."""


@contextlib.contextmanager
def query_budget(seconds):
    """Bound one query's warmup+timing with a SIGALRM (main thread only;
    no-op where unavailable). A query that overruns raises
    QueryBudgetExceeded at the next Python bytecode, is recorded as
    skipped, and the suite moves on — the always-complete contract."""
    if seconds is None or seconds <= 0 or not hasattr(signal, "SIGALRM") \
            or threading_main() is False:
        yield
        return

    def on_alarm(signum, frame):
        raise QueryBudgetExceeded(f"query budget {seconds:.0f}s exceeded")
    old = signal.signal(signal.SIGALRM, on_alarm)
    signal.setitimer(signal.ITIMER_REAL, seconds)
    try:
        yield
    finally:
        signal.setitimer(signal.ITIMER_REAL, 0)
        signal.signal(signal.SIGALRM, old)


def threading_main() -> bool:
    import threading
    return threading.current_thread() is threading.main_thread()


def timed(fn, reps=3):
    import numpy as np
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


def probe_backend() -> str:
    """Check in a throwaway subprocess whether the default JAX backend
    initializes and runs one op. Returns '' on success, else a reason.

    A flapping tunnel must not forfeit the TPU measurement (VERDICT r3
    item 1c): three probes with backoff spread over ~10 minutes before
    falling back to the CPU backend."""
    code = ("import jax, jax.numpy as jnp;"
            "print(jax.devices());"
            "print(int(jnp.arange(8).sum()))")
    reason = ""
    for attempt, backoff_s in enumerate((0, 60, 120)):
        if backoff_s:
            print(f"[bench] tpu probe retry in {backoff_s}s "
                  f"(attempt {attempt + 1}/3): {reason}", file=sys.stderr)
            time.sleep(backoff_s)
        try:
            proc = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True, text=True, timeout=PROBE_TIMEOUT_S,
                cwd=os.path.dirname(os.path.abspath(__file__)))
        except subprocess.TimeoutExpired:
            reason = f"backend probe timed out after {PROBE_TIMEOUT_S}s"
            continue
        if proc.returncode != 0:
            tail = (proc.stderr or proc.stdout).strip().splitlines()[-1:]
            reason = f"backend probe failed (rc={proc.returncode}): " \
                     f"{tail[0] if tail else 'no output'}"
            continue
        return ""
    return reason + " (after 3 probes over ~10min)"


def _geo(xs):
    return math.exp(sum(math.log(x) for x in xs) / len(xs))


def tunnel_diagnostics() -> dict:
    """Measured link characteristics, reported so the artifact is
    interpretable: on the axon tunnel every collect pays one dispatch+
    download round trip, and bandwidth has been observed anywhere from
    2 to 20 MB/s — numbers a colocated deployment would not pay."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    jax.device_get(jnp.arange(8).sum())      # settle/compile
    t0 = time.perf_counter()
    for _ in range(3):
        jax.device_get(jnp.arange(8).sum())
    rt = (time.perf_counter() - t0) / 3
    buf = jnp.zeros((1 << 21,), jnp.int64)   # 16 MB
    jax.device_get(buf)
    t0 = time.perf_counter()
    jax.device_get(buf)
    dl = time.perf_counter() - t0
    return {"backend": jax.default_backend(),
            "tunnel_rt_ms": round(rt * 1e3, 1),
            "tunnel_download_mbps": round(16 / max(dl - rt, 1e-3), 1)}


def write_parquet_tables(tables: dict, out_dir: str) -> dict:
    """Write generated tables to parquet ONCE per run (ISSUE 11 /
    ROADMAP item 1: the timed region must include the device parquet
    decoder, which had never appeared in a headline number). Returns
    {table name: file path}."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    os.makedirs(out_dir, exist_ok=True)
    paths = {}
    t0 = time.perf_counter()
    total = 0
    for name, rb in tables.items():
        path = os.path.join(out_dir, f"{name}.parquet")
        pq.write_table(pa.Table.from_batches([rb]), path)
        total += os.path.getsize(path)
        paths[name] = path
    print(f"[bench] wrote {len(paths)} parquet tables "
          f"({total / 1e6:.0f} MB) to {out_dir} "
          f"in {time.perf_counter() - t0:.1f}s", file=sys.stderr)
    return paths


def parquet_frames(session, paths: dict) -> dict:
    """Per-engine DataFrames that SCAN the parquet files — every collect
    re-reads them, so scan+decode are inside the timed region."""
    return {name: session.read.parquet(path)
            for name, path in paths.items()}


def measure_pipeline_overlap(tpch, tables, timed_fn):
    """ISSUE-5 acceptance probe: cold uncached wall time of the
    multi-boundary join queries q3/q5 with the pipeline layer enabled
    (default) vs spark.rapids.tpu.pipeline.enabled=false, on this bench
    backend. >1 = the pipeline wins; the target deployment (high-latency
    tunnel, where uploads are mostly link waits) is where the overlap
    pays most — a host-saturated CPU backend has little idle to harvest."""
    from spark_rapids_tpu.data import upload_cache
    from spark_rapids_tpu.session import TpuSession
    out = {}
    on = TpuSession({"spark.rapids.sql.enabled": True,
                     "spark.rapids.sql.variableFloatAgg.enabled": True})
    off = on.with_conf(**{"spark.rapids.tpu.pipeline.enabled": False})
    t_on = tpch.load(on, tables, cache=False)
    t_off = tpch.load(off, tables, cache=False)
    for name in ("q3", "q5"):
        q = tpch.QUERIES[name]
        q(t_on).collect()  # shared warmup (same plan shape both modes)
        q(t_off).collect()

        def cold(t):
            upload_cache.clear()
            return q(t).collect()
        t_pipe = timed_fn(lambda: cold(t_on))
        t_serial = timed_fn(lambda: cold(t_off))
        out[f"pipeline_cold_speedup_{name}"] = round(t_serial / t_pipe, 3)
        print(f"[bench] pipeline A/B {name}: on={t_pipe*1e3:.1f}ms "
              f"off={t_serial*1e3:.1f}ms "
              f"speedup={t_serial/t_pipe:.2f}", file=sys.stderr)
    return out


def run_suite(budget_s=DEFAULT_BUDGET_S,
              query_budget_s=DEFAULT_QUERY_BUDGET_S,
              n_rows=DEFAULT_ROWS):
    # NOTE: do not enable the persistent executable cache here
    # (spark.rapids.tpu.compileCache.enabled / jax_compilation_cache_dir) —
    # it deadlocks the axon remote-compile helper (observed: queries hang
    # indefinitely), and its XLA-level executable replay can SIGILL on
    # cross-machine AOT artifacts (see spark_rapids_tpu/__init__.py and
    # docs/compile-cache.md).
    from spark_rapids_tpu.session import TpuSession
    from spark_rapids_tpu.utils import kernel_cache as KC
    from spark_rapids_tpu.workloads import tpch
    from spark_rapids_tpu.workloads.compare import tables_match
    suite_t0 = time.perf_counter()
    diag = tunnel_diagnostics()
    print(f"[bench] backend={diag['backend']} rt={diag['tunnel_rt_ms']}ms "
          f"download={diag['tunnel_download_mbps']}MB/s", file=sys.stderr)

    tables = tpch.gen_tables(n_rows, seed=42)

    cpu = TpuSession({"spark.rapids.sql.enabled": False})
    # variableFloatAgg: same stance as the reference's benchmarks — float
    # aggregation order differs from CPU (documented incompat,
    # docs/compatibility.md); the correctness gate compares with tolerance.
    # ESSENTIAL metrics so every timed query leaves a QueryProfile
    # (emitted next to the BENCH_*.json artifacts; docs/monitoring.md).
    tpu = TpuSession({"spark.rapids.sql.enabled": True,
                      "spark.rapids.sql.variableFloatAgg.enabled": True,
                      "spark.rapids.tpu.metrics.level": "ESSENTIAL"})
    # PARQUET-INCLUSIVE timed region (ISSUE 11 / ROADMAP item 1): the
    # generated tables land in parquet once, and every timed collect
    # SCANS them — the device parquet decoder finally shows up in the
    # headline number instead of only in its unit tests.
    pq_dir = tempfile.mkdtemp(prefix="bench_parquet_")
    # The staged tables are hundreds of MB at 4M rows; repeated runs must
    # not accumulate them until /tmp fills.
    import functools
    import shutil
    cleanup = functools.partial(shutil.rmtree, pq_dir, ignore_errors=True)
    atexit.register(cleanup)
    # The signal kill path exits via os._exit (skipping atexit), so it
    # runs the same callable itself before exiting.
    _KILL_CLEANUPS.append(cleanup)
    cpu_t = parquet_frames(cpu, write_parquet_tables(tables, pq_dir))
    tpu_t = parquet_frames(
        tpu, {n: os.path.join(pq_dir, f"{n}.parquet") for n in tables})

    from spark_rapids_tpu.data import upload_cache

    ratios, tpu_times, cold_ratios = [], [], []
    # Subset: every operator shape (scan/filter/project/agg, 1-4 joins,
    # semi join, disjunctive band join, conditional sums, float scoring)
    # without double-paying remote-compile time for shapes q5/q3 already
    # cover (q10/q18 re-run under pytest, tests/test_tpch.py).
    bench_queries = ["q1", "q3", "q4", "q5", "q6", "q12", "q14", "q19",
                     "xbb_score"]
    # TPCxBB suite entries (the reference's headline chart is TPCxBB;
    # round-5 adds the basket self-join, ML feature build, and
    # clickstream sessionization shapes from workloads/tpcxbb.py)
    from spark_rapids_tpu.workloads import tpcxbb
    xbb_tables = tpcxbb.gen_tables(1 << 17, seed=42)
    xbb_dir = os.path.join(pq_dir, "xbb")
    bb_cpu = parquet_frames(cpu, write_parquet_tables(xbb_tables, xbb_dir))
    bb_tpu = parquet_frames(
        tpu, {n: os.path.join(xbb_dir, f"{n}.parquet") for n in xbb_tables})
    xbb_specs = [("bb_q01", tpcxbb.q01), ("bb_q05", tpcxbb.q05),
                 ("bb_q30", tpcxbb.q30)]
    runs = [(name, tpch.QUERIES[name], cpu_t, tpu_t)
            for name in bench_queries]
    runs += [(name, q, bb_cpu, bb_tpu) for name, q in xbb_specs]
    from spark_rapids_tpu.compile import executables as _executables
    from spark_rapids_tpu.exec import fusion
    profiles = {}
    skipped = {}
    # Per-query compile breakdown (ISSUE 6): compile_seconds,
    # kernels_compiled, executables_reused, cold_vs_cached_ratio land in
    # the BENCH JSON so the win curve is machine-readable (the ROADMAP
    # success metric is cold within 2x of cached, per query).
    query_compile = {}

    def cumulative(extra_error=None):
        """The cumulative BENCH payload over queries completed SO FAR —
        emitted as a checkpoint line after every query, so an external
        kill at any point leaves machine-readable totals behind."""
        out = {
            "metric": f"tpch_tpcxbb_{len(tpu_times)}q_{n_rows}row_"
                      "parquet_geomean_device_time",
            "value": round(_geo(tpu_times) * 1000, 2) if tpu_times else 0.0,
            "unit": "ms",
            "vs_baseline": round(_geo(ratios), 3) if ratios else 0.0,
            "cold_vs_baseline": round(_geo(cold_ratios), 3)
            if cold_ratios else 0.0,
            "completed": len(tpu_times),
            "queries": query_compile,
            **diag,
        }
        if skipped:
            out["skipped"] = skipped
        if extra_error:
            out["error"] = extra_error
        return out

    for name, q, cpu_frames, tpu_frames in runs:
        elapsed = time.perf_counter() - suite_t0
        if budget_s and elapsed > budget_s:
            # Wall-clock budget exhausted (rc=124 class of failure in
            # BENCH_r05): record the skip and keep the JSON contract.
            skipped[name] = (f"suite budget {budget_s:.0f}s exhausted "
                             f"after {elapsed:.0f}s; warmup skipped")
            print(f"[bench] SKIP {name}: {skipped[name]}", file=sys.stderr)
            emit_checkpoint(cumulative())
            continue
        per_query = query_budget_s
        if budget_s:
            per_query = min(per_query or budget_s, budget_s - elapsed)
        t0 = time.perf_counter()
        try:
            with query_budget(per_query):
                stats0 = KC.cache_stats()
                exe0 = _executables.stats()
                cpu_result = q(cpu_frames).collect()  # oracle
                tpu_result = q(tpu_frames).collect()  # warmup + compile
                assert tables_match(tpu_result, cpu_result), \
                    f"{name}: TPU result != CPU oracle result"
                stats1 = KC.cache_stats()
                exe1 = _executables.stats()
                # Headline: parquet scan + decode INSIDE the timed region
                # for both engines (executables and upload memo warm).
                cpu_time = timed(lambda: q(cpu_frames).collect())
                tpu_time = timed(lambda: q(tpu_frames).collect())
                # Per-query QueryProfile of the last timed device run,
                # emitted next to BENCH_*.json (tools/profile_bench.py
                # --compare diffs two bundles for >20% regressions).
                profiles[name] = tpu.last_query_profile()
                # cold: upload memo dropped first, so host-side prep +
                # transfer land fully inside the timed region too

                def cold_run():
                    upload_cache.clear()
                    return q(tpu_frames).collect()
                ctpu = timed(cold_run, reps=1)
        except QueryBudgetExceeded as e:
            skipped[name] = f"{e} (started at {t0 - suite_t0:.0f}s)"
            print(f"[bench] SKIP {name}: {skipped[name]}", file=sys.stderr)
            emit_checkpoint(cumulative())
            continue
        ratios.append(cpu_time / tpu_time)
        cold_ratios.append(cpu_time / ctpu)
        tpu_times.append(tpu_time)
        reused0 = exe0["aot_hits"] + exe0["jit_calls"] - exe0["jit_compiles"]
        reused1 = exe1["aot_hits"] + exe1["jit_calls"] - exe1["jit_compiles"]
        query_compile[name] = {
            # Fused-program compile time plus host kernel-build time paid
            # by this query's warmup run.
            "compile_seconds": round(
                exe1["compile_seconds"] - exe0["compile_seconds"]
                + (stats1["build_ns"] - stats0["build_ns"]) / 1e9, 3),
            "kernels_compiled": stats1["misses"] - stats0["misses"],
            "fused_compiles": exe1["jit_compiles"] - exe0["jit_compiles"],
            "executables_reused": reused1 - reused0,
            "ratio": round(cpu_time / tpu_time, 3),
            # ROADMAP success metric: cold within 2x of cached (<= 2.0).
            "cold_vs_cached_ratio": round(ctpu / tpu_time, 3),
        }
        # Perf evidence (VERDICT r3 item 1b): kernels compiled for this
        # query's warmup, fused-program count, and steady-state dispatch
        # counts — "compiles and matches" AND "how it runs".
        print(f"[bench] {name}: cpu={cpu_time*1e3:.1f}ms "
              f"tpu={tpu_time*1e3:.1f}ms ratio={cpu_time/tpu_time:.2f} "
              f"cold_ratio={cpu_time/ctpu:.2f} "
              f"kernels_compiled={stats1['misses'] - stats0['misses']} "
              f"compile_s={query_compile[name]['compile_seconds']:.1f} "
              f"cold_vs_cached={ctpu/tpu_time:.2f} "
              f"fused_programs={len(fusion._FUSED_CACHE)} "
              f"(warmup+compile {time.perf_counter()-t0:.0f}s)",
              file=sys.stderr)
        # Cumulative checkpoint: the rc=124 insurance — every completed
        # query updates the JSON the driver would parse after a kill.
        emit_checkpoint(cumulative())

    # Per-query QueryProfile bundle next to the BENCH_*.json artifacts
    # (best-effort: profiles must never fail the bench contract).
    try:
        from spark_rapids_tpu.metrics.profile import dump_profiles
        prof_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "BENCH_profiles.json")
        dump_profiles(prof_path, profiles)
        print(f"[bench] wrote {len([p for p in profiles.values() if p])} "
              f"query profiles to {prof_path}", file=sys.stderr)
    except Exception as e:  # noqa: BLE001 - observability is best-effort
        print(f"[bench] profile dump failed: {e}", file=sys.stderr)

    # Compile-once layer counters (docs/compile-cache.md): how many fused
    # programs exist, how many AOT executables warm-up built, and how the
    # steady-state dispatches split between the AOT table and jit.
    from spark_rapids_tpu.compile import budget as _compile_budget
    from spark_rapids_tpu.compile import warmup as _compile_warmup
    _aot = _executables.stats()
    print(f"[bench] compile-once: programs={_aot['programs']} "
          f"aot_executables={_aot['aot_executables']} "
          f"aot_hits={_aot['aot_hits']} jit_calls={_aot['jit_calls']} "
          f"fused_compiles={_aot['jit_compiles']} "
          f"compile_seconds={_aot['compile_seconds']:.1f} "
          f"budget={_compile_budget.stats()} "
          f"warmup={_compile_warmup.stats()}", file=sys.stderr)

    if not tpu_times:
        return cumulative(
            extra_error="every query skipped by the wall-clock budget")
    geo_r = _geo(ratios)
    print(f"[bench] geomean ratio warm={geo_r:.3f} "
          f"cold={_geo(cold_ratios):.3f} "
          f"(>1 = device wins; both scan the parquet tables inside the "
          f"timed region — warm keeps the upload memo, cold clears it so "
          f"prep+transfer are fully timed too)",
          file=sys.stderr)
    out = {
        **cumulative(),
        # Per-query compile breakdown + suite compile totals (ISSUE 6):
        # the machine-readable compile win curve.
        "compile": {
            "fused_programs": _aot["programs"],
            "fused_compiles": _aot["jit_compiles"],
            "compile_seconds": round(_aot["compile_seconds"], 1),
            "executables_reused": _aot["aot_hits"] + _aot["jit_calls"]
            - _aot["jit_compiles"],
            "cold_vs_cached_geomean": round(_geo(
                [q["cold_vs_cached_ratio"] for q in query_compile.values()
                 if q.get("cold_vs_cached_ratio", 0) > 0] or [1.0]), 3),
        },
        # Durability evidence (ISSUE 7, docs/fault-tolerance.md): the
        # per-query recovery counters from the QueryProfiles. All-zero
        # totals PROVE the run was clean (no silent corruption was
        # retried through); non-zero counters under fault injection prove
        # the recovery machinery actually ran.
        "faults": _fault_section(profiles),
        # Pallas kernel evidence (ISSUE 8, docs/tuning-guide.md): which
        # hand-written kernels served each query (staged launches,
        # compiled pallas programs, fallback reasons). With the gate off
        # (the default) this records {enabled: false} — the per-kernel
        # win curve comes from tools/kernel_bench.py's BENCH_kernels.json.
        "pallas": _pallas_bench_section(profiles),
    }
    # Pipelined-execution A/B (ISSUE-5 acceptance): cold q3/q5 with the
    # pipeline on vs off, budget-guarded like everything else. Runs at a
    # reduced scale — the A/B isolates overlap, not throughput.
    if not budget_s or time.perf_counter() - suite_t0 < budget_s:
        try:
            with query_budget(query_budget_s):
                ab_tables = tables if n_rows <= (1 << 20) \
                    else tpch.gen_tables(1 << 20, seed=42)
                out.update(measure_pipeline_overlap(tpch, ab_tables, timed))
        except Exception as e:  # noqa: BLE001 — incl. QueryBudgetExceeded
            print(f"[bench] pipeline A/B skipped: {e}", file=sys.stderr)
    # Critical-path attribution (ISSUE 13): ONE traced q3 rerun OUTSIDE
    # every timed region (tracing adds spans, so it must never touch the
    # headline numbers), summarized by tools/trace_report.py into the
    # BENCH JSON — the "where did the time go" artifact the hardware win
    # curve round needs (ROADMAP item 1: per-kernel/per-stage
    # device-time attribution populated).
    if not budget_s or time.perf_counter() - suite_t0 < budget_s:
        try:
            with query_budget(query_budget_s):
                out["trace_report"] = _traced_query_report(
                    tpu, tpu_t, tpch.QUERIES["q3"])
        except Exception as e:  # noqa: BLE001 — best-effort attribution
            print(f"[bench] trace report skipped: {e}", file=sys.stderr)
    return out


def _traced_query_report(tpu, frames, q) -> dict:
    """Re-run one query with tracing on and summarize its critical path
    (tools/trace_report.py). The traced session shares the warm engine
    state, so the trace shows the STEADY-STATE timeline."""
    import functools
    import shutil

    import tools.trace_report as trace_report
    trace_dir = tempfile.mkdtemp(prefix="bench_trace_")
    # Same accumulation guard as the parquet staging dir above: repeated
    # runs must not pile temp dirs up in /tmp (atexit + kill path).
    cleanup = functools.partial(shutil.rmtree, trace_dir,
                                ignore_errors=True)
    atexit.register(cleanup)
    _KILL_CLEANUPS.append(cleanup)
    traced = tpu.with_conf(**{
        "spark.rapids.tpu.trace.enabled": True,
        "spark.rapids.tpu.trace.dir": trace_dir,
    })
    traced.execute(q(frames)._plan)
    rep = trace_report.summarize_dir(trace_dir)
    return rep["worst"] if rep else {}


def _fault_section(profiles) -> dict:
    """The BENCH JSON ``faults`` section: suite totals + per-query
    durability counters (only queries with any non-zero counter are
    listed — the common all-clean case stays one small totals dict)."""
    totals = {"checksumFailures": 0, "shuffleBlocksRefetched": 0,
              "mapTasksRecomputed": 0, "deadlineCancels": 0,
              "peersBlacklisted": 0}
    per_query = {}
    for qname, p in profiles.items():
        engine = getattr(p, "engine", None) or {}
        dur = engine.get("durability")
        if not dur:
            continue
        counters = {k: int(dur.get(k, 0)) for k in totals}
        for k, v in counters.items():
            totals[k] += v
        if any(counters.values()):
            per_query[qname] = counters
    out = {"totals": totals}
    if per_query:
        out["queries"] = per_query
    return out


def _pallas_bench_section(profiles) -> dict:
    """The BENCH JSON ``pallas`` section: per-kernel suite totals
    (staged launches, compiled programs, fallback reasons) plus the
    per-query kernel breakdown for queries where any Pallas kernel ran
    or fell back — all zeros / empty with the gate off (the default)."""
    totals: dict = {}
    per_query: dict = {}
    enabled = False
    for qname, p in profiles.items():
        engine = getattr(p, "engine", None) or {}
        pal = engine.get("pallas") or {}
        enabled = enabled or bool(pal.get("enabled"))
        kernels = pal.get("kernels") or {}
        if not kernels:
            continue
        per_query[qname] = kernels
        for k, m in kernels.items():
            t = totals.setdefault(k, {"staged": 0, "programsCompiled": 0,
                                      "fallbacks": {}})
            t["staged"] += int(m.get("staged", 0))
            t["programsCompiled"] += int(m.get("programsCompiled", 0))
            for r, n in (m.get("fallbacks") or {}).items():
                t["fallbacks"][r] = t["fallbacks"].get(r, 0) + int(n)
    out = {"enabled": enabled, "totals": totals}
    if per_query:
        out["queries"] = per_query
    return out


def parse_args(argv=None):
    ap = argparse.ArgumentParser(
        description="TPC-H/TPCxBB-like bench (always emits one JSON line, "
                    "always exits 0)")
    ap.add_argument(
        "--budget", type=float,
        default=float(os.environ.get("SPARK_RAPIDS_TPU_BENCH_BUDGET",
                                     DEFAULT_BUDGET_S)),
        help="suite wall-clock budget in seconds; queries whose warmup "
             "would start past it are skipped (recorded per query in the "
             "output JSON). 0 disables.")
    ap.add_argument(
        "--query-budget", type=float,
        default=float(os.environ.get("SPARK_RAPIDS_TPU_BENCH_QUERY_BUDGET",
                                     DEFAULT_QUERY_BUDGET_S)),
        help="per-query ceiling in seconds (SIGALRM-guarded warmup+timing; "
             "an over-budget query is recorded as skipped and the suite "
             "continues). 0 disables.")
    ap.add_argument(
        "--rows", type=int,
        default=int(os.environ.get("SPARK_RAPIDS_TPU_BENCH_ROWS",
                                   DEFAULT_ROWS)),
        help="lineitem row count for the parquet-inclusive headline "
             f"(default {DEFAULT_ROWS} = 4M — a scale the device can "
             "legitimately win; the CPU oracle at 1M finishes under the "
             "tunnel round-trip floor).")
    return ap.parse_args(argv)


def main():
    args = parse_args()
    install_kill_dump()
    if os.environ.get("SPARK_RAPIDS_TPU_BENCH_CHILD") != "1":
        reason = probe_backend()
        if reason:
            # Accelerator unreachable: rerun this script on the CPU XLA
            # backend in a scrubbed env so a number still lands, and say so.
            # The child gets a hard timeout too — the always-emit-JSON
            # contract must survive a wedged child as well.
            env = dict(os.environ)
            env["JAX_PLATFORMS"] = "cpu"
            env.pop("PALLAS_AXON_POOL_IPS", None)
            # The remote-compile helper can serve XLA:CPU AOT executables
            # built for CPU features this host lacks (SIGILL risk) — the
            # CPU fallback must compile locally.
            env.pop("PALLAS_AXON_REMOTE_COMPILE", None)
            env["JAX_ENABLE_COMPILATION_CACHE"] = "false"
            env["SPARK_RAPIDS_TPU_BENCH_CHILD"] = "1"
            env["SPARK_RAPIDS_TPU_BENCH_BUDGET"] = str(args.budget)
            env["SPARK_RAPIDS_TPU_BENCH_QUERY_BUDGET"] = \
                str(args.query_budget)
            env["SPARK_RAPIDS_TPU_BENCH_ROWS"] = str(args.rows)
            stdout, stderr = "", ""
            try:
                proc = subprocess.run(
                    [sys.executable, os.path.abspath(__file__)], env=env,
                    capture_output=True, text=True, timeout=3000,
                    cwd=os.path.dirname(os.path.abspath(__file__)))
                stdout, stderr = proc.stdout or "", proc.stderr or ""
            except subprocess.TimeoutExpired as te:
                stdout = (te.stdout or b"").decode(errors="replace") \
                    if isinstance(te.stdout, bytes) else (te.stdout or "")
                stderr = f"cpu-fallback child timed out after {te.timeout}s"
            sys.stderr.write(stderr)
            line = None
            for ln in stdout.strip().splitlines():
                try:
                    parsed = json.loads(ln)
                except (json.JSONDecodeError, ValueError):
                    continue
                # json.loads accepts bare scalars; only a dict payload can
                # take the "error" key without breaking the exit-0 contract
                if isinstance(parsed, dict):
                    line = parsed
            if line is None:
                line = {"metric": "tpchlike_geomean_device_time",
                        "value": 0.0, "unit": "ms", "vs_baseline": 0.0}
            line["error"] = (f"tpu backend unreachable ({reason}); "
                             "measured on cpu XLA backend instead")
            emit_final(line)
            return
    try:
        result = run_suite(budget_s=args.budget,
                           query_budget_s=args.query_budget,
                           n_rows=args.rows)
    except Exception as e:  # noqa: BLE001 — the JSON line must always land
        import traceback
        traceback.print_exc()
        # Keep the cumulative per-query totals gathered before the crash
        # (if any) so a late failure doesn't zero the whole artifact.
        result = dict(_CHECKPOINT["payload"] or
                      {"metric": "tpchlike_geomean_device_time",
                       "value": 0.0, "unit": "ms", "vs_baseline": 0.0})
        result.pop("partial", None)
        result["error"] = f"{type(e).__name__}: {e}"
    emit_final(result)


if __name__ == "__main__":
    main()
