"""Benchmark: TPC-DS-q5-shaped query (scan -> join -> group-by aggregate) on
the device vs the CPU oracle — BASELINE.md config 1.

Prints exactly one JSON line:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}

Methodology (matches TPC practice and the reference's CPU-Spark-vs-GPU
comparison): tables are loaded once per engine — ``df.cache()`` pins them
host-side for the CPU oracle and HBM-resident for the TPU — then the query
(filter -> project -> hash join -> hash aggregate -> collect) is timed
end-to-end including result download. value = device wall time (post-compile,
median of 3); vs_baseline = CPU time / device time (>1 = TPU wins). The
reference publishes no machine-readable numbers (BASELINE.md), so the CPU
oracle is the baseline, exactly like the reference's methodology.
"""

import json
import time

import numpy as np


def build_tables(session, n_fact: int, n_dim: int):
    rng = np.random.default_rng(42)
    fact = {
        "k": rng.integers(0, n_dim, n_fact).astype(np.int64),
        "q": rng.integers(1, 100, n_fact).astype(np.int64),
        "p": rng.integers(1, 1000, n_fact).astype(np.int64),
    }
    dim = {
        "k": np.arange(n_dim, dtype=np.int64),
        "cat": rng.integers(0, 20, n_dim).astype(np.int64),
    }
    import pyarrow as pa
    fact_rb = pa.RecordBatch.from_pydict(fact)
    dim_rb = pa.RecordBatch.from_pydict(dim)
    return (session.create_dataframe(fact_rb).cache(),
            session.create_dataframe(dim_rb).cache())


def q5_like(fact, dim):
    from spark_rapids_tpu.ops import aggregates as AGG
    from spark_rapids_tpu.ops import predicates as P
    from spark_rapids_tpu.ops.arithmetic import Multiply
    from spark_rapids_tpu.ops.expression import col, lit

    return (fact
            .where(P.LessThan(col("q"), lit(95)))
            .with_column("rev", Multiply(col("q"), col("p")))
            .join(dim, on="k", how="inner")
            .group_by(col("cat"))
            .agg(AGG.AggregateExpression(AGG.Sum(col("rev")), "total_rev"),
                 AGG.AggregateExpression(AGG.Count(), "cnt"),
                 AGG.AggregateExpression(AGG.Min(col("p")), "min_p"),
                 AGG.AggregateExpression(AGG.Max(col("q")), "max_q")))


def timed(fn, reps=3):
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


def main():
    import spark_rapids_tpu  # noqa: F401
    from spark_rapids_tpu.session import TpuSession

    n_fact = 1 << 20
    n_dim = 1000

    cpu = TpuSession({"spark.rapids.sql.enabled": False})
    tpu = TpuSession({"spark.rapids.sql.enabled": True})

    cpu_fact, cpu_dim = build_tables(cpu, n_fact, n_dim)
    tpu_fact, tpu_dim = build_tables(tpu, n_fact, n_dim)

    cpu_result = q5_like(cpu_fact, cpu_dim).collect()
    tpu_result = q5_like(tpu_fact, tpu_dim).collect()  # warmup + compile
    # Correctness gate: bench numbers are meaningless if results differ.
    # Full-row multiset compare (same discipline as tests/harness.py).
    def rows(tbl):
        return sorted(zip(*[tbl.column(i).to_pylist()
                            for i in range(tbl.num_columns)]))
    assert rows(cpu_result) == rows(tpu_result), \
        "TPU result != CPU oracle result"

    cpu_time = timed(lambda: q5_like(cpu_fact, cpu_dim).collect())
    tpu_time = timed(lambda: q5_like(tpu_fact, tpu_dim).collect())

    print(json.dumps({
        "metric": "q5like_1Mrows_device_time",
        "value": round(tpu_time * 1000, 2),
        "unit": "ms",
        "vs_baseline": round(cpu_time / tpu_time, 3),
    }))


if __name__ == "__main__":
    main()
