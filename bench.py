"""Benchmark: TPC-H-like suite + TPCxBB-like scoring query, device vs the
CPU oracle — BASELINE.md configs 1-3 (the reference's own harnesses are
TpchLikeSpark / TpcxbbLikeSpark; its headline chart is the TPCxBB-like
suite). The metric is the suite GEOMEAN, matching BASELINE.md's stated
"geomean query time" metric.

Prints exactly one JSON line:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}

Methodology (TPC practice + the reference's CPU-vs-accelerator compare):
tables load once per engine — ``df.cache()`` pins them host-side for the
CPU oracle and HBM-resident for the TPU. Each query runs once for compile
warmup WITH a full-row correctness gate against the oracle, then is timed
end-to-end (plan -> execute -> result download), median of 3.
value = geomean TPU time; vs_baseline = geomean(CPU time / TPU time),
>1 = TPU wins.
"""

import json
import os
import math
import time

import numpy as np


def timed(fn, reps=3):
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


def main():
    # NOTE: do not enable jax_compilation_cache_dir here — it deadlocks the
    # axon remote-compile helper (observed: queries hang indefinitely).
    from spark_rapids_tpu.session import TpuSession
    from spark_rapids_tpu.workloads import tpch

    n_li = 1 << 20
    tables = tpch.gen_tables(n_li, seed=42)

    cpu = TpuSession({"spark.rapids.sql.enabled": False})
    # variableFloatAgg: same stance as the reference's benchmarks — float
    # aggregation order differs from CPU (documented incompat,
    # docs/compatibility.md); the correctness gate compares with tolerance.
    tpu = TpuSession({"spark.rapids.sql.enabled": True,
                      "spark.rapids.sql.variableFloatAgg.enabled": True})
    cpu_t = tpch.load(cpu, tables)
    tpu_t = tpch.load(tpu, tables)

    import sys
    from spark_rapids_tpu.workloads.compare import tables_match
    ratios, tpu_times = [], []
    # Subset: every operator shape (scan/filter/project/agg, 1-4 joins,
    # semi join, disjunctive band join, conditional sums, float scoring)
    # without double-paying remote-compile time for shapes q5/q3 already
    # cover (q10/q18 re-run under pytest, tests/test_tpch.py).
    bench_queries = ["q1", "q3", "q4", "q5", "q6", "q12", "q14", "q19",
                     "xbb_score"]
    for name in bench_queries:
        q = tpch.QUERIES[name]
        t0 = time.perf_counter()
        cpu_result = q(cpu_t).collect()       # oracle
        tpu_result = q(tpu_t).collect()       # warmup + compile
        assert tables_match(tpu_result, cpu_result), \
            f"{name}: TPU result != CPU oracle result"
        cpu_time = timed(lambda: q(cpu_t).collect())
        tpu_time = timed(lambda: q(tpu_t).collect())
        ratios.append(cpu_time / tpu_time)
        tpu_times.append(tpu_time)
        print(f"[bench] {name}: cpu={cpu_time*1e3:.1f}ms "
              f"tpu={tpu_time*1e3:.1f}ms ratio={cpu_time/tpu_time:.2f} "
              f"(warmup+compile {time.perf_counter()-t0:.0f}s)",
              file=sys.stderr)

    geo_t = math.exp(sum(math.log(t) for t in tpu_times) / len(tpu_times))
    geo_r = math.exp(sum(math.log(r) for r in ratios) / len(ratios))
    print(json.dumps({
        "metric": f"tpchlike_{len(tpu_times)}q_1Mrow_geomean_device_time",
        "value": round(geo_t * 1000, 2),
        "unit": "ms",
        "vs_baseline": round(geo_r, 3),
    }))


if __name__ == "__main__":
    main()
