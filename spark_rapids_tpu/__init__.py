"""spark_rapids_tpu — a TPU-native columnar SQL execution framework.

A brand-new framework with the capabilities of the RAPIDS Accelerator for
Apache Spark (the reference at /root/reference): columnar operators whose
batches live in TPU HBM and are evaluated as fused XLA programs, a
plan-rewrite layer with per-operator CPU fallback and explain output, a
collective-based shuffle over the device mesh, a device→host→disk spill
hierarchy, a UDF bytecode compiler, and zero-copy export to JAX ML.

See SURVEY.md for the capability blueprint and the mapping from each
reference component to its TPU-native counterpart here.
"""

import jax

# The SQL type system requires real int64/float64 columns (Spark bigint /
# double). jax disables 64-bit types by default; turn them on before any
# array is created anywhere in the package.
jax.config.update("jax_enable_x64", True)

from .version import __version__  # noqa: E402,F401
from . import types  # noqa: E402,F401
from .config import TpuConf  # noqa: E402,F401
