"""spark_rapids_tpu — a TPU-native columnar SQL execution framework.

A brand-new framework with the capabilities of the RAPIDS Accelerator for
Apache Spark (the reference at /root/reference): columnar operators whose
batches live in TPU HBM and are evaluated as fused XLA programs, a
plan-rewrite layer with per-operator CPU fallback and explain output, a
collective-based shuffle over the device mesh, a device→host→disk spill
hierarchy, a UDF bytecode compiler, and zero-copy export to JAX ML.

See SURVEY.md for the capability blueprint and the mapping from each
reference component to its TPU-native counterpart here.
"""

import os

import jax

# The SQL type system requires real int64/float64 columns (Spark bigint /
# double). jax disables 64-bit types by default; turn them on before any
# array is created anywhere in the package.
jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache — opt-IN via
# SPARK_RAPIDS_TPU_COMPILE_CACHE=<dir>. Default is OFF: in this
# environment compile requests can be served by a remote helper whose AOT
# results target CPU features this machine lacks (+avx512*,
# +prefer-no-gather); setting jax_compilation_cache_dir also activates
# XLA-internal executable caches that replay those foreign binaries even
# when jax_enable_compilation_cache is False — observed as mid-suite
# SIGILL/segfaults under cpu_aot_loader.cc in rounds 3-4.
_cache_dir = os.environ.get("SPARK_RAPIDS_TPU_COMPILE_CACHE", "off")
if _cache_dir.lower() != "off":
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

from .version import __version__  # noqa: E402,F401
from . import types  # noqa: E402,F401
from .config import TpuConf  # noqa: E402,F401
