"""spark_rapids_tpu — a TPU-native columnar SQL execution framework.

A brand-new framework with the capabilities of the RAPIDS Accelerator for
Apache Spark (the reference at /root/reference): columnar operators whose
batches live in TPU HBM and are evaluated as fused XLA programs, a
plan-rewrite layer with per-operator CPU fallback and explain output, a
collective-based shuffle over the device mesh, a device→host→disk spill
hierarchy, a UDF bytecode compiler, and zero-copy export to JAX ML.

See SURVEY.md for the capability blueprint and the mapping from each
reference component to its TPU-native counterpart here.
"""

import os

import jax

# The SQL type system requires real int64/float64 columns (Spark bigint /
# double). jax disables 64-bit types by default; turn them on before any
# array is created anywhere in the package.
jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache: query kernels compile once per machine,
# not once per process — the pre-compiled-kernel-library property of the
# reference's libcudf substrate (SURVEY.md §2.10). Opt out or relocate with
# SPARK_RAPIDS_TPU_COMPILE_CACHE=off|<dir>.
_cache_dir = os.environ.get("SPARK_RAPIDS_TPU_COMPILE_CACHE",
                            os.path.expanduser("~/.cache/spark_rapids_tpu"))
if _cache_dir.lower() != "off":
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

from .version import __version__  # noqa: E402,F401
from . import types  # noqa: E402,F401
from .config import TpuConf  # noqa: E402,F401
