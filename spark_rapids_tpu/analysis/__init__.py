"""Static plan analysis — post-planning verification of physical plans.

The reference proves convertibility statically (GpuOverrides tagging)
*before* execution; :mod:`.plan_lint` is the complementary pass that
re-verifies the invariants of the plan that planning and the TPU rewrite
actually produced. See docs/plan-lint.md.
"""

from .plan_lint import (PlanLintError, PlanLintViolation,  # noqa: F401
                        lint_plan, verify_plan)
