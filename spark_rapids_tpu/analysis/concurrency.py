"""Static concurrency analysis — the zero-schedule twin of runtime lockdep.

(concurrency: skip-file — this analyzer is a dev-time tool; none of its
code runs on engine threads, so it excludes itself from its own scan.)

Where ``utils/lockdep.py`` learns the lock-order graph from the schedules
tier-1 happens to run, this module derives the same model from the SOURCE:
one stdlib-``ast`` pass over the package discovers every lock object,
every acquisition site, and an approximate inter-procedural call graph,
then reports

* ``lock-cycle`` — a cycle in the static lock-order digraph (two
  functions that nest the same locks in opposite orders can deadlock,
  whether or not any test interleaves them). Reentrant-RLock self-cycles
  are suppressed (re-acquiring your own RLock is the point of an RLock).
* ``hold-across-blocking`` — an acquisition scope that (directly or via
  a resolvable call chain) reaches a known-blocking call: device
  dispatch/transfer (``block_until_ready``, ``to_arrow``/``from_arrow``),
  ``Future.result`` waits, ``time.sleep``, socket/file I/O. Locks
  declared ``io_ok=True`` at their ``lockdep`` construction are exempt —
  that annotation is the reviewed claim "this lock exists to serialize
  I/O" (docs/concurrency.md lists them all).
* ``unguarded-shared-write`` — a write to shared state from
  *worker-reachable* code (functions reachable from ``submit`` /
  ``ordered_map_iter`` / ``unit_partitions`` / ``prefetch_iter`` call
  sites — the pipeline-pool entry points) with no lock held: writes to
  module globals, to closure variables captured from an enclosing scope,
  and to ``self`` attributes of lock-owning classes outside their lock.

The analysis is deliberately approximate (documented per helper): call
targets resolve by name with a same-class > same-module > unique-global
preference; ``with`` statements are the only acquisitions tracked for
held-sets; a function called from under a lock at EVERY resolved call
site inherits that lock (``always_held`` fixpoint), which keeps private
``_helper`` methods of locked classes from flooding the write rule.
False negatives are possible by design — runtime lockdep covers the
dynamic remainder; false positives land once in the ratcheted baseline
(``tools/lock_order_baseline.json``) and may only go DOWN, exactly like
``tools/tpu_lint_baseline.json``.

Standalone on purpose: no package imports, so ``tools/tpu_lint.py
--concurrency`` can load this file by path without importing the engine
(and therefore jax). CLI mirrors tpu_lint::

    python -m tools.tpu_lint --concurrency            # CI gate
    python -m tools.tpu_lint --concurrency --list
    python -m tools.tpu_lint --concurrency --update-baseline
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

#: call names that hand work to pipeline workers: their function-valued
#: arguments (and the functions those wrap) execute on worker threads.
WORKER_ENTRY_CALLS = frozenset({
    "submit", "ordered_map_iter", "unit_partitions", "prefetch_iter",
    "materialize_boundaries",
})

#: bare call names treated as blocking, with the wait class they imply.
BLOCKING_CALLS: Dict[str, str] = {
    "result": "future wait",
    "block_until_ready": "device sync",
    "to_arrow": "device->host download",
    "from_arrow": "host->device upload",
    "device_get": "device->host download",
    "sleep": "sleep",
    "join": "thread join",
    "recv": "socket read",
    "_recv_exact": "socket read",
    "sendall": "socket write",
    "accept": "socket accept",
    "create_connection": "socket connect",
    "fetch_one": "network fetch",
    "open": "file open",
}

#: methods whose writes are lifecycle bookkeeping, not shared-state races
_WRITE_EXEMPT_FUNCS = frozenset({"__init__", "__post_init__", "__enter__",
                                 "__exit__", "close", "reset", "clear"})

#: bare names too generic for STRICT call resolution: `f.read(n)` on a
#: file object must not resolve to `SpillFile.read` just because they
#: share a name. A `self.<name>()` call with a same-class match still
#: resolves (that one IS the method). Worker-reachability (generous
#: mode) also ignores these — `q.get()` tainting every `get` would make
#: reachability meaningless.
_GENERIC_CALL_NAMES = frozenset({
    "read", "write", "get", "put", "open", "close", "clear", "append",
    "pop", "popitem", "update", "copy", "add", "remove", "discard",
    "items", "keys", "values", "sort", "extend", "insert", "send",
    "flush", "seek", "devices", "result", "join", "acquire", "release",
    "wait", "notify", "notify_all", "set", "start", "cancel", "run",
    "free", "next", "tell", "name", "setdefault",
})

IGNORE_MARKER = "concurrency: ignore"
#: a file whose first lines carry this marker is excluded from analysis
#: (dev-only modules that never run in the engine process)
SKIP_FILE_MARKER = "concurrency: skip-file"

_RULES = ("lock-cycle", "hold-across-blocking", "unguarded-shared-write")


# ---------------------------------------------------------------------------
# Model
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class LockDef:
    lock_id: str       # "memory/spill.py::SpillFile._lock"
    path: str
    owner: str         # class name, "" for module scope
    attr: str          # attribute / global name
    lineno: int
    kind: str          # lock | rlock | condition
    io_ok: bool
    declared: str      # the lockdep name string, "" when raw/unnamed


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str
    rule: str
    lineno: int
    message: str

    @property
    def key(self) -> str:
        return f"{self.path}::{self.rule}"

    def __str__(self) -> str:
        return f"{self.path}:{self.lineno}: [{self.rule}] {self.message}"


# Raw (unresolved) lock references collected during the per-function walk.
# ("self", attr) | ("name", name) | ("attr", base, attr)
_RawRef = Tuple[str, ...]


@dataclasses.dataclass
class _CallEvent:
    name: str                      # bare callee name
    recv: Optional[str]            # "self", receiver name, or None
    lineno: int
    held: Tuple[_RawRef, ...]      # raw refs held at the call site
    fn_args: Tuple[str, ...]       # bare names of function-valued args


@dataclasses.dataclass
class _AcquireEvent:
    ref: _RawRef
    lineno: int
    held: Tuple[_RawRef, ...]      # refs already held (outer scopes)


@dataclasses.dataclass
class _BlockEvent:
    kind: str
    lineno: int
    held: Tuple[_RawRef, ...]
    suppressed: bool


@dataclasses.dataclass
class _WriteEvent:
    desc: str                      # human-readable target
    base: str                      # base name being written through
    is_self_attr: bool
    attr: str                      # attribute written (self/global writes)
    lineno: int
    held: Tuple[_RawRef, ...]
    suppressed: bool


@dataclasses.dataclass
class _FuncInfo:
    func_id: str                   # "path::Cls.meth" / "path::f.<locals>.g"
    path: str
    bare: str
    cls: str                       # enclosing class name ("" if none)
    lineno: int
    locals: Set[str] = dataclasses.field(default_factory=set)
    parent: Optional[str] = None   # enclosing function id (closures)
    acquires: List[_AcquireEvent] = dataclasses.field(default_factory=list)
    calls: List[_CallEvent] = dataclasses.field(default_factory=list)
    blocks: List[_BlockEvent] = dataclasses.field(default_factory=list)
    writes: List[_WriteEvent] = dataclasses.field(default_factory=list)
    has_yield: bool = False
    #: names the function declared `global` — never locals, and plain
    #: rebinds of them are module-state writes
    globals_decl: Set[str] = dataclasses.field(default_factory=set)


class Model:
    """Everything the three passes share, built by :func:`analyze_tree`."""

    def __init__(self):
        self.locks: Dict[str, LockDef] = {}
        self.funcs: Dict[str, _FuncInfo] = {}
        #: bare name -> [func_id] (call resolution index)
        self.by_bare: Dict[str, List[str]] = {}
        #: path -> set of module-global names
        self.globals: Dict[str, Set[str]] = {}
        #: path -> names bound by plain `import X [as Y]` (module aliases)
        self.module_imports: Dict[str, Set[str]] = {}
        #: path -> module globals holding threading.local() instances
        #: (attribute writes through them are per-thread by construction)
        self.thread_locals: Dict[str, Set[str]] = {}
        #: path -> names referenced as VALUES (not direct-call targets):
        #: a nested function absent from here that is only ever called
        #: inline (and has no yield) cannot escape to another thread
        self.value_loads: Dict[str, Set[str]] = {}
        #: lock attr/name -> [lock_id] (reference resolution index)
        self.by_attr: Dict[str, List[str]] = {}
        #: lock-order digraph: lock_id -> {succ lock_id: site string}
        self.edges: Dict[str, Dict[str, str]] = {}
        self.findings: List[Finding] = []
        #: acquisitions that could not be resolved to a LockDef
        self.unresolved: List[Tuple[str, int, str]] = []
        self.worker_reachable: Set[str] = set()

    # -- reference resolution (documented approximation) -------------------
    def resolve_ref(self, ref: _RawRef, path: str, cls: str
                    ) -> Optional[str]:
        """self.X -> this class's lock; bare NAME -> this module's
        module-level lock; other.X -> unique same-module, else unique
        repo-wide match by attribute name. Ambiguity resolves to None
        (recorded as unresolved, never guessed)."""
        kind = ref[0]
        if kind == "self":
            attr = ref[1]
            lid = f"{path}::{cls}.{attr}"
            if lid in self.locks:
                return lid
            cands = [i for i in self.by_attr.get(attr, ())]
            return cands[0] if len(cands) == 1 else None
        if kind == "name":
            name = ref[1]
            lid = f"{path}::{name}"
            if lid in self.locks:
                return lid
            cands = self.by_attr.get(name, ())
            return cands[0] if len(cands) == 1 else None
        if kind == "attr":
            attr = ref[2]
            same_mod = [i for i in self.by_attr.get(attr, ())
                        if self.locks[i].path == path]
            if len(same_mod) == 1:
                return same_mod[0]
            cands = self.by_attr.get(attr, ())
            return cands[0] if len(cands) == 1 else None
        return None

    def resolve_held(self, held: Sequence[_RawRef], path: str, cls: str
                     ) -> List[str]:
        out = []
        for r in held:
            lid = self.resolve_ref(r, path, cls)
            if lid is not None and lid not in out:
                out.append(lid)
        return out

    def resolve_call(self, ev: _CallEvent, caller: _FuncInfo,
                     generous: bool = False) -> List[str]:
        """Callee candidates for a call event. Strict mode (lock edges,
        blocking chains): same class, else same module, else a UNIQUE
        repo-wide bare-name match. Generous mode (worker reachability
        only): all bare-name matches — ``b.execute(...)`` from a worker
        must taint every ``execute`` because boundary workers really do
        run arbitrary exec subtrees. Guards against the classic
        approximate-callgraph traps: calls through a plain-``import``
        module alias (``jax.devices(...)``) and container/file method
        names (``_GENERIC_CALL_NAMES``) resolve only to a same-class
        method on an explicit ``self`` receiver."""
        cands = self.by_bare.get(ev.name, ())
        if not cands:
            return []
        if ev.recv == "self" and caller.cls:
            same_cls = [c for c in cands
                        if self.funcs[c].path == caller.path
                        and self.funcs[c].cls == caller.cls]
            if same_cls:
                return same_cls
        if ev.recv is not None \
                and ev.recv in self.module_imports.get(caller.path, ()):
            return []
        if ev.name in _GENERIC_CALL_NAMES:
            return []
        if generous:
            return list(cands)
        same_mod = [c for c in cands if self.funcs[c].path == caller.path]
        if same_mod:
            return same_mod
        return list(cands) if len(cands) == 1 else []


# ---------------------------------------------------------------------------
# Phase 1: per-file collection
# ---------------------------------------------------------------------------


def _lock_ctor_kind(call: ast.Call) -> Optional[Tuple[str, bool]]:
    """(kind, io_ok) when ``call`` constructs a lock: threading.Lock /
    RLock / Condition (raw) or lockdep.lock / rlock / condition."""
    f = call.func
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else None)
    kind = {"Lock": "lock", "RLock": "rlock", "Condition": "condition",
            "lock": "lock", "rlock": "rlock",
            "condition": "condition"}.get(name or "")
    if kind is None:
        return None
    if name in ("lock", "rlock", "condition"):
        # only the lockdep factories, not arbitrary .lock() calls
        if not (isinstance(f, ast.Attribute)
                and isinstance(f.value, ast.Name)
                and f.value.id == "lockdep"):
            return None
    io_ok = any(kw.arg == "io_ok" and isinstance(kw.value, ast.Constant)
                and bool(kw.value.value) for kw in call.keywords)
    return kind, io_ok


def _declared_name(call: ast.Call) -> str:
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str):
        return call.args[0].value
    return ""


def _ref_of(expr: ast.expr) -> Optional[_RawRef]:
    """The raw lock reference of a ``with`` context expression."""
    if isinstance(expr, ast.Name):
        return ("name", expr.id)
    if isinstance(expr, ast.Attribute):
        base = expr.value
        if isinstance(base, ast.Name):
            if base.id == "self":
                return ("self", expr.attr)
            return ("attr", base.id, expr.attr)
    return None


def _line_suppressed(lines: List[str], lineno: int) -> bool:
    line = lines[lineno - 1] if 0 < lineno <= len(lines) else ""
    return IGNORE_MARKER in line


class _FileCollector(ast.NodeVisitor):
    def __init__(self, relpath: str, lines: List[str], model: Model):
        self.path = relpath
        self.lines = lines
        self.model = model
        self._cls: List[str] = []
        self._funcs: List[_FuncInfo] = []
        self._held: List[_RawRef] = []
        self._module_globals: Set[str] = set()
        model.globals[relpath] = self._module_globals
        self._module_imports: Set[str] = set()
        model.module_imports[relpath] = self._module_imports
        self._thread_locals: Set[str] = set()
        model.thread_locals[relpath] = self._thread_locals
        self._value_loads: Set[str] = set()
        model.value_loads[relpath] = self._value_loads
        #: id()s of Name nodes that are direct-call targets (not values)
        self._call_func_nodes: Set[int] = set()

    def visit_Import(self, node: ast.Import):
        for alias in node.names:
            self._module_imports.add(alias.asname
                                     or alias.name.split(".")[0])

    # -- scope bookkeeping --------------------------------------------------
    def _cur(self) -> Optional[_FuncInfo]:
        return self._funcs[-1] if self._funcs else None

    def _func_path_name(self, name: str) -> str:
        parts = []
        if self._funcs:
            parts.append(self._funcs[-1].func_id.split("::", 1)[1]
                         + ".<locals>")
        elif self._cls:
            parts.append(self._cls[-1])
        parts.append(name)
        return f"{self.path}::{'.'.join(parts)}"

    def visit_ClassDef(self, node: ast.ClassDef):
        if self._funcs:
            self.generic_visit(node)  # class inside a function: rare; walk
            return
        self._cls.append(node.name)
        # class-level lock attributes (DeviceManager._lock style)
        for stmt in node.body:
            if isinstance(stmt, ast.Assign) \
                    and isinstance(stmt.value, ast.Call):
                info = _lock_ctor_kind(stmt.value)
                if info:
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            self._add_lock(node.name, t.id, stmt.lineno,
                                           info, stmt.value)
        self.generic_visit(node)
        self._cls.pop()

    def _add_lock(self, owner: str, attr: str, lineno: int,
                  info: Tuple[str, bool], call: ast.Call):
        kind, io_ok = info
        lid = f"{self.path}::{owner + '.' if owner else ''}{attr}"
        if lid in self.model.locks:
            return
        d = LockDef(lid, self.path, owner, attr, lineno, kind, io_ok,
                    _declared_name(call))
        self.model.locks[lid] = d
        self.model.by_attr.setdefault(attr, []).append(lid)

    def _visit_func(self, node):
        fid = self._func_path_name(node.name)
        info = _FuncInfo(fid, self.path, node.name,
                         self._cls[-1] if self._cls and not self._funcs
                         else "", node.lineno,
                         parent=self._funcs[-1].func_id if self._funcs
                         else None)
        args = node.args
        for a in [*args.posonlyargs, *args.args, *args.kwonlyargs] \
                + ([args.vararg] if args.vararg else []) \
                + ([args.kwarg] if args.kwarg else []):
            info.locals.add(a.arg)
        self.model.funcs[fid] = info
        self.model.by_bare.setdefault(node.name, []).append(fid)
        self._funcs.append(info)
        held_before = list(self._held)
        self._held = []           # held sets do not cross a def boundary
        self.generic_visit(node)
        self._held = held_before
        self._funcs.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    # -- assignments (lock discovery, local binding, write events) ---------
    def _note_local(self, target: ast.expr):
        cur = self._cur()
        if cur is None:
            return
        for n in ast.walk(target):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store) \
                    and n.id not in cur.globals_decl:
                cur.locals.add(n.id)

    def visit_Global(self, node: ast.Global):
        # names declared global are module bindings, not locals — and
        # they stay that way (a later `x = v` rebind must register as a
        # module-state write, not re-enter the locals set)
        cur = self._cur()
        if cur is not None:
            cur.locals.difference_update(node.names)
            cur.globals_decl.update(node.names)
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign):
        self._handle_assign(node, node.targets, node.value)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign):
        if node.value is not None:
            self._handle_assign(node, [node.target], node.value)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign):
        self._handle_assign(node, [node.target], node.value, aug=True)
        self.generic_visit(node)

    def _handle_assign(self, node, targets, value, aug: bool = False):
        cur = self._cur()
        if cur is None:
            # module scope: record globals; discover module-level locks
            for t in targets:
                if isinstance(t, ast.Name):
                    self._module_globals.add(t.id)
            if isinstance(value, ast.Call):
                info = _lock_ctor_kind(value)
                if info:
                    for t in targets:
                        if isinstance(t, ast.Name) and not self._cls:
                            self._add_lock("", t.id, node.lineno, info,
                                           value)
                f = value.func
                lname = f.attr if isinstance(f, ast.Attribute) else (
                    f.id if isinstance(f, ast.Name) else None)
                if lname == "local":
                    for t in targets:
                        if isinstance(t, ast.Name):
                            self._thread_locals.add(t.id)
            return
        # inside a function
        if isinstance(value, ast.Call):
            info = _lock_ctor_kind(value)
            if info:
                for t in targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self" and self._cls:
                        self._add_lock(self._cls[-1], t.attr, node.lineno,
                                       info, value)
        for t in targets:
            if not aug:
                self._note_local(t)
            self._note_write(t, node.lineno)

    def _note_write(self, target: ast.expr, lineno: int):
        """Record attribute/subscript writes (plain local rebinds are not
        shared-state hazards; mutation THROUGH a name is)."""
        cur = self._cur()
        if cur is None:
            return
        desc = None
        base = ""
        attr = ""
        is_self = False
        t = target
        if isinstance(t, ast.Subscript):
            t = t.value
        if isinstance(t, ast.Attribute):
            if isinstance(t.value, ast.Name):
                base = t.value.id
                attr = t.attr
                is_self = base == "self"
                desc = f"{base}.{attr}"
        elif isinstance(t, ast.Name) and isinstance(target, ast.Subscript):
            base = t.id
            attr = t.id
            desc = f"{base}[...]"
        elif isinstance(t, ast.Name) and isinstance(target, ast.Name) \
                and t.id not in cur.locals:
            # plain Name rebind of a non-local (needs `global`/`nonlocal`)
            base = t.id
            attr = t.id
            desc = base
        if desc is None:
            return
        cur.writes.append(_WriteEvent(
            desc, base, is_self, attr, lineno, tuple(self._held),
            _line_suppressed(self.lines, lineno)))

    # -- with / calls -------------------------------------------------------
    def visit_With(self, node: ast.With):
        cur = self._cur()
        pushed = 0
        for item in node.items:
            expr = item.context_expr
            # lockdep.blocking("kind") regions are explicit block markers
            if isinstance(expr, ast.Call) \
                    and isinstance(expr.func, ast.Attribute) \
                    and expr.func.attr == "blocking":
                if cur is not None:
                    kind = "blocking region"
                    if expr.args and isinstance(expr.args[0], ast.Constant):
                        kind = str(expr.args[0].value)
                    cur.blocks.append(_BlockEvent(
                        kind, expr.lineno, tuple(self._held),
                        _line_suppressed(self.lines, expr.lineno)))
                continue
            ref = _ref_of(expr)
            if ref is None:
                # Not a lock ref: VISIT the context expression — `with
                # lock: with open(p):` must record the open() blocking
                # call, and `with helper():` its call-graph edge.
                self.visit(expr)
                if item.optional_vars is not None:
                    self._note_local(item.optional_vars)
                continue
            # Only track refs that look like locks (resolution happens in
            # phase 2; unknown names simply resolve to nothing).
            if cur is not None:
                cur.acquires.append(_AcquireEvent(
                    ref, expr.lineno, tuple(self._held)))
            self._held.append(ref)
            pushed += 1
            if item.optional_vars is not None:
                self._note_local(item.optional_vars)
        for stmt in node.body:
            self.visit(stmt)
        for _ in range(pushed):
            self._held.pop()

    def visit_For(self, node: ast.For):
        self._note_local(node.target)
        self.generic_visit(node)

    def visit_comprehension(self, node):
        self._note_local(node.target)
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name):
        if isinstance(node.ctx, ast.Load) \
                and id(node) not in self._call_func_nodes:
            self._value_loads.add(node.id)
        self.generic_visit(node)

    def _visit_yield(self, node):
        cur = self._cur()
        if cur is not None:
            cur.has_yield = True
        self.generic_visit(node)

    visit_Yield = _visit_yield
    visit_YieldFrom = _visit_yield

    def visit_Call(self, node: ast.Call):
        if isinstance(node.func, ast.Name):
            self._call_func_nodes.add(id(node.func))
        cur = self._cur()
        if cur is not None:
            f = node.func
            name = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None)
            recv = None
            if isinstance(f, ast.Attribute) \
                    and isinstance(f.value, ast.Name):
                recv = f.value.id
            if name:
                fn_args: List[str] = []
                if name in WORKER_ENTRY_CALLS:
                    for a in list(node.args) + [kw.value
                                                for kw in node.keywords]:
                        if isinstance(a, ast.Name):
                            fn_args.append(a.id)
                        elif isinstance(a, ast.Attribute):
                            fn_args.append(a.attr)
                        elif isinstance(a, ast.Call):
                            inner = a.func
                            if isinstance(inner, ast.Name):
                                fn_args.append(inner.id)
                            elif isinstance(inner, ast.Attribute):
                                fn_args.append(inner.attr)
                        elif isinstance(a, ast.Lambda):
                            for sub in ast.walk(a.body):
                                if isinstance(sub, ast.Call):
                                    inner = sub.func
                                    if isinstance(inner, ast.Name):
                                        fn_args.append(inner.id)
                                    elif isinstance(inner, ast.Attribute):
                                        fn_args.append(inner.attr)
                cur.calls.append(_CallEvent(name, recv, node.lineno,
                                            tuple(self._held),
                                            tuple(fn_args)))
                block_kind = BLOCKING_CALLS.get(name)
                # "join" is blocking only in its zero-arg thread-join
                # shape: str.join/os.path.join always take arguments and
                # must not trip the rule under a lock.
                if name == "join" and (node.args or node.keywords):
                    block_kind = None
                if block_kind is not None:
                    cur.blocks.append(_BlockEvent(
                        block_kind, node.lineno, tuple(self._held),
                        _line_suppressed(self.lines, node.lineno)))
        self.generic_visit(node)


# ---------------------------------------------------------------------------
# Phase 2: inter-procedural passes
# ---------------------------------------------------------------------------


def _transitive_locks(model: Model, fid: str,
                      memo: Dict[str, Set[str]],
                      visiting: Set[str]) -> Set[str]:
    """Locks a call into ``fid`` may acquire (resolved; bounded by the
    strict call-resolution rules)."""
    if fid in memo:
        return memo[fid]
    if fid in visiting:
        return set()
    visiting.add(fid)
    info = model.funcs[fid]
    out: Set[str] = set()
    for acq in info.acquires:
        lid = model.resolve_ref(acq.ref, info.path, info.cls)
        if lid is not None:
            out.add(lid)
    for ev in info.calls:
        for callee in model.resolve_call(ev, info):
            out |= _transitive_locks(model, callee, memo, visiting)
    visiting.discard(fid)
    memo[fid] = out
    return out


def _transitive_blocking(model: Model, fid: str,
                         memo: Dict[str, Optional[Tuple[str, str]]],
                         visiting: Set[str]
                         ) -> Optional[Tuple[str, str]]:
    """(kind, where) when calling ``fid`` may block, directly or through
    its strict-resolution callees (the finding is attributed to whichever
    caller holds a lock across the call chain)."""
    if fid in memo:
        return memo[fid]
    if fid in visiting:
        return None
    visiting.add(fid)
    info = model.funcs[fid]
    found: Optional[Tuple[str, str]] = None
    for b in info.blocks:
        if not b.suppressed:
            found = (b.kind, f"{info.path}:{b.lineno}")
            break
    if found is None:
        for ev in info.calls:
            for callee in model.resolve_call(ev, info):
                sub = _transitive_blocking(model, callee, memo, visiting)
                if sub is not None:
                    found = sub
                    break
            if found is not None:
                break
    visiting.discard(fid)
    memo[fid] = found
    return found


def _always_held(model: Model) -> Dict[str, Set[str]]:
    """For each function, the locks held at EVERY resolved call site
    (meet-over-call-sites fixpoint, TOP = all locks). A locked class's
    private helpers — only ever called under the class lock — inherit it,
    so the write rule doesn't flood on them."""
    top = set(model.locks)
    state: Dict[str, Set[str]] = {f: set(top) for f in model.funcs}
    # call-site index: callee -> [(caller, resolved held at site)]
    sites: Dict[str, List[Tuple[str, List[str]]]] = {}
    callers: Set[str] = set()
    for fid, info in model.funcs.items():
        for ev in info.calls:
            for callee in model.resolve_call(ev, info):
                held = model.resolve_held(ev.held, info.path, info.cls)
                sites.setdefault(callee, []).append((fid, held))
                callers.add(callee)
    for fid in model.funcs:
        if fid not in callers:
            state[fid] = set()   # entry point: nothing held on arrival
    for _ in range(len(model.funcs)):
        changed = False
        for fid, callsites in sites.items():
            acc: Optional[Set[str]] = None
            for caller, held in callsites:
                s = state[caller] | set(held)
                acc = s if acc is None else (acc & s)
            acc = acc or set()
            if acc != state[fid]:
                state[fid] = acc
                changed = True
        if not changed:
            break
    return state


def _worker_reachable(model: Model) -> Set[str]:
    """Functions that may run on pipeline workers: seeds are the
    function-valued arguments of WORKER_ENTRY_CALLS sites, closed over
    the call graph with GENEROUS resolution (dynamic dispatch like
    ``b.execute(...)`` must taint every ``execute``)."""
    seeds: Set[str] = set()
    for fid, info in model.funcs.items():
        for ev in info.calls:
            if ev.name in WORKER_ENTRY_CALLS:
                for bare in ev.fn_args:
                    seeds.update(model.by_bare.get(bare, ()))
    out = set(seeds)
    frontier = list(seeds)
    while frontier:
        fid = frontier.pop()
        info = model.funcs[fid]
        # a worker runs this function, so it runs its nested closures too
        for other, oinfo in model.funcs.items():
            if oinfo.parent == fid and other not in out:
                out.add(other)
                frontier.append(other)
        for ev in info.calls:
            for callee in model.resolve_call(ev, info, generous=True):
                if callee not in out:
                    out.add(callee)
                    frontier.append(callee)
    return out


def _ancestor_locals(model: Model, info: _FuncInfo) -> Set[str]:
    out: Set[str] = set()
    parent = info.parent
    while parent is not None:
        pinfo = model.funcs[parent]
        out |= pinfo.locals
        parent = pinfo.parent
    return out


# ---------------------------------------------------------------------------
# Findings
# ---------------------------------------------------------------------------


def _build_edges(model: Model) -> None:
    lock_memo: Dict[str, Set[str]] = {}
    for fid, info in model.funcs.items():
        for acq in info.acquires:
            inner = model.resolve_ref(acq.ref, info.path, info.cls)
            if inner is None:
                model.unresolved.append(
                    (info.path, acq.lineno, "/".join(map(str, acq.ref))))
                continue
            for outer in model.resolve_held(acq.held, info.path, info.cls):
                if outer != inner:
                    model.edges.setdefault(outer, {}).setdefault(
                        inner, f"{info.path}:{acq.lineno}")
                elif model.locks[inner].kind not in ("rlock", "condition"):
                    # same-lock nesting: an RLock re-entry is fine, and
                    # lockdep.condition() is RLock-backed (matching raw
                    # threading.Condition); a plain Lock would
                    # self-deadlock (the runtime twin raises) — surface
                    # as a one-lock cycle.
                    model.edges.setdefault(outer, {}).setdefault(
                        inner, f"{info.path}:{acq.lineno}")
        for ev in info.calls:
            held = model.resolve_held(ev.held, info.path, info.cls)
            if not held:
                continue
            for callee in model.resolve_call(ev, info):
                for inner in _transitive_locks(model, callee, lock_memo,
                                               set()):
                    for outer in held:
                        if outer == inner:
                            if model.locks[inner].kind in ("rlock",
                                                           "condition"):
                                continue
                        model.edges.setdefault(outer, {}).setdefault(
                            inner,
                            f"{info.path}:{ev.lineno} via {ev.name}()")


def _find_cycles(model: Model) -> None:
    """Tarjan SCCs over the lock-order digraph; every SCC with more than
    one lock (or a non-reentrant self-loop) is one ``lock-cycle``
    finding, attributed to the first lock's file."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    stack: List[str] = []
    on: Set[str] = set()
    counter = [0]
    sccs: List[List[str]] = []

    def strong(v: str):
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        for w in model.edges.get(v, ()):
            if w not in index:
                strong(w)
                low[v] = min(low[v], low[w])
            elif w in on:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            comp = []
            while True:
                w = stack.pop()
                on.discard(w)
                comp.append(w)
                if w == v:
                    break
            sccs.append(comp)

    for v in sorted(set(model.edges)
                    | {s for d in model.edges.values() for s in d}):
        if v not in index:
            strong(v)
    for comp in sccs:
        comp = sorted(comp)
        self_loop = len(comp) == 1 and comp[0] in model.edges.get(
            comp[0], {})
        if len(comp) < 2 and not self_loop:
            continue
        sites = []
        for a in comp:
            for b, site in sorted(model.edges.get(a, {}).items()):
                if b in comp:
                    sites.append(f"{a} -> {b} at {site}")
        first = model.locks[comp[0]]
        model.findings.append(Finding(
            first.path, "lock-cycle", first.lineno,
            "lock-order cycle among {%s}: %s — concurrent threads taking "
            "these orders can deadlock; pick one order and document it in "
            "docs/concurrency.md" % (", ".join(comp), "; ".join(sites))))


def _find_hold_across_blocking(model: Model) -> None:
    block_memo: Dict[str, Optional[Tuple[str, str]]] = {}
    seen: Set[Tuple[str, str, int]] = set()
    for fid, info in model.funcs.items():
        events: List[Tuple[Tuple[str, str], int, Tuple[_RawRef, ...]]] = []
        for b in info.blocks:
            if not b.suppressed and b.held:
                events.append(((b.kind, f"{info.path}:{b.lineno}"),
                               b.lineno, b.held))
        for ev in info.calls:
            if not ev.held or ev.name in BLOCKING_CALLS:
                continue
            for callee in model.resolve_call(ev, info):
                sub = _transitive_blocking(model, callee, block_memo,
                                           set())
                if sub is not None:
                    events.append((sub, ev.lineno, ev.held))
                    break
        for (kind, where), lineno, held in events:
            for lid in model.resolve_held(held, info.path, info.cls):
                if model.locks[lid].io_ok:
                    continue
                key = (lid, kind, lineno)
                if key in seen:
                    continue
                seen.add(key)
                model.findings.append(Finding(
                    info.path, "hold-across-blocking", lineno,
                    f"'{lid}' held across {kind} ({where}) — every "
                    "thread contending on it serializes behind the "
                    "wait; release before blocking, or declare io_ok "
                    "at the lockdep construction if guarding this I/O "
                    "is the lock's purpose (docs/concurrency.md)"))


def _find_unguarded_writes(model: Model) -> None:
    always = _always_held(model)
    model.worker_reachable = _worker_reachable(model)
    #: classes that own at least one lock: their self-writes are shared
    locked_classes = {(d.path, d.owner) for d in model.locks.values()
                      if d.owner}
    for fid in sorted(model.worker_reachable):
        info = model.funcs[fid]
        if info.bare in _WRITE_EXEMPT_FUNCS:
            continue
        anc_locals = _ancestor_locals(model, info) if info.parent else set()
        for w in info.writes:
            if w.suppressed:
                continue
            if w.held or always.get(fid):
                continue  # some lock is held — treated as guarded
            flag = None
            if w.base in model.thread_locals.get(info.path, ()):
                continue  # threading.local(): per-thread by construction
            if info.parent and w.base in anc_locals \
                    and w.base not in info.locals:
                # A nested function whose name is never used as a value
                # and that has no yield runs inline on its creator's
                # thread — its captured-variable writes cannot race.
                escapes = info.has_yield or info.bare in \
                    model.value_loads.get(info.path, ())
                if escapes:
                    flag = (f"write to closure-shared '{w.desc}' "
                            "(captured from the enclosing scope)")
            elif w.is_self_attr and (info.path, info.cls) in locked_classes:
                # skip the lock attributes themselves
                if f"{info.path}::{info.cls}.{w.attr}" not in model.locks:
                    flag = (f"write to shared '{w.desc}' of lock-owning "
                            f"class {info.cls} outside its lock")
            elif not w.is_self_attr \
                    and w.base in model.globals.get(info.path, ()) \
                    and w.base not in info.locals:
                flag = f"write to module-global '{w.desc}'"
            if flag:
                model.findings.append(Finding(
                    info.path, "unguarded-shared-write", w.lineno,
                    f"{flag} from worker-reachable {fid.split('::')[1]} "
                    "with no lock held — concurrent pipeline workers "
                    "lose updates here; guard it with a lockdep lock "
                    "(utils/lockdep.py) or move it off the worker path"))


# ---------------------------------------------------------------------------
# Driver
# ---------------------------------------------------------------------------


def analyze_tree(root: str) -> Model:
    """Build the concurrency model for every .py file under ``root``."""
    model = Model()
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames
                             if d not in ("__pycache__", "_build"))
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            full = os.path.join(dirpath, fn)
            rel = os.path.relpath(full, root).replace(os.sep, "/")
            with open(full, encoding="utf-8") as f:
                src = f.read()
            if SKIP_FILE_MARKER in "\n".join(src.splitlines()[:12]):
                continue
            try:
                tree = ast.parse(src, filename=full)
            except SyntaxError as e:
                model.findings.append(Finding(rel, "parse-error",
                                              e.lineno or 0, str(e)))
                continue
            _FileCollector(rel, src.splitlines(), model).visit(tree)
    _build_edges(model)
    _find_cycles(model)
    _find_hold_across_blocking(model)
    _find_unguarded_writes(model)
    model.findings.sort(key=lambda f: (f.path, f.rule, f.lineno))
    return model


# -- ratchet (same shape as tools/tpu_lint_baseline.json) -------------------


def counts_of(findings: List[Finding]) -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.key] = counts.get(f.key, 0) + 1
    return counts


def compare_to_baseline(findings: List[Finding], baseline: Dict[str, int]
                        ) -> Tuple[List[Finding], List[str]]:
    by_key: Dict[str, List[Finding]] = {}
    for f in findings:
        by_key.setdefault(f.key, []).append(f)
    new: List[Finding] = []
    for key, fs in sorted(by_key.items()):
        allowed = baseline.get(key, 0)
        if len(fs) > allowed:
            new.extend(fs[allowed:])
    counts = counts_of(findings)
    improved = sorted(k for k, n in baseline.items()
                      if counts.get(k, 0) < n)
    return new, improved


def load_baseline(path: str) -> Dict[str, int]:
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        return dict(json.load(f).get("counts", {}))


def write_baseline(path: str, findings: List[Finding]) -> None:
    data = {
        "comment": "Ratcheted static-concurrency debt: per (file, rule) "
                   "finding counts for lock-cycle / hold-across-blocking "
                   "/ unguarded-shared-write (analysis/concurrency.py). "
                   "Regenerate with `python -m tools.tpu_lint "
                   "--concurrency --update-baseline`; counts may only go "
                   "DOWN in review.",
        "counts": dict(sorted(counts_of(findings).items())),
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2)
        f.write("\n")


# -- docs generation --------------------------------------------------------


def inventory_markdown(model: Model) -> str:
    """The generated section of docs/concurrency.md: the engine's lock
    inventory and the statically observed acquisition order
    (tests/test_docs.py regenerates and compares)."""
    out = ["| Lock | Kind | io_ok | Defined at |",
           "|------|------|-------|------------|"]
    for lid in sorted(model.locks):
        d = model.locks[lid]
        name = d.declared or f"{d.owner + '.' if d.owner else ''}{d.attr}"
        out.append(f"| `{name}` | {d.kind} | "
                   f"{'yes' if d.io_ok else 'no'} | "
                   f"`{d.path}:{d.lineno}` |")
    out.append("")
    out.append("Statically observed acquisition order (outer → inner; "
               "cycles would fail the `lock-cycle` gate):")
    out.append("")
    edges = sorted((a, b) for a, d in model.edges.items() for b in d)
    if not edges:
        out.append("*(no nested acquisitions observed)*")
    for a, b in edges:
        da, db = model.locks[a], model.locks[b]
        na = da.declared or f"{da.owner + '.' if da.owner else ''}{da.attr}"
        nb = db.declared or f"{db.owner + '.' if db.owner else ''}{db.attr}"
        out.append(f"- `{na}` → `{nb}` (at `{model.edges[a][b]}`)")
    out.append("")
    return "\n".join(out) + "\n"


def run(root: str, baseline_path: str, update: bool = False,
        list_all: bool = False) -> int:
    """The ``tools/tpu_lint.py --concurrency`` entry point."""
    import sys
    model = analyze_tree(root)
    findings = [f for f in model.findings]
    if update:
        write_baseline(baseline_path, findings)
        print(f"concurrency baseline updated: {len(findings)} finding(s) "
              f"across {len(counts_of(findings))} (file, rule) key(s)")
        return 0
    if list_all:
        for f in findings:
            print(f)
    baseline = load_baseline(baseline_path)
    new, improved = compare_to_baseline(findings, baseline)
    for k in improved:
        print(f"note: {k} is below its concurrency baseline — tighten "
              "with --concurrency --update-baseline")
    if new:
        print(f"{len(new)} NEW concurrency finding(s) above the baseline:",
              file=sys.stderr)
        for f in new:
            print(f"  {f}", file=sys.stderr)
        return 1
    print(f"concurrency analysis clean: {len(model.locks)} lock(s), "
          f"{sum(len(d) for d in model.edges.values())} order edge(s), "
          f"{len(findings)} baselined finding(s), 0 new")
    return 0
