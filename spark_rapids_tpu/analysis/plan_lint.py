"""Plan-lint — a static schema/partitioning verifier for physical plans.

The reference's tagging pass (``GpuOverrides``) statically proves every
operator convertible *before* execution; nothing in this engine re-verified
the plan the rewrite actually produced, so a bug in a rule (or an encoder
declaring one physical width and serializing another) shipped as a
successful query. This module is the missing static layer: a post-planning
walk that checks, per node,

* **schema consistency** — declared output schema vs child schemas (dtype,
  nullability direction, field order) for every node that *stores* a schema
  rather than deriving it (unions, joins, windows, expand, generate), plus
  reference resolution: every ``AttributeReference`` must name a column of
  the node's input and every ``BoundReference`` ordinal/dtype must agree
  with the input field it points at;
* **cast-lattice legality** — every ``Cast`` in the plan must be a pair the
  engine's cast matrix (:mod:`..ops.cast`) actually implements, so illegal
  casts fail at plan time instead of as a mid-query ``NotImplementedError``;
* **host/device transition correctness** — a node consumes device batches
  iff its children produce them; ``HostToDeviceExec``/``DeviceToHostExec``
  are the only legal flips, and the plan root must be host-side;
* **partitioning contracts** — when both inputs of a shuffled hash join are
  hash-partitioned exchanges they must agree on partition count and be
  partitioned on the join keys (both warn: this single-process engine
  materializes whole sides, so misaligned partitioning degrades, not
  corrupts; CI promotes via ``planLint.failOnWarn``);
* **writer physical-type consistency** — the parquet physical type width
  each column *declares* must equal the width the device encoder actually
  serializes, and ConvertedType annotations must match the parquet spec.
  The spec constants here are declared independently of
  :mod:`..io.parquet_encode` on purpose: the verifier re-derives, it does
  not trust (this exact class of bug silently corrupted smallint/tinyint
  writes before this pass existed).

Violations carry the offending node path. Error severity raises
:class:`PlanLintError`; warn severity is returned to the caller
(``TpuSession.plan`` logs and falls back to the CPU plan). Config:
``spark.rapids.tpu.planLint.enabled`` / ``...planLint.failOnWarn``.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

from .. import types as T
from ..ops.cast import Cast
from ..ops.expression import AttributeReference, BoundReference, Expression

ERROR = "error"
WARN = "warn"


@dataclasses.dataclass(frozen=True)
class PlanLintViolation:
    check: str  # schema | cast | transition | partitioning | writer-width
                # | ml (ModelScore registry contract)
                # | internal (a lint pass itself could not run)
    severity: str   # error | warn
    node_path: str  # e.g. "DeviceToHostExec/TpuProjectExec[0]"
    message: str

    def __str__(self) -> str:
        return f"[{self.severity}] {self.check} at {self.node_path}: " \
               f"{self.message}"


class PlanLintError(Exception):
    """One or more error-severity plan-lint violations."""

    def __init__(self, violations: List[PlanLintViolation]):
        self.violations = list(violations)
        super().__init__(
            "plan verification failed:\n  "
            + "\n  ".join(str(v) for v in violations))


# ---------------------------------------------------------------------------
# Cast lattice (mirrors what ops/cast.py implements on BOTH paths)
# ---------------------------------------------------------------------------

_NUMERIC_ISH = frozenset(
    ["boolean", "tinyint", "smallint", "int", "bigint", "float", "double"])
_STRING_PARSE_TARGETS = _NUMERIC_ISH | {"date", "timestamp"}
_STRING_FORMAT_SOURCES = _NUMERIC_ISH | {"date", "timestamp"}


def legal_cast(src: T.DataType, to: T.DataType) -> bool:
    """True when the engine's cast matrix implements src -> to."""
    if isinstance(src, (T.ArrayType, T.StructType)) \
            or isinstance(to, (T.ArrayType, T.StructType)):
        return src.name == to.name
    if src.name == to.name or src is T.NULL:
        return True
    if src.name in _NUMERIC_ISH and to.name in _NUMERIC_ISH:
        return True
    if src is T.STRING and to.name in _STRING_PARSE_TARGETS:
        return True
    if to is T.STRING and src.name in _STRING_FORMAT_SOURCES:
        return True
    if (src is T.DATE and to is T.TIMESTAMP) \
            or (src is T.TIMESTAMP and to is T.DATE):
        return True
    return False


# ---------------------------------------------------------------------------
# Parquet spec constants — independent copies (see module doc)
# ---------------------------------------------------------------------------

#: physical type code -> PLAIN value width in bytes (None: bit-/length-coded)
_SPEC_PHYS_WIDTH = {0: None, 1: 4, 2: 8, 4: 4, 5: 8, 6: None}
#: engine type name -> ConvertedType the parquet spec assigns it
_SPEC_CONVERTED = {"int": None, "bigint": None, "float": None, "double": None,
                   "boolean": None, "date": 6, "timestamp": 10,
                   "smallint": 16, "tinyint": 15, "string": 0}


# ---------------------------------------------------------------------------
# Plan walking helpers
# ---------------------------------------------------------------------------


def _node_path(path: List[str]) -> str:
    return "/".join(path) if path else "<root>"


def _expr_lists(node) -> List[Tuple[Expression, Optional[T.Schema]]]:
    """(expression, input schema it resolves against) pairs for one node.

    Attribute names are shared between the Cpu and Tpu exec variants, so a
    generic attribute sweep covers both sides of every rewrite rule."""
    out: List[Tuple[Expression, Optional[T.Schema]]] = []
    child = node.children[0].schema if node.children else None
    combined = None
    if len(node.children) == 2:
        combined = T.Schema(list(node.children[0].schema)
                            + list(node.children[1].schema))
    for e in getattr(node, "exprs", []) or []:
        out.append((e, child))
    cond = getattr(node, "condition", None)
    if isinstance(cond, Expression):
        out.append((cond, combined if combined is not None else child))
    for g in getattr(node, "groupings", []) or []:
        out.append((g, child))
    for a in getattr(node, "aggregates", []) or []:
        fn = getattr(a, "func", None)
        if isinstance(fn, Expression):
            out.append((fn, child))
    if len(node.children) == 2:
        left = node.children[0].schema
        right = node.children[1].schema
        for k in getattr(node, "left_keys", []) or []:
            out.append((k, left))
        for k in getattr(node, "right_keys", []) or []:
            out.append((k, right))
    for o in getattr(node, "orders", []) or []:
        out.append((o.child, child))
    for _, we in getattr(node, "window_exprs", []) or []:
        for c in we.func.children:
            out.append((c, child))
        for e in we.spec.partition_by:
            out.append((e, child))
        for o in we.spec.order_by:
            out.append((o.child, child))
    for proj in getattr(node, "projections", []) or []:
        for e in proj:
            out.append((e, child))
    gen = getattr(node, "generator", None)
    if isinstance(gen, Expression):
        out.append((gen, child))
    factory = getattr(node, "partitioner_factory", None)
    if factory is not None:
        for k in getattr(factory, "keys", None) or []:
            out.append((k, child))
        for o in getattr(factory, "orders", None) or []:
            out.append((o.child, child))
    return out


def _walk_expr(e: Expression):
    yield e
    for c in e.children:
        yield from _walk_expr(c)


def _nullable_ok(child_field: T.StructField, out_field: T.StructField) -> bool:
    """Nullability may widen (False -> True) across a node, never narrow:
    a nullable input feeding a non-nullable declared output can produce
    nulls where the schema promises none."""
    return out_field.nullable or not child_field.nullable


# ---------------------------------------------------------------------------
# Per-check passes
# ---------------------------------------------------------------------------


def _check_expressions(node, path, out: List[PlanLintViolation]):
    for expr, schema in _expr_lists(node):
        for e in _walk_expr(expr):
            if isinstance(e, Cast):
                try:
                    src = e.child.data_type
                except Exception:
                    continue  # unresolved subtree; legality unknowable here
                if src is not None and not legal_cast(src, e.to):
                    out.append(PlanLintViolation(
                        "cast", ERROR, _node_path(path),
                        f"illegal cast {src} -> {e.to} in {expr}"))
            elif isinstance(e, AttributeReference) and schema is not None:
                if schema.field_maybe(e._name) is None:
                    out.append(PlanLintViolation(
                        "schema", ERROR, _node_path(path),
                        f"column {e._name!r} referenced by {expr} is not "
                        f"in the input schema {schema}"))
            elif isinstance(e, BoundReference) and schema is not None:
                if not 0 <= e.ordinal < len(schema):
                    out.append(PlanLintViolation(
                        "schema", ERROR, _node_path(path),
                        f"bound ordinal {e.ordinal} out of range for input "
                        f"schema of {len(schema)} columns"))
                elif schema[e.ordinal].data_type.name != e.data_type.name \
                        and e.data_type is not T.NULL:
                    out.append(PlanLintViolation(
                        "schema", ERROR, _node_path(path),
                        f"bound ordinal {e.ordinal} declares "
                        f"{e.data_type} but the input column "
                        f"{schema[e.ordinal].name!r} is "
                        f"{schema[e.ordinal].data_type}"))


def _check_schema(node, path, out: List[PlanLintViolation]):
    name = type(node).__name__
    try:
        schema = node.schema
    except Exception as e:  # schema must always be derivable statically
        out.append(PlanLintViolation(
            "schema", ERROR, _node_path(path),
            f"output schema is not derivable: {e!r}"))
        return
    if "UnionExec" in name:
        for i, c in enumerate(node.children):
            cs = c.schema
            if len(cs) != len(schema):
                out.append(PlanLintViolation(
                    "schema", ERROR, _node_path(path),
                    f"union child {i} has {len(cs)} columns, output "
                    f"declares {len(schema)}"))
                continue
            for cf, of in zip(cs, schema):
                if not legal_cast(cf.data_type, of.data_type):
                    out.append(PlanLintViolation(
                        "schema", ERROR, _node_path(path),
                        f"union child {i} column {cf.name!r}: "
                        f"{cf.data_type} cannot cast to declared "
                        f"{of.data_type}"))
        return
    if _is_equi_join(node) or "NestedLoopJoin" in name \
            or "CartesianProduct" in name:
        jt = getattr(node, "join_type", "inner")
        left, right = node.children[0].schema, node.children[1].schema
        expect = list(left) if jt in ("left_semi", "left_anti") \
            else list(left) + list(right)
        if len(schema) != len(expect):
            out.append(PlanLintViolation(
                "schema", ERROR, _node_path(path),
                f"{jt} join declares {len(schema)} output columns, "
                f"children supply {len(expect)}"))
            return
        for i, (cf, of) in enumerate(zip(expect, schema)):
            if cf.data_type.name != of.data_type.name:
                out.append(PlanLintViolation(
                    "schema", ERROR, _node_path(path),
                    f"join output column {i} ({of.name!r}) declares "
                    f"{of.data_type} but the child supplies "
                    f"{cf.data_type}"))
            elif not _nullable_ok(cf, of):
                out.append(PlanLintViolation(
                    "schema", ERROR, _node_path(path),
                    f"join output column {i} ({of.name!r}) declares "
                    f"non-nullable but the child column is nullable"))
        return
    if "WindowExec" in name:
        child = node.children[0].schema
        if len(schema) < len(child):
            out.append(PlanLintViolation(
                "schema", ERROR, _node_path(path),
                f"window output drops child columns ({len(schema)} < "
                f"{len(child)})"))
            return
        for i, (cf, of) in enumerate(zip(child, schema)):
            if cf.data_type.name != of.data_type.name \
                    or not _nullable_ok(cf, of):
                out.append(PlanLintViolation(
                    "schema", ERROR, _node_path(path),
                    f"window pass-through column {i} ({of.name!r}) "
                    f"declares {of.data_type} but the child supplies "
                    f"{cf.data_type}"))
        return
    if "ExpandExec" in name:
        for pi, proj in enumerate(getattr(node, "projections", []) or []):
            if len(proj) != len(schema):
                out.append(PlanLintViolation(
                    "schema", ERROR, _node_path(path),
                    f"expand projection {pi} has {len(proj)} expressions, "
                    f"output declares {len(schema)} columns"))
                continue
            for e, of in zip(proj, schema):
                try:
                    dt = e.data_type
                except Exception:
                    continue
                if not legal_cast(dt, of.data_type):
                    out.append(PlanLintViolation(
                        "schema", ERROR, _node_path(path),
                        f"expand projection {pi} column {of.name!r}: "
                        f"{dt} cannot cast to declared {of.data_type}"))
        return
    if "GenerateExec" in name:
        child = node.children[0].schema
        for i, (cf, of) in enumerate(zip(child, schema)):
            if cf.data_type.name != of.data_type.name:
                out.append(PlanLintViolation(
                    "schema", ERROR, _node_path(path),
                    f"generate pass-through column {i} ({of.name!r}) "
                    f"declares {of.data_type} but the child supplies "
                    f"{cf.data_type}"))
        return


def _check_transitions(node, path, parent_wants: Optional[bool],
                       out: List[PlanLintViolation]):
    name = type(node).__name__
    columnar = bool(getattr(node, "columnar", False))
    if parent_wants is not None and columnar != parent_wants:
        want = "device (columnar)" if parent_wants else "host"
        have = "device" if columnar else "host"
        out.append(PlanLintViolation(
            "transition", ERROR, _node_path(path),
            f"parent consumes {want} batches but this node produces "
            f"{have} batches — missing "
            f"{'HostToDeviceExec' if parent_wants else 'DeviceToHostExec'}"))
    if name == "HostToDeviceExec":
        wants = False
    elif name == "DeviceToHostExec":
        wants = True
    else:
        wants = bool(getattr(node, "children_columnar", columnar))
    for i, c in enumerate(node.children):
        _check_transitions(c, path + [f"{type(c).__name__}[{i}]"], wants, out)


def _is_equi_join(node) -> bool:
    return type(node).__name__ in (
        "CpuJoinExec", "CpuBroadcastHashJoinExec",
        "TpuShuffledHashJoinExec", "TpuBroadcastHashJoinExec")


def _is_shuffled_join(node) -> bool:
    return type(node).__name__ in ("CpuJoinExec", "TpuShuffledHashJoinExec")


#: nodes that pass their single child's partitioning through unchanged
_PARTITION_PRESERVING = (
    "CpuFilterExec", "TpuFilterExec", "CpuLocalLimitExec",
    "TpuLocalLimitExec", "TpuCoalesceBatchesExec", "HostToDeviceExec",
    "DeviceToHostExec",
)


def _expr_name(e) -> str:
    return getattr(e, "_name", None) or getattr(e, "name", None) or str(e)


def _partitioning(node):
    """Output partitioning property, bottom-up (outputPartitioning analog).
    Returns ("hash", key-name tuple, n_parts) | ("single",) | None."""
    name = type(node).__name__
    if "ShuffleExchangeExec" in name:
        factory = node.partitioner_factory
        mode = getattr(factory, "mode", None)
        if mode == "hash":
            keys = tuple(_expr_name(k)
                         for k in (getattr(factory, "keys", None) or []))
            return ("hash", keys, node.n_parts)
        if mode == "single":
            return ("single",)
        return None
    if name in _PARTITION_PRESERVING and node.children:
        return _partitioning(node.children[0])
    if name in ("CpuProjectExec", "TpuProjectExec"):
        child = _partitioning(node.children[0])
        if child is not None and child[0] == "hash":
            names = {_expr_name(e) for e in node.exprs}
            if all(k in names for k in child[1]):
                return child
        return None
    return None


def _check_partitioning(node, path, out: List[PlanLintViolation]):
    if not _is_shuffled_join(node) or not getattr(node, "left_keys", None):
        return
    lp = _partitioning(node.children[0])
    rp = _partitioning(node.children[1])
    if lp is None or rp is None or lp[0] != "hash" or rp[0] != "hash":
        return
    # Both partitioning violations are WARN: this single-process engine
    # materializes whole join sides, so a broken co-partitioning contract
    # degrades (extra shuffle work) rather than corrupts — and
    # left.repartition(4).join(right.repartition(8)) is a legal API shape
    # that must keep answering. CI promotes via planLint.failOnWarn.
    if lp[2] != rp[2]:
        out.append(PlanLintViolation(
            "partitioning", WARN, _node_path(path),
            f"shuffled join inputs are hash-partitioned into {lp[2]} vs "
            f"{rp[2]} partitions — co-partitioning contract broken"))
    lkeys = tuple(_expr_name(k) for k in node.left_keys)
    rkeys = tuple(_expr_name(k) for k in node.right_keys)
    if lp[1] != lkeys or rp[1] != rkeys:
        out.append(PlanLintViolation(
            "partitioning", WARN, _node_path(path),
            f"shuffled join inputs are hash-partitioned on {lp[1]}/{rp[1]} "
            f"but joined on {lkeys}/{rkeys}; rows with equal join keys may "
            f"land in different partitions"))


def _check_ml(node, path, out: List[PlanLintViolation]):
    """ModelScore contract verification (exec/ml_score.py): the output
    schema must be the child schema plus exactly one nullable float
    score column, and the operator's feature list must satisfy the
    registered model's feature-schema contract — a mismatched handoff
    (model dropped or retrained to a different width between DataFrame
    construction and planning) fails HERE, not as a shape error
    mid-query (docs/ml-integration.md)."""
    if not type(node).__name__.endswith("ModelScoreExec"):
        return
    child = node.children[0].schema
    schema = node.schema
    if len(schema) != len(child) + 1:
        out.append(PlanLintViolation(
            "ml", ERROR, _node_path(path),
            f"ModelScore declares {len(schema)} output columns; the child "
            f"supplies {len(child)} (+1 score column expected)"))
        return
    for i, (cf, of) in enumerate(zip(child, schema)):
        if cf.data_type.name != of.data_type.name \
                or not _nullable_ok(cf, of):
            out.append(PlanLintViolation(
                "ml", ERROR, _node_path(path),
                f"ModelScore pass-through column {i} ({of.name!r}) "
                f"declares {of.data_type} but the child supplies "
                f"{cf.data_type}"))
    score = schema[len(schema) - 1]
    if score.data_type.name != "float" or not score.nullable:
        out.append(PlanLintViolation(
            "ml", ERROR, _node_path(path),
            f"ModelScore score column {score.name!r} must be nullable "
            f"float, declared {score.data_type}"))
    reg = getattr(node, "_ml_registry", None)
    meta = reg.meta_maybe(node.model_name) if reg is not None else None
    if meta is None:
        out.append(PlanLintViolation(
            "ml", ERROR, _node_path(path),
            f"model {node.model_name!r} is not registered on the "
            "session ModelRegistry"))
    elif meta.n_features != len(getattr(node, "exprs", [])):
        out.append(PlanLintViolation(
            "ml", ERROR, _node_path(path),
            f"feature-schema contract: model {node.model_name!r} expects "
            f"{meta.n_features} features, the operator supplies "
            f"{len(node.exprs)}"))
    elif meta.version != getattr(node, "model_version", meta.version):
        out.append(PlanLintViolation(
            "ml", WARN, _node_path(path),
            f"model {node.model_name!r} was re-registered "
            f"(v{meta.version}) after this plan was built "
            f"(v{node.model_version}); re-plan to score the new model"))


def _check_writer(node, path, out: List[PlanLintViolation]):
    if type(node).__name__ != "TpuWriteFilesExec" \
            or getattr(node, "fmt", None) != "parquet":
        return
    from ..io import parquet_encode as PE
    part_cols = set(getattr(node, "partition_by", []) or [])
    for f in node.children[0].schema:
        if f.name in part_cols or f.data_type.name not in PE._PHYS:
            continue
        phys, conv = PE._PHYS[f.data_type.name]
        spec_width = _SPEC_PHYS_WIDTH.get(phys)
        emitted = PE.encoded_value_dtype(f.data_type)
        if spec_width is not None and (emitted is None
                                       or emitted.itemsize != spec_width):
            have = "nothing" if emitted is None \
                else f"{emitted.itemsize}-byte {emitted} values"
            out.append(PlanLintViolation(
                "writer-width", ERROR, _node_path(path),
                f"column {f.name!r} ({f.data_type}) declares a "
                f"{spec_width}-byte parquet physical type but the device "
                f"encoder serializes {have} — readers would see a "
                f"truncated stream"))
        spec_conv = _SPEC_CONVERTED.get(f.data_type.name)
        if conv != spec_conv:
            out.append(PlanLintViolation(
                "writer-width", ERROR, _node_path(path),
                f"column {f.name!r} ({f.data_type}) annotates "
                f"ConvertedType {conv} but the parquet spec assigns "
                f"{spec_conv}"))


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------


def lint_plan(plan, stage: str = "post-overrides"
              ) -> List[PlanLintViolation]:
    """Run every check over the plan; returns all violations (pure)."""
    out: List[PlanLintViolation] = []

    def guarded(check, node, path):
        # The verifier must never crash uncontrolled out of session.plan:
        # a check tripping over an underivable child schema (the child's
        # own visit reports the root cause) degrades to a structured
        # violation, not a raw exception.
        try:
            check(node, path, out)
        except Exception as e:
            out.append(PlanLintViolation(
                "internal", ERROR, _node_path(path),
                f"{check.__name__} could not run: {e!r}"))

    def walk(node, path):
        guarded(_check_schema, node, path)
        guarded(_check_expressions, node, path)
        guarded(_check_partitioning, node, path)
        guarded(_check_writer, node, path)
        guarded(_check_ml, node, path)
        for i, c in enumerate(node.children):
            walk(c, path + [f"{type(c).__name__}[{i}]"])

    root_path = [type(plan).__name__]
    walk(plan, root_path)
    # Transition correctness is a POST-rewrite invariant: the planner's CPU
    # tree legitimately contains device-resident leaves (DeviceSourceExec
    # over cached HBM partitions) under host parents — insert_transitions
    # adds the flips during the overrides pass, so only the rewritten plan
    # is required to be transition-complete.
    if stage == "post-overrides":
        _check_transitions(plan, root_path, None, out)
        if getattr(plan, "columnar", False):
            out.append(PlanLintViolation(
                "transition", ERROR, _node_path(root_path),
                "plan root produces device batches; the root must be "
                "host-side (missing DeviceToHostExec)"))
    return out


def verify_plan(plan, conf=None, stage: str = "post-overrides"
                ) -> List[PlanLintViolation]:
    """Gated entry: raises :class:`PlanLintError` on error-severity
    violations (or any violation under planLint.failOnWarn) and returns
    the surviving warn-severity list for the caller's fallback decision."""
    from ..config import PLAN_LINT_ENABLED, PLAN_LINT_FAIL_ON_WARN
    if conf is not None and not conf.get(PLAN_LINT_ENABLED):
        return []
    violations = lint_plan(plan, stage)
    fail_on_warn = conf is not None and conf.get(PLAN_LINT_FAIL_ON_WARN)
    errors = [v for v in violations
              if v.severity == ERROR or fail_on_warn]
    if errors:
        raise PlanLintError(errors)
    return [v for v in violations if v.severity == WARN]
