"""The compile-once layer — everything that turns "XLA compiles a program
per (plan, shape)" from a cold-start tax into a managed, warmable cache.

The reference engine never compiles device code at query time: libcudf ships
pre-compiled kernels, so the first run of a query is as fast as the tenth.
Under XLA the first run of every (plan shape, capacity bucket) pays a full
compile — seconds on a remote-compile TPU backend — which is the dominant
cold-start cost for a serving system that sees the same query shapes from
millions of users. This package is the analog of the reference's
"kernels are already compiled" property, built from four pieces:

* :mod:`.ladder` — the bucket ladder: every dynamic size in the engine
  (row capacities, string byte capacities) is rounded onto one shared,
  configurable geometric ladder, which bounds the number of distinct
  programs XLA can ever be asked for.
* :mod:`.persist` — the persistent executable cache: wires JAX's on-disk
  compilation cache to the session conf, and keeps a small manifest of
  (plan hash -> capacity vectors) so a NEW process knows which rungs the
  previous one ran.
* :mod:`.executables` — the in-process program cache: one jitted callable
  per plan signature plus AOT-compiled executables per input-aval
  signature, so warm-up work is visible to the dispatch path (jit's own
  lower().compile() does not populate its tracing cache).
* :mod:`.warmup` — AOT warm-up: builds abstract (ShapeDtypeStruct) batches
  at neighbor ladder rungs and compiles them in the background, so a
  growing dataset never stalls at a rung boundary and a restarted process
  re-compiles everything it served yesterday before the first query.

See docs/compile-cache.md for the user-facing story.
"""

from __future__ import annotations

from .ladder import BucketLadder, bucket_capacity, get_ladder, set_ladder

__all__ = [
    "BucketLadder",
    "bucket_capacity",
    "get_ladder",
    "set_ladder",
    "configure",
]


def configure(conf) -> dict:
    """Configure every compile-layer global from a :class:`..config.TpuConf`
    snapshot: the process bucket ladder, the persistent XLA cache, and the
    warm-up worker. Called by ``TpuSession`` at construction; idempotent.

    Returns a status dict (ladder + persistent-cache state) for
    diagnostics."""
    from . import budget as _budget
    from . import persist as _persist
    from . import warmup as _warmup
    ladder = _ladder_from_conf(conf)
    if ladder != get_ladder() and _programs_exist():
        # Capacities bake into compiled programs: changing the ladder
        # mid-process (e.g. with_conf on a live session) silently carries
        # BOTH rung populations — the duplication this layer exists to
        # prevent. Allowed, but never silent.
        import warnings
        warnings.warn(
            "bucket ladder reconfigured after programs were compiled "
            f"({get_ladder()} -> {ladder}); existing sessions will "
            "re-bucket onto the new rungs and already-compiled programs "
            "for the old rungs stay resident (docs/compile-cache.md)",
            stacklevel=3)
    set_ladder(ladder)
    cache_status = _persist.configure(conf)
    _warmup.configure(conf)
    _budget.configure(conf)
    return {"ladder": ladder, "persistent_cache": dict(cache_status)}


def _programs_exist() -> bool:
    from ..exec import fusion
    from ..utils import kernel_cache
    return bool(fusion._FUSED_CACHE) \
        or kernel_cache.cache_stats()["entries"] > 0


def _ladder_from_conf(conf) -> BucketLadder:
    from ..config import (POLYMORPHIC_TIER_GROWTH, TPU_CAPACITY_BUCKETING,
                          TPU_LADDER_GROWTH, TPU_LADDER_MAX_CAPACITY,
                          TPU_MIN_CAPACITY)
    return BucketLadder(
        min_capacity=conf.get(TPU_MIN_CAPACITY),
        growth=conf.get(TPU_LADDER_GROWTH),
        max_capacity=conf.get(TPU_LADDER_MAX_CAPACITY),
        enabled=conf.get(TPU_CAPACITY_BUCKETING),
        tier_growth=conf.get(POLYMORPHIC_TIER_GROWTH),
    )
