"""Compile-cost budget — feeds observed compile time back into fusion.

The fusion planner's default is maximal: inline every join into ONE
whole-stage program. That is the right call when compiles are cheap, and
catastrophically wrong on slow remote-compile backends, where a
many-join fused region (TPC-H q3: 18 kernels; bb_q01) can spend minutes
in XLA while the query itself runs in milliseconds. This module closes
the loop: the fused dispatch path reports how long each region's first
compile actually took (:func:`note_compile`), and when a region blows
``spark.rapids.tpu.fusion.compileBudgetSecs`` the plan's **split level**
escalates so the NEXT build of the same plan splits the region at its
most expensive boundary:

* level 0 — inline everything the conf allows (the default planner).
* level 1 — demote the single largest inlined join (by inline subtree
  size) to a fusion boundary: the region splits roughly in half, each
  half a separately cached compile.
* level 2 — demote every join (the ``fusion.inlineJoins=false`` shape):
  per-join kernels amortize across queries on their own.

Levels are remembered per plan hash for the process and persisted in the
compile manifest (:mod:`.persist`) when the cache is on, so a restarted
process splits the historically expensive plans from the first build —
"historically blew the budget" genuinely means history, not this
process's first painful compile repeated every morning.

Splitting never changes results (a demoted join just runs on the eager
boundary path that ``fusion.inlineJoins=false`` already exercises); it
only trades fused-region size against compile cost.
"""

from __future__ import annotations

import logging
from typing import Dict

from ..utils import lockdep
from . import persist

_LOG = logging.getLogger(__name__)

#: Escalation ceiling: past "every join is a boundary" there is nothing
#: coarser to split (scans/windows/shuffles are already boundaries).
MAX_SPLIT_LEVEL = 2

_LOCK = lockdep.lock("budget._LOCK")
_BUDGET_SECS = 120.0
_LEVELS: Dict[str, int] = {}
_SECONDS: Dict[str, float] = {}
_STATS = {"compiles_noted": 0, "splits_escalated": 0}

#: Bound the in-memory maps like the manifest bounds its index.
_MAX_PLANS = 512


def configure(conf) -> None:
    """Apply the conf's budget key to the process (idempotent)."""
    global _BUDGET_SECS
    from ..config import FUSION_COMPILE_BUDGET_SECS
    with _LOCK:
        _BUDGET_SECS = float(conf.get(FUSION_COMPILE_BUDGET_SECS))


def has_levels() -> bool:
    """True when ANY plan has an escalated split level (in memory or in
    the manifest) — the fused dispatch path's fast-path check, so the
    common no-escalations process never pays a plan hash per dispatch."""
    with _LOCK:
        if _LEVELS:
            return True
    m = persist.manifest()
    return m is not None and m.has_split_levels()


def split_level(plan_hash: str) -> int:
    """The fusion split level for ``plan_hash`` — in-memory history
    first, then the compile manifest (a restarted process inherits the
    previous one's escalations). Only ESCALATED levels are cached:
    caching level-0 misses would let a later-configured manifest be
    shadowed forever and could evict genuine escalations from the
    bounded map."""
    with _LOCK:
        lvl = _LEVELS.get(plan_hash)
    if lvl is not None:
        return lvl
    m = persist.manifest()
    lvl = m.split_level(plan_hash) if m is not None else 0
    if lvl:
        with _LOCK:
            while len(_LEVELS) >= _MAX_PLANS:
                _LEVELS.pop(next(iter(_LEVELS)))
            lvl = _LEVELS.setdefault(plan_hash, lvl)
    return lvl


def note_compile(plan_hash: str, seconds: float, level: int) -> None:
    """Record one fused-region compile observed at ``level``; escalate
    the plan's split level when it blew the budget. Called from the
    fused dispatch path only for dispatches that actually compiled."""
    with _LOCK:
        _STATS["compiles_noted"] += 1
        _SECONDS[plan_hash] = _SECONDS.get(plan_hash, 0.0) + float(seconds)
        while len(_SECONDS) > _MAX_PLANS:
            _SECONDS.pop(next(iter(_SECONDS)))
        escalate = (_BUDGET_SECS > 0 and seconds > _BUDGET_SECS
                    and level >= _LEVELS.get(plan_hash, 0)
                    and level < MAX_SPLIT_LEVEL)
        if escalate:
            _LEVELS[plan_hash] = level + 1
            _STATS["splits_escalated"] += 1
    if not escalate:
        return
    _LOG.info(
        "fused region for plan %s compiled in %.1fs (budget %.0fs); "
        "future builds split the region at level %d (%s)",
        plan_hash, seconds, _BUDGET_SECS, level + 1,
        "largest join demoted to a boundary" if level + 1 == 1
        else "every join demoted to a boundary")
    m = persist.manifest()
    if m is not None:
        m.record_split_level(plan_hash, level + 1)


def stats() -> dict:
    with _LOCK:
        return {
            "budget_secs": _BUDGET_SECS,
            "plans_tracked": len(_SECONDS),
            "compile_seconds_total": round(sum(_SECONDS.values()), 3),
            "splits_escalated": _STATS["splits_escalated"],
            "compiles_noted": _STATS["compiles_noted"],
            "split_levels": {h: lvl for h, lvl in _LEVELS.items() if lvl},
        }


def reset_for_tests() -> None:
    global _BUDGET_SECS
    with _LOCK:
        _BUDGET_SECS = 120.0
        _LEVELS.clear()
        _SECONDS.clear()
        for k in _STATS:
            _STATS[k] = 0
