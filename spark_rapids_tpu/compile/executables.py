"""In-process compiled-program cache with AOT-visible dispatch.

``jax.jit``'s tracing cache is populated only by CALLING the wrapped
function with concrete arguments; programs built through the AOT path
(``fn.lower(...).compile()``) never enter it. A warm-up pass that relied
on ``lower().compile()`` alone would therefore leave the hot dispatch
path re-tracing and re-compiling the very shapes it just warmed — the
work would land in the persistent on-disk cache but the first real query
would still pay tracing plus a cache probe.

:class:`FusedProgram` closes that gap by holding both sides in one
object: the jitted callable AND a table of AOT-compiled executables
keyed by the input aval signature. Dispatch prefers the AOT table (a
dict probe on static shapes), so anything :mod:`.warmup` compiled in the
background — or replayed from a previous process via the compile
manifest — is hit directly, with the jit path as the always-correct
fallback for shapes nobody warmed.
"""

from __future__ import annotations

import time
import weakref
from typing import Dict, Set, Tuple

from ..utils import lockdep

import jax

#: Every live FusedProgram, for aggregate diagnostics (bench.py,
#: TpuSession.compile_status). Weak: programs die with their cache entry.
_REGISTRY: "weakref.WeakSet[FusedProgram]" = weakref.WeakSet()


def aval_signature(tree) -> tuple:
    """Hashable (treedef, leaf shapes/dtypes) signature of an argument
    pytree — exactly the specialization key ``jax.jit`` uses, minus weak
    types. Works on concrete arrays and ``ShapeDtypeStruct`` templates
    alike, so a warmed abstract shape and the concrete batch that later
    arrives at it produce the SAME key."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return (treedef,
            tuple((tuple(int(d) for d in leaf.shape), str(leaf.dtype))
                  for leaf in leaves))


def abstract_like(tree):
    """``ShapeDtypeStruct`` template of a concrete pytree. Safe to hold on
    the warm-up queue: no device buffers stay pinned through it."""
    return jax.tree_util.tree_map(
        lambda leaf: jax.ShapeDtypeStruct(tuple(leaf.shape), leaf.dtype),
        tree)


class FusedProgram:
    """One compiled query program: a jitted callable plus its AOT table.

    Stored in ``exec.fusion._FUSED_CACHE`` per structural plan signature;
    callers invoke it exactly like the bare jitted function.
    """

    def __init__(self, fn, label: str = ""):
        self.fn = fn
        self.label = label
        self._aot: Dict[tuple, object] = {}
        #: Aval signatures the jit path has already traced+compiled —
        #: how dispatch knows a call is a reuse, not a fresh compile
        #: (the polymorphic compile counters and the fusion compile-cost
        #: budget both key off this).
        self._jit_seen: Set[tuple] = set()
        self._lock = lockdep.lock("FusedProgram._lock")
        self._stats = {"aot_hits": 0, "aot_call_errors": 0, "jit_calls": 0,
                       "aot_compiles": 0, "jit_compiles": 0,
                       "compile_seconds": 0.0}
        _REGISTRY.add(self)

    def __call__(self, *args):
        key = aval_signature(args)
        with self._lock:
            exe = self._aot.get(key)
        if exe is not None:
            try:
                out = exe(*args)
                with self._lock:
                    self._stats["aot_hits"] += 1
                return out
            except (TypeError, ValueError):
                # Aval subtleties the signature cannot see (weak types,
                # commitments): the jit path below is always correct.
                with self._lock:
                    self._stats["aot_call_errors"] += 1
        with self._lock:
            new_shape = key not in self._jit_seen
        t0 = time.perf_counter()
        out = self.fn(*args)
        with self._lock:
            self._stats["jit_calls"] += 1
            if new_shape and key not in self._jit_seen:
                # First call at this signature paid trace+compile (the
                # execution itself dispatches async and is not waited on
                # here, so the wall time is ~all compile).
                self._jit_seen.add(key)
                self._stats["jit_compiles"] += 1
                self._stats["compile_seconds"] += time.perf_counter() - t0
        return out

    def seen(self, *args) -> bool:
        """True when dispatching ``args`` cannot trigger a fresh XLA
        compile: the aval signature is in the AOT table or has already
        gone down the jit path."""
        key = aval_signature(args)
        with self._lock:
            return key in self._aot or key in self._jit_seen

    def jit_compiled(self, *args) -> bool:
        """True when the jit path has compiled EXACTLY this signature.
        Checked before/after a dispatch it attributes a compile to the
        key that actually paid it — immune to concurrent compiles of
        other signatures on the same program, and it still catches the
        rare AOT-table fall-through that :meth:`seen` cannot."""
        key = aval_signature(args)
        with self._lock:
            return key in self._jit_seen

    def compile_abstract(self, args: Tuple) -> str:
        """AOT-compile for the given (possibly abstract) argument tuple.
        Returns ``"compiled"``, or ``"cached"`` when the shape is already
        warm. With the persistent cache on, the XLA compile inside
        ``lower().compile()`` deserializes from disk when a previous
        process built the same HLO."""
        key = aval_signature(args)
        with self._lock:
            if key in self._aot:
                return "cached"
        exe = self.fn.lower(*args).compile()
        with self._lock:
            if key in self._aot:
                return "cached"
            self._aot[key] = exe
            self._stats["aot_compiles"] += 1
        return "compiled"

    @property
    def n_aot(self) -> int:
        with self._lock:
            return len(self._aot)

    def stats(self) -> dict:
        return dict(self._stats, aot_executables=self.n_aot)


def stats() -> dict:
    """Aggregate dispatch/warm-up counters over every live program.
    ``jit_compiles`` counts distinct aval signatures actually compiled
    through jit; ``jit_calls - jit_compiles + aot_hits`` is therefore
    the number of dispatches an already-built executable served — the
    polymorphic reuse the compile layer exists to maximize."""
    total = {"programs": 0, "aot_executables": 0, "aot_hits": 0,
             "aot_call_errors": 0, "jit_calls": 0, "aot_compiles": 0,
             "jit_compiles": 0, "compile_seconds": 0.0}
    for prog in list(_REGISTRY):
        s = prog.stats()
        total["programs"] += 1
        for k in ("aot_executables", "aot_hits", "aot_call_errors",
                  "jit_calls", "aot_compiles", "jit_compiles",
                  "compile_seconds"):
            total[k] += s[k]
    total["compile_seconds"] = round(total["compile_seconds"], 6)
    return total
