"""Bucket-ladder control — ONE shared capacity ladder for the whole engine.

Every compiled XLA program in this engine is keyed (through the batch pytree
treedef and leaf avals) on static capacities: row capacities, string byte
capacities, dictionary sizes. The seed hard-wired "round up to a power of
two" at ~40 call sites through ``data.column.bucket_capacity``; this module
makes that policy an object:

* ``growth`` controls the rung spacing. 2.0 reproduces the power-of-two
  ladder; 4.0 quarters the program population at the price of up to 4x
  padding (attractive when compiles are served by a slow remote helper);
  1.5 halves the padding waste at ~1.7x the program count.
* ``min_capacity`` floors the ladder (the conf key
  ``spark.rapids.tpu.minCapacity``, previously registered but never read).
  A serving deployment that never sees small batches can start the ladder
  at its typical size and avoid compiling the tiny rungs entirely.
* ``max_capacity`` caps the ladder: requests above it get an exact
  lane-aligned fit instead of the next geometric rung, bounding padded HBM
  waste for huge batches (the programs up there are rare and data-bound,
  so program-count control matters less than memory).
* ``enabled=False`` degrades to bare lane alignment — one program per
  distinct 128-row count. Only sensible for debugging compile-cache
  behavior; the conf key existed since the seed and now actually works.
* ``tier_growth`` spaces the POLYMORPHIC TIER ladder — the coarse
  sub-ladder the shape-polymorphic fused path (``exec/fusion.py``) pads
  boundary inputs onto, so one compiled executable serves every bucket
  rung inside a tier. ``tier()`` maps a capacity to its tier; tiers are
  always bucket rungs themselves, so the mapping is idempotent for any
  ``growth``. 4.0 (default) bounds padding waste at 4x while merging
  every ~2 power-of-two rungs into one executable; 16.0 merges 4 rungs
  per executable (right for compile-dominated remote backends) at up to
  16x padding. See docs/compile-cache.md.

Rungs are always multiples of the 8x128 VPU lane layout. The ladder is
process-global (``get_ladder``/``set_ladder``) because capacities bake into
compiled programs: two sessions with different ladders would silently
double the program population, which is exactly what this layer exists to
prevent. ``TpuSession`` configures it from the conf at construction.
"""

from __future__ import annotations

import dataclasses
from typing import List

from ..utils import lockdep

#: Lane width of the VPU — the minimum sensible capacity granularity.
LANE = 128


def _align_up(n: int, step: int = LANE) -> int:
    return -(-int(n) // step) * step


@dataclasses.dataclass(frozen=True)
class BucketLadder:
    """Immutable capacity-ladder policy. ``bucket`` is the hot call."""

    min_capacity: int = LANE
    growth: float = 2.0
    max_capacity: int = 0  # 0 = unbounded ladder
    enabled: bool = True
    tier_growth: float = 4.0  # polymorphic tier spacing (see class doc)

    def __post_init__(self):
        if self.growth < 1.125:
            raise ValueError(f"ladder growth {self.growth} must be >= 1.125 "
                             "(below that rungs collapse to lane steps)")
        if self.tier_growth < 1.125:
            raise ValueError(f"tier growth {self.tier_growth} must be >= "
                             "1.125 (below that tiers collapse to rungs)")
        if self.min_capacity < 1:
            raise ValueError("min_capacity must be positive")

    @property
    def base(self) -> int:
        return _align_up(max(self.min_capacity, LANE))

    def bucket(self, n: int, min_capacity: int = LANE) -> int:
        """Smallest rung >= n (and >= max(min_capacity, ladder base)).

        Matches the seed's ``bucket_capacity`` exactly at the default
        ``growth=2.0, min_capacity=128``: powers of two starting at 128.
        """
        n = max(int(n), 1)
        cap = max(self.base, _align_up(max(int(min_capacity), 1)))
        if not self.enabled:
            return max(cap, _align_up(n))
        top = _align_up(self.max_capacity) if self.max_capacity > 0 else 0
        while cap < n:
            if top and cap >= top:
                # Above the ladder top: exact lane-aligned fit, no rung.
                return _align_up(n)
            cap = self._next(cap)
        return cap

    def bucket_bytes(self, n: int, min_capacity: int = LANE) -> int:
        """Byte/dictionary-capacity variant: same geometric climb, but the
        conf row-capacity floor/cap (``min_capacity``/``max_capacity``) do
        NOT apply — raising ``spark.rapids.tpu.minCapacity`` to skip tiny
        row rungs must not inflate string payload, dictionary, or decode
        scratch buffers (which call in with their own small floors)."""
        n = max(int(n), 1)
        cap = max(_align_up(max(int(min_capacity), 1)), LANE)
        if not self.enabled:
            return max(cap, _align_up(n))
        while cap < n:
            cap = self._next(cap)
        return cap

    def _next(self, cap: int) -> int:
        """The rung above ``cap`` (strictly greater, lane aligned)."""
        return max(_align_up(cap * self.growth), cap + LANE)

    def next_up(self, cap: int, steps: int = 1) -> int:
        """``steps`` rungs above the rung containing ``cap``."""
        cap = self.bucket(cap)
        for _ in range(max(steps, 0)):
            cap = self._next(cap)
        return cap

    def next_down(self, cap: int, steps: int = 1) -> int:
        """``steps`` rungs below the rung containing ``cap`` (floored at
        the ladder base)."""
        target = self.bucket(cap)
        for _ in range(max(steps, 0)):
            if target <= self.base:
                return self.base
            target = self._prev(target)
        return target

    def _prev(self, cap: int) -> int:
        lo, step = self.base, self.base
        while (nxt := self._next(step)) < cap:
            lo, step = step, nxt
        return lo if step >= cap else step

    def rungs(self, lo: int, hi: int) -> List[int]:
        """Every rung covering ``[lo, hi]`` (inclusive), ascending."""
        out = [cap := self.bucket(lo)]
        while cap < hi:
            cap = self._next(cap)
            out.append(cap)
        return out

    def tier(self, n: int, min_capacity: int = LANE) -> int:
        """The polymorphic capacity tier containing ``n``: the smallest
        rung of the coarse tier ladder (``tier_growth`` spacing, anchored
        at the base) that is >= ``bucket(n)``. Tier values are always
        bucket rungs, so ``tier(tier(n)) == tier(n)`` for any growth.

        The shape-polymorphic fused path pads boundary inputs up to their
        tier, collapsing every rung inside it onto ONE executable. Above
        the ladder top (``max_capacity``) dispatch already uses exact
        lane-aligned fits, so no tiering applies there; with bucketing
        disabled the tier degrades to the bare aligned fit too."""
        cap = self.bucket(n, min_capacity)
        if not self.enabled:
            return cap
        if self.max_capacity > 0:
            top = self.bucket(self.max_capacity)
            if cap >= top:
                return cap
        t = self.base
        while t < cap:
            # bucket() snaps the geometric tier point onto a real rung,
            # which is what keeps the mapping idempotent for growths
            # that are not integer powers of each other.
            t = self.bucket(max(_align_up(t * self.tier_growth), t + LANE))
        if self.max_capacity > 0:
            t = min(t, self.bucket(self.max_capacity))
        return t

    def tiers(self, lo: int, hi: int) -> List[int]:
        """Every polymorphic tier covering ``[lo, hi]``, ascending
        (tools/bake_executables.py enumerates the corpus with this).
        Above a configured ladder top there are no tiers — dispatch uses
        exact lane-aligned fits there — so enumeration stops at the top
        rung instead of degenerating to one entry per lane step; with
        bucketing disabled no tier ladder exists at all, so only the
        endpoints are returned."""
        if not self.enabled:
            lo_t, hi_t = self.tier(lo), self.tier(max(hi, lo))
            return [lo_t] if hi_t <= lo_t else [lo_t, hi_t]
        if self.max_capacity > 0:
            hi = min(hi, self.bucket(self.max_capacity))
        out = [cap := self.tier(lo)]
        while cap < hi:
            nxt = self.tier(cap + LANE)
            if nxt <= cap:
                break
            out.append(cap := nxt)
        return out


_LOCK = lockdep.lock("ladder._LOCK")
_LADDER = BucketLadder()


def get_ladder() -> BucketLadder:
    return _LADDER


def set_ladder(ladder: BucketLadder) -> None:
    global _LADDER
    with _LOCK:
        _LADDER = ladder


def bucket_capacity(n: int, min_capacity: int = LANE) -> int:
    """Round ``n`` up onto the process bucket ladder (the drop-in body of
    the seed's ``data.column.bucket_capacity``, which now delegates here)."""
    return _LADDER.bucket(n, min_capacity)


def bucket_byte_capacity(n: int, min_capacity: int = LANE) -> int:
    """Round a byte/dictionary capacity up the process ladder WITHOUT the
    conf row floor/cap (see :meth:`BucketLadder.bucket_bytes`)."""
    return _LADDER.bucket_bytes(n, min_capacity)
