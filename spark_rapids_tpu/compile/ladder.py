"""Bucket-ladder control — ONE shared capacity ladder for the whole engine.

Every compiled XLA program in this engine is keyed (through the batch pytree
treedef and leaf avals) on static capacities: row capacities, string byte
capacities, dictionary sizes. The seed hard-wired "round up to a power of
two" at ~40 call sites through ``data.column.bucket_capacity``; this module
makes that policy an object:

* ``growth`` controls the rung spacing. 2.0 reproduces the power-of-two
  ladder; 4.0 quarters the program population at the price of up to 4x
  padding (attractive when compiles are served by a slow remote helper);
  1.5 halves the padding waste at ~1.7x the program count.
* ``min_capacity`` floors the ladder (the conf key
  ``spark.rapids.tpu.minCapacity``, previously registered but never read).
  A serving deployment that never sees small batches can start the ladder
  at its typical size and avoid compiling the tiny rungs entirely.
* ``max_capacity`` caps the ladder: requests above it get an exact
  lane-aligned fit instead of the next geometric rung, bounding padded HBM
  waste for huge batches (the programs up there are rare and data-bound,
  so program-count control matters less than memory).
* ``enabled=False`` degrades to bare lane alignment — one program per
  distinct 128-row count. Only sensible for debugging compile-cache
  behavior; the conf key existed since the seed and now actually works.

Rungs are always multiples of the 8x128 VPU lane layout. The ladder is
process-global (``get_ladder``/``set_ladder``) because capacities bake into
compiled programs: two sessions with different ladders would silently
double the program population, which is exactly what this layer exists to
prevent. ``TpuSession`` configures it from the conf at construction.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import List

#: Lane width of the VPU — the minimum sensible capacity granularity.
LANE = 128


def _align_up(n: int, step: int = LANE) -> int:
    return -(-int(n) // step) * step


@dataclasses.dataclass(frozen=True)
class BucketLadder:
    """Immutable capacity-ladder policy. ``bucket`` is the hot call."""

    min_capacity: int = LANE
    growth: float = 2.0
    max_capacity: int = 0  # 0 = unbounded ladder
    enabled: bool = True

    def __post_init__(self):
        if self.growth < 1.125:
            raise ValueError(f"ladder growth {self.growth} must be >= 1.125 "
                             "(below that rungs collapse to lane steps)")
        if self.min_capacity < 1:
            raise ValueError("min_capacity must be positive")

    @property
    def base(self) -> int:
        return _align_up(max(self.min_capacity, LANE))

    def bucket(self, n: int, min_capacity: int = LANE) -> int:
        """Smallest rung >= n (and >= max(min_capacity, ladder base)).

        Matches the seed's ``bucket_capacity`` exactly at the default
        ``growth=2.0, min_capacity=128``: powers of two starting at 128.
        """
        n = max(int(n), 1)
        cap = max(self.base, _align_up(max(int(min_capacity), 1)))
        if not self.enabled:
            return max(cap, _align_up(n))
        top = _align_up(self.max_capacity) if self.max_capacity > 0 else 0
        while cap < n:
            if top and cap >= top:
                # Above the ladder top: exact lane-aligned fit, no rung.
                return _align_up(n)
            cap = self._next(cap)
        return cap

    def bucket_bytes(self, n: int, min_capacity: int = LANE) -> int:
        """Byte/dictionary-capacity variant: same geometric climb, but the
        conf row-capacity floor/cap (``min_capacity``/``max_capacity``) do
        NOT apply — raising ``spark.rapids.tpu.minCapacity`` to skip tiny
        row rungs must not inflate string payload, dictionary, or decode
        scratch buffers (which call in with their own small floors)."""
        n = max(int(n), 1)
        cap = max(_align_up(max(int(min_capacity), 1)), LANE)
        if not self.enabled:
            return max(cap, _align_up(n))
        while cap < n:
            cap = self._next(cap)
        return cap

    def _next(self, cap: int) -> int:
        """The rung above ``cap`` (strictly greater, lane aligned)."""
        return max(_align_up(cap * self.growth), cap + LANE)

    def next_up(self, cap: int, steps: int = 1) -> int:
        """``steps`` rungs above the rung containing ``cap``."""
        cap = self.bucket(cap)
        for _ in range(max(steps, 0)):
            cap = self._next(cap)
        return cap

    def next_down(self, cap: int, steps: int = 1) -> int:
        """``steps`` rungs below the rung containing ``cap`` (floored at
        the ladder base)."""
        target = self.bucket(cap)
        for _ in range(max(steps, 0)):
            if target <= self.base:
                return self.base
            target = self._prev(target)
        return target

    def _prev(self, cap: int) -> int:
        lo, step = self.base, self.base
        while (nxt := self._next(step)) < cap:
            lo, step = step, nxt
        return lo if step >= cap else step

    def rungs(self, lo: int, hi: int) -> List[int]:
        """Every rung covering ``[lo, hi]`` (inclusive), ascending."""
        out = [cap := self.bucket(lo)]
        while cap < hi:
            cap = self._next(cap)
            out.append(cap)
        return out


_LOCK = threading.Lock()
_LADDER = BucketLadder()


def get_ladder() -> BucketLadder:
    return _LADDER


def set_ladder(ladder: BucketLadder) -> None:
    global _LADDER
    with _LOCK:
        _LADDER = ladder


def bucket_capacity(n: int, min_capacity: int = LANE) -> int:
    """Round ``n`` up onto the process bucket ladder (the drop-in body of
    the seed's ``data.column.bucket_capacity``, which now delegates here)."""
    return _LADDER.bucket(n, min_capacity)


def bucket_byte_capacity(n: int, min_capacity: int = LANE) -> int:
    """Round a byte/dictionary capacity up the process ladder WITHOUT the
    conf row floor/cap (see :meth:`BucketLadder.bucket_bytes`)."""
    return _LADDER.bucket_bytes(n, min_capacity)
