"""Persistent XLA executable cache + compile manifest.

Two cooperating pieces of cross-process memory:

1. **JAX persistent compilation cache** — XLA executables keyed by HLO
   hash, written under ``spark.rapids.tpu.compileCache.dir``. With it, a
   restarted process pays deserialization (milliseconds) instead of
   compilation (seconds per program on remote-compile backends) for every
   program any previous process built.

2. **Compile manifest** (``tpu_compile_manifest.json`` in the same dir) —
   the engine-level index the JAX cache lacks: which (plan signature,
   capacity vector) pairs were actually executed. The JAX cache can only
   answer "have I compiled this exact HLO"; the manifest lets a NEW
   process *ask the right questions* — warm-up replays the recorded rungs
   through AOT lowering (:mod:`.warmup`), each of which then hits the
   on-disk executable, so cold start collapses to tracing time.

Safety: the environment kill-switch ``JAX_ENABLE_COMPILATION_CACHE=false``
always wins (the CPU test tier sets it because replaying cross-machine AOT
artifacts can SIGILL; some remote-compile helpers deadlock on the cache —
see bench.py and tests/conftest.py). Configuration failures degrade to
disabled, never to an error: a broken cache must not break queries.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Callable, Dict, List, Optional, Tuple

from ..utils import lockdep

_LOCK = lockdep.lock("persist._LOCK", io_ok=True)
_STATUS: Dict[str, object] = {"enabled": False, "reason": "not configured"}
_MANIFEST: Optional["CompileManifest"] = None
#: True while this process's jax config points at our cache dir — so a
#: later disable actually reverts it instead of only updating _STATUS.
_APPLIED = False

#: Bounds on the manifest so it stays a small index, not a log.
_MAX_PLANS = 256
_MAX_VECTORS_PER_PLAN = 8

MANIFEST_NAME = "tpu_compile_manifest.json"


def _env_killed() -> bool:
    return os.environ.get("JAX_ENABLE_COMPILATION_CACHE", "").strip().lower() \
        in ("false", "0", "no")


def default_cache_dir() -> str:
    return os.path.join(os.path.expanduser("~"), ".cache",
                        "spark_rapids_tpu", "xla")


def configure(conf) -> Dict[str, object]:
    """Apply the conf's compile-cache keys to the process. Idempotent;
    returns the resulting status dict (also available via :func:`status`)."""
    global _MANIFEST, _APPLIED
    from ..config import (COMPILE_CACHE_DIR, COMPILE_CACHE_ENABLED,
                          COMPILE_CACHE_MIN_COMPILE_SECS)
    with _LOCK:
        if not conf.get(COMPILE_CACHE_ENABLED):
            _deactivate_locked("disabled by conf")
            return dict(_STATUS)
        if _env_killed():
            _deactivate_locked(
                "JAX_ENABLE_COMPILATION_CACHE=false in environment")
            return dict(_STATUS)
        cache_dir = conf.get(COMPILE_CACHE_DIR) or default_cache_dir()
        try:
            os.makedirs(cache_dir, exist_ok=True)
            _apply_jax_config(cache_dir,
                              conf.get(COMPILE_CACHE_MIN_COMPILE_SECS))
            _APPLIED = True
        except Exception as e:  # noqa: BLE001 - cache must never break queries
            _deactivate_locked(f"jax cache config failed: {e}")
            return dict(_STATUS)
        if _MANIFEST is None or _MANIFEST.path != \
                os.path.join(cache_dir, MANIFEST_NAME):
            _MANIFEST = CompileManifest(os.path.join(cache_dir,
                                                     MANIFEST_NAME))
        _STATUS.update(enabled=True, reason="", dir=cache_dir)
        return dict(_STATUS)


def _deactivate_locked(reason: str) -> None:
    """Turn the cache OFF for real: revert any jax config this module
    applied earlier, not just the reported status (a session disabling the
    key — or the env kill-switch appearing — must stop XLA persisting and
    replaying executables)."""
    global _MANIFEST, _APPLIED
    if _APPLIED:
        # The compile layer is process-global and follows the most
        # recently constructed session's conf: flipping OFF a cache an
        # earlier session enabled is allowed, but never silent.
        import warnings
        warnings.warn(
            f"persistent compile cache deactivated ({reason}); it was "
            "enabled by an earlier session's conf — the compile layer is "
            "process-global (docs/compile-cache.md)", stacklevel=4)
        try:
            _revert_jax_config()
        except Exception:  # noqa: BLE001 - cache must never break queries
            pass
        _APPLIED = False
    _STATUS.clear()
    _STATUS.update(enabled=False, reason=reason)
    _MANIFEST = None


def _revert_jax_config() -> None:
    import jax
    jax.config.update("jax_enable_compilation_cache", False)
    try:
        jax.config.update("jax_compilation_cache_dir", None)
    except Exception:  # noqa: BLE001 - enable=False is the load-bearing one
        pass


def _apply_jax_config(cache_dir: str, min_secs: float) -> None:
    import jax
    updates = {
        "jax_enable_compilation_cache": True,
        "jax_compilation_cache_dir": cache_dir,
        "jax_persistent_cache_min_compile_time_secs": float(min_secs),
        # Entry size floor of 0: tiny shrink/transition kernels recompile
        # per rung too, and on remote-compile links they are not cheap.
        "jax_persistent_cache_min_entry_size_bytes": 0,
    }
    for key, value in updates.items():
        try:
            jax.config.update(key, value)
        except AttributeError:
            # Older jax without this knob: the dir + enable flags are the
            # load-bearing ones and exist back to 0.4.x.
            if key in ("jax_enable_compilation_cache",
                       "jax_compilation_cache_dir"):
                raise


def status() -> Dict[str, object]:
    with _LOCK:
        return dict(_STATUS)


def manifest() -> Optional["CompileManifest"]:
    """The configured manifest, or None when the cache is off."""
    with _LOCK:
        return _MANIFEST


def plan_hash(plan_sig: tuple) -> str:
    """Stable short hash of a structural plan signature
    (utils.kernel_cache.plan_signature output: type names + primitives,
    deterministic across processes)."""
    return hashlib.sha256(repr(plan_sig).encode()).hexdigest()[:16]


def _to_jsonable(v):
    if isinstance(v, (list, tuple)):
        return [_to_jsonable(x) for x in v]
    return int(v)


def _to_hashable(v):
    if isinstance(v, list):
        return tuple(_to_hashable(x) for x in v)
    return int(v)


class CompileManifest:
    """Tiny crash-safe index: plan hash -> capacity vectors executed.

    A capacity vector mirrors the nesting of a fused program's boundary
    inputs (boundary -> partition -> batch) with each batch replaced by
    its integer row capacity — exactly what :mod:`.warmup` needs to
    rebuild abstract inputs for another rung. Writes are atomic
    (tmp + rename); a corrupt or missing file loads as empty.
    """

    def __init__(self, path: str):
        self.path = path
        self._lock = lockdep.lock("CompileManifest._lock", io_ok=True)
        self._plans: Dict[str, List[tuple]] = {}
        #: plan hash -> fusion split level (compile/budget.py): plans
        #: whose fused region historically blew the compile budget.
        self._levels: Dict[str, int] = {}
        self._load()

    def _load(self) -> None:
        try:
            with open(self.path, encoding="utf-8") as f:
                data = json.load(f)
            for h, vecs in data.get("plans", {}).items():
                self._plans[str(h)] = [_to_hashable(v) for v in vecs]
            for h, lvl in data.get("split_levels", {}).items():
                self._levels[str(h)] = int(lvl)
        except (OSError, ValueError):
            self._plans = {}
            self._levels = {}

    def record(self, plan_hash_: str, cap_vector: tuple) -> bool:
        """Remember that ``plan_hash_`` ran with ``cap_vector``. Returns
        True (and flushes) when the pair is new."""
        with self._lock:
            vecs = self._plans.setdefault(plan_hash_, [])
            if cap_vector in vecs:
                return False
            vecs.append(cap_vector)
            del vecs[:-_MAX_VECTORS_PER_PLAN]
            while len(self._plans) > _MAX_PLANS:
                self._plans.pop(next(iter(self._plans)))
            self._flush_locked()
            return True

    def vectors_for(self, plan_hash_: str,
                    canonicalize: Optional[Callable] = None) -> List[tuple]:
        """Recorded capacity vectors for a plan. With ``canonicalize``
        (the polymorphic tier mapper — warmup.py passes capacity->tier),
        vectors are mapped through it and DEDUPED post-map: a manifest
        written by per-rung processes holds one vector per rung, and
        replaying those raw would recompile the same polymorphic
        executable once per recorded rung on every restart."""
        with self._lock:
            vecs = list(self._plans.get(plan_hash_, []))
        if canonicalize is None:
            return vecs
        out: List[tuple] = []
        seen = set()
        for v in vecs:
            cv = canonicalize(v)
            if cv not in seen:
                seen.add(cv)
                out.append(cv)
        return out

    def split_level(self, plan_hash_: str) -> int:
        """Fusion split level recorded for a plan (compile/budget.py)."""
        with self._lock:
            return int(self._levels.get(plan_hash_, 0))

    def has_split_levels(self) -> bool:
        with self._lock:
            return bool(self._levels)

    def record_split_level(self, plan_hash_: str, level: int) -> None:
        """Remember that ``plan_hash_``'s fused region blew the compile
        budget and future builds should split at ``level``."""
        with self._lock:
            if self._levels.get(plan_hash_) == int(level):
                return
            self._levels[plan_hash_] = int(level)
            while len(self._levels) > _MAX_PLANS:
                self._levels.pop(next(iter(self._levels)))
            self._flush_locked()

    def _flush_locked(self) -> None:
        data = {
            "comment": "Compile manifest: capacity vectors each plan "
                       "signature has executed with; warm-up replays "
                       "them after restart (docs/compile-cache.md). "
                       "split_levels records plans whose fused region "
                       "blew the compile budget (compile/budget.py).",
            "plans": {h: [_to_jsonable(v) for v in vecs]
                      for h, vecs in self._plans.items()},
        }
        if self._levels:
            data["split_levels"] = dict(self._levels)
        tmp = self.path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(data, f, indent=1)
            os.replace(tmp, self.path)
        except OSError:
            pass  # manifest is an optimization; never fail the query


def reset_for_tests() -> None:
    global _MANIFEST, _APPLIED
    with _LOCK:
        _MANIFEST = None
        _APPLIED = False
        _STATUS.clear()
        _STATUS.update(enabled=False, reason="not configured")
