"""AOT warm-up — compile neighbor ladder rungs before the data gets there.

The bucket ladder (:mod:`.ladder`) bounds how many programs a query shape
can ever need; this module makes sure the NEXT one is already compiled
when a growing dataset crosses a rung boundary, and that a restarted
process re-builds everything the previous one served (the compile
manifest, :mod:`.persist`) before the second query.

Mechanics: after a fused query dispatches, :func:`note_run` records the
run's **capacity vector** — the nesting of the fused program's boundary
inputs (boundary -> partition -> batch) with each batch replaced by its
integer row capacity — in the manifest, then (when
``spark.rapids.tpu.warmup.auto`` is on) enqueues AOT compiles for:

* the same vector scaled to neighboring ladder rungs
  (``warmup.rungsAhead`` / ``warmup.rungsBehind``), and
* every vector the manifest recorded for this plan in ANY process.

A single daemon worker drains the queue through
:meth:`..compile.executables.FusedProgram.compile_abstract`, so warmed
shapes are visible to the dispatch path (plain ``lower().compile()``
would not be — see executables.py). The queue holds only
``ShapeDtypeStruct`` templates: no device buffers are pinned by pending
warm-ups, and a warm-up failure only increments a counter — it can never
fail a query.

Best-effort by design: rebucketing rescales array dimensions that match
the batch's row capacity, so a warmed rung is exact for fixed-width and
dict-encoded-string batches (the engine default) and approximate when an
unrelated static dimension (flat-string byte capacity) happens to grow in
step; a miss there costs one ordinary jit compile, nothing more.
"""

from __future__ import annotations

import atexit
import logging
import threading
import time
from collections import deque
from typing import List, Optional

import jax

from ..utils import lockdep
from . import persist
from .executables import FusedProgram, abstract_like
from .ladder import get_ladder

_LOG = logging.getLogger(__name__)

_CV = lockdep.condition("warmup._CV")
_QUEUE: deque = deque()
_WORKER: Optional[threading.Thread] = None
_INFLIGHT = 0
_AUTO = False
_AHEAD = 1
_BEHIND = 0
_STATS = {"scheduled": 0, "compiled": 0, "already_cached": 0, "errors": 0,
          "skipped_covered": 0}

#: Worker exits after this long with nothing to do; it restarts on demand.
_IDLE_EXIT_SECS = 60.0

#: At interpreter exit, wait at most this long for an in-flight compile.
_SHUTDOWN_JOIN_SECS = 120.0
_SHUTDOWN = False
_ATEXIT_REGISTERED = False


def configure(conf) -> None:
    """Apply the conf's warm-up keys to the process (idempotent)."""
    global _AUTO, _AHEAD, _BEHIND
    from ..config import (WARMUP_AUTO, WARMUP_RUNGS_AHEAD,
                          WARMUP_RUNGS_BEHIND)
    with _CV:
        _AUTO = bool(conf.get(WARMUP_AUTO))
        _AHEAD = max(int(conf.get(WARMUP_RUNGS_AHEAD)), 0)
        _BEHIND = max(int(conf.get(WARMUP_RUNGS_BEHIND)), 0)


def capacity_vector(inputs) -> tuple:
    """Nested row-capacity vector of a fused program's boundary inputs:
    tuples mirror the nesting, each ColumnarBatch becomes its capacity."""
    if isinstance(inputs, tuple):
        return tuple(capacity_vector(x) for x in inputs)
    return int(inputs.capacity)


def _map_vec(vec, f):
    if isinstance(vec, tuple):
        return tuple(_map_vec(v, f) for v in vec)
    return int(f(int(vec)))


def _neighbor_vectors(vec) -> List[tuple]:
    ladder = get_ladder()
    out = []
    for step in range(1, _AHEAD + 1):
        out.append(_map_vec(vec, lambda c: ladder.next_up(c, step)))
    for step in range(1, _BEHIND + 1):
        out.append(_map_vec(vec, lambda c: ladder.next_down(c, step)))
    if ladder.max_capacity > 0:
        # Above the ladder top, dispatch uses exact lane-aligned fits —
        # a geometric rung up there can never be dispatched, so compiling
        # it would be pure waste.
        top = ladder.bucket(ladder.max_capacity)
        out = [v for v in out if _max_cap(v) <= top]
    return out


def _max_cap(vec) -> int:
    if isinstance(vec, tuple):
        return max((_max_cap(v) for v in vec), default=0)
    return int(vec)


def _rebucket(template, vec):
    """Abstract boundary inputs with every batch re-capacitied to ``vec``
    (same nesting as :func:`capacity_vector`)."""
    if isinstance(template, tuple):
        return tuple(_rebucket(t, v) for t, v in zip(template, vec))
    return _rebucket_batch(template, int(vec))


def _rebucket_batch(batch, new_cap: int):
    old = batch.capacity
    if new_cap == old:
        return batch

    def leaf(x):
        shape = list(x.shape)
        if shape and shape[0] == old:
            shape[0] = new_cap          # data/validity/codes/lengths/live
        elif shape and shape[0] == old + 1:
            shape[0] = new_cap + 1      # string offsets
        return jax.ShapeDtypeStruct(tuple(shape), x.dtype)
    return jax.tree_util.tree_map(leaf, batch)


def note_run(program: FusedProgram, plan_sig: tuple, inputs,
             polymorphic: bool = False) -> None:
    """Post-dispatch hook from the fused execution path: record the run's
    capacity vector in the compile manifest and schedule background AOT
    warm-ups. Called between program dispatch and the result download so
    scheduling overlaps the transfer; near-free when both the persistent
    cache and auto warm-up are off (``plan_sig`` is hashed only past the
    early exit).

    With ``polymorphic`` (the caller dispatched tier-padded inputs),
    every candidate rung is canonicalized through the ladder's tier
    mapping first: a neighbor or manifest rung inside an already-running
    tier CANNOT miss — its dispatch pads onto this very executable — so
    warming it would only burn the compile thread. Skips are counted
    (``skipped_covered``) and logged at DEBUG."""
    m = persist.manifest()
    with _CV:
        auto = _AUTO
    if m is None and not auto:
        return
    plan_hash_ = persist.plan_hash(plan_sig)
    vec = capacity_vector(inputs)
    ladder = get_ladder()
    canon = (lambda v: _map_vec(v, ladder.tier)) if polymorphic else None
    recorded: List[tuple] = []
    if m is not None:
        recorded = m.vectors_for(plan_hash_, canonicalize=canon)
        m.record(plan_hash_, vec)
    if not auto or _SHUTDOWN:
        return
    seen = {vec}
    targets = []
    skipped = 0
    for v in _neighbor_vectors(vec) + recorded:
        cv = canon(v) if canon is not None else v
        if cv in seen:
            # Count only genuine tier collapses (the raw rung differed
            # from its tier): a vector that was already a duplicate
            # pre-canonicalization — e.g. the plan's own recorded tier
            # on every steady-state dispatch — is not a skipped warm-up.
            if cv != v:
                skipped += 1
            continue
        seen.add(cv)
        targets.append(cv)
    if skipped:
        with _CV:
            _STATS["skipped_covered"] += skipped
        _LOG.debug(
            "plan %s: skipped %d neighbor/manifest rung warm-up(s) already "
            "covered by the polymorphic tier executable", plan_hash_,
            skipped)
    if not targets:
        return
    template = abstract_like(inputs)
    with _CV:
        for v in targets:
            _QUEUE.append((program, template, v))
            _STATS["scheduled"] += 1
        _ensure_worker_locked()
        _CV.notify_all()


def _ensure_worker_locked() -> None:
    global _WORKER, _ATEXIT_REGISTERED
    if _SHUTDOWN:
        return
    if _WORKER is None or not _WORKER.is_alive():
        _WORKER = threading.Thread(target=_work, name="tpu-compile-warmup",
                                   daemon=True)
        _WORKER.start()
        if not _ATEXIT_REGISTERED:
            # A daemon thread frozen mid-XLA-compile while C++ static
            # destructors run aborts the process (std::terminate at exit,
            # observed on the CPU backend). Stop scheduling and join the
            # in-flight compile before the interpreter finalizes.
            atexit.register(_stop_at_exit)
            _ATEXIT_REGISTERED = True


def _stop_at_exit() -> None:
    global _SHUTDOWN
    with _CV:
        _SHUTDOWN = True
        _QUEUE.clear()
        _CV.notify_all()
    worker = _WORKER
    if worker is not None and worker.is_alive():
        worker.join(timeout=_SHUTDOWN_JOIN_SECS)


def _work() -> None:
    global _INFLIGHT, _WORKER
    while True:
        with _CV:
            if not _QUEUE and not _CV.wait(timeout=_IDLE_EXIT_SECS) \
                    and not _QUEUE:
                # Idle exit. Clear _WORKER under the lock so a concurrent
                # note_run cannot observe a still-alive-but-exiting thread
                # and strand its freshly queued warm-ups.
                _WORKER = None
                return
            if _SHUTDOWN:
                return
            if not _QUEUE:
                continue
            program, template, vec = _QUEUE.popleft()
            _INFLIGHT += 1
        try:
            t0 = time.monotonic()
            abstract = _rebucket(template, vec)
            result = program.compile_abstract((abstract,))
            with _CV:
                _STATS["compiled" if result == "compiled"
                       else "already_cached"] += 1
            if result == "compiled":
                # Flight-recorder breadcrumb (metrics/trace.py, ISSUE
                # 13): warm-up compiles run outside any query's trace,
                # but a post-mortem dump must still show the compile
                # thread's activity (Flare's amortized-compilation
                # thesis: these vanish from warm timelines).
                from ..metrics import trace as _trace
                _trace.record_event(
                    "compile.warmup", label=program.label,
                    secs=round(time.monotonic() - t0, 3))
        except Exception:  # noqa: BLE001 - warm-up must never fail a query
            with _CV:
                _STATS["errors"] += 1
        finally:
            with _CV:
                _INFLIGHT -= 1
                _CV.notify_all()


def quiesce(timeout: float = 10.0) -> bool:
    """Drop every queued warm-up and wait out the in-flight compile —
    the ``TpuSession.close`` step. Unlike :func:`_stop_at_exit` this
    does NOT set the permanent shutdown flag (a session used after
    close keeps working, and later sessions re-arm the worker), and it
    is safe for CONCURRENT closers: each just clears the queue and
    waits under the condition — no join of a thread another closer may
    already have observed dying (the one-closer assumption the serving
    pool reaper violates; docs/serving.md). True when quiesced, False
    on timeout."""
    deadline = time.monotonic() + timeout
    with _CV:
        _QUEUE.clear()
        _CV.notify_all()
        while _INFLIGHT:
            left = deadline - time.monotonic()
            if left <= 0:
                return False
            _CV.wait(left)
    return True


def drain(timeout: float = 60.0) -> bool:
    """Block until the warm-up queue is empty and no compile is in flight
    (tests/diagnostics). True when drained, False on timeout."""
    deadline = time.monotonic() + timeout
    with _CV:
        while _QUEUE or _INFLIGHT:
            left = deadline - time.monotonic()
            if left <= 0:
                return False
            _CV.wait(left)
    return True


def stats() -> dict:
    with _CV:
        return dict(_STATS, queued=len(_QUEUE), in_flight=_INFLIGHT,
                    auto=_AUTO, rungs_ahead=_AHEAD, rungs_behind=_BEHIND)


def reset_for_tests() -> None:
    global _AUTO, _AHEAD, _BEHIND
    with _CV:
        _QUEUE.clear()
        for k in _STATS:
            _STATS[k] = 0
        _AUTO, _AHEAD, _BEHIND = False, 1, 0
        _CV.notify_all()
