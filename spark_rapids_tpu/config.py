"""Typed configuration registry — the analog of ``RapidsConf``.

The reference builds every config key through a typed builder DSL that records
the key, type, default, and doc string in one registry, then auto-generates
``docs/configs.md`` from it (reference: ``RapidsConf.scala:100-170`` for the
builders, ``:641`` for the doc generator). Per-operator enable keys are
synthesized from class names (``GpuOverrides.scala:126-131``).

We keep the same architecture: ``ConfEntry`` descriptors registered at import
time, a ``TpuConf`` snapshot object with typed accessors, and
``TpuConf.help_markdown()`` regenerating the user docs. Key namespace follows
the reference (``spark.rapids.sql.*``) with TPU-specific keys under
``spark.rapids.tpu.*``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional

from .utils import lockdep as _lockdep

_REGISTRY: Dict[str, "ConfEntry"] = {}
#: registrations normally happen at module import, but extension points
#: (and the serving layer's worker-reachable call graph) make the write
#: path formally concurrent — the registry mutates under a lock.
_REGISTRY_LOCK = _lockdep.lock("config._REGISTRY_LOCK")


@dataclasses.dataclass(frozen=True)
class ConfEntry:
    key: str
    default: Any
    doc: str
    conv: Callable[[str], Any]
    internal: bool = False

    def get(self, conf: Dict[str, Any]) -> Any:
        if self.key in conf:
            v = conf[self.key]
            return self.conv(v) if isinstance(v, str) else v
        return self.default


def _to_bool(s: str) -> bool:
    return s.strip().lower() in ("true", "1", "yes")


def _register(key, default, doc, conv, internal=False) -> ConfEntry:
    with _REGISTRY_LOCK:
        if key in _REGISTRY:
            raise ValueError(f"duplicate conf key {key}")
        e = ConfEntry(key, default, doc, conv, internal)
        _REGISTRY[key] = e
    return e


def conf_bool(key: str, default: bool, doc: str, internal: bool = False) -> ConfEntry:
    return _register(key, default, doc, _to_bool, internal)


def conf_int(key: str, default: int, doc: str, internal: bool = False) -> ConfEntry:
    return _register(key, default, doc, int, internal)


def conf_float(key: str, default: float, doc: str, internal: bool = False) -> ConfEntry:
    return _register(key, default, doc, float, internal)


def conf_str(key: str, default: Optional[str], doc: str, internal: bool = False) -> ConfEntry:
    return _register(key, default, doc, str, internal)


# ---------------------------------------------------------------------------
# Core feature gates (reference RapidsConf.scala:329-478)
# ---------------------------------------------------------------------------

SQL_ENABLED = conf_bool(
    "spark.rapids.sql.enabled", True,
    "Enable or disable the TPU columnar execution of SQL plans entirely.")

EXPLAIN = conf_str(
    "spark.rapids.sql.explain", "NONE",
    "Explain why parts of a query were or were not placed on the TPU. "
    "Options: NONE, NOT_ON_TPU, ALL.")

TEST_ENABLED = conf_bool(
    "spark.rapids.sql.test.enabled", False,
    "Intended for internal tests only: fail if any operator in an executed plan "
    "fell back to the CPU instead of running on the TPU.")

TEST_ALLOWED_NON_TPU = conf_str(
    "spark.rapids.sql.test.allowedNonTpu", "",
    "Comma-separated operator class names allowed to stay on CPU when "
    "spark.rapids.sql.test.enabled is on.")

INCOMPATIBLE_OPS = conf_bool(
    "spark.rapids.sql.incompatibleOps.enabled", False,
    "Enable operators that produce results that differ from Spark in corner "
    "cases (e.g. float-to-string formatting).")

HAS_NANS = conf_bool(
    "spark.rapids.sql.hasNans", True,
    "Assume floating point data may contain NaNs; disables some device "
    "aggregations/joins on float keys unless set to false.")

VARIABLE_FLOAT_AGG = conf_bool(
    "spark.rapids.sql.variableFloatAgg.enabled", False,
    "Allow float/double aggregations whose result can differ from CPU Spark "
    "because parallel reduction order is not fixed.")

IMPROVED_FLOAT_OPS = conf_bool(
    "spark.rapids.sql.improvedFloatOps.enabled", False,
    "Enable float ops (e.g. float->string cast) that do not match Spark exactly.")

CAST_FLOAT_TO_STRING = conf_bool(
    "spark.rapids.sql.castFloatToString.enabled", False,
    "Enable float/double to string casts; formatting can differ from Spark.")

CAST_STRING_TO_FLOAT = conf_bool(
    "spark.rapids.sql.castStringToFloat.enabled", False,
    "Enable string to float casts; some edge-case strings parse differently.")

CAST_STRING_TO_TIMESTAMP = conf_bool(
    "spark.rapids.sql.castStringToTimestamp.enabled", False,
    "Enable string to timestamp casts; only fixed formats are supported.")

REPLACE_SORT_MERGE_JOIN = conf_bool(
    "spark.rapids.sql.replaceSortMergeJoin.enabled", True,
    "Replace sort-merge joins with hash joins on the device "
    "(reference RapidsConf.scala:384).")

EXPORT_COLUMNAR_RDD = conf_bool(
    "spark.rapids.sql.exportColumnarRdd", False,
    "Allow exporting device-resident columnar batches to ML frameworks "
    "zero-copy (reference RapidsConf.scala:329).")

UDF_COMPILER_ENABLED = conf_bool(
    "spark.rapids.sql.udfCompiler.enabled", True,
    "Compile Python UDF bytecode into the expression IR so UDFs run as fused "
    "XLA/Pallas code instead of falling back to the CPU.")

# ---------------------------------------------------------------------------
# Batch sizing (reference RapidsConf.scala:306-325)
# ---------------------------------------------------------------------------

BATCH_SIZE_ROWS = conf_int(
    "spark.rapids.sql.batchSizeRows", 1 << 20,
    "Target number of rows for device batches produced by coalescing and reads.")

MAX_READ_BATCH_SIZE_ROWS = conf_int(
    "spark.rapids.sql.reader.batchSizeRows", 1 << 19,
    "Soft limit on rows per batch produced by file readers.")

MAX_READ_BATCH_SIZE_BYTES = conf_int(
    "spark.rapids.sql.reader.batchSizeBytes", 512 * 1024 * 1024,
    "Soft limit on bytes per batch produced by file readers.")

# ---------------------------------------------------------------------------
# Memory & admission (reference RapidsConf.scala:241-301)
# ---------------------------------------------------------------------------

SORT_EXTERNAL_THRESHOLD = conf_int(
    "spark.rapids.sql.sort.externalThresholdBytes", 0,
    "Accumulated input bytes above which a global sort switches to the "
    "external merge-sort path (sorted runs through the spill store, "
    "bounded device residency). 0 = auto: a quarter of the device spill "
    "budget. The reference bounds sorts with RequireSingleBatch + the "
    "spill store (GpuSortExec.scala:50); the external path removes the "
    "single-batch ceiling.")

CONCURRENT_TPU_TASKS = conf_int(
    "spark.rapids.sql.concurrentTpuTasks", 2,
    "Number of tasks that may hold the TPU concurrently "
    "(reference spark.rapids.sql.concurrentGpuTasks).")

CONCURRENT_ACQUIRE_TIMEOUT = conf_float(
    "spark.rapids.tpu.concurrentTpuTasks.acquireTimeout", 0.0,
    "Seconds a task may block acquiring the admission semaphore before "
    "failing with a diagnostic error naming the holding threads and their "
    "held counts (a silent deadlock becomes an actionable failure). 0 "
    "waits forever. See docs/fault-tolerance.md.")

RETRY_MAX_RETRIES = conf_int(
    "spark.rapids.tpu.retry.maxRetries", 3,
    "In-place retries of an operator attempt after a classified OOM or "
    "transient fault (memory/retry.py) before escalating: OOMs escalate "
    "to splitting the input batch in half by rows (SplitAndRetryOOM at "
    "unsplittable sites), transients re-raise. Each OOM retry first "
    "synchronizes the device and spills every spillable buffer below "
    "on-deck priority. See docs/fault-tolerance.md.")

RETRY_BACKOFF_BASE_MS = conf_float(
    "spark.rapids.tpu.retry.backoffBaseMs", 10.0,
    "Base delay for the capped exponential retry backoff (doubles per "
    "attempt, deterministic jitter derived from the site name). 0 "
    "disables sleeping between retries (test hook).")

RETRY_BACKOFF_MAX_MS = conf_float(
    "spark.rapids.tpu.retry.backoffMaxMs", 1000.0,
    "Ceiling on one retry backoff sleep, milliseconds.")

FAULT_INJECTION_SITES = conf_str(
    "spark.rapids.tpu.test.faultInjection.sites", "",
    "Intended for tests: comma-separated retry-site names (or prefixes; "
    "'*' matches every site) where the deterministic fault injector "
    "raises synthetic faults (utils/fault_injection.py). Empty disables "
    "injection. Site names are listed in docs/fault-tolerance.md.")

FAULT_INJECTION_SEED = conf_int(
    "spark.rapids.tpu.test.faultInjection.seed", 0,
    "Phase/flavor seed for the fault injector: shifts WHICH visit of a "
    "site faults and which transient flavor (remote-compile race vs "
    "spill-disk OSError) is raised. Same seed = same fault schedule.")

FAULT_INJECTION_OOM_EVERY_N = conf_int(
    "spark.rapids.tpu.test.faultInjection.oomEveryN", 0,
    "Raise a synthetic RESOURCE_EXHAUSTED at every Nth visit of each "
    "matched injection site; negative N faults the FIRST |N| visits and "
    "then heals (the schedule that exhausts a site's retries into a "
    "split while still letting the query finish). 0 disables OOM "
    "injection; N=1 faults every visit (drives sites to "
    "SplitAndRetryOOM).")

FAULT_INJECTION_TRANSIENT_EVERY_N = conf_int(
    "spark.rapids.tpu.test.faultInjection.transientEveryN", 0,
    "Raise a synthetic transient fault (remote-compile helper race or "
    "spill-disk OSError, flavor chosen deterministically from the seed) "
    "at every Nth visit of each matched injection site; negative N "
    "faults the first |N| visits then heals. 0 disables.")

FAULT_INJECTION_NET_EVERY_N = conf_int(
    "spark.rapids.tpu.test.faultInjection.netEveryN", 0,
    "Apply a deterministic NETWORK fault at every Nth visit of the "
    "matched shuffle-transport site (shuffle.fetchBlock — one visit per "
    "block fetch; the 'sites' patterns gate it). Negative N faults the "
    "first |N| "
    "visits then heals — the schedule that exercises refetch and "
    "recompute while letting the query finish. The fault class per "
    "visit is chosen deterministically from the seed among "
    "faultInjection.netFaults. 0 disables.")

FAULT_INJECTION_NET_FAULTS = conf_str(
    "spark.rapids.tpu.test.faultInjection.netFaults",
    "peerDeath,torn,bitFlip,stall",
    "Comma-separated network fault classes the injector may apply: "
    "peerDeath (connection dies mid-fetch), torn (payload truncated "
    "mid-block), bitFlip (one payload bit corrupted — caught by CRC32C), "
    "stall (peer stops sending past "
    "spark.rapids.tpu.shuffle.net.requestTimeout), replicaLoss (the "
    "replication push at the shuffle.replicate seam is silently "
    "dropped, so a later primary failure must fall through to lineage "
    "recompute — not in the default set, preserving pre-replication "
    "fault schedules). A single name pins every injected fault to that "
    "class.")

FAULT_INJECTION_NET_STALL_SECS = conf_float(
    "spark.rapids.tpu.test.faultInjection.netStallSecs", 0.05,
    "How long an injected 'stall' fault blocks before surfacing as the "
    "request-timeout failure the real stalled peer would produce (kept "
    "small so CI fault matrices stay fast).")

FAULT_INJECTION_MESH_EVERY_N = conf_int(
    "spark.rapids.tpu.test.faultInjection.meshEveryN", 0,
    "Raise a synthetic MeshDegradedError (a mid-query device loss) at "
    "every Nth visit of the matched mesh site (mesh.collect — one visit "
    "per SPMD dispatch; the 'sites' patterns gate it). Negative N "
    "faults the first |N| visits then heals. The session records the "
    "failover (meshFailovers metric, flight-recorder dump) and re-runs "
    "the query on the single-chip path — the degraded-mesh drill real "
    "device loss cannot provide in CI. 0 disables.")

HBM_ALLOC_FRACTION = conf_float(
    "spark.rapids.memory.tpu.allocFraction", 0.9,
    "Fraction of HBM the arena allocator may use "
    "(reference spark.rapids.memory.gpu.allocFraction).")

HOST_SPILL_STORAGE_SIZE = conf_int(
    "spark.rapids.memory.host.spillStorageSize", 1 << 30,
    "Bytes of host memory used to hold spilled device buffers before "
    "overflowing to disk (reference RapidsConf.scala:274).")

MEMORY_DEBUG = conf_bool(
    "spark.rapids.memory.tpu.debug", False,
    "Log every device allocation/free for leak hunting "
    "(reference spark.rapids.memory.gpu.debug).")

SPILL_DIR = conf_str(
    "spark.rapids.memory.tpu.spillDir", None,
    "Directory for the disk spill tier; defaults to a fresh temp directory "
    "(reference uses Spark's disk block manager directories).")

DEVICE_SPILL_BUDGET = conf_int(
    "spark.rapids.memory.tpu.spillBudgetBytes", 0,
    "Explicit device-store byte budget for spillable buffers; 0 derives it "
    "from allocFraction of detected HBM (test hook for forcing spills).")

SPILL_IO_THREADS = conf_int(
    "spark.rapids.tpu.spill.ioThreads", 2,
    "Concurrency of the dedicated spill-IO lane on the shared pipeline "
    "pool: device<->host copies, spill-file appends/reads, and disk-tier "
    "shuffle-block I/O run OFF the catalog lock with up to this many "
    "units in flight, so concurrent spills overlap and no thread ever "
    "waits on a catalog lock held across I/O. 0 runs spill I/O inline on "
    "the requesting thread (still off-lock, just without overlap). See "
    "docs/fault-tolerance.md#async-spill and docs/tuning-guide.md.")

TENANT_ID = conf_str(
    "spark.rapids.tpu.tenantId", "",
    "Session/tenant identity for memory QoS: spill victim selection "
    "prefers the requesting query's own buffers, then same-tenant "
    "buffers, then other tenants ordered by query-deadline slack — so "
    "one tenant's OOM-retry ladder stops evicting a neighbor's hot "
    "build tables (docs/fault-tolerance.md#async-spill). Empty = the "
    "default shared tenant.")

AUTO_BROADCAST_JOIN_ROWS = conf_int(
    "spark.rapids.sql.autoBroadcastJoinRows", 100_000,
    "Equi joins whose build side is estimated at or below this many rows "
    "plan as broadcast hash joins; -1 disables (row-count analog of "
    "spark.sql.autoBroadcastJoinThreshold).")

ORC_DEVICE_DECODE = conf_bool(
    "spark.rapids.sql.orc.deviceDecode.enabled", True,
    "Decode ORC stripes ON DEVICE: the host parses the protobuf tail, "
    "stripe footers, and RLEv2 run headers into compact run tables; "
    "traced kernels expand runs to rows, scatter non-null slots through "
    "the PRESENT bitmask, and gather dictionary codes (the GpuOrcScan "
    "stripe-reassembly split, GpuOrcScan.scala:65,211). Stripes outside "
    "the decoder's scope fall back to the host reader per stripe.")

PARQUET_DEVICE_DECODE = conf_bool(
    "spark.rapids.sql.parquet.deviceDecode.enabled", True,
    "Decode parquet pages ON DEVICE: the host parses footers/page headers "
    "and uploads raw page bytes + RLE run tables; traced kernels expand "
    "definition levels and dictionary indices (the GpuParquetScan -> "
    "Table.readParquet split, GpuParquetScan.scala:365-388). Row groups "
    "outside the decoder's scope fall back to the host reader per unit.")

PARQUET_REBASE_READ = conf_str(
    "spark.sql.legacy.parquet.datetimeRebaseModeInRead", "EXCEPTION",
    "Spark's own rebase-mode key, honored by the device parquet reader "
    "(the RebaseHelper.scala:60 guard): EXCEPTION raises on "
    "legacy-calendar files whose date/timestamp statistics reach below "
    "the 1582-10-15 / 1900-01-01 switchover (this reader never "
    "rebases), CORRECTED reads raw proleptic values, LEGACY is "
    "unsupported.")

CSV_DEVICE_DECODE = conf_bool(
    "spark.rapids.sql.csv.deviceDecode.enabled", True,
    "Parse CSV ON DEVICE (the GpuBatchScanExec.scala:87 cudf-csv role): "
    "the host finds line/field boundaries in one vectorized pass, the "
    "raw bytes upload once, and a traced digit-DP kernel converts "
    "int/double/bool columns while string columns gather their char "
    "matrix from the same buffer. Files with quoted fields, custom null "
    "tokens, or values beyond the DP's exact range fall back to the "
    "host reader per file.")

PARQUET_DEVICE_ENCODE = conf_bool(
    "spark.rapids.sql.parquet.deviceEncode.enabled", True,
    "Encode parquet ON DEVICE (the Table.writeParquetChunked split, "
    "GpuParquetFileFormat.scala:243): a traced kernel compacts def-level "
    "and value lanes in encoding order; the host RLE-frames pages and "
    "writes the thrift footer. Columns outside the encoder's scope fall "
    "back to the host Arrow writer per file.")

ADAPTIVE_ENABLED = conf_bool(
    "spark.rapids.sql.adaptive.enabled", False,
    "Re-plan shuffle reads with OBSERVED map-output sizes: coalesce "
    "adjacent small reduce partitions toward the target size, and split "
    "skewed partitions by map ranges where co-partitioning is not required "
    "(GpuCustomShuffleReaderExec.scala:38 / ShuffledBatchRDD.scala:31-105 "
    "analog). Off by default because every exchange here carries a "
    "user-specified partition count, which Spark's AQE also respects.")

ADAPTIVE_TARGET_SIZE = conf_int(
    "spark.rapids.sql.adaptive.targetPartitionSizeBytes", 64 << 20,
    "Advisory serialized size per post-shuffle partition for adaptive "
    "coalescing/splitting (spark.sql.adaptive.advisoryPartitionSizeInBytes "
    "analog).")

WINDOW_EXTERNAL_THRESHOLD = conf_int(
    "spark.rapids.sql.window.externalThresholdBytes", 0,
    "Window inputs above this many device bytes evaluate in bounded "
    "chunks: the input external-sorts by the (shared) partition-by keys "
    "through the spill catalog and complete key groups stream one chunk "
    "at a time (GpuWindowExec + spill store interplay). 0 = a quarter "
    "of the device spill budget. Chunked output rows arrive partition-"
    "sorted rather than in input order.")

ADAPTIVE_BROADCAST_THRESHOLD = conf_int(
    "spark.rapids.sql.adaptive.autoBroadcastThresholdBytes", 10 << 20,
    "Re-plan a shuffled exchange whose OBSERVED output is at most this "
    "many serialized bytes into a broadcast-style mapper-local read "
    "(PartialMapper specs, ShuffledBatchRDD.scala:31-105): reduce-side "
    "routing is skipped and downstream joins build from the whole "
    "(small) output. Range exchanges never convert (order contract).")

ADAPTIVE_SKEW_FACTOR = conf_float(
    "spark.rapids.sql.adaptive.skewedPartitionFactor", 5.0,
    "A reduce partition is skewed when its size exceeds this multiple of "
    "the median partition size (and the threshold below).")

ADAPTIVE_SKEW_THRESHOLD = conf_int(
    "spark.rapids.sql.adaptive.skewedPartitionThresholdBytes", 256 << 20,
    "Minimum serialized size before a partition can be considered skewed.")

# ---------------------------------------------------------------------------
# Shuffle (reference RapidsConf.scala:522-618)
# ---------------------------------------------------------------------------

SHUFFLE_COMPRESSION_CODEC = conf_str(
    "spark.rapids.shuffle.compression.codec", "none",
    "Codec for shuffle payloads: none, lz4, zstd.")

SHUFFLE_PARTITIONS = conf_int(
    "spark.sql.shuffle.partitions", 16,
    "Number of partitions used for exchanges (Spark's own key, honored here).")

SHUFFLE_ICI_ENABLED = conf_bool(
    "spark.rapids.shuffle.ici.enabled", True,
    "Exchange partitions between chips with XLA all_to_all collectives over "
    "ICI instead of host round-trips (the UCX-transport analog).")

SHUFFLE_MAX_INFLIGHT_BYTES = conf_int(
    "spark.rapids.shuffle.maxReceiveInflightBytes", 1 << 30,
    "Throttle on bytes being fetched concurrently by the shuffle client "
    "(reference RapidsShuffleTransport.scala:418-425).")

SHUFFLE_NET_CONNECT_TIMEOUT = conf_float(
    "spark.rapids.tpu.shuffle.net.connectTimeout", 5.0,
    "Seconds the shuffle wire client waits to establish a TCP connection "
    "to a peer's NetShuffleServer before the attempt counts as a fetch "
    "failure (retried by RetryingBlockIterator, then escalated to "
    "recompute/blacklist). See docs/fault-tolerance.md.")

SHUFFLE_NET_REQUEST_TIMEOUT = conf_float(
    "spark.rapids.tpu.shuffle.net.requestTimeout", 30.0,
    "Seconds the shuffle wire client waits on any single socket "
    "read/write once connected — the slow-peer stall bound: a peer that "
    "stops sending mid-block fails this fetch attempt instead of "
    "wedging the query. See docs/fault-tolerance.md.")

SHUFFLE_NET_ENABLED = conf_bool(
    "spark.rapids.tpu.shuffle.net.enabled", False,
    "Route reduce-side shuffle reads through the TCP wire plane: the "
    "exchange serves its block catalog from a NetShuffleServer and "
    "fetches every block back through the full protocol-v3 client "
    "(handshake, CRC32C verification, timeouts, retry/refetch, "
    "recompute escalation) over a real loopback socket — the same code "
    "path a remote peer exercises, used to harden and CI-gate the "
    "distributed plane. Off by default: in-process reads skip the wire.")

SHUFFLE_NET_MAX_PEER_FAILURES = conf_int(
    "spark.rapids.tpu.shuffle.net.maxPeerFailures", 3,
    "Exhausted fetch attempts (full retry ladders, not individual "
    "refetches) against one peer before the MapOutputTracker "
    "blacklists it for the session: later reads stop dialing it and go "
    "straight to lineage recompute. 0 disables blacklisting.")

SHUFFLE_REPLICATION_FACTOR = conf_int(
    "spark.rapids.tpu.shuffle.replication.factor", 0,
    "Replica peers each map output is pushed to (through the wire "
    "protocol's PUT op, CRC32C-verified at the replica) after the "
    "exchange's write phase. A dead, stalled, or blacklisted primary "
    "then answers from a replica instead of paying a lineage recompute, "
    "and hedged fetches have somewhere to race. Costs factor x the "
    "shuffle's serialized bytes in replica host/disk storage. 0 "
    "(default) disables replication. See docs/fault-tolerance.md.")

SHUFFLE_HEDGE_ENABLED = conf_bool(
    "spark.rapids.tpu.shuffle.hedge.enabled", True,
    "Hedge straggling shuffle fetches: when one block fetch exceeds "
    "hedge.quantileFactor x the peer's observed p50 latency (EWMA, "
    "shuffle/net.py PeerLatencyStats), launch a duplicate request "
    "against a replica (or the local recompute closure) on the shared "
    "pipeline pool — first verified result wins, the loser is "
    "cancelled. Only fires when a hedge source exists (replication "
    "factor > 0 or a recompute closure), so it is free otherwise. "
    "See docs/fault-tolerance.md#hedged-fetches.")

SHUFFLE_HEDGE_QUANTILE_FACTOR = conf_float(
    "spark.rapids.tpu.shuffle.hedge.quantileFactor", 3.0,
    "Straggler threshold: a block fetch is hedged once it has been "
    "outstanding longer than this factor x the peer's observed p50 "
    "fetch latency (never below hedge.minDelayMs). Lower values hedge "
    "more aggressively (more duplicate work, tighter tail); raise it "
    "if hedges fire on healthy jitter.")

SHUFFLE_HEDGE_MIN_DELAY_MS = conf_float(
    "spark.rapids.tpu.shuffle.hedge.minDelayMs", 20.0,
    "Floor on the hedge delay, milliseconds. Keeps sub-millisecond "
    "p50s from hedging every fetch; a COLD peer (no observed latency "
    "yet) is never hedged — the model warms on its first fetch.")

QUERY_DEADLINE_SECS = conf_float(
    "spark.rapids.tpu.query.deadlineSecs", 0.0,
    "Wall-clock budget for one query, seconds. Cooperatively cancels "
    "in-flight shuffle fetches, pipeline waits, and retry/backoff loops "
    "once exceeded, raising QueryDeadlineExceeded naming the slowest "
    "site (classified fatal — deadlines are a contract, not a fault to "
    "retry). The per-tenant time-budget primitive of the multi-tenant "
    "serving roadmap. 0 (default) disables. See docs/fault-tolerance.md.")

LOCKDEP_ENABLED = conf_bool(
    "spark.rapids.tpu.lockdep.enabled", False,
    "Instrument engine locks constructed AFTER session init with runtime "
    "lockdep (utils/lockdep.py): named locks, an observed lock-order "
    "graph, and recorded lock-order-inversion / self-deadlock / "
    "hold-across-blocking violations. Module-level locks are built at "
    "import time, so full coverage needs the TPU_LOCKDEP=1 environment "
    "variable before the engine is imported (tier-1 CI sets it). "
    "Near-zero cost when off: lock factories return raw threading "
    "primitives. See docs/concurrency.md.")

SHUFFLE_CHECKSUM_ENABLED = conf_bool(
    "spark.rapids.tpu.shuffle.checksum.enabled", True,
    "Compute and verify CRC32C checksums on every shuffle block "
    "(catalog registration, wire protocol v3 fetches, local reads) and "
    "every spill range, so corruption surfaces as a typed transient "
    "error — recovered by refetch or map recompute — never as a wrong "
    "answer. Disabling skips verification across every SHUFFLE catalog "
    "tier including its disk spill file (kill switch; the wire protocol "
    "still carries checksums, and the OOM spill catalog always "
    "verifies).")

# ---------------------------------------------------------------------------
# TPU-specific knobs (no reference analog; new hardware, new keys)
# ---------------------------------------------------------------------------

TOPK_THRESHOLD = conf_int(
    "spark.rapids.tpu.sort.topKThreshold", 16384,
    "ORDER BY ... LIMIT n with n at or below this collapses to the "
    "streaming top-k exec (lax.top_k, O(n log k)) instead of a global "
    "sort. 0 disables limit-into-sort.")

TPU_PALLAS_ENABLED = conf_bool(
    "spark.rapids.tpu.pallas.enabled", False,
    "Run the join/sort/groupby/string hot paths through the hand-written "
    "Pallas TPU kernel library (ops/kernels/pallas/: fused hash-join "
    "build+probe with the key table VMEM-resident across the probe grid, "
    "sorted-order segmented aggregation, blockwise bitonic sort over a "
    "packed key lane, ragged string gather/compare, and the string "
    "murmur3 row hash) instead of the default jnp implementations — "
    "which remain the bit-identity oracles. Read PER SESSION at "
    "dispatch; shapes a kernel cannot serve fall back to the oracle "
    "with a recorded reason (QueryProfile engine.pallas). On non-TPU "
    "backends kernels run in Pallas interpreter mode (slow; intended "
    "for tests). See docs/tuning-guide.md.")

TPU_PALLAS_KERNELS = conf_str(
    "spark.rapids.tpu.pallas.kernels", "all",
    "Comma-separated Pallas kernel families to enable when "
    "spark.rapids.tpu.pallas.enabled is on: hash, joinProbe, segmented, "
    "sortStep, strings — or 'all' (default). Use with "
    "tools/kernel_bench.py's per-kernel A/B (BENCH_kernels.json) to "
    "enable only the families that win on your shapes.")

TPU_PALLAS_VMEM_BUDGET = conf_int(
    "spark.rapids.tpu.pallas.vmemBudgetBytes", 8 << 20,
    "Byte budget a Pallas kernel may keep resident in VMEM (join key "
    "tables, whole sort lanes, ragged source matrices). Shapes over "
    "budget fall back to the jnp oracle and record a 'vmem' fallback "
    "reason. TPU cores have ~16MB VMEM; the default leaves headroom for "
    "blocks and double buffering.")

TPU_PALLAS_BLOCK_ROWS = conf_int(
    "spark.rapids.tpu.pallas.blockRows", 256,
    "Rows per Pallas grid step (rounded down to a divisor of the batch "
    "capacity). Larger blocks amortize grid overhead, smaller ones cut "
    "VMEM residency per step.")

TPU_UPLOAD_CACHE_BYTES = conf_int(
    "spark.rapids.tpu.uploadCache.maxBytes", 1 << 30,
    "Byte budget for the host->device upload memo: conversions are keyed "
    "on the immutable arrow buffers, so re-collecting over the same host "
    "data skips dictionary encoding, padding, and the transfer. 0 "
    "disables.")

TPU_CAPACITY_BUCKETING = conf_bool(
    "spark.rapids.tpu.capacityBucketing.enabled", True,
    "Pad device batches to bucket-ladder capacities so XLA compiles one "
    "program per rung instead of one per row count (compile/ladder.py). "
    "Disabling degrades to bare 128-lane alignment — debugging only.")

TPU_MIN_CAPACITY = conf_int(
    "spark.rapids.tpu.minCapacity", 128,
    "Smallest device batch capacity (the bucket ladder's bottom rung); "
    "aligns with the 8x128 VPU lane layout. Deployments that never see "
    "small batches can raise this to skip compiling the tiny rungs.")

TPU_LADDER_GROWTH = conf_float(
    "spark.rapids.tpu.bucketLadder.growth", 2.0,
    "Geometric spacing between capacity-ladder rungs. 2.0 is the classic "
    "power-of-two ladder; 4.0 quarters the number of programs XLA ever "
    "compiles at the price of up to 4x padding (attractive on slow "
    "remote-compile backends); values toward 1.5 trade more programs for "
    "less padded HBM. Rungs stay 128-lane aligned. See "
    "docs/compile-cache.md.")

TPU_LADDER_MAX_CAPACITY = conf_int(
    "spark.rapids.tpu.bucketLadder.maxCapacity", 0,
    "Ladder top: batches above this capacity get an exact lane-aligned "
    "fit instead of the next geometric rung, bounding padded HBM waste "
    "for huge batches. 0 = unbounded.")

POLYMORPHIC_ENABLED = conf_bool(
    "spark.rapids.tpu.polymorphic.enabled", True,
    "Shape-polymorphic fused executables: pad a fused program's boundary "
    "inputs up to coarse capacity TIERS (see polymorphic.tierGrowth) "
    "before dispatch, so ONE compiled XLA executable serves every "
    "bucket-ladder rung inside a tier instead of re-specializing per "
    "rung — O(kernels) compiles instead of O(rungs x kernels). Row "
    "counts stay dynamic scalar operands (the live-mask invariant makes "
    "padded rows dead), so results are bit-identical to the per-rung "
    "path, which remains available as the oracle by disabling this key. "
    "See docs/compile-cache.md.")

POLYMORPHIC_TIER_GROWTH = conf_float(
    "spark.rapids.tpu.polymorphic.tierGrowth", 4.0,
    "Geometric spacing of the polymorphic capacity tiers, anchored at "
    "the bucket-ladder base. 4.0 bounds padded HBM/compute waste at 4x "
    "while merging ~2 power-of-two rungs per executable; 16.0 merges 4 "
    "rungs per executable (one compile per 16x of data growth — right "
    "for slow remote-compile backends where compile time dominates) at "
    "up to 16x padding. Tiers always land on bucket-ladder rungs. See "
    "docs/tuning-guide.md for the padding-waste vs compile-count "
    "tradeoff.")

FUSION_COMPILE_BUDGET_SECS = conf_float(
    "spark.rapids.tpu.fusion.compileBudgetSecs", 120.0,
    "Compile-cost budget for one fused region: when compiling a fused "
    "program takes longer than this (measured at first dispatch, "
    "recorded per plan in the compile manifest), future builds of the "
    "same plan SPLIT the fusion region at its most expensive boundary — "
    "first the largest inlined join, then every join — trading one "
    "giant compile for smaller cacheable ones (the q3/bb_q01 class of "
    "compile blowups). 0 disables splitting. See docs/compile-cache.md.")

COMPILE_CACHE_ENABLED = conf_bool(
    "spark.rapids.tpu.compileCache.enabled", False,
    "Persist XLA executables to disk (JAX persistent compilation cache) "
    "plus a manifest of (plan, capacity-rung) shapes, so a restarted "
    "process skips recompiling everything it served before. Off by "
    "default: some remote-compile helpers deadlock on the cache and "
    "cross-machine AOT artifacts can SIGILL on replay (see "
    "docs/compile-cache.md before enabling). The "
    "JAX_ENABLE_COMPILATION_CACHE=false environment kill-switch always "
    "wins.")

COMPILE_CACHE_DIR = conf_str(
    "spark.rapids.tpu.compileCache.dir", None,
    "Directory for the persistent executable cache + compile manifest. "
    "Default: ~/.cache/spark_rapids_tpu/xla.")

COMPILE_CACHE_MIN_COMPILE_SECS = conf_float(
    "spark.rapids.tpu.compileCache.minCompileSecs", 0.0,
    "Only persist executables whose compile took at least this long "
    "(jax_persistent_cache_min_compile_time_secs). 0 persists "
    "everything.")

WARMUP_AUTO = conf_bool(
    "spark.rapids.tpu.warmup.auto", False,
    "After each fused query runs at some capacity rung, AOT-compile the "
    "same program at neighboring ladder rungs (and any rung recorded in "
    "the compile manifest) in a background thread, so growing data never "
    "stalls at a rung boundary. Off by default: it multiplies compile "
    "work, which only pays off for long-lived serving sessions.")

WARMUP_RUNGS_AHEAD = conf_int(
    "spark.rapids.tpu.warmup.rungsAhead", 1,
    "How many ladder rungs ABOVE the observed capacity the auto warm-up "
    "pre-compiles (growing datasets climb the ladder upward).")

WARMUP_RUNGS_BEHIND = conf_int(
    "spark.rapids.tpu.warmup.rungsBehind", 0,
    "How many ladder rungs BELOW the observed capacity the auto warm-up "
    "pre-compiles.")

TPU_JOIN_OUTPUT_GROWTH = conf_float(
    "spark.rapids.tpu.join.outputGrowthFactor", 1.0,
    "Initial output-capacity estimate for joins as a multiple of the probe "
    "side; joins re-execute with a larger bucket on overflow.")

TPU_COLLECT_GUESS_ROWS = conf_int(
    "spark.rapids.tpu.collect.guessRows", 1024,
    "Row-capacity guess for the single-round-trip result download of a fused "
    "query: results at most this large come back in ONE device->host "
    "transfer; larger results pay a second, bandwidth-bound transfer. "
    "Default sized for high-latency low-bandwidth links (measured ~20MB/s "
    "on the axon tunnel, where an 8192-row guess added ~300ms per collect); "
    "typical analytic results (aggregates, top-N) fit in 1024.")

TPU_FUSION_ENABLED = conf_bool(
    "spark.rapids.tpu.fusion.enabled", True,
    "Trace an entire device plan into one compiled XLA program (whole-stage "
    "fusion): one dispatch and one device->host transfer per query.")

TPU_FUSION_INLINE_JOINS = conf_bool(
    "spark.rapids.tpu.fusion.inlineJoins", True,
    "Inline hash joins into the fused whole-stage program instead of "
    "running each join as an eager boundary: removes per-join dispatches "
    "and intermediate materialization. Disable when a slow remote compile "
    "helper makes many-sort fused programs too expensive to build.")

TPU_MESH_ENABLED = conf_bool(
    "spark.rapids.tpu.mesh.enabled", False,
    "Run mesh-capable queries as ONE SPMD program over all devices "
    "(jax.sharding.Mesh): sources shard row-wise, aggregate/join "
    "boundaries exchange over ICI via all_to_all (exec/mesh.py). The "
    "engine-integrated form of the reference's GPU-resident shuffle "
    "manager.")

MESH_HEALTH_PROBE_ENABLED = conf_bool(
    "spark.rapids.tpu.mesh.health.probeEnabled", False,
    "Probe every mesh device (a tiny put + block_until_ready) before "
    "dispatching a mesh-capable query as an SPMD program: a device that "
    "fails the probe degrades the session to the single-chip path "
    "up front (meshFailovers metric, flight-recorder dump) instead of "
    "failing mid-collect. Off by default — the probe costs one device "
    "round-trip per dispatch.")

MESH_HEALTH_REPROBE_SECS = conf_float(
    "spark.rapids.tpu.mesh.health.reprobeSecs", 0.0,
    "Seconds after a mesh degradation before the session re-probes the "
    "mesh and, if every device answers, restores SPMD dispatch. 0 "
    "(default): a degraded session stays on the single-chip path for "
    "its lifetime (probe_mesh() re-probes on demand).")

PIPELINE_ENABLED = conf_bool(
    "spark.rapids.tpu.pipeline.enabled", True,
    "Overlap the host-side execution pipeline (exec/pipeline.py): "
    "independent fusion-boundary subtrees materialize concurrently on a "
    "shared worker pool, file readers decode ahead with bounded prefetch, "
    "the streaming download path starts the next batch's dispatch before "
    "downloading the previous one, and shuffle serialization overlaps "
    "device work. Results are bit-identical with the pipeline on or off; "
    "a session with fault injection active always runs the serial path so "
    "per-site fault schedules stay deterministic. See docs/tuning-guide.md.")

PIPELINE_DECODE_THREADS = conf_int(
    "spark.rapids.tpu.pipeline.decodeThreads", 0,
    "Concurrent file/row-group decode tasks the pipeline layer runs on "
    "the shared pool (scan decode + upload assembly). 0 = auto "
    "(min(4, cpu count), at least 2). Raising it helps many-file scans on "
    "hosts with spare cores; each in-flight decode holds one host batch "
    "plus its upload buffers.")

PIPELINE_PREFETCH_DEPTH = conf_int(
    "spark.rapids.tpu.pipeline.prefetchDepth", 2,
    "Bounded look-ahead of every pipeline stage: batches a prefetch "
    "worker keeps ready ahead of its consumer, and decode tasks in "
    "flight ahead of the scan cursor. Deeper prefetch hides more "
    "producer latency at the price of that many extra live batches in "
    "host memory and HBM (see docs/tuning-guide.md for sizing against "
    "HBM pressure).")

PIPELINE_BOUNDARY_PARALLELISM = conf_int(
    "spark.rapids.tpu.pipeline.boundaryParallelism", 0,
    "Independent fusion-boundary subtrees materialized concurrently "
    "before a fused dispatch (exec/fusion.py). 0 = auto (min(4, cpu "
    "count), at least 2); 1 forces serial boundary materialization. "
    "Device admission of the concurrent workers is still bounded by "
    "spark.rapids.sql.concurrentTpuTasks — the dispatching thread "
    "releases its own slot while it waits, the reference's "
    "release-during-shuffle discipline.")

METRICS_LEVEL = conf_str(
    "spark.rapids.tpu.metrics.level", "MODERATE",
    "Operator metrics level: NONE disables the whole query-profile layer "
    "(no metric recording, no QueryProfile, no timing fences — asserted "
    "bit-identical to metrics-free execution by tests), ESSENTIAL records "
    "the core taxonomy (rows/batches/bytes/opTime/spill), MODERATE adds "
    "build/semaphore/compile timings, DEBUG adds serialization and concat "
    "internals. The GpuMetric-level analog "
    "(spark.rapids.sql.metrics.level). See docs/monitoring.md.")

METRICS_DEVICE_TIMING = conf_bool(
    "spark.rapids.tpu.metrics.deviceTiming", False,
    "Attribute DEVICE time per query: insert a block-until-ready fence "
    "after the fused dispatch and record dispatch-to-ready nanoseconds as "
    "the deviceTime metric. Off by default because the fence serializes "
    "the dispatch pipeline — the default path runs with zero fences (the "
    "tests assert none are inserted). See docs/monitoring.md.")

METRICS_EVENT_LOG_DIR = conf_str(
    "spark.rapids.tpu.metrics.eventLog.dir", None,
    "Directory for the structured query event log: every executed query "
    "appends its QueryProfile as one JSON line to query_profiles.jsonl "
    "(crash-safe append; torn lines are skipped on read — same stance as "
    "the compile manifest). Unset disables the log. See "
    "docs/monitoring.md for the record schema.")

METRICS_EVENT_LOG_MAX_BYTES = conf_int(
    "spark.rapids.tpu.metrics.eventLog.maxBytes", 64 << 20,
    "Size-capped rotation for the event log in a long-lived serving "
    "process: when an append would push query_profiles.jsonl past this "
    "many bytes, the file atomically rotates to query_profiles.jsonl.1 "
    "(one prior generation kept) and the append starts a fresh file — "
    "crash-safe (os.replace) and torn-line tolerant like the append "
    "itself. 0 disables rotation (unbounded growth). See "
    "docs/monitoring.md.")

TRACE_ENABLED = conf_bool(
    "spark.rapids.tpu.trace.enabled", False,
    "Per-query distributed tracing (metrics/trace.py): a span tree "
    "spanning serve admission/queue wait, session dispatch, the retry "
    "ladder, pipeline workers, the spill-IO lane, compile/warmup "
    "events, and shuffle map/fetch/recompute — with trace context "
    "propagated over both wire protocols (the SRTQS serve field and the "
    "shuffle net protocol-v4 header) so multi-peer fetches stitch into "
    "one trace. Each query exports Chrome trace-event JSON "
    "(Perfetto-loadable) beside the event log; tools/trace_report.py "
    "computes the critical path. Off by default: the disabled path is "
    "no-op spans, no fences, bit-identical results (asserted by tests). "
    "Read per session. See docs/monitoring.md#distributed-tracing.")

TRACE_DIR = conf_str(
    "spark.rapids.tpu.trace.dir", None,
    "Directory for exported per-query trace files "
    "(trace_<trace_id>.json). Unset: traces land beside the event log "
    "(spark.rapids.tpu.metrics.eventLog.dir); with neither set, spans "
    "still feed the in-memory flight recorder but no per-query file is "
    "written.")

TRACE_MAX_FILES = conf_int(
    "spark.rapids.tpu.trace.maxFiles", 256,
    "Retention bound on exported trace files: after each export the "
    "oldest trace_*.json beyond this count are pruned from the trace "
    "directory, so a long-lived traced serving process cannot fill the "
    "disk (the eventLog.maxBytes stance applied to traces). 0 disables "
    "pruning.")

TRACE_FLIGHT_SPANS = conf_int(
    "spark.rapids.tpu.trace.flightRecorder.spans", 4096,
    "Bound on the in-memory flight recorder: the ring buffer keeps this "
    "many recent finished spans + engine events across all queries, "
    "dumped to JSON on QueryDeadlineExceeded, circuit-breaker "
    "quarantine trips, SessionCrashError, and SIGTERM. See "
    "docs/monitoring.md#flight-recorder.")

TRACE_FLIGHT_DIR = conf_str(
    "spark.rapids.tpu.trace.flightRecorder.dir", "artifacts",
    "Directory flight-recorder dumps are written to "
    "(flight_<reason>_<pid>_<n>.json; bounded per reason so a crash "
    "loop cannot flood it).")

# ---------------------------------------------------------------------------
# ML scenario subsystem (ml/, exec/ml_score.py, docs/ml-integration.md)
# ---------------------------------------------------------------------------

TPU_ML_ENABLED = conf_bool(
    "spark.rapids.tpu.ml.enabled", True,
    "Run ModelScore (df.with_model_score — batch inference over a "
    "registered model INSIDE the query plan) on the device: features "
    "gather straight from the device batch and the prediction kernel "
    "rides the kernel cache and fused-dispatch machinery. false keeps "
    "the operator on the CPU oracle path, which evaluates the SAME "
    "predict function on host-assembled features — the bit-identity "
    "twin the differential tests compare against. See "
    "docs/ml-integration.md.")

TPU_ML_MAX_MODELS = conf_int(
    "spark.rapids.tpu.ml.maxRegisteredModels", 64,
    "Bound on models a session's ModelRegistry holds at once "
    "(re-registering an existing name replaces it in place and does not "
    "count). Registered models are spillable device buffers, so the "
    "bound caps registry HBM/host residency the way the result cache "
    "caps serving memory; exceeding it raises instead of silently "
    "evicting a model a running query may score with. See "
    "docs/ml-integration.md.")

PLAN_LINT_ENABLED = conf_bool(
    "spark.rapids.tpu.planLint.enabled", True,
    "Statically verify every physical plan after planning and again after "
    "the TPU rewrite (analysis/plan_lint.py): per-node schema consistency "
    "against child schemas, cast-lattice legality, host<->device "
    "transition correctness, shuffle partitioning contracts at joins, and "
    "parquet writer physical-type widths. Error-severity violations raise "
    "PlanLintError with the offending node path; warn-severity violations "
    "log and fall the query back to the CPU plan. See docs/plan-lint.md.")

PLAN_LINT_FAIL_ON_WARN = conf_bool(
    "spark.rapids.tpu.planLint.failOnWarn", False,
    "Promote warn-severity plan-lint violations (which normally log and "
    "fall back to the CPU plan) to hard PlanLintError failures. Intended "
    "for CI and tests. See docs/plan-lint.md.")

# ---------------------------------------------------------------------------
# Multi-tenant query service (serve/, docs/serving.md)
# ---------------------------------------------------------------------------

SERVE_SESSIONS = conf_int(
    "spark.rapids.tpu.serve.sessions", 2,
    "Warm TpuSessions the query service (serve/) pools. Each pooled "
    "session loads the registered tables once and serves one query at a "
    "time; a session that dies mid-query is torn down and replaced "
    "without disturbing its neighbors. See docs/serving.md.")

SERVE_MAX_CONCURRENT = conf_int(
    "spark.rapids.tpu.serve.maxConcurrentQueries", 0,
    "Queries the service admits concurrently (the fair-share gate's slot "
    "count, layered in FRONT of spark.rapids.sql.concurrentTpuTasks). "
    "0 = one per pooled session. See docs/serving.md.")

SERVE_MAX_QUEUE_DEPTH = conf_int(
    "spark.rapids.tpu.serve.maxQueueDepth", 16,
    "Bound on each tenant's admission queue: a submit arriving when the "
    "tenant already has this many queries waiting is SHED with a typed "
    "ServiceOverloadedError carrying a retry-after hint — overload "
    "answers as fast typed backpressure, never as unbounded queueing. "
    "See docs/serving.md.")

SERVE_TENANT_WEIGHTS = conf_str(
    "spark.rapids.tpu.serve.tenantWeights", "",
    "Comma-separated 'tenant:weight' fair-share weights for the "
    "admission gate (stride scheduling: a weight-2 tenant is admitted "
    "twice as often under contention). Unlisted tenants weigh 1. "
    "See docs/serving.md.")

SERVE_TENANT_TIME_BUDGET = conf_str(
    "spark.rapids.tpu.serve.tenantTimeBudgetSecs", "",
    "Comma-separated 'tenant:seconds' per-query wall-clock budgets, "
    "enforced through the PR-7 cooperative Deadline spanning queue wait "
    "AND execution (including the retry ladder). 'default:N' applies to "
    "unlisted tenants; 0/absent = unbounded. Exceeding the budget "
    "raises the typed QueryDeadlineExceeded. See docs/serving.md.")

SERVE_TENANT_MEMORY_BUDGET = conf_str(
    "spark.rapids.tpu.serve.tenantMemoryBudgetBytes", "",
    "Comma-separated 'tenant:bytes' device-memory budgets: before each "
    "of a tenant's queries runs, its device-resident spillable bytes "
    "above budget are spilled via the QoS victim order (its OWN buffers "
    "— an over-budget tenant pays with its own residency, never a "
    "neighbor's). 'default:N' applies to unlisted tenants; 0/absent = "
    "unbounded. See docs/serving.md.")

SERVE_QUARANTINE_FAILURES = conf_int(
    "spark.rapids.tpu.serve.quarantine.maxFailures", 2,
    "Retry-ladder exhaustions (OOM-classified failures that escaped the "
    "whole memory/retry.py ladder, or repeated session crashes) of one "
    "plan hash before the circuit breaker quarantines it: further "
    "submits of that plan are rejected with the typed "
    "QueryQuarantinedError instead of re-admitted to burn the pool. "
    "0 disables the breaker. See docs/serving.md.")

SERVE_QUARANTINE_SECS = conf_float(
    "spark.rapids.tpu.serve.quarantine.secs", 300.0,
    "How long a quarantined plan hash stays rejected before one probe "
    "execution is allowed again (half-open breaker).")

SERVE_RESULT_CACHE_ENTRIES = conf_int(
    "spark.rapids.tpu.serve.resultCache.maxEntries", 64,
    "LRU capacity of the serving result cache, keyed by (tenant, PR-2 "
    "plan hash). Entries store the CRC32C-verified serialized result, so "
    "a poisoned entry is detected on hit and recomputed, never served. "
    "Invalidation is tenant-scoped (QueryService.invalidate). 0 "
    "disables. See docs/serving.md.")

SERVE_SHED_RETRY_AFTER_SECS = conf_float(
    "spark.rapids.tpu.serve.shedRetryAfterSecs", 0.25,
    "Base of the retry-after hint a shed (ServiceOverloadedError) "
    "carries; scaled by how loaded the admission gate is when the shed "
    "happens.")

FAULT_INJECTION_SERVE_EVERY_N = conf_int(
    "spark.rapids.tpu.test.faultInjection.serveEveryN", 0,
    "Apply a deterministic SERVING-SEAM fault at every Nth visit of the "
    "matched serve.* site (serve.admission / serve.execute / "
    "serve.cache; the 'sites' patterns gate it). Negative N faults the "
    "first |N| visits then heals. The fault class per visit is chosen "
    "deterministically from the seed among faultInjection.serveFaults "
    "(restricted to the classes valid at that seam). 0 disables.")

FAULT_INJECTION_SERVE_FAULTS = conf_str(
    "spark.rapids.tpu.test.faultInjection.serveFaults",
    "tenantKill,sessionCrash,cachePoison,admissionStall",
    "Comma-separated serving fault classes the injector may apply: "
    "tenantKill (the victim query is cancelled mid-flight — typed "
    "QueryCancelledError, neighbors unaffected), sessionCrash (the "
    "pooled session dies — torn down, replaced, read-only query re-run "
    "once), cachePoison (the stored result-cache entry is corrupted — "
    "CRC32C catches it on hit and the query recomputes), admissionStall "
    "(a delay inside the admission queue — drives shed paths). A single "
    "name pins every injected fault to that class.")

DEVICE_BACKEND = conf_str(
    "spark.rapids.tpu.backend", None,
    "Force a jax backend for device execution (tpu/cpu). Default: jax default.",
    internal=True)


class TpuConf:
    """Immutable snapshot of configuration, with typed accessors.

    Mirrors the accessor layer of ``RapidsConf`` (reference
    RapidsConf.scala:700-885).
    """

    def __init__(self, conf: Optional[Dict[str, Any]] = None):
        self._conf = dict(conf or {})
        for k in self._conf:
            if k.startswith("spark.rapids.") and k not in _REGISTRY:
                raise KeyError(f"unknown rapids conf key: {k}")

    def get(self, entry: ConfEntry) -> Any:
        return entry.get(self._conf)

    def with_overrides(self, **kv: Any) -> "TpuConf":
        merged = dict(self._conf)
        merged.update(kv)
        return TpuConf(merged)

    def raw(self, key: str, default: Optional[str] = None) -> Optional[str]:
        return self._conf.get(key, default)

    # Typed shortcuts used widely.
    @property
    def sql_enabled(self) -> bool:
        return self.get(SQL_ENABLED)

    @property
    def explain(self) -> str:
        return str(self.get(EXPLAIN)).upper()

    @property
    def test_enabled(self) -> bool:
        return self.get(TEST_ENABLED)

    @property
    def allowed_non_tpu(self) -> List[str]:
        raw = self.get(TEST_ALLOWED_NON_TPU)
        return [s.strip() for s in raw.split(",") if s.strip()]

    @property
    def batch_size_rows(self) -> int:
        return self.get(BATCH_SIZE_ROWS)

    @property
    def shuffle_partitions(self) -> int:
        return self.get(SHUFFLE_PARTITIONS)

    @property
    def collect_guess_rows(self) -> int:
        return self.get(TPU_COLLECT_GUESS_ROWS)

    @property
    def fusion_enabled(self) -> bool:
        return self.get(TPU_FUSION_ENABLED)

    @property
    def fusion_inline_joins(self) -> bool:
        return self.get(TPU_FUSION_INLINE_JOINS)

    @property
    def polymorphic_enabled(self) -> bool:
        return self.get(POLYMORPHIC_ENABLED)

    @property
    def fusion_compile_budget_secs(self) -> float:
        return self.get(FUSION_COMPILE_BUDGET_SECS)

    @property
    def mesh_enabled(self) -> bool:
        return self.get(TPU_MESH_ENABLED)

    @property
    def pipeline_enabled(self) -> bool:
        return self.get(PIPELINE_ENABLED)

    @property
    def pipeline_decode_threads(self) -> int:
        return self.get(PIPELINE_DECODE_THREADS)

    @property
    def pipeline_prefetch_depth(self) -> int:
        return self.get(PIPELINE_PREFETCH_DEPTH)

    @property
    def pipeline_boundary_parallelism(self) -> int:
        return self.get(PIPELINE_BOUNDARY_PARALLELISM)

    @property
    def metrics_level(self) -> str:
        return str(self.get(METRICS_LEVEL)).upper()

    @property
    def metrics_device_timing(self) -> bool:
        return self.get(METRICS_DEVICE_TIMING)

    @property
    def metrics_event_log_dir(self) -> Optional[str]:
        return self.get(METRICS_EVENT_LOG_DIR)

    def is_operator_enabled(self, conf_key: str, incompat: bool, disabled_by_default: bool) -> bool:
        """Three-state per-operator gating (reference RapidsMeta.tagForGpu:195-210)."""
        raw = self._conf.get(conf_key)
        if raw is not None:
            return raw if isinstance(raw, bool) else _to_bool(raw)
        if incompat:
            return self.get(INCOMPATIBLE_OPS)
        return not disabled_by_default

    @staticmethod
    def operator_conf_key(kind: str, name: str) -> str:
        """Synthesized per-op enable key (reference GpuOverrides.scala:126-131)."""
        return f"spark.rapids.sql.{kind}.{name}"

    @staticmethod
    def register_operator_key(kind: str, name: str, incompat: bool,
                              disabled_by_default: bool, doc: str) -> str:
        key = TpuConf.operator_conf_key(kind, name)
        if key not in _REGISTRY:
            default = not disabled_by_default and not incompat
            conf_bool(key, default, doc)
        return key

    @staticmethod
    def help_markdown() -> str:
        """Generate docs/configs.md, like ``RapidsConf.help`` (RapidsConf.scala:641)."""
        lines = [
            "# TPU Accelerator for Apache Spark Configuration",
            "",
            "The following configs control the TPU-native execution backend. They can be",
            "set at session creation or per query. Generated by "
            "`TpuConf.help_markdown()` — do not edit by hand.",
            "",
            "Name | Description | Default Value",
            "-----|-------------|--------------",
        ]
        for key in sorted(_REGISTRY):
            e = _REGISTRY[key]
            if e.internal:
                continue
            lines.append(f"{e.key}|{e.doc}|{e.default}")
        return "\n".join(lines) + "\n"


DEFAULT_CONF = TpuConf()
