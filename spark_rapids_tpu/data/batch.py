"""Device columnar batches — the ``ColumnarBatch``/``Table`` analog.

A :class:`ColumnarBatch` is a pytree of :class:`DeviceColumn` plus a traced
``n_rows`` scalar; its capacity and schema are static treedef data. This is
the unit that flows between device operators, exactly as cudf-backed
``ColumnarBatch`` objects flow between GPU execs in the reference
(``GpuColumnVector.java:40``, ``GpuExec`` iterators) — but shaped for XLA:
one compiled program per (schema, capacity-bucket), row count fully dynamic.

``HostBatch`` wraps a pyarrow ``RecordBatch`` and is the currency of the CPU
(oracle / fallback) execution path, standing in for Spark's host
``ColumnarBatch`` of rows.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from .. import types as T
from .column import DeviceColumn, bucket_capacity


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class ColumnarBatch:
    """A device-resident table slice with a dynamic live-row count.

    Liveness has two representations:

    * **physical** (``live is None``): rows ``[0, n_rows)`` are live — the
      compacted form every positional consumer (concat, slice, download,
      serialize) requires.
    * **lazy** (``live`` is a ``bool[capacity]`` mask): live rows sit
      scattered at their original positions and ``n_rows`` is their traced
      COUNT. A filter then costs one mask AND instead of a full sort-based
      compaction (the dominant cost of filter-heavy plans); mask-native
      consumers (aggregate, join, sort, further filters) read
      :meth:`row_mask` and never pay the compaction. Positional consumers
      call :func:`..ops.kernels.rowops.physical` first.
    """

    columns: tuple  # tuple[DeviceColumn]
    n_rows: jax.Array  # int32 scalar, traced — COUNT of live rows
    schema: T.Schema  # static
    live: Optional[jax.Array] = None  # bool[capacity]; None = physical

    def tree_flatten(self):
        if self.live is None:
            return (self.columns, self.n_rows), (self.schema, False)
        return (self.columns, self.n_rows, self.live), (self.schema, True)

    @classmethod
    def tree_unflatten(cls, aux, children):
        schema, has_live = aux
        if has_live:
            columns, n_rows, live = children
            return cls(columns=tuple(columns), n_rows=n_rows, schema=schema,
                       live=live)
        columns, n_rows = children
        return cls(columns=tuple(columns), n_rows=n_rows, schema=schema)

    @property
    def num_columns(self) -> int:
        return len(self.columns)

    @property
    def capacity(self) -> int:
        if self.columns:
            return self.columns[0].capacity
        return 0

    def column(self, key: Union[int, str]) -> DeviceColumn:
        if isinstance(key, str):
            key = self.schema.index_of(key)
        return self.columns[key]

    def with_columns(self, columns: Sequence[DeviceColumn],
                     schema: T.Schema) -> "ColumnarBatch":
        return ColumnarBatch(tuple(columns), self.n_rows, schema,
                             live=self.live)

    def row_mask(self) -> jax.Array:
        """bool[capacity] — True for live rows."""
        if self.live is not None:
            return self.live
        return jnp.arange(self.capacity, dtype=jnp.int32) < self.n_rows

    # -- host interchange ---------------------------------------------------
    @staticmethod
    def from_arrow(rb: pa.RecordBatch, min_capacity: int = 128,
                   capacity: Optional[int] = None) -> "ColumnarBatch":
        schema = T.schema_from_arrow(rb.schema)
        cap = capacity or bucket_capacity(rb.num_rows, min_capacity)
        cols = tuple(DeviceColumn.from_arrow(rb.column(i), cap)
                     for i in range(rb.num_columns))
        return ColumnarBatch(cols, jnp.asarray(rb.num_rows, dtype=jnp.int32), schema)

    def to_arrow(self) -> pa.RecordBatch:
        """Download to host. Syncs ``n_rows`` — only call at stage boundaries.

        Transfer discipline (the tunnel charges ~a round trip per blocking
        read): one scalar sync for the row count, one cached shrink kernel
        when live rows occupy a smaller capacity bucket, then ONE batched
        ``jax.device_get`` for every buffer of every column.
        """
        return self.to_arrow_finish(self.to_arrow_begin(async_copy=False))

    def to_arrow_begin(self, async_copy: bool = True):
        """Start a download without blocking on the data: materialize +
        shrink, sync only the row-count scalar, and (where the backend
        supports it) start an async device->host copy of every buffer.
        Returns an opaque handle for :meth:`to_arrow_finish`. The split
        lets the pipelined DeviceToHost path dispatch the NEXT batch's
        device work while this batch's bytes are still in flight
        (exec/pipeline.py; the reference's overlapped-download stance)."""
        from ..ops.kernels.rowops import physical_jit
        batch = physical_jit(self)
        n = int(batch.n_rows)
        cap = bucket_capacity(max(n, 1))
        batch = _shrink_batch(batch, cap) if cap < batch.capacity else batch
        bufs = [c.device_buffers() for c in batch.columns]
        if async_copy:
            for leaf in jax.tree_util.tree_leaves(bufs):
                start = getattr(leaf, "copy_to_host_async", None)
                if callable(start):
                    start()
        return batch, n, bufs

    def to_arrow_finish(self, handle) -> pa.RecordBatch:
        """Block on a download started by :meth:`to_arrow_begin` and
        assemble the host RecordBatch (one batched ``jax.device_get``;
        a completed async copy makes it a cache read)."""
        batch, n, bufs = handle
        host = jax.device_get(bufs)
        arrays = [c.arrow_from_host(hb, n)
                  for c, hb in zip(batch.columns, host)]
        fields = [pa.field(f.name, T.to_arrow_type(f.data_type), f.nullable)
                  for f in self.schema]
        return pa.RecordBatch.from_arrays(arrays, schema=pa.schema(fields))

    @property
    def device_size_bytes(self) -> int:
        return sum(c.size_bytes for c in self.columns)


@functools.partial(jax.jit, static_argnums=(1,))
def _shrink_batch(batch: ColumnarBatch, cap: int) -> ColumnarBatch:
    """Copy a batch into a smaller capacity bucket (>= its live rows), so
    downloads move O(live) bytes instead of O(capacity). Rows past n_rows
    are dead by invariant, so a front slice is sufficient."""
    return ColumnarBatch(tuple(c.head(cap) for c in batch.columns),
                         batch.n_rows, batch.schema)


@functools.partial(jax.jit, static_argnums=(1,))
def _grow_batch(batch: ColumnarBatch, cap: int) -> ColumnarBatch:
    """Copy a batch into a LARGER capacity bucket, padding every column
    with dead rows (validity False, zero data — the padding-never-
    changes-results invariant) and extending any lazy live mask with
    False. The shape-polymorphic fused path (exec/fusion.py) pads
    boundary inputs onto coarse capacity tiers with this, so one
    compiled executable serves every bucket-ladder rung in a tier."""
    live = None if batch.live is None else \
        jnp.pad(batch.live, (0, cap - batch.live.shape[0]))
    return ColumnarBatch(tuple(c.grow(cap) for c in batch.columns),
                         batch.n_rows, batch.schema, live=live)


@dataclasses.dataclass
class HostBatch:
    """Host-side batch: the CPU oracle / fallback path currency."""

    rb: pa.RecordBatch

    @property
    def num_rows(self) -> int:
        return self.rb.num_rows

    @property
    def schema(self) -> T.Schema:
        return T.schema_from_arrow(self.rb.schema)

    def to_device(self, min_capacity: int = 128) -> ColumnarBatch:
        return ColumnarBatch.from_arrow(self.rb, min_capacity)

    @staticmethod
    def from_device(batch: ColumnarBatch) -> "HostBatch":
        return HostBatch(batch.to_arrow())

    @staticmethod
    def from_pydict(data: dict, schema: Optional[T.Schema] = None) -> "HostBatch":
        if schema is not None:
            rb = pa.RecordBatch.from_pydict(data, schema=T.schema_to_arrow(schema))
        else:
            rb = pa.RecordBatch.from_pydict(data)
        return HostBatch(rb)


def concat_host(batches: List[HostBatch]) -> HostBatch:
    tables = pa.Table.from_batches([b.rb for b in batches])
    combined = tables.combine_chunks()
    if combined.num_rows == 0:
        return HostBatch(pa.RecordBatch.from_pydict(
            {n: [] for n in combined.schema.names}, schema=combined.schema))
    return HostBatch(combined.to_batches()[0])
