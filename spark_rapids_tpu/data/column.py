"""Device-resident columnar vectors — the ``GpuColumnVector`` analog.

The reference wraps cudf device columns in Spark ``ColumnVector`` objects
(reference: ``sql-plugin/src/main/java/.../GpuColumnVector.java:40``). cuDF's
model is eager and dynamically shaped: every kernel allocates an exactly-sized
output. That model is hostile to XLA, which wants static shapes and traced
programs.

The TPU-native model here is different by design:

* A :class:`DeviceColumn` owns a **fixed-capacity** buffer (power-of-two
  bucketed, lane-aligned) plus a validity mask. The number of live rows is
  tracked by the enclosing batch as a *traced* scalar, so data-dependent row
  counts (filters, joins) flow through a compiled program without host syncs
  or recompilation.
* Invariant: rows at index >= n_rows always have ``validity == False`` and
  deterministic (zero) data, so masked reductions never need the row count and
  padding never changes results.
* Strings use the Arrow layout — ``offsets: int32[capacity+1]`` into a
  ``uint8[byte_capacity]`` payload — the same layout cudf uses on GPU, which is
  also the right layout for TPU gather/scatter kernels.

Columns are registered as jax pytrees, so whole batches can be passed straight
through ``jax.jit`` boundaries; the dtype/capacity live in the static treedef,
giving one compiled program per capacity bucket.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from .. import types as T

#: Lane width of the VPU — the minimum sensible capacity granularity.
#: Canonical definition (and the bucket policy itself) live in
#: compile/ladder.py; re-exported here because every exec imports them
#: from this module since the seed.
from ..compile.ladder import (LANE, bucket_byte_capacity,  # noqa: E402,F401
                              bucket_capacity)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class DeviceColumn:
    """One column of one device batch.

    For fixed-width types, ``data`` has shape ``[capacity]``. Strings come
    in two layouts:

    * **flat**: ``data`` is the ``uint8`` byte payload, ``offsets`` is
      ``int32[capacity+1]`` (Arrow layout); offsets past the live row count
      clamp to the last valid offset.
    * **dictionary-encoded** (``codes is not None``): ``codes`` is
      ``int32[capacity]`` indexing a small dictionary whose entries live in
      ``data``/``offsets`` (``int32[n_dict+1]``). This is the TPU-native
      string representation: row rearrangement (filters, sorts, joins,
      shuffles) moves ONE int32 lane instead of a char matrix, and when
      ``dict_sorted`` (entries unique + bytewise ascending — the upload
      default) code ORDER and EQUALITY coincide with string order and
      equality, so sorts and group-bys use codes directly. cudf gets the
      same wins from its dictionary category type; here it also keeps XLA
      programs narrow.
    """

    data: jax.Array
    validity: jax.Array  # bool[capacity]
    dtype: T.DataType
    offsets: Optional[jax.Array] = None  # int32 offsets (see class doc)
    #: Static upper bound on any single string's byte length (strings only).
    #: Host-known at upload; device string kernels use it to bound the padded
    #: char-matrix width. Propagates through string ops (substr keeps it,
    #: concat sums it).
    max_bytes: int = 0
    #: int32[capacity] dictionary codes (dict-encoded strings only).
    codes: Optional[jax.Array] = None
    #: True when the dictionary is unique + sorted ascending (static).
    dict_sorted: bool = False
    #: ARRAY columns (padded-ragged layout, see types.ArrayType): ``data``
    #: is ``[capacity, max_len]`` element values, ``elem_validity`` the
    #: matching element mask, ``lengths`` int32[capacity] live lengths.
    elem_validity: Optional[jax.Array] = None
    lengths: Optional[jax.Array] = None
    #: STRUCT columns (column-shredded, see types.StructType): one child
    #: DeviceColumn per field; ``data`` is unused, ``validity`` is the
    #: struct-level null lane.
    children: tuple = ()

    # -- pytree protocol ----------------------------------------------------
    def tree_flatten(self):
        if self.children:
            return ((self.validity, self.children), (self.dtype, 5, 0))
        if self.lengths is not None:
            return ((self.data, self.validity, self.elem_validity,
                     self.lengths), (self.dtype, 4, 0))
        if self.offsets is None:
            return (self.data, self.validity), (self.dtype, 0, 0)
        if self.codes is None:
            return ((self.data, self.validity, self.offsets),
                    (self.dtype, 1, self.max_bytes))
        return ((self.data, self.validity, self.offsets, self.codes),
                (self.dtype, 3 if self.dict_sorted else 2, self.max_bytes))

    @classmethod
    def tree_unflatten(cls, aux, children):
        dtype, kind, max_bytes = aux
        if kind == 5:
            validity, kids = children
            return cls(data=None, validity=validity, dtype=dtype,
                       children=tuple(kids))
        if kind == 4:
            data, validity, elem_validity, lengths = children
            return cls(data=data, validity=validity, dtype=dtype,
                       elem_validity=elem_validity, lengths=lengths)
        if kind == 0:
            data, validity = children
            return cls(data=data, validity=validity, dtype=dtype)
        if kind == 1:
            data, validity, offsets = children
            return cls(data=data, validity=validity, dtype=dtype,
                       offsets=offsets, max_bytes=max_bytes)
        data, validity, offsets, codes = children
        return cls(data=data, validity=validity, dtype=dtype, offsets=offsets,
                   max_bytes=max_bytes, codes=codes, dict_sorted=kind == 3)

    # -- properties ---------------------------------------------------------
    @property
    def is_string(self) -> bool:
        return self.offsets is not None

    @property
    def is_dict(self) -> bool:
        return self.codes is not None

    @property
    def is_array(self) -> bool:
        return self.lengths is not None

    @property
    def is_struct(self) -> bool:
        return bool(self.children)

    @property
    def is_complex(self) -> bool:
        return self.is_array or self.is_struct

    @property
    def max_len(self) -> int:
        assert self.is_array
        return int(self.data.shape[1])

    @property
    def capacity(self) -> int:
        if self.children:
            return int(self.validity.shape[0])
        if self.codes is not None:
            return int(self.codes.shape[0])
        if self.is_string:
            return int(self.offsets.shape[0]) - 1
        return int(self.data.shape[0])

    @property
    def dict_size(self) -> int:
        assert self.is_dict
        return int(self.offsets.shape[0]) - 1

    @property
    def byte_capacity(self) -> int:
        assert self.is_string
        return int(self.data.shape[0])

    @property
    def size_bytes(self) -> int:
        total = self.validity.size
        if self.data is not None:
            total += self.data.size * self.data.dtype.itemsize
        if self.offsets is not None:
            total += self.offsets.size * 4
        if self.codes is not None:
            total += self.codes.size * 4
        if self.elem_validity is not None:
            total += self.elem_validity.size
        if self.lengths is not None:
            total += self.lengths.size * 4
        for c in self.children:
            total += c.size_bytes
        return total

    # -- constructors -------------------------------------------------------
    @staticmethod
    def from_numpy(values: np.ndarray, validity: Optional[np.ndarray],
                   dtype: T.DataType, capacity: int) -> "DeviceColumn":
        """Upload a host fixed-width array, padding to ``capacity``."""
        n = len(values)
        assert n <= capacity, (n, capacity)
        np_dt = dtype.np_dtype
        buf = np.zeros(capacity, dtype=np_dt)
        buf[:n] = values.astype(np_dt, copy=False)
        mask = np.zeros(capacity, dtype=np.bool_)
        if validity is None:
            mask[:n] = True
        else:
            mask[:n] = validity
            buf[:n] = np.where(validity, buf[:n], np.zeros((), np_dt))
        return DeviceColumn(jnp.asarray(buf), jnp.asarray(mask), dtype)

    @staticmethod
    def string_from_host(offsets: np.ndarray, data: np.ndarray,
                         validity: Optional[np.ndarray], capacity: int,
                         byte_capacity: Optional[int] = None) -> "DeviceColumn":
        """Upload Arrow string buffers, padding offsets by clamping to the end."""
        n = len(offsets) - 1
        assert n <= capacity
        nbytes = int(offsets[-1])
        byte_capacity = byte_capacity or bucket_byte_capacity(max(nbytes, 1))
        off = np.full(capacity + 1, nbytes, dtype=np.int32)
        off[: n + 1] = offsets.astype(np.int32, copy=False)
        payload = np.zeros(byte_capacity, dtype=np.uint8)
        payload[:nbytes] = data[:nbytes]
        mask = np.zeros(capacity, dtype=np.bool_)
        if validity is None:
            mask[:n] = True
        else:
            mask[:n] = validity
        item_lens = np.diff(offsets)
        max_bytes = bucket_byte_capacity(int(item_lens.max()) if n else 1, 8)
        return DeviceColumn(jnp.asarray(payload), jnp.asarray(mask), T.STRING,
                            offsets=jnp.asarray(off), max_bytes=max_bytes)

    @staticmethod
    def from_arrow(arr: pa.Array, capacity: int) -> "DeviceColumn":
        """Upload a pyarrow array (the host interchange format, like
        JCudfSerialization host buffers in the reference). Conversions are
        memoized on the immutable arrow buffers (see data/upload_cache.py)
        so re-uploading data the device has already seen skips both the
        host-side prep and the transfer."""
        from . import upload_cache
        arr = arr.combine_chunks() if isinstance(arr, pa.ChunkedArray) else arr
        hit = upload_cache.lookup(arr, capacity)
        if hit is not None:
            return hit
        col = DeviceColumn._from_arrow_uncached(arr, capacity)
        upload_cache.insert(arr, capacity, col)
        return col

    @staticmethod
    def _from_arrow_uncached(arr: pa.Array, capacity: int) -> "DeviceColumn":
        dtype = T.from_arrow_type(arr.type)
        if isinstance(dtype, T.ArrayType):
            return DeviceColumn.array_from_arrow(arr, dtype, capacity)
        if isinstance(dtype, T.StructType):
            validity = _arrow_validity(arr)
            mask = np.zeros(capacity, dtype=np.bool_)
            mask[: len(arr)] = True if validity is None else validity
            kids = tuple(DeviceColumn.from_arrow(arr.field(i), capacity)
                         for i in range(arr.type.num_fields))
            return DeviceColumn(data=None, validity=jnp.asarray(mask),
                                dtype=dtype, children=kids)
        if dtype is T.STRING:
            return DeviceColumn.dict_string_from_arrow(arr, capacity)
        if dtype is T.NULL:
            return DeviceColumn.from_numpy(
                np.zeros(len(arr), dtype=np.int8),
                np.zeros(len(arr), dtype=np.bool_), T.NULL, capacity)
        values, validity = _fixed_np_from_arrow(arr, dtype)
        return DeviceColumn.from_numpy(values, validity, dtype, capacity)

    @staticmethod
    def array_from_arrow(arr: pa.Array, dtype: "T.ArrayType",
                         capacity: int) -> "DeviceColumn":
        """Upload a pyarrow list array in the padded-ragged device layout:
        ``[capacity, max_len]`` element matrix + element mask + length lane
        (see types.ArrayType). max_len buckets to a power of two so jit
        programs are shared across close array sizes."""
        if pa.types.is_large_list(arr.type):
            arr = arr.cast(pa.list_(arr.type.value_type))
        n = len(arr)
        validity = _arrow_validity(arr)
        offs = np.asarray(arr.offsets.to_numpy(zero_copy_only=False),
                          dtype=np.int64)
        lens = np.diff(offs)
        if validity is not None:
            lens = np.where(validity, lens, 0)
        max_len = _pow2(int(lens.max()) if n and lens.size else 1)
        child_vals, child_valid = _fixed_np_from_arrow(
            arr.values, dtype.element_type)
        if child_valid is None:
            child_valid = np.ones(len(child_vals), dtype=np.bool_)
        # Pad the flat child by one zero slot so out-of-range gathers are safe.
        child_vals = np.concatenate(
            [child_vals, np.zeros(1, child_vals.dtype)])
        child_valid = np.concatenate([child_valid, np.zeros(1, np.bool_)])
        j = np.arange(max_len, dtype=np.int64)[None, :]
        idx = offs[:n, None] + j                     # [n, max_len]
        in_row = j < lens[:, None]
        idx = np.where(in_row, idx, len(child_vals) - 1)
        data = np.zeros((capacity, max_len), dtype=child_vals.dtype)
        emask = np.zeros((capacity, max_len), dtype=np.bool_)
        data[:n] = np.where(in_row, child_vals[idx],
                            np.zeros((), child_vals.dtype))
        emask[:n] = in_row & child_valid[idx]
        data[:n] = np.where(emask[:n], data[:n],
                            np.zeros((), child_vals.dtype))
        lengths = np.zeros(capacity, dtype=np.int32)
        lengths[:n] = lens.astype(np.int32)
        mask = np.zeros(capacity, dtype=np.bool_)
        mask[:n] = True if validity is None else validity
        return DeviceColumn(
            data=jnp.asarray(data), validity=jnp.asarray(mask), dtype=dtype,
            elem_validity=jnp.asarray(emask), lengths=jnp.asarray(lengths))

    @staticmethod
    def dict_string_from_arrow(arr: pa.Array, capacity: int
                               ) -> "DeviceColumn":
        """Upload a string array dictionary-encoded: codes[capacity] into a
        SORTED unique dictionary, so code order/equality match string
        order/equality on device."""
        import pyarrow.compute as pc
        arr = arr.cast(pa.string())
        validity = _arrow_validity(arr)
        d = pc.dictionary_encode(arr)
        entries = d.dictionary  # unique, appearance order
        codes = d.indices.fill_null(0).to_numpy(zero_copy_only=False) \
            .astype(np.int32)
        vals = entries.to_pylist()
        order = np.argsort(np.asarray(
            [v.encode() for v in vals], dtype=object), kind="stable") \
            if vals else np.zeros(0, np.int64)
        rank = np.empty(len(vals), dtype=np.int32)
        rank[order] = np.arange(len(vals), dtype=np.int32)
        codes = rank[codes] if len(vals) else codes
        sorted_vals = [vals[i] for i in order]
        raw = [v.encode() for v in sorted_vals] or [b""]
        n_dict = len(raw)
        lens = np.asarray([len(b) for b in raw], dtype=np.int32)
        offsets = np.concatenate([[0], np.cumsum(lens)]).astype(np.int32)
        payload = np.frombuffer(b"".join(raw), dtype=np.uint8) \
            if offsets[-1] else np.zeros(0, np.uint8)
        byte_cap = bucket_byte_capacity(max(int(offsets[-1]), 1))
        buf = np.zeros(byte_cap, np.uint8)
        buf[: offsets[-1]] = payload
        code_buf = np.zeros(capacity, np.int32)
        code_buf[: len(codes)] = codes
        mask = np.zeros(capacity, np.bool_)
        if validity is None:
            mask[: len(arr)] = True
        else:
            mask[: len(arr)] = validity
            code_buf[: len(codes)] = np.where(validity, codes, 0)
        max_bytes = bucket_byte_capacity(int(lens.max()) if n_dict else 1, 8)
        return DeviceColumn(
            data=jnp.asarray(buf), validity=jnp.asarray(mask),
            dtype=T.STRING, offsets=jnp.asarray(offsets),
            max_bytes=max_bytes, codes=jnp.asarray(code_buf),
            dict_sorted=True)

    def head(self, cap: int) -> "DeviceColumn":
        """Front-slice to a smaller capacity (rows past n_rows are dead by
        invariant, so a plain slice is sufficient)."""
        if self.is_struct:
            return DeviceColumn(
                data=None, validity=self.validity[:cap], dtype=self.dtype,
                children=tuple(c.head(cap) for c in self.children))
        if self.is_array:
            return DeviceColumn(
                data=self.data[:cap], validity=self.validity[:cap],
                dtype=self.dtype, elem_validity=self.elem_validity[:cap],
                lengths=self.lengths[:cap])
        if self.is_dict:
            return self.replace_rows(self.validity[:cap],
                                     codes=self.codes[:cap])
        if self.is_string:
            return DeviceColumn(self.data, self.validity[:cap], self.dtype,
                                self.offsets[: cap + 1], self.max_bytes)
        return DeviceColumn(self.data[:cap], self.validity[:cap], self.dtype)

    def grow(self, cap: int) -> "DeviceColumn":
        """Pad to a LARGER capacity with dead rows — the inverse of
        :meth:`head`. Padding preserves the core invariant (rows at
        index >= n_rows have validity False and zero data; flat-string
        offsets clamp to the end), so growing a batch never changes
        results. The shape-polymorphic fused path (exec/fusion.py) uses
        this to canonicalize boundary inputs onto coarse capacity tiers.
        Traceable: safe inside jit."""
        old = self.capacity
        if cap == old:
            return self
        assert cap > old, (cap, old)
        pad = cap - old
        validity = jnp.pad(self.validity, (0, pad))
        if self.is_struct:
            return DeviceColumn(
                data=None, validity=validity, dtype=self.dtype,
                children=tuple(c.grow(cap) for c in self.children))
        if self.is_array:
            return DeviceColumn(
                data=jnp.pad(self.data, ((0, pad), (0, 0))),
                validity=validity, dtype=self.dtype,
                elem_validity=jnp.pad(self.elem_validity, ((0, pad), (0, 0))),
                lengths=jnp.pad(self.lengths, (0, pad)))
        if self.is_dict:
            return self.replace_rows(validity,
                                     codes=jnp.pad(self.codes, (0, pad)))
        if self.is_string:
            return DeviceColumn(self.data, validity, self.dtype,
                                jnp.pad(self.offsets, (0, pad), mode="edge"),
                                self.max_bytes)
        return DeviceColumn(jnp.pad(self.data, (0, pad)), validity,
                            self.dtype)

    def replace_rows(self, validity, data=None, codes=None) -> "DeviceColumn":
        """Same column with row-level arrays swapped (dict buffers kept)."""
        return DeviceColumn(
            data=self.data if data is None else data,
            validity=validity, dtype=self.dtype, offsets=self.offsets,
            max_bytes=self.max_bytes,
            codes=self.codes if codes is None else codes,
            dict_sorted=self.dict_sorted)

    # -- download -----------------------------------------------------------
    def device_buffers(self) -> tuple:
        """The device arrays to download for host reassembly (batch these
        through one ``jax.device_get`` — the tunnel charges per round trip).
        Struct columns nest their children's buffers (device_get treats the
        whole thing as one pytree)."""
        if self.is_struct:
            return (self.validity,
                    tuple(c.device_buffers() for c in self.children))
        if self.is_array:
            return (self.data, self.validity, self.elem_validity,
                    self.lengths)
        if self.is_dict:
            return (self.data, self.validity, self.offsets, self.codes)
        if self.is_string:
            return (self.data, self.validity, self.offsets)
        return (self.data, self.validity)

    def arrow_from_host(self, bufs: tuple, n_rows: int) -> pa.Array:
        """Reassemble a pyarrow array from downloaded buffers (see
        :meth:`device_buffers`). Zero-copy: the device layout IS the Arrow
        layout (offsets + bytes, values + validity); no per-row Python."""
        if self.dtype is T.NULL:
            return pa.nulls(n_rows)
        if self.is_struct:
            validity = np.ascontiguousarray(bufs[0][:n_rows])
            all_valid = bool(validity.all())
            mask_buf = None if all_valid else \
                pa.py_buffer(np.packbits(validity, bitorder="little"))
            kids = [c.arrow_from_host(b, n_rows)
                    for c, b in zip(self.children, bufs[1])]
            return pa.Array.from_buffers(
                T.to_arrow_type(self.dtype), n_rows, [mask_buf],
                0 if all_valid else int(n_rows - validity.sum()),
                children=kids)
        if self.is_array:
            data, validity, emask, lengths = bufs
            validity = np.ascontiguousarray(validity[:n_rows])
            all_valid = bool(validity.all())
            mask_buf = None if all_valid else \
                pa.py_buffer(np.packbits(validity, bitorder="little"))
            lens = np.where(validity, lengths[:n_rows], 0).astype(np.int64)
            offsets = np.concatenate([[0], np.cumsum(lens)]).astype(np.int32)
            keep = np.arange(data.shape[1])[None, :] < lens[:, None]
            flat_vals = np.ascontiguousarray(data[:n_rows][keep])
            flat_valid = np.ascontiguousarray(emask[:n_rows][keep])
            et = self.dtype.element_type
            child = _np_values_to_arrow(flat_vals, flat_valid, et)
            return pa.Array.from_buffers(
                T.to_arrow_type(self.dtype), n_rows,
                [mask_buf, pa.py_buffer(offsets)],
                0 if all_valid else int(n_rows - validity.sum()),
                children=[child])
        validity = np.ascontiguousarray(bufs[1][:n_rows])
        all_valid = bool(validity.all())
        null_count = 0 if all_valid else int(n_rows - validity.sum())
        mask_buf = None if all_valid else \
            pa.py_buffer(np.packbits(validity, bitorder="little"))
        if self.is_dict:
            payload, _, offsets, codes = bufs
            n_dict = len(offsets) - 1
            entries = pa.StringArray.from_buffers(
                n_dict, pa.py_buffer(np.ascontiguousarray(offsets)),
                pa.py_buffer(np.ascontiguousarray(
                    payload[: offsets[-1]])), None, 0)
            idx = pa.Array.from_buffers(
                pa.int32(), n_rows,
                [mask_buf, pa.py_buffer(np.ascontiguousarray(
                    np.clip(codes[:n_rows], 0, max(n_dict - 1, 0))))],
                null_count)
            return pa.DictionaryArray.from_arrays(idx, entries) \
                .cast(pa.string())
        if self.is_string:
            offsets = np.ascontiguousarray(bufs[2][: n_rows + 1])
            payload = np.ascontiguousarray(bufs[0])
            return pa.StringArray.from_buffers(
                n_rows, pa.py_buffer(offsets), pa.py_buffer(payload),
                mask_buf, null_count)
        values = np.ascontiguousarray(bufs[0][:n_rows])
        arrow_type = T.to_arrow_type(self.dtype)
        if self.dtype is T.BOOLEAN:
            values_buf = pa.py_buffer(np.packbits(values, bitorder="little"))
        else:
            values_buf = pa.py_buffer(values)
        return pa.Array.from_buffers(
            arrow_type, n_rows, [mask_buf, values_buf], null_count)

    def to_arrow(self, n_rows: int) -> pa.Array:
        """Download the first ``n_rows`` live rows as a pyarrow array."""
        return self.arrow_from_host(
            jax.device_get(self.device_buffers()), n_rows)


def _arrow_validity(arr: pa.Array) -> Optional[np.ndarray]:
    if arr.null_count == 0:
        return None
    return np.asarray(arr.is_valid())


def _pow2(n: int, lo: int = 1) -> int:
    cap = max(lo, 1)
    while cap < n:
        cap <<= 1
    return cap


def _np_values_to_arrow(values: np.ndarray, validity: Optional[np.ndarray],
                        dtype: T.DataType) -> pa.Array:
    """Fixed-width numpy values (+ optional bool validity) -> arrow array."""
    n = len(values)
    if validity is None or bool(np.asarray(validity).all()):
        mask_buf, null_count = None, 0
    else:
        mask_buf = pa.py_buffer(np.packbits(validity, bitorder="little"))
        null_count = int(n - validity.sum())
    if dtype is T.BOOLEAN:
        values_buf = pa.py_buffer(np.packbits(values, bitorder="little"))
    else:
        values_buf = pa.py_buffer(np.ascontiguousarray(values))
    return pa.Array.from_buffers(
        T.to_arrow_type(dtype), n, [mask_buf, values_buf], null_count)


def _fixed_np_from_arrow(arr: pa.Array, dtype: T.DataType):
    """(values, validity) numpy pair for a fixed-width arrow array, nulls
    zero-filled (the null-data-is-zero invariant)."""
    if dtype is T.TIMESTAMP:
        arr = arr.cast(pa.timestamp("us"))
    validity = _arrow_validity(arr)
    filled = arr.fill_null(False if dtype is T.BOOLEAN else 0) \
        if arr.null_count else arr
    values = filled.to_numpy(zero_copy_only=False)
    if values.dtype.kind == "M":  # datetime64 from date32/timestamp
        unit = "D" if dtype is T.DATE else "us"
        values = values.astype(f"datetime64[{unit}]").view(np.int64)
    return values.astype(dtype.np_dtype, copy=False), validity


def null_column(dtype: T.DataType, capacity: int) -> DeviceColumn:
    """An all-null column of the given type (used for outer-join padding)."""
    if isinstance(dtype, T.ArrayType):
        return DeviceColumn(
            data=jnp.zeros((capacity, 1), dtype=dtype.element_type.np_dtype),
            validity=jnp.zeros(capacity, dtype=jnp.bool_), dtype=dtype,
            elem_validity=jnp.zeros((capacity, 1), dtype=jnp.bool_),
            lengths=jnp.zeros(capacity, dtype=jnp.int32))
    if isinstance(dtype, T.StructType):
        return DeviceColumn(
            data=None, validity=jnp.zeros(capacity, dtype=jnp.bool_),
            dtype=dtype,
            children=tuple(null_column(f.data_type, capacity)
                           for f in dtype.fields))
    if dtype is T.STRING:
        # Dict-encoded: one empty dictionary entry, all codes 0, all null.
        return DeviceColumn(
            data=jnp.zeros(8, dtype=jnp.uint8),
            validity=jnp.zeros(capacity, dtype=jnp.bool_),
            dtype=T.STRING,
            offsets=jnp.zeros(2, dtype=jnp.int32),
            max_bytes=8,
            codes=jnp.zeros(capacity, dtype=jnp.int32),
            dict_sorted=True)
    return DeviceColumn(
        data=jnp.zeros(capacity, dtype=dtype.np_dtype),
        validity=jnp.zeros(capacity, dtype=jnp.bool_),
        dtype=dtype)


def scalar_column(value, dtype: T.DataType, capacity: int,
                  live) -> DeviceColumn:
    """Broadcast a literal into a column (GpuLiteral expansion,
    reference literals.scala:128). ``live`` is the batch's row MASK —
    lazy-filtered batches have scattered live rows, so a prefix
    (iota < n_rows) would mark the wrong lanes valid."""
    import jax.numpy as _jnp
    if value is None:
        return null_column(dtype, capacity)
    live = _jnp.asarray(live)
    if dtype is T.STRING:
        # Dict-encoded: ONE dictionary entry, every live row points at it —
        # O(1) payload instead of a capacity-wide tiled buffer.
        raw = np.frombuffer(str(value).encode("utf-8"), dtype=np.uint8)
        ln = len(raw)
        byte_cap = bucket_byte_capacity(max(ln, 1), 8)
        payload = np.zeros(byte_cap, dtype=np.uint8)
        payload[:ln] = raw
        valid = live
        return DeviceColumn(
            data=jnp.asarray(payload),
            validity=valid,
            dtype=T.STRING,
            offsets=jnp.asarray(np.asarray([0, ln], np.int32)),
            max_bytes=bucket_byte_capacity(max(ln, 1), 8),
            codes=jnp.zeros(capacity, dtype=jnp.int32),
            dict_sorted=True)
    valid = live
    data = jnp.where(valid, jnp.asarray(value, dtype=dtype.np_dtype), 0)
    return DeviceColumn(data=data.astype(dtype.np_dtype), validity=valid, dtype=dtype)
