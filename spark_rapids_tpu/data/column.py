"""Device-resident columnar vectors — the ``GpuColumnVector`` analog.

The reference wraps cudf device columns in Spark ``ColumnVector`` objects
(reference: ``sql-plugin/src/main/java/.../GpuColumnVector.java:40``). cuDF's
model is eager and dynamically shaped: every kernel allocates an exactly-sized
output. That model is hostile to XLA, which wants static shapes and traced
programs.

The TPU-native model here is different by design:

* A :class:`DeviceColumn` owns a **fixed-capacity** buffer (power-of-two
  bucketed, lane-aligned) plus a validity mask. The number of live rows is
  tracked by the enclosing batch as a *traced* scalar, so data-dependent row
  counts (filters, joins) flow through a compiled program without host syncs
  or recompilation.
* Invariant: rows at index >= n_rows always have ``validity == False`` and
  deterministic (zero) data, so masked reductions never need the row count and
  padding never changes results.
* Strings use the Arrow layout — ``offsets: int32[capacity+1]`` into a
  ``uint8[byte_capacity]`` payload — the same layout cudf uses on GPU, which is
  also the right layout for TPU gather/scatter kernels.

Columns are registered as jax pytrees, so whole batches can be passed straight
through ``jax.jit`` boundaries; the dtype/capacity live in the static treedef,
giving one compiled program per capacity bucket.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from .. import types as T

#: Lane width of the VPU — the minimum sensible capacity granularity.
LANE = 128


def bucket_capacity(n: int, min_capacity: int = LANE) -> int:
    """Round up to a power of two (>= min_capacity) to bound jit cache size."""
    cap = max(int(min_capacity), LANE)
    n = max(int(n), 1)
    while cap < n:
        cap <<= 1
    return cap


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class DeviceColumn:
    """One column of one device batch.

    For fixed-width types, ``data`` has shape ``[capacity]``. For strings,
    ``data`` is the ``uint8`` byte payload, ``offsets`` is ``int32[capacity+1]``
    and for entries past the live row count offsets are clamped to the last
    valid offset.
    """

    data: jax.Array
    validity: jax.Array  # bool[capacity]
    dtype: T.DataType
    offsets: Optional[jax.Array] = None  # int32[capacity + 1], strings only
    #: Static upper bound on any single string's byte length (strings only).
    #: Host-known at upload; device string kernels use it to bound the padded
    #: char-matrix width. Propagates through string ops (substr keeps it,
    #: concat sums it).
    max_bytes: int = 0

    # -- pytree protocol ----------------------------------------------------
    def tree_flatten(self):
        if self.offsets is None:
            return (self.data, self.validity), (self.dtype, False, 0)
        return (self.data, self.validity, self.offsets), (self.dtype, True, self.max_bytes)

    @classmethod
    def tree_unflatten(cls, aux, children):
        dtype, has_offsets, max_bytes = aux
        if has_offsets:
            data, validity, offsets = children
            return cls(data=data, validity=validity, dtype=dtype, offsets=offsets,
                       max_bytes=max_bytes)
        data, validity = children
        return cls(data=data, validity=validity, dtype=dtype, offsets=None)

    # -- properties ---------------------------------------------------------
    @property
    def is_string(self) -> bool:
        return self.offsets is not None

    @property
    def capacity(self) -> int:
        if self.is_string:
            return int(self.offsets.shape[0]) - 1
        return int(self.data.shape[0])

    @property
    def byte_capacity(self) -> int:
        assert self.is_string
        return int(self.data.shape[0])

    # -- constructors -------------------------------------------------------
    @staticmethod
    def from_numpy(values: np.ndarray, validity: Optional[np.ndarray],
                   dtype: T.DataType, capacity: int) -> "DeviceColumn":
        """Upload a host fixed-width array, padding to ``capacity``."""
        n = len(values)
        assert n <= capacity, (n, capacity)
        np_dt = dtype.np_dtype
        buf = np.zeros(capacity, dtype=np_dt)
        buf[:n] = values.astype(np_dt, copy=False)
        mask = np.zeros(capacity, dtype=np.bool_)
        if validity is None:
            mask[:n] = True
        else:
            mask[:n] = validity
            buf[:n] = np.where(validity, buf[:n], np.zeros((), np_dt))
        return DeviceColumn(jnp.asarray(buf), jnp.asarray(mask), dtype)

    @staticmethod
    def string_from_host(offsets: np.ndarray, data: np.ndarray,
                         validity: Optional[np.ndarray], capacity: int,
                         byte_capacity: Optional[int] = None) -> "DeviceColumn":
        """Upload Arrow string buffers, padding offsets by clamping to the end."""
        n = len(offsets) - 1
        assert n <= capacity
        nbytes = int(offsets[-1])
        byte_capacity = byte_capacity or bucket_capacity(max(nbytes, 1))
        off = np.full(capacity + 1, nbytes, dtype=np.int32)
        off[: n + 1] = offsets.astype(np.int32, copy=False)
        payload = np.zeros(byte_capacity, dtype=np.uint8)
        payload[:nbytes] = data[:nbytes]
        mask = np.zeros(capacity, dtype=np.bool_)
        if validity is None:
            mask[:n] = True
        else:
            mask[:n] = validity
        item_lens = np.diff(offsets)
        max_bytes = bucket_capacity(int(item_lens.max()) if n else 1, 8)
        return DeviceColumn(jnp.asarray(payload), jnp.asarray(mask), T.STRING,
                            offsets=jnp.asarray(off), max_bytes=max_bytes)

    @staticmethod
    def from_arrow(arr: pa.Array, capacity: int) -> "DeviceColumn":
        """Upload a pyarrow array (the host interchange format, like
        JCudfSerialization host buffers in the reference)."""
        dtype = T.from_arrow_type(arr.type)
        arr = arr.combine_chunks() if isinstance(arr, pa.ChunkedArray) else arr
        if dtype is T.STRING:
            arr = arr.cast(pa.string())
            validity = _arrow_validity(arr)
            offsets = np.asarray(arr.buffers()[1], dtype=np.uint8).view(np.int32)
            offsets = offsets[arr.offset: arr.offset + len(arr) + 1].copy()
            base = offsets[0]
            offsets -= base
            payload_buf = arr.buffers()[2]
            if payload_buf is None:
                payload = np.zeros(0, dtype=np.uint8)
            else:
                payload = np.asarray(payload_buf, dtype=np.uint8)[
                    base: base + offsets[-1]]
            # Null slots may have nonzero extent in arrow; normalize so hashes
            # of null rows are deterministic.
            return DeviceColumn.string_from_host(offsets, payload, validity, capacity)
        if dtype is T.NULL:
            return DeviceColumn.from_numpy(
                np.zeros(len(arr), dtype=np.int8),
                np.zeros(len(arr), dtype=np.bool_), T.NULL, capacity)
        if dtype is T.TIMESTAMP:
            arr = arr.cast(pa.timestamp("us"))
        validity = _arrow_validity(arr)
        # Null slots get a deterministic zero so padded/invalid data never
        # perturbs hashes or reductions.
        filled = arr.fill_null(False if dtype is T.BOOLEAN else 0) \
            if arr.null_count else arr
        values = filled.to_numpy(zero_copy_only=False)
        if values.dtype.kind == "M":  # datetime64 from date32/timestamp
            unit = "D" if dtype is T.DATE else "us"
            values = values.astype(f"datetime64[{unit}]").view(np.int64)
        return DeviceColumn.from_numpy(
            values.astype(dtype.np_dtype, copy=False), validity, dtype, capacity)

    # -- download -----------------------------------------------------------
    def device_buffers(self) -> tuple:
        """The device arrays to download for host reassembly (batch these
        through one ``jax.device_get`` — the tunnel charges per round trip)."""
        if self.is_string:
            return (self.data, self.validity, self.offsets)
        return (self.data, self.validity)

    def arrow_from_host(self, bufs: tuple, n_rows: int) -> pa.Array:
        """Reassemble a pyarrow array from downloaded buffers (see
        :meth:`device_buffers`). Zero-copy: the device layout IS the Arrow
        layout (offsets + bytes, values + validity); no per-row Python."""
        if self.dtype is T.NULL:
            return pa.nulls(n_rows)
        validity = np.ascontiguousarray(bufs[1][:n_rows])
        all_valid = bool(validity.all())
        null_count = 0 if all_valid else int(n_rows - validity.sum())
        mask_buf = None if all_valid else \
            pa.py_buffer(np.packbits(validity, bitorder="little"))
        if self.is_string:
            offsets = np.ascontiguousarray(bufs[2][: n_rows + 1])
            payload = np.ascontiguousarray(bufs[0])
            return pa.StringArray.from_buffers(
                n_rows, pa.py_buffer(offsets), pa.py_buffer(payload),
                mask_buf, null_count)
        values = np.ascontiguousarray(bufs[0][:n_rows])
        arrow_type = T.to_arrow_type(self.dtype)
        if self.dtype is T.BOOLEAN:
            values_buf = pa.py_buffer(np.packbits(values, bitorder="little"))
        else:
            values_buf = pa.py_buffer(values)
        return pa.Array.from_buffers(
            arrow_type, n_rows, [mask_buf, values_buf], null_count)

    def to_arrow(self, n_rows: int) -> pa.Array:
        """Download the first ``n_rows`` live rows as a pyarrow array."""
        return self.arrow_from_host(
            jax.device_get(self.device_buffers()), n_rows)


def _arrow_validity(arr: pa.Array) -> Optional[np.ndarray]:
    if arr.null_count == 0:
        return None
    return np.asarray(arr.is_valid())


def null_column(dtype: T.DataType, capacity: int) -> DeviceColumn:
    """An all-null column of the given type (used for outer-join padding)."""
    if dtype is T.STRING:
        return DeviceColumn(
            data=jnp.zeros(LANE, dtype=jnp.uint8),
            validity=jnp.zeros(capacity, dtype=jnp.bool_),
            dtype=T.STRING,
            offsets=jnp.zeros(capacity + 1, dtype=jnp.int32),
            max_bytes=8)
    return DeviceColumn(
        data=jnp.zeros(capacity, dtype=dtype.np_dtype),
        validity=jnp.zeros(capacity, dtype=jnp.bool_),
        dtype=dtype)


def scalar_column(value, dtype: T.DataType, capacity: int,
                  n_rows) -> DeviceColumn:
    """Broadcast a literal into a column (GpuLiteral expansion,
    reference literals.scala:128)."""
    if value is None:
        return null_column(dtype, capacity)
    if dtype is T.STRING:
        raw = np.frombuffer(str(value).encode("utf-8"), dtype=np.uint8)
        ln = len(raw)
        byte_cap = bucket_capacity(max(ln, 1) * capacity)
        payload = np.zeros(byte_cap, dtype=np.uint8)
        if ln:
            payload[: ln * capacity] = np.tile(raw, capacity)
        offsets = np.arange(capacity + 1, dtype=np.int64) * ln
        valid = jnp.arange(capacity) < n_rows
        return DeviceColumn(
            data=jnp.asarray(payload),
            validity=valid,
            dtype=T.STRING,
            offsets=jnp.asarray(offsets.astype(np.int32)),
            max_bytes=bucket_capacity(max(ln, 1), 8))
    valid = jnp.arange(capacity) < n_rows
    data = jnp.where(valid, jnp.asarray(value, dtype=dtype.np_dtype), 0)
    return DeviceColumn(data=data.astype(dtype.np_dtype), validity=valid, dtype=dtype)
