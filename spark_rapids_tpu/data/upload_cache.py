"""Upload memo cache: host->device conversion keyed on arrow buffer
identity.

The expensive half of a host->device transition is not the DMA — it is
the host-side columnar prep (dictionary-encoding strings, null-mask
expansion, capacity padding) plus the transfer itself. Re-collecting a
query over the same immutable host data (a cached DataFrame re-read, an
AQE re-planned stage, a bench loop) repays that cost for bytes the
device has already seen.

pyarrow buffers are immutable, so ``(buffer address, size)`` tuples
identify content for the lifetime of the buffer. Each cache entry pins a
strong reference to its source array, which keeps those addresses from
being recycled — a hit can therefore never alias freed memory. Eviction
is LRU under a byte budget (device bytes of the cached columns).

Reference analog: the RapidsBufferCatalog keeps shuffle/broadcast
batches device-resident so re-reads skip the host round trip
(RapidsBufferCatalog.scala:30); this memo plays that role for repeated
host->device uploads, where the reference relies on Spark's block
manager caching instead.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Optional

import pyarrow as pa

from ..utils import lockdep

#: byte budget for cached device columns (set from conf at session init)
_budget_bytes = 1 << 30
_lock = lockdep.lock("upload_cache._lock")
_entries: "OrderedDict[tuple, tuple]" = OrderedDict()  # key -> (col, src, nb)
_bytes = 0
stats = {"hits": 0, "misses": 0, "evictions": 0}


def set_budget(n_bytes: int) -> None:
    global _budget_bytes
    with _lock:
        _budget_bytes = int(n_bytes)
    _trim()


def _key(arr: pa.Array, capacity: int) -> Optional[tuple]:
    try:
        bufs = arr.buffers()
    except NotImplementedError:  # pragma: no cover - exotic array types
        return None
    return (str(arr.type), arr.offset, len(arr), capacity,
            tuple((b.address, b.size) if b is not None else None
                  for b in bufs))


def lookup(arr: pa.Array, capacity: int):
    """Return the cached DeviceColumn for (arr, capacity) or None."""
    if _budget_bytes <= 0:
        return None
    k = _key(arr, capacity)
    if k is None:
        return None
    with _lock:
        ent = _entries.get(k)
        if ent is None:
            stats["misses"] += 1
            return None
        _entries.move_to_end(k)
        stats["hits"] += 1
        return ent[0]


def insert(arr: pa.Array, capacity: int, col) -> None:
    global _bytes
    if _budget_bytes <= 0:
        return
    k = _key(arr, capacity)
    if k is None:
        return
    nb = col.size_bytes
    if nb > _budget_bytes:
        return
    with _lock:
        if k in _entries:
            return
        # the strong ref to ``arr`` pins its buffer addresses (no ABA)
        _entries[k] = (col, arr, nb)
        _bytes += nb
    _trim()


def _trim() -> None:
    global _bytes
    with _lock:
        while _bytes > _budget_bytes and _entries:
            _, (_, _, nb) = _entries.popitem(last=False)
            _bytes -= nb
            stats["evictions"] += 1


def shrink_by(n_bytes: int) -> int:
    """LRU-evict until ~n_bytes are freed (or the cache is empty);
    returns bytes actually freed. Used by the spill catalog to reclaim
    pure-cache HBM before spilling real buffers."""
    global _bytes
    freed = 0
    with _lock:
        while freed < n_bytes and _entries:
            _, (_, _, nb) = _entries.popitem(last=False)
            _bytes -= nb
            freed += nb
            stats["evictions"] += 1
    return freed


def clear() -> None:
    global _bytes
    with _lock:
        _entries.clear()
        _bytes = 0


def cache_bytes() -> int:
    return _bytes
