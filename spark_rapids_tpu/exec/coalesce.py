"""Batch coalescing — the ``GpuCoalesceBatches`` analog.

The reference concatenates small batches toward a size goal before ops that
want large inputs, with a goal algebra deciding where the planner must insert
coalesce nodes (CoalesceGoal:91-113, exec GpuCoalesceBatches.scala:502,
insertion GpuTransitionOverrides.scala:103). Same architecture here; the
device concat is the traced scatter kernel (ops/kernels/concat.py), and
accumulated batches are registered with the spill catalog so memory pressure
can push them to host/disk while they wait (the reference makes its
coalesce inputs spillable the same way)."""

from __future__ import annotations

from typing import List, Optional

from .. import types as T
from ..data.batch import ColumnarBatch
from ..memory import spill as SP
from ..plan.physical import PhysicalPlan
from .execs import TpuExec, _coalesce_device


class CoalesceGoal:
    def satisfied_by(self, other: "CoalesceGoal") -> bool:
        """True when batches produced under ``other`` also meet this goal."""
        raise NotImplementedError


class TargetSize(CoalesceGoal):
    def __init__(self, rows: int):
        self.rows = rows

    def satisfied_by(self, other):
        if isinstance(other, RequireSingleBatch):
            return True
        return isinstance(other, TargetSize) and other.rows >= self.rows

    def __repr__(self):
        return f"TargetSize({self.rows})"


class RequireSingleBatch(CoalesceGoal):
    def satisfied_by(self, other):
        return isinstance(other, RequireSingleBatch)

    def __repr__(self):
        return "RequireSingleBatch"


class TpuCoalesceBatchesExec(TpuExec):
    def __init__(self, child: PhysicalPlan, goal: CoalesceGoal):
        self.children = [child]
        self.goal = goal

    @property
    def schema(self):
        return self.children[0].schema

    def describe(self):
        return f"TpuCoalesceBatches ({self.goal!r})"

    def execute(self, ctx):
        from ..memory import retry as R
        catalog: Optional[SP.BufferCatalog] = getattr(ctx, "catalog", None)
        single = isinstance(self.goal, RequireSingleBatch)
        target = None if single else self.goal.rows
        name = self.node_name()

        def run(part):
            # Accumulation is accounted by CAPACITY, not live rows: capacity
            # is static (known without a device->host sync), and rows <=
            # capacity so the goal is still met. The old int(n_rows) read
            # here cost one tunnel round trip per batch — the single most
            # expensive operation on the critical path — and made the exec
            # untraceable under whole-stage fusion.
            pending: List[int] = []    # catalog buffer ids
            direct: List[ColumnarBatch] = []  # no-catalog fallback
            pending_cap = 0

            def concat_ids(ids):
                from .execs import _pinned_concat
                with ctx.registry.timer(name, "concatTime",
                                        trace="coalesce.concat"):
                    return _pinned_concat(catalog, ids)

            def concat_direct(batches):
                with ctx.registry.timer(name, "concatTime",
                                        trace="coalesce.concat"):
                    return _coalesce_device(list(batches))

            def flush():
                nonlocal pending_cap
                if pending:
                    # On OOM the accumulated ids split in half: each half
                    # concatenates separately, so the goal degrades to two
                    # smaller output batches instead of the query dying.
                    outs = R.with_retry(ctx, f"{name}.concat",
                                        list(pending), concat_ids,
                                        split=R.halve_list, node=name)
                    for b in pending:
                        catalog.free(b)
                elif direct:
                    outs = R.with_retry(ctx, f"{name}.concat",
                                        list(direct), concat_direct,
                                        split=R.halve_list, node=name)
                else:
                    return []
                ctx.metric(name, "numInputBatches",
                           len(pending) + len(direct))
                ctx.metric(name, "numOutputBatches", len(outs))
                pending.clear()
                direct.clear()
                pending_cap = 0
                return outs

            for db in part:
                if db.capacity == 0:
                    continue
                if catalog is not None and not ctx.in_fusion:
                    pending.append(catalog.register_batch(
                        db, SP.ACTIVE_BATCHING_PRIORITY,
                        owner=getattr(ctx, "qos", None)))
                else:
                    direct.append(db)
                pending_cap += db.capacity
                if not single and pending_cap >= target:
                    yield from flush()
            yield from flush()
        return [run(p) for p in self.children[0].execute(ctx)]


def insert_coalesce(plan: PhysicalPlan, default_target_rows: int
                    ) -> PhysicalPlan:
    """Insert coalesce nodes per operators' declared child goals, skipping
    where the child already satisfies the goal
    (GpuTransitionOverrides.optimizeCoalesce analog)."""

    def fix(node: PhysicalPlan) -> PhysicalPlan:
        new_children = [fix(c) for c in node.children]
        goals = getattr(node, "children_coalesce_goals", None)
        if goals:
            assert len(goals) == len(new_children), \
                (node.node_name(), goals, len(new_children))
            wrapped = []
            for c, goal in zip(new_children, goals):
                if goal is None or not getattr(c, "columnar", False):
                    wrapped.append(c)
                    continue
                # Execs declare goals as strings to avoid import cycles.
                if goal == "single":
                    goal = RequireSingleBatch()
                elif goal == "target":
                    goal = TargetSize(default_target_rows)
                from .execs import HostToDeviceExec
                if isinstance(c, TpuCoalesceBatchesExec):
                    produced = c.goal
                elif isinstance(c, HostToDeviceExec):
                    # Uploads already accumulate to their goal
                    # (optimizeCoalesce recognizes HostColumnarToGpu goals).
                    produced = TargetSize(c.goal_rows)
                else:
                    produced = None
                if produced is not None and goal.satisfied_by(produced):
                    wrapped.append(c)
                elif isinstance(c, TpuCoalesceBatchesExec):
                    # Replace a weaker coalesce instead of stacking two.
                    wrapped.append(TpuCoalesceBatchesExec(c.children[0], goal))
                else:
                    wrapped.append(TpuCoalesceBatchesExec(c, goal))
            new_children = wrapped
        if list(new_children) != list(node.children):
            node = node.with_children(new_children)
        return node

    return fix(plan)
