"""TPU physical operators — the ``Gpu*Exec`` analogs.

Each exec consumes/produces device :class:`ColumnarBatch` streams. Per-batch
work is a jitted function over the batch pytree: XLA compiles one program per
(schema, capacity bucket) and fuses the whole operator expression tree
(project chains, filter masks, aggregation updates) into a handful of fused
kernels — the TPU answer to cudf's pre-compiled kernel library.

Operator parity map (reference locations in SURVEY.md §2.3):
* TpuProjectExec / TpuFilterExec  <- basicPhysicalOperators.scala:66,127
* TpuHashAggregateExec            <- aggregate.scala:227 (partial/merge loop)
* TpuSortExec                     <- GpuSortExec.scala:50 (RequireSingleBatch)
* TpuShuffledHashJoinExec         <- GpuShuffledHashJoinExec.scala:76 +
                                     GpuHashJoin.doJoin:113-166
* TpuRangeExec / TpuUnionExec / TpuLimitExec / TpuExpandExec
                                  <- basicPhysicalOperators.scala:182,301 /
                                     limit.scala:115 / GpuExpandExec.scala:66
* HostToDeviceExec / DeviceToHostExec <- HostColumnarToGpu.scala:222 /
                                     GpuColumnarToRowExec.scala:35
"""

from __future__ import annotations

from typing import Iterator, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from .. import types as T
from ..data.batch import ColumnarBatch, HostBatch
from ..data.column import DeviceColumn, bucket_capacity
from ..ops import aggregates as AGG
from ..ops.expression import BoundReference, Expression, make_column
from ..ops.kernels import concat as KC
from ..ops.kernels import groupby as KG
from ..ops.kernels import join as KJ
from ..ops.kernels import rowops as KR
from ..plan.logical import SortOrder
from ..plan.physical import ExecContext, PhysicalPlan
from ..utils.kernel_cache import cached_kernel, kernel_key
from ..utils.tracing import trace_range


def _bind_all(exprs: List[Expression], schema: T.Schema) -> List[Expression]:
    return [e.bind(schema) for e in exprs]


def _tick(ctx, name: str, t0: int) -> int:
    """Record one output batch + host-side dispatch time for an exec
    (GpuExec.scala:25-52's NUM_OUTPUT_BATCHES / OP_TIME analog — dispatch
    wall time only: device execution is async and row counts would cost a
    tunnel round trip). Times are nanoseconds (the taxonomy's NANO_TIMING
    opTime; metrics/registry.py)."""
    import time as _time
    now = _time.perf_counter_ns()
    ctx.metric(name, "numOutputBatches", 1)
    ctx.metric(name, "opTime", now - t0)
    return now


def _counted_stream(ctx, name: str, batches):
    """Pass-through generator recording numOutputBatches per batch — the
    minimum ESSENTIAL instrumentation for execs whose per-batch work is too
    cheap to time (union, limits, replays)."""
    for db in batches:
        ctx.metric(name, "numOutputBatches", 1)
        yield db


class TpuExec(PhysicalPlan):
    columnar = True

    #: Per-child coalesce goal ("single" | "target" | None), consumed by
    #: exec.coalesce.insert_coalesce (CoalesceGoal declaration analog,
    #: reference GpuExec.childrenCoalesceGoal).
    children_coalesce_goals = None

    def describe(self):
        return self.node_name()


# ---------------------------------------------------------------------------
# Transitions
# ---------------------------------------------------------------------------


class HostToDeviceExec(TpuExec):
    """Upload host batches, coalescing toward the batch-size goal
    (HostColumnarToGpu + CoalesceGoal, reference HostColumnarToGpu.scala:222)."""

    def __init__(self, child: PhysicalPlan, goal_rows: int = 1 << 20):
        self.children = [child]
        self.goal_rows = goal_rows

    @property
    def schema(self):
        return self.children[0].schema

    def execute(self, ctx):
        arrow = T.schema_to_arrow(self.schema)

        def run(part):
            pending: List[pa.RecordBatch] = []
            pending_rows = 0
            for hb in part:
                rb = hb.rb
                if rb.num_rows == 0:
                    continue
                pending.append(rb.cast(arrow))
                pending_rows += rb.num_rows
                if pending_rows >= self.goal_rows:
                    yield self._upload(pending, ctx)
                    pending, pending_rows = [], 0
            if pending:
                yield self._upload(pending, ctx)
        from ..utils.prefetch import prefetch_iter
        from . import pipeline
        depth = pipeline.prefetch_depth(ctx.conf)
        name = self.node_name()
        return [prefetch_iter(run(p), depth=depth, ctx=ctx, node=name)
                for p in self.children[0].execute(ctx)]

    def _upload(self, rbs: List[pa.RecordBatch],
                ctx=None) -> ColumnarBatch:
        import time as _time
        t0 = _time.perf_counter_ns()
        with trace_range("HostToDevice.upload"):
            if len(rbs) == 1:
                combined = rbs[0]
            else:
                combined = pa.Table.from_batches(rbs).combine_chunks() \
                    .to_batches()[0]
            batch = ColumnarBatch.from_arrow(combined)
        if ctx is not None:
            # uploadBytes = the Arrow buffer footprint crossing the link
            # (the transfer itself is async; opTime is host dispatch wall).
            name = self.node_name()
            ctx.metric(name, "uploadBytes", combined.nbytes)
            ctx.metric(name, "numInputRows", combined.num_rows)
            ctx.metric(name, "numOutputBatches", 1)
            ctx.metric(name, "opTime", _time.perf_counter_ns() - t0)
        return batch


class DeviceToHostExec(PhysicalPlan):
    """Download device batches to host (GpuColumnarToRowExec analog)."""

    columnar = False

    def __init__(self, child: PhysicalPlan):
        self.children = [child]

    @property
    def schema(self):
        return self.children[0].schema

    def execute(self, ctx):
        name = self.node_name()

        def emit(ctx, hb, t0):
            # The download already synced the row count — the one place
            # row metrics are free (GpuExec.NUM_OUTPUT_ROWS analog).
            import time as _time
            ctx.metric(name, "numOutputRows", hb.num_rows)
            ctx.metric(name, "numOutputBatches", 1)
            ctx.metric(name, "downloadBytes", hb.rb.nbytes)
            ctx.metric(name, "opTime", _time.perf_counter_ns() - t0)
            return hb

        def run(part):
            import time as _time
            for db in part:
                t0 = _time.perf_counter_ns()
                with trace_range("DeviceToHost.download"):
                    hb = HostBatch.from_device(db)
                yield emit(ctx, hb, t0)

        def run_overlapped(part):
            # Pipelined streaming download: pulling the NEXT device batch
            # (which dispatches its device work) and starting its async
            # copy-to-host happen BEFORE blocking on the PREVIOUS batch's
            # bytes — transfer and compute stay concurrent (the tentpole
            # overlap; to_arrow_begin/finish split in data/batch.py).
            # opTime carries only this batch's begin+finish spans, NOT the
            # overlapped consumer/upstream time in between — overlapped
            # profiles must stay comparable to serial ones.
            import time as _time
            pending = None  # (begin ns, batch, download handle)
            for db in part:
                t0 = _time.perf_counter_ns()
                with trace_range("DeviceToHost.download_begin"):
                    handle = db.to_arrow_begin()
                begin_ns = _time.perf_counter_ns() - t0
                if pending is not None:
                    yield self._finish_download(ctx, emit, pending)
                pending = (begin_ns, db, handle)
            if pending is not None:
                yield self._finish_download(ctx, emit, pending)

        from . import pipeline
        parts = self.children[0].execute(ctx)
        if not pipeline.parallel_active(ctx):
            return [run(p) for p in parts]
        from ..utils.prefetch import prefetch_iter
        depth = pipeline.prefetch_depth(ctx.conf)
        return [prefetch_iter(run_overlapped(p), depth=depth, ctx=ctx,
                              node=name)
                for p in parts]

    @staticmethod
    def _finish_download(ctx, emit, pending):
        import time as _time
        begin_ns, db, handle = pending
        t0 = _time.perf_counter_ns()
        with trace_range("DeviceToHost.download"):
            hb = HostBatch(db.to_arrow_finish(handle))
        # emit() computes opTime as now - t0; shift t0 back by the begin
        # span so both download phases (and nothing else) are counted.
        return emit(ctx, hb, t0 - begin_ns)


class DeviceSourceExec(TpuExec):
    """Source over device-resident cached partitions (df.cache() analog):
    batches were pinned in HBM by ``TpuSession.materialize`` and replay with
    zero upload cost."""

    def __init__(self, partitions, schema: T.Schema):
        self.children = []
        self.partitions = partitions  # List[List[ColumnarBatch]]
        self._schema = schema

    @property
    def schema(self):
        return self._schema

    def describe(self):
        return f"DeviceSource parts={len(self.partitions)}"

    def execute(self, ctx):
        return [iter(list(p)) for p in self.partitions]


# ---------------------------------------------------------------------------
# Narrow operators
# ---------------------------------------------------------------------------


class TpuProjectExec(TpuExec):
    def __init__(self, child: PhysicalPlan, exprs: List[Expression]):
        self.children = [child]
        self.exprs = exprs

    @property
    def schema(self):
        return T.Schema([T.StructField(e.name, e.data_type, e.nullable)
                         for e in self.exprs])

    def describe(self):
        return "TpuProject [" + ", ".join(e.name for e in self.exprs) + "]"

    def execute(self, ctx):
        from ..ops import nondeterministic as ND
        bound = _bind_all(self.exprs, self.children[0].schema)
        out_schema = self.schema
        nondet = any(ND.has_nondeterministic(e) for e in bound)

        if nondet:
            # Partition id and the running row offset enter the kernel as
            # TRACED arguments so one compile serves every partition/batch
            # (the reference's GpuSparkPartitionID reads TaskContext; here
            # the exec threads the same facts through eval_context).
            def build_nd():
                def project_nd(batch: ColumnarBatch, row_base, pid
                               ) -> ColumnarBatch:
                    # Positional expressions (monotonic id, rand stream)
                    # number LOGICAL rows — scattered lazy rows must
                    # compact first to match the oracle's numbering.
                    batch = KR.physical(batch)
                    with ND.eval_context(pid, row_base):
                        cols = tuple(e.eval_device(batch) for e in bound)
                    return batch.with_columns(cols, out_schema)
                return project_nd
            project_nd = cached_kernel(
                "project_nd", kernel_key(bound, out_schema), build_nd)

            def run_nd(part, pidx):
                row_base = jnp.asarray(0, jnp.int64)
                pid = jnp.asarray(pidx, jnp.int32)
                for db in part:
                    yield project_nd(db, row_base, pid)
                    row_base = row_base + db.n_rows.astype(jnp.int64)
            return [run_nd(p, i)
                    for i, p in enumerate(self.children[0].execute(ctx))]

        def build():
            def project(batch: ColumnarBatch) -> ColumnarBatch:
                cols = tuple(e.eval_device(batch) for e in bound)
                return batch.with_columns(cols, out_schema)
            return project
        project = cached_kernel("project", kernel_key(bound, out_schema),
                                build)

        name = self.node_name()

        def run(part):
            import time as _time
            t0 = _time.perf_counter_ns()
            for db in part:
                out = project(db)
                t0 = _tick(ctx, name, t0)
                yield out
        return [run(p) for p in self.children[0].execute(ctx)]


class TpuFilterExec(TpuExec):
    def __init__(self, child: PhysicalPlan, condition: Expression):
        self.children = [child]
        self.condition = condition

    @property
    def schema(self):
        return self.children[0].schema

    def describe(self):
        return f"TpuFilter ({self.condition})"

    def execute(self, ctx):
        bound = self.condition.bind(self.children[0].schema)

        def build():
            def filt(batch: ColumnarBatch) -> ColumnarBatch:
                mask_col = bound.eval_device(batch)
                keep = mask_col.data & mask_col.validity
                return KR.compact(batch, keep)
            return filt
        filt = cached_kernel("filter", kernel_key(bound), build)

        name = self.node_name()

        def run(part):
            import time as _time
            t0 = _time.perf_counter_ns()
            for db in part:
                out = filt(db)
                t0 = _tick(ctx, name, t0)
                yield out
        return [run(p) for p in self.children[0].execute(ctx)]


class TpuRangeExec(TpuExec):
    def __init__(self, start: int, end: int, step: int,
                 batch_rows: int = 1 << 20):
        self.start, self.end, self.step = start, end, step
        self.batch_rows = batch_rows

    @property
    def schema(self):
        return T.Schema([T.StructField("id", T.LONG, False)])

    def execute(self, ctx):
        name = self.node_name()

        def gen():
            n_total = max(0, -(-(self.end - self.start) // self.step))
            done = 0
            while done < n_total:
                n = min(self.batch_rows, n_total - done)
                cap = bucket_capacity(n)
                start = self.start + done * self.step
                data = start + jnp.arange(cap, dtype=jnp.int64) * self.step
                valid = jnp.arange(cap, dtype=jnp.int32) < n
                col = DeviceColumn(data=jnp.where(valid, data, 0),
                                   validity=valid, dtype=T.LONG)
                ctx.metric(name, "numOutputRows", n)
                ctx.metric(name, "numOutputBatches", 1)
                yield ColumnarBatch((col,), jnp.asarray(n, jnp.int32),
                                    self.schema)
                done += n
        return [gen()]


class TpuUnionExec(TpuExec):
    def __init__(self, children: List[PhysicalPlan], schema: T.Schema):
        self.children = list(children)
        self._schema = schema

    @property
    def schema(self):
        return self._schema

    def execute(self, ctx):
        name = self.node_name()
        parts = []
        for c in self.children:
            def relabel(p):
                for db in p:
                    ctx.metric(name, "numOutputBatches", 1)
                    yield ColumnarBatch(db.columns, db.n_rows,
                                        self._schema, live=db.live)
            parts.extend(relabel(p) for p in c.execute(ctx))
        return parts


def _limit_stream(batches, n: int, in_fusion: bool):
    """Truncate a device-batch stream to a running limit of n rows.

    Traced (fusion) path: the running remainder is a device scalar so no
    host sync interrupts the fused program — loses the early-exit, which
    fusion (a materialized, finite batch list) does not need. Streaming
    path: one host sync per batch with early-exit, the reference's
    per-batch row slicing (limit.scala:115)."""
    if in_fusion:
        remaining = jnp.asarray(n, jnp.int32)
        for db in batches:
            db = KR.physical(db)  # truncation is positional
            take = jnp.minimum(db.n_rows, remaining)
            yield _truncate(db, take)
            remaining = remaining - take
        return
    remaining = n
    for db in batches:
        if remaining <= 0:
            return
        rows = int(db.n_rows)
        take = min(rows, remaining)
        remaining -= take
        if take == rows:
            yield db
        else:
            yield _truncate(KR.physical_jit(db), take)


class TpuLocalLimitExec(TpuExec):
    """Per-partition limit (GpuLocalLimitExec, limit.scala:115): each
    partition truncates independently, preserving the partitioning — the
    cheap first phase of a collect-limit."""

    def __init__(self, child: PhysicalPlan, n: int):
        self.children = [child]
        self.n = n

    @property
    def schema(self):
        return self.children[0].schema

    def execute(self, ctx):
        name = self.node_name()
        return [_counted_stream(ctx, name,
                                _limit_stream(p, self.n, ctx.in_fusion))
                for p in self.children[0].execute(ctx)]


class TpuLimitExec(TpuExec):
    """Global limit: one running limit over the flattened partition stream
    (GpuGlobalLimitExec, limit.scala:120)."""

    def __init__(self, child: PhysicalPlan, n: int):
        self.children = [child]
        self.n = n

    @property
    def schema(self):
        return self.children[0].schema

    def execute(self, ctx):
        def flat():
            for part in self.children[0].execute(ctx):
                yield from part
        return [_counted_stream(ctx, self.node_name(),
                                _limit_stream(flat(), self.n,
                                              ctx.in_fusion))]


@jax.jit
def _truncate(db: ColumnarBatch, take) -> ColumnarBatch:
    take = jnp.asarray(take, jnp.int32)
    live = jnp.arange(db.capacity, dtype=jnp.int32) < take
    cols = []
    for c in db.columns:
        v = c.validity & live
        if c.is_string:
            cols.append(c.replace_rows(v))
        else:
            cols.append(DeviceColumn(
                jnp.where(v, c.data, jnp.zeros((), c.data.dtype)), v, c.dtype))
    return ColumnarBatch(tuple(cols), take, db.schema)


class TpuExpandExec(TpuExec):
    def __init__(self, child: PhysicalPlan, projections, schema: T.Schema):
        self.children = [child]
        self.projections = projections
        self._schema = schema

    @property
    def schema(self):
        return self._schema

    def execute(self, ctx):
        child_schema = self.children[0].schema
        bound = [
            _bind_all(proj, child_schema) for proj in self.projections]
        out_schema = self._schema

        def make_projection(proj):
            def project(batch):
                cols = []
                for e, f in zip(proj, out_schema):
                    c = e.eval_device(batch)
                    if c.dtype.name != f.data_type.name:
                        from ..ops.cast import _jnp_cast
                        data = _jnp_cast(c.data, c.dtype, f.data_type)
                        c = make_column(data, c.validity, f.data_type)
                    cols.append(c)
                return batch.with_columns(tuple(cols), out_schema)
            return project

        fns = [cached_kernel("expand", kernel_key(p, out_schema),
                             lambda p=p: make_projection(p))
               for p in bound]
        name = self.node_name()

        def run(part):
            import time as _time
            t0 = _time.perf_counter_ns()
            for db in part:
                for fn in fns:
                    out = fn(db)
                    t0 = _tick(ctx, name, t0)
                    yield out
        return [run(p) for p in self.children[0].execute(ctx)]


class TpuGenerateExec(TpuExec):
    """Explode / posexplode over the padded-ragged array layout
    (GpuGenerateExec.scala:101 does the same with a cudf gather).

    Traced kernels: flatten the ``[rows, max_len]`` element matrix to
    ``rows * max_len`` output lanes, repeat parent rows by a single 1D
    gather (``row = lane // max_len``), then compact on the element-liveness
    mask. When ``capacity * max_len`` exceeds :attr:`TILE_LANES`, the batch
    explodes in row tiles so no single invocation allocates more than
    ``TILE_LANES`` lanes per output column (the reference chunks similarly
    through its iterator); each tile yields its own output batch."""

    #: Lane bound per explode invocation: a coalesced 1M-row batch with a
    #: 64-wide array bucket would otherwise allocate 64M lanes per output
    #: column in one program — an HBM blow-up at exactly the batch sizes
    #: coalescing produces.
    TILE_LANES = 1 << 22

    def __init__(self, child: PhysicalPlan, generator: Expression,
                 outer: bool, pos: bool, schema: T.Schema):
        self.children = [child]
        self.generator = generator
        self.outer = outer
        self.pos = pos
        self._schema = schema

    @property
    def schema(self):
        return self._schema

    def describe(self):
        return f"TpuGenerate [{self.generator}]"

    def execute(self, ctx):
        bound = self.generator.bind(self.children[0].schema)
        out_schema = self._schema
        outer, pos = self.outer, self.pos
        elem_dt = out_schema[len(out_schema) - 1].data_type

        eval_arr = cached_kernel(
            "generate_arr", kernel_key(bound, out_schema),
            lambda: lambda db: bound.eval_device(db))

        def make_explode(tile_rows: int):
            """Explode rows [offset, offset+tile_rows) of the evaluated
            array column. Row indices past the live count read clamped
            garbage that the keep mask then drops."""
            def explode(db: ColumnarBatch, arr,
                        offset: jnp.ndarray) -> ColumnarBatch:
                w = arr.data.shape[1]
                rows_sel = offset + jnp.arange(tile_rows, dtype=jnp.int32)
                data = arr.data[rows_sel]
                elem_validity = arr.elem_validity[rows_sel]
                lengths = arr.lengths[rows_sel]
                validity = arr.validity[rows_sel]
                out_cap = tile_rows * w
                lane = jnp.arange(out_cap, dtype=jnp.int32)
                local_r = lane // w
                flat_r = offset + local_r
                flat_j = lane % w
                live = flat_r < db.n_rows
                lens = lengths[local_r]
                valid = validity[local_r]
                keep_elem = live & (flat_j < lens)
                if outer:
                    extra = live & (flat_j == 0) & (~valid | (lens == 0))
                    keep = keep_elem | extra
                else:
                    keep = keep_elem
                parent = KR.gather_batch(
                    db, flat_r, jnp.asarray(out_cap, jnp.int32),
                    index_valid=None)
                cols = list(parent.columns)
                if pos:
                    cols.append(make_column(flat_j, keep_elem, T.INT))
                cols.append(make_column(
                    data.reshape(-1),
                    elem_validity.reshape(-1) & keep_elem, elem_dt))
                expanded = ColumnarBatch(
                    tuple(cols), jnp.asarray(out_cap, jnp.int32), out_schema)
                return KR.compact(expanded, keep)
            return explode

        def run(part):
            import time as _time
            from ..data.column import bucket_capacity
            t0 = _time.perf_counter_ns()
            for db in part:
                # Explode liveness is positional (flat_r < n_rows).
                db = KR.physical(db) if ctx.in_fusion \
                    else KR.physical_jit(db)
                arr = eval_arr(db)
                cap, w = arr.data.shape
                tile_rows = cap if cap * w <= self.TILE_LANES else \
                    bucket_capacity(max(self.TILE_LANES // w, 128))
                fn = cached_kernel(
                    "generate",
                    kernel_key(bound, outer, pos, out_schema, tile_rows),
                    lambda: make_explode(tile_rows))
                # When tiling, bound the loop by live rows, not bucket
                # capacity — a filtered batch in a large bucket would
                # otherwise run dead kernels past n_rows. The device sync
                # is paid only on the (large-batch) tiled path.
                live_rows = cap if tile_rows == cap else \
                    max(int(jax.device_get(db.n_rows)), 1)
                for off in range(0, live_rows, tile_rows):
                    out = fn(db, arr, jnp.asarray(off, jnp.int32))
                    t0 = _tick(ctx, self.node_name(), t0)
                    yield out
        return [run(p) for p in self.children[0].execute(ctx)]


# ---------------------------------------------------------------------------
# Sort
# ---------------------------------------------------------------------------


class TpuSortExec(TpuExec):
    """Global sort. Small inputs coalesce to a single batch and sort once
    (RequireSingleBatch, reference GpuSortExec.scala:54); inputs above the
    external threshold run the bounded-memory external merge sort
    (exec/external_sort.py): per-batch sorted runs through the spill
    catalog, pairwise chunked merges, a stream of globally ordered chunks
    out — the device never holds more than a few chunks."""

    children_coalesce_goals = ["target"]

    def __init__(self, child: PhysicalPlan, orders: List[SortOrder]):
        self.children = [child]
        self.orders = orders

    @property
    def schema(self):
        return self.children[0].schema

    def execute(self, ctx):
        schema = self.schema
        key_exprs = [o.child.bind(schema) for o in self.orders]
        asc = [o.ascending for o in self.orders]
        nf = [o.effective_nulls_first for o in self.orders]
        pallas = ctx.pallas  # per-session Pallas gate, read at dispatch

        def build():
            def do_sort(b):
                keys = [e.eval_device(b) for e in key_exprs]
                return KR.sort_batch_by_columns(b, keys, asc, nf,
                                                pallas=pallas)
            return do_sort
        do_sort = cached_kernel(
            "sort", kernel_key(key_exprs, asc, nf, pallas.token()), build)

        def gen():
            from ..config import SORT_EXTERNAL_THRESHOLD
            from ..memory import retry as R
            name = self.node_name()
            catalog = getattr(ctx, "catalog", None)
            if ctx.in_fusion or catalog is None:
                merged = _accumulate_spillable(self.children[0], ctx, "sort")
                if merged is None:
                    return
                ctx.metric(name, "numOutputBatches", 1)
                with ctx.registry.timer(name, "sortTime"):
                    out = do_sort(merged)
                yield out
                return
            from ..memory import spill as SP_MOD
            threshold = ctx.conf.get(SORT_EXTERNAL_THRESHOLD) or \
                catalog.device_budget // 4
            ids, total, sorter = [], 0, None
            try:
                for part in self.children[0].execute(ctx):
                    for db in part:
                        ids.append(catalog.register_batch(
                            db, SP_MOD.ACTIVE_BATCHING_PRIORITY,
                            owner=getattr(ctx, "qos", None)))
                        total += db.device_size_bytes
                if not ids:
                    return
                if total <= threshold:
                    def assemble_and_sort(id_list):
                        merged = _pinned_concat(catalog, id_list)
                        with ctx.registry.timer(name, "sortTime"):
                            return do_sort(merged)
                    # Single-batch sorts cannot split (two sorted halves
                    # are not a global sort): spill + retry only.
                    out = R.with_retry(ctx, f"{name}.sort", ids,
                                       assemble_and_sort, node=name)[0]
                    ctx.metric(name, "numOutputBatches", 1)
                    yield out
                    return
                from .external_sort import ExternalSorter
                sorter = ExternalSorter(self.orders, schema, catalog,
                                        key_exprs, ctx=ctx)
                for b in ids:
                    # The reload itself can OOM (the batch may have
                    # spilled), so acquisition runs under retry too; the
                    # sort step then splits in half by rows when it cannot
                    # fit — each half becomes its own sorted run, which
                    # the merge tree absorbs naturally.
                    batch = R.with_retry(ctx, f"{name}.runGeneration", b,
                                         catalog.acquire_batch,
                                         node=name)[0]
                    R.with_retry(ctx, f"{name}.runGeneration", batch,
                                 sorter.add_batch,
                                 split=R.halve_by_rows, node=name)
                    catalog.free(b)
                ids = []
                n_out = 0
                for chunk in sorter.sorted_chunks():
                    n_out += 1
                    yield chunk
                ctx.metric(self.node_name(), "numOutputBatches", n_out)
                ctx.metric(self.node_name(), "externalSort", 1)
            finally:
                for b in ids:
                    catalog.free(b)
                if sorter is not None:
                    # An abandoned chunk stream (limit above an external
                    # sort) must not leak the un-merged runs' registrations.
                    sorter.release()
        return [gen()]


class TpuTopKExec(TpuExec):
    """Limit-into-sort: ORDER BY ... LIMIT n keeps a running top-k batch
    instead of globally sorting the input (the reference gets the same
    shape from cudf partial sorts under GpuSortExec.scala:50 +
    GpuCollectLimitExec; planned by the CpuLimitExec rule when n is
    under spark.rapids.tpu.sort.topKThreshold).

    Streaming: each incoming batch reduces to its top-k (single-key
    keys ride one int64 lane through ``lax.top_k``, O(n log k)); the
    running best merges pairwise, so the device never holds more than
    (batch + 2k) rows for the sort tail."""

    children_coalesce_goals = ["target"]

    def __init__(self, child: PhysicalPlan, orders: List[SortOrder],
                 n: int):
        self.children = [child]
        self.orders = orders
        self.n = n

    @property
    def schema(self):
        return self.children[0].schema

    def describe(self):
        return f"TpuTopK n={self.n}"

    def execute(self, ctx):
        schema = self.schema
        key_exprs = [o.child.bind(schema) for o in self.orders]
        asc = [o.ascending for o in self.orders]
        nf = [o.effective_nulls_first for o in self.orders]

        def build(fast):
            def do_topk(b):
                keys = [e.eval_device(b) for e in key_exprs]
                top, ok = KR.topk_batch_by_columns(
                    b, keys, asc, nf, self.n, allow_data_fallback=fast)
                # literal True would jit-box into a device array; None
                # survives jit so the static-exact case stays sync-free
                return top, (None if ok is True else ok)
            return do_topk

        def gen():
            # The float64-lane fast path is optimistic for float/int64
            # keys (exactness is data-dependent); its deferred fail flag
            # rides the same session dense-mode retry as the dense
            # joins/aggs — no per-batch host syncs, fusion-safe.
            site = ctx.next_join_site()
            fast = not ctx.eager_overflow \
                and ctx.dense_modes.get(site, 0) == 0
            do_topk = cached_kernel(
                "topk", kernel_key(key_exprs, asc, nf) + (self.n, fast),
                lambda: build(fast))

            def reduce_one(b):
                top, ok = do_topk(b)
                if ok is not None:
                    fail = ~ok
                    ctx.overflow_flags.append(fail)
                    ctx.dense_fails.append((site, fail))
                return top

            best = None
            for part in self.children[0].execute(ctx):
                for db in part:
                    top = reduce_one(db)
                    best = top if best is None else \
                        reduce_one(_coalesce_device([best, top]))
            if best is not None:
                ctx.metric(self.node_name(), "numOutputBatches", 1)
                yield best
        return [gen()]


def _accumulate_spillable(child: PhysicalPlan, ctx, label: str,
                          node: Optional[str] = None
                          ) -> Optional[ColumnarBatch]:
    """Collect ALL of a child's batches into one, registering each with the
    spill catalog while accumulating so memory pressure can push earlier
    batches to host/disk (the reference makes join build sides and sort
    inputs spillable the same way, RapidsBufferStore.scala:40). Under
    whole-stage fusion tracing the catalog is bypassed (tracers cannot move
    hosts).

    The assembly (unspill + concat) runs under the OOM-retry combinator
    without a split: the consumer's contract is ONE batch, so exhausted
    retries surface SplitAndRetryOOM naming the site."""
    from ..memory import retry as R
    from ..memory import spill as SP
    catalog = getattr(ctx, "catalog", None)
    use_catalog = catalog is not None and not ctx.in_fusion
    if not use_catalog:
        batches = [b for part in child.execute(ctx) for b in part]
        return _coalesce_device(batches) if batches else None
    ids = []
    try:
        for part in child.execute(ctx):
            for db in part:
                ids.append(catalog.register_batch(
                    db, SP.ACTIVE_BATCHING_PRIORITY,
                    owner=getattr(ctx, "qos", None)))
        if not ids:
            return None

        with trace_range(f"{label}.assemble"):
            out = R.with_retry(ctx, f"{node or label}.assemble", ids,
                               lambda id_list: _pinned_concat(catalog,
                                                              id_list),
                               node=node)[0]
    finally:
        # Free even when the child raises mid-stream (e.g. a transient
        # remote-compile failure that session._run_with_retries retries) —
        # leaked registrations would shrink the spill budget for the whole
        # session.
        for b in ids:
            catalog.free(b)
    return out


def _pinned_concat(catalog, ids):
    """Acquire + concat a set of catalog buffers with on-deck pinning
    (pin first so acquiring one buffer can't evict another of the same
    set); unpins in finally so a failed — and retried — attempt leaves
    them spillable for the retry's spill-down. The one assembly routine
    behind every with_retry'd concat site (coalesce flush, join build,
    single-batch sort)."""
    for b in ids:
        catalog.pin(b)
    try:
        return _coalesce_device([catalog.acquire_batch(b) for b in ids])
    finally:
        for b in ids:
            catalog.unpin(b)


_concat_jit = jax.jit(KC.concat_batches, static_argnums=(1,))


def _coalesce_device(batches: List[ColumnarBatch]) -> ColumnarBatch:
    """Concat device batches, sizing output by the (static) sum of input
    capacities. Live rows <= capacity, so the bound is safe, and unlike the
    true row total it needs no device->host sync — which keeps concat off the
    tunnel's ~100ms round-trip path and traceable under whole-stage fusion.
    The output is at most one capacity bucket larger than a row-exact concat.
    """
    if len(batches) == 1:
        # Stays lazy: mask-native consumers (agg, join, sort, filter)
        # read row_mask(); positional consumers materialize themselves.
        return batches[0]
    total = sum(b.capacity for b in batches)
    cap = bucket_capacity(max(total, 1))
    return _concat_jit(batches, cap)


# ---------------------------------------------------------------------------
# Hash aggregate
# ---------------------------------------------------------------------------


class TpuHashAggregateExec(TpuExec):
    """Partial-per-batch aggregation with a device merge loop, mirroring the
    reference's concat + re-aggregate accumulation (aggregate.scala:330-400),
    then a final buffer-evaluation projection."""

    children_coalesce_goals = ["target"]

    def __init__(self, child: PhysicalPlan, groupings: List[Expression],
                 aggregates: List[AGG.AggregateExpression]):
        self.children = [child]
        self.groupings = groupings
        self.aggregates = aggregates

    @property
    def schema(self):
        fields = [T.StructField(g.name, g.data_type, g.nullable)
                  for g in self.groupings]
        fields += [T.StructField(a.name, a.func.data_type, a.func.nullable)
                   for a in self.aggregates]
        return T.Schema(fields)

    def describe(self):
        return ("TpuHashAggregate [" + ", ".join(g.name for g in self.groupings)
                + "] [" + ", ".join(a.name for a in self.aggregates) + "]")

    # Buffer schema: groupings then per-agg buffers.
    def _buffer_schema(self) -> T.Schema:
        fields = [T.StructField(g.name, g.data_type, g.nullable)
                  for g in self.groupings]
        for i, a in enumerate(self.aggregates):
            for spec in a.func.buffers():
                fields.append(T.StructField(f"_buf{i}_{spec.suffix}",
                                            spec.dtype, True))
        return T.Schema(fields)

    def execute(self, ctx):
        child_schema = self.children[0].schema
        groupings = _bind_all(self.groupings, child_schema)
        aggs = [AGG.AggregateExpression(a.func.bind(child_schema), a.name)
                for a in self.aggregates]
        buf_schema = self._buffer_schema()
        n_keys = len(groupings)
        agg_key = kernel_key(groupings, [(a.name, a.func) for a in aggs],
                             buf_schema)

        def build_partial(dense_mode, pallas):
            def partial(batch: ColumnarBatch):
                return _aggregate_batch(batch, groupings, aggs, buf_schema,
                                        n_keys, update_mode=True,
                                        dense_mode=dense_mode,
                                        pallas=pallas)
            return partial

        def build_merge(dense_mode, pallas):
            def merge(batch: ColumnarBatch):
                key_refs = [BoundReference(i, f.data_type, f.nullable)
                            for i, f in enumerate(buf_schema)][:n_keys]
                return _aggregate_batch(batch, key_refs, aggs, buf_schema,
                                        n_keys, update_mode=False,
                                        dense_mode=dense_mode,
                                        pallas=pallas)
            return merge

        def gen():
            # Dense/hash grouping is optimistic like the dense joins:
            # a deferred fail flag (key span or collision sidecar
            # overflow) escalates this site to the sort path via the
            # session's dense-mode retry.
            site = ctx.next_join_site()
            dense_mode = 1 if ctx.eager_overflow else \
                min(ctx.dense_modes.get(site, 0), 1)
            # Per-session Pallas gate: read at dispatch, folded into the
            # process-wide kernel-cache key so sessions with different
            # gates never share a traced kernel.
            pallas = ctx.pallas
            pkey = agg_key + (dense_mode, pallas.token())
            partial_k = cached_kernel(
                "agg_partial", pkey,
                lambda: build_partial(dense_mode, pallas))
            merge_k = cached_kernel(
                "agg_merge", pkey,
                lambda: build_merge(dense_mode, pallas))

            def run_k(k, b):
                out, fail = k(b)
                if fail is not None:
                    ctx.overflow_flags.append(fail)
                    ctx.dense_fails.append((site, fail))
                return out

            def partial(b):
                return run_k(partial_k, b)

            def merge(b):
                return run_k(merge_k, b)
            # Merge-sort-style reduction stack: merge two partials only when
            # the newer one has caught up in capacity. With capacity-sum
            # concat sizing (no row-count syncs), a linear state-accumulator
            # would re-sort the whole accumulated capacity per batch —
            # O(N^2); the tree keeps total merge work O(N log N).
            stack: List[ColumnarBatch] = []

            def push(b: ColumnarBatch):
                stack.append(b)
                while len(stack) >= 2 and \
                        stack[-1].capacity >= stack[-2].capacity:
                    b2, b1 = stack.pop(), stack.pop()
                    stack.append(merge(_coalesce_device([b1, b2])))

            for part in self.children[0].execute(ctx):
                for db in part:
                    push(partial(db))
            state: Optional[ColumnarBatch] = None
            if stack:
                state = stack.pop()
                while stack:
                    state = merge(_coalesce_device([stack.pop(), state]))
            if state is None:
                # No input batches at all — statically known, no sync.
                # Grouped agg of nothing is nothing; global agg is the
                # count-0 row. With >=1 input batch the global-agg kernel
                # itself always emits exactly one group (even for zero live
                # rows), so no row-count sync is ever needed here.
                if self.groupings:
                    return
                ctx.metric(self.node_name(), "numOutputBatches", 1)
                yield self._empty_result()
                return
            ctx.metric(self.node_name(), "numOutputBatches", 1)
            yield self._finalize(state, buf_schema)
        return [gen()]

    def _finalize(self, state: ColumnarBatch, buf_schema: T.Schema
                  ) -> ColumnarBatch:
        final = finalize_agg_kernel(len(self.groupings), self.aggregates,
                                    buf_schema, self.schema)
        return final(state)

    def _empty_result(self) -> ColumnarBatch:
        """Global aggregation of empty input: one row (count=0, rest null)."""
        arrays = []
        for a in self.aggregates:
            if isinstance(a.func, AGG.Count):
                arrays.append(pa.array([0], pa.int64()))
            else:
                arrays.append(pa.nulls(1, T.to_arrow_type(a.func.data_type)))
        rb = pa.RecordBatch.from_arrays(
            arrays, schema=T.schema_to_arrow(self.schema))
        return ColumnarBatch.from_arrow(rb)


def finalize_agg_kernel(n_keys: int, aggregates: List[AGG.AggregateExpression],
                        buf_schema: T.Schema, out_schema: T.Schema):
    """Cached buffer-evaluation projection (agg result-expression pass);
    shared by the streaming exec and the SPMD mesh path."""
    def build():
        def final(b: ColumnarBatch) -> ColumnarBatch:
            cols = list(b.columns[:n_keys])
            bi = n_keys
            for a in aggregates:
                specs = a.func.buffers()
                refs = [BoundReference(bi + j, s.dtype, True)
                        for j, s in enumerate(specs)]
                bi += len(specs)
                result_expr = a.func.evaluate(refs)
                cols.append(result_expr.eval_device(b))
            return ColumnarBatch(tuple(cols), b.n_rows, out_schema,
                                 live=b.live)
        return final
    return cached_kernel(
        "agg_final",
        kernel_key(n_keys, [(a.name, a.func) for a in aggregates],
                   buf_schema, out_schema),
        build)


def _aggregate_batch(batch: ColumnarBatch, key_exprs: List[Expression],
                     aggs: List[AGG.AggregateExpression],
                     buf_schema: T.Schema, n_keys: int,
                     update_mode: bool, dense_mode: int = 1, pallas=None):
    """One grouping pass. update_mode: inputs are raw rows (evaluate agg
    children, apply update ops). merge mode: inputs are buffer columns.

    Grouped path: KG.grouped_aggregate — TWO sorts carrying all inputs +
    segmented prefix scans; no per-column gathers, no scatters (both are
    extra full memory passes on TPU). Global path: plain fused masked
    reductions, always emitting exactly one group so emptiness never needs
    a host sync."""
    capacity = batch.capacity
    live = batch.row_mask()
    keys = [e.eval_device(batch) for e in key_exprs]
    inputs = []  # (values, validity, op, spec)
    bi = n_keys
    for a in aggs:
        specs = a.func.buffers()
        for j, spec in enumerate(specs):
            if update_mode:
                if a.func.child is None:  # count(*)
                    values = jnp.ones(capacity, dtype=jnp.int64)
                    validity = jnp.ones(capacity, dtype=jnp.bool_)
                else:
                    c = a.func.child.eval_device(batch)
                    from ..ops.cast import _jnp_cast
                    values = _jnp_cast(c.data, c.dtype, spec.dtype) \
                        if c.dtype.name != spec.dtype.name else c.data
                    validity = c.validity
                op = spec.update_op
            else:
                c = batch.columns[bi + j]
                values = c.data
                validity = c.validity
                op = spec.merge_op
            inputs.append((values, validity, op, spec))
        bi += len(specs)
    triples = [(v, val, op) for v, val, op, _ in inputs]
    fail = None
    if keys:
        key_cols, results, n_groups, group_live, fail = \
            KG.grouped_aggregate(keys, live, triples,
                                 dense_mode=dense_mode, pallas=pallas)
        if fail is False:
            fail = None  # statically exact path: nothing to observe
    else:
        key_cols, results, n_groups, group_live = KG.global_aggregate(
            capacity, live, triples)
    out_cols = list(key_cols)
    for (_, _, op, spec), (result, counts) in zip(inputs, results):
        if spec.from_count:
            data = counts if op == "count" else result
            validity_out = group_live
        else:
            data = result
            validity_out = (counts > 0) & group_live
        out_cols.append(make_column(data.astype(spec.dtype.np_dtype),
                                    validity_out, spec.dtype))
    return ColumnarBatch(tuple(out_cols), n_groups, buf_schema), fail


# ---------------------------------------------------------------------------
# Joins
# ---------------------------------------------------------------------------


def hash_join_kernel(jt: str, lkeys: List[Expression],
                     rkeys: List[Expression], out_schema: T.Schema,
                     pallas=None):
    """Process-cached local equi-join kernel ``(probe, build, out_cap)``.

    Shared by the streaming exec and the SPMD mesh path (exec/mesh.py):
    both are, per shard, exactly this local join. Semantics per join type:
    semi/anti return a compacted probe; left/full expand unmatched probe
    rows with nulls; full also returns the build-side hit mask for the
    caller's unmatched-build pass. ``pallas`` is the caller's per-session
    gate snapshot (ExecContext.pallas): it selects the fused VMEM
    build+probe for the dense modes and the ragged string gather for the
    output assembly, and rides the cache key so differently-gated
    sessions never share a kernel."""
    from ..ops.kernels.pallas import resolve as _pallas_resolve
    pallas = _pallas_resolve(pallas)

    def kernel_impl(probe, build, out_cap, dense=0):
        pk = [e.eval_device(probe) for e in lkeys]
        bk = [e.eval_device(build) for e in rkeys]
        if dense == 1:
            # Direct-address fast path (unique int build keys; semi/anti
            # tolerate duplicates): returns a lazy probe-capacity batch +
            # a dense-fail flag the retry machinery consumes; no overflow
            # possible.
            return KJ.dense_join(jt, probe, build, pk[0], bk[0],
                                 out_schema, pallas=pallas)
        if dense == 2:
            # Swapped mode (inner only): the table builds over the
            # UNIQUE-keyed probe side — the dim.join(fact) shape.
            return KJ.dense_join_swapped(probe, build, pk[0], bk[0],
                                         out_schema, pallas=pallas)
        hits = None
        if jt != "full" and len(bk) == 1 \
                and KJ.binsearch_joinable(bk[0]) \
                and KJ.binsearch_joinable(pk[0]):
            # Fact-to-dimension shape: build-side-only sort + probe binary
            # search (full joins need the build hit mask, which this path
            # can't produce without sorting the probe side).
            lo, counts, build_at_rank = KJ.join_match_binsearch(
                bk[0], pk[0], build.row_mask(), probe.row_mask())
        else:
            lo, counts, build_at_rank, hits = KJ.join_match(
                bk, pk, build.row_mask(), probe.row_mask(),
                need_build_hits=(jt == "full"))
        live_p = probe.row_mask()
        counts = jnp.where(live_p, counts, 0)
        matched = counts > 0
        if jt in ("left_semi", "left_anti"):
            keep = matched if jt == "left_semi" else (~matched & live_p)
            return KR.compact(probe, keep), hits
        exp_counts = counts
        if jt in ("left", "full"):
            exp_counts = KJ.left_outer_counts(counts, live_p)
        p_idx, b_idx, n_out, total = KJ.expand_matches_binsearch(
            lo, exp_counts, build_at_rank, out_cap)
        real = matched[p_idx]
        out_live = jnp.arange(out_cap, dtype=jnp.int32) < n_out
        pcols = KR.gather_columns(probe.columns, p_idx, out_live,
                                  pallas=pallas)
        bcols = KR.gather_columns(build.columns, b_idx, out_live & real,
                                  pallas=pallas)
        out = ColumnarBatch(tuple(pcols) + tuple(bcols), n_out, out_schema)
        return (out, hits), total

    return cached_kernel(
        "hash_join",
        kernel_key(jt, lkeys, rkeys, out_schema, pallas.token()),
        lambda: kernel_impl, static_argnums=(2, 3))


def join_post_filter(condition: Optional[Expression], out_schema: T.Schema):
    """Cached residual-condition filter applied to join output rows."""
    if condition is None:
        return None
    cond = condition.bind(out_schema)

    def build_post():
        def post_filter(b):
            mask = cond.eval_device(b)
            return KR.compact(b, mask.data & mask.validity)
        return post_filter
    return cached_kernel("join_post_filter", kernel_key(cond), build_post)


def unmatched_build_kernel(left_schema: T.Schema, out_schema: T.Schema):
    """Cached full-outer tail: unmatched build rows null-extended on the
    left (shared by the streaming exec and the mesh path)."""
    def builder():
        def kernel(build, hits):
            live_b = build.row_mask()
            keep = live_b & ~hits if hits is not None else live_b
            compacted = KR.compact(build, keep)
            null_left = [_null_col(f.data_type, build.capacity)
                         for f in left_schema]
            cols = tuple(null_left) + compacted.columns
            return ColumnarBatch(cols, compacted.n_rows, out_schema,
                                 live=compacted.live)
        return kernel
    return cached_kernel("join_unmatched_build",
                         kernel_key(left_schema, out_schema), builder)


class TpuShuffledHashJoinExec(TpuExec):
    """Equi-join: build side fully concatenated on device, probe side
    streamed (GpuShuffledHashJoinExec/GpuHashJoin analog). Also covers the
    broadcast-join shape in single-process mode."""

    def __init__(self, left: PhysicalPlan, right: PhysicalPlan,
                 join_type: str, left_keys: List[Expression],
                 right_keys: List[Expression], schema: T.Schema,
                 condition: Optional[Expression] = None,
                 growth: float = 1.0):
        self.children = [left, right]
        self.join_type = join_type
        self.left_keys = left_keys
        self.right_keys = right_keys
        self._schema = schema
        self.condition = condition
        self.growth = growth

    @property
    def schema(self):
        return self._schema

    def describe(self):
        return f"TpuShuffledHashJoin {self.join_type}"

    def execute(self, ctx):
        left, right = self.children
        if self.join_type == "right":
            # Mirror: right outer = left outer with sides swapped.
            inner = TpuShuffledHashJoinExec(
                right, left, "left", self.right_keys, self.left_keys,
                _swap_schema(self._schema, len(left.schema)),
                self.condition, self.growth)
            parts = inner.execute(ctx)
            n_right = len(right.schema)
            out_schema = self._schema

            def reorder(p):
                for db in p:
                    cols = db.columns[n_right:] + db.columns[:n_right]
                    yield ColumnarBatch(cols, db.n_rows, out_schema,
                                        live=db.live)
            return [reorder(p) for p in parts]

        lkeys = _bind_all(self.left_keys, left.schema)
        rkeys = _bind_all(self.right_keys, right.schema)
        jt = self.join_type
        out_schema = self._schema
        kernel = hash_join_kernel(jt, lkeys, rkeys, out_schema,
                                  pallas=ctx.pallas)
        post_filter = join_post_filter(self.condition, out_schema)

        dense_eligible = KJ.dense_joinable(jt, _bind_all(
            self.right_keys, right.schema)) and self.condition is None

        def join_batch(probe, build, site, learn=True):
            # Optimistic output sizing: allocate from the learned exact
            # capacity for this join site when a previous run of this plan
            # observed it (ctx.join_caps, filled by the session's
            # overflow-learning retry), else from the probe capacity. The
            # real match count stays a deferred device-side observation the
            # session reads ONCE per query — no per-batch host syncs.
            # ``site`` is taken by the CALLER, outside the retry wrapper:
            # a retried/split attempt must not consume extra ordinals or
            # every later join's learned capacity would key-shift.
            # ``learn=False`` on split halves: a half's match total would
            # teach the session an UNDER-estimate of the full batch and
            # the cached capacity would overflow on every later run.
            mode = 1 + ctx.dense_modes.get(site, 0)
            if mode == 2 and jt != "inner":
                mode = 3  # swapped mode only exists for inner joins
            if dense_eligible and not ctx.eager_overflow and mode <= 2:
                # Direct-address path: optimistic like the capacity
                # guess — a dense-fail flag (out-of-range keys; duplicate
                # build keys for inner/left) escalates this site's mode
                # (1 = build-side table, 2 = swapped probe-side table,
                # then the general kernel).
                out, fail = kernel(probe, build, 0, mode)
                ctx.overflow_flags.append(fail)
                ctx.dense_fails.append((site, fail))
                if not ctx.in_fusion and out.capacity >= 4 * 128:
                    # Streaming mode: shrink sparse lazy outputs to their
                    # live bucket — downstream capacity-proportional ops
                    # (the group-by argsort, sorts) would otherwise pay
                    # the full probe/build capacity for a few live rows.
                    # One row-count sync per probe batch, same cadence as
                    # the reference's per-batch sizing.
                    total = int(jax.device_get(out.n_rows))
                    cap = bucket_capacity(max(total, 128))
                    if cap * 4 <= out.capacity:
                        from ..data.batch import _shrink_batch
                        out = _shrink_batch(KR.physical_jit(out), cap)
                return out, None
            if jt in ("left_semi", "left_anti"):
                out, hits = kernel(probe, build, probe.capacity)
                return ColumnarBatch(out.columns, out.n_rows, out_schema,
                                     live=out.live), hits
            out_cap = ctx.join_caps.get(site) or bucket_capacity(
                max(int(probe.capacity * self.growth * ctx.join_growth), 128))
            (out, hits), total = kernel(probe, build, out_cap)
            if ctx.eager_overflow:
                # Exact resize with a per-batch sync: for side-effecting
                # plans (writes) and the guaranteed last retry rung.
                t = int(total)
                if t > out_cap:
                    (out, hits), _ = kernel(probe, build, bucket_capacity(t))
            else:
                ctx.overflow_flags.append(total > out_cap)
                if learn:
                    ctx.join_totals.append((site, total))
            if post_filter is not None:
                out = post_filter(out)
            return out, hits

        name = self.node_name()

        def gen():
            import time as _time
            from ..memory import retry as R
            with ctx.registry.timer(name, "buildTime"):
                build = _accumulate_spillable(right, ctx, "join.build",
                                              node=name)
            hit_acc = None
            t0 = _time.perf_counter_ns()
            for part in left.execute(ctx):
                for probe in part:
                    if build is None:
                        if jt in ("left", "full"):
                            yield _null_extend_right(probe, out_schema,
                                                     len(right.schema))
                        elif jt == "left_anti":
                            yield ColumnarBatch(probe.columns, probe.n_rows,
                                                out_schema, live=probe.live)
                        continue
                    # Probe batches split in half by rows when retries
                    # alone cannot fit the gather's output allocation —
                    # each half joins against the same build table and
                    # streams out as its own batch.
                    site = ctx.next_join_site()
                    tracker = R.SplitTracker(R.halve_by_rows)
                    results = R.with_retry(
                        ctx, f"{name}.probe", probe,
                        lambda p: join_batch(p, build, site,
                                             learn=not
                                             tracker.split_happened),
                        split=tracker, node=name)
                    t0 = _tick(ctx, name, t0)
                    for out, hits in results:
                        if hit_acc is None:
                            hit_acc = hits
                        elif hits is not None:
                            hit_acc = hit_acc | hits
                        yield out
            if jt == "full" and build is not None:
                ctx.metric(name, "numOutputBatches", 1)
                yield self._unmatched_build(build, hit_acc)
        return [gen()]

    def _unmatched_build(self, build: ColumnarBatch, hit_acc) -> ColumnarBatch:
        kernel = unmatched_build_kernel(self.children[0].schema, self._schema)
        return kernel(build, hit_acc)


def _null_col(dtype: T.DataType, capacity: int) -> DeviceColumn:
    from ..data.column import null_column
    return null_column(dtype, capacity)


def _null_extend_right(probe: ColumnarBatch, schema: T.Schema,
                       n_right: int) -> ColumnarBatch:
    null_cols = tuple(_null_col(schema[len(probe.columns) + i].data_type,
                                probe.capacity)
                      for i in range(n_right))
    return ColumnarBatch(probe.columns + null_cols, probe.n_rows, schema,
                         live=probe.live)


def _swap_schema(schema: T.Schema, n_first: int) -> T.Schema:
    fields = list(schema)
    return T.Schema(fields[n_first:] + fields[:n_first])
