"""External merge sort — bounded-memory global sort over the spill catalog.

The reference bounds sort memory with ``RequireSingleBatch`` + the spill
store (GpuSortExec.scala:50-54 with RapidsBufferStore behind it): the
single concatenated input can spill, but the sort itself still needs the
whole dataset on the device. This module removes that ceiling the TPU way:

1. **Run generation** — each input batch is sorted on-device (one
   ``lax.sort`` program) and registered with the spill catalog, so runs
   migrate device->host->disk under pressure. A run is a FIFO of sorted
   chunks; its head key rides along host-side (downloaded once per chunk,
   a few scalars).
2. **Binary merge tree** — runs merge pairwise. A merge step holds at most
   THREE chunks on device (carry + one chunk + the emitted prefix): the
   two-chunk union is sorted together with a 1-row SENTINEL carrying the
   other run's next head; rows sorting strictly before the sentinel are
   exactly the elements ``<= every future element of both runs`` and are
   emitted as a final sorted chunk (re-bucketed to its live size), the
   rest carry over. No data-dependent shapes: the live split point is the
   batch's traced ``n_rows``.
3. The final run is a stream of globally ordered chunks — downstream
   consumers (limits, windows, downloads) never see a single oversized
   batch.

Host coordination (which run to pull, re-bucketing) happens between
device programs, exactly like the reference's iterator-driven execution.
"""

from __future__ import annotations

import math
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..data.batch import ColumnarBatch, _shrink_batch
from ..data.column import bucket_capacity
from ..memory import spill as SP
from ..ops.kernels import concat as KC
from ..ops.kernels import rowops as KR
from ..utils.kernel_cache import cached_kernel, kernel_key
from ..utils.tracing import trace_range


def _head_key_values(batch: ColumnarBatch, key_exprs) -> tuple:
    """Download row 0's key values as a host tuple (None for null)."""
    out = []
    for e in key_exprs:
        c = e.eval_device(batch)
        if c.is_string:
            # Compare dictionary strings by their decoded bytes.
            from ..ops.strings_util import char_matrix
            m = char_matrix(c)
            row = np.asarray(jax.device_get(m[:1]))[0]
            valid = bool(jax.device_get(c.validity[0]))
            out.append(bytes(int(x) for x in row if x >= 0)
                       if valid else None)
        else:
            valid = bool(jax.device_get(c.validity[0]))
            out.append(jax.device_get(c.data[0]).item() if valid else None)
    return tuple(out)


def _key_less(a: tuple, b: tuple, orders) -> bool:
    """Host comparator for head tuples, honoring asc / nulls_first."""
    for av, bv, o in zip(a, b, orders):
        nf = o.effective_nulls_first
        if av is None or bv is None:
            if av is None and bv is None:
                continue
            return nf if av is None else not nf
        if av == bv or (isinstance(av, float) and isinstance(bv, float)
                        and math.isnan(av) and math.isnan(bv)):
            continue
        if isinstance(av, float) and math.isnan(av):
            return not o.ascending  # NaN sorts greatest
        if isinstance(bv, float) and math.isnan(bv):
            return o.ascending
        return (av < bv) == o.ascending
    return False


class _Run:
    """FIFO of sorted spill-registered chunks with host-side head keys."""

    def __init__(self):
        self.chunks: List[Tuple[int, tuple, int]] = []  # (id, head, cap)

    def head(self) -> Optional[tuple]:
        return self.chunks[0][1] if self.chunks else None

    def max_cap(self) -> int:
        return max((c for _, _, c in self.chunks), default=128)

    def pop(self, catalog) -> ColumnarBatch:
        """Acquire the next chunk and release its catalog entry — the
        returned batch keeps the device arrays alive by reference, and a
        consumed chunk must not stay registered (it would sit unspillable
        in the device store for the rest of the merge)."""
        bid, _, _ = self.chunks.pop(0)
        batch = catalog.acquire_batch(bid)
        catalog.free(bid)
        return batch

    def peek_head_row(self, catalog, slice_k) -> ColumnarBatch:
        """1-row batch holding the next chunk's first row (the merge
        sentinel). Acquires without consuming."""
        import jax.numpy as _jnp
        bid, _, _ = self.chunks[0]
        src = catalog.acquire_batch(bid)
        return slice_k(src, _jnp.asarray(0, _jnp.int32),
                       _jnp.asarray(1, _jnp.int32), 128)


def _merge_step_kernel(key_exprs, asc, nf, schema, with_sentinel: bool):
    """(carry, chunk[, sentinel_row]) -> (merged_sorted, n_emit).

    The union is sorted once; with a sentinel, n_emit = count of data rows
    sorting strictly before the sentinel row (stable sort with a source
    tag ordering the sentinel after equal keys), else every live row."""
    def build():
        def step(a: ColumnarBatch, b: ColumnarBatch,
                 sent: Optional[ColumnarBatch] = None):
            parts = [a, b] + ([sent] if sent is not None else [])
            total = sum(p.capacity for p in parts)
            merged = KC.concat_batches(tuple(parts), total)
            keys = [e.eval_device(merged) for e in key_exprs]
            iota = jnp.arange(total, dtype=jnp.int32)
            if sent is not None:
                # concat_batches compacts live rows to a prefix, so the
                # sentinel's rows start at the live-row count, not at the
                # capacity offset.
                n_data = a.n_rows + b.n_rows
                is_sent = (iota >= n_data) & (iota < n_data + sent.n_rows)
            operands = []
            for k, kasc, knf in zip(keys, asc, nf):
                if k.is_string:
                    operands.extend(KR.string_sort_keys(k, kasc, knf))
                else:
                    key, null_bucket = KR.orderable_key(k, kasc, knf)
                    operands.append(null_bucket)
                    operands.append(key)
            live = merged.row_mask()
            # dead rows sink to the end
            operands.insert(0, jnp.where(live, 0, 1).astype(jnp.int8))
            if sent is not None:
                # sentinel sorts AFTER equal keys
                operands.append(is_sent.astype(jnp.int8))
            sorted_ops = jax.lax.sort(tuple(operands) + (iota,),
                                      num_keys=len(operands),
                                      is_stable=True)
            perm = sorted_ops[-1]
            out = KR.gather_batch(merged, perm,
                                  jnp.asarray(total, jnp.int32),
                                  index_valid=None)
            if sent is not None:
                sent_sorted = is_sent[perm]
                sent_pos = jnp.argmax(sent_sorted).astype(jnp.int32)
                n_emit = jnp.minimum(sent_pos, n_data)
                # drop the sentinel row from the ordered stream: rows after
                # it shift left by one
                shift_idx = iota + (iota >= sent_pos).astype(jnp.int32)
                out = KR.gather_batch(
                    out, jnp.clip(shift_idx, 0, total - 1),
                    jnp.asarray(total, jnp.int32), index_valid=None)
                out = ColumnarBatch(out.columns, n_data, schema)
            else:
                n_data = a.n_rows + b.n_rows
                out = ColumnarBatch(out.columns, n_data, schema)
                n_emit = n_data
            return out, n_emit
        return step
    return cached_kernel(
        "extsort_merge", kernel_key(key_exprs, tuple(asc), tuple(nf),
                                    schema, with_sentinel), build)


def _slice_kernel(schema):
    """(batch, start, count, out_cap static) -> rows [start, start+count)."""
    def build():
        def do_slice(batch: ColumnarBatch, start, count, out_cap: int):
            idx = start + jnp.arange(out_cap, dtype=jnp.int32)
            live = jnp.arange(out_cap, dtype=jnp.int32) < count
            out = KR.gather_batch(batch, jnp.clip(idx, 0, batch.capacity - 1),
                                  jnp.asarray(out_cap, jnp.int32),
                                  index_valid=None)
            return ColumnarBatch(out.columns, count.astype(jnp.int32),
                                 schema)
        return do_slice
    return cached_kernel("extsort_slice", kernel_key(schema), build,
                         static_argnums=(3,))


class _TrackingCatalog:
    """Thin catalog proxy recording which chunk ids this sorter still owns,
    so an abandoned chunk stream (e.g. a limit closing the generator early)
    can free every outstanding registration instead of leaking them into
    the session-lifetime spill budget."""

    def __init__(self, catalog, owner=None):
        self._c = catalog
        #: QoS identity stamped on every chunk registration (ISSUE 11):
        #: the spill victim order drains this query's own chunks first.
        self._owner = owner
        self.live = set()

    def register_batch(self, batch, priority):
        bid = self._c.register_batch(batch, priority, owner=self._owner)
        self.live.add(bid)
        return bid

    def free(self, bid):
        self.live.discard(bid)
        self._c.free(bid)

    def acquire_batch(self, bid):
        return self._c.acquire_batch(bid)

    def release_all(self):
        for bid in list(self.live):
            self._c.free(bid)
        self.live.clear()


class ExternalSorter:
    """Streaming global sort: feed batches, then iterate sorted chunks."""

    def __init__(self, orders, schema: T.Schema, catalog,
                 key_exprs=None, ctx=None):
        self.orders = orders
        self.schema = schema
        self.catalog = _TrackingCatalog(catalog,
                                        owner=getattr(ctx, "qos", None))
        self.key_exprs = key_exprs or [o.child.bind(schema) for o in orders]
        self.asc = [o.ascending for o in orders]
        self.nf = [o.effective_nulls_first for o in orders]
        self._runs: List[_Run] = []
        #: ExecContext for the OOM-retry combinator around merge steps
        #: (spill + retry only — a merge step cannot split); None keeps
        #: the bare-unit-test construction unchanged. Assigned BEFORE
        #: _make_sort_one, which reads ctx.pallas.
        self._ctx = ctx
        self._sort_one = self._make_sort_one()

    def _retry_step(self, tag: str, fn):
        """One merge-tree device step under the retry combinator."""
        if self._ctx is None:
            return fn(None)
        from ..memory import retry as R
        return R.with_retry(self._ctx, f"ExternalSorter.{tag}", None, fn,
                            node="ExternalSorter")[0]

    def release(self):
        """Free every chunk this sorter still has registered (safe to call
        after normal completion — it is then a no-op)."""
        self._runs = []
        self.catalog.release_all()

    def _make_sort_one(self):
        key_exprs, asc, nf = self.key_exprs, self.asc, self.nf
        # Run generation is the external sort's device hot loop; the
        # per-session Pallas gate (ctx.pallas) routes a single packable
        # key through the VMEM bitonic kernel, and rides the cache key.
        from ..ops.kernels.pallas import resolve as _pallas_resolve
        pallas = _pallas_resolve(getattr(self._ctx, "pallas", None))

        def build():
            def do_sort(b):
                keys = [e.eval_device(b) for e in key_exprs]
                return KR.sort_batch_by_columns(b, keys, asc, nf,
                                                pallas=pallas)
            return do_sort
        return cached_kernel("sort", kernel_key(key_exprs, tuple(asc),
                                                tuple(nf), pallas.token()),
                             build)

    def add_batch(self, batch: ColumnarBatch):
        sdb = self._sort_one(batch)
        run = _Run()
        run.chunks.append((self.catalog.register_batch(
            sdb, SP.ACTIVE_BATCHING_PRIORITY),
            _head_key_values(sdb, self.key_exprs), sdb.capacity))
        self._runs.append(run)

    # -- merging ------------------------------------------------------------
    def _merge_two(self, r1: _Run, r2: _Run) -> _Run:
        """Streaming two-run merge with bounded device residency.

        Per step the device holds the carry (typically <= one chunk), one
        pulled chunk, the merged union, and a 1-row sentinel. Emission is
        bounded by the MINIMUM of BOTH runs' next heads — the carry can
        hold elements larger than the pulled run's own next chunk, so the
        other run's head alone is not a valid bound. Emitted prefixes
        re-chunk to the base chunk capacity so chunk sizes stay constant
        up the whole merge tree."""
        out = _Run()
        merge_s = _merge_step_kernel(self.key_exprs, self.asc, self.nf,
                                     self.schema, True)
        merge_ns = _merge_step_kernel(self.key_exprs, self.asc, self.nf,
                                      self.schema, False)
        slice_k = _slice_kernel(self.schema)
        catalog = self.catalog
        base_cap = max(r1.max_cap(), r2.max_cap())

        def emit(batch, start, n_emit_host):
            off = start
            end = start + n_emit_host
            while off < end:
                take = min(base_cap, end - off)
                cap = base_cap if take == base_cap else \
                    bucket_capacity(max(take, 128))
                chunk = slice_k(batch, jnp.asarray(off, jnp.int32),
                                jnp.asarray(take, jnp.int32), cap)
                out.chunks.append((catalog.register_batch(
                    chunk, SP.ACTIVE_BATCHING_PRIORITY),
                    _head_key_values(chunk, self.key_exprs), cap))
                off += take

        def smaller_head_run():
            h1, h2 = r1.head(), r2.head()
            if h1 is None:
                return r2
            if h2 is None:
                return r1
            return r1 if _key_less(h1, h2, self.orders) else r2

        carry = None
        while r1.chunks or r2.chunks or carry is not None:
            if carry is None:
                if not (r1.chunks or r2.chunks):
                    break
                carry = smaller_head_run().pop(catalog)
                continue
            if not (r1.chunks or r2.chunks):
                emit(carry, 0, int(jax.device_get(carry.n_rows)))
                carry = None
                continue
            src = smaller_head_run()
            chunk = src.pop(catalog)
            # Emission bound: the smaller of the two runs' NEXT heads.
            bound_run = smaller_head_run() \
                if r1.chunks and r2.chunks else \
                (r1 if r1.chunks else (r2 if r2.chunks else None))
            if bound_run is None or not bound_run.chunks:
                merged, n_emit = self._retry_step(
                    "mergeStep", lambda _: merge_ns(carry, chunk))
                n = int(jax.device_get(n_emit))
                emit(merged, 0, n)
                carry = None
                continue
            sent = bound_run.peek_head_row(catalog, slice_k)
            merged, n_emit = self._retry_step(
                "mergeStep", lambda _: merge_s(carry, chunk, sent))
            n = int(jax.device_get(n_emit))
            total_live = int(jax.device_get(merged.n_rows))
            emit(merged, 0, n)
            rest = total_live - n
            if rest > 0:
                cap = bucket_capacity(max(rest, 128))
                carry = slice_k(merged, jnp.asarray(n, jnp.int32),
                                jnp.asarray(rest, jnp.int32), cap)
            else:
                carry = None
        return out

    def sorted_chunks(self):
        """Merge all runs; yield the final run's chunks in order (each
        acquired from the catalog, freed after the caller consumes it)."""
        with trace_range("extsort.merge"):
            runs = self._runs
            while len(runs) > 1:
                nxt = []
                for i in range(0, len(runs) - 1, 2):
                    nxt.append(self._merge_two(runs[i], runs[i + 1]))
                if len(runs) % 2:
                    nxt.append(runs[-1])
                runs = nxt
            self._runs = runs
        if not runs:
            return
        for bid, _, _ in runs[0].chunks:
            batch = self.catalog.acquire_batch(bid)
            self.catalog.free(bid)
            yield batch
        runs[0].chunks = []
