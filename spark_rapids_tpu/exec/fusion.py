"""Whole-stage fusion — the TPU answer to Spark's ``WholeStageCodegenExec``.

The reference leans on Spark's whole-stage codegen for CPU operators and on
libcudf's pre-compiled kernels for GPU ones (SURVEY.md §2.10): a query still
dispatches one kernel per operator per batch. Under XLA the natural unit is
larger. Every device operator in this engine is already a pure traced
function over batch pytrees, so an entire device subtree
(source -> filter -> project -> join -> aggregate) can be traced ONCE into a
single jitted program. XLA then fuses across operator boundaries, and —
decisive on a high-latency host<->TPU link — the host dispatches ONE program
and performs ONE device->host transfer per query instead of one per
operator-batch.

Contract:

* :func:`fusable` — True when the plan root is ``DeviceToHostExec`` over a
  columnar subtree. Non-whitelisted *columnar* subtrees (window, broadcast
  exchange, shuffle, scans...) become fusion BOUNDARIES: they execute
  eagerly outside the trace and feed the fused program as traced inputs, so
  fusion degrades gracefully instead of turning off.
* The fused callable is cached per structural plan signature (expression
  trees, schemas, static params — the :mod:`..utils.kernel_cache`
  discipline); ``jax.jit`` re-specializes per input capacity bucket through
  the pytree avals, so re-running a query never recompiles. With
  ``spark.rapids.tpu.polymorphic.enabled`` (default) boundary inputs are
  padded onto coarse capacity TIERS first (compile/ladder.py ``tier()``),
  so ONE compiled executable serves every ladder rung inside a tier —
  O(kernels) compiles instead of O(rungs x kernels); the per-rung path
  (conf off) stays as the bit-identity oracle.
* Fusion regions split by compile-cost budget: when a region's compile
  blew ``spark.rapids.tpu.fusion.compileBudgetSecs`` (recorded per plan
  hash, persisted in the compile manifest), later builds demote the most
  expensive join(s) to boundaries (compile/budget.py).
* Results return through ONE ``jax.device_get`` of ``(n_rows, overflow
  flags, guess-shrunk batch)``. If the result had more rows than the guess
  bucket, the full batch (still device-resident) downloads in a second
  round trip — the price only large collects pay.
* Join overflow flags ride the same transfer; ``TpuSession.execute``
  re-runs the query with learned exact join capacities when one trips.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from .. import types as T
from ..compile import budget as _budget
from ..compile import persist as _persist
from ..compile import warmup as _warmup
from ..compile.executables import FusedProgram
from ..utils import lockdep as _lockdep
from ..compile.ladder import get_ladder
from ..data.batch import ColumnarBatch, _grow_batch, _shrink_batch
from ..data.column import bucket_capacity
from ..plan.physical import ExecContext
from ..utils.kernel_cache import plan_signature as _plan_sig
from .coalesce import TpuCoalesceBatchesExec
from .execs import (DeviceToHostExec, TpuExec, TpuExpandExec, TpuFilterExec,
                    TpuHashAggregateExec, TpuLimitExec, TpuLocalLimitExec,
                    TpuProjectExec, TpuTopKExec,
                    TpuUnionExec, _coalesce_device)


class _NotFusable(Exception):
    pass


class FusedInputExec(TpuExec):
    """Leaf of a fused plan: replays pre-materialized device batches from
    ``ctx.fused_inputs`` — the fused program's traced arguments."""

    def __init__(self, index: int, schema: T.Schema):
        self.children = []
        self.index = index
        self._schema = schema

    @property
    def schema(self):
        return self._schema

    def describe(self):
        return f"FusedInput #{self.index}"

    def execute(self, ctx):
        return [iter(list(p)) for p in ctx.fused_inputs[self.index]]


#: Execs whose execute() path is fully traceable (no host syncs, no host
#: data): these are inlined into the fused program. Everything else columnar
#: becomes a boundary input.
_INLINE = (TpuProjectExec, TpuFilterExec, TpuHashAggregateExec,
           TpuCoalesceBatchesExec, TpuExpandExec,
           TpuUnionExec, TpuLimitExec, TpuLocalLimitExec,
           FusedInputExec)

#: TpuTopKExec is deliberately NOT inlined: as a boundary it keeps its
#: child subtree on the streaming path, where dense-join outputs shrink
#: to their live buckets between operators — for join-chain plans that
#: beats one fused program running every stage at full lazy capacity
#: (measured round 5: q10 fused-at-full-capacity 1073ms vs 174ms).
assert TpuTopKExec not in _INLINE


def _inline_types():
    """Joins inline too when the conf allows: one fused program per query
    instead of per-join boundary dispatches + intermediate
    materialization. Default ON for locally-compiled backends; the conf
    exists because a fused multi-join program accumulates enough lax.sort
    stages to strain SLOW remote compile helpers (tpu tunnel) — boundaries
    amortize their per-join kernels across queries there."""
    from .execs import TpuShuffledHashJoinExec
    return _INLINE + (TpuShuffledHashJoinExec,)


def _is_boundary(p, inline=None) -> bool:
    if isinstance(p, inline or _INLINE):
        return False
    return bool(getattr(p, "columnar", False))


def _split(plan, boundaries: List, inline=None,
           demote: frozenset = frozenset()) -> TpuExec:
    """Rebuild the device subtree with every boundary subtree replaced by a
    :class:`FusedInputExec` leaf; boundary nodes append to ``boundaries`` in
    deterministic traversal order (the fused program's argument order).
    Nodes in ``demote`` (by identity — the compile-cost budget's split
    decision, :func:`_budget_split`) become boundaries even though they
    are inlineable."""
    inline = inline or _INLINE
    if id(plan) in demote or _is_boundary(plan, inline):
        boundaries.append(plan)
        return FusedInputExec(len(boundaries) - 1, plan.schema)
    if not isinstance(plan, inline):
        raise _NotFusable(type(plan).__name__)
    kids = [_split(c, boundaries, inline, demote) for c in plan.children]
    return plan.with_children(kids) if kids else plan


def _conf_inline(conf):
    return _inline_types() if conf is not None \
        and conf.fusion_inline_joins else _INLINE


def fusable(root, conf=None) -> bool:
    if not isinstance(root, DeviceToHostExec):
        return False
    child = root.children[0]
    if not getattr(child, "columnar", False):
        return False
    try:
        _split(child, [], _conf_inline(conf))
    except _NotFusable:
        return False
    return True


_FUSED_CACHE = {}


def clear_fused_cache() -> None:
    _FUSED_CACHE.clear()


def _budget_split(device_plan, conf, base_hash: str):
    """Apply the compile-cost budget's split decision for this plan
    (compile/budget.py): returns ``(inline types, demoted node ids,
    level)``. Level 1 demotes the single largest inlined join (by inline
    subtree size — the region's most expensive boundary candidate, and
    the cut that best halves the region); level 2 demotes every join."""
    inline = _conf_inline(conf)
    level = _budget.split_level(base_hash)
    if level <= 0 or inline is _INLINE:
        return inline, frozenset(), level
    if level >= _budget.MAX_SPLIT_LEVEL:
        return _INLINE, frozenset(), level
    from .execs import TpuShuffledHashJoinExec
    joins: List[list] = []  # [inline subtree size, pre-order slot, id]

    def walk(p) -> int:
        if _is_boundary(p, inline):
            return 0
        slot = None
        if isinstance(p, TpuShuffledHashJoinExec):
            slot = len(joins)
            joins.append([0, slot, id(p)])
        size = 1 + sum(walk(c) for c in p.children)
        if slot is not None:
            joins[slot][0] = size
        return size
    walk(device_plan)
    if not joins:
        return inline, frozenset(), level
    joins.sort(key=lambda j: (-j[0], j[1]))
    return inline, frozenset({joins[0][2]}), level


def _has_inline_join(plan) -> bool:
    """True when the (already split) fused region still inlines a join —
    i.e. the compile-cost budget has a boundary left to demote."""
    from .execs import TpuShuffledHashJoinExec
    if isinstance(plan, TpuShuffledHashJoinExec):
        return True
    return any(_has_inline_join(c) for c in plan.children)


#: Distinct (input aval signature, tier) pairs the tier padding has
#: dispatched ``_grow_batch`` for. Each pair is one TINY XLA pad kernel
#: compiled on first visit of a rung — the O(rungs x boundary-schemas)
#: residue of tier padding (the fused programs themselves are O(tiers)).
#: Tracked so the compile-count gate (tests/test_compile_gate.py) can
#: ratchet it; these kernels bypass utils/kernel_cache, so the
#: ``kernels_compiled`` counter alone would never see them growing.
_PAD_PROGRAMS: set = set()


def pad_program_count() -> int:
    return len(_PAD_PROGRAMS)


def _pad_inputs_to_tiers(inputs):
    """Pad every boundary batch up to its polymorphic capacity tier
    (compile/ladder.py tier()) so the fused program's input avals — and
    therefore its compiled executable — are shared by every bucket rung
    inside a tier. Row counts stay dynamic scalar operands; padded rows
    are dead by the engine invariant, so results are bit-identical to
    the per-rung path. Returns ``(padded inputs, rows of padding)``."""
    from ..compile.executables import aval_signature
    ladder = get_ladder()
    pad_rows = 0

    def rec(x):
        nonlocal pad_rows
        if isinstance(x, tuple):
            return tuple(rec(v) for v in x)
        if not x.columns:
            return x
        tier = ladder.tier(x.capacity)
        if tier <= x.capacity:
            return x
        pad_rows += tier - x.capacity
        _PAD_PROGRAMS.add((aval_signature((x,)), tier))
        return _grow_batch(x, tier)
    return rec(inputs), pad_rows


def _build_fused(fused_plan, conf, join_growth: float, guess_rows: int,
                 join_caps=None, dense_modes=None):
    caps = dict(join_caps or {})
    nd = dict(dense_modes or {})

    def run(inputs):
        ictx = ExecContext(conf, catalog=None)
        ictx.join_growth = join_growth
        ictx.join_caps = dict(caps)
        ictx.dense_modes = dict(nd)
        ictx.fused_inputs = inputs
        ictx.in_fusion = True
        outs = []
        for part in fused_plan.execute(ictx):
            outs.extend(part)
        flags = (jnp.stack(ictx.overflow_flags) if ictx.overflow_flags
                 else jnp.zeros((0,), jnp.bool_))
        # Inlined joins' observed match totals ride the head transfer as a
        # static-keyed dict so the session's capacity learning still works
        # (without it every overflow repeats the growth-escalation ladder,
        # and each rung is a fresh whole-program compile).
        totals = {site: t for site, t in ictx.join_totals}
        # OR per-site: one agg site reports a fail per batch + merge pass,
        # and a single True must survive to teach the dense-mode retry
        dfails: dict = {}
        for site, f in ictx.dense_fails:
            dfails[site] = f if site not in dfails else (dfails[site] | f)
        if not outs:
            # Statically empty (no batches at all) — no device work needed.
            return (None, flags, totals, dfails, None), None
        from ..ops.kernels import rowops as KR
        batch = KR.physical(_coalesce_device(outs))
        guess_cap = min(batch.capacity, bucket_capacity(guess_rows))
        shrunk = _shrink_batch(batch, guess_cap) \
            if guess_cap < batch.capacity else batch
        # The head tuple is the single downloaded transfer; the full batch
        # stays device-resident for the (rare) guess-miss second pass.
        return (batch.n_rows, flags, totals, dfails, shrunk), batch
    return jax.jit(run)


def fused_collect(root: DeviceToHostExec, ctx: ExecContext
                  ) -> Tuple[Optional[pa.Table], bool]:
    """Run a fusable plan as one compiled program.

    Returns ``(table, overflowed)``; ``table`` is None when a join's
    deferred overflow check tripped and the caller must retry with the
    learned exact join capacities (``ctx.join_caps``)."""
    device_plan = root.children[0]
    # Compile-cost budget (compile/budget.py): a plan whose fused region
    # historically blew the budget builds SPLIT — the most expensive
    # join(s) demoted to boundaries — trading one giant compile for
    # smaller cacheable ones. The base hash is the pre-split signature,
    # so history accumulates across split levels; it is computed lazily
    # (an extra full-tree signature walk) only when some plan actually
    # escalated or when this dispatch is about to compile.
    budget_secs = ctx.conf.fusion_compile_budget_secs \
        if ctx.conf is not None else 0.0
    base_hash = None
    inline, demote, level = _conf_inline(ctx.conf), frozenset(), 0
    if budget_secs > 0 and _budget.has_levels():
        base_hash = _persist.plan_hash(_plan_sig(device_plan))
        inline, demote, level = _budget_split(device_plan, ctx.conf,
                                              base_hash)
    boundaries: List = []
    fused_plan = _split(device_plan, boundaries, inline, demote)
    guess_rows = ctx.conf.collect_guess_rows
    caps = tuple(sorted(ctx.join_caps.items())) if ctx.join_caps else ()
    # The per-session Pallas gate changes the traced program (fused
    # kernels pick Pallas or jnp paths at trace time), so it must key the
    # fused cache — sessions with different gates get distinct programs.
    sig = (_plan_sig(fused_plan), float(ctx.join_growth), guess_rows, caps,
           tuple(sorted(ctx.dense_modes.items())), ctx.pallas.token())
    fn = _FUSED_CACHE.get(sig)
    if fn is None:
        # FusedProgram: the jitted callable plus its AOT executable table,
        # so background warm-ups (compile/warmup.py) are visible to this
        # dispatch instead of rotting in jit's invisible lower() path.
        fn = FusedProgram(_build_fused(fused_plan, ctx.conf,
                                       ctx.join_growth, guess_rows,
                                       ctx.join_caps, ctx.dense_modes),
                          label=type(device_plan).__name__)
        # Last-wins under concurrent sessions: a GIL-atomic dict store
        # of an equivalent program (same sig); the loser only wasted a
        # build. No lock on the dispatch path.
        _FUSED_CACHE[sig] = fn  # concurrency: ignore
    # Boundary subtrees run eagerly (uploads, windows, shuffles, ...); their
    # materialized batches are the fused program's positional arguments.
    # Independent boundaries materialize CONCURRENTLY on the shared
    # pipeline pool (exec/pipeline.py) — argument order and accumulator
    # merge order stay deterministic; serial when the pipeline is off or
    # a fault injector is active.
    from . import pipeline as _pipeline
    from ..metrics import trace as _trace
    tr = ctx.trace
    with _trace.span(tr, "fusion.boundaries", cat="dispatch",
                     n=len(boundaries)):
        inputs = _pipeline.materialize_boundaries(boundaries, ctx)
    reg = ctx.registry
    # Shape polymorphism (spark.rapids.tpu.polymorphic.enabled): pad the
    # boundary inputs onto coarse capacity tiers so one executable serves
    # every ladder rung in a tier. The unpadded per-rung path (conf off)
    # is the bit-identity oracle.
    polymorphic = ctx.conf is not None and ctx.conf.polymorphic_enabled
    if polymorphic:
        inputs, pad_rows = _pad_inputs_to_tiers(inputs)
        if pad_rows and reg.enabled:
            reg.add("WholeStageFusion", "polymorphicPadRows", pad_rows)
    key_compiled_before = fn.jit_compiled(inputs)
    import time as _time
    t_dispatch = _time.perf_counter_ns()
    # Lockdep blocking marker: the fused dispatch (and on first touch of
    # a signature, its trace+compile) is THE device wait of the engine —
    # holding any engine lock across it serializes every sibling thread
    # behind the device (utils/lockdep.py, docs/concurrency.md).
    with _trace.span(tr, "fusion.dispatch", cat="dispatch") as _sp, \
            _lockdep.blocking("fusion.dispatch"):
        head, full = fn(inputs)
        if _sp is not None and not key_compiled_before \
                and fn.jit_compiled(inputs):
            _sp.annotate(compiled=True)
    if budget_secs > 0 and not key_compiled_before \
            and fn.jit_compiled(inputs):
        # THIS key's dispatch paid trace+compile (per-key, so a
        # concurrent thread compiling another signature on the same
        # program cannot misattribute; and unlike seen() it catches the
        # rare AOT-table fall-through): feed the observed cost back
        # into the budget so chronically expensive regions split. A
        # region with no inlined join left has nothing to demote —
        # report at the ceiling so the level cannot escalate uselessly.
        if base_hash is None:
            base_hash = _persist.plan_hash(_plan_sig(device_plan))
        compile_secs = (_time.perf_counter_ns() - t_dispatch) / 1e9
        _budget.note_compile(base_hash, compile_secs,
                             level if _has_inline_join(fused_plan)
                             else _budget.MAX_SPLIT_LEVEL)
        # Flight-recorder breadcrumb (ISSUE 13): fused compiles are the
        # single largest cold-path cost — a post-mortem dump must show
        # which plan paid one and when (Flare's amortized-compile thesis
        # verified on the warm timeline: these events vanish).
        _trace.record_event("compile.fused", plan=base_hash,
                            secs=round(compile_secs, 3))
    # Between dispatch and download: record this run's capacity rungs in
    # the compile manifest and schedule neighbor-rung AOT warm-ups, so the
    # scheduling work overlaps the device->host transfer below.
    _warmup.note_run(fn, sig, inputs, polymorphic=polymorphic)
    if reg.device_timing:
        # Device-time attribution (spark.rapids.tpu.metrics.deviceTiming):
        # fence the fused dispatch so dispatch->ready is measurable. The
        # ONLY place a fence is ever inserted — off by default, and tests
        # assert the default path stays fence-free.
        jax.block_until_ready(head)
        reg.add("WholeStageFusion", "deviceTime",
                _time.perf_counter_ns() - t_dispatch)
    with _trace.span(tr, "fusion.download", cat="download"):
        head_np = jax.device_get(head)  # ONE round trip
    n_rows_np, flags_np, totals_np, dfails_np, shrunk_np = head_np
    if reg.enabled:
        reg.add("WholeStageFusion", "opTime",
                _time.perf_counter_ns() - t_dispatch)
        reg.add(root.node_name(), "downloadBytes", _host_nbytes(head_np))
    # Surface inlined joins' observed totals and dense-fail flags for the
    # session's learning (capacity ratchet + no_dense re-planning).
    for site, t in totals_np.items():
        ctx.join_totals.append((site, t))
    for site, f in dfails_np.items():
        ctx.dense_fails.append((site, f))
    if flags_np.size and bool(np.any(flags_np)):
        return None, True
    arrow_schema = T.schema_to_arrow(root.schema)
    if n_rows_np is None:
        if reg.enabled:
            reg.add(root.node_name(), "numOutputRows", 0)
        return pa.Table.from_batches([], schema=arrow_schema), False
    n = int(n_rows_np)
    if reg.enabled:
        reg.add(root.node_name(), "numOutputRows", n)
        reg.add(root.node_name(), "numOutputBatches", 1)
    if n <= shrunk_np.capacity:
        arrays = [c.arrow_from_host(c.device_buffers(), n)
                  for c in shrunk_np.columns]
    else:
        # Guess miss: download the full device-resident batch, shrunk to the
        # now-known row bucket (second round trip; bandwidth-bound anyway).
        cap = bucket_capacity(n)
        fb = _shrink_batch(full, cap) if cap < full.capacity else full
        host = jax.device_get([c.device_buffers() for c in fb.columns])
        if reg.enabled:
            reg.add(root.node_name(), "downloadBytes", _host_nbytes(host))
        arrays = [c.arrow_from_host(bufs, n)
                  for c, bufs in zip(fb.columns, host)]
    rb = pa.RecordBatch.from_arrays(arrays, schema=arrow_schema)
    return pa.Table.from_batches([rb]).cast(arrow_schema), False


def _host_nbytes(tree) -> int:
    """Byte footprint of a downloaded host pytree (downloadBytes metric)."""
    return sum(getattr(leaf, "nbytes", 0)
               for leaf in jax.tree_util.tree_leaves(tree))


def any_overflow(ctx: ExecContext) -> bool:
    """One deferred check for the non-fused streaming path: a single stacked
    download instead of the per-join-batch syncs it replaced."""
    if not ctx.overflow_flags:
        return False
    return bool(jax.device_get(jnp.any(jnp.stack(ctx.overflow_flags))))
