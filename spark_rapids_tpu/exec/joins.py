"""Broadcast exchange and non-equi joins — the ``GpuBroadcastExchangeExec`` /
``GpuBroadcastHashJoinExec`` / ``GpuBroadcastNestedLoopJoinExec`` /
``GpuCartesianProductExec`` analogs.

Reference shapes (SURVEY.md §2.3): broadcast exchange collects device batches
into serialized host buffers, ships them via Spark broadcast, and lazily
re-uploads on each executor (GpuBroadcastExchangeExec.scala:242,
SerializeConcatHostBuffersDeserializeBatch:47). Broadcast hash join feeds the
broadcast as the hash-join build side (GpuBroadcastHashJoinExec.scala:91);
nested-loop join covers cross joins and inner joins with arbitrary conditions
(GpuBroadcastNestedLoopJoinExec.scala:135); cartesian product is the
no-broadcast cross (GpuCartesianProductExec.scala:226).

TPU-native: the exchange caches one coalesced device batch plus its Arrow IPC
host serialization (the single-process stand-in for the torrent broadcast),
so many joins can reuse it without re-upload. The nested-loop join evaluates
the condition on all (probe, build) pairs at once — a gather-expanded pair
batch that XLA fuses with the condition expression — instead of looping rows.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp

from .. import types as T
from ..data.batch import ColumnarBatch
from ..data.column import bucket_capacity
from ..ops.expression import Expression
from ..ops.kernels import rowops as KR
from ..plan.physical import PhysicalPlan
from ..utils.kernel_cache import cached_kernel, kernel_key
from ..utils.tracing import trace_range
from .execs import (TpuExec, TpuShuffledHashJoinExec, _bind_all,
                    _coalesce_device, _null_col, _null_extend_right)


class TpuBroadcastExchangeExec(TpuExec):
    """Materialize the child once: coalesced device batch + host IPC bytes.

    The host serialization is the broadcast payload (what the reference ships
    through TorrentBroadcast); the device batch is the lazily re-uploaded
    executor-side copy. Both are cached so N consumers pay once."""

    def __init__(self, child: PhysicalPlan):
        self.children = [child]
        self._device_batch: Optional[ColumnarBatch] = None
        self._buffer_id: Optional[int] = None
        self._payload_bytes = 0
        self._empty = False

    @property
    def schema(self):
        return self.children[0].schema

    def broadcast_batch(self, ctx) -> Optional[ColumnarBatch]:
        if self._empty:
            return None
        catalog = getattr(ctx, "catalog", None)
        if self._buffer_id is not None and catalog is not None:
            # Cached in the spill catalog: may restore from host/disk if
            # memory pressure pushed it out between consumers.
            return catalog.acquire_batch(self._buffer_id)
        if self._device_batch is not None:
            return self._device_batch
        batches = []
        for part in self.children[0].execute(ctx):
            batches.extend(part)
        if not batches:
            self._empty = True
            return None
        with trace_range("broadcast.collect"):
            from ..memory import retry as R
            # The broadcast payload must be ONE batch (every consumer
            # builds from it): spill + retry only, no split.
            name = self.node_name()
            merged = R.with_retry(ctx, f"{name}.collect", batches,
                                  _coalesce_device, node=name)[0]
            # Payload size from the device buffer footprint; the IPC bytes
            # are only materialized if a multi-process transport needs them
            # — in-process, consumers share the device batch directly.
            self._payload_bytes = merged.device_size_bytes
        ctx.metric(self.node_name(), "dataSize", self._payload_bytes)
        if catalog is not None and not ctx.in_fusion:
            from ..memory import spill as SP
            bid = catalog.register_batch(merged, SP.ACTIVE_ON_DECK_PRIORITY,
                                         owner=getattr(ctx, "qos", None))
            self._buffer_id = bid

            def _release():
                # The exchange node dies with the query; free its catalog
                # entry at query end or the session-lifetime catalog leaks
                # one build table per broadcast query.
                catalog.free(bid)
                # Cleanups run on the query thread at query end, never
                # on pipeline workers.
                self._buffer_id = None  # concurrency: ignore
            ctx.add_cleanup(_release)
            return catalog.acquire_batch(bid)
        self._device_batch = merged
        return merged

    @property
    def payload_bytes(self) -> int:
        return self._payload_bytes

    def execute(self, ctx):
        b = self.broadcast_batch(ctx)
        if b is not None:
            ctx.metric(self.node_name(), "numOutputBatches", 1)
        return [iter([b] if b is not None else [])]


class TpuBroadcastHashJoinExec(TpuShuffledHashJoinExec):
    """Equi-join whose build side is a broadcast exchange: identical device
    join core (GpuHashJoin.doJoin analog), build batch shared across
    consumers via the exchange cache."""

    def describe(self):
        return f"TpuBroadcastHashJoin {self.join_type}"


class TpuBroadcastNestedLoopJoinExec(TpuExec):
    """Cross / conditional join without equi keys.

    Evaluates the condition over the full (probe x build-chunk) pair grid:
    pair index vectors gather both sides into one wide batch, the bound
    condition evaluates on it (fused by XLA), and matches compact out.
    Supported types mirror the reference's BNLJ: cross, inner (condition),
    left outer, left_semi, left_anti."""

    def __init__(self, left: PhysicalPlan, right: PhysicalPlan,
                 join_type: str, condition: Optional[Expression],
                 schema: T.Schema):
        self.children = [left, right]
        self.join_type = join_type
        self.condition = condition
        self._schema = schema

    @property
    def schema(self):
        return self._schema

    def describe(self):
        return f"TpuBroadcastNestedLoopJoin {self.join_type}"

    def execute(self, ctx):
        left, right = self.children
        jt = self.join_type
        out_schema = self._schema
        pallas = ctx.pallas  # per-session Pallas gate, read at dispatch
        pair_schema = T.Schema(
            list(left.schema) + [
                T.StructField(f"__b_{f.name}", f.data_type, f.nullable)
                for f in right.schema])
        cond = None
        if self.condition is not None:
            # The condition references output-position columns; rebind it to
            # the pair schema by ordinal identity (left cols then right cols).
            cond = self.condition.bind(
                T.Schema(list(left.schema) + list(right.schema)))

        def kernel_impl(probe: ColumnarBatch, build: ColumnarBatch,
                        out_cap: int):
            pcap, bcap = probe.capacity, build.capacity
            n_pairs = pcap * bcap
            p_idx = jnp.repeat(jnp.arange(pcap, dtype=jnp.int32), bcap)
            b_idx = jnp.tile(jnp.arange(bcap, dtype=jnp.int32), pcap)
            live = probe.row_mask()[p_idx] & build.row_mask()[b_idx]
            pcols = KR.gather_columns(probe.columns, p_idx, live,
                                      pallas=pallas)
            bcols = KR.gather_columns(build.columns, b_idx, live,
                                      pallas=pallas)
            pairs = ColumnarBatch(tuple(pcols) + tuple(bcols),
                                  jnp.asarray(n_pairs, jnp.int32), pair_schema)
            if cond is not None:
                m = cond.eval_device(pairs)
                match = live & m.data & m.validity
            else:
                match = live
            match_count_per_probe = jax.ops.segment_sum(
                match.astype(jnp.int32), p_idx, num_segments=pcap)
            if jt in ("left_semi", "left_anti"):
                keep = match_count_per_probe > 0
                if jt == "left_anti":
                    keep = ~keep & probe.row_mask()
                return KR.compact(probe, keep), None
            # Compact matching pairs to the front of out_cap rows.
            n_match = jnp.sum(match.astype(jnp.int32))
            order = jnp.where(match, jnp.int8(0), jnp.int8(1))
            iota = jnp.arange(n_pairs, dtype=jnp.int32)
            _, perm = jax.lax.sort((order, iota), num_keys=1, is_stable=True)
            sel = perm[:out_cap] if out_cap <= n_pairs else jnp.concatenate(
                [perm, jnp.full(out_cap - n_pairs, n_pairs - 1, jnp.int32)])
            out_live = jnp.arange(out_cap, dtype=jnp.int32) < n_match
            sp_idx = p_idx[sel]
            sb_idx = b_idx[sel]
            ocols = KR.gather_columns(probe.columns, sp_idx, out_live,
                                      pallas=pallas) \
                + KR.gather_columns(build.columns, sb_idx, out_live,
                                    pallas=pallas)
            out = ColumnarBatch(tuple(ocols),
                                jnp.minimum(n_match, out_cap).astype(jnp.int32),
                                out_schema)
            if jt == "left":
                unmatched = (match_count_per_probe == 0) & probe.row_mask()
                extra = KR.compact(probe, unmatched)
                return (out, extra), n_match
            return (out, None), n_match

        kernel = cached_kernel(
            "nested_loop_join",
            kernel_key(jt, cond, pair_schema, out_schema, pallas.token()),
            lambda: kernel_impl, static_argnums=(2,))

        name = self.node_name()

        def counted(db):
            ctx.metric(name, "numOutputBatches", 1)
            return db

        def gen():
            from ..memory import retry as R
            with ctx.registry.timer(name, "buildTime"):
                build_batches = []
                for part in right.execute(ctx):
                    build_batches.extend(part)
                build = _coalesce_device(build_batches) if build_batches \
                    else None
            n_right = len(right.schema)

            for part in left.execute(ctx):
                for probe in part:
                    if build is None:
                        if jt in ("left", "left_anti"):
                            if jt == "left":
                                yield counted(_null_extend_right(
                                    probe, out_schema, n_right))
                            else:
                                yield counted(ColumnarBatch(
                                    probe.columns, probe.n_rows, out_schema,
                                    live=probe.live))
                        continue
                    if jt in ("left_semi", "left_anti"):
                        # The pair grid is the memory hazard (probe cap x
                        # build cap): a probe half quarters it.
                        for out in R.with_retry(
                                ctx, f"{name}.pairGrid", probe,
                                lambda p: kernel(p, build, 0)[0],
                                split=R.halve_by_rows, node=name):
                            yield counted(ColumnarBatch(
                                out.columns, out.n_rows, out_schema,
                                live=out.live))
                        continue
                    # Optimistic sizing + deferred overflow flag — same
                    # no-sync discipline as TpuShuffledHashJoinExec; the
                    # session retries with the learned exact capacity when
                    # the pair count exceeded the allocation.
                    site = ctx.next_join_site()
                    tracker = R.SplitTracker(R.halve_by_rows)

                    def sized_join(p):
                        out_cap = ctx.join_caps.get(site) or \
                            bucket_capacity(
                                max(int(p.capacity * ctx.join_growth), 128))
                        (out, extra), n_match = kernel(p, build, out_cap)
                        if ctx.eager_overflow:
                            t = int(n_match)
                            if t > out_cap:
                                (out, extra), _ = kernel(p, build,
                                                         bucket_capacity(t))
                        else:
                            ctx.overflow_flags.append(n_match > out_cap)
                            if not tracker.split_happened:
                                ctx.join_totals.append((site, n_match))
                        return out, extra
                    for out, extra in R.with_retry(
                            ctx, f"{name}.pairGrid", probe, sized_join,
                            split=tracker, node=name):
                        yield counted(out)
                        if extra is not None:
                            yield counted(_null_extend_right(
                                extra, out_schema, n_right))
        return [gen()]


class TpuCartesianProductExec(TpuBroadcastNestedLoopJoinExec):
    """Cross product of two non-broadcast sides (GpuCartesianProductExec);
    the pairwise device kernel is shared with the nested-loop join."""

    def __init__(self, left: PhysicalPlan, right: PhysicalPlan,
                 schema: T.Schema, condition: Optional[Expression] = None):
        super().__init__(left, right, "cross", condition, schema)

    def describe(self):
        return "TpuCartesianProduct"
