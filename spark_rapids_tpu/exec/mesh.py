"""SPMD mesh query execution — the engine-integrated ICI shuffle.

The reference integrates its GPU-resident shuffle by BEING the shuffle
manager (``RapidsShuffleInternalManager.scala:73-149``): stages stay on the
GPU and exchange over UCX. The TPU-native integration is stronger: a whole
query compiles to ONE SPMD program over a ``jax.sharding.Mesh``. Sources
shard row-wise across chips; narrow operators run on the local shard with
the SAME kernels as single-chip execution; aggregation and join boundaries
insert a hash-partition + ``all_to_all`` exchange over ICI
(:mod:`..shuffle.ici`) so co-keyed rows land on one chip, where the
ordinary local kernel finishes the job. No host round-trips anywhere in
the stage — the property the reference's bounce-buffer/progress-thread
machinery (UCX.scala:84-190) only approximates.

Topology of one mesh query:

    per-chip: filter -> project -> partial agg      (local, XLA-fused)
    exchange: murmur3 partition -> all_to_all       (ICI collective)
    per-chip: merge agg / local join -> finalize    (local)
    collect : one sharded device_get

Plans whose operators are all mesh-capable run here when
``spark.rapids.tpu.mesh.enabled`` is set; anything else falls back to the
single-chip fused/streaming paths.

**Strings over the mesh** ride the dictionary encoding: a source batch is
materialized centrally, so its dictionary is global — the int32 CODES
shard and exchange like any fixed-width lane while the dictionary buffers
REPLICATE across chips (passed as unsharded shard_map inputs). Group-bys
keep the sorted-dict fast path per shard, joins and hash partitioning read
strings through the shared dictionaries, and the collect downloads one
dictionary plus per-shard code lanes. Only dictionary-encoded strings
qualify; expressions that produce FLAT strings (per-row payloads would
need a variable-width exchange) keep the single-chip fallback.

Exchange buckets are capacity-bounded with the deferred-overflow contract:
a ``psum``-reduced flag rides back with the result and the session retries
with a larger bucket growth, exactly like the join ladder.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .. import types as T
from ..data.batch import ColumnarBatch
from ..data.column import DeviceColumn, bucket_capacity
from ..ops.expression import BoundReference, Expression
from ..ops.kernels import rowops as KR
from ..parallel.mesh import (PART_AXIS, MeshDegradedError, is_device_loss,
                             make_mesh, shard_map)
from ..plan.physical import ExecContext
from ..shuffle import ici
from ..shuffle.partitioning import pmod_partition, spark_hash_columns_device
from ..utils.kernel_cache import cached_kernel, kernel_key, \
    plan_signature as _plan_sig
from .coalesce import TpuCoalesceBatchesExec
from .execs import (DeviceSourceExec, DeviceToHostExec, TpuFilterExec,
                    TpuHashAggregateExec, TpuProjectExec,
                    TpuShuffledHashJoinExec, TpuSortExec, _aggregate_batch,
                    _bind_all, _coalesce_device, _swap_schema,
                    finalize_agg_kernel, hash_join_kernel, join_post_filter,
                    unmatched_build_kernel)


class NotMeshCapable(Exception):
    pass


def _require(cond: bool, why: str):
    if not cond:
        raise NotMeshCapable(why)


# ---------------------------------------------------------------------------
# Exchange: hash-partition a local batch and all_to_all it over the mesh
# ---------------------------------------------------------------------------


def _exchange_by_key(batch: ColumnarBatch, key_exprs: List[Expression],
                     n_parts: int, bucket_cap: int, flags: List,
                     pallas=None) -> ColumnarBatch:
    """Repartition a local shard batch by Spark-murmur3 of the keys: rows
    whose keys hash to chip p land on chip p. One scatter into
    [n_parts, bucket_cap] send buffers, one XLA all_to_all, one compaction.
    Appends a bucket-overflow flag (psum-reduced) to ``flags``.
    ``pallas`` is the session's gate snapshot (string keys route through
    the VMEM murmur3 kernel when enabled)."""
    keys = [e.eval_device(batch) for e in key_exprs]
    h = spark_hash_columns_device(keys, pallas=pallas)
    pid = pmod_partition(h, n_parts)
    return _exchange_by_pid(batch, pid, n_parts, bucket_cap, flags)


def _exchange_by_pid(batch: ColumnarBatch, pid, n_parts: int,
                     bucket_cap: int, flags: List) -> ColumnarBatch:
    """Exchange rows to the chip named by per-row ``pid`` (hash exchange
    for aggs/joins, RANGE exchange for the distributed sort)."""
    live = batch.row_mask()
    payload = {}
    for i, c in enumerate(batch.columns):
        # Dict strings move as their int32 code lane; the dictionary
        # buffers are replicated (identical on every chip), so codes stay
        # meaningful after the exchange.
        payload[f"d{i}"] = c.codes if c.is_dict else c.data
        payload[f"v{i}"] = c.validity
    send, send_valid, overflow = ici.build_send_buffers(
        payload, jnp.ones(batch.capacity, jnp.bool_), pid, live,
        n_parts, bucket_cap)
    recv, recv_valid = ici.exchange(send, send_valid)
    flat, flat_valid, n_live = ici.flatten_received(recv, recv_valid)
    flags.append(jax.lax.psum(overflow, PART_AXIS) > 0)
    cols = []
    for i, c in enumerate(batch.columns):
        validity = flat[f"v{i}"] & flat_valid
        lane = jnp.where(validity, flat[f"d{i}"],
                         jnp.zeros((), flat[f"d{i}"].dtype))
        if c.is_dict:
            cols.append(DeviceColumn(
                data=c.data, validity=validity, dtype=c.dtype,
                offsets=c.offsets, max_bytes=c.max_bytes, codes=lane,
                dict_sorted=c.dict_sorted))
        else:
            cols.append(DeviceColumn(data=lane, validity=validity,
                                     dtype=c.dtype))
    return ColumnarBatch(tuple(cols), n_live.astype(jnp.int32), batch.schema)


# ---------------------------------------------------------------------------
# Plan -> per-shard program
# ---------------------------------------------------------------------------


_NARROW = (TpuProjectExec, TpuFilterExec, TpuCoalesceBatchesExec)


def _compile(node, sources: List, n_parts: int, bucket_growth: float,
             conf) -> "callable":
    """Translate a plan subtree into fn(env, flags) -> local ColumnarBatch,
    where env maps source index -> the local shard batch. Raises
    NotMeshCapable for anything without a mesh story yet."""
    if isinstance(node, DeviceSourceExec):
        # String columns qualify only dictionary-encoded (codes shard, the
        # dictionary replicates); the source batches exist at plan time so
        # this is checkable here.
        for p in node.partitions:
            for b in p:
                for c, f in zip(b.columns, node.schema):
                    if f.data_type is T.STRING:
                        _require(c.is_dict,
                                 "flat (non-dictionary) string column in "
                                 "mesh source")
        sources.append(node)
        idx = len(sources) - 1
        return lambda env, flags: env[idx]

    if _is_scan_source(node):
        # File scans (and any host subtree behind an upload) are mesh
        # sources too: the scan materializes at execution time, uploads
        # (strings dict-encode on upload, so the dictionary is global),
        # and shards row-wise across the chips — row groups land on chips
        # the way the reference's resident shuffle serves arbitrary stages
        # (RapidsShuffleInternalManager.scala:73-149). Decode happens once
        # host-side in this single-host runtime; a multi-host deployment
        # would decode per-host before the same sharding step.
        for f in node.schema:
            _require(T.device_supported(f.data_type),
                     f"scan column type {f.data_type} over the mesh")
        sources.append(node)
        idx = len(sources) - 1
        return lambda env, flags: env[idx]

    if isinstance(node, TpuProjectExec):
        from ..ops.expression import Alias, AttributeReference, \
            BoundReference
        for e in node.exprs:
            if e.data_type is T.STRING:
                inner = e.children[0] if isinstance(e, Alias) else e
                _require(isinstance(inner, (AttributeReference,
                                            BoundReference)),
                         "string-PRODUCING expression over the mesh "
                         "(could yield flat per-shard payloads)")
        child = _compile(node.children[0], sources, n_parts, bucket_growth,
                         conf)
        bound = _bind_all(node.exprs, node.children[0].schema)
        out_schema = node.schema

        def project(env, flags):
            b = child(env, flags)
            cols = tuple(e.eval_device(b) for e in bound)
            return b.with_columns(cols, out_schema)
        return project

    if isinstance(node, TpuFilterExec):
        child = _compile(node.children[0], sources, n_parts, bucket_growth,
                         conf)
        bound = node.condition.bind(node.children[0].schema)

        def filt(env, flags):
            b = child(env, flags)
            mask = bound.eval_device(b)
            return KR.compact(b, mask.data & mask.validity)
        return filt

    if isinstance(node, TpuCoalesceBatchesExec):
        return _compile(node.children[0], sources, n_parts, bucket_growth,
                        conf)

    if isinstance(node, TpuHashAggregateExec):
        child = _compile(node.children[0], sources, n_parts, bucket_growth,
                         conf)
        child_schema = node.children[0].schema
        if not node.groupings:
            return _compile_global_agg(node, child, child_schema)
        from ..ops.expression import Alias, AttributeReference, \
            BoundReference
        for g in node.groupings:
            if g.data_type is T.STRING:
                inner = g.children[0] if isinstance(g, Alias) else g
                _require(isinstance(inner, (AttributeReference,
                                            BoundReference)),
                         "computed string grouping key over the mesh")
        groupings = _bind_all(node.groupings, child_schema)
        from ..ops import aggregates as AGG
        aggs = [AGG.AggregateExpression(a.func.bind(child_schema), a.name)
                for a in node.aggregates]
        buf_schema = node._buffer_schema()
        n_keys = len(groupings)
        key_refs = [BoundReference(i, f.data_type, f.nullable)
                    for i, f in enumerate(buf_schema)][:n_keys]
        final = finalize_agg_kernel(n_keys, node.aggregates, buf_schema,
                                    node.schema)

        def agg(env, flags):
            local = child(env, flags)
            # Mesh stays on the always-exact sort path (dense_mode=1):
            # its growth-escalation retry cannot learn dense-mode flags.
            part, _ = _aggregate_batch(local, groupings, aggs, buf_schema,
                                       n_keys, update_mode=True,
                                       dense_mode=1)
            cap = max(part.capacity // n_parts, 128)
            from ..ops.kernels.pallas import from_conf as _pallas_from_conf
            shuffled = _exchange_by_key(
                part, key_refs, n_parts,
                bucket_capacity(int(cap * bucket_growth)), flags,
                pallas=_pallas_from_conf(conf))
            merged, _ = _aggregate_batch(shuffled, key_refs, aggs,
                                         buf_schema, n_keys,
                                         update_mode=False, dense_mode=1)
            return final(merged)
        return agg

    if isinstance(node, TpuShuffledHashJoinExec):
        if node.join_type == "right":
            # Mirror through the left-outer path, reordering columns.
            mirrored = TpuShuffledHashJoinExec(
                node.children[1], node.children[0], "left",
                node.right_keys, node.left_keys,
                _swap_schema(node.schema, len(node.children[0].schema)),
                node.condition, node.growth)
            inner = _compile(mirrored, sources, n_parts, bucket_growth, conf)
            n_right = len(node.children[1].schema)
            out_schema = node.schema

            def reorder(env, flags):
                b = inner(env, flags)
                cols = b.columns[n_right:] + b.columns[:n_right]
                return ColumnarBatch(cols, b.n_rows, out_schema,
                                     live=b.live)
            return reorder

        from .joins import TpuBroadcastExchangeExec
        left, right = node.children
        jt = node.join_type
        # A broadcast build side replicates via all_gather (no keyed
        # exchange needed); correctness holds for the probe-preserving
        # types. Full outer over a broadcast would duplicate the
        # unmatched-build pass per chip, so it co-partitions instead.
        build_is_bcast = isinstance(right, TpuBroadcastExchangeExec) \
            and jt in ("inner", "left", "left_semi", "left_anti")
        right_src = right.children[0] if isinstance(
            right, TpuBroadcastExchangeExec) else right
        # A mirrored right-broadcast join leaves the exchange on the probe
        # side; the wrapper is just a caching layer, so co-partition its
        # child directly.
        left = left.children[0] if isinstance(
            left, TpuBroadcastExchangeExec) else left
        lfn = _compile(left, sources, n_parts, bucket_growth, conf)
        rfn = _compile(right_src, sources, n_parts, bucket_growth, conf)
        lkeys = _bind_all(node.left_keys, left.schema)
        rkeys = _bind_all(node.right_keys, right_src.schema)
        out_schema = node.schema
        from ..ops.kernels.pallas import from_conf as _pallas_from_conf
        kernel = hash_join_kernel(jt, lkeys, rkeys, out_schema,
                                  pallas=_pallas_from_conf(conf))
        post = join_post_filter(node.condition, out_schema)
        unmatched = unmatched_build_kernel(left.schema, out_schema) \
            if jt == "full" else None

        def join(env, flags):
            probe = lfn(env, flags)
            build = rfn(env, flags)
            if build_is_bcast:
                build = _replicate(build)
            else:
                # Co-partition both sides: equal keys meet on one chip, so
                # the ordinary local join kernel is globally correct for
                # every join type (each unmatched row exists on exactly
                # one chip).
                pcap = bucket_capacity(
                    max(int(probe.capacity * bucket_growth) // n_parts, 128))
                bcap = bucket_capacity(
                    max(int(build.capacity * bucket_growth) // n_parts, 128))
                from ..ops.kernels.pallas import \
                    from_conf as _pallas_from_conf2
                probe = _exchange_by_key(probe, lkeys, n_parts, pcap,
                                         flags,
                                         pallas=_pallas_from_conf2(conf))
                build = _exchange_by_key(build, rkeys, n_parts, bcap,
                                         flags,
                                         pallas=_pallas_from_conf2(conf))
            out_cap = bucket_capacity(
                max(int(probe.capacity * node.growth * bucket_growth), 128))
            if jt in ("left_semi", "left_anti"):
                out, _ = kernel(probe, build, out_cap)
                out = ColumnarBatch(out.columns, out.n_rows, out_schema,
                                    live=out.live)
            else:
                (out, hits), total = kernel(probe, build, out_cap)
                flags.append(jax.lax.psum(
                    (total > out_cap).astype(jnp.int32), PART_AXIS) > 0)
                if post is not None:
                    out = post(out)
                if jt == "full":
                    tail = unmatched(build, hits)
                    out = _coalesce_device([out, tail])
            return out
        return join

    if isinstance(node, TpuSortExec):
        return _compile_sort(node, sources, n_parts, bucket_growth, conf)

    raise NotMeshCapable(type(node).__name__)


#: samples per shard for the range-partition bounds; P*64 candidates give
#: boundary error O(1/64) of a shard, well inside the 2x bucket slack.
_SORT_SAMPLES = 64


def _compile_sort(node, sources: List, n_parts: int, bucket_growth: float,
                  conf):
    """Distributed ORDER BY — range-exchange + per-chip sort, never a
    collect-then-sort: each shard samples its first sort key, the samples
    all_gather into global range bounds, rows exchange to the chip owning
    their key range (ties share one chip because bounds are VALUES), and
    the ordinary local sort kernel finishes each shard. Shard s then holds
    global range s, so the collect's in-order concatenation IS the total
    order — the reference's GpuRangePartitioner + per-partition
    GpuSortExec stage shape, as one SPMD program."""
    child = _compile(node.children[0], sources, n_parts, bucket_growth,
                     conf)
    schema = node.schema
    orders = node.orders
    from ..ops.expression import Alias, AttributeReference, BoundReference
    for o in orders:
        if o.child.data_type is T.STRING:
            inner = o.child.children[0] if isinstance(o.child, Alias) \
                else o.child
            _require(isinstance(inner, (AttributeReference, BoundReference)),
                     "computed string sort key over the mesh")
    key_exprs = _bind_all([o.child for o in orders], schema)
    asc = [o.ascending for o in orders]
    nfirst = [o.effective_nulls_first for o in orders]

    def rank_lane(col):
        """Orderable per-row lane in ASCENDING rank space for the first
        key: dict codes for (sorted-dict) strings, raw data otherwise.
        Descending flips with bitwise NOT for integers (order-reversing
        with no overflow at INT_MIN, where negation wraps) and negation
        for floats."""
        lane = col.codes if col.is_dict else col.data
        if col.is_dict:
            # Engine invariant: mesh strings are upload-dictionary-encoded,
            # whose dictionaries are unique+sorted (codes order == string
            # order). Exchanges preserve the flag.
            assert col.dict_sorted, "unsorted dict reached the mesh sort"
        if not asc[0]:
            lane = jnp.negative(lane) \
                if jnp.issubdtype(lane.dtype, jnp.floating) else ~lane
        return lane

    def sortfn(env, flags):
        b = child(env, flags)
        b = KR.physical(b)              # sampling reads the [0, n) prefix
        keys = [e.eval_device(b) for e in key_exprs]
        k0 = keys[0]
        lane = rank_lane(k0)
        n = b.n_rows
        # -- sampled global bounds ---------------------------------------
        pos = (jnp.arange(_SORT_SAMPLES, dtype=jnp.int32) * n) \
            // _SORT_SAMPLES
        samp = lane[jnp.clip(pos, 0, lane.shape[0] - 1)]
        sflag = (jnp.arange(_SORT_SAMPLES, dtype=jnp.int32) < n) \
            & k0.validity[jnp.clip(pos, 0, lane.shape[0] - 1)]
        if jnp.issubdtype(lane.dtype, jnp.floating):
            # NaN keys route explicitly (below), never into the bounds.
            sflag = sflag & ~jnp.isnan(samp)
        all_s = jax.lax.all_gather(samp, PART_AXIS).reshape(-1)
        all_f = jax.lax.all_gather(sflag, PART_AXIS).reshape(-1)
        if all_s.dtype == jnp.bool_:
            all_s = all_s.astype(jnp.int32)
            lane = lane.astype(jnp.int32)
        hi = jnp.asarray(jnp.finfo(all_s.dtype).max
                         if jnp.issubdtype(all_s.dtype, jnp.floating)
                         else jnp.iinfo(all_s.dtype).max, all_s.dtype)
        ordered = jnp.sort(jnp.where(all_f, all_s, hi))
        total = all_f.sum()
        b_idx = (jnp.arange(1, n_parts) * total) // n_parts
        bounds = jnp.where(
            total > 0,
            ordered[jnp.clip(b_idx, 0, ordered.shape[0] - 1)], hi)
        # -- per-row destination -----------------------------------------
        pid = jnp.zeros(lane.shape[0], jnp.int32)
        for j in range(n_parts - 1):
            pid = pid + (lane > bounds[j]).astype(jnp.int32)
        if jnp.issubdtype(lane.dtype, jnp.floating):
            # Spark: NaN is the LARGEST value — last shard ascending,
            # shard 0 descending (rank space already folds direction for
            # finite values, but every NaN comparison is False).
            nan_dest = n_parts - 1 if asc[0] else 0
            pid = jnp.where(jnp.isnan(lane), nan_dest, pid)
        # nulls-first (w.r.t. the ORDER BY direction) puts nulls on shard
        # 0; the asc/desc direction is already folded into rank space.
        null_dest = 0 if nfirst[0] else n_parts - 1
        pid = jnp.where(k0.validity, pid, null_dest)
        # -- range exchange + local sort ---------------------------------
        bucket = bucket_capacity(
            max(int(2 * b.capacity * bucket_growth) // n_parts, 128))
        shuffled = _exchange_by_pid(b, pid, n_parts, bucket, flags)
        keys2 = [e.eval_device(shuffled) for e in key_exprs]
        return KR.sort_batch_by_columns(shuffled, keys2, asc, nfirst)
    return sortfn


def _compile_global_agg(node, child, child_schema):
    """Global (no-key) aggregate over the mesh: local partial buffers per
    shard, then ONE cross-chip collective per buffer (psum/pmin/pmax over
    ICI — no keyed exchange needed), finalize, and emit the single row on
    chip 0 only."""
    from ..ops import aggregates as AGG
    from ..ops.kernels.groupby import _max_value, _min_value
    aggs = [AGG.AggregateExpression(a.func.bind(child_schema), a.name)
            for a in node.aggregates]
    buf_schema = node._buffer_schema()
    merge_ops = [s.merge_op for a in aggs for s in a.func.buffers()]
    for op in merge_ops:
        _require(op in ("sum", "count", "min", "max"),
                 f"global-agg merge op {op!r} over the mesh")
    final = finalize_agg_kernel(0, node.aggregates, buf_schema,
                                node.schema)

    def gagg(env, flags):
        local = child(env, flags)
        part, _ = _aggregate_batch(local, [], aggs, buf_schema, 0,
                                   update_mode=True, dense_mode=1)
        row0 = jnp.arange(part.capacity, dtype=jnp.int32) == 0
        cols = []
        for c, op in zip(part.columns, merge_ops):
            valid = c.validity & row0
            any_valid = jax.lax.pmax(valid.astype(jnp.int32),
                                     PART_AXIS) > 0
            if op in ("sum", "count"):
                data = jax.lax.psum(
                    jnp.where(valid, c.data, jnp.zeros((), c.data.dtype)),
                    PART_AXIS)
            elif op == "min":
                data = jax.lax.pmin(
                    jnp.where(valid, c.data, _max_value(c.data.dtype)),
                    PART_AXIS)
            else:
                data = jax.lax.pmax(
                    jnp.where(valid, c.data, _min_value(c.data.dtype)),
                    PART_AXIS)
            v = any_valid & row0
            cols.append(DeviceColumn(
                data=jnp.where(v, data, jnp.zeros((), data.dtype)),
                validity=v, dtype=c.dtype))
        mine = jax.lax.axis_index(PART_AXIS) == 0
        n = jnp.where(mine, 1, 0).astype(jnp.int32)
        merged = ColumnarBatch(tuple(cols), n, buf_schema)
        return final(merged)
    return gagg


def _replicate(batch: ColumnarBatch) -> ColumnarBatch:
    """all_gather every chip's shard and compact: the mesh broadcast —
    every chip ends up with the full (small) table resident locally.
    Dict strings gather their code lane; the dictionary is already
    replicated."""
    def ag(x):
        return jax.lax.all_gather(x, PART_AXIS, axis=0, tiled=True)
    live_g = ag(batch.row_mask())
    cols = []
    for c in batch.columns:
        if c.is_dict:
            cols.append(DeviceColumn(
                data=c.data, validity=ag(c.validity), dtype=c.dtype,
                offsets=c.offsets, max_bytes=c.max_bytes,
                codes=ag(c.codes), dict_sorted=c.dict_sorted))
        else:
            cols.append(DeviceColumn(data=ag(c.data),
                                     validity=ag(c.validity),
                                     dtype=c.dtype))
    total_cap = live_g.shape[0]
    gb = ColumnarBatch(tuple(cols), jnp.asarray(total_cap, jnp.int32),
                       batch.schema)
    return KR.compact(gb, live_g)


def _encoding_fingerprint(node) -> tuple:
    """Per-source string-encoding layout (dict vs flat), which lives in the
    DATA (DeviceSourceExec.partitions — excluded from plan signatures), so
    mesh cache keys must carry it explicitly: capability and the compiled
    program both depend on it."""
    out = []

    def walk(n):
        if isinstance(n, DeviceSourceExec):
            per_col = []
            for ci, f in enumerate(n.schema):
                if f.data_type is T.STRING:
                    per_col.append(all(
                        b.columns[ci].is_dict
                        for p in n.partitions for b in p))
                else:
                    per_col.append(None)
            out.append(tuple(per_col))
            return
        kids = list(n.children)
        if isinstance(n, TpuShuffledHashJoinExec) and n.join_type == "right":
            kids = [n.children[1], n.children[0]]
        for c in kids:
            walk(c)
    walk(node)
    return tuple(out)


def _sort_mesh_ok(node) -> bool:
    """Static twin of _compile_sort's gates: the in-mesh range sort needs
    every STRING sort key to be a direct column reference (computed string
    keys could yield flat per-shard payloads)."""
    from ..ops.expression import Alias, AttributeReference, BoundReference
    for o in node.orders:
        if o.child.data_type is T.STRING:
            inner = o.child.children[0] if isinstance(o.child, Alias) \
                else o.child
            if not isinstance(inner, (AttributeReference, BoundReference)):
                return False
    return True


def _split_tail(plan):
    """Split trailing single-chip finishers (limit / top-k / project /
    coalesce above the last wide op) off the mesh core: a LIMIT's result
    is tiny by contract, so it finishes on the collected output through
    the ordinary streaming path — the reference likewise finishes LIMIT
    driver-side after its accelerated stages. ORDER BY is NOT peeled when
    _compile_sort can take it: TpuSortExec compiles in-mesh as a
    range-exchange + per-chip sort, so sort tails stay distributed; a
    sort OUTSIDE that scope (computed string key) peels like a limit
    rather than disqualifying the whole plan from the mesh."""
    from .execs import TpuLimitExec, TpuLocalLimitExec, TpuTopKExec
    always_peel = (TpuTopKExec, TpuLimitExec, TpuLocalLimitExec)
    narrow = (TpuProjectExec, TpuCoalesceBatchesExec)

    def peelable(n):
        if isinstance(n, always_peel) or isinstance(n, narrow):
            return True
        return isinstance(n, TpuSortExec) and not _sort_mesh_ok(n)

    def prefix_has_ordered(n):
        while peelable(n):
            if isinstance(n, always_peel) or isinstance(n, TpuSortExec):
                return True
            n = n.children[0]
        return False

    tail = []
    node = plan
    while peelable(node) and prefix_has_ordered(node):
        tail.append(node)
        node = node.children[0]
    return tail, node


def mesh_capable(root, conf) -> bool:
    if not isinstance(root, DeviceToHostExec):
        return False
    sig = ("mesh_capable", _plan_sig(root.children[0]),
           _encoding_fingerprint(root.children[0]))
    cached = _MESH_CACHE.get(sig)
    if cached is None:
        try:
            _, core = _split_tail(root.children[0])
            _compile(core, [], 2, 1.0, conf)
            cached = True
        except NotMeshCapable:
            cached = False
        _MESH_CACHE[sig] = cached  # GIL-atomic last-wins probe cache; concurrency: ignore
    return cached


_MESH_CACHE: Dict[tuple, object] = {}


def clear_mesh_cache() -> None:
    _MESH_CACHE.clear()


def _is_scan_source(node) -> bool:
    """Upload-at-execution source nodes: a host scan behind its upload
    transition, or the device parquet decoder."""
    from ..io.orc_device import TpuOrcScanExec
    from ..io.parquet_device import TpuParquetScanExec
    from .execs import HostToDeviceExec
    return isinstance(node, (HostToDeviceExec, TpuParquetScanExec,
                             TpuOrcScanExec))


def _collect_sources(node, out: List) -> None:
    """Source nodes in the exact order _compile visits them (a mirrored
    right join compiles its children swapped)."""
    if isinstance(node, DeviceSourceExec) or _is_scan_source(node):
        out.append(node)
        return
    kids = list(node.children)
    if isinstance(node, TpuShuffledHashJoinExec) \
            and node.join_type == "right":
        kids = [node.children[1], node.children[0]]
    for c in kids:
        _collect_sources(c, out)


def _shard_source(batch: ColumnarBatch, mesh: Mesh, n_parts: int):
    """Lay a source batch out across the mesh: shard s owns rows
    [s*shard_cap, (s+1)*shard_cap); per-shard live counts derive from the
    traced n_rows with no host sync.

    Per column, the sharded LANE is (data, validity) for fixed-width and
    (codes, validity) for dict strings, whose (payload, offsets) ride
    separately as REPLICATED arrays. Returns (lanes, counts, shard_cap,
    kinds, sides): ``kinds`` is the static per-column descriptor the
    traced program specializes on."""
    shard_cap = bucket_capacity(max(-(-batch.capacity // n_parts), 128))
    global_cap = shard_cap * n_parts
    sharding = NamedSharding(mesh, PartitionSpec(PART_AXIS))
    kinds = tuple(
        ("dict", c.max_bytes, c.dict_sorted) if c.is_dict else ("fixed",)
        for c in batch.columns)

    def build_pad():
        def pad(batch):
            cols = []
            for c in batch.columns:
                lane = c.codes if c.is_dict else c.data
                pad_n = global_cap - c.capacity
                cols.append((jnp.pad(lane, (0, pad_n)),
                             jnp.pad(c.validity, (0, pad_n))))
            counts = jnp.clip(
                batch.n_rows
                - jnp.arange(n_parts, dtype=jnp.int32) * shard_cap,
                0, shard_cap).astype(jnp.int32)
            return cols, counts
        return pad

    pad = cached_kernel(
        "mesh_shard_pad",
        kernel_key(n_parts, shard_cap, batch.schema, batch.capacity, kinds),
        build_pad)
    cols, counts = pad(batch)
    cols = [(jax.device_put(d, sharding), jax.device_put(v, sharding))
            for d, v in cols]
    counts = jax.device_put(counts, sharding)
    repl = NamedSharding(mesh, PartitionSpec())
    sides = tuple(
        (jax.device_put(c.data, repl), jax.device_put(c.offsets, repl))
        if c.is_dict else ()
        for c in batch.columns)
    return cols, counts, shard_cap, kinds, sides


def _mesh_fault_check(ctx) -> None:
    """Deterministic device-loss seam (ISSUE 19). The injector's
    ``mesh.collect`` site stands in for a chip/host dying mid-dispatch:
    a scheduled ``deviceLoss`` raises the typed
    :class:`~..parallel.mesh.MeshDegradedError` BEFORE the SPMD program
    launches, so the failover travels the exact path a real loss takes —
    TRANSIENT classification, session failover record, single-chip
    re-run (docs/fault-tolerance.md#degraded-mesh-fallback)."""
    from ..utils.fault_injection import register_site
    register_site("mesh.collect")
    injector = getattr(ctx, "fault_injector", None)
    if injector is None:
        return
    flavor = injector.check_mesh("mesh.collect")
    if flavor == "deviceLoss":
        raise MeshDegradedError(
            "injected device loss at mesh.collect (mesh.deviceLoss)")


def mesh_collect(root: DeviceToHostExec, ctx: ExecContext,
                 mesh: Optional[Mesh] = None
                 ) -> Tuple[Optional[pa.Table], bool]:
    """Run a mesh-capable plan as one SPMD program over the device mesh.
    Returns (table, overflowed).

    A backend error that reads as device loss (runtime disconnect /
    device-health markers, :func:`~..parallel.mesh.is_device_loss`) is
    re-raised as the typed :class:`~..parallel.mesh.MeshDegradedError`
    so the session fails over to the single-chip path instead of
    surfacing an opaque XlaRuntimeError."""
    _mesh_fault_check(ctx)
    try:
        tail, core = _split_tail(root.children[0])
        if tail:
            table, overflowed = _mesh_core_collect(core, ctx, mesh)
            if overflowed or table is None:
                return None, True
            # Finish sort/limit/project on the (small) collected result
            # via the ordinary streaming path.
            from ..plan.physical import collect_partitions
            src = DeviceSourceExec(
                [[ColumnarBatch.from_arrow(rb)
                  for rb in table.combine_chunks().to_batches()]],
                core.schema)
            plan = src
            for op in reversed(tail):
                plan = op.with_children([plan])
            out = collect_partitions(DeviceToHostExec(plan), ctx)
            return out, False
        return _mesh_core_collect(core, ctx, mesh)
    except MeshDegradedError:
        raise
    except Exception as e:  # tpu-lint: ignore — re-raised unless device loss; XLA surfaces DATA_LOSS as varying exception types
        if is_device_loss(e):
            raise MeshDegradedError(
                f"device loss during mesh dispatch: {e}") from e
        raise


def _mesh_core_collect(device_plan, ctx: ExecContext,
                       mesh: Optional[Mesh] = None
                       ) -> Tuple[Optional[pa.Table], bool]:
    mesh = mesh or make_mesh()
    n_parts = mesh.devices.size
    bucket_growth = float(ctx.join_growth)
    sig = (_plan_sig(device_plan), _encoding_fingerprint(device_plan),
           n_parts, bucket_growth, ctx.conf.collect_guess_rows)
    entry = _MESH_CACHE.get(sig)
    if entry is None:
        sources: List = []
        fn = _compile(device_plan, sources, n_parts, bucket_growth, ctx.conf)
        entry = {"fn": fn, "n_sources": len(sources), "jit": {}}
        _MESH_CACHE[sig] = entry  # GIL-atomic last-wins compile cache; concurrency: ignore
    # The CURRENT plan's source batches, in _compile's traversal order.
    cur_sources: List = []
    _collect_sources(device_plan, cur_sources)
    assert len(cur_sources) == entry["n_sources"]

    sharded = []
    for s in cur_sources:
        if isinstance(s, DeviceSourceExec):
            batches = [b for p in s.partitions for b in p]
        else:  # scan source: execute now (host decode + upload)
            batches = [b for p in s.execute(ctx) for b in p]
        if batches:
            # _shard_source lays rows out positionally — materialize any
            # lazily-filtered cached batch first.
            batch = KR.physical_jit(_coalesce_device(batches))
        else:
            import pyarrow as _pa
            rb = _pa.RecordBatch.from_arrays(
                [_pa.array([], type=f.type)
                 for f in T.schema_to_arrow(s.schema)],
                schema=T.schema_to_arrow(s.schema))
            batch = ColumnarBatch.from_arrow(rb, 128)
        sharded.append(_shard_source(batch, mesh, n_parts))
    shard_caps = tuple(sc for _, _, sc, _, _ in sharded)
    src_kinds = tuple(k for _, _, _, k, _ in sharded)
    schemas = tuple(s.schema for s in cur_sources)

    run = entry["jit"].get((shard_caps, src_kinds))
    if run is None:
        fn = entry["fn"]

        def spmd(source_cols, source_counts, source_sides):
            env = {}
            for i, (cols, counts, sides) in enumerate(
                    zip(source_cols, source_counts, source_sides)):
                n = counts[0]
                cap = cols[0][0].shape[0]
                live = jnp.arange(cap, dtype=jnp.int32) < n
                dcs = []
                for (lane, validity), side, kind, f in zip(
                        cols, sides, src_kinds[i], schemas[i]):
                    validity = validity & live
                    lane = jnp.where(validity, lane,
                                     jnp.zeros((), lane.dtype))
                    if kind[0] == "dict":
                        payload, offsets = side
                        dcs.append(DeviceColumn(
                            data=payload, validity=validity,
                            dtype=f.data_type, offsets=offsets,
                            max_bytes=kind[1], codes=lane,
                            dict_sorted=kind[2]))
                    else:
                        dcs.append(DeviceColumn(data=lane,
                                                validity=validity,
                                                dtype=f.data_type))
                env[i] = ColumnarBatch(tuple(dcs), n.astype(jnp.int32),
                                       schemas[i])
            flags: List = []
            out = fn(env, flags)
            # Host assembly slices each shard's [0, n) prefix — a lazy
            # (mask-live) root must materialize inside the SPMD program.
            out = KR.physical(out)
            flag = jnp.any(jnp.stack(flags)) if flags else \
                jnp.zeros((), jnp.bool_)
            # Dict output columns: the code lane shards; the dictionary
            # buffers are shard-invariant, returned TILED (host slices
            # shard 0's copy — replicated out_specs would need invariance
            # proofs through the collectives).
            out_bufs = tuple(
                (c.codes, c.validity, c.data, c.offsets) if c.is_dict
                else (c.data, c.validity)
                for c in out.columns)
            return out_bufs, out.n_rows.reshape(1), flag.reshape(1)

        spec = PartitionSpec(PART_AXIS)
        run = jax.jit(shard_map(
            spmd, mesh=mesh,
            in_specs=(spec, spec, PartitionSpec()),
            out_specs=(spec, spec, spec)))
        entry["jit"][(shard_caps, src_kinds)] = run

    source_cols = tuple(tuple(cols) for cols, _, _, _, _ in sharded)
    source_counts = tuple(counts for _, counts, _, _, _ in sharded)
    source_sides = tuple(sides for _, _, _, _, sides in sharded)
    out_bufs, out_counts, out_flags = run(source_cols, source_counts,
                                          source_sides)
    got_bufs, counts_np, flags_np = jax.device_get(
        (out_bufs, out_counts, out_flags))
    if bool(np.any(flags_np)):
        return None, True
    out_schema = device_plan.schema
    arrow_schema = T.schema_to_arrow(out_schema)
    shard_out_cap = got_bufs[0][0].shape[0] // n_parts if got_bufs else 0
    batches = []
    for s in range(n_parts):
        n = int(counts_np[s])
        if n == 0:
            continue
        arrays = []
        for bufs, f in zip(got_bufs, out_schema):
            lo = s * shard_out_cap
            if len(bufs) == 4:  # dict string: codes shard, dict tiled
                codes, validity, payload_t, offsets_t = bufs
                n_dict = offsets_t.shape[0] // n_parts - 1
                payload = payload_t[: payload_t.shape[0] // n_parts]
                offsets = offsets_t[: n_dict + 1]
                col = DeviceColumn(
                    data=payload,
                    validity=validity[lo: lo + shard_out_cap],
                    dtype=f.data_type, offsets=offsets,
                    codes=codes[lo: lo + shard_out_cap])
                arrays.append(col.arrow_from_host(
                    (payload, col.validity, offsets, col.codes), n))
            else:
                data, validity = bufs
                col = DeviceColumn(data=data[lo: lo + shard_out_cap],
                                   validity=validity[lo: lo + shard_out_cap],
                                   dtype=f.data_type)
                arrays.append(col.arrow_from_host(
                    (col.data, col.validity), n))
        batches.append(pa.RecordBatch.from_arrays(arrays,
                                                  schema=arrow_schema))
    if not batches:
        return pa.Table.from_batches([], schema=arrow_schema), False
    return pa.Table.from_batches(batches).cast(arrow_schema), False
