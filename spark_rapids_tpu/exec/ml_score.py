"""ModelScore — batch model inference as a physical plan operator.

Tentpole of the ML scenario subsystem (docs/ml-integration.md): a model
registered in the session :class:`~..ml.registry.ModelRegistry` scores
INSIDE queries (``df.with_model_score(name, feature_cols, output_col)``)
instead of round-tripping results to a host scoring service — the
Theseus lens (PAPERS.md) applied to inference: keep the data movement
off the critical path. The Ragged Paged Attention paper (PAPERS.md) is
the TPU idiom this follows for batched on-device inference as a kernel,
not a service hop.

Two implementations, differential twins:

* :class:`CpuModelScoreExec` — the oracle: evaluates the SAME predict
  function (ml/export.py) on host-assembled features. This is what
  ``spark.rapids.tpu.ml.enabled=false`` runs, and what the bit-identity
  tests compare against.
* :class:`TpuModelScoreExec` — the device operator. Features gather
  straight out of the device batch (zero extra transfers), the
  prediction kernel routes through the PR-2 kernel cache (model leaves
  ride as pytree ARGUMENTS, so one compiled program serves every model
  of the same structure and re-registration never stales a cached
  program), each batch is wrapped in the PR-4 retry taxonomy (site
  ``TpuModelScoreExec.score``, halve-by-rows split escalation), model
  acquisition unspills through the PR-11 state machine (site
  ``ml.modelAcquire``), and PR-13 trace spans (``ml.modelAcquire`` /
  ``ml.score``) put scoring on the query timeline. Under whole-stage
  fusion the operator is a BOUNDARY (the TpuTopKExec stance): its
  subtree materializes eagerly with the real context — retry/catalog/
  metrics semantics intact — and its output feeds the fused program as a
  traced input, padded onto the PR-6 polymorphic tiers like every other
  boundary.

Null semantics: a row with a null in ANY feature column scores null
(the feature_matrix masking rule applied per-row).
"""

from __future__ import annotations

from typing import List

import jax.numpy as jnp
import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from .. import types as T
from ..data.batch import ColumnarBatch, HostBatch
from ..data.column import DeviceColumn
from ..ops.expression import Expression, host_to_array
from ..plan.physical import PhysicalPlan
from ..utils.kernel_cache import cached_kernel, kernel_key
from .execs import TpuExec, _tick


def _predict_fn(kind: str):
    from ..ml.export import predict_gbt, predict_logistic
    return predict_gbt if kind == "gbt" else predict_logistic


class CpuModelScoreExec(PhysicalPlan):
    """Host-side ModelScore oracle: assemble features from host batches
    (nulls filled with the device's deterministic zero), run the SAME
    predict function the device kernel traces, null out rows with null
    features. The bit-identity twin behind
    ``spark.rapids.tpu.ml.enabled=false``."""

    def __init__(self, child: PhysicalPlan, registry, model_name: str,
                 model_version: int, feature_exprs: List[Expression],
                 output_col: str, schema: T.Schema):
        self.children = [child]
        #: skipped from plan signatures (utils/kernel_cache.py); the
        #: (model_name, model_version) statics carry the cache identity.
        self._ml_registry = registry
        self.model_name = model_name
        self.model_version = int(model_version)
        self.exprs = list(feature_exprs)
        self.output_col = output_col
        self._schema = schema

    @property
    def schema(self):
        return self._schema

    def describe(self):
        feats = ", ".join(e.name for e in self.exprs)
        return (f"CpuModelScore[{self.model_name} v{self.model_version}]"
                f"({feats}) -> {self.output_col}")

    def execute(self, ctx):
        meta, model = self._ml_registry.acquire(self.model_name, ctx)
        predict = _predict_fn(meta.kind)
        arrow = T.schema_to_arrow(self.schema)
        name = self.node_name()

        def run(part):
            for hb in part:
                n = hb.num_rows
                valid = np.ones(n, bool)
                cols = []
                for e in self.exprs:
                    arr = host_to_array(e.eval_host(hb), n)
                    valid &= pc.is_valid(arr).to_numpy(zero_copy_only=False)
                    filled = pc.fill_null(arr, pa.scalar(0, arr.type))
                    cols.append(filled.to_numpy(zero_copy_only=False)
                                .astype(np.float32))
                if n:
                    x = np.stack(cols, axis=1)
                    preds = np.asarray(predict(model, jnp.asarray(x)),
                                       np.float32)
                else:
                    preds = np.zeros(0, np.float32)
                score = pa.array(preds, pa.float32(), mask=~valid)
                arrays = list(hb.rb.columns) + [score]
                arrays = [a.cast(f.type) for a, f in zip(arrays, arrow)]
                ctx.metric(name, "numOutputBatches", 1)
                ctx.ml_score_rows.append(n)
                yield HostBatch(pa.RecordBatch.from_arrays(arrays,
                                                           schema=arrow))
        return [run(p) for p in self.children[0].execute(ctx)]


class TpuModelScoreExec(TpuExec):
    """Device ModelScore (see module doc): one cached traced kernel per
    (child schema, feature ordinals, model structure) — the model's
    array leaves are pytree arguments, so the program is shared across
    models and model versions of the same shape."""

    def __init__(self, child: PhysicalPlan, registry, model_name: str,
                 model_version: int, feature_exprs: List[Expression],
                 output_col: str, schema: T.Schema):
        self.children = [child]
        self._ml_registry = registry
        self.model_name = model_name
        self.model_version = int(model_version)
        self.exprs = list(feature_exprs)
        self.output_col = output_col
        self._schema = schema

    @property
    def schema(self):
        return self._schema

    def describe(self):
        feats = ", ".join(e.name for e in self.exprs)
        return (f"TpuModelScore[{self.model_name} v{self.model_version}]"
                f"({feats}) -> {self.output_col}")

    def execute(self, ctx):
        from ..memory import retry as R
        from ..metrics import trace as TR
        name = self.node_name()
        child_schema = self.children[0].schema
        with TR.span(ctx.trace, "ml.modelAcquire", cat="ml",
                     model=self.model_name):
            meta, model = self._ml_registry.acquire(self.model_name, ctx)
        leaves = {k: v for k, v in model.items() if hasattr(v, "dtype")}
        static = tuple(sorted((k, v) for k, v in model.items()
                              if not hasattr(v, "dtype")))
        f_idx = tuple(child_schema.index_of(e.name) for e in self.exprs)
        out_schema = self.schema
        kind = meta.kind

        def build():
            predict = _predict_fn(kind)

            def score(batch: ColumnarBatch, arrays) -> ColumnarBatch:
                m = dict(arrays)
                m.update(dict(static))
                cols = [batch.columns[i] for i in f_idx]
                x = jnp.stack([c.data.astype(jnp.float32) for c in cols],
                              axis=1)
                pred = predict(m, x).astype(jnp.float32)
                valid = batch.row_mask()
                for c in cols:
                    valid = valid & c.validity
                out = DeviceColumn(
                    data=jnp.where(valid, pred, jnp.zeros((), jnp.float32)),
                    validity=valid, dtype=T.FLOAT)
                return batch.with_columns(tuple(batch.columns) + (out,),
                                          out_schema)
            return score
        score = cached_kernel(
            "ml_score",
            kernel_key(child_schema, f_idx, kind, static, out_schema),
            build)

        def run(part):
            import time as _time
            t0 = _time.perf_counter_ns()
            for db in part:
                with TR.span(ctx.trace, "ml.score", cat="ml",
                             model=self.model_name):
                    outs = R.with_retry(ctx, "TpuModelScoreExec.score", db,
                                        lambda b: score(b, leaves),
                                        split=R.halve_by_rows, node=name)
                for out in outs:
                    # Traced live counts; summed by ONE deferred device
                    # read into the engine.ml profile section.
                    ctx.ml_score_rows.append(out.n_rows)
                    t0 = _tick(ctx, name, t0)
                    yield out
        return [run(p) for p in self.children[0].execute(ctx)]
