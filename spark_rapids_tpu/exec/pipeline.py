"""Pipelined execution layer — overlap the host side of the query pipeline.

BENCH r05 showed the device path losing to the CPU oracle on most
multi-boundary queries: not because device compute was slow, but because
every fusion boundary (scan, decode, upload, shuffle, window) materialized
one after another on a single thread before the one fused dispatch. The
reference explicitly overlaps the next host buffer assembly with the
previous GPU decode (GpuParquetScan.scala:314 readPartFile /
Table.readParquet split), and the data-movement literature (Theseus,
arxiv 2508.05029; "Accelerating Presto with GPUs", arxiv 2606.24647)
attributes most accelerator wins to keeping transfer and compute
concurrent. This module is the engine-wide version of that discipline:

* :class:`PipelinePool` — ONE shared, elastic worker pool for every
  pipeline stage (prefetch iterators, decode tasks, boundary
  materialization, shuffle serialization), replacing the raw
  ``threading.Thread``-per-iterator pattern (ratcheted by the
  ``raw-thread`` tpu_lint rule). Elastic on purpose: a fixed-size pool
  deadlocks when every slot holds a producer whose consumer is itself a
  queued task; here a submit never waits behind a busy worker, and idle
  workers are reused. :func:`shutdown` joins every worker
  (``TpuSession.close`` calls it; the conftest leak check asserts no
  pipeline thread survives).
* :func:`ordered_map_iter` / :func:`unit_partitions` — bounded decode-ahead
  for the file readers: up to ``prefetchDepth`` files/row-groups decode
  concurrently (capped globally by ``decodeThreads``) while results yield
  in deterministic input order.
* :func:`materialize_boundaries` — independent fusion-boundary subtrees
  materialize concurrently on forked :class:`~..plan.physical.ExecContext`
  children (private accumulators merged back in boundary order; disjoint
  deterministic join-site namespaces), with device admission still
  serialized through the existing task semaphore: each worker acquires it,
  and the dispatching thread releases its own slot while it waits — the
  reference's release-during-shuffle-fetch discipline.

Determinism contract: results are bit-identical with the pipeline on or
off — concurrency only reorders WHEN work happens, never what it
computes, and everything order-sensitive (fused argument order, decode
output order, accumulator merges) is sequenced explicitly. When a fault
injector is active the parallel paths fall back to serial execution so
per-site injection schedules stay deterministic
(:func:`parallel_active`; docs/fault-tolerance.md).

Occupancy counters (ESSENTIAL level, folded into the QueryProfile):
``prefetchProducerStallNs`` / ``prefetchConsumerStallNs`` (which side of
each bounded queue is the bottleneck), ``decodeThreadBusyNs`` (decode-pool
utilization), ``boundaryOverlapNs`` (wall time saved by concurrent
boundary materialization). See docs/tuning-guide.md for sizing.
"""

from __future__ import annotations

import collections
import contextlib
import math
import os
import queue
import threading
import time
from concurrent.futures import Future
from typing import Callable, Iterator, List, Optional, Sequence

from ..metrics import trace as _trace
from ..utils import lockdep

_STOP = object()


class PoolShutdownError(RuntimeError):
    """The shared pool was shut down under this caller (a concurrent
    ``TpuSession.close`` — e.g. the serving layer's session reaper
    retiring a crashed neighbor). Classified TRANSIENT by the retry
    taxonomy (memory/retry.py): the pool is lazily recreated, so a
    retry in place lands on fresh workers and the query survives."""


# ---------------------------------------------------------------------------
# The shared elastic worker pool
# ---------------------------------------------------------------------------


class PipelinePool:
    """Shared elastic worker pool for pipeline stages.

    Unlike a fixed-size executor, ``submit`` never queues a task behind a
    busy worker: it hands the task to an idle worker when one exists and
    spawns a fresh (reusable, daemon) thread otherwise. Long-lived
    occupants — prefetch producers that block for their whole iterator
    lifetime — therefore can never starve short decode tasks into a
    deadlock. Concurrency limits live at the call sites (decode slots,
    boundary slots, prefetch depth), not in the pool size.
    """

    def __init__(self, name: str = "tpu-pipeline"):
        self._name = name
        self._tasks: "queue.Queue" = queue.Queue()
        self._lock = lockdep.lock("PipelinePool._lock")
        self._threads: List[threading.Thread] = []
        self._idle = 0
        self._seq = 0
        self._closed = False
        #: Set when shutdown starts; prefetch producers poll it so a
        #: blocked put() cannot outlive the pool.
        self.shutting_down = threading.Event()

    def submit(self, fn: Callable, *args) -> Future:
        f: Future = Future()
        # Enqueue AND start entirely under the lock (the queue is
        # unbounded, so neither blocks): shutdown() snapshots alive
        # threads under the same lock, so a spawned worker is either
        # visible to its join + _STOP accounting or the submit already
        # saw _closed and raised — no window where a late-starting
        # worker misses both.
        with self._lock:
            if self._closed:
                raise PoolShutdownError("pipeline pool is shut down")
            spawn = self._idle == 0
            if not spawn:
                self._idle -= 1
            self._tasks.put((f, fn, args))
            if spawn:
                # The engine's ONE sanctioned thread-spawn site: every
                # other module routes here (tpu_lint rule raw-thread).
                t = threading.Thread(  # tpu-lint: ignore
                    target=self._work, name=f"{self._name}-{self._seq}",
                    daemon=True)
                self._seq += 1
                self._threads.append(t)
                t.start()
        return f

    def _work(self) -> None:
        while True:
            item = self._tasks.get()
            if item is _STOP:
                return
            f, fn, args = item
            ran = f.set_running_or_notify_cancel()
            result = exc = None
            if ran:
                try:
                    result = fn(*args)
                # Forwarded verbatim to the future: the CONSUMER's
                # result() re-raises it where the retry taxonomy (or the
                # exchange/reader handlers) classify it — the pool must
                # stay classification-neutral.
                except BaseException as e:  # tpu-lint: ignore
                    exc = e
            # Return to the idle pool BEFORE publishing the result: a
            # consumer that wakes on result() and immediately submits its
            # next task must see this worker as reusable — publishing
            # first left a window where sequential submit/result loops
            # spawned one fresh thread per task.
            with self._lock:
                closed = self._closed
                if not closed:
                    self._idle += 1
            if ran:
                if exc is not None:
                    f.set_exception(exc)
                else:
                    f.set_result(result)
            if closed:
                return

    def alive_threads(self) -> List[threading.Thread]:
        with self._lock:
            return [t for t in self._threads if t.is_alive()]

    def shutdown(self, timeout: float = 10.0) -> List[threading.Thread]:
        """Stop accepting work, wake every worker, join them. Returns the
        threads (if any) that failed to stop within ``timeout`` — the
        conftest leak check asserts this list is empty."""
        self.shutting_down.set()
        with self._lock:
            self._closed = True
            threads = [t for t in self._threads if t.is_alive()]
        for _ in threads:
            self._tasks.put(_STOP)
        deadline = time.monotonic() + timeout
        leaked = []
        for t in threads:
            t.join(max(0.0, deadline - time.monotonic()))
            if t.is_alive():
                leaked.append(t)
        # Cancel anything that raced past the closed check into the queue,
        # so no consumer blocks forever on a future nobody will run.
        while True:
            try:
                item = self._tasks.get_nowait()
            except queue.Empty:
                break
            if item is not _STOP:
                item[0].cancel()
        return leaked


_LOCK = lockdep.lock("pipeline._LOCK")
_POOL: Optional[PipelinePool] = None
_DECODE_SLOTS: Optional[threading.BoundedSemaphore] = None
#: Conf snapshot (TpuSession.configure); defaults match the conf defaults.
_CONF = {"decode_threads": 0, "boundary_parallelism": 0,
         "prefetch_depth": 2}


def configure(conf) -> None:
    """Snapshot the pool-sizing confs from a session's TpuConf (the same
    configure() idiom as the compile layer). Limiter semaphores rebuild
    lazily so a resize takes effect for new work without disturbing
    in-flight holders of the old one."""
    global _DECODE_SLOTS
    with _LOCK:
        try:
            _CONF["decode_threads"] = int(conf.pipeline_decode_threads)
            _CONF["boundary_parallelism"] = \
                int(conf.pipeline_boundary_parallelism)
            _CONF["prefetch_depth"] = int(conf.pipeline_prefetch_depth)
        except AttributeError:
            return  # bare test conf without the pipeline properties
        _DECODE_SLOTS = None


def get_pool() -> PipelinePool:
    """The process-wide shared pool (lazily created; recreated after a
    shutdown, so closing one session only quiesces it)."""
    global _POOL
    with _LOCK:
        if _POOL is None or _POOL.shutting_down.is_set():
            _POOL = PipelinePool()
        return _POOL


def shutdown(timeout: float = 10.0) -> List[threading.Thread]:
    """Join every pipeline worker thread (TpuSession.close / conftest leak
    check). Returns threads that failed to stop in time."""
    global _POOL
    with _LOCK:
        pool, _POOL = _POOL, None
    if pool is None:
        return []
    return pool.shutdown(timeout)


def _auto_threads() -> int:
    return max(2, min(4, os.cpu_count() or 2))


def submit_spill_io(fn, *args) -> Optional[Future]:
    """Spill-IO lane entry (memory/spill.py): hand one spill/restore copy
    or disk append/read to the shared pool. Concurrency is bounded by the
    CALLER's lane slots (``spark.rapids.tpu.spill.ioThreads`` — each
    catalog holds its own slot semaphore inside the submitted unit, the
    decode-limiter pattern), never by pool size. Returns None when the
    pool refuses the task (shutdown race) — the caller runs the unit
    inline, because spilling must survive pool teardown: a query draining
    memory during session close still has to land its bytes."""
    try:
        return get_pool().submit(fn, *args)
    except RuntimeError:
        return None


def _conf_int(conf, prop: str, fallback_key: str) -> int:
    """Per-session conf value when available (sizing must not leak
    between sessions through the process-global snapshot), else the
    configure() fallback."""
    try:
        if conf is not None:
            return int(getattr(conf, prop))
    except (AttributeError, TypeError, ValueError):
        pass
    return _CONF[fallback_key]


def _decode_limiter(conf=None) -> threading.BoundedSemaphore:
    """Global decode-slot semaphore, keyed by the effective size so two
    sessions with different decodeThreads each get their bound (in-flight
    holders of a resized limiter keep their own reference)."""
    global _DECODE_SLOTS
    n = _conf_int(conf, "pipeline_decode_threads", "decode_threads")
    n = n if n > 0 else _auto_threads()
    with _LOCK:
        if _DECODE_SLOTS is None \
                or getattr(_DECODE_SLOTS, "_initial_value", None) != n:
            _DECODE_SLOTS = threading.BoundedSemaphore(n)
        return _DECODE_SLOTS


def boundary_parallelism(conf=None) -> int:
    n = _conf_int(conf, "pipeline_boundary_parallelism",
                  "boundary_parallelism")
    return n if n > 0 else _auto_threads()


def prefetch_depth(conf=None) -> int:
    try:
        if conf is not None:
            return max(1, int(conf.pipeline_prefetch_depth))
    except AttributeError:
        pass
    return max(1, _CONF["prefetch_depth"])


def parallel_active(ctx) -> bool:
    """True when the pipeline's PARALLEL paths may engage for this
    execution. A live fault injector forces the serial path: concurrent
    visits to one injection site would make WHICH visit faults depend on
    thread interleaving, and injection schedules are contractually
    per-site deterministic (docs/fault-tolerance.md)."""
    if getattr(ctx, "fault_injector", None) is not None:
        return False
    conf = getattr(ctx, "conf", None)
    try:
        return bool(conf.pipeline_enabled)
    except AttributeError:
        return True


# ---------------------------------------------------------------------------
# Bounded decode-ahead (io readers)
# ---------------------------------------------------------------------------


def _result_or_shutdown(f: Future, timeout: Optional[float] = None):
    """``f.result(timeout)`` with pool-teardown cancellation translated
    to the typed (transient) :class:`PoolShutdownError` — raw
    CancelledError derives from BaseException on modern Pythons and
    would sail past every ``except Exception`` retry arm. The futures
    TimeoutError passes through untouched for the caller's deadline
    loop."""
    from concurrent.futures import CancelledError
    try:
        return f.result(timeout=timeout)
    except CancelledError:
        raise PoolShutdownError(
            "pipeline pool shut down while this future was awaited "
            "(concurrent TpuSession.close); the unit was cancelled "
            "unrun") from None


def _stalled_result(f: Future, ctx, node: Optional[str]):
    """future.result() with the blocked time accounted to the consumer
    stall counter — the signal that the producer side is the bottleneck.
    An active query deadline bounds the wait: an expired deadline raises
    QueryDeadlineExceeded instead of blocking on a slow producer forever
    (cooperative cancellation — the worker's in-flight unit completes and
    is discarded)."""
    from concurrent.futures import TimeoutError as _FutTimeout
    deadline = getattr(ctx, "deadline", None)
    if f.done():
        return _result_or_shutdown(f)
    t0 = time.perf_counter_ns()
    # ONE span for the whole wait (opened only once we know we block):
    # the deadline branch polls in 0.1s ticks, and a span per tick would
    # flood the tracer and flight ring during a long producer stall.
    try:
        with _trace.span(getattr(ctx, "trace", None), "pipeline.wait",
                         cat="pipeline", node=node or "prefetch"):
            if deadline is None:
                with lockdep.blocking("pipeline.future_wait"):
                    return _result_or_shutdown(f)
            while True:
                try:
                    with lockdep.blocking("pipeline.future_wait"):
                        # An INFINITE deadline (the serving layer's
                        # cancel-only Deadline(math.inf)) polls bounded:
                        # result(timeout=inf) is an OverflowError in
                        # CPython, and a cancel() could never wake an
                        # unbounded wait.
                        rem = deadline.remaining()
                        return _result_or_shutdown(
                            f, timeout=max(rem, 0.0)
                            if math.isfinite(rem) else 0.1)
                except _FutTimeout:
                    # On py3.11+ futures.TimeoutError IS the builtin
                    # TimeoutError, which a WORKER can legitimately raise
                    # (requestTimeout, injected stall). A done future
                    # means the exception came from the work — re-raise
                    # it instead of misreading it as a wait-timeout and
                    # spinning.
                    if f.done():
                        return _result_or_shutdown(f)
                    # Raises once expired; a spurious early wake re-arms.
                    deadline.check(f"pipeline.wait:{node or 'prefetch'}",
                                   ctx, node)
    finally:
        if ctx is not None and node:
            ctx.metric(node, "prefetchConsumerStallNs",
                       time.perf_counter_ns() - t0)


def _decode_task(fn: Callable, item, ctx, node: Optional[str]):
    """One decode unit on the shared pool: bounded by the global decode
    slots, busy time accounted to decodeThreadBusyNs. Runs on a worker
    thread — its span parents under the trace root (the fork fallback),
    which is exactly where concurrent decode lanes belong on the
    timeline."""
    with _decode_limiter(getattr(ctx, "conf", None)):
        t0 = time.perf_counter_ns()
        try:
            with _trace.span(getattr(ctx, "trace", None), "pipeline.decode",
                             cat="decode", node=node or "scan"):
                return fn(item)
        finally:
            if ctx is not None and node:
                ctx.metric(node, "decodeThreadBusyNs",
                           time.perf_counter_ns() - t0)


def ordered_map_iter(fn: Callable, items: Sequence, ctx=None,
                     node: Optional[str] = None,
                     depth: Optional[int] = None) -> Iterator:
    """Map ``fn`` over ``items`` with up to ``depth`` results decoding
    ahead on the shared pool, yielding in input order — the bounded
    producer side of every single-stream reader (ORC stripes, CSV files).
    Serial (plain map) when the pipeline is off or an injector is live."""
    if not parallel_active(ctx):
        for item in items:
            yield fn(item)
        return
    pool = get_pool()
    if depth is None:
        depth = prefetch_depth(getattr(ctx, "conf", None))
    futs: "collections.deque[Future]" = collections.deque()
    try:
        for item in items:
            futs.append(pool.submit(_decode_task, fn, item, ctx, node))
            if len(futs) >= max(depth, 1):
                yield _stalled_result(futs.popleft(), ctx, node)
        while futs:
            yield _stalled_result(futs.popleft(), ctx, node)
    finally:
        # Early abandonment (LIMIT): drop the look-ahead; running decodes
        # finish and are discarded, unstarted ones never run.
        for f in futs:
            f.cancel()


class _UnitScheduler:
    """Decode-ahead over per-unit scan partitions (parquet's one
    partition per row group): partition i's generator waits on future i,
    and pulling it schedules units i..i+depth-1 — so the next row groups
    decode while the consumer uploads/dispatches the current one, without
    changing the scan's partition structure."""

    def __init__(self, fn: Callable, units: Sequence, ctx,
                 node: Optional[str]):
        self._fn = fn
        self._units = list(units)
        self._ctx = ctx
        self._node = node
        self._depth = prefetch_depth(getattr(ctx, "conf", None))
        self._pool = get_pool()
        self._futs: dict = {}
        self._lock = lockdep.lock("_UnitScheduler._lock")
        # A LIMIT can abandon trailing partitions; drop their look-ahead
        # at query end (running decodes finish, unstarted never run).
        if hasattr(ctx, "add_cleanup"):
            ctx.add_cleanup(self._cancel_pending)

    def _ensure(self, i: int) -> Future:
        with self._lock:
            for j in range(i, min(i + self._depth, len(self._units))):
                if j not in self._futs:
                    self._futs[j] = self._pool.submit(
                        _decode_task, self._fn, self._units[j],
                        self._ctx, self._node)
            return self._futs[i]

    def _cancel_pending(self) -> None:
        with self._lock:
            for f in self._futs.values():
                f.cancel()

    def partition(self, i: int) -> Iterator:
        yield _stalled_result(self._ensure(i), self._ctx, self._node)


def _serial_unit(fn: Callable, unit) -> Iterator:
    yield fn(unit)


def unit_partitions(fn: Callable, units: Sequence, ctx,
                    node: Optional[str] = None) -> List[Iterator]:
    """One single-batch partition per unit (the scan partition contract),
    decoded ahead on the shared pool when the pipeline is active."""
    units = list(units)
    if len(units) <= 1 or not parallel_active(ctx):
        return [_serial_unit(fn, u) for u in units]
    sched = _UnitScheduler(fn, units, ctx, node)
    return [sched.partition(i) for i in range(len(units))]


# ---------------------------------------------------------------------------
# Concurrent fusion-boundary materialization
# ---------------------------------------------------------------------------


def _serial_boundary(b, index: int, ctx, tr) -> tuple:
    """One boundary materialized on the calling thread (single boundary,
    pipeline off, or injector active) — same span as the worker path so
    traces always show the boundary stage, overlapped or not."""
    with _trace.span(tr, "pipeline.boundary", cat="pipeline", index=index,
                     node=type(b).__name__):
        return tuple(tuple(p) for p in b.execute(ctx))


def materialize_boundaries(boundaries: Sequence, ctx,
                           node: str = "WholeStageFusion") -> tuple:
    """Materialize every fusion-boundary subtree's partitions, preserving
    the deterministic argument order of the fused program.

    With the pipeline active and more than one boundary, each boundary
    executes on a worker with a forked context (private accumulator
    lists, a disjoint deterministic join-site namespace — see
    ExecContext.fork_for_boundary) and the parent absorbs the forks in
    boundary order afterward, so accumulator contents never depend on
    thread interleaving. Device admission stays serialized through the
    existing task semaphore: every worker acquires it, and the
    dispatching thread releases its own slot(s) while it waits so the
    default concurrentTpuTasks budget actually admits the workers."""
    boundaries = list(boundaries)
    parallelism = boundary_parallelism(getattr(ctx, "conf", None))
    if len(boundaries) <= 1 or not parallel_active(ctx) \
            or parallelism <= 1:
        tr = getattr(ctx, "trace", None)
        return tuple(
            _serial_boundary(b, i, ctx, tr)
            for i, b in enumerate(boundaries))
    subs = [ctx.fork_for_boundary(i) for i in range(len(boundaries))]
    pool = get_pool()
    slots = threading.BoundedSemaphore(parallelism)
    sem = getattr(ctx, "semaphore", None)
    # Span context forked ONCE on the dispatching thread: every worker's
    # boundary span parents under the span open HERE (fusion.boundaries),
    # not wherever the worker's own stack happens to be.
    span_fork = _trace.fork(getattr(ctx, "trace", None))

    def run_one(b, sub, index):
        with slots:
            admission = sem if sem is not None else contextlib.nullcontext()
            with admission:
                t0 = time.perf_counter_ns()
                with _trace.span(span_fork, "pipeline.boundary",
                                 cat="pipeline", index=index,
                                 node=type(b).__name__):
                    out = tuple(tuple(p) for p in b.execute(sub))
                return out, time.perf_counter_ns() - t0

    t_wall = time.perf_counter_ns()
    futs = [pool.submit(run_one, b, sub, i)
            for i, (b, sub) in enumerate(zip(boundaries, subs))]
    release = sem.released() if sem is not None \
        else contextlib.nullcontext()
    results: List = []
    err: Optional[BaseException] = None
    with release:
        # Wait for EVERY worker even after a failure: forks must not be
        # absorbed (or their cleanups run) while a worker still mutates
        # them, and cleanups of successful boundaries must reach the
        # parent so ctx.close() can run them.
        for f in futs:
            try:
                with _trace.span(getattr(ctx, "trace", None),
                                 "pipeline.boundary_wait", cat="pipeline"), \
                        lockdep.blocking("pipeline.boundary_wait"):
                    results.append(f.result())
            # Collect-and-re-raise: the FIRST failure propagates verbatim
            # after every worker has stopped touching its fork (the
            # session's retry loop then classifies it).
            except BaseException as e:  # tpu-lint: ignore
                err = err or e
                results.append(None)
    for sub in subs:
        ctx.absorb_boundary(sub)
    if err is not None:
        raise err
    wall = time.perf_counter_ns() - t_wall
    busy = sum(ns for _, ns in results)
    if busy > wall:
        ctx.metric(node, "boundaryOverlapNs", busy - wall)
    return tuple(out for out, _ in results)
