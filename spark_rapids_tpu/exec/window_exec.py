"""TpuWindowExec — the ``GpuWindowExec`` analog (GpuWindowExec.scala:92).

The reference evaluates each window expression with cudf rolling-window
aggregations over partition groups. Here each window expression is evaluated
by one fused XLA program per batch (see :mod:`..ops.kernels.window` for the
formulation): sort once per distinct (partitionBy, orderBy), derive every
row's frame as index arithmetic, reduce with prefix sums / sparse tables,
scatter results back to input row order.

Like TpuSortExec, window evaluation needs the whole partition in one batch
(the reference declares ``RequireSingleBatch`` for its sort; windows get
whole-partition data via Spark's required child ordering + exchange).
"""

from __future__ import annotations

from typing import List, Tuple

import jax
import jax.numpy as jnp

from .. import types as T
from ..data.batch import ColumnarBatch
from ..data.column import DeviceColumn
from ..ops import aggregates as AGG
from ..ops import windows as W
from ..ops.expression import Expression
from ..ops.kernels import rowops as KR
from ..ops.kernels import window as KW
from ..plan.physical import PhysicalPlan
from ..utils.kernel_cache import cached_kernel, kernel_key
from .execs import TpuExec, _coalesce_device


class TpuWindowExec(TpuExec):
    children_coalesce_goals = ["single"]

    def __init__(self, child: PhysicalPlan,
                 window_exprs: List[Tuple[str, W.WindowExpression]],
                 schema: T.Schema):
        self.children = [child]
        self.window_exprs = window_exprs
        self._schema = schema

    @property
    def schema(self):
        return self._schema

    def describe(self):
        return "TpuWindow [" + ", ".join(n for n, _ in self.window_exprs) + "]"

    def execute(self, ctx):
        child_schema = self.children[0].schema
        bound = []
        for name, we in self.window_exprs:
            spec = we.spec
            part = [e.bind(child_schema) for e in spec.partition_by]
            orders = [(o.child.bind(child_schema), o.ascending,
                       o.effective_nulls_first) for o in spec.order_by]
            func = we.func.bind(child_schema) if we.func.children else we.func
            bound.append((name, func, part, orders, spec.effective_frame()))
        out_schema = self._schema

        def build():
            def window_all(batch: ColumnarBatch) -> ColumnarBatch:
                # Window evaluation is positional (prefix run bounds,
                # identity perm for the no-key case) — materialize lazy
                # batches first.
                batch = KR.physical(batch)
                out_cols = list(batch.columns)
                for name, func, part, orders, frame in bound:
                    out_cols.append(_eval_window(batch, func, part,
                                                 orders, frame))
                return ColumnarBatch(tuple(out_cols), batch.n_rows,
                                     out_schema)
            return window_all
        window_all = cached_kernel("window", kernel_key(bound, out_schema),
                                   build)

        # Bounded-memory chunking applies when every window expression
        # shares ONE non-empty partition-by key list: the input external-
        # sorts by those keys through the spill catalog, and complete key
        # groups evaluate chunk by chunk — the device never holds the
        # whole dataset (GpuWindowExec + spill store interplay,
        # GpuWindowExec.scala:92).
        part_sigs = {kernel_key(pt) for _, _, pt, _, _ in bound}
        common_parts = bound[0][2] if bound and len(part_sigs) == 1 \
            and bound[0][2] else None

        name = self.node_name()

        def run(parts):
            from ..config import WINDOW_EXTERNAL_THRESHOLD
            from ..memory import retry as R
            from ..memory import spill as SP
            catalog = getattr(ctx, "catalog", None)
            batches = [db for part in parts for db in part]
            if not batches:
                return
            threshold = None
            if catalog is not None and not ctx.in_fusion \
                    and common_parts is not None:
                threshold = ctx.conf.get(WINDOW_EXTERNAL_THRESHOLD) or \
                    catalog.device_budget // 4
            total = sum(b.device_size_bytes for b in batches)
            if threshold is None or total <= threshold:
                # Whole-partition contract: a window piece cannot split by
                # rows without breaking its partition groups, so this site
                # is spill + retry only (SplitAndRetryOOM when exhausted;
                # the chunked path below is the real degradation valve).
                def evaluate(bs):
                    with ctx.registry.timer(name, "opTime"):
                        return window_all(_coalesce_device(bs))
                out = R.with_retry(ctx, f"{name}.evaluate", batches,
                                   evaluate, node=name)[0]
                ctx.metric(name, "numOutputBatches", 1)
                yield out
                return
            for piece in _chunked_pieces(batches, common_parts,
                                         child_schema, catalog, ctx,
                                         threshold):
                ctx.metric(name, "chunkedWindow", 1)
                ctx.metric(name, "numOutputBatches", 1)
                yield R.with_retry(ctx, f"{name}.evaluate", piece,
                                   window_all, node=name)[0]
        return [run(self.children[0].execute(ctx))]


def _chunked_pieces(batches, part_exprs, child_schema, catalog, ctx,
                    threshold):
    """Stream complete partition-key groups under a bounded device
    footprint: external-sort the input by the partition keys (runs spill
    through the catalog), then walk the globally sorted chunks carrying
    the trailing (possibly incomplete) key group into the next chunk.
    Each yielded piece holds only COMPLETE groups — except the final one,
    which flushes the remainder."""
    import jax
    import jax.numpy as jnp

    from ..plan.logical import SortOrder
    from .execs import _coalesce_device
    from .external_sort import ExternalSorter, _slice_kernel

    orders = [SortOrder(e) for e in part_exprs]
    sorter = ExternalSorter(orders, child_schema, catalog,
                            key_exprs=list(part_exprs), ctx=ctx)
    try:
        slice_k = _slice_kernel(child_schema)
        from ..data.column import bucket_capacity
        for b in batches:
            # The upstream coalesce (RequireSingleBatch goal) may hand us
            # one oversized batch; re-slice so sorted runs (and therefore
            # the merged chunk stream) stay threshold-bounded.
            per_row = max(b.device_size_bytes // max(b.capacity, 1), 1)
            rows_per = bucket_capacity(
                max(int(threshold // per_row) or 128, 128))
            if b.capacity <= rows_per:
                sorter.add_batch(b)
                continue
            b = KR.physical_jit(b)
            total = int(jax.device_get(b.n_rows))
            off = 0
            while off < total:
                take = min(rows_per, total - off)
                sorter.add_batch(slice_k(
                    b, jnp.asarray(off, jnp.int32),
                    jnp.asarray(take, jnp.int32),
                    bucket_capacity(max(take, 128))))
                off += take

        def build_split():
            def n_complete(piece):
                keys = [e.eval_device(piece) for e in part_exprs]
                live = piece.row_mask()
                last = jnp.clip(piece.n_rows - 1, 0, piece.capacity - 1)
                eq_last = live
                for k in keys:
                    if k.is_string:
                        if k.is_dict:
                            same = k.codes == k.codes[last]
                        else:
                            from ..ops.strings_util import char_matrix
                            m = char_matrix(k)
                            same = jnp.all(m == m[last], axis=1)
                        vsame = k.validity == k.validity[last]
                        eq_last = eq_last & vsame & \
                            jnp.where(k.validity[last], same, True)
                    else:
                        vsame = k.validity == k.validity[last]
                        dsame = k.data == k.data[last]
                        eq_last = eq_last & vsame & \
                            jnp.where(k.validity[last], dsame, True)
                return piece.n_rows - jnp.sum(eq_last.astype(jnp.int32))
            return n_complete
        n_complete_k = cached_kernel(
            "window_chunk_split", kernel_key(list(part_exprs)), build_split)

        carry = None
        chunks = iter(sorter.sorted_chunks())
        chunk = next(chunks, None)
        while chunk is not None:
            piece = _coalesce_device([carry, chunk]) if carry is not None \
                else chunk
            nxt = next(chunks, None)
            if nxt is None:
                yield piece
                return
            n_c = int(jax.device_get(n_complete_k(piece)))
            total = int(jax.device_get(piece.n_rows))
            if n_c > 0:
                from ..data.column import bucket_capacity
                head = slice_k(piece, jnp.asarray(0, jnp.int32),
                               jnp.asarray(n_c, jnp.int32),
                               bucket_capacity(max(n_c, 128)))
                yield head
                rest = total - n_c
                carry = slice_k(piece, jnp.asarray(n_c, jnp.int32),
                                jnp.asarray(rest, jnp.int32),
                                bucket_capacity(max(rest, 128)))
            else:
                carry = piece
            chunk = nxt
        if carry is not None:
            yield carry
    finally:
        sorter.release()


def _eval_window(batch: ColumnarBatch, func: Expression,
                 part: List[Expression],
                 orders: List[Tuple[Expression, bool, bool]],
                 frame: W.WindowFrame):
    cap = batch.capacity
    n_rows = batch.n_rows
    iota = jnp.arange(cap, dtype=jnp.int32)
    live = iota < n_rows

    part_cols = [e.eval_device(batch) for e in part]
    order_cols = [e.eval_device(batch) for e, _, _ in orders]
    keys = part_cols + order_cols
    if keys:
        asc = [True] * len(part_cols) + [a for _, a, _ in orders]
        nf = [True] * len(part_cols) + [n for _, _, n in orders]
        perm = KR.sort_permutation(keys, n_rows, asc, nf)
    else:
        perm = iota

    sorted_parts = [KR.gather_column(c, perm) for c in part_cols]
    sorted_orders = [KR.gather_column(c, perm) for c in order_cols]
    new_seg = KW.change_flags(sorted_parts, cap)
    seg_start, seg_end = KW.run_bounds(new_seg, n_rows)
    new_peer = KW.change_flags(sorted_parts + sorted_orders, cap)
    peer_start, peer_end = KW.run_bounds(new_peer, n_rows)

    # -- ranking functions (frame-independent) ------------------------------
    if isinstance(func, W.RowNumber):
        res = iota - seg_start + 1
        return _scatter(res.astype(jnp.int32), live, perm, cap, T.INT)
    if isinstance(func, W.Rank):
        res = peer_start - seg_start + 1
        return _scatter(res.astype(jnp.int32), live, perm, cap, T.INT)
    if isinstance(func, W.DenseRank):
        ps = KW.exclusive_prefix((new_peer & live).astype(jnp.int32))
        res = ps[iota + 1] - ps[seg_start]
        return _scatter(res.astype(jnp.int32), live, perm, cap, T.INT)

    # -- frame bounds -------------------------------------------------------
    lo, hi = _frame_bounds(frame, iota, seg_start, seg_end, peer_start,
                           peer_end, sorted_orders, orders)

    # -- windowed aggregates ------------------------------------------------
    assert isinstance(func, W.WINDOW_AGG_TYPES), type(func)
    child = func.children[0].eval_device(batch) if func.children else None
    sv = KR.gather_column(child, perm) if child is not None else None

    if sv is not None:
        cnt_ps = KW.exclusive_prefix(sv.validity.astype(jnp.int64))
        cnt = KW.range_sum(cnt_ps, lo, hi)
    else:
        cnt = (hi - lo).astype(jnp.int64)

    if isinstance(func, AGG.Count):
        return _scatter(cnt, live, perm, cap, T.LONG)
    if isinstance(func, AGG.Sum):
        acc = func.data_type  # LONG or DOUBLE (Spark's sum widening)
        vals = jnp.where(sv.validity, sv.data, 0).astype(acc.np_dtype)
        s = KW.range_sum(KW.exclusive_prefix(vals), lo, hi)
        return _scatter(s, live & (cnt > 0), perm, cap, acc)
    if isinstance(func, AGG.Average):
        vals = jnp.where(sv.validity, sv.data, 0).astype(jnp.float64)
        s = KW.range_sum(KW.exclusive_prefix(vals), lo, hi)
        avg = s / jnp.maximum(cnt, 1).astype(jnp.float64)
        return _scatter(avg, live & (cnt > 0), perm, cap, T.DOUBLE)
    # Min / Max over the canonical total order (int64): NaN ranks greatest
    # and -0.0 == 0.0, matching Spark instead of jnp.minimum's NaN poison.
    is_min = isinstance(func, AGG.Min)
    dtype = func.data_type
    if dtype is T.STRING:
        # Strings: rank every row's string (sorted-dictionary codes are the
        # rank already; otherwise one char-matrix sort + inversion), then
        # min/max the packed (rank, row) key over the frame and gather the
        # winning row's string — layout-preserving, so dictionary columns
        # stay dictionary columns.
        if sv.is_dict and sv.dict_sorted:
            rank = sv.codes.astype(jnp.int64)
        else:
            ops = KR.string_sort_keys(sv)
            s = jax.lax.sort(tuple(ops) + (iota,), num_keys=len(ops),
                             is_stable=True)
            _, rank32 = jax.lax.sort((s[-1], iota), num_keys=1,
                                     is_stable=True)
            rank = rank32.astype(jnp.int64)
        packed = rank * cap + iota.astype(jnp.int64)
        info = jnp.iinfo(jnp.int64)
        neutral = jnp.int64(info.max if is_min else info.min)
        masked = jnp.where(sv.validity, packed, neutral)
        mm = KW.range_min_max(KW.sparse_table(masked, is_min), lo, hi,
                              is_min)
        valid_sorted = live & (cnt > 0)
        win_row = jnp.where(valid_sorted, (mm % cap).astype(jnp.int32), 0)
        win_orig = jnp.zeros(cap, jnp.int32).at[perm].set(win_row)
        valid = jnp.zeros(cap, jnp.bool_).at[perm].set(valid_sorted)
        out = KR.gather_column(sv, win_orig)
        return DeviceColumn(data=out.data, validity=valid, dtype=T.STRING,
                            offsets=out.offsets, max_bytes=out.max_bytes,
                            codes=out.codes, dict_sorted=out.dict_sorted)
    keys = KR.orderable_values(sv.data, dtype.is_floating)
    info = jnp.iinfo(jnp.int64)
    neutral = jnp.int64(info.max if is_min else info.min)
    masked = jnp.where(sv.validity, keys, neutral)
    mm_key = KW.range_min_max(KW.sparse_table(masked, is_min), lo, hi, is_min)
    mm = KW.from_total_order(mm_key, dtype)
    return _scatter(mm, live & (cnt > 0), perm, cap, dtype)


def _frame_bounds(frame: W.WindowFrame, iota, seg_start, seg_end,
                  peer_start, peer_end, sorted_orders, orders):
    if frame.frame_type == "rows":
        lo = seg_start if frame.lower.kind == "unbounded" else \
            jnp.clip(iota + frame.lower.offset
                     if frame.lower.kind == "offset" else iota,
                     seg_start, seg_end)
        hi = seg_end if frame.upper.kind == "unbounded" else \
            jnp.clip((iota + frame.upper.offset
                      if frame.upper.kind == "offset" else iota) + 1,
                     seg_start, seg_end)
        return lo, jnp.maximum(hi, lo)

    # RANGE frame. current/unbounded bounds are peer-run boundaries; literal
    # offsets need the single order key and a per-row binary search.
    need_search = frame.lower.kind == "offset" or frame.upper.kind == "offset"
    if need_search:
        assert len(sorted_orders) == 1, \
            "range frame with offsets requires exactly one order-by key"
        oc = sorted_orders[0]
        _, asc, nf = orders[0]
        bucket, key, raw, floating = KW.order_key_arrays(oc, asc, nf)

    def one(bound: W.Bound, is_lower: bool):
        if bound.kind == "unbounded":
            return seg_start if is_lower else seg_end
        if bound.kind == "current":
            return peer_start if is_lower else peer_end
        delta = bound.offset if asc else -bound.offset
        t_raw = KW.saturating_offset(raw, delta, floating)
        t_key = KW.transform_target(t_raw, floating, asc)
        # Null order values keep their own (bucket, key): their frame is the
        # null peer run, matching Spark's null-range semantics.
        t_key = jnp.where(oc.validity, t_key, key)
        return KW.seg_search(bucket, key, bucket, t_key, seg_start, seg_end,
                             left=is_lower)

    lo = one(frame.lower, True)
    hi = one(frame.upper, False)
    return lo, jnp.maximum(hi, lo)


def _scatter(data_sorted, valid_sorted, perm, cap, dtype: T.DataType):
    """Scatter sorted-space results back to original row order."""
    data = jnp.zeros(cap, data_sorted.dtype).at[perm].set(data_sorted)
    valid = jnp.zeros(cap, jnp.bool_).at[perm].set(valid_sorted)
    data = jnp.where(valid, data, jnp.zeros((), data.dtype))
    return DeviceColumn(data=data.astype(dtype.np_dtype), validity=valid,
                        dtype=dtype)
