"""Device-side CSV parse — the ``GpuBatchScanExec`` CSV analog.

The reference parses CSV on the GPU (``GpuBatchScanExec.scala:87`` ->
cudf's csv reader). The TPU-native split mirrors the parquet/ORC
decoders' contract:

* HOST (structure-sized work): one vectorized numpy pass finds line and
  field boundaries — newline/delimiter positions via ``np.where``, the
  k-th delimiter of each line via ``searchsorted`` — WITHOUT converting
  a single value.
* DEVICE (data-sized work): the raw file bytes upload ONCE; one traced
  kernel gathers each column's byte matrix from the boundary tables and
  runs the digit DP — sign fold, mantissa accumulation, decimal-point
  split — producing value + validity lanes. String columns gather their
  char matrix from the same buffer (no second host pass).

Correct-rounding note: doubles parse as integer mantissa m and decimal
exponent f, finished as ``m / 10^f`` in float64. That division is
correctly rounded whenever both operands are exact (m <= 15 digits,
f <= 22), which makes it bit-identical to strtod/pyarrow on that range;
anything wider trips the kernel's ``bad`` flag and the FILE falls back
to the host pyarrow reader (per-file graceful degradation, like the
per-stripe/rowgroup fallback of the other decoders). The same flag
catches malformed digits, exponent notation, inf/nan spellings, and
int64 overflow risk (>18 digits) — the device never guesses.

Out of scope (host fallback): quoted fields (quote char anywhere in the
file), custom nullValue tokens, escape chars, non-UTF-8, types beyond
int8/16/32/64, float/double, boolean, string.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..data.batch import ColumnarBatch
from ..data.column import (DeviceColumn, bucket_byte_capacity,
                           bucket_capacity)
from ..utils.kernel_cache import cached_kernel
from ..utils.tracing import trace_range


class NotCsvDecodable(Exception):
    """File outside the device parser's scope; caller reads it host-side."""


_INT_TYPES = ("bigint", "int", "smallint", "tinyint")
_SUPPORTED = set(_INT_TYPES) | {"double", "float", "boolean", "string"}


def scan_files(paths: List[str]) -> List[str]:
    out = []
    for p in paths:
        if os.path.isdir(p):
            for root, _, files in os.walk(p):
                out.extend(os.path.join(root, fn) for fn in sorted(files)
                           if fn.endswith(".csv"))
        elif p.endswith(".csv"):
            out.append(p)
        else:
            return []
    return sorted(out)


def device_decodable(schema: T.Schema, options: dict) -> bool:
    """Static (pre-data) scope check; data-dependent hazards (quotes,
    overlong numbers) fall back per file at decode time."""
    if any(f.data_type.name not in _SUPPORTED for f in schema):
        return False
    if "nullValue" in options or options.get("escape"):
        return False
    return True


# ---------------------------------------------------------------------------
# Host: vectorized boundary finding
# ---------------------------------------------------------------------------


def _boundaries(buf: np.ndarray, delim: int, n_cols: int,
                header: bool) -> Tuple[np.ndarray, np.ndarray]:
    """(field_starts [n, C], field_ends [n, C]) — one vectorized pass;
    raises NotCsvDecodable on ragged lines."""
    n_bytes = len(buf)
    if n_bytes == 0:
        return (np.zeros((0, n_cols), np.int64),
                np.zeros((0, n_cols), np.int64))
    nl = np.nonzero(buf == 10)[0]
    line_starts = np.concatenate(([0], nl + 1))
    line_ends = np.concatenate((nl, [n_bytes]))
    # CRLF: trim the \r BEFORE the empty-line filter, so a blank "\r\n"
    # line is recognized as empty (pyarrow skips it; a post-trim check
    # would let it through as a spurious null row).
    crlf = (line_ends > line_starts) \
        & (buf[np.maximum(line_ends - 1, 0)] == 13)
    line_ends = line_ends - crlf.astype(np.int64)
    # Drop the phantom line after a trailing newline (and any empty lines
    # — Spark/pyarrow skip fully empty lines).
    live = line_starts < line_ends
    line_starts = line_starts[live]
    line_ends = line_ends[live]
    if header:
        line_starts, line_ends = line_starts[1:], line_ends[1:]
    n = len(line_starts)
    if n == 0:
        return (np.zeros((0, n_cols), np.int64),
                np.zeros((0, n_cols), np.int64))
    dpos = np.nonzero(buf == delim)[0]
    first = np.searchsorted(dpos, line_starts)
    after = np.searchsorted(dpos, line_ends)
    if not ((after - first) == (n_cols - 1)).all():
        raise NotCsvDecodable("ragged rows (field count != schema)")
    starts = np.empty((n, n_cols), np.int64)
    ends = np.empty((n, n_cols), np.int64)
    starts[:, 0] = line_starts
    for j in range(1, n_cols):
        d = dpos[first + (j - 1)]
        ends[:, j - 1] = d
        starts[:, j] = d + 1
    ends[:, n_cols - 1] = line_ends
    return starts, ends


# ---------------------------------------------------------------------------
# Device: the digit DP
# ---------------------------------------------------------------------------


def _build_parse_kernel(dtypes: Tuple[str, ...], widths: Tuple[int, ...],
                        cap: int):
    def parse_int(mat, lens, w):
        neg = mat[:, 0] == 45
        plus = mat[:, 0] == 43
        skip = (neg | plus).astype(jnp.int32)
        col_idx = jnp.arange(w, dtype=jnp.int32)[None, :]
        in_field = col_idx < lens[:, None]
        digit_pos = in_field & (col_idx >= skip[:, None])
        d = mat - 48
        bad_char = digit_pos & ((d < 0) | (d > 9))
        ndig = lens - skip
        has = lens > 0
        bad = (bad_char.any(axis=1) | (has & (ndig <= 0))
               | (has & (ndig > 18)))
        v = jnp.zeros(mat.shape[0], jnp.int64)
        for k in range(w):
            v = jnp.where(digit_pos[:, k], v * 10 + d[:, k].astype(jnp.int64),
                          v)
        v = jnp.where(neg, -v, v)
        return v, has, bad, ndig

    def parse_double(mat, lens, w):
        neg = mat[:, 0] == 45
        plus = mat[:, 0] == 43
        skip = (neg | plus).astype(jnp.int32)
        col_idx = jnp.arange(w, dtype=jnp.int32)[None, :]
        in_field = col_idx < lens[:, None]
        body = in_field & (col_idx >= skip[:, None])
        is_dot = body & (mat == 46)
        d = mat - 48
        is_digit = body & (d >= 0) & (d <= 9)
        bad_char = body & ~is_digit & ~is_dot
        ndots = is_dot.sum(axis=1)
        has = lens > 0
        ndig = is_digit.sum(axis=1)
        # f = digits after the dot
        dot_rel = jnp.where(is_dot.any(axis=1),
                            jnp.argmax(is_dot, axis=1), 0)
        frac = jnp.where(is_dot.any(axis=1),
                         (lens - 1 - dot_rel).astype(jnp.int32), 0)
        bad = (bad_char.any(axis=1) | (ndots > 1) | (has & (ndig <= 0))
               | (ndig > 15) | (frac > 22) | (frac < 0))
        m = jnp.zeros(mat.shape[0], jnp.int64)
        for k in range(w):
            m = jnp.where(is_digit[:, k], m * 10 + d[:, k].astype(jnp.int64),
                          m)
        pow10 = jnp.asarray([10.0 ** i for i in range(23)], jnp.float64)
        v = m.astype(jnp.float64) / pow10[jnp.clip(frac, 0, 22)]
        v = jnp.where(neg, -v, v)
        return v, has, bad.any()

    def parse_bool(mat, lens, w):
        """Exactly pyarrow's accepted spellings: true/True/TRUE,
        false/False/FALSE, 1, 0 — anything else trips ``bad`` so the file
        falls back instead of guessing ('tree' is not true)."""
        has = lens > 0

        def word(token: bytes):
            tl = len(token)
            if w < tl:
                return jnp.zeros(mat.shape[0], jnp.bool_)
            folded_ok = jnp.ones(mat.shape[0], jnp.bool_)
            all_lower = jnp.ones(mat.shape[0], jnp.bool_)
            all_upper = jnp.ones(mat.shape[0], jnp.bool_)
            title = jnp.ones(mat.shape[0], jnp.bool_)
            for k, ch in enumerate(token):
                b = mat[:, k]
                folded_ok &= (b | 0x20) == ch
                all_lower &= b == ch
                all_upper &= b == (ch - 32)
                title &= b == (ch - 32 if k == 0 else ch)
            case_ok = all_lower | all_upper | title
            return (lens == tl) & folded_ok & case_ok

        t = word(b"true") | ((lens == 1) & (mat[:, 0] == 49))    # '1'
        f = word(b"false") | ((lens == 1) & (mat[:, 0] == 48))   # '0'
        bad = (has & ~(t | f)).any()
        return t, has, bad

    def run(buf, starts, ends, n_rows):
        live = jnp.arange(cap, dtype=jnp.int32) < n_rows
        out = []
        bads = []
        nb = buf.shape[0]
        for j, (tn, w) in enumerate(zip(dtypes, widths)):
            s = starts[:, j]
            lens = jnp.where(live, (ends[:, j] - s).astype(jnp.int32), 0)
            pos = s[:, None] + jnp.arange(w, dtype=jnp.int64)[None, :]
            in_field = jnp.arange(w, dtype=jnp.int32)[None, :] < lens[:, None]
            mat = jnp.where(
                in_field,
                buf[jnp.clip(pos, 0, nb - 1)].astype(jnp.int32), -1)
            if tn in _INT_TYPES:
                v, has, badv, _ = parse_int(mat, lens, w)
                if tn != "bigint":
                    info = jnp.iinfo(T.type_by_name(tn).np_dtype)
                    badv = badv | (has & ((v > info.max) | (v < info.min)))
                bad = badv.any()
            elif tn in ("double", "float"):
                v, has, bad = parse_double(mat, lens, w)
            elif tn == "boolean":
                v, has, bad = parse_bool(mat, lens, w)
            else:                               # string: char matrix
                out.append((jnp.where(in_field, mat, -1).astype(jnp.int16),
                            lens, live))
                bads.append(jnp.asarray(False))
                continue
            validity = live & has
            out.append((jnp.where(validity, v, 0), validity, None))
            bads.append(bad)
        return tuple(out), jnp.stack(bads).any()

    return lambda: run


def decode_file(path: str, schema: T.Schema, options: dict,
                max_rows: int = 1 << 20):
    """Yield ColumnarBatches parsed on device; NotCsvDecodable when the
    file's DATA is out of scope (quotes, overlong numbers, ragged rows)."""
    buf = np.fromfile(path, dtype=np.uint8)
    q_opt = options.get("quote", '"')
    if q_opt not in (False, None, ""):
        # Quoting disabled (quote=False, pyarrow-style) needs no check.
        quote = ord(str(q_opt))
        if len(buf) and (buf == quote).any():
            raise NotCsvDecodable("quoted fields")
    delim = ord(str(options.get("delimiter", ",")))
    header = bool(options.get("header", True))
    starts, ends = _boundaries(buf, delim, len(schema), header)
    n = len(starts)
    dev_buf = jax.device_put(buf if len(buf) else np.zeros(1, np.uint8))
    if n == 0:
        yield _decode_slice(dev_buf, starts, ends, schema)
        return
    for lo in range(0, n, max_rows):
        hi = min(lo + max_rows, n)
        yield _decode_slice(dev_buf, starts[lo:hi], ends[lo:hi], schema)


def _decode_slice(dev_buf, starts: np.ndarray, ends: np.ndarray,
                  schema: T.Schema) -> ColumnarBatch:
    n = len(starts)
    cap = bucket_capacity(n)
    widths = tuple(
        int(bucket_byte_capacity(int((ends[:, j] - starts[:, j]).max())
                            if n else 1, 8))
        for j in range(len(schema)))
    dtypes = tuple(f.data_type.name for f in schema)
    s_pad = np.zeros((cap, len(schema)), np.int64)
    e_pad = np.zeros((cap, len(schema)), np.int64)
    s_pad[:n] = starts
    e_pad[:n] = ends
    kern = cached_kernel("csv_device.parse", (dtypes, widths, cap),
                         _build_parse_kernel(dtypes, widths, cap))
    with trace_range("csv.device_parse"):
        outs, bad = kern(dev_buf, jnp.asarray(s_pad), jnp.asarray(e_pad),
                         jnp.asarray(n, jnp.int32))
    if bool(bad):   # one scalar sync per batch
        raise NotCsvDecodable("value outside the digit DP's exact range")
    cols = []
    for f, payload in zip(schema, outs):
        if f.data_type is T.STRING:
            from ..ops.kernels.rowops import strings_from_matrix
            mat, lens, live = payload
            col = strings_from_matrix(mat, live, mat.shape[1])
            cols.append(col)
        else:
            v, validity, _ = payload
            np_dt = f.data_type.np_dtype
            cols.append(DeviceColumn(
                data=jnp.asarray(v).astype(np_dt),
                validity=validity, dtype=f.data_type))
    return ColumnarBatch(tuple(cols), jnp.asarray(n, jnp.int32), schema)


class TpuCsvScanExec:
    """Device CSV scan; per-FILE fallback to the host pyarrow reader."""

    columnar = True
    children = ()
    children_coalesce_goals = None

    def __init__(self, files: List[str], schema: T.Schema, options: dict):
        self.files = list(files)
        self._schema = schema
        self.options = dict(options)

    @property
    def schema(self):
        return self._schema

    def node_name(self):
        return "TpuCsvScanExec"

    def describe(self):
        return f"TpuCsvScan files={len(self.files)}"

    def tree_string(self, indent: int = 0) -> str:
        return "  " * indent + self.describe() + "\n"

    def with_children(self, children):
        assert not children
        return self

    def execute(self, ctx):
        name = self.node_name()

        def read_file(path):
            from ..memory.retry import Classification, classify
            from ..utils.fault_injection import maybe_inject
            try:
                maybe_inject(ctx, "io.csv.file")
                with ctx.registry.timer(name, "opTime",
                                        trace="csv.decode_file"):
                    return list(decode_file(path, self._schema,
                                            self.options))
            except Exception as e:  # noqa: BLE001 - classify-narrowed
                # Out-of-scope files (NotCsvDecodable) and classified
                # device faults fall back to the host reader per file;
                # parser-logic bugs still fail loudly.
                if not isinstance(e, NotCsvDecodable) \
                        and classify(e) == Classification.FATAL:
                    raise
                ctx.metric(name, "fileHostFallback", 1)
                return self._host_file(path)

        # Files decode ahead on the shared pipeline pool (bounded by
        # decodeThreads/prefetchDepth), yielding in file order; with the
        # pipeline off, the serial stream keeps its depth-2 prefetch
        # worker (pre-pipeline behavior).
        from ..exec import pipeline

        def gen():
            for batches in pipeline.ordered_map_iter(
                    read_file, self.files, ctx, name):
                for b in batches:
                    ctx.metric(name, "numOutputBatches", 1)
                    yield b
        if pipeline.parallel_active(ctx):
            return [gen()]
        from ..utils.prefetch import prefetch_iter
        return [prefetch_iter(gen(), ctx=ctx, node=name)]

    def _host_file(self, path: str) -> List[ColumnarBatch]:
        import pyarrow as pa
        from .files import _dataset
        table = _dataset("csv", [path], self.options).to_table()
        arrow_schema = T.schema_to_arrow(self._schema)
        table = table.select([f.name for f in self._schema]) \
            .cast(arrow_schema)
        if table.num_rows == 0:
            rb = pa.RecordBatch.from_arrays(
                [pa.array([], type=f.type) for f in arrow_schema],
                schema=arrow_schema)
            return [ColumnarBatch.from_arrow(rb)]
        return [ColumnarBatch.from_arrow(rb)
                for rb in table.combine_chunks().to_batches()]
