"""File scans — Parquet/ORC/CSV readers (host decode milestone).

The reference reads files in two stages: CPU-side footer/stripe selection and
byte assembly, then device-side decode via cudf (GpuParquetScan.scala:314 —
readPartFile rebuilds a mini parquet file in host memory, then
Table.readParquet decodes on GPU). The TPU analog of stage two (device decode
kernels for RLE/dictionary/bitpack leaves) is a later milestone (SURVEY.md §7
hard parts); this module implements stage one with pyarrow: predicate
pushdown, column pruning, and row-group-granular chunked reads honoring
``spark.rapids.sql.reader.batchSizeRows/Bytes``.
"""

from __future__ import annotations

import os
from typing import List, Optional

import pyarrow as pa
import pyarrow.dataset as ds

from .. import types as T
from ..config import MAX_READ_BATCH_SIZE_BYTES, MAX_READ_BATCH_SIZE_ROWS
from ..data.batch import HostBatch
from ..ops import predicates as PRED
from ..ops.expression import AttributeReference, Expression, Literal
from ..plan.physical import PhysicalPlan


def infer_schema(fmt: str, paths: List[str], options: dict) -> T.Schema:
    dataset = _dataset(fmt, paths, options)
    return T.schema_from_arrow(dataset.schema)


def _dataset(fmt: str, paths: List[str], options: dict) -> ds.Dataset:
    # A single directory (a write target) must pass as a bare string;
    # pyarrow rejects directories inside path lists. Default ignore_prefixes
    # skip _SUCCESS and hidden files, like Spark's readers. hive partitioning
    # restores partitionBy columns from key=value directory names.
    src = paths[0] if len(paths) == 1 else paths
    hive = "hive" if len(paths) == 1 and os.path.isdir(paths[0]) else None
    if fmt == "parquet":
        return ds.dataset(src, format="parquet", partitioning=hive)
    if fmt == "orc":
        return ds.dataset(src, format="orc", partitioning=hive)
    if fmt == "csv":
        import pyarrow.csv as pacsv
        _validate_csv_options(options)
        parse = pacsv.ParseOptions(
            delimiter=options.get("delimiter", ","),
            quote_char=options.get("quote", '"'),
            escape_char=options.get("escape", False) or False)
        read = pacsv.ReadOptions()
        # Spark treats empty fields as null ALWAYS, plus the custom
        # nullValue when given (which nulls string cells too — pyarrow
        # needs the explicit opt-in for that).
        convert = pacsv.ConvertOptions(
            null_values=["", options["nullValue"]]
            if "nullValue" in options else [""],
            strings_can_be_null="nullValue" in options)
        if not options.get("header", True):
            read = pacsv.ReadOptions(autogenerate_column_names=True)
        fmt_obj = ds.CsvFileFormat(parse_options=parse,
                                   read_options=read,
                                   convert_options=convert)
        # hive partitioning here too: a partitionBy CSV write read back
        # through this reader must restore the partition columns rather
        # than silently dropping them.
        return ds.dataset(src, format=fmt_obj, partitioning=hive)
    raise ValueError(f"unknown format {fmt}")


def _validate_csv_options(options: dict) -> None:
    """CSV option gates (GpuCSVScan object:87 validates the same surface:
    single-char delimiter distinct from quote/newline, no multiLine, UTF-8
    only; unsupported combinations fail loudly instead of misparsing)."""
    delim = str(options.get("delimiter", ","))
    if len(delim) != 1:
        raise ValueError(f"CSV delimiter must be a single character, "
                         f"got {delim!r}")
    if delim in ("\n", "\r", '"'):
        raise ValueError(f"unsupported CSV delimiter {delim!r}")
    quote = str(options.get("quote", '"'))
    if len(quote) != 1:
        raise ValueError(f"CSV quote must be a single character, "
                         f"got {quote!r}")
    if quote == delim:
        raise ValueError("CSV quote and delimiter must differ")
    if str(options.get("multiLine", "false")).lower() == "true":
        raise ValueError("multiLine CSV is not supported "
                         "(reference GpuCSVScan rejects it too)")
    charset = str(options.get("charset", options.get("encoding", "UTF-8")))
    if charset.upper().replace("-", "") not in ("UTF8",):
        raise ValueError(f"unsupported CSV charset {charset} (UTF-8 only)")
    esc = options.get("escape")
    if esc is not None and len(str(esc)) != 1:
        raise ValueError(f"CSV escape must be a single character, got {esc!r}")


def to_arrow_filter(expr: Expression) -> Optional[ds.Expression]:
    """Best-effort conversion of a pushed filter to a pyarrow dataset filter
    (the ParquetFilters predicate-pushdown analog, GpuParquetScan.scala:290)."""
    import pyarrow.compute as pc
    try:
        if isinstance(expr, PRED.And):
            l = to_arrow_filter(expr.children[0])
            r = to_arrow_filter(expr.children[1])
            if l is not None and r is not None:
                return l & r
            return l if r is None else r
        if isinstance(expr, PRED.Or):
            l = to_arrow_filter(expr.children[0])
            r = to_arrow_filter(expr.children[1])
            return (l | r) if l is not None and r is not None else None
        if isinstance(expr, PRED.Comparison):
            left, right = expr.children
            if isinstance(left, AttributeReference) and isinstance(right, Literal):
                f = pc.field(left._name)
                v = right.value
                op = {"equal": f.__eq__, "not_equal": f.__ne__,
                      "less": f.__lt__, "less_equal": f.__le__,
                      "greater": f.__gt__, "greater_equal": f.__ge__}[expr.op]
                return op(v)
        if isinstance(expr, PRED.IsNotNull) and isinstance(
                expr.children[0], AttributeReference):
            return ~pc.field(expr.children[0]._name).is_null()
        if isinstance(expr, PRED.IsNull) and isinstance(
                expr.children[0], AttributeReference):
            return pc.field(expr.children[0]._name).is_null()
    except Exception:
        return None
    return None


class CpuFileScanExec(PhysicalPlan):
    """Host file scan; one partition per input fragment (file/row-group
    cluster), chunked by reader batch-size limits."""

    def __init__(self, fmt: str, paths: List[str], schema: T.Schema,
                 options: dict, pushed_filters: List[Expression],
                 emit_file_meta: bool = False):
        self.fmt = fmt
        self.paths = paths
        self._schema = schema
        self.options = options
        self.pushed_filters = pushed_filters
        #: emit the hidden __input_file_* metadata columns (set by the
        #: input_file_name() rewrite, plan/input_file.py); the columns are
        #: part of ``schema`` but synthesized per fragment, not read.
        self.emit_file_meta = emit_file_meta

    @property
    def schema(self):
        return self._schema

    def describe(self):
        return f"CpuFileScan {self.fmt} {self.paths}"

    def execute(self, ctx):
        import pyarrow as pa_mod
        dataset = _dataset(self.fmt, self.paths, self.options)
        arrow_schema = T.schema_to_arrow(self._schema)
        meta_names = ()
        if self.emit_file_meta:
            from ..plan.input_file import (FILE_LENGTH_COL, FILE_NAME_COL,
                                           FILE_START_COL)
            meta_names = (FILE_NAME_COL, FILE_START_COL, FILE_LENGTH_COL)
        names = [f.name for f in arrow_schema if f.name not in meta_names]
        filt = None
        for f in self.pushed_filters:
            af = to_arrow_filter(f)
            if af is not None:
                filt = af if filt is None else (filt & af)
        max_rows = ctx.conf.get(MAX_READ_BATCH_SIZE_ROWS)
        fragments = list(dataset.get_fragments())

        def read_fragment(frag):
            # dataset.schema carries hive partition fields; passing it lets
            # the fragment materialize partition columns from its
            # partition_expression.
            scanner = ds.Scanner.from_fragment(
                frag, schema=dataset.schema, columns=names, filter=filt,
                batch_size=max_rows)
            meta_present = [f.name for f in arrow_schema
                            if f.name in meta_names]
            if meta_present:
                # Whole-file fragments: the split is the file, so block
                # start is 0 and block length the file size (the reference
                # reports the Hadoop split, GpuInputFileBlock.scala:114).
                path = getattr(frag, "path", "") or ""
                try:
                    import os
                    size = os.path.getsize(path)
                except OSError:
                    size = -1
                meta_value = {meta_names[0]: (path, pa_mod.string()),
                              meta_names[1]: (0, pa_mod.int64()),
                              meta_names[2]: (size, pa_mod.int64())}
            data_schema = pa_mod.schema(
                [f for f in arrow_schema if f.name not in meta_names])
            for rb in scanner.to_batches():
                if not rb.num_rows:
                    continue
                rb = rb.cast(data_schema)
                if meta_present:
                    n = rb.num_rows
                    by_name = {f.name: c for f, c in zip(data_schema,
                                                         rb.columns)}
                    arrays = []
                    for f in arrow_schema:
                        if f.name in meta_value:
                            v, t = meta_value[f.name]
                            arrays.append(pa_mod.array([v] * n, t))
                        else:
                            arrays.append(by_name[f.name])
                    rb = pa_mod.RecordBatch.from_arrays(
                        arrays, schema=arrow_schema)
                yield HostBatch(rb)
        if not fragments:
            return [iter([])]
        return [read_fragment(f) for f in fragments]
