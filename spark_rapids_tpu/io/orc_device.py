"""ORC device scan — stripe streams decoded through device run tables.

The reference reassembles ORC stripes host-side and device-decodes them
with cudf (``GpuOrcScan.scala:65,211``). The TPU-native split mirrors the
parquet decoder (:mod:`.parquet_device`): the host parses the protobuf
tail + stripe footers and the RLEv2 RUN HEADERS into compact run tables
(a few ints per run), and a jitted device kernel expands runs to row
space, scatters non-null slots through the PRESENT bitmask, and gathers
dictionary codes — the memory-proportional work stays on the device.

Scope (everything else falls back per stripe to a host pyarrow read, the
reference's graceful degradation):

* flat struct schemas,
* SHORT/INT/LONG/DATE via RLEv2 (short-repeat, direct, delta,
  patched-base), decoded as run tables: ``const``/``linear`` runs expand
  arithmetically on device, ``direct`` runs gather host-unpacked values,
* FLOAT/DOUBLE plain streams (uploaded, slot-scattered on device),
* STRING in DIRECT_V2 (lengths RLEv2 + blob -> host dictionary build,
  codes upload) and DICTIONARY_V2 (codes RLEv2 expand ON DEVICE against
  the uploaded dictionary),
* PRESENT byte-RLE (host-decoded to a packed bitmask; bits expand on
  device),
* NONE / ZLIB / SNAPPY / ZSTD block compression.
"""

from __future__ import annotations

import dataclasses
import functools
import os
import struct
import zlib
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from ..utils import lockdep

from .. import types as T
from ..data.batch import ColumnarBatch
from ..data.column import (DeviceColumn, bucket_byte_capacity,
                           bucket_capacity)
from ..utils.kernel_cache import cached_kernel
from ..utils.tracing import trace_range

MAGIC = b"ORC"

#: ORC type kinds (Types.proto)
_K_BOOL, _K_BYTE, _K_SHORT, _K_INT, _K_LONG = 0, 1, 2, 3, 4
_K_FLOAT, _K_DOUBLE, _K_STRING, _K_DATE, _K_STRUCT = 5, 6, 7, 15, 12
#: stream kinds
_S_PRESENT, _S_DATA, _S_LENGTH, _S_DICT = 0, 1, 2, 3
#: column encodings
_E_DIRECT, _E_DICT, _E_DIRECT_V2, _E_DICT_V2 = 0, 1, 2, 3

#: decode-path observability (tests assert rare encodings were exercised).
#: Incremented from DECODE WORKERS (the readers run stripes through
#: ordered_map_iter, exec/pipeline.py), so the bump must hold the lock —
#: an unlocked `+=` from concurrent workers loses updates (found by the
#: unguarded-shared-write pass, analysis/concurrency.py; regression:
#: tests/test_lockdep.py::TestOrcDecodeStats).
decode_stats = {"patched_base_runs": 0}
_STATS_LOCK = lockdep.lock("orc_device._STATS_LOCK")

#: RLEv2 5-bit width-code table (ORC spec "Closest fixed bit sizes").
_WIDTH_TABLE = [1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15, 16,
                17, 18, 19, 20, 21, 22, 23, 24, 26, 28, 30, 32, 40, 48,
                56, 64]


class NotOrcDecodable(Exception):
    pass


def _parse_boundary(fn):
    """Malformed/truncated input makes the hand-rolled parsers raise bare
    IndexError/ValueError/KeyError; translate those to NotOrcDecodable at
    the parser boundary so decode_stripe's fallback catch can stay
    narrow (decoder-logic regressions elsewhere still fail loudly)."""
    @functools.wraps(fn)
    def wrap(*a, **kw):
        try:
            return fn(*a, **kw)
        except (IndexError, ValueError, KeyError, struct.error) as e:
            raise NotOrcDecodable(f"{fn.__name__}: {e!r}") from e
    return wrap


# ---------------------------------------------------------------------------
# protobuf + file tail
# ---------------------------------------------------------------------------


@_parse_boundary
def _proto_fields(b: bytes) -> List[Tuple[int, int, object]]:
    out, i, n = [], 0, len(b)
    while i < n:
        tag = b[i]
        i += 1
        f, wt = tag >> 3, tag & 7
        if wt == 0:
            v, s = 0, 0
            while True:
                x = b[i]
                i += 1
                v |= (x & 0x7F) << s
                s += 7
                if not x & 0x80:
                    break
            out.append((f, wt, v))
        elif wt == 2:
            ln, s = 0, 0
            while True:
                x = b[i]
                i += 1
                ln |= (x & 0x7F) << s
                s += 7
                if not x & 0x80:
                    break
            out.append((f, wt, b[i:i + ln]))
            i += ln
        else:
            raise NotOrcDecodable(f"protobuf wire type {wt}")
    return out


@dataclasses.dataclass
class StripeInfo:
    offset: int
    index_length: int
    data_length: int
    footer_length: int
    n_rows: int


@dataclasses.dataclass
class OrcTail:
    compression: int  # 0 none, 1 zlib, 2 snappy, 5 zstd
    block_size: int
    stripes: List[StripeInfo]
    kinds: List[int]        # per column id (0 = root struct)
    names: List[str]        # root field names (column ids 1..n)


def read_tail(path: str) -> OrcTail:
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        f.seek(max(0, size - (1 << 14)))
        tail = f.read()
        ps_len = tail[-1]
        ps = _proto_fields(tail[-1 - ps_len:-1])
        pd = {fl: v for fl, _, v in ps}
        footer_len = pd.get(1, 0)
        compression = pd.get(2, 0)
        block_size = pd.get(3, 1 << 18)
        foot_raw = tail[-1 - ps_len - footer_len:-1 - ps_len]
        if len(foot_raw) < footer_len:
            f.seek(size - 1 - ps_len - footer_len)
            foot_raw = f.read(footer_len)
    foot = _decompress_all(compression, foot_raw)
    stripes, kinds, names = [], [], []
    for fl, wt, v in _proto_fields(foot):
        if fl == 3:
            sv = {a: c for a, _, c in _proto_fields(v)}
            stripes.append(StripeInfo(sv.get(1, 0), sv.get(2, 0),
                                      sv.get(3, 0), sv.get(4, 0),
                                      sv.get(5, 0)))
        elif fl == 4:
            tf = _proto_fields(v)
            kinds.append(next((c for a, _, c in tf if a == 1), 0))
            if len(kinds) == 1:
                names = [c.decode() for a, _, c in tf if a == 3]
    return OrcTail(compression, block_size, stripes, kinds, names)


@_parse_boundary
def _decompress_all(compression: int, raw: bytes) -> bytes:
    """Undo ORC's block framing: 3-byte little-endian header per block,
    (length << 1) | is_original."""
    if compression == 0:
        return raw
    out, i = [], 0
    while i + 3 <= len(raw):
        hdr = raw[i] | (raw[i + 1] << 8) | (raw[i + 2] << 16)
        i += 3
        ln, orig = hdr >> 1, hdr & 1
        chunk = raw[i:i + ln]
        i += ln
        if orig:
            out.append(chunk)
        elif compression == 1:  # zlib (raw deflate)
            out.append(zlib.decompress(chunk, wbits=-15))
        elif compression == 2:  # snappy (raw block; leading varint = size)
            usize, s, j = 0, 0, 0
            while True:
                x = chunk[j]
                j += 1
                usize |= (x & 0x7F) << s
                s += 7
                if not x & 0x80:
                    break
            buf = pa.Codec("snappy").decompress(chunk,
                                                decompressed_size=usize)
            out.append(buf.to_pybytes() if hasattr(buf, "to_pybytes")
                       else bytes(buf))
        elif compression == 5:  # zstd
            import zstandard
            out.append(zstandard.ZstdDecompressor().decompress(
                chunk, max_output_size=1 << 26))
        else:
            raise NotOrcDecodable(f"compression kind {compression}")
    return b"".join(out)


# ---------------------------------------------------------------------------
# RLEv2 -> run tables (host header parse, device expansion)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _Runs:
    """Run table: kind 0 = linear (base + delta * within), 1 = direct
    (values[vstart + within])."""

    kinds: List[int]
    counts: List[int]
    bases: List[int]
    deltas: List[int]
    vstarts: List[int]
    values: List[int]

    def __init__(self):
        self.kinds, self.counts, self.bases = [], [], []
        self.deltas, self.vstarts, self.values = [], [], []

    def add_linear(self, count, base, delta=0):
        self.kinds.append(0)
        self.counts.append(count)
        self.bases.append(base)
        self.deltas.append(delta)
        self.vstarts.append(0)

    def add_direct(self, vals):
        self.kinds.append(1)
        self.counts.append(len(vals))
        self.bases.append(0)
        self.deltas.append(0)
        self.vstarts.append(len(self.values))
        self.values.extend(int(v) for v in vals)


def _varint(b: bytes, i: int) -> Tuple[int, int]:
    v, s = 0, 0
    while True:
        x = b[i]
        i += 1
        v |= (x & 0x7F) << s
        s += 7
        if not x & 0x80:
            return v, i


def _zigzag(v: int) -> int:
    return (v >> 1) ^ -(v & 1)


def _unpack_be(b: bytes, i: int, count: int, width: int
               ) -> Tuple[np.ndarray, int]:
    """Unpack ``count`` big-endian ``width``-bit values starting at byte
    ``i`` (vectorized via numpy bit arithmetic)."""
    total_bits = count * width
    nbytes = (total_bits + 7) // 8
    raw = np.frombuffer(b, np.uint8, count=nbytes, offset=i)
    bits = np.unpackbits(raw)
    bits = bits[: count * width].reshape(count, width).astype(np.uint64)
    weights = (np.uint64(1) << np.arange(width - 1, -1, -1,
                                         dtype=np.uint64))
    vals = (bits * weights).sum(axis=1)
    return vals, i + nbytes


@_parse_boundary
def parse_rlev2(b: bytes, signed: bool, expected: int) -> _Runs:
    """Parse an RLEv2 byte stream into a run table; values count must
    reach ``expected``."""
    runs = _Runs()
    i, produced = 0, 0
    while produced < expected:
        if i >= len(b):
            raise NotOrcDecodable("rlev2 stream truncated")
        hdr = b[i]
        enc = hdr >> 6
        if enc == 0:  # SHORT_REPEAT
            width = ((hdr >> 3) & 7) + 1
            count = (hdr & 7) + 3
            i += 1
            v = int.from_bytes(b[i:i + width], "big")
            i += width
            if signed:
                v = _zigzag(v)
            runs.add_linear(count, v)
            produced += count
        elif enc == 1:  # DIRECT
            wcode = (hdr >> 1) & 0x1F
            width = _WIDTH_TABLE[wcode]
            count = ((hdr & 1) << 8 | b[i + 1]) + 1
            i += 2
            vals, i = _unpack_be(b, i, count, width)
            vals = vals.astype(np.int64)
            if signed:
                vals = (vals >> 1) ^ -(vals & 1)
            runs.add_direct(vals)
            produced += count
        elif enc == 3:  # DELTA
            wcode = (hdr >> 1) & 0x1F
            width = _WIDTH_TABLE[wcode] if wcode else 0
            count = ((hdr & 1) << 8 | b[i + 1]) + 1
            i += 2
            raw_base, i = _varint(b, i)
            base = _zigzag(raw_base) if signed else raw_base
            raw_db, i = _varint(b, i)
            delta_base = _zigzag(raw_db)
            if width == 0:
                runs.add_linear(count, base, delta_base)
            else:
                # variable deltas: first two values then |count-2| deltas
                # whose sign follows delta_base — materialize host-side.
                deltas, i = _unpack_be(b, i, count - 2, width)
                sign = 1 if delta_base >= 0 else -1
                vals = np.empty(count, np.int64)
                vals[0] = base
                vals[1] = base + delta_base
                np.cumsum(deltas.astype(np.int64) * sign, out=vals[2:],
                          dtype=np.int64)
                vals[2:] += vals[1]
                runs.add_direct(vals)
            produced += count
        else:  # enc == 2, PATCHED_BASE — materialize host-side
            with _STATS_LOCK:
                decode_stats["patched_base_runs"] += 1
            wcode = (hdr >> 1) & 0x1F
            width = _WIDTH_TABLE[wcode]
            count = ((hdr & 1) << 8 | b[i + 1]) + 1
            third, fourth = b[i + 2], b[i + 3]
            bw = ((third >> 5) & 7) + 1          # base bytes
            pw = _WIDTH_TABLE[third & 0x1F]      # patch width
            pgw = ((fourth >> 5) & 7) + 1        # patch gap width (bits)
            pll = fourth & 0x1F                  # patch list length
            i += 4
            base = int.from_bytes(b[i:i + bw], "big")
            i += bw
            msb = 1 << (bw * 8 - 1)
            if base & msb:
                base = -(base & (msb - 1))
            vals, i = _unpack_be(b, i, count, width)
            vals = vals.astype(np.int64)
            # writers pack patch entries with getClosestFixedBits(pgw+pw),
            # not the raw sum (e.g. 25 -> 26)
            pe_width = next((w for w in _WIDTH_TABLE if w >= pgw + pw), 64)
            pcombined, i = _unpack_be(b, i, pll, pe_width)
            gap_pos = 0
            for pc in pcombined:
                gap_pos += int(pc) >> pw
                patch = int(pc) & ((1 << pw) - 1)
                vals[gap_pos] |= patch << width
            runs.add_direct(vals + base)
            produced += count
    if produced != expected:
        raise NotOrcDecodable("rlev2 produced wrong count")
    return runs


@_parse_boundary
def parse_byte_rle_bits(b: bytes, n_rows: int) -> np.ndarray:
    """PRESENT stream: byte-RLE over MSB-first bit-packed bytes ->
    packed uint8 bitmask of n_rows bits."""
    out = bytearray()
    need = (n_rows + 7) // 8
    i = 0
    while len(out) < need and i < len(b):
        ctrl = b[i]
        i += 1
        if ctrl < 128:  # run of ctrl+3 copies
            out.extend(b[i:i + 1] * (ctrl + 3))
            i += 1
        else:  # 256-ctrl literals
            lit = 256 - ctrl
            out.extend(b[i:i + lit])
            i += lit
    if len(out) < need:
        raise NotOrcDecodable("present stream truncated")
    return np.frombuffer(bytes(out[:need]), np.uint8)


# ---------------------------------------------------------------------------
# device expansion
# ---------------------------------------------------------------------------


def _runs_arrays(runs: _Runs, pad: int):
    def arr(xs, fill, dt=np.int64):
        a = np.full(pad, fill, dt)
        a[: len(xs)] = xs
        return jnp.asarray(a)
    vals = np.asarray(runs.values or [0], np.int64)
    vcap = bucket_byte_capacity(max(len(vals), 1), 8)
    vbuf = np.zeros(vcap, np.int64)
    vbuf[: len(vals)] = vals
    return (arr(runs.kinds, 0, np.int32), arr(runs.counts, 0, np.int32),
            arr(runs.bases, 0), arr(runs.deltas, 0),
            arr(runs.vstarts, 0, np.int32), jnp.asarray(vbuf))


def _expand_runs(table, capacity: int) -> jnp.ndarray:
    kinds, counts, bases, deltas, vstarts, values = table
    ends = jnp.cumsum(counts)
    starts = ends - counts
    i = jnp.arange(capacity, dtype=jnp.int32)
    r = jnp.searchsorted(ends, i, side="right")
    r = jnp.clip(r, 0, kinds.shape[0] - 1)
    within = (i - starts[r]).astype(jnp.int64)
    linear = bases[r] + deltas[r] * within
    nv = values.shape[0]
    direct = values[jnp.clip(vstarts[r].astype(jnp.int64) + within, 0,
                             nv - 1)]
    return jnp.where(kinds[r] == 1, direct, linear)


def _expand_present(packed: jnp.ndarray, capacity: int) -> jnp.ndarray:
    i = jnp.arange(capacity, dtype=jnp.int32)
    byte = packed[jnp.clip(i >> 3, 0, packed.shape[0] - 1)]
    return ((byte >> (7 - (i & 7).astype(jnp.uint8))) & 1).astype(jnp.bool_)


def _pad_bits(bits: Optional[np.ndarray], capacity: int) -> jnp.ndarray:
    cap = bucket_byte_capacity(max(capacity // 8 + 1, 8), 8)
    buf = np.full(cap, 0xFF, np.uint8)
    if bits is not None:
        buf[: len(bits)] = bits
    return jnp.asarray(buf)


# ---------------------------------------------------------------------------
# column decode
# ---------------------------------------------------------------------------

_INT_KINDS = {_K_SHORT: T.SHORT, _K_INT: T.INT, _K_LONG: T.LONG,
              _K_DATE: T.DATE}


def _decode_int_column(runs: _Runs, bits, n_rows: int, capacity: int,
                       dtype: T.DataType) -> DeviceColumn:
    pad = bucket_byte_capacity(max(len(runs.kinds), 1), 8)
    table = _runs_arrays(runs, pad)
    packed = _pad_bits(bits, capacity)

    def build():
        def kern(table, packed, n):
            live = jnp.arange(capacity, dtype=jnp.int32) < n
            validity = _expand_present(packed, capacity) & live
            slot = jnp.clip(jnp.cumsum(validity.astype(jnp.int32)) - 1, 0,
                            capacity - 1)
            vals = _expand_runs(table, capacity)
            data = jnp.where(validity, vals[slot], 0)
            return data.astype(dtype.np_dtype), validity
        return kern
    kern = cached_kernel(
        "orc_int_decode",
        (dtype.name, capacity, pad, int(table[5].shape[0]),
         int(packed.shape[0])), build)
    data, validity = kern(table, packed, jnp.asarray(n_rows, jnp.int32))
    return DeviceColumn(data=data, validity=validity, dtype=dtype)


def _decode_float_column(vals: np.ndarray, bits, n_rows: int,
                         capacity: int, dtype: T.DataType) -> DeviceColumn:
    buf = np.zeros(capacity, vals.dtype)
    buf[: len(vals)] = vals
    plain = jnp.asarray(buf)
    packed = _pad_bits(bits, capacity)

    def build():
        def kern(plain, packed, n):
            live = jnp.arange(capacity, dtype=jnp.int32) < n
            validity = _expand_present(packed, capacity) & live
            slot = jnp.clip(jnp.cumsum(validity.astype(jnp.int32)) - 1, 0,
                            capacity - 1)
            data = jnp.where(validity, plain[slot],
                             jnp.zeros((), plain.dtype))
            return data, validity
        return kern
    kern = cached_kernel("orc_float_decode",
                         (dtype.name, capacity, int(packed.shape[0])),
                         build)
    data, validity = kern(plain, packed, jnp.asarray(n_rows, jnp.int32))
    return DeviceColumn(data=data.astype(dtype.np_dtype), validity=validity,
                        dtype=dtype)


def _dict_from_blob(blob: bytes, lengths: np.ndarray
                    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(sorted unique payload, offsets, code remap old->sorted).

    Entries are deduped: the dict_sorted contract (data/column.py) needs
    code equality == string equality, and DIRECT_V2 feeds every row's
    value through here (duplicates guaranteed)."""
    offs = np.zeros(len(lengths) + 1, np.int64)
    np.cumsum(lengths, out=offs[1:])
    entries = [blob[offs[k]:offs[k + 1]] for k in range(len(lengths))]
    uniq = sorted(set(entries))
    rank = {e: r for r, e in enumerate(uniq)}
    remap = np.fromiter((rank[e] for e in entries), np.int32,
                        count=len(entries)) if entries else \
        np.zeros(0, np.int32)
    payload = b"".join(uniq)
    soffs = np.zeros(len(uniq) + 1, np.int32)
    np.cumsum([len(e) for e in uniq], out=soffs[1:])
    return (np.frombuffer(payload, np.uint8) if payload else
            np.zeros(0, np.uint8), soffs, remap)


def _string_column_from_codes(codes_dev, validity, payload: np.ndarray,
                              offsets: np.ndarray) -> DeviceColumn:
    max_bytes = bucket_byte_capacity(
        max(int(np.diff(offsets).max()) if len(offsets) > 1 else 1, 1), 8)
    byte_cap = bucket_byte_capacity(max(int(offsets[-1]), 1))
    buf = np.zeros(byte_cap, np.uint8)
    buf[: len(payload)] = payload
    return DeviceColumn(data=jnp.asarray(buf), validity=validity,
                        dtype=T.STRING, offsets=jnp.asarray(offsets),
                        max_bytes=max_bytes, codes=codes_dev,
                        dict_sorted=True)


# ---------------------------------------------------------------------------
# stripe decode
# ---------------------------------------------------------------------------


def decode_stripe(path: str, tail: OrcTail, si: StripeInfo,
                  schema: T.Schema) -> ColumnarBatch:
    with open(path, "rb") as f:
        f.seek(si.offset)
        raw = f.read(si.index_length + si.data_length + si.footer_length)
    sf = _proto_fields(_decompress_all(
        tail.compression,
        raw[si.index_length + si.data_length:]))
    streams, encodings = [], []
    for fl, _, v in sf:
        if fl == 1:
            sv = {a: c for a, _, c in _proto_fields(v)}
            streams.append((sv.get(1, 0), sv.get(2, 0), sv.get(3, 0)))
        elif fl == 2:
            ev = {a: c for a, _, c in _proto_fields(v)}
            encodings.append(ev.get(1, 0))
    # stream payloads laid out in order from the stripe start
    payloads: Dict[Tuple[int, int], bytes] = {}
    pos = 0
    for kind, col, ln in streams:
        payloads[(kind, col)] = raw[pos:pos + ln]
        pos += ln

    def stream(kind, col) -> bytes:
        p = payloads.get((kind, col))
        if p is None:
            return b""
        return _decompress_all(tail.compression, p)

    n_rows = si.n_rows
    capacity = bucket_capacity(max(n_rows, 1))
    name_to_col = {nm: ci + 1 for ci, nm in enumerate(tail.names)}
    cols = []
    for field in schema:
        cid = name_to_col[field.name]
        kind = tail.kinds[cid]
        enc = encodings[cid] if cid < len(encodings) else _E_DIRECT
        present = stream(_S_PRESENT, cid)
        bits = parse_byte_rle_bits(present, n_rows) if present else None
        n_valid = n_rows if bits is None else int(
            np.unpackbits(bits)[:n_rows].sum())
        with trace_range("orc.decode_column"):
            if kind in _INT_KINDS:
                if enc not in (_E_DIRECT_V2,):
                    raise NotOrcDecodable(f"int encoding {enc}")
                runs = parse_rlev2(stream(_S_DATA, cid), True, n_valid)
                cols.append(_decode_int_column(runs, bits, n_rows,
                                               capacity,
                                               _INT_KINDS[kind]))
            elif kind in (_K_FLOAT, _K_DOUBLE):
                dt = np.float32 if kind == _K_FLOAT else np.float64
                vals = np.frombuffer(stream(_S_DATA, cid), dt,
                                     count=n_valid)
                cols.append(_decode_float_column(
                    vals, bits, n_rows, capacity,
                    T.FLOAT if kind == _K_FLOAT else T.DOUBLE))
            elif kind == _K_STRING and enc == _E_DICT_V2:
                dict_blob = stream(_S_DICT, cid)
                # dictionarySize lives in the encoding proto (field 2)
                ev = [dict({a: c for a, _, c in _proto_fields(v)})
                      for fl, _, v in sf if fl == 2]
                dsize = ev[cid].get(2, 0)
                lr = parse_rlev2(stream(_S_LENGTH, cid), False, dsize)
                lengths = _expand_runs_host(lr, dsize)
                payload, soffs, remap = _dict_from_blob(dict_blob, lengths)
                cruns = parse_rlev2(stream(_S_DATA, cid), False, n_valid)
                codes = _decode_int_column(cruns, bits, n_rows, capacity,
                                           T.INT)
                remap_pad = np.zeros(
                    bucket_byte_capacity(max(len(remap), 1), 8), np.int32)
                remap_pad[: len(remap)] = remap
                rdev = jnp.asarray(remap_pad)
                code_vals = rdev[jnp.clip(codes.data.astype(jnp.int32), 0,
                                          rdev.shape[0] - 1)]
                code_vals = jnp.where(codes.validity, code_vals, 0)
                cols.append(_string_column_from_codes(
                    code_vals, codes.validity, payload, soffs))
            elif kind == _K_STRING and enc == _E_DIRECT_V2:
                lr = parse_rlev2(stream(_S_LENGTH, cid), False, n_valid)
                lengths = _expand_runs_host(lr, n_valid)
                blob = stream(_S_DATA, cid)
                payload, soffs, remap = _dict_from_blob(blob, lengths)
                # codes per non-null slot (host: the dictionary build is
                # host-side anyway), scattered to rows on device
                cruns = _Runs()
                cruns.add_direct(remap)
                codes = _decode_int_column(cruns, bits, n_rows, capacity,
                                           T.INT)
                cols.append(_string_column_from_codes(
                    codes.data.astype(jnp.int32), codes.validity, payload,
                    soffs))
            else:
                raise NotOrcDecodable(
                    f"column kind {kind} encoding {enc}")
    return ColumnarBatch(tuple(cols), jnp.asarray(n_rows, jnp.int32),
                         T.Schema(list(schema)))


def _expand_runs_host(runs: _Runs, n: int) -> np.ndarray:
    out = np.empty(n, np.int64)
    pos = 0
    vals = np.asarray(runs.values, np.int64)
    for k, c, b, d, vs in zip(runs.kinds, runs.counts, runs.bases,
                              runs.deltas, runs.vstarts):
        if k == 0:
            out[pos:pos + c] = b + d * np.arange(c, dtype=np.int64)
        else:
            out[pos:pos + c] = vals[vs:vs + c]
        pos += c
    return out


# ---------------------------------------------------------------------------
# scan exec + gating
# ---------------------------------------------------------------------------


def scan_files(paths: List[str]) -> List[str]:
    out = []
    for p in paths:
        if os.path.isdir(p):
            for root, _, files in os.walk(p):
                out.extend(os.path.join(root, fn) for fn in sorted(files)
                           if fn.endswith(".orc"))
        elif p.endswith(".orc"):
            out.append(p)
        else:
            return []
    return sorted(out)


_SUPPORTED_KINDS = set(_INT_KINDS) | {_K_FLOAT, _K_DOUBLE, _K_STRING}


def device_decodable(path: str, schema: T.Schema,
                     tail: Optional[OrcTail] = None) -> bool:
    try:
        tail = tail or read_tail(path)
    except Exception:
        return False
    if tail.compression not in (0, 1, 2, 5):
        return False
    if not tail.kinds or tail.kinds[0] != _K_STRUCT:
        return False
    name_to_col = {nm: ci + 1 for ci, nm in enumerate(tail.names)}
    for f in schema:
        cid = name_to_col.get(f.name)
        if cid is None or cid >= len(tail.kinds):
            return False
        if tail.kinds[cid] not in _SUPPORTED_KINDS:
            return False
    return True


class TpuOrcScanExec:
    """Device ORC scan: one partition per (file, stripe); per-stripe
    fallback to a host pyarrow read keeps out-of-scope stripes working
    (GpuOrcScan.scala:65,211 role)."""

    columnar = True
    children = ()
    children_coalesce_goals = None

    def __init__(self, files: List[str], schema: T.Schema,
                 tails: Optional[dict] = None):
        self.files = list(files)
        self._schema = schema
        self._tails = dict(tails or {})

    @property
    def schema(self):
        return self._schema

    def node_name(self):
        return "TpuOrcScanExec"

    def describe(self):
        return f"TpuOrcScan files={len(self.files)}"

    def tree_string(self, indent: int = 0) -> str:
        return "  " * indent + self.describe() + "\n"

    def with_children(self, children):
        assert not children
        return self

    def execute(self, ctx):
        units = []
        for path in self.files:
            tail = self._tails.get(path) or read_tail(path)
            units.extend((path, tail, si) for si in tail.stripes)

        name = self.node_name()

        def read(unit):
            path, tail, si = unit
            from ..memory.retry import Classification, classify
            from ..utils.fault_injection import maybe_inject
            try:
                maybe_inject(ctx, "io.orc.stripe")
                with ctx.registry.timer(name, "opTime",
                                        trace="orc.device_decode_stripe"):
                    return decode_stripe(path, tail, si, self._schema)
            except Exception as e:  # noqa: BLE001 - classify-narrowed
                # parsers translate malformed-input errors to
                # NotOrcDecodable at their boundary (_parse_boundary), and
                # classified device faults (OOM/transient) degrade to the
                # host reader per stripe — the correctness baseline;
                # decoder-logic bugs elsewhere still fail loudly.
                if not isinstance(e, NotOrcDecodable) \
                        and classify(e) == Classification.FATAL:
                    raise
                ctx.metric(name, "stripeHostFallback", 1)
                return self._host_stripe(path, tail, si)

        # Stripes decode ahead on the shared pipeline pool (bounded by
        # decodeThreads/prefetchDepth), yielding in stripe order; with
        # the pipeline off, the serial stream keeps its depth-2 prefetch
        # worker (pre-pipeline behavior).
        from ..exec import pipeline

        def gen():
            for u, b in zip(units, pipeline.ordered_map_iter(
                    read, units, ctx, name)):
                ctx.metric(name, "numOutputBatches", 1)
                ctx.metric(name, "numOutputRows", u[2].n_rows)
                yield b
        if pipeline.parallel_active(ctx):
            return [gen()]
        from ..utils.prefetch import prefetch_iter
        return [prefetch_iter(gen(), ctx=ctx, node=name)]

    def _host_stripe(self, path, tail, si) -> ColumnarBatch:
        import pyarrow.orc as orc
        f = orc.ORCFile(path)
        idx = tail.stripes.index(si)
        rb = f.read_stripe(idx, columns=[f_.name for f_ in self._schema])
        table = pa.Table.from_batches([rb]) if isinstance(
            rb, pa.RecordBatch) else rb
        rb = table.combine_chunks().to_batches()[0] if table.num_rows else \
            pa.RecordBatch.from_arrays(
                [pa.array([], type=fld.type)
                 for fld in T.schema_to_arrow(self._schema)],
                schema=T.schema_to_arrow(self._schema))
        return ColumnarBatch.from_arrow(
            rb.cast(T.schema_to_arrow(self._schema)))
