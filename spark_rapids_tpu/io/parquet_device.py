"""Device-side parquet decode — the ``Table.readParquet`` stage analog.

The reference's scan splits work exactly this way: the CPU parses the footer
and reassembles the selected row-group bytes in host memory, then cuDF
decodes ON DEVICE (GpuParquetScan.scala:365-388 -> Table.readParquet). The
TPU-native split here:

* HOST (metadata-sized work): pyarrow reads the footer; a minimal
  thrift-compact parser walks page headers; page payloads decompress; the
  RLE/bit-packed hybrid streams (definition levels + dictionary indices)
  are sliced into RUN TABLES — (kind, count, value | bit offset) per run —
  without expanding a single value.
* DEVICE (data-sized work): one traced kernel expands the run tables —
  ``searchsorted`` over run ends finds each output's run, RLE runs
  broadcast their value, bit-packed runs gather+shift+mask straight from
  the uploaded page bytes — then definition levels become the validity
  mask and dictionary indices scatter into row order. Everything is
  vectorized; no per-value host loop anywhere.

Parquet dictionaries pair perfectly with this engine's dict-encoded string
columns: the page dictionary IS the column dictionary. The host sorts the
(small) dictionary and uploads a rank table; the device remaps codes, so
decoded string columns arrive ``dict_sorted`` and every downstream sort /
group-by / join uses the fast code paths.

Scope (falls back to the host scan otherwise, reference-style graceful
degradation): v1 data pages, PLAIN + PLAIN_DICTIONARY/RLE_DICTIONARY
encodings, flat schemas, dictionary bit widths <= 24.
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import struct as _struct
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..data.batch import ColumnarBatch
from ..data.column import (DeviceColumn, bucket_byte_capacity,
                           bucket_capacity)
from ..utils.kernel_cache import cached_kernel

# -- minimal thrift compact protocol reader ---------------------------------


class _Thrift:
    """Just enough of the thrift compact protocol for parquet PageHeader."""

    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def _byte(self) -> int:
        b = self.buf[self.pos]
        self.pos += 1
        return b

    def varint(self) -> int:
        out = shift = 0
        while True:
            b = self._byte()
            out |= (b & 0x7F) << shift
            if not b & 0x80:
                return out
            shift += 7

    def zigzag(self) -> int:
        v = self.varint()
        return (v >> 1) ^ -(v & 1)

    def read_struct(self) -> Dict[int, object]:
        """Field id -> value; nested structs become dicts, unneeded types
        are skipped structurally."""
        out: Dict[int, object] = {}
        field_id = 0
        while True:
            header = self._byte()
            if header == 0:
                return out
            delta = header >> 4
            ftype = header & 0x0F
            field_id = field_id + delta if delta else self.zigzag()
            out[field_id] = self._read_value(ftype)

    def _read_value(self, ftype: int):
        if ftype in (1, 2):  # bool true/false encoded in the type nibble
            return ftype == 1
        if ftype == 3:
            return self._byte()
        if ftype in (4, 5, 6):  # i16/i32/i64
            return self.zigzag()
        if ftype == 7:
            v = _struct.unpack_from("<d", self.buf, self.pos)[0]
            self.pos += 8
            return v
        if ftype == 8:  # binary
            n = self.varint()
            v = self.buf[self.pos: self.pos + n]
            self.pos += n
            return v
        if ftype == 9:  # list
            head = self._byte()
            size = head >> 4
            etype = head & 0x0F
            if size == 15:
                size = self.varint()
            return [self._read_value(etype) for _ in range(size)]
        if ftype == 12:
            return self.read_struct()
        raise NotImplementedError(f"thrift compact type {ftype}")


@dataclasses.dataclass
class _PageHeader:
    page_type: int            # 0 data v1, 2 dictionary, 3 data v2
    compressed_size: int
    uncompressed_size: int
    num_values: int = 0
    encoding: int = 0
    def_encoding: int = 3     # RLE
    header_len: int = 0


def _parse_page_header(buf: bytes, pos: int) -> _PageHeader:
    t = _Thrift(buf, pos)
    d = t.read_struct()
    ph = _PageHeader(page_type=d[1], uncompressed_size=d[2],
                     compressed_size=d[3], header_len=t.pos - pos)
    if ph.page_type == 0:
        dph = d[5]
        ph.num_values = dph[1]
        ph.encoding = dph[2]
        ph.def_encoding = dph[3]
    elif ph.page_type == 2:
        ph.num_values = d[7][1]
        ph.encoding = d[7][2]
    return ph


# -- host page walk: bytes -> run tables ------------------------------------

PLAIN, PLAIN_DICTIONARY, RLE, RLE_DICTIONARY = 0, 2, 3, 8


@dataclasses.dataclass
class _HybridRuns:
    """Run table for one RLE/bit-packed hybrid stream, offsets relative to
    ONE shared packed-bytes buffer uploaded to the device."""

    kinds: List[int]          # 1 = RLE, 0 = bit-packed
    counts: List[int]
    values: List[int]         # RLE value (0 for bit-packed runs)
    bit_starts: List[int]     # absolute bit offset into the packed buffer
    widths: List[int]         # per-run bit width (dict width grows as the
    #                           dictionary fills across pages)

    def __init__(self):
        self.kinds, self.counts, self.values = [], [], []
        self.bit_starts, self.widths = [], []

    def non_null_count(self, start_run: int, packed: bytearray) -> int:
        """Popcount of a bit-width-1 (definition level) run suffix: the
        number of NON-NULL values — which is exactly how many entries the
        page's index stream stores."""
        total = 0
        for i in range(start_run, len(self.kinds)):
            if self.kinds[i] == 1:
                total += self.counts[i] * (self.values[i] & 1)
            else:
                b0 = self.bit_starts[i]
                count = self.counts[i]
                chunk = np.frombuffer(
                    packed, np.uint8,
                    count=(b0 % 8 + count + 7) // 8, offset=b0 // 8)
                bits = np.unpackbits(chunk, bitorder="little")
                total += int(bits[b0 % 8: b0 % 8 + count].sum())
        return total


def _parse_hybrid(buf: bytes, pos: int, end: int, bit_width: int,
                  n_values: int, runs: _HybridRuns, packed: bytearray,
                  pad_tail: bool = True) -> None:
    """Slice one hybrid stream into runs; bit-packed payloads append to
    ``packed``. Never expands values. Counts CAP at the page's n_values so
    the (multiple-of-8 padded) last bit-packed group never leaks phantom
    positions into the next page's runs."""
    produced = 0
    t = _Thrift(buf, pos)
    byte_w = (bit_width + 7) // 8
    while produced < n_values and t.pos < end:
        header = t.varint()
        if header & 1:  # bit-packed: (header>>1) groups of 8 values
            groups = header >> 1
            count = min(groups * 8, n_values - produced)
            nbytes = groups * bit_width  # groups * 8 * bw / 8
            runs.kinds.append(0)
            runs.counts.append(count)
            runs.values.append(0)
            runs.bit_starts.append(len(packed) * 8)
            runs.widths.append(bit_width)
            packed.extend(buf[t.pos: t.pos + nbytes])
            t.pos += nbytes
        else:
            count = min(header >> 1, n_values - produced)
            raw = buf[t.pos: t.pos + byte_w]
            t.pos += byte_w
            runs.kinds.append(1)
            runs.counts.append(count)
            runs.values.append(int.from_bytes(raw, "little"))
            runs.bit_starts.append(0)
            runs.widths.append(bit_width)
        produced += count
    if pad_tail and produced < n_values:
        # Implicit trailing zeros (writers may omit the final RLE run).
        runs.kinds.append(1)
        runs.counts.append(n_values - produced)
        runs.values.append(0)
        runs.bit_starts.append(0)
        runs.widths.append(bit_width)


_PHYS_NP = {"INT32": np.int32, "INT64": np.int64, "FLOAT": np.float32,
            "DOUBLE": np.float64, "BOOLEAN": np.bool_}


@dataclasses.dataclass
class ColumnChunkPlan:
    """Everything the device kernel needs for one column chunk, prepared
    host-side from page bytes."""

    dtype: T.DataType
    n_rows: int
    nullable: bool
    # definition-level hybrid (bw=1): validity
    def_runs: Optional[_HybridRuns]
    # value source: either dictionary indices (hybrid) + dictionary, or
    # PLAIN values uploaded directly
    idx_runs: Optional[_HybridRuns]
    idx_bit_width: int
    packed: bytes             # shared packed buffer (def + idx bitpacks)
    plain_values: Optional[np.ndarray]
    # dictionary: fixed-width values, or sorted string dict + rank remap
    dict_values: Optional[np.ndarray]
    dict_rank: Optional[np.ndarray]
    dict_offsets: Optional[np.ndarray]
    dict_payload: Optional[np.ndarray]


def _decompress(codec: str, payload: bytes, uncompressed_size: int) -> bytes:
    if codec == "UNCOMPRESSED":
        return payload
    import pyarrow as pa
    return pa.Codec(codec.lower()).decompress(
        payload, decompressed_size=uncompressed_size).to_pybytes()


def plan_column_chunk(f, col_md, field: T.StructField,
                      max_def_level: int) -> ColumnChunkPlan:
    """Host phase for one column chunk: page headers -> run tables.

    ``f`` is an open file object; ``col_md`` a pyarrow ColumnChunkMetaData;
    ``max_def_level`` comes from the FILE's schema (a REQUIRED column has
    no definition-level stream regardless of what the engine schema says
    about nullability — trusting the engine schema here mis-frames the
    page payload). Raises NotImplementedError for shapes outside scope
    (caller falls back to the host scan)."""
    if max_def_level > 1:
        raise NotImplementedError("nested columns (max_def_level > 1)")
    phys = col_md.physical_type
    if phys not in _PHYS_NP and phys != "BYTE_ARRAY":
        raise NotImplementedError(f"physical type {phys}")
    start = col_md.data_page_offset
    if col_md.has_dictionary_page:
        start = min(start, col_md.dictionary_page_offset)
    f.seek(start)
    chunk = f.read(col_md.total_compressed_size)
    codec = col_md.compression

    pos = 0
    dict_vals_raw: Optional[bytes] = None
    def_runs = _HybridRuns()
    idx_runs = _HybridRuns()
    packed = bytearray()
    plain_parts: List[bytes] = []
    idx_bw = 0
    n_rows = 0
    uses_dict = False
    uses_plain = False
    while pos < len(chunk):
        ph = _parse_page_header(chunk, pos)
        pos += ph.header_len
        payload = _decompress(codec, chunk[pos: pos + ph.compressed_size],
                              ph.uncompressed_size)
        pos += ph.compressed_size
        if ph.page_type == 2:  # dictionary page (PLAIN-encoded)
            dict_vals_raw = payload
            continue
        if ph.page_type != 0:
            raise NotImplementedError(f"page type {ph.page_type} (v2?)")
        p = 0
        page_def_start = len(def_runs.kinds)
        if max_def_level > 0:
            if ph.def_encoding != RLE:
                raise NotImplementedError("non-RLE definition levels")
            (def_len,) = _struct.unpack_from("<I", payload, p)
            p += 4
            _parse_hybrid(payload, p, p + def_len, 1, ph.num_values,
                          def_runs, packed)
            p += def_len
            non_null = def_runs.non_null_count(page_def_start, packed)
        else:
            def_runs.kinds.append(1)
            def_runs.counts.append(ph.num_values)
            def_runs.values.append(1)
            def_runs.bit_starts.append(0)
            def_runs.widths.append(1)
            non_null = ph.num_values
        if ph.encoding in (PLAIN_DICTIONARY, RLE_DICTIONARY):
            uses_dict = True
            bw = payload[p]
            p += 1
            if bw > 24:
                raise NotImplementedError(f"dictionary bit width {bw}")
            idx_bw = max(idx_bw, bw)
            # The page stores exactly non_null indices (indices exist only
            # for non-null slots; the def mask scatters them into row order
            # on device). Capping at the EXACT count keeps multi-page run
            # tables positionally aligned; per-run widths let later pages
            # use wider codes as the dictionary fills.
            _parse_hybrid(payload, p, len(payload), bw, non_null,
                          idx_runs, packed)
        elif ph.encoding == PLAIN:
            uses_plain = True
            plain_parts.append(payload[p:])
        else:
            raise NotImplementedError(f"encoding {ph.encoding}")
        n_rows += ph.num_values
    if uses_dict and uses_plain:
        raise NotImplementedError("mixed PLAIN + dictionary pages")
    if phys == "BYTE_ARRAY" and not uses_dict:
        raise NotImplementedError("PLAIN byte-array pages")

    plan = ColumnChunkPlan(
        dtype=field.data_type, n_rows=n_rows, nullable=field.nullable,
        def_runs=def_runs, idx_runs=idx_runs if uses_dict else None,
        idx_bit_width=idx_bw, packed=bytes(packed),
        plain_values=None, dict_values=None, dict_rank=None,
        dict_offsets=None, dict_payload=None)

    if uses_plain:
        raw = b"".join(plain_parts)
        if phys == "BOOLEAN":
            raise NotImplementedError("PLAIN boolean pages")
        plan.plain_values = np.frombuffer(
            raw, dtype=_PHYS_NP[phys]).astype(
                field.data_type.np_dtype, copy=False)
    if uses_dict:
        assert dict_vals_raw is not None, "dict pages missing"
        if phys == "BYTE_ARRAY":
            # PLAIN byte-array dictionary: [u32 len][bytes]... Host-parse
            # (dictionary-sized, small), sort, build the rank remap so the
            # device column lands dict_sorted.
            vals: List[bytes] = []
            q = 0
            while q < len(dict_vals_raw):
                (ln,) = _struct.unpack_from("<I", dict_vals_raw, q)
                q += 4
                vals.append(dict_vals_raw[q: q + ln])
                q += ln
            order = np.argsort(np.asarray(vals, dtype=object), kind="stable")
            rank = np.empty(len(vals), dtype=np.int32)
            rank[order] = np.arange(len(vals), dtype=np.int32)
            sorted_vals = [vals[i] for i in order] or [b""]
            lens = np.asarray([len(b) for b in sorted_vals], np.int32)
            plan.dict_offsets = np.concatenate(
                [[0], np.cumsum(lens)]).astype(np.int32)
            plan.dict_payload = np.frombuffer(
                b"".join(sorted_vals) or b"\0", dtype=np.uint8)
            plan.dict_rank = rank
        else:
            plan.dict_values = np.frombuffer(
                dict_vals_raw, dtype=_PHYS_NP[phys]).astype(
                    field.data_type.np_dtype, copy=False)
    return plan


# -- device expansion kernels -----------------------------------------------


def _expand_hybrid(kinds, counts, values, bit_starts, widths, packed,
                   capacity):
    """Expand a hybrid run table to ``capacity`` int32 values (traced).

    For output i: its run via searchsorted over cumulative counts; RLE runs
    broadcast, bit-packed runs gather 4 bytes around the value's bit
    position and shift/mask. Widths are per RUN (a dictionary's bit width
    grows across pages as it fills; <= 24, so shift <= 7 + width <= 24
    keeps every value inside the 4 gathered bytes)."""
    ends = jnp.cumsum(counts)
    starts = ends - counts
    i = jnp.arange(capacity, dtype=jnp.int32)
    r = jnp.searchsorted(ends, i, side="right")
    r = jnp.clip(r, 0, kinds.shape[0] - 1)
    within = i - starts[r]
    w = widths[r]
    bit0 = bit_starts[r] + within * w
    byte0 = bit0 >> 3
    shift = (bit0 & 7).astype(jnp.uint32)
    nb = packed.shape[0]
    b = [packed[jnp.clip(byte0 + k, 0, nb - 1)].astype(jnp.uint32)
         for k in range(4)]
    word = b[0] | (b[1] << 8) | (b[2] << 16) | (b[3] << 24)
    mask = (jnp.uint32(1) << jnp.clip(w, 0, 31).astype(jnp.uint32)) \
        - jnp.uint32(1)
    packed_val = ((word >> shift) & mask).astype(jnp.int32)
    return jnp.where(kinds[r] == 1, values[r], packed_val)


def _decode_chunk_device(def_table, idx_table, packed, plain, dict_table,
                         n_rows, capacity, idx_bw, dtype,
                         dict_string: bool):
    """Traced device decode of one column chunk (see module doc)."""
    live = jnp.arange(capacity, dtype=jnp.int32) < n_rows
    dk, dc, dv, db, dw = def_table
    levels = _expand_hybrid(dk, dc, dv, db, dw, packed, capacity)
    validity = (levels == 1) & live
    # Indices/values are stored for NON-NULL slots only, compacted: row ->
    # slot via an exclusive cumsum of the validity mask.
    slot = jnp.cumsum(validity.astype(jnp.int32)) - 1
    slot = jnp.clip(slot, 0, capacity - 1)
    if idx_table is not None:
        ik, ic, iv, ib, iw = idx_table
        raw_idx = _expand_hybrid(ik, ic, iv, ib, iw, packed, capacity)
        codes = jnp.where(validity, raw_idx[slot], 0)
        if dict_string:
            rank = dict_table
            codes = jnp.where(validity,
                              rank[jnp.clip(codes, 0, rank.shape[0] - 1)], 0)
            return codes, validity
        vals = dict_table[jnp.clip(codes, 0, dict_table.shape[0] - 1)]
        data = jnp.where(validity, vals, jnp.zeros((), vals.dtype))
        return data, validity
    data = jnp.where(validity, plain[slot], jnp.zeros((), plain.dtype))
    return data, validity


def _pad_packed(packed: bytes) -> jnp.ndarray:
    raw = np.frombuffer(packed or b"\0\0\0\0", dtype=np.uint8)
    cap = bucket_byte_capacity(max(len(raw), 4), 8)
    buf = np.zeros(cap, np.uint8)
    buf[: len(raw)] = raw
    return jnp.asarray(buf)


def _runs_arrays(runs: _HybridRuns, pad_to: int):
    def arr(xs, fill):
        a = np.full(pad_to, fill, np.int32)
        a[: len(xs)] = xs
        return jnp.asarray(a)
    # Padding runs have count 0 -> they own no output positions.
    return (arr(runs.kinds, 1), arr(runs.counts, 0), arr(runs.values, 0),
            arr(runs.bit_starts, 0), arr(runs.widths, 1))


def decode_chunk(plan: ColumnChunkPlan, capacity: int) -> DeviceColumn:
    """Upload one chunk's page bytes + run tables and decode on device."""
    pad = bucket_byte_capacity(max(len(plan.def_runs.kinds),
                              len(plan.idx_runs.kinds)
                              if plan.idx_runs else 1, 1), 8)
    def_table = _runs_arrays(plan.def_runs, pad)
    idx_table = _runs_arrays(plan.idx_runs, pad) if plan.idx_runs else None
    packed_dev = _pad_packed(plan.packed)
    def _bucketed(arr, dtype):
        """Pad to a power-of-two length: unbucketed shapes would retrace
        the jitted kernel per row group (kernel_cache discipline). Also
        keeps (masked-out) gathers in range for empty dictionaries."""
        cap = bucket_byte_capacity(max(len(arr), 1), 8)
        buf = np.zeros(cap, dtype)
        buf[: len(arr)] = arr
        return jnp.asarray(buf)

    dict_string = plan.dict_rank is not None
    if dict_string:
        dict_table = _bucketed(plan.dict_rank, np.int32)
    elif plan.dict_values is not None:
        dict_table = _bucketed(plan.dict_values, plan.dict_values.dtype)
    else:
        dict_table = None
    plain = None
    if plan.plain_values is not None:
        buf = np.zeros(capacity, plan.plain_values.dtype)
        buf[: len(plan.plain_values)] = plan.plain_values
        plain = jnp.asarray(buf)

    idx_bw, dtype = plan.idx_bit_width, plan.dtype

    def build():
        def kern(dt, it, pk, pl, dtab, n):
            return _decode_chunk_device(dt, it, pk, pl, dtab, n, capacity,
                                        idx_bw, dtype, dict_string)
        return kern
    kern = cached_kernel(
        "parquet_decode",
        (dtype.name, capacity, idx_bw, idx_table is not None, dict_string,
         plain is not None, pad),
        build)
    data, validity = kern(def_table, idx_table, packed_dev, plain,
                          dict_table, jnp.asarray(plan.n_rows, jnp.int32))
    if dict_string:
        max_bytes = 8
        if plan.dict_offsets is not None and len(plan.dict_offsets) > 1:
            max_bytes = bucket_byte_capacity(
                int(np.diff(plan.dict_offsets).max() or 1), 8)
        byte_cap = bucket_byte_capacity(max(int(plan.dict_offsets[-1]), 1))
        payload = np.zeros(byte_cap, np.uint8)
        payload[: len(plan.dict_payload)] = plan.dict_payload
        return DeviceColumn(
            data=jnp.asarray(payload), validity=validity, dtype=T.STRING,
            offsets=jnp.asarray(plan.dict_offsets), max_bytes=max_bytes,
            codes=data, dict_sorted=True)
    return DeviceColumn(data=data, validity=validity, dtype=plan.dtype)


def decode_row_group(path: str, row_group: int, schema: T.Schema,
                     pf=None, meta=None, pq_schema=None) -> ColumnarBatch:
    """Decode one row group of a parquet file into a device batch.
    Pass either an open ``pyarrow.parquet.ParquetFile`` or its parsed
    ``(meta, pq_schema)`` to amortize the footer parse across a file's
    row groups (metadata objects hold no file descriptor)."""
    import pyarrow.parquet as pq
    if meta is None:
        if pf is None:
            pf = pq.ParquetFile(path)
        meta, pq_schema = pf.metadata, pf.schema
    md = meta.row_group(row_group)
    name_to_idx = {md.column(i).path_in_schema: i
                   for i in range(md.num_columns)}
    cols = []
    n_rows = md.num_rows
    capacity = bucket_capacity(max(n_rows, 1))
    with open(path, "rb") as f:
        for field in schema:
            ci = name_to_idx[field.name]
            plan = plan_column_chunk(
                f, md.column(ci), field,
                pq_schema.column(ci).max_definition_level)
            cols.append(decode_chunk(plan, capacity))
    return ColumnarBatch(tuple(cols), jnp.asarray(n_rows, jnp.int32),
                         schema)


class SparkUpgradeError(RuntimeError):
    """Ambiguous legacy-calendar datetimes (the SparkUpgradeException the
    reference raises via RebaseHelper.newRebaseExceptionInRead)."""


#: Proleptic/Julian switchover bounds (RebaseDateTime.lastSwitchJulianDay/
#: Ts): dates before 1582-10-15 and timestamps before 1900-01-01 differ
#: between the hybrid and proleptic Gregorian calendars.
_JULIAN_SWITCH_DATE = _dt.date(1582, 10, 15)
_JULIAN_SWITCH_TS = _dt.datetime(1900, 1, 1)
_LEGACY_MARKER = b"org.apache.spark.legacyDateTime"


def rebase_guard(meta, schema: T.Schema, mode: str, path: str) -> None:
    """The RebaseHelper.isDateTimeRebaseNeededRead analog
    (reference RebaseHelper.scala:60,82): files written by Spark 2.x /
    legacy Hive carry the legacyDateTime marker and a hybrid-calendar
    encoding for ancient datetimes. This reader never rebases, so under
    the default EXCEPTION mode a marked file whose date/timestamp
    statistics reach (or may reach — stats absent) below the 1582-10-15 /
    1900-01-01 switchover raises instead of silently mis-reading;
    CORRECTED reads raw values as proleptic, LEGACY is unsupported."""
    mode = (mode or "EXCEPTION").upper()
    if mode == "CORRECTED":
        return
    kv = meta.metadata or {}
    if _LEGACY_MARKER not in kv:
        return      # proleptic writer: nothing ambiguous
    if mode == "LEGACY":
        raise SparkUpgradeError(
            f"{path}: LEGACY datetime rebase is not supported on the TPU "
            "parquet reader (reference raises the same; "
            "RebaseHelper.scala:66). Set "
            "spark.sql.legacy.parquet.datetimeRebaseModeInRead=CORRECTED "
            "to read raw proleptic values.")
    dt_names = {f.name for f in schema
                if f.data_type in (T.DATE, T.TIMESTAMP)}
    if not dt_names:
        return
    for rg in range(meta.num_row_groups):
        md = meta.row_group(rg)
        for ci in range(md.num_columns):
            c = md.column(ci)
            if c.path_in_schema not in dt_names:
                continue
            st = c.statistics
            ancient = True      # stats absent: conservative
            if st is not None and st.has_min_max:
                mn = st.min
                if isinstance(mn, _dt.datetime):
                    ancient = mn.replace(tzinfo=None) < _JULIAN_SWITCH_TS
                elif isinstance(mn, _dt.date):
                    ancient = mn < _JULIAN_SWITCH_DATE
            if ancient:
                raise SparkUpgradeError(
                    f"{path}: reading dates before 1582-10-15 or "
                    "timestamps before 1900-01-01T00:00:00Z from parquet "
                    "files written with the legacy hybrid calendar is "
                    "ambiguous (SPARK-31404); this reader does not rebase. "
                    "Set spark.sql.legacy.parquet."
                    "datetimeRebaseModeInRead=CORRECTED to read the raw "
                    "values as-is.")


class TpuParquetScanExec:
    """Device parquet scan: one partition per (file, row group); each batch
    decodes ON DEVICE from uploaded page bytes (the GpuParquetScan +
    Table.readParquet split). A row group outside the decoder's scope
    falls back to a host pyarrow read + upload for JUST that row group —
    the reference's graceful per-unit degradation."""

    columnar = True
    children = ()
    children_coalesce_goals = None

    def __init__(self, files: List[str], schema: T.Schema, pf_cache=None):
        self.files = list(files)
        self._schema = schema
        # Parsed footers carried from the planning-time gate so each one
        # parses ONCE: {path: (FileMetaData, ParquetSchema)} — metadata
        # objects only, NOT open file handles (a thousand-file scan must
        # not pin a thousand descriptors from plan time). Excluded from
        # plan signatures via PLAN_SIG_SKIP_ATTRS.
        self._pf_cache = dict(pf_cache or {})

    @property
    def schema(self):
        return self._schema

    def node_name(self):
        return "TpuParquetScanExec"

    def describe(self):
        return f"TpuParquetScan files={len(self.files)}"

    def tree_string(self, indent: int = 0) -> str:
        return "  " * indent + self.describe() + "\n"

    def with_children(self, children):
        assert not children
        return self

    def execute(self, ctx):
        import pyarrow.parquet as pq
        from ..config import PARQUET_REBASE_READ
        rebase_mode = ctx.conf.get(PARQUET_REBASE_READ)
        units = []
        for path in self.files:
            cached = self._pf_cache.get(path)
            if cached is None:
                with pq.ParquetFile(path) as pf:
                    cached = (pf.metadata, pf.schema)
            meta, pq_schema = cached
            # Raised HERE, outside the per-row-group fallback, so the
            # ambiguity error cannot be swallowed by the host-read path.
            rebase_guard(meta, self._schema, rebase_mode, path)
            units.extend((path, meta, pq_schema, rg)
                         for rg in range(meta.num_row_groups))

        name = self.node_name()

        def read_unit(unit):
            path, meta, pq_schema, rg = unit
            from ..utils.fault_injection import maybe_inject
            from ..utils.tracing import trace_range
            n_rows = meta.row_group(rg).num_rows
            try:
                maybe_inject(ctx, "io.parquet.rowGroup")
                with ctx.registry.timer(name, "opTime",
                                        trace="parquet.device_decode"):
                    batch = decode_row_group(path, rg, self._schema,
                                             meta=meta, pq_schema=pq_schema)
                ctx.metric(name, "deviceDecodedRowGroups", 1)
            # ANY decode failure (unsupported shape, decompression codec
            # mismatch, corrupt/truncated page metadata) degrades to the
            # host reader for just this row group — the host result is the
            # correctness baseline, so falling back is always safe.
            except Exception:  # noqa: BLE001 - graceful per-unit fallback
                with trace_range("parquet.host_fallback"), \
                        pq.ParquetFile(path) as pf:
                    tbl = pf.read_row_group(
                        rg, columns=self._schema.names)
                    rb = tbl.combine_chunks().to_batches()[0] \
                        if tbl.num_rows else None
                    import pyarrow as pa
                    if rb is None:
                        rb = pa.RecordBatch.from_pydict(
                            {n: [] for n in self._schema.names},
                            schema=T.schema_to_arrow(self._schema))
                    batch = ColumnarBatch.from_arrow(
                        rb.cast(T.schema_to_arrow(self._schema)))
                ctx.metric(name, "hostFallbackRowGroups", 1)
            ctx.metric(name, "numOutputRows", n_rows)
            ctx.metric(name, "numOutputBatches", 1)
            return batch
        # One partition per row group (the scan partition contract), but
        # with the pipeline active the next `prefetchDepth` units decode
        # on the shared pool while the consumer uploads/dispatches the
        # current one — the reference's overlapped readPartFile stance.
        from ..exec.pipeline import unit_partitions
        return unit_partitions(read_unit, units, ctx, name)


def scan_files(paths: List[str]) -> Optional[List[str]]:
    """Concrete parquet files behind a scan's paths (None when the layout
    is unsupported, e.g. hive-partitioned directories)."""
    import os
    import pyarrow.dataset as pads
    try:
        src = paths[0] if len(paths) == 1 else paths
        if len(paths) == 1 and os.path.isdir(paths[0]):
            # Hive layouts carry partition columns in directory names that
            # the file-level decoder cannot restore — host path handles it.
            d = pads.dataset(src, format="parquet", partitioning="hive")
            if any("=" in os.path.basename(os.path.dirname(f))
                   for f in d.files):
                return None
            return list(d.files)
        return list(pads.dataset(src, format="parquet").files)
    except Exception:
        return None


def device_decodable(path: str, schema: T.Schema, pf=None) -> bool:
    """Cheap metadata-only check: can every SELECTED column of every row
    group go through the device decoder? (The graceful-fallback gate.)"""
    import pyarrow.parquet as pq
    try:
        if pf is None:
            pf = pq.ParquetFile(path)
    except Exception:
        return False
    for field in schema:
        if isinstance(field.data_type, (T.ArrayType, T.StructType)):
            return False
    file_cols = set(pf.schema_arrow.names)
    if not set(schema.names) <= file_cols:
        return False
    md = pf.metadata
    wanted = set(schema.names)
    for rg in range(md.num_row_groups):
        g = md.row_group(rg)
        for ci in range(g.num_columns):
            cm = g.column(ci)
            if cm.path_in_schema not in wanted:
                continue  # pruned away; its shape is irrelevant
            if cm.physical_type not in _PHYS_NP and \
                    cm.physical_type != "BYTE_ARRAY":
                return False
            encs = set(cm.encodings)
            # NOTE: "PLAIN" always appears (the dictionary page itself is
            # PLAIN-encoded), so a byte-array chunk that actually fell back
            # to PLAIN data pages is indistinguishable here — the
            # authoritative gate is plan_column_chunk raising at scan time,
            # which the scan catches to fall back to the host path.
            if not encs <= {"PLAIN", "PLAIN_DICTIONARY", "RLE_DICTIONARY",
                            "RLE", "BIT_PACKED"}:
                return False
            # No "LZ4": parquet's legacy LZ4 is Hadoop-block-framed, which
            # pa.Codec("lz4") (frame format) cannot decode.
            if cm.compression not in ("UNCOMPRESSED", "SNAPPY", "ZSTD",
                                      "GZIP"):
                return False
    return True
