"""Device-side parquet encode — the ``Table.writeParquetChunked`` analog.

The reference encodes parquet ON DEVICE and streams finished buffers to
the filesystem (``GpuParquetFileFormat.scala:243`` ->
``Table.writeParquetChunked``, ``ColumnarOutputWriter.scala:37``). This is
the inverse of :mod:`.parquet_device`'s decode split, and the work divides
the same way in reverse:

* DEVICE (data-sized work): one traced kernel per batch compacts each
  column — live rows in lane order, then non-null values scattered dense
  by a cumsum index — so the page VALUES buffer and the def-level bits
  leave the device already in encoding order. Dictionary string columns
  ship their (small) dictionary plus compacted int32 codes; no string
  bytes are rematerialized per row.
* HOST (metadata-sized work): RLE/bit-pack the downloaded def-level and
  dictionary-code lanes (vectorized numpy, run-table style), frame pages,
  write thrift-compact PageHeaders and the FileMetaData footer.

Scope (per-FILE fallback to the host Arrow writer otherwise, the same
graceful-degradation contract as the decoders): flat schemas; INT32/INT64/
FLOAT/DOUBLE/BOOLEAN/DATE/TIMESTAMP plain encoding, dictionary-encoded
strings as PLAIN dictionary page + RLE_DICTIONARY data page; optional
values via RLE def-levels; one row group per file; UNCOMPRESSED or SNAPPY
data pages. Files are readable by pyarrow AND by this engine's own device
decoder (round-trip differentials in tests/test_parquet_encode.py).
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from .. import types as T
from ..data.batch import ColumnarBatch
from ..data.column import DeviceColumn
from ..utils.kernel_cache import cached_kernel


class NotDeviceEncodable(Exception):
    """Column/type outside the device encoder's scope; caller falls back."""


# ---------------------------------------------------------------------------
# Thrift compact protocol WRITER (inverse of parquet_device._Thrift)
# ---------------------------------------------------------------------------

_T_BOOL_TRUE = 1
_T_BOOL_FALSE = 2
_T_BYTE = 3
_T_I16 = 4
_T_I32 = 5
_T_I64 = 6
_T_DOUBLE = 7
_T_BINARY = 8
_T_LIST = 9
_T_STRUCT = 12


def _varint(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _zigzag(v: int) -> bytes:
    return _varint((v << 1) ^ (v >> 63))


class _ThriftWriter:
    """Just enough of the thrift compact protocol for parquet metadata."""

    def __init__(self):
        self.buf = bytearray()
        self._last_fid = [0]

    def _field(self, fid: int, ftype: int):
        delta = fid - self._last_fid[-1]
        if 1 <= delta <= 15:
            self.buf.append((delta << 4) | ftype)
        else:
            self.buf.append(ftype)
            self.buf += _zigzag(fid)
        self._last_fid[-1] = fid

    def i32(self, fid: int, v: int):
        self._field(fid, _T_I32)
        self.buf += _zigzag(v)

    def i64(self, fid: int, v: int):
        self._field(fid, _T_I64)
        self.buf += _zigzag(v)

    def string(self, fid: int, s: str):
        self._field(fid, _T_BINARY)
        raw = s.encode("utf-8")
        self.buf += _varint(len(raw))
        self.buf += raw

    def struct_begin(self, fid: int):
        self._field(fid, _T_STRUCT)
        self._last_fid.append(0)

    def struct_end(self):
        self.buf.append(0x00)
        self._last_fid.pop()

    def list_begin(self, fid: int, elem_type: int, size: int):
        self._field(fid, _T_LIST)
        if size < 15:
            self.buf.append((size << 4) | elem_type)
        else:
            self.buf.append(0xF0 | elem_type)
            self.buf += _varint(size)

    def i32_elem(self, v: int):
        self.buf += _zigzag(v)

    def done(self) -> bytes:
        self.buf.append(0x00)   # terminate the top-level struct
        return bytes(self.buf)


# ---------------------------------------------------------------------------
# Physical-type mapping
# ---------------------------------------------------------------------------

_PQ_BOOLEAN, _PQ_INT32, _PQ_INT64, _PQ_FLOAT, _PQ_DOUBLE, _PQ_BYTE_ARRAY = \
    0, 1, 2, 4, 5, 6
_ENC_PLAIN, _ENC_RLE, _ENC_RLE_DICTIONARY, _ENC_PLAIN_DICTIONARY = 0, 3, 8, 2
_CODEC_UNCOMPRESSED, _CODEC_SNAPPY = 0, 1

#: engine type -> (parquet physical type, converted_type or None)
_PHYS: Dict[str, Tuple[int, Optional[int]]] = {
    "int": (_PQ_INT32, None),
    "bigint": (_PQ_INT64, None),
    "float": (_PQ_FLOAT, None),
    "double": (_PQ_DOUBLE, None),
    "boolean": (_PQ_BOOLEAN, None),
    "date": (_PQ_INT32, 6),            # DATE converted type
    "timestamp": (_PQ_INT64, 10),      # TIMESTAMP_MICROS
    "smallint": (_PQ_INT32, 16),       # INT_16
    "tinyint": (_PQ_INT32, 15),        # INT_8
    "string": (_PQ_BYTE_ARRAY, 0),     # UTF8
}

#: PLAIN-encoding value dtype per parquet physical type (BOOLEAN and
#: BYTE_ARRAY are bit-/length-encoded, not fixed-width).
_PHYS_NP: Dict[int, np.dtype] = {
    _PQ_INT32: np.dtype(np.int32),
    _PQ_INT64: np.dtype(np.int64),
    _PQ_FLOAT: np.dtype(np.float32),
    _PQ_DOUBLE: np.dtype(np.float64),
}


def encoded_value_dtype(dtype: T.DataType) -> Optional[np.dtype]:
    """The numpy dtype the PLAIN value stream serializes for one engine
    type — the declared physical width, not the device lane width
    (smallint/tinyint lanes are int16/int8 but declare INT32). The plan
    verifier (analysis/plan_lint.py) cross-checks this against its own
    copy of the parquet spec widths."""
    if dtype.name not in _PHYS:
        return None
    phys, _ = _PHYS[dtype.name]
    return _PHYS_NP.get(phys)


# ---------------------------------------------------------------------------
# Device compaction kernel
# ---------------------------------------------------------------------------


def _build_compact():
    def run(batch: ColumnarBatch):
        live = batch.row_mask()
        cap = batch.capacity
        live_pos = jnp.cumsum(live) - 1      # position of each live row
        outs = []
        for c in batch.columns:
            valid = c.validity & live
            # def-levels, compacted to live-row order
            defl = jnp.zeros(cap, jnp.bool_).at[
                jnp.where(live, live_pos, cap)].set(c.validity, mode="drop")
            vals_src = c.codes if c.codes is not None else c.data
            val_pos = jnp.cumsum(valid) - 1
            vals = jnp.zeros(cap, vals_src.dtype).at[
                jnp.where(valid, val_pos, cap)].set(vals_src, mode="drop")
            outs.append((defl, vals, valid.sum()))
        return tuple(outs), batch.n_rows
    return run


def _compact_columns(batch: ColumnarBatch):
    """One traced program for the whole batch: per column, (validity in
    live-row order, values dense in non-null order, dict codes dense).
    Invalid/dead lanes scatter to a dropped out-of-bounds slot."""
    key = (batch.capacity, batch.live is not None,
           tuple(f.data_type.name for f in batch.schema),
           tuple(c.codes is not None for c in batch.columns))
    fn = cached_kernel("parquet_encode.compact", key, _build_compact)
    return fn(batch)


# ---------------------------------------------------------------------------
# Host-side RLE / bit-pack framing
# ---------------------------------------------------------------------------


def _rle_runs(values: np.ndarray) -> List[Tuple[int, int]]:
    """(run_length, value) pairs over an int array (vectorized breaks)."""
    n = len(values)
    if n == 0:
        return []
    breaks = np.nonzero(values[1:] != values[:-1])[0] + 1
    starts = np.concatenate(([0], breaks))
    ends = np.concatenate((breaks, [n]))
    return [(int(e - s), int(values[s])) for s, e in zip(starts, ends)]


def _rle_encode(values: np.ndarray, bit_width: int) -> bytes:
    """Parquet RLE/bit-pack hybrid, RLE runs only (def levels and dict
    codes compress well as runs; bit-packed fallback kicks in when runs
    are short)."""
    byte_w = (bit_width + 7) // 8
    out = bytearray()
    runs = _rle_runs(values)
    # Heuristic: many tiny runs -> bit-pack groups of 8 instead.
    if bit_width and runs and len(runs) > max(4, len(values) // 4):
        return _bitpack_encode(values, bit_width)
    for count, value in runs:
        out += _varint(count << 1)
        out += int(value).to_bytes(byte_w, "little") if byte_w else b""
    return bytes(out)


def _bitpack_encode(values: np.ndarray, bit_width: int) -> bytes:
    n = len(values)
    groups = (n + 7) // 8
    padded = np.zeros(groups * 8, np.uint64)
    padded[:n] = values.astype(np.uint64)
    # Little-endian bit order within each group.
    bits = ((padded[:, None] >> np.arange(bit_width, dtype=np.uint64))
            & 1).astype(np.uint8)           # [8g, bw]
    flat = bits.reshape(-1)                  # value-major LSB-first
    packed = np.packbits(flat, bitorder="little")
    out = bytearray(_varint((groups << 1) | 1))
    out += packed.tobytes()
    return bytes(out)


def _length_prefixed(payload: bytes) -> bytes:
    return struct.pack("<I", len(payload)) + payload


def _compress(payload: bytes, codec: int) -> bytes:
    if codec == _CODEC_UNCOMPRESSED:
        return payload
    return pa.Codec("snappy").compress(payload).to_pybytes()


# ---------------------------------------------------------------------------
# Page assembly
# ---------------------------------------------------------------------------


def _page_header(page_type: int, uncomp: int, comp: int, num_values: int,
                 encoding: int) -> bytes:
    w = _ThriftWriter()
    w.i32(1, page_type)
    w.i32(2, uncomp)
    w.i32(3, comp)
    if page_type == 0:        # data page v1
        w.struct_begin(5)
        w.i32(1, num_values)
        w.i32(2, encoding)
        w.i32(3, _ENC_RLE)    # definition levels
        w.i32(4, _ENC_RLE)    # repetition levels (none written: flat)
        w.struct_end()
    else:                     # dictionary page
        w.struct_begin(7)
        w.i32(1, num_values)
        w.i32(2, _ENC_PLAIN)
        w.struct_end()
    return w.done()


def _plain_values(vals: np.ndarray, dtype: T.DataType, n_valid: int) -> bytes:
    v = vals[:n_valid]
    if dtype is T.BOOLEAN:
        return np.packbits(v.astype(np.uint8), bitorder="little").tobytes()
    phys_np = encoded_value_dtype(dtype)
    if phys_np is not None and v.dtype != phys_np:
        # The device lane is narrower than the declared physical type
        # (smallint/tinyint are int16/int8 on device, INT32 in the file):
        # widen to the declared width or readers see a truncated stream.
        v = v.astype(phys_np)
    return np.ascontiguousarray(v).tobytes()


def _string_dict_plain(col: DeviceColumn) -> Tuple[bytes, int]:
    """PLAIN-encode the dictionary entries (4-byte LE length + bytes) —
    fully vectorized; uploads dict-encode every string column, so a
    near-unique column makes the dictionary row-count-sized."""
    offs = np.asarray(col.offsets).astype(np.int64)
    n = len(offs) - 1
    payload_end = int(offs[-1])
    payload = np.asarray(col.data, dtype=np.uint8)[:payload_end]
    lens = np.diff(offs).astype("<u4")
    out = np.zeros(4 * n + payload_end, np.uint8)
    # Each entry's 4-byte length lands at 4*i + (payload bytes before it).
    len_pos = 4 * np.arange(n, dtype=np.int64) + (offs[:-1])
    len_bytes = lens.view(np.uint8).reshape(n, 4)
    for b in range(4):
        out[len_pos + b] = len_bytes[:, b]
    # Payload byte j belongs to entry e(j); it shifts right by 4*(e(j)+1).
    if payload_end:
        byte_entry = np.repeat(np.arange(n, dtype=np.int64),
                               np.diff(offs))
        out[np.arange(payload_end, dtype=np.int64)
            + 4 * (byte_entry + 1)] = payload
    return out.tobytes(), n


class _ColumnPlan:
    __slots__ = ("name", "dtype", "phys", "conv", "nullable", "is_dict",
                 "dict_bytes", "dict_n")

    def __init__(self, field: T.StructField, col: DeviceColumn):
        self.name = field.name
        self.dtype = field.data_type
        if self.dtype.name not in _PHYS:
            raise NotDeviceEncodable(f"type {self.dtype} not encodable")
        self.phys, self.conv = _PHYS[self.dtype.name]
        self.nullable = field.nullable
        self.is_dict = col.codes is not None
        if self.dtype is T.STRING and not self.is_dict:
            raise NotDeviceEncodable("flat (non-dictionary) string column")
        self.dict_bytes = None
        self.dict_n = 0


def write_device_batch(batch: ColumnarBatch, path: str,
                       compression: Optional[str] = "snappy") -> int:
    """Encode one device batch as a single-row-group parquet file.

    Returns bytes written. Raises :class:`NotDeviceEncodable` BEFORE
    touching the filesystem when any column is out of scope, so the
    caller's host fallback writes the whole file instead."""
    schema = batch.schema
    plans = [_ColumnPlan(f, c) for f, c in zip(schema, batch.columns)]
    if compression in (None, "none", "uncompressed"):
        codec = _CODEC_UNCOMPRESSED
    elif compression == "snappy":
        codec = _CODEC_SNAPPY
    else:
        raise NotDeviceEncodable(f"codec {compression!r} not encodable")

    compacted, n_rows_dev = _compact_columns(batch)
    n_rows = int(n_rows_dev)

    chunks: List[bytes] = []
    metas: List[Dict] = []
    offset = 4  # after magic
    for plan, col, (defl_dev, vals_dev, nv_dev) in zip(
            plans, batch.columns, compacted):
        defl = np.asarray(defl_dev)[:n_rows]
        n_valid = int(nv_dev)
        vals = np.asarray(vals_dev)
        piece = bytearray()
        dict_off = None
        uncomp_total = 0
        encodings = [_ENC_RLE]
        if plan.is_dict:
            dict_plain, dict_n = _string_dict_plain(col)
            payload = _compress(dict_plain, codec)
            dict_off = offset + len(piece)
            hdr = _page_header(2, len(dict_plain), len(payload), dict_n,
                               _ENC_PLAIN)
            piece += hdr
            piece += payload
            uncomp_total += len(hdr) + len(dict_plain)
            bw = max(int(dict_n - 1).bit_length(), 1)
            body = bytes([bw]) + _rle_encode(vals[:n_valid], bw)
            enc = _ENC_RLE_DICTIONARY
            encodings += [_ENC_PLAIN, _ENC_RLE_DICTIONARY]
        else:
            body = _plain_values(vals, plan.dtype, n_valid)
            enc = _ENC_PLAIN
            encodings += [_ENC_PLAIN]
        if plan.nullable:
            levels = _length_prefixed(_rle_encode(defl.astype(np.int64), 1))
        else:
            levels = b""
        data_plain = levels + body
        payload = _compress(data_plain, codec)
        data_off = offset + len(piece)
        hdr = _page_header(0, len(data_plain), len(payload), n_rows, enc)
        piece += hdr
        piece += payload
        uncomp_total += len(hdr) + len(data_plain)
        metas.append(dict(plan=plan, dict_off=dict_off, data_off=data_off,
                          encodings=encodings, n_values=n_rows,
                          total=len(piece), uncomp=uncomp_total,
                          start=offset))
        chunks.append(bytes(piece))
        offset += len(piece)

    footer = _file_metadata(schema, plans, metas, n_rows, codec)
    with open(path, "wb") as f:
        f.write(b"PAR1")
        for ch in chunks:
            f.write(ch)
        f.write(footer)
        f.write(struct.pack("<I", len(footer)))
        f.write(b"PAR1")
    return 8 + sum(len(c) for c in chunks) + len(footer) + 4


def _file_metadata(schema: T.Schema, plans: List[_ColumnPlan],
                   metas: List[Dict], n_rows: int, codec: int) -> bytes:
    w = _ThriftWriter()
    w.i32(1, 1)                                   # version
    w.list_begin(2, _T_STRUCT, len(plans) + 1)    # schema elements
    # List elements carry no field headers; each struct body opens a fresh
    # field-id frame (compact-protocol deltas are per-struct).
    w._last_fid.append(0)                         # root element
    w.string(4, "schema")
    w.i32(5, len(plans))
    w.buf.append(0x00)
    w._last_fid.pop()
    for p in plans:
        w._last_fid.append(0)
        w.i32(1, p.phys)
        w.i32(3, 1 if p.nullable else 0)          # OPTIONAL / REQUIRED
        w.string(4, p.name)
        if p.conv is not None:
            w.i32(6, p.conv)
        w.buf.append(0x00)
        w._last_fid.pop()
    w.i64(3, n_rows)
    w.list_begin(4, _T_STRUCT, 1)                 # one row group
    w._last_fid.append(0)
    w.list_begin(1, _T_STRUCT, len(metas))        # column chunks
    total = 0
    for m in metas:
        p = m["plan"]
        w._last_fid.append(0)
        w.i64(2, m["start"])                      # file_offset
        w.struct_begin(3)                         # ColumnMetaData
        w.i32(1, p.phys)
        w.list_begin(2, _T_I32, len(m["encodings"]))
        for e in m["encodings"]:
            w.i32_elem(e)
        w.list_begin(3, _T_BINARY, 1)
        raw = p.name.encode()
        w.buf += _varint(len(raw))
        w.buf += raw
        w.i32(4, codec)
        w.i64(5, m["n_values"])
        w.i64(6, m["uncomp"])                     # total_uncompressed_size
        w.i64(7, m["total"])                      # total_compressed_size
        w.i64(9, m["data_off"])
        if m["dict_off"] is not None:
            w.i64(11, m["dict_off"])
        w.struct_end()
        w.buf.append(0x00)
        w._last_fid.pop()
        total += m["total"]
    w.i64(2, total)
    w.i64(3, n_rows)
    w.buf.append(0x00)
    w._last_fid.pop()
    w.string(6, "spark-rapids-tpu device encoder")
    return w.done()
