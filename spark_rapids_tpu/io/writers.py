"""Columnar file writers — the ``GpuFileFormatWriter`` stack analog.

The reference clones Spark's whole writer framework columnar-side (SURVEY.md
§2.5): ``ColumnarOutputWriter[Factory]`` streams cudf-encoded buffers to the
filesystem (ColumnarOutputWriter.scala:37), ``GpuFileFormatWriter.scala:338``
orchestrates the job, ``GpuFileFormatDataWriter.scala:417`` implements the
single-directory and dynamic-partition (hive-layout) writers — the dynamic
writer sorts by partition keys and switches output files on key change — and
write-stats trackers count files/partitions/rows/bytes
(BasicColumnarWriteStatsTracker.scala:168).

Same architecture here. Encoding happens host-side via Arrow (the device
parquet/ORC *encode* kernel is a later milestone, like the reference's device
decode); the TPU writer's device-side work is the dynamic-partition split:
one device sort by partition keys, then contiguous runs slice out per
partition directory — the same sort-based strategy the reference's dynamic
writer uses, but as one XLA program.
"""

from __future__ import annotations

import dataclasses
import os
import uuid
from typing import Dict, List

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from .. import types as T
from ..data.batch import HostBatch
from ..plan.physical import ExecContext, PhysicalPlan
from ..utils.tracing import trace_range

#: Spark-compatible save modes.
MODES = ("error", "overwrite", "append", "ignore")

_EXT = {"parquet": "parquet", "orc": "orc", "csv": "csv"}


@dataclasses.dataclass
class WriteStats:
    """BasicColumnarWriteStatsTracker analog."""

    files: int = 0
    partitions: int = 0
    rows: int = 0
    bytes: int = 0

    def to_batch(self) -> HostBatch:
        schema = pa.schema([("files", pa.int64()), ("partitions", pa.int64()),
                            ("rows", pa.int64()), ("bytes", pa.int64())])
        return HostBatch(pa.RecordBatch.from_arrays(
            [pa.array([self.files]), pa.array([self.partitions]),
             pa.array([self.rows]), pa.array([self.bytes])], schema=schema))


STATS_SCHEMA = T.Schema([T.StructField("files", T.LONG, False),
                         T.StructField("partitions", T.LONG, False),
                         T.StructField("rows", T.LONG, False),
                         T.StructField("bytes", T.LONG, False)])


def _write_one(data, fmt: str, path: str, options: Dict) -> int:
    """Encode one file; returns bytes written (ColumnarOutputWriter analog)."""
    table = data if isinstance(data, pa.Table) else pa.Table.from_batches(
        [data])
    compression = options.get("compression")
    with trace_range(f"write.{fmt}"):
        if fmt == "parquet":
            import pyarrow.parquet as pq
            pq.write_table(table, path,
                           compression=compression or "snappy")
        elif fmt == "orc":
            import pyarrow.orc as orc
            orc.write_table(table, path)
        elif fmt == "csv":
            import pyarrow.csv as pacsv
            opts = pacsv.WriteOptions(
                include_header=bool(options.get("header", True)),
                delimiter=options.get("delimiter", ","))
            pacsv.write_csv(table, path, opts)
        else:
            raise ValueError(f"unknown write format {fmt}")
    return os.path.getsize(path)


#: Characters Spark escapes in partition directory names
#: (ExternalCatalogUtils.escapePathName): controls + these ASCII specials.
_ESCAPE_CHARS = set('"#%\'*/:=?\\{[]^\x7f') | {chr(c) for c in range(0x20)}


def _escape_path_name(s: str) -> str:
    return "".join(f"%{ord(c):02X}" if c in _ESCAPE_CHARS else c for c in s)


def _partition_dir_value(v) -> str:
    if v is None:
        return "__HIVE_DEFAULT_PARTITION__"
    if isinstance(v, bool):
        return str(v).lower()
    return _escape_path_name(str(v))


def prepare_target(path: str, mode: str) -> bool:
    """Apply the save mode; returns False when the write should be skipped
    (mode=ignore on existing target)."""
    assert mode in MODES, mode
    exists = os.path.exists(path) and (not os.path.isdir(path)
                                       or bool(os.listdir(path)))
    if exists:
        if mode == "error":
            raise FileExistsError(
                f"path {path} already exists (SaveMode.ErrorIfExists)")
        if mode == "ignore":
            return False
        if mode == "overwrite":
            if os.path.isdir(path):
                import shutil
                shutil.rmtree(path, ignore_errors=True)
            else:
                os.remove(path)
    os.makedirs(path, exist_ok=True)
    return True


def run_boundaries(key_cols: List[pa.ChunkedArray], n: int) -> List[int]:
    """Indices where any sorted partition-key column changes (vectorized
    shifted comparison; two nulls compare equal)."""
    if n == 0:
        return [0]
    neq = None
    for c in key_cols:
        c = c.combine_chunks() if isinstance(c, pa.ChunkedArray) else c
        a, b = c.slice(1), c.slice(0, n - 1)
        d = pc.fill_null(pc.not_equal(a, b), False)
        d = pc.or_(d, pc.xor(pc.is_null(a), pc.is_null(b)))
        neq = d if neq is None else pc.or_(neq, d)
    if neq is None:
        return [0, n]
    changed = np.nonzero(neq.to_numpy(zero_copy_only=False))[0]
    return [0] + [int(i) + 1 for i in changed] + [n]


class _WriteFilesBase(PhysicalPlan):
    """Shared writer-job skeleton (GpuFileFormatWriter analog): target prep,
    per-file encode + stats, hive subdirs, job-commit marker. Subclasses
    supply the batch stream and the dynamic-partition grouping strategy."""

    def __init__(self, child: PhysicalPlan, fmt: str, path: str,
                 options: Dict, partition_by: List[str], mode: str):
        self.children = [child]
        self.fmt = fmt
        self.path = path
        self.options = options
        self.partition_by = partition_by
        self.mode = mode
        # Unique per job so append mode never collides with the files of an
        # earlier write (Spark embeds the job UUID the same way).
        self._job_id = uuid.uuid4().hex[:8]
        self._prepare_result: bool = None
        self._emitted: set = set()

    def _prepare_once(self) -> bool:
        """Apply the save mode exactly once per plan instance: a
        dispatch-level transient retry (session._run_with_retries)
        re-executes the plan, and re-applying the mode would rmtree fresh
        output (overwrite), raise (error), or silently skip (ignore).
        A re-execution instead deletes the previous attempt's own files
        (task ids can shift when a batch split-and-retried, so
        name-overwrite alone is not a sound cleanup)."""
        if self._prepare_result is None:
            self._prepare_result = prepare_target(self.path, self.mode)
        elif self._prepare_result and self._emitted:
            for p in self._emitted:
                try:
                    os.remove(p)
                except OSError:
                    pass
            self._emitted.clear()
        return self._prepare_result

    @property
    def schema(self):
        return STATS_SCHEMA

    def describe(self):
        extra = f" partitionBy={self.partition_by}" if self.partition_by \
            else ""
        return f"{self.node_name()} {self.fmt} {self.path}{extra}"

    def _data_arrow(self) -> pa.Schema:
        fields = [f for f in self.children[0].schema
                  if f.name not in self.partition_by]
        return pa.schema([pa.field(f.name, T.to_arrow_type(f.data_type),
                                   f.nullable) for f in fields])

    def _file_name(self, task_id: int, file_no: int) -> str:
        return f"part-{task_id:05d}-{self._job_id}-{file_no:03d}" \
               f".{_EXT[self.fmt]}"

    def _emit(self, data, target_dir: str, task_id: int, file_no: int,
              stats: WriteStats, n_rows: int):
        os.makedirs(target_dir, exist_ok=True)
        target = os.path.join(target_dir, self._file_name(task_id, file_no))
        stats.bytes += _write_one(data, self.fmt, target, self.options)
        self._emitted.add(target)
        stats.files += 1
        stats.rows += n_rows

    def _emit_partition(self, table: pa.Table, key_values: tuple,
                        task_id: int, file_no: int, stats: WriteStats,
                        seen_dirs: set, data_arrow: pa.Schema):
        subdir = os.path.join(self.path, *(
            f"{c}={_partition_dir_value(v)}"
            for c, v in zip(self.partition_by, key_values)))
        seen_dirs.add(subdir)
        out = pa.Table.from_arrays(
            [table.column(nm).combine_chunks() for nm in data_arrow.names],
            schema=data_arrow)
        self._emit(out, subdir, task_id, file_no, stats, table.num_rows)

    def _finish(self, stats: WriteStats, seen_dirs: set):
        stats.partitions = len(seen_dirs)
        # Job-commit marker, like Spark's Hadoop committer.
        open(os.path.join(self.path, "_SUCCESS"), "w").close()
        return [iter([stats.to_batch()])]


class CpuWriteFilesExec(_WriteFilesBase):
    """Host-side writer job: one output file per input batch, group-by based
    dynamic partitioning."""

    def execute(self, ctx: ExecContext):
        stats = WriteStats()
        if not self._prepare_once():
            return [iter([stats.to_batch()])]
        data_arrow = self._data_arrow()
        seen_dirs: set = set()
        task_id = 0
        for part in self.children[0].execute(ctx):
            for hb in part:
                if hb.num_rows == 0:
                    continue
                self._write_batch(hb.rb, task_id, stats, seen_dirs,
                                  data_arrow)
                task_id += 1
        return self._finish(stats, seen_dirs)

    def _write_batch(self, rb: pa.RecordBatch, task_id: int,
                     stats: WriteStats, seen_dirs: set,
                     data_arrow: pa.Schema):
        if not self.partition_by:
            self._emit(rb, self.path, task_id, 0, stats, rb.num_rows)
            return
        table = pa.Table.from_batches([rb])
        key_rows = list(zip(*[table.column(c).to_pylist()
                              for c in self.partition_by]))
        groups: Dict[tuple, List[int]] = {}
        for i, kr in enumerate(key_rows):
            groups.setdefault(kr, []).append(i)
        for file_no, (kr, idxs) in enumerate(sorted(
                groups.items(), key=lambda kv: tuple(map(repr, kv[0])))):
            sub = table.take(pa.array(idxs, pa.int64()))
            self._emit_partition(sub, kr, task_id, file_no, stats, seen_dirs,
                                 data_arrow)


class TpuWriteFilesExec(_WriteFilesBase):
    """Device-side writer (GpuDataWritingCommandExec + dynamic
    GpuFileFormatDataWriter analog): batches arrive on device; the
    dynamic-partition path sorts by partition keys on device so each output
    file's rows are one contiguous run (the reference's dynamic writer relies
    on the same sorted order), then the host encoder streams each run."""

    columnar = False        # emits the host stats row...
    children_columnar = True  # ...but consumes device batches
    children_coalesce_goals = ["target"]

    def execute(self, ctx: ExecContext):
        import time as _time
        from ..config import PARQUET_DEVICE_ENCODE
        from ..memory import retry as R
        from ..ops.kernels import rowops as KR
        name = self.node_name()
        t_start = _time.perf_counter_ns()
        stats = WriteStats()
        if not self._prepare_once():
            return [iter([stats.to_batch()])]
        child_schema = self.children[0].schema
        part_ordinals = [child_schema.index_of(c) for c in self.partition_by]
        data_arrow = self._data_arrow()
        seen_dirs: set = set()
        device_encode = (self.fmt == "parquet" and not part_ordinals
                         and ctx.conf.get(PARQUET_DEVICE_ENCODE))

        def device_sort(b):
            """The writer's device-side memory hazard (dynamic-partition
            sort). File emission stays OUTSIDE the retry: a retried
            attempt must never re-write a committed file."""
            if part_ordinals:
                with trace_range("write.device_partition_sort"):
                    b = KR.sort_batch(b, part_ordinals,
                                      [True] * len(part_ordinals),
                                      [True] * len(part_ordinals))
            return b

        task_id = 0
        for part in self.children[0].execute(ctx):
            for db in part:
                if int(db.n_rows) == 0:
                    continue
                # A split input emits two (smaller) files — content is
                # identical; only the file count changes.
                for piece in R.with_retry(ctx, f"{name}.deviceWrite", db,
                                          device_sort,
                                          split=R.halve_by_rows, node=name):
                    if device_encode and self._emit_device(piece, task_id,
                                                           stats):
                        task_id += 1
                        continue
                    rb = piece.to_arrow()
                    if not part_ordinals:
                        self._emit(rb, self.path, task_id, 0, stats,
                                   rb.num_rows)
                    else:
                        self._write_sorted_runs(rb, task_id, stats,
                                                seen_dirs, data_arrow)
                    task_id += 1
        # Writer metrics mirror WriteStats (BasicColumnarWriteStatsTracker):
        # the stats row is the query result, the metrics feed the profile.
        ctx.metric(name, "numOutputRows", stats.rows)
        ctx.metric(name, "bytesWritten", stats.bytes)
        ctx.metric(name, "numFiles", stats.files)
        ctx.metric(name, "writeTime", _time.perf_counter_ns() - t_start)
        return self._finish(stats, seen_dirs)

    def _emit_device(self, db, task_id: int, stats: WriteStats) -> bool:
        """Device-encode one batch as one parquet file; False when out of
        the encoder's scope (caller falls back to the host Arrow path)."""
        from .parquet_encode import NotDeviceEncodable, write_device_batch
        target = os.path.join(self.path, self._file_name(task_id, 0))
        with trace_range("write.parquet_device_encode"):
            try:
                # `or "snappy"`: an explicit compression=None means snappy
                # on the host path too (_write_one) — keep one codec per job.
                n = write_device_batch(
                    db, target,
                    compression=self.options.get("compression") or "snappy")
            except NotDeviceEncodable:
                return False
        self._emitted.add(target)
        stats.bytes += n
        stats.files += 1
        stats.rows += int(db.n_rows)
        return True

    def _write_sorted_runs(self, rb: pa.RecordBatch, task_id: int,
                           stats: WriteStats, seen_dirs: set,
                           data_arrow: pa.Schema):
        """Slice contiguous partition-key runs out of the device-sorted
        batch; run boundaries come from one vectorized shifted comparison."""
        table = pa.Table.from_batches([rb])
        key_cols = [table.column(c) for c in self.partition_by]
        bounds = run_boundaries(key_cols, rb.num_rows)
        for file_no in range(len(bounds) - 1):
            lo, hi = bounds[file_no], bounds[file_no + 1]
            kr = tuple(kc[lo].as_py() for kc in key_cols)
            self._emit_partition(table.slice(lo, hi - lo), kr, task_id,
                                 file_no, stats, seen_dirs, data_arrow)
