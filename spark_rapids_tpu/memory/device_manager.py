"""Device manager — the ``GpuDeviceManager`` analog.

The reference acquires the single GPU per executor, initializes the RMM pool
with a fraction of VRAM, and wires the spill event handler
(GpuDeviceManager.scala:120-214). JAX/XLA owns HBM allocation on TPU, so the
TPU-native analog manages: backend selection, the one-device invariant for
local execution, HBM budget accounting for the spill framework, and the task
semaphore bootstrap. Multi-chip execution goes through the mesh layer
(:mod:`..parallel.mesh`) instead of one-process-per-device.
"""

from __future__ import annotations

import threading
from typing import Optional

import jax

from ..config import (CONCURRENT_TPU_TASKS, DEVICE_BACKEND,
                      DEVICE_SPILL_BUDGET, HBM_ALLOC_FRACTION,
                      HOST_SPILL_STORAGE_SIZE, MEMORY_DEBUG, SPILL_DIR,
                      TpuConf)
from .semaphore import TpuSemaphore


class DeviceManager:
    _instances: dict = {}
    _lock = threading.Lock()

    def __init__(self, conf: TpuConf):
        backend = conf.get(DEVICE_BACKEND)
        self.devices = (jax.devices(backend) if backend else jax.devices())
        self.device = self.devices[0]
        self.debug = conf.get(MEMORY_DEBUG)
        # HBM budget for the spill framework; jax doesn't expose exact HBM
        # sizes for every backend, so fall back to a conservative default.
        frac = conf.get(HBM_ALLOC_FRACTION)
        try:
            stats = self.device.memory_stats() or {}
            total = stats.get("bytes_limit", 16 << 30)
        except Exception:
            total = 16 << 30
        self.hbm_budget_bytes = int(total * frac)
        self.semaphore = TpuSemaphore(conf.get(CONCURRENT_TPU_TASKS))
        # Spill catalog: the GpuShuffleEnv.initStorage chain
        # (device -> host -> disk, GpuShuffleEnv.scala:52-69).
        from .spill import BufferCatalog
        explicit = conf.get(DEVICE_SPILL_BUDGET)
        self.catalog = BufferCatalog(
            explicit if explicit > 0 else self.hbm_budget_bytes,
            conf.get(HOST_SPILL_STORAGE_SIZE),
            conf.get(SPILL_DIR))

    @classmethod
    def get_or_create(cls, conf: TpuConf) -> "DeviceManager":
        # One manager per distinct device/memory configuration: sessions that
        # override spill budgets or directories (test hooks) must not silently
        # inherit the first session's catalog.
        key = (conf.get(DEVICE_BACKEND), conf.get(HBM_ALLOC_FRACTION),
               conf.get(DEVICE_SPILL_BUDGET),
               conf.get(HOST_SPILL_STORAGE_SIZE), conf.get(SPILL_DIR),
               conf.get(CONCURRENT_TPU_TASKS))
        with cls._lock:
            inst = cls._instances.get(key)
            if inst is None:
                inst = cls._instances[key] = DeviceManager(conf)
            return inst

    @classmethod
    def reset(cls):
        with cls._lock:
            for inst in cls._instances.values():
                inst.catalog.close()
            cls._instances.clear()

    def memory_in_use(self) -> int:
        try:
            stats = self.device.memory_stats() or {}
            return stats.get("bytes_in_use", 0)
        except Exception:
            return 0
