"""Device manager — the ``GpuDeviceManager`` analog.

The reference acquires the single GPU per executor, initializes the RMM pool
with a fraction of VRAM, and wires the spill event handler
(GpuDeviceManager.scala:120-214). JAX/XLA owns HBM allocation on TPU, so the
TPU-native analog manages: backend selection, the one-device invariant for
local execution, HBM budget accounting for the spill framework, and the task
semaphore bootstrap. Multi-chip execution goes through the mesh layer
(:mod:`..parallel.mesh`) instead of one-process-per-device.

Backend init is LAZY: constructing a session (CPU-oracle sessions included,
``sql.enabled=false``) must never initialize the accelerator backend — the
reference likewise only touches the GPU from the *executor* plugin, never on
the driver (Plugin.scala:104-143). ``jax.devices()`` on a broken/unreachable
TPU backend can hang or raise; deferring it to first device use keeps pure
host paths (oracle runs, planning, explain) alive regardless.
"""

from __future__ import annotations

import logging

from ..config import (CONCURRENT_ACQUIRE_TIMEOUT, CONCURRENT_TPU_TASKS,
                      DEVICE_BACKEND, DEVICE_SPILL_BUDGET,
                      HBM_ALLOC_FRACTION, HOST_SPILL_STORAGE_SIZE,
                      MEMORY_DEBUG, SPILL_DIR, SPILL_IO_THREADS, TpuConf)
from ..utils import lockdep
from .semaphore import TpuSemaphore

#: Conservative HBM guess used when the backend can't report a size (CPU
#: backend, or device never touched). Matches the reference's stance of a
#: fraction-of-total pool (RapidsConf.scala:257).
_DEFAULT_HBM_BYTES = 16 << 30

#: Probe-shaped failures of ``device.memory_stats()``: the backend simply
#: cannot report (CPU backends, plugin API drift). Tolerated alongside the
#: retry taxonomy's OOM/transient classes; anything else raises.
_PROBE_ERRORS = (NotImplementedError, AttributeError, TypeError,
                 ValueError, KeyError)


class DeviceManager:
    _instances: dict = {}
    _lock = lockdep.lock("DeviceManager._lock")

    def __init__(self, conf: TpuConf):
        self._backend = conf.get(DEVICE_BACKEND)
        self._frac = conf.get(HBM_ALLOC_FRACTION)
        self.debug = conf.get(MEMORY_DEBUG)
        self.semaphore = TpuSemaphore(
            conf.get(CONCURRENT_TPU_TASKS),
            conf.get(CONCURRENT_ACQUIRE_TIMEOUT))
        self._devices = None
        self._hbm_budget = None
        self._peak_in_use = 0
        self._init_lock = lockdep.lock("DeviceManager._init_lock", io_ok=True)
        self._warned_probes: set = set()
        # Spill catalog: the GpuShuffleEnv.initStorage chain
        # (device -> host -> disk, GpuShuffleEnv.scala:52-69). The device
        # budget resolves lazily on the first budget check — by then device
        # buffers exist, so the backend is necessarily live.
        from .spill import BufferCatalog
        explicit = conf.get(DEVICE_SPILL_BUDGET)
        self.catalog = BufferCatalog(
            explicit if explicit > 0 else (lambda: self.hbm_budget_bytes),
            conf.get(HOST_SPILL_STORAGE_SIZE),
            conf.get(SPILL_DIR),
            io_threads=conf.get(SPILL_IO_THREADS))

    @property
    def devices(self):
        if self._devices is None:
            with self._init_lock:
                if self._devices is None:
                    import jax
                    self._devices = (jax.devices(self._backend)
                                     if self._backend else jax.devices())
        return self._devices

    @property
    def device(self):
        return self.devices[0]

    def _classify_probe_failure(self, what: str, e: Exception) -> None:
        """Narrowed swallow for memory-probe failures: OOM/transient
        classes from the retry taxonomy and probe-shaped backend errors
        degrade to defaults with ONE warning per probe; anything else —
        a genuinely broken backend — raises instead of silently lying."""
        from .retry import Classification, classify
        if not isinstance(e, _PROBE_ERRORS) \
                and classify(e) == Classification.FATAL:
            raise e
        if what not in self._warned_probes:
            self._warned_probes.add(what)
            logging.getLogger(__name__).warning(
                "device memory probe %s failed (%s: %s); reporting "
                "defaults from here on", what, type(e).__name__, e)

    @property
    def hbm_budget_bytes(self) -> int:
        """Fraction-of-HBM byte budget for the spill framework; jax doesn't
        expose exact HBM sizes for every backend, so fall back to a
        conservative default."""
        if self._hbm_budget is None:
            try:
                stats = self.device.memory_stats() or {}
                total = stats.get("bytes_limit", _DEFAULT_HBM_BYTES)
            except Exception as e:  # noqa: BLE001 - classify-narrowed
                self._classify_probe_failure("memory_stats(bytes_limit)", e)
                total = _DEFAULT_HBM_BYTES
            self._hbm_budget = int(total * self._frac)
        return self._hbm_budget

    @classmethod
    def get_or_create(cls, conf: TpuConf) -> "DeviceManager":
        # One manager per distinct device/memory configuration: sessions that
        # override spill budgets or directories (test hooks) must not silently
        # inherit the first session's catalog.
        key = (conf.get(DEVICE_BACKEND), conf.get(HBM_ALLOC_FRACTION),
               conf.get(DEVICE_SPILL_BUDGET),
               conf.get(HOST_SPILL_STORAGE_SIZE), conf.get(SPILL_DIR),
               conf.get(SPILL_IO_THREADS),
               conf.get(CONCURRENT_TPU_TASKS),
               conf.get(CONCURRENT_ACQUIRE_TIMEOUT))
        with cls._lock:
            inst = cls._instances.get(key)
            if inst is None:
                inst = cls._instances[key] = DeviceManager(conf)
            return inst

    @classmethod
    def reset(cls):
        with cls._lock:
            for inst in cls._instances.values():
                inst.catalog.close()
            cls._instances.clear()

    def memory_in_use(self) -> int:
        try:
            stats = self.device.memory_stats() or {}
            used = stats.get("bytes_in_use", 0)
        except Exception as e:  # noqa: BLE001 - classify-narrowed
            self._classify_probe_failure("memory_stats(bytes_in_use)", e)
            used = 0
        # Under the init lock: concurrent queries race the read-compare-
        # write otherwise and the watermark can go backwards.
        with self._init_lock:
            if used > self._peak_in_use:
                self._peak_in_use = used
        return used

    def hbm_watermarks(self) -> dict:
        """HBM usage snapshot for the query profile. NEVER initializes the
        backend: a CPU-oracle session (sql.enabled=false) querying its
        profile must not touch the accelerator — watermarks report 0 until
        some device work has forced init (the lazy-init contract above)."""
        if self._devices is None:
            return {"hbmBytesInUse": 0, "hbmPeakBytesInUse": 0}
        return {"hbmBytesInUse": self.memory_in_use(),
                "hbmPeakBytesInUse": self._peak_in_use}
