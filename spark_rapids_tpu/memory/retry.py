"""OOM-resilience retry framework — the ``RmmRapidsRetryIterator`` analog.

The reference survives GPU memory exhaustion by catching allocation
failures at operator boundaries, spilling lower-priority buffers, and
re-executing with the input split in half
(``RmmRapidsRetryIterator.withRetry`` / ``splitSpillableInHalfByRows``,
with ``RmmSpark.forceRetryOOM``-style injection to exercise the paths).
XLA owns the TPU allocator and raises ``RESOURCE_EXHAUSTED`` instead of
calling back, so the TPU-native port classifies *exceptions* at operator
boundaries:

* :data:`Classification.OOM` — device HBM exhaustion (XLA
  ``RESOURCE_EXHAUSTED`` messages, :class:`RetryOOM`). The retry first
  synchronizes the device (drain in-flight work so freed buffers are
  really reusable), synchronously spills every spillable buffer below
  on-deck priority (:func:`spill_device_below`), and re-runs the attempt
  with capped exponential backoff + deterministic jitter. After
  ``spark.rapids.tpu.retry.maxRetries`` it escalates to splitting the
  input batch in half by rows (:func:`halve_by_rows`) and processing the
  halves; sites that cannot split raise :class:`SplitAndRetryOOM` naming
  the site.
* :data:`Classification.TRANSIENT` — remote-compile/helper races and
  spill-disk ``OSError``: retried in place with the same backoff, never
  spilled or split.
* :data:`Classification.FATAL` — everything else propagates untouched.

:func:`with_retry` is the combinator the memory-intensive operator
boundaries wrap (coalesce concat, join build + probe, external-sort runs
and merges, window evaluation, shuffle partition split, device writers);
``TpuSession._run_with_retries`` rebases its transient-compile loop onto
the same taxonomy and backoff policy. Every retry site doubles as a
deterministic fault-injection point (:mod:`..utils.fault_injection`), so
all of these paths are exercised in tier-1 on the CPU backend.

Observability: ``retryCount`` / ``splitAndRetryCount`` /
``retryBlockTimeNs`` / ``retryWastedComputeNs`` flow into the metrics
registry under the wrapping operator's node name and surface in the
query profile (docs/monitoring.md). See docs/fault-tolerance.md.
"""

from __future__ import annotations

import dataclasses
import logging
import time
import zlib
from typing import Callable, List, Optional

from ..utils import lockdep

_LOG = logging.getLogger(__name__)

#: Serializes concurrent DEVICE SYNCS between OOM recoveries (ISSUE 11 —
#: narrowed from the whole sync+spill sequence): overlapping
#: effects_barriers would each re-drain the other's freshly dispatched
#: work for no benefit. The SPILL step no longer needs this lock at all:
#: the spill catalog's state machine (memory/spill.py) reserves each
#: victim exactly once under the catalog lock, respects pins, and never
#: selects an in-flight buffer — so concurrent spill-downs divide the
#: victims instead of corrupting each other, and one query's sync->spill
#: no longer serializes behind another query's disk write.
_OOM_RECOVERY_LOCK = lockdep.rlock("retry._OOM_RECOVERY_LOCK", io_ok=True)

#: Hard ceiling on attempts one ``with_retry`` call may make across all
#: split fragments — a runaway-injection backstop, far above any real
#: retry ladder (maxRetries deep on each of up to ~dozens of fragments).
_MAX_ATTEMPTS_PER_CALL = 256

#: Smallest fragment :func:`halve_by_rows` will split further; below this
#: the rows fit one VPU lane tile and splitting cannot relieve pressure.
_MIN_SPLIT_ROWS = 2


class Classification:
    """The error taxonomy's three buckets."""

    OOM = "oom"
    TRANSIENT = "transient"
    FATAL = "fatal"


class RetryOOM(MemoryError):
    """Device memory exhaustion an operator boundary may survive by
    spilling + retrying (the reference's ``RetryOOM``). Raised directly by
    budget checks; XLA's own ``RESOURCE_EXHAUSTED`` errors classify the
    same without wrapping."""


class SplitAndRetryOOM(RetryOOM):
    """Retries alone could not fit the attempt: the input must split in
    half by rows (the reference's ``SplitAndRetryOOM``). Escapes to the
    user only from sites that cannot split — the message names the site."""

    def __init__(self, site: Optional[str] = None, detail: str = ""):
        self.site = site
        msg = "retries exhausted and the input cannot be split"
        if site:
            msg += f" at retry site '{site}'"
        if detail:
            msg += f": {detail}"
        super().__init__(msg)


#: Substrings identifying device memory exhaustion in backend errors
#: (XlaRuntimeError carries the grpc-style RESOURCE_EXHAUSTED code).
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Resource exhausted",
                "resource exhausted", "out of memory", "Out of memory",
                "OUT_OF_MEMORY", "HBM space exhausted")

#: Substrings identifying transient infrastructure races: the axon remote
#: compile helper's known failure modes (previously substring-matched ad
#: hoc in session._run_with_retries), plus the pipeline pool's teardown
#: signals — a query racing a concurrent ``TpuSession.close()`` sees the
#: shared pool shut down under it, and the pool is lazily recreated, so
#: retrying in place succeeds (the serving layer's session-reaper relies
#: on this: retiring a crashed session must be a non-event for its
#: neighbors' in-flight queries; docs/serving.md).
_TRANSIENT_MARKERS = ("remote_compile", "tpu_compile_helper",
                      "pool is shut down", "pool shut down while")

#: OSError shapes that are DETERMINISTIC user errors (missing input path,
#: permissions, write target already exists), not I/O flakiness —
#: retrying only delays the real message.
_DETERMINISTIC_OS_ERRORS = (FileNotFoundError, PermissionError,
                            FileExistsError, IsADirectoryError,
                            NotADirectoryError)


def classify(exc: BaseException) -> str:
    """Classify an exception into the retry taxonomy (see module doc)."""
    from ..utils.deadline import QueryDeadlineExceeded
    if isinstance(exc, QueryDeadlineExceeded):
        # A deadline is a user contract, not a fault: retrying through it
        # would spend wall time the user explicitly capped.
        return Classification.FATAL
    if isinstance(exc, RetryOOM):
        return Classification.OOM
    from ..parallel.mesh import MeshDegradedError
    if isinstance(exc, MeshDegradedError):
        # Device/host loss mid-SPMD-dispatch (ISSUE 19): the session
        # marks the mesh degraded before this classifies, so the re-run
        # plans the surviving work onto the single-chip path — a slower
        # correct answer, never a wrong one.
        return Classification.TRANSIENT
    from concurrent.futures import CancelledError
    if isinstance(exc, CancelledError):
        # The only canceller of pipeline futures is pool shutdown (a
        # concurrent TpuSession.close); the pool lazily recreates, so a
        # retry in place lands on fresh workers. CancelledError derives
        # from BaseException on modern Pythons — wait sites translate it
        # (exec/pipeline.PoolShutdownError), this arm covers any that
        # escapes raw.
        return Classification.TRANSIENT
    msg = str(exc)
    if any(m in msg for m in _OOM_MARKERS):
        return Classification.OOM
    # Spill-disk I/O failures (full/slow disk, vanished spill file) are
    # worth a bounded in-place retry; so are the remote-compile races.
    # Deterministic path errors are not — they reproduce identically.
    if isinstance(exc, OSError) \
            and not isinstance(exc, _DETERMINISTIC_OS_ERRORS):
        return Classification.TRANSIENT
    if any(m in msg for m in _TRANSIENT_MARKERS):
        return Classification.TRANSIENT
    return Classification.FATAL


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Retry count + backoff shape, snapshotted from conf."""

    max_retries: int = 3
    backoff_base_ms: float = 10.0
    backoff_max_ms: float = 1000.0

    @classmethod
    def from_conf(cls, conf) -> "RetryPolicy":
        from ..config import (RETRY_BACKOFF_BASE_MS, RETRY_BACKOFF_MAX_MS,
                              RETRY_MAX_RETRIES)
        try:
            return cls(int(conf.get(RETRY_MAX_RETRIES)),
                       float(conf.get(RETRY_BACKOFF_BASE_MS)),
                       float(conf.get(RETRY_BACKOFF_MAX_MS)))
        except AttributeError:
            # Bare test contexts whose conf is not a TpuConf.
            return cls()

    def delay_seconds(self, site: str, attempt: int) -> float:
        """Capped exponential backoff with DETERMINISTIC jitter: the
        jitter fraction hashes (site, attempt), so a re-run of the same
        query faults and sleeps identically — retries must not make plan
        timing nondeterministic."""
        if self.backoff_base_ms <= 0:
            return 0.0
        raw = min(self.backoff_base_ms * (2.0 ** attempt),
                  self.backoff_max_ms)
        frac = (zlib.crc32(f"{site}:{attempt}".encode()) % 1000) / 1000.0
        return raw * (0.5 + 0.5 * frac) / 1000.0


def _policy_of(ctx) -> RetryPolicy:
    policy = getattr(ctx, "_retry_policy", None)
    if policy is None:
        policy = RetryPolicy.from_conf(getattr(ctx, "conf", None))
        try:
            ctx._retry_policy = policy
        except AttributeError:  # frozen/slots test doubles
            pass
    return policy


def synchronize_device() -> None:
    """Drain in-flight device work so buffers freed by the spill below are
    actually reusable before the retry (the cudaDeviceSynchronize step of
    the reference's retry loop). Best-effort: backends without an effects
    barrier just proceed."""
    try:
        import jax
        jax.effects_barrier()
    except Exception:  # tpu-lint: ignore - best-effort barrier, no classes
        pass


def spill_device_below(ctx, priority_ceiling: Optional[int] = None) -> int:
    """Push every spillable device buffer below ``priority_ceiling``
    (default: everything under on-deck priority) off the device, and drop
    the upload memo entirely — the forced device drain between OOM
    retries. The catalog drains victims in QoS order keyed by this
    query's :class:`~.spill.QosTag` (its OWN buffers first, then by
    tenant and deadline slack — an OOM ladder must not evict its
    neighbors' hot tables while its own spillable state suffices), with
    the copies overlapped off-lock on the spill-IO lane. Returns device
    bytes moved."""
    from . import spill as SP
    if priority_ceiling is None:
        priority_ceiling = SP.ACTIVE_ON_DECK_PRIORITY
    moved = 0
    catalog = getattr(ctx, "catalog", None)
    if catalog is not None:
        moved = catalog.spill_below(priority_ceiling,
                                    requester=getattr(ctx, "qos", None))
    from ..data import upload_cache
    moved += upload_cache.shrink_by(upload_cache.cache_bytes())
    return moved


def backoff_sleep(policy: RetryPolicy, site: str, attempt: int,
                  ctx=None, node: Optional[str] = None) -> None:
    """Sleep the policy's backoff for this attempt, accounting the block
    time to the node's ``retryBlockTimeNs``. An active query deadline
    bounds the sleep and cancels the retry once expired (a retry ladder
    must never outlive the user's wall-clock contract)."""
    delay = policy.delay_seconds(site, attempt)
    deadline = getattr(ctx, "deadline", None)
    if deadline is not None:
        deadline.check(site, ctx, node)
        delay = deadline.bound(delay)
    if delay <= 0:
        return
    from ..metrics import trace as TR
    t0 = time.perf_counter_ns()
    with TR.span(getattr(ctx, "trace", None), "retry.backoff", cat="retry",
                 site=site, attempt=attempt), \
            lockdep.blocking("retry.backoff_sleep"):
        time.sleep(delay)
    if ctx is not None and node is not None:
        ctx.metric(node, "retryBlockTimeNs", time.perf_counter_ns() - t0)


def halve_by_rows(batch):
    """Split one device ``ColumnarBatch`` into two row-halves (the
    ``splitSpillableInHalfByRows`` analog). Materializes lazy batches
    first (slicing is positional), so it must only run on the failure
    path. Raises :class:`SplitAndRetryOOM` when the batch is too small to
    split further."""
    import jax
    import jax.numpy as jnp

    from ..data.column import bucket_capacity
    from ..exec.external_sort import _slice_kernel
    from ..ops.kernels import rowops as KR
    batch = KR.physical_jit(batch)
    n = int(jax.device_get(batch.n_rows))
    if n < _MIN_SPLIT_ROWS:
        raise SplitAndRetryOOM(
            detail=f"a {n}-row batch cannot be halved")
    slice_k = _slice_kernel(batch.schema)
    k = n // 2
    first = slice_k(batch, jnp.asarray(0, jnp.int32),
                    jnp.asarray(k, jnp.int32),
                    bucket_capacity(max(k, 128)))
    second = slice_k(batch, jnp.asarray(k, jnp.int32),
                     jnp.asarray(n - k, jnp.int32),
                     bucket_capacity(max(n - k, 128)))
    return [first, second]


class SplitTracker:
    """Wraps a split function and remembers whether it ever ran. Join
    sites consult :attr:`split_happened` inside their attempt to suppress
    capacity learning on fragments — a half batch's match total would
    under-teach the cached capacity of the full batch (see
    execs.join_batch)."""

    def __init__(self, split: Callable):
        self._split = split
        self.split_happened = False

    def __call__(self, item):
        self.split_happened = True
        return self._split(item)


def halve_list(items):
    """Split a list of inputs (batches or spill-catalog buffer ids) into
    its two halves; a single remaining item cannot split at the list
    level."""
    if len(items) < 2:
        raise SplitAndRetryOOM(
            detail="a single pending buffer cannot be split")
    k = len(items) // 2
    return [list(items[:k]), list(items[k:])]


def with_retry(ctx, site: str, inputs, attempt: Callable,
               split: Optional[Callable] = None,
               node: Optional[str] = None) -> List:
    """Run ``attempt(inputs)``, surviving classified OOM and transient
    faults (the ``withRetry`` / ``withRetryNoSplit`` combinator).

    Returns the list of results — one element normally; several after a
    split escalation (each fragment produced by ``split`` is processed
    with a fresh retry budget, so downstream consumers must accept a
    stream of results). ``split=None`` marks the site unsplittable:
    exhausted OOM retries raise :class:`SplitAndRetryOOM` naming it.

    The success path adds no device fences and no syncs — classification,
    spilling, and splitting all live on the failure path. Under
    whole-stage fusion tracing the combinator is a passthrough (tracers
    cannot be retried, and injection inside a trace would poison the
    cached program).

    ``node`` keys the retry metrics in the registry (defaults to the site
    name up to the first dot, the wrapping exec's node_name()).
    """
    if node is None:
        node = site.split(".", 1)[0]
    if getattr(ctx, "in_fusion", False):
        return [attempt(inputs)]
    from ..utils.fault_injection import register_site
    register_site(site)
    injector = getattr(ctx, "fault_injector", None)
    deadline = getattr(ctx, "deadline", None)
    policy = _policy_of(ctx)
    work: List = [inputs]
    results: List = []
    attempts_total = 0
    while work:
        item = work.pop(0)
        retries = 0
        while True:
            attempts_total += 1
            if attempts_total > _MAX_ATTEMPTS_PER_CALL:
                raise RetryOOM(
                    f"retry site '{site}' exceeded "
                    f"{_MAX_ATTEMPTS_PER_CALL} attempts (runaway fault "
                    "schedule or unrecoverable memory pressure)")
            t0 = time.perf_counter_ns()
            try:
                if deadline is not None:
                    deadline.check(site, ctx, node)
                if injector is not None:
                    injector.check(site)
                results.append(attempt(item))
                break
            except Exception as e:  # noqa: BLE001 - classified below
                cls = classify(e)
                if cls == Classification.FATAL:
                    raise
                ctx.metric(node, "retryWastedComputeNs",
                           time.perf_counter_ns() - t0)
                if cls == Classification.OOM:
                    # The lock covers ONLY the device sync (ISSUE 11);
                    # the spill-down runs off-lock — the catalog's state
                    # machine makes concurrent drains safe, so one
                    # query's recovery never queues behind a neighbor's
                    # disk write.
                    from ..metrics import trace as TR
                    with TR.span(getattr(ctx, "trace", None),
                                 "retry.oom_recovery", cat="retry",
                                 site=site):
                        with _OOM_RECOVERY_LOCK:
                            synchronize_device()
                        spill_device_below(ctx)
                    if retries >= policy.max_retries:
                        if split is None:
                            raise SplitAndRetryOOM(site) from e
                        try:
                            halves = split(item)
                        except SplitAndRetryOOM as se:
                            raise SplitAndRetryOOM(site, str(se)) from e
                        except Exception as se:  # noqa: BLE001
                            # The split itself does device work (halving
                            # materializes + slices) at peak pressure; an
                            # OOM there must surface as this site's
                            # SplitAndRetryOOM, not escape raw.
                            if classify(se) == Classification.OOM:
                                raise SplitAndRetryOOM(
                                    site,
                                    f"splitting itself hit OOM: {se}"
                                ) from se
                            raise
                        _LOG.info("retry site %s: splitting input after "
                                  "%d OOM retries", site, retries)
                        ctx.metric(node, "splitAndRetryCount", 1)
                        work[:0] = halves
                        break
                elif retries >= policy.max_retries:
                    raise
                ctx.metric(node, "retryCount", 1)
                backoff_sleep(policy, site, retries, ctx, node)
                retries += 1
    return results
