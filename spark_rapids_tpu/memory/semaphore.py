"""Task admission semaphore — the ``GpuSemaphore`` analog.

The reference bounds concurrent tasks holding the GPU
(``spark.rapids.sql.concurrentGpuTasks``) with a per-task reentrant acquire
released by a completion listener (GpuSemaphore.scala:74-161). Our execution
threads acquire it around device work; re-entrant per thread so nested
operators don't deadlock.
"""

from __future__ import annotations

import threading
import time
from typing import Dict


class TpuSemaphore:
    def __init__(self, max_concurrent: int):
        self.max_concurrent = max_concurrent
        self._sem = threading.Semaphore(max_concurrent)
        self._held: Dict[int, int] = {}
        self._lock = threading.Lock()
        #: Lifetime nanoseconds threads spent blocked on acquire — the
        #: semaphoreWaitNs metric source; the query profile takes deltas
        #: (metrics/profile.py, GpuSemaphore's SEMAPHORE_WAIT analog).
        self.wait_ns = 0

    def acquire_if_necessary(self):
        """Reentrant acquire (GpuSemaphore.acquireIfNecessary:74)."""
        tid = threading.get_ident()
        with self._lock:
            if self._held.get(tid, 0) > 0:
                self._held[tid] += 1
                return
        t0 = time.perf_counter_ns()
        self._sem.acquire()
        waited = time.perf_counter_ns() - t0
        with self._lock:
            self.wait_ns += waited
            self._held[tid] = self._held.get(tid, 0) + 1

    def release_if_necessary(self):
        tid = threading.get_ident()
        with self._lock:
            count = self._held.get(tid, 0)
            if count == 0:
                return
            if count > 1:
                self._held[tid] = count - 1
                return
            del self._held[tid]
        self._sem.release()

    def __enter__(self):
        self.acquire_if_necessary()
        return self

    def __exit__(self, *exc):
        self.release_if_necessary()
        return False
