"""Task admission semaphore — the ``GpuSemaphore`` analog.

The reference bounds concurrent tasks holding the GPU
(``spark.rapids.sql.concurrentGpuTasks``) with a per-task reentrant acquire
released by a completion listener (GpuSemaphore.scala:74-161). Our execution
threads acquire it around device work; re-entrant per thread so nested
operators don't deadlock.
"""

from __future__ import annotations

import threading
from typing import Dict


class TpuSemaphore:
    def __init__(self, max_concurrent: int):
        self.max_concurrent = max_concurrent
        self._sem = threading.Semaphore(max_concurrent)
        self._held: Dict[int, int] = {}
        self._lock = threading.Lock()

    def acquire_if_necessary(self):
        """Reentrant acquire (GpuSemaphore.acquireIfNecessary:74)."""
        tid = threading.get_ident()
        with self._lock:
            if self._held.get(tid, 0) > 0:
                self._held[tid] += 1
                return
        self._sem.acquire()
        with self._lock:
            self._held[tid] = self._held.get(tid, 0) + 1

    def release_if_necessary(self):
        tid = threading.get_ident()
        with self._lock:
            count = self._held.get(tid, 0)
            if count == 0:
                return
            if count > 1:
                self._held[tid] = count - 1
                return
            del self._held[tid]
        self._sem.release()

    def __enter__(self):
        self.acquire_if_necessary()
        return self

    def __exit__(self, *exc):
        self.release_if_necessary()
        return False
