"""Task admission semaphore — the ``GpuSemaphore`` analog.

The reference bounds concurrent tasks holding the GPU
(``spark.rapids.sql.concurrentGpuTasks``) with a per-task reentrant acquire
released by a completion listener (GpuSemaphore.scala:74-161). Our execution
threads acquire it around device work; re-entrant per thread so nested
operators don't deadlock.

A stuck or leaked holder used to deadlock every other task silently
(``acquire`` blocked forever); ``acquireTimeout``
(``spark.rapids.tpu.concurrentTpuTasks.acquireTimeout``) turns that into a
:class:`SemaphoreTimeoutError` naming the holding thread ids and their
held counts — an actionable diagnostic instead of a hang
(docs/fault-tolerance.md).
"""

from __future__ import annotations

import collections
import math
import threading
import time
from typing import Deque, Dict, Optional

from ..utils import lockdep


class SemaphoreTimeoutError(RuntimeError):
    """Task-admission acquire timed out — almost always a stuck or leaked
    holder, not real contention. Classified FATAL by the retry taxonomy:
    retrying against a wedged semaphore only hides the deadlock."""


class TpuSemaphore:
    def __init__(self, max_concurrent: int, acquire_timeout_s: float = 0.0):
        self.max_concurrent = max_concurrent
        #: seconds to block in acquire before raising; 0 = wait forever
        self.acquire_timeout_s = acquire_timeout_s
        self._sem = threading.Semaphore(max_concurrent)
        self._held: Dict[int, int] = {}
        self._lock = lockdep.lock("TpuSemaphore._lock")
        #: Lifetime nanoseconds threads spent blocked on acquire — the
        #: semaphoreWaitNs metric source; the query profile takes deltas
        #: (metrics/profile.py, GpuSemaphore's SEMAPHORE_WAIT analog).
        self.wait_ns = 0

    def holders(self) -> Dict[int, int]:
        """Snapshot of {thread ident: held count} (diagnostics)."""
        with self._lock:
            return dict(self._held)

    def acquire_if_necessary(self):
        """Reentrant acquire (GpuSemaphore.acquireIfNecessary:74); raises
        :class:`SemaphoreTimeoutError` when ``acquire_timeout_s`` elapses
        without a slot."""
        tid = threading.get_ident()
        with self._lock:
            if self._held.get(tid, 0) > 0:
                self._held[tid] += 1
                return
        t0 = time.perf_counter_ns()
        if self.acquire_timeout_s > 0:
            acquired = self._sem.acquire(timeout=self.acquire_timeout_s)
        else:
            acquired = self._sem.acquire()
        waited = time.perf_counter_ns() - t0
        with self._lock:
            self.wait_ns += waited
            if acquired:
                self._held[tid] = self._held.get(tid, 0) + 1
                return
            holders = dict(self._held)
        held_desc = ", ".join(
            f"thread {t} holds {c}" for t, c in sorted(holders.items())) \
            or "no recorded holders (leak outside acquire_if_necessary?)"
        raise SemaphoreTimeoutError(
            f"thread {tid} could not acquire the TPU task semaphore within "
            f"{self.acquire_timeout_s:g}s "
            f"(spark.rapids.tpu.concurrentTpuTasks.acquireTimeout); "
            f"{self.max_concurrent} slot(s) total, {held_desc}")

    def released(self):
        """Context manager that temporarily releases EVERY slot this
        thread holds and re-acquires the same count on exit — the
        reference's release-the-semaphore-while-blocked-on-IO discipline
        (GpuSemaphore around shuffle fetches). The pipeline layer uses it
        while the dispatching thread waits on boundary workers, so the
        freed slots actually admit those workers
        (spark.rapids.tpu.pipeline.boundaryParallelism)."""
        return _Released(self)

    def release_if_necessary(self):
        tid = threading.get_ident()
        with self._lock:
            count = self._held.get(tid, 0)
            if count == 0:
                return
            if count > 1:
                self._held[tid] = count - 1
                return
            del self._held[tid]
        self._sem.release()

    def __enter__(self):
        self.acquire_if_necessary()
        return self

    def __exit__(self, *exc):
        self.release_if_necessary()
        return False


class AdmissionQueueFull(RuntimeError):
    """A tenant's bounded admission queue was full at submit — the typed
    SHED signal (docs/serving.md): the caller should answer the client
    with retry-after backpressure, never queue unboundedly. Carries the
    tenant, the observed depth, and the retry-after hint."""

    def __init__(self, tenant: str, depth: int, retry_after_s: float):
        super().__init__(
            f"admission queue for tenant '{tenant or '<default>'}' is "
            f"full ({depth} waiting); retry after ~{retry_after_s:.2f}s")
        self.tenant = tenant
        self.depth = depth
        self.retry_after_s = retry_after_s


class AdmissionCancelled(RuntimeError):
    """The waiter was cancelled while queued (client disconnect or an
    injected tenant-kill): its queue entry is already removed and no
    slot was consumed."""


class _Waiter:
    __slots__ = ("tenant", "granted", "cancelled")

    def __init__(self, tenant: str):
        self.tenant = tenant
        self.granted = False
        self.cancelled = False


class FairShareGate:
    """Weighted fair-share admission LAYERED IN FRONT of the task
    semaphore (the serving layer's front door, docs/serving.md): each
    tenant gets a bounded FIFO queue, and free slots are granted by
    stride scheduling — the nonempty tenant with the smallest virtual
    pass runs next, and a grant advances its pass by ``1/weight``, so a
    weight-2 tenant is admitted twice as often under contention while an
    idle tenant's first query never waits behind a burst from another.
    The gate bounds how many queries hold pooled sessions at once; the
    semaphore below it (``spark.rapids.sql.concurrentTpuTasks``) still
    bounds device admission exactly as for non-served queries.

    Overload is answered typed: a submit past ``max_depth`` raises
    :class:`AdmissionQueueFull` immediately (shed with retry-after), a
    cancelled waiter raises :class:`AdmissionCancelled` with its entry
    removed, and an expired query deadline raises through
    ``deadline.check`` — queue wait spends the tenant's time budget."""

    def __init__(self, slots: int, max_depth: int,
                 weights: Optional[Dict[str, float]] = None,
                 retry_after_base_s: float = 0.25):
        self.slots = max(1, int(slots))
        self.max_depth = max(1, int(max_depth))
        self.weights = {t: max(float(w), 1e-9)
                        for t, w in (weights or {}).items()}
        self.retry_after_base_s = float(retry_after_base_s)
        self._cond = lockdep.condition("FairShareGate._cond")
        self._free = self.slots
        self._queues: Dict[str, Deque[_Waiter]] = {}
        self._passes: Dict[str, float] = {}
        self.stats = {"admitted": 0, "shed": 0, "cancelled": 0,
                      "peak_depth": 0, "peak_concurrent": 0}

    # -- scheduling (caller holds self._cond) -------------------------------
    def _weight(self, tenant: str) -> float:
        return self.weights.get(tenant, 1.0)

    def _gc_tenant_locked(self, tenant: str) -> None:
        """Drop an emptied tenant's queue AND pass entry. Tenant ids
        arrive off the wire, so per-tenant state must not grow with
        every distinct id ever seen; a returning tenant re-joins at the
        current pass floor, which is the documented idle-tenant
        semantics anyway."""
        q = self._queues.get(tenant)
        if q is not None and not q:
            del self._queues[tenant]
            self._passes.pop(tenant, None)

    def _dispatch_locked(self) -> None:
        while self._free > 0:
            ready = [(self._passes.get(t, 0.0), t)
                     for t, q in self._queues.items() if q]
            if not ready:
                return
            _, tenant = min(ready)
            q = self._queues[tenant]
            w = q.popleft()
            if w.cancelled:
                self._gc_tenant_locked(tenant)
                continue
            w.granted = True
            self._free -= 1
            # The pass floor is applied at ENQUEUE time (acquire):
            # clamping here against a min that includes the granted
            # tenant's own stale pass let a returning burst (pass far
            # below the field) monopolize the gate until it caught up.
            self._passes[tenant] = self._passes.get(tenant, 0.0) \
                + 1.0 / self._weight(tenant)
            self.stats["admitted"] += 1
            used = self.slots - self._free
            if used > self.stats["peak_concurrent"]:
                self.stats["peak_concurrent"] = used
            self._gc_tenant_locked(tenant)

    def _depth_locked(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def _retry_after_locked(self) -> float:
        return self.retry_after_base_s * (1.0 + self._depth_locked()
                                          / float(self.slots))

    # -- public API ---------------------------------------------------------
    def acquire(self, tenant: str, deadline=None,
                waiter_out: Optional[list] = None) -> None:
        """Block until admitted. ``waiter_out`` (a one-slot list) receives
        the queue entry so a canceller can target it via :meth:`cancel`.
        Raises :class:`AdmissionQueueFull` on a full tenant queue,
        :class:`AdmissionCancelled` after a cancel, and whatever
        ``deadline.check`` raises once the time budget is spent (the
        entry is removed in every raising path — a shed or cancelled
        query never leaks queue depth or a slot)."""
        with self._cond:
            q = self._queues.setdefault(tenant, collections.deque())
            if len(q) >= self.max_depth:
                self.stats["shed"] += 1
                raise AdmissionQueueFull(tenant, len(q),
                                         self._retry_after_locked())
            if tenant not in self._passes:
                # A NEW or returning tenant joins at the current pass
                # floor of the queued field: it cannot claim credit for
                # time it was not queued, and (unlike clamping at grant
                # time against a min that includes its own stale pass) a
                # returning BURST cannot monopolize the gate either.
                self._passes[tenant] = min(
                    (p for t, p in self._passes.items()
                     if self._queues.get(t)), default=0.0)
            w = _Waiter(tenant)
            if waiter_out is not None:
                waiter_out.append(w)
            q.append(w)
            depth = self._depth_locked()
            if depth > self.stats["peak_depth"]:
                self.stats["peak_depth"] = depth
            self._dispatch_locked()
            try:
                while not w.granted:
                    if w.cancelled:
                        self.stats["cancelled"] += 1
                        raise AdmissionCancelled(
                            f"tenant '{tenant}' cancelled while queued")
                    timeout = None
                    if deadline is not None:
                        deadline.check("serve.admission")
                        rem = deadline.remaining()
                        if math.isfinite(rem):
                            timeout = max(min(rem, 0.05), 0.005)
                    self._cond.wait(timeout)
            except BaseException:  # tpu-lint: ignore - cleanup-only
                # handler: re-raises verbatim (classification is the
                # OUTER layer's job — serve/service.py maps these), it
                # only unwinds this waiter's queue entry / slot.
                if w.granted:
                    # Granted in the same race window the raise came
                    # from: give the slot back or it leaks forever.
                    self._free += 1
                    self._dispatch_locked()
                    self._cond.notify_all()
                else:
                    w.cancelled = True
                    try:
                        q.remove(w)
                    except ValueError:
                        pass
                    self._gc_tenant_locked(tenant)
                raise

    def release(self) -> None:
        with self._cond:
            self._free += 1
            self._dispatch_locked()
            self._cond.notify_all()

    def cancel(self, waiter: _Waiter) -> None:
        """Cancel a queued waiter (client disconnect / tenant kill). A
        waiter already granted is untouched — its query is cancelled
        cooperatively through the deadline instead."""
        with self._cond:
            waiter.cancelled = True
            q = self._queues.get(waiter.tenant)
            if q is not None:
                try:
                    q.remove(waiter)
                except ValueError:
                    pass
                self._gc_tenant_locked(waiter.tenant)
            self._cond.notify_all()

    def depth(self, tenant: Optional[str] = None) -> int:
        with self._cond:
            if tenant is None:
                return self._depth_locked()
            return len(self._queues.get(tenant, ()))


class _Released:
    """Release the calling thread's underlying permit for a scope, then
    re-take it and restore the reentrant hold count. A thread holds
    exactly ONE underlying permit no matter how deep its reentrancy
    (acquire_if_necessary's fast path never touches the semaphore), so
    exactly one permit moves in each direction — releasing per-hold
    would inflate the counter past max_concurrent and over-admit."""

    def __init__(self, sem: TpuSemaphore):
        self._sem = sem
        self._count = 0

    def __enter__(self):
        sem = self._sem
        tid = threading.get_ident()
        with sem._lock:
            self._count = sem._held.pop(tid, 0)
        if self._count:
            sem._sem.release()
        return self

    def __exit__(self, *exc):
        sem = self._sem
        if not self._count:
            return False
        t0 = time.perf_counter_ns()
        # Honor the acquireTimeout diagnostic here too: a wedged worker
        # must surface as the named error, not a silent hang at re-entry.
        if sem.acquire_timeout_s > 0:
            acquired = sem._sem.acquire(timeout=sem.acquire_timeout_s)
        else:
            acquired = sem._sem.acquire()
        tid = threading.get_ident()
        with sem._lock:
            sem.wait_ns += time.perf_counter_ns() - t0
            if acquired:
                sem._held[tid] = sem._held.get(tid, 0) + self._count
                return False
            holders = dict(sem._held)
        raise SemaphoreTimeoutError(
            f"thread {tid} could not re-acquire the TPU task semaphore "
            f"within {sem.acquire_timeout_s:g}s after waiting on pipeline "
            f"workers; holders: {holders or 'none recorded'}")
