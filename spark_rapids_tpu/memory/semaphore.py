"""Task admission semaphore — the ``GpuSemaphore`` analog.

The reference bounds concurrent tasks holding the GPU
(``spark.rapids.sql.concurrentGpuTasks``) with a per-task reentrant acquire
released by a completion listener (GpuSemaphore.scala:74-161). Our execution
threads acquire it around device work; re-entrant per thread so nested
operators don't deadlock.

A stuck or leaked holder used to deadlock every other task silently
(``acquire`` blocked forever); ``acquireTimeout``
(``spark.rapids.tpu.concurrentTpuTasks.acquireTimeout``) turns that into a
:class:`SemaphoreTimeoutError` naming the holding thread ids and their
held counts — an actionable diagnostic instead of a hang
(docs/fault-tolerance.md).
"""

from __future__ import annotations

import threading
import time
from typing import Dict

from ..utils import lockdep


class SemaphoreTimeoutError(RuntimeError):
    """Task-admission acquire timed out — almost always a stuck or leaked
    holder, not real contention. Classified FATAL by the retry taxonomy:
    retrying against a wedged semaphore only hides the deadlock."""


class TpuSemaphore:
    def __init__(self, max_concurrent: int, acquire_timeout_s: float = 0.0):
        self.max_concurrent = max_concurrent
        #: seconds to block in acquire before raising; 0 = wait forever
        self.acquire_timeout_s = acquire_timeout_s
        self._sem = threading.Semaphore(max_concurrent)
        self._held: Dict[int, int] = {}
        self._lock = lockdep.lock("TpuSemaphore._lock")
        #: Lifetime nanoseconds threads spent blocked on acquire — the
        #: semaphoreWaitNs metric source; the query profile takes deltas
        #: (metrics/profile.py, GpuSemaphore's SEMAPHORE_WAIT analog).
        self.wait_ns = 0

    def holders(self) -> Dict[int, int]:
        """Snapshot of {thread ident: held count} (diagnostics)."""
        with self._lock:
            return dict(self._held)

    def acquire_if_necessary(self):
        """Reentrant acquire (GpuSemaphore.acquireIfNecessary:74); raises
        :class:`SemaphoreTimeoutError` when ``acquire_timeout_s`` elapses
        without a slot."""
        tid = threading.get_ident()
        with self._lock:
            if self._held.get(tid, 0) > 0:
                self._held[tid] += 1
                return
        t0 = time.perf_counter_ns()
        if self.acquire_timeout_s > 0:
            acquired = self._sem.acquire(timeout=self.acquire_timeout_s)
        else:
            acquired = self._sem.acquire()
        waited = time.perf_counter_ns() - t0
        with self._lock:
            self.wait_ns += waited
            if acquired:
                self._held[tid] = self._held.get(tid, 0) + 1
                return
            holders = dict(self._held)
        held_desc = ", ".join(
            f"thread {t} holds {c}" for t, c in sorted(holders.items())) \
            or "no recorded holders (leak outside acquire_if_necessary?)"
        raise SemaphoreTimeoutError(
            f"thread {tid} could not acquire the TPU task semaphore within "
            f"{self.acquire_timeout_s:g}s "
            f"(spark.rapids.tpu.concurrentTpuTasks.acquireTimeout); "
            f"{self.max_concurrent} slot(s) total, {held_desc}")

    def released(self):
        """Context manager that temporarily releases EVERY slot this
        thread holds and re-acquires the same count on exit — the
        reference's release-the-semaphore-while-blocked-on-IO discipline
        (GpuSemaphore around shuffle fetches). The pipeline layer uses it
        while the dispatching thread waits on boundary workers, so the
        freed slots actually admit those workers
        (spark.rapids.tpu.pipeline.boundaryParallelism)."""
        return _Released(self)

    def release_if_necessary(self):
        tid = threading.get_ident()
        with self._lock:
            count = self._held.get(tid, 0)
            if count == 0:
                return
            if count > 1:
                self._held[tid] = count - 1
                return
            del self._held[tid]
        self._sem.release()

    def __enter__(self):
        self.acquire_if_necessary()
        return self

    def __exit__(self, *exc):
        self.release_if_necessary()
        return False


class _Released:
    """Release the calling thread's underlying permit for a scope, then
    re-take it and restore the reentrant hold count. A thread holds
    exactly ONE underlying permit no matter how deep its reentrancy
    (acquire_if_necessary's fast path never touches the semaphore), so
    exactly one permit moves in each direction — releasing per-hold
    would inflate the counter past max_concurrent and over-admit."""

    def __init__(self, sem: TpuSemaphore):
        self._sem = sem
        self._count = 0

    def __enter__(self):
        sem = self._sem
        tid = threading.get_ident()
        with sem._lock:
            self._count = sem._held.pop(tid, 0)
        if self._count:
            sem._sem.release()
        return self

    def __exit__(self, *exc):
        sem = self._sem
        if not self._count:
            return False
        t0 = time.perf_counter_ns()
        # Honor the acquireTimeout diagnostic here too: a wedged worker
        # must surface as the named error, not a silent hang at re-entry.
        if sem.acquire_timeout_s > 0:
            acquired = sem._sem.acquire(timeout=sem.acquire_timeout_s)
        else:
            acquired = sem._sem.acquire()
        tid = threading.get_ident()
        with sem._lock:
            sem.wait_ns += time.perf_counter_ns() - t0
            if acquired:
                sem._held[tid] = sem._held.get(tid, 0) + self._count
                return False
            holders = dict(sem._held)
        raise SemaphoreTimeoutError(
            f"thread {tid} could not re-acquire the TPU task semaphore "
            f"within {sem.acquire_timeout_s:g}s after waiting on pipeline "
            f"workers; holders: {holders or 'none recorded'}")
