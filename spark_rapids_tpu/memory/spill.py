"""Spillable buffer framework — async device -> host -> disk tiers.

Architectural port of the reference's spill subsystem (SURVEY.md §2.1):
``RapidsBufferCatalog`` (RapidsBufferCatalog.scala:30) maps buffer ids to
tiered buffers; ``RapidsBufferStore`` (RapidsBufferStore.scala:40) owns one
tier and spills to the next via ``synchronousSpill:137-149`` in
spill-priority order (SpillPriorities.scala:26); the device store's pressure
callback is ``DeviceMemoryEventHandler.onAllocFailure:35-59``.

TPU-native differences: XLA owns the HBM allocator and exposes no
alloc-failure callback, so the device store enforces a *byte budget*
(fraction of HBM, GpuDeviceManager-style) and spills when a registration
would exceed it — pressure is handled before allocation rather than on
allocation failure. Host interchange is Arrow IPC (the reference uses
JCudfSerialization host buffers); the disk tier appends IPC-serialized
batches to a shared spill file, like the reference's disk block manager
files.

Async spill engine (ISSUE 11). Every buffer is an explicit state machine

    DEVICE -> SPILLING -> HOST/DISK -> RESTORING -> DEVICE

and the catalog lock is held only to *reserve* a transition (pick victims,
mark state) and to *publish* its result (install the copied payload,
update byte accounting, wake waiters). The actual device<->host copy,
CRC32C checksum, and :class:`SpillFile` append/read run OFF the lock, on
a dedicated spill-IO lane of the shared pipeline pool
(:func:`~..exec.pipeline.submit_spill_io`, bounded by
``spark.rapids.tpu.spill.ioThreads``), so

* a spill never stalls threads touching OTHER buffers — the PR-9
  lock-order debt (catalog lock held across transfers and file opens,
  ``tools/lock_order_baseline.json``) is gone, and the static gate keeps
  it gone (the baseline is EMPTY and ratcheted);
* concurrent spills overlap on the lane instead of convoying;
* readers of an in-flight buffer wait on the buffer's own condition
  (:func:`~..utils.lockdep.condition_on` — the wait releases the catalog
  lock), never on the catalog.

Victim selection is QoS-aware (memory QoS for the multi-tenant roadmap
item): within each spill-priority band, candidates order by (requesting
query's own buffers first, then same tenant, then other tenants by
descending query-deadline slack, then descending size), so one query's
OOM ladder drains its own and the most-slack neighbors' buffers before a
deadline-constrained neighbor's hot build tables. See
docs/fault-tolerance.md#async-spill.
"""

from __future__ import annotations

import dataclasses
import io
import math
import os
import tempfile
import time
from typing import Dict, List, Optional, Tuple

import pyarrow as pa

from .. import types as T
from ..data.batch import ColumnarBatch
from ..utils import lockdep
from ..utils.tracing import trace_range


# ---------------------------------------------------------------------------
# Spill priorities (SpillPriorities.scala:26): LOWER values spill FIRST.
# ---------------------------------------------------------------------------

#: Shuffle outputs spill before anything else: they are re-fetchable and
#: typically long-lived.
OUTPUT_FOR_SHUFFLE_PRIORITY = -10_000_000
#: Buffers parked by operators between batches (coalesce accumulation).
ACTIVE_BATCHING_PRIORITY = 0
#: Buffers an operator is actively using; spill only under extreme pressure.
ACTIVE_ON_DECK_PRIORITY = 10_000_000


class StorageTier:
    DEVICE = "device"
    HOST = "host"
    DISK = "disk"
    #: transitional: a device->host (or host->disk) copy is in flight on
    #: the spill-IO lane; readers wait on the entry's condition
    SPILLING = "spilling"
    #: transitional: a host/disk->device restore is in flight
    RESTORING = "restoring"


#: states during which an entry's payload is owned by an IO-lane worker
TRANSITIONAL_TIERS = (StorageTier.SPILLING, StorageTier.RESTORING)


@dataclasses.dataclass
class QosTag:
    """Identity of one executing query for spill victim selection: the
    session's tenant id (``spark.rapids.tpu.tenantId``) plus the query's
    deadline (PR-7 :class:`~..utils.deadline.Deadline`, None when the
    query has no wall-clock contract). One instance per
    :class:`~..plan.physical.ExecContext`; boundary forks share it, so
    "own buffer" means "same query"."""

    tenant: str = ""
    deadline: object = None
    #: the requesting query's span tracer (metrics/trace.py, ISSUE 13):
    #: spill-IO lane units opened on this query's behalf record their
    #: device<->host/disk transitions as spans in ITS trace — None (the
    #: default) records nothing
    trace: object = None

    def slack(self) -> float:
        """Seconds of deadline headroom; +inf without a deadline. A
        neighbor with more slack is the safer victim — it can afford the
        reload round trip."""
        if self.deadline is None:
            return math.inf
        try:
            return float(self.deadline.remaining())
        except Exception:  # tpu-lint: ignore - accounting only: a
            return math.inf  # broken deadline must not poison selection


@dataclasses.dataclass
class TableMeta:
    """What's needed to faithfully restore a batch on device (the flatbuffer
    TableMeta analog, MetaUtils.scala:41)."""

    schema: T.Schema
    capacity: int
    size_bytes: int


@dataclasses.dataclass
class _Entry:
    buffer_id: int
    priority: int
    meta: TableMeta
    tier: str
    device_batch: Optional[ColumnarBatch] = None
    host_batch: Optional[pa.RecordBatch] = None
    disk_range: Optional[Tuple[int, int]] = None  # (offset, length)
    freed: bool = False
    #: QoS identity of the registering query (None in bare tests)
    owner: Optional[QosTag] = None
    #: which settled tier a SPILLING/RESTORING transition left from
    moving_from: str = ""
    #: per-buffer wait channel for in-flight transitions; shares the
    #: catalog lock (lockdep.condition_on) — created at first transition
    cond: object = None
    #: catalog _compact_gen at free() time for a freed-while-RESTORING
    #: entry: the restore worker honors the deferred free_range only if
    #: no compaction rewrote the file since (stale offsets would skew
    #: freed accounting and can delete a live range's CRC record)
    freed_gen: int = -1


#: Compact the shared spill file once this fraction of its bytes is dead
#: (freed ranges of a still-open catalog previously leaked until close).
DISK_COMPACT_FRACTION = 0.5


class SpillFileClosedError(RuntimeError):
    """A SpillFile operation (or a catalog ``_disk()`` resolve) raced
    close(): the file is gone. Typed so straggler publish paths can
    settle as a stand-down instead of treating it like a transient I/O
    failure — an untyped append would silently RE-CREATE the removed
    path via ``open(path, 'ab')`` and leak it."""


class SpillFile:
    """Shared spill file (RapidsDiskStore's block-manager file): appends
    serialized payloads, tracks freed ranges, and compacts itself when the
    owner asks — so freed disk space reclaims during the catalog's
    lifetime instead of leaking until close.

    Durability (ISSUE 7): every appended range records its CRC32C and
    every read verifies it, so disk bit rot (or a concurrent writer
    scribbling over the file) surfaces as a typed
    :class:`~..utils.checksum.ChecksumError` — classified transient by
    the retry taxonomy — instead of deserializing garbage into a query
    answer.

    Concurrency contract (ISSUE 11): every operation is atomic under the
    file's own ``io_ok`` lock, so an off-catalog-lock read can never see
    a half-compacted file. Range STALENESS (the catalog's offset for a
    buffer moving during a concurrent :meth:`compact`) is the OWNER's
    problem: catalogs snapshot ranges under their lock, exclude readers
    while a compaction is claimed, and re-validate the range after the
    read (see ``BufferCatalog._read_disk_payload``)."""

    def __init__(self, spill_dir: Optional[str] = None,
                 verify: bool = True):
        self._owns_dir = spill_dir is None
        self.dir = spill_dir or tempfile.mkdtemp(prefix="tpu_spill_")
        os.makedirs(self.dir, exist_ok=True)
        # Unique per catalog so concurrent catalogs (or a reused spillDir
        # from a previous process) never interleave offsets.
        fd, self.path = tempfile.mkstemp(prefix="spill_", suffix=".bin",
                                         dir=self.dir)
        os.close(fd)
        self._offset = 0
        self._freed = 0
        #: offset -> (length, crc32c) of every live appended range
        self._crcs: Dict[int, Tuple[int, int]] = {}
        #: False = record checksums but skip verification (the shuffle
        #: catalog threads spark.rapids.tpu.shuffle.checksum.enabled here
        #: so the kill switch covers its disk tier too)
        self.verify = verify
        self._closed = False
        self._lock = lockdep.lock("SpillFile._lock", io_ok=True)

    def close(self):
        import shutil
        with self._lock:
            # Flag BEFORE removing: an append serialized behind this
            # lock would otherwise re-create the removed path ('ab'
            # creates) and leak a stray file nothing ever deletes.
            self._closed = True
            try:
                os.remove(self.path)
            except OSError:
                pass
        if self._owns_dir:
            shutil.rmtree(self.dir, ignore_errors=True)

    def append(self, payload: bytes) -> Tuple[int, int]:
        from ..utils import checksum as CK
        crc = CK.crc32c(payload)
        with self._lock:
            if self._closed:
                raise SpillFileClosedError(self.path)
            offset = self._offset
            with open(self.path, "ab") as f:
                f.write(payload)
            self._offset += len(payload)
            self._crcs[offset] = (len(payload), crc)
            return offset, len(payload)

    def read_with_crc(self, offset: int, length: int
                      ) -> Tuple[bytes, Optional[int]]:
        """(payload, recorded crc32c or None) WITHOUT verification — for
        callers that must verify outside their own wider lock (the
        shuffle catalog's disk tier). None when the range has no
        recorded checksum or verification is disabled."""
        # Under the lock: compact() rewrites the file and its checksum
        # table atomically, so payload+crc are always a consistent pair.
        with self._lock:
            if self._closed:
                raise SpillFileClosedError(self.path)
            with open(self.path, "rb") as f:
                f.seek(offset)
                payload = f.read(length)
            rec = self._crcs.get(offset)
        if self.verify and rec is not None and rec[0] == length:
            return payload, rec[1]
        return payload, None

    def read(self, offset: int, length: int) -> bytes:
        from ..utils import checksum as CK
        # Verification runs OUTSIDE the lock — the payload is a private
        # copy, and a full-payload CRC pass must not serialize readers.
        payload, crc = self.read_with_crc(offset, length)
        if crc is not None:
            CK.verify(payload, crc,
                      f"spill range [{offset}:{offset + length}) of "
                      f"{self.path}")
        return payload

    # -- space reclaim ------------------------------------------------------
    @property
    def size_bytes(self) -> int:
        with self._lock:
            return self._offset

    @property
    def freed_bytes(self) -> int:
        with self._lock:
            return self._freed

    @property
    def live_bytes(self) -> int:
        """Bytes still referenced by live ranges (file size minus freed
        ranges not yet reclaimed by compact()) — what the
        diskSpillFileBytes metric reports."""
        with self._lock:
            return self._offset - self._freed

    def free_range(self, offset: int, length: int) -> None:
        """Mark [offset, offset+length) dead; the space reclaims at the
        owner's next :meth:`compact` call."""
        with self._lock:
            self._freed += length
            rec = self._crcs.get(offset)
            if rec is not None and rec[0] == length:
                del self._crcs[offset]

    def freed_fraction(self) -> float:
        with self._lock:
            return self._freed / self._offset if self._offset else 0.0

    def compact(self, live_ranges: Dict) -> Dict:
        """Rewrite the file keeping only ``live_ranges`` ({key: (offset,
        length)}); returns the keys' new ranges. The owner must hold its
        own entry bookkeeping consistent (it passes every live range and
        installs every returned one) and keep readers out while a
        compaction is claimed (the owner's ``_compacting`` flag)."""
        from ..utils import checksum as CK
        with self._lock:
            if self._closed:
                raise SpillFileClosedError(self.path)
            fd, tmp = tempfile.mkstemp(prefix="spill_compact_",
                                       suffix=".bin", dir=self.dir)
            try:
                new_ranges: Dict = {}
                new_crcs: Dict[int, Tuple[int, int]] = {}
                pos = 0
                with os.fdopen(fd, "wb") as out, \
                        open(self.path, "rb") as src:
                    for key, (offset, length) in sorted(
                            live_ranges.items(), key=lambda kv: kv[1][0]):
                        src.seek(offset)
                        payload = src.read(length)
                        # Verify while relocating: compaction must not
                        # launder rotted bytes into a fresh file with a
                        # fresh crc.
                        rec = self._crcs.get(offset)
                        if not self.verify:
                            new_crcs[pos] = rec if rec is not None \
                                and rec[0] == length \
                                else (length, CK.crc32c(payload))
                        elif rec is not None and rec[0] == length:
                            CK.verify(payload, rec[1],
                                      f"spill range [{offset}:"
                                      f"{offset + length}) of {self.path} "
                                      "during compaction")
                            new_crcs[pos] = (length, rec[1])
                        else:
                            new_crcs[pos] = (length, CK.crc32c(payload))
                        out.write(payload)
                        new_ranges[key] = (pos, length)
                        pos += length
                os.replace(tmp, self.path)
            # A failed rewrite (rot surfacing as ChecksumError, disk
            # full, the path removed) must not leak the mkstemp temp —
            # the exact stray-file class the closed-aware guards exist
            # to prevent. os.replace consumed it on success.
            except BaseException:  # tpu-lint: ignore
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
            self._offset = pos
            self._freed = 0
            self._crcs = new_crcs
            return new_ranges


def _ipc_serialize(rb: pa.RecordBatch) -> bytes:
    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, rb.schema) as w:
        w.write_batch(rb)
    return sink.getvalue()


def _ipc_deserialize(payload: bytes) -> pa.RecordBatch:
    with pa.ipc.open_stream(io.BytesIO(payload)) as r:
        return next(iter(r))


#: bounded wait tick for transition/compaction waiters — workers always
#: notify, the timeout only guards against a worker dying mid-publish
_WAIT_TICK_S = 1.0

#: how long close() waits for in-flight spill IO before giving up and
#: marking the catalog closed (stragglers then stand down at publish)
_CLOSE_DRAIN_DEADLINE_S = 10.0


class BufferCatalog:
    """id -> tiered buffer, with budget-driven spill through the per-buffer
    state machine (module doc).

    The three tiers live inside one catalog (the reference splits catalog
    and three store objects; the chain wiring is identical —
    GpuShuffleEnv.initStorage, GpuShuffleEnv.scala:52-69). The public API
    keeps the synchronous CONTRACT of the reference — ``register_batch``
    returns within budget, ``spill_below`` returns with the bytes moved —
    but the waiting happens with the catalog lock RELEASED and the copies
    overlapped on the spill-IO lane."""

    def __init__(self, device_budget_bytes,
                 host_budget_bytes: int,
                 spill_dir: Optional[str] = None,
                 io_threads: int = 2):
        # int, or a 0-arg callable resolved on first budget check (lets the
        # device manager defer accelerator-backend init until device buffers
        # actually exist — see DeviceManager).
        self._device_budget = device_budget_bytes
        self.host_budget = host_budget_bytes
        self._entries: Dict[int, _Entry] = {}
        self.device_bytes = 0
        self.host_bytes = 0
        #: bytes reserved for in-flight device->host / host->disk copies
        #: (still counted in device_bytes/host_bytes until publish);
        #: budget loops subtract these so one drain never over-reserves
        self._spilling_device_bytes = 0
        self._spilling_host_bytes = 0
        self._next_id = 0
        self._lock = lockdep.rlock("BufferCatalog._lock")
        #: catalog-wide wait channel: compaction exclusion + IO-pending
        #: drain at close (shares the catalog lock, like the entry conds)
        self._state_cond = lockdep.condition_on(self._lock)
        self._compacting = False
        #: set by close() (even when its IO drain times out): late lane
        #: workers check it at publish time and stand down instead of
        #: resurrecting accounting — and _disk() refuses to lazily
        #: recreate a fresh SpillFile post-close (stray temp dir leak)
        self._closed = False
        #: disk appends in flight (range not yet published): a compaction
        #: snapshot taken now would MISS those bytes and the rewrite would
        #: silently drop them — _claim_compact refuses while > 0, and
        #: appenders stand aside while a claimed rewrite runs.
        self._disk_appends = 0
        #: bumped when a compaction installs relocated ranges; guards
        #: deferred free_range calls against stale pre-compaction offsets
        self._compact_gen = 0
        self._spill_dir = spill_dir
        self._spill_file: Optional[SpillFile] = None  # lazy: first disk spill
        self._pinned: set = set()
        # Spill-IO lane (spark.rapids.tpu.spill.ioThreads): up to
        # io_threads copies in flight on the shared pipeline pool; 0 =
        # inline on the requesting thread (still off-lock).
        self._io_threads = max(0, int(io_threads))
        import threading
        self._io_slots = threading.BoundedSemaphore(self._io_threads) \
            if self._io_threads > 0 else None
        self._io_pending = 0
        self._io_running = 0
        self.metrics = {"spilled_to_host": 0, "spilled_to_disk": 0,
                        "reloaded_from_host": 0, "reloaded_from_disk": 0,
                        # byte counters feed the query profile's spillBytes
                        # (metrics/profile.py takes per-query deltas)
                        "spill_bytes_to_host": 0, "spill_bytes_to_disk": 0,
                        # live size of the shared disk spill file (the
                        # diskSpillFileBytes profile metric) + compactions
                        "disk_spill_file_bytes": 0,
                        "disk_spill_file_compactions": 0,
                        # async-engine counters (ISSUE 11): wall ns and
                        # bytes of off-lock IO (spillThroughputBytesPerSec),
                        # submitted-not-finished watermark (spillQueueDepth),
                        # simultaneous-IO watermark (the overlap proof the
                        # spill-storm test asserts), and ns spent WAITING
                        # to acquire the catalog lock (spillLockWaitNs —
                        # the convoy detector).
                        "spill_io_ns": 0, "spill_io_bytes": 0,
                        "spill_queue_peak": 0, "spill_concurrent_peak": 0,
                        "spill_lock_wait_ns": 0}

    @property
    def device_budget(self) -> int:
        # Resolve through a LOCAL so two first readers racing here can
        # never interleave check-then-call with the other's just-assigned
        # int (TypeError: 'int' object is not callable); a double resolve
        # of the idempotent callable is harmless. The resolve itself runs
        # OFF-lock (it may probe the device for HBM size); the install is
        # identity-guarded under the (reentrant) lock so it can never
        # clobber a budget the setter assigned mid-resolve — the lost
        # update would silently disable a forced drain.
        b = self._device_budget
        if callable(b):
            val = b()
            with self._lock:
                if self._device_budget is b:
                    self._device_budget = val
                b = self._device_budget
            if callable(b):  # a different lazy callable was installed
                b = val
        return b

    @device_budget.setter
    def device_budget(self, value: int):
        with self._lock:
            self._device_budget = value

    def _disk(self) -> SpillFile:
        # Double-checked under the catalog lock (reentrant) so IO-lane
        # workers can resolve it off-lock without racing the lazy init.
        f = self._spill_file
        if f is None:
            with self._lock:
                if self._closed:
                    # Backstop: never lazily recreate a SpillFile after
                    # close() removed it — a straggler past the close
                    # drain deadline would leak a fresh temp file/dir.
                    raise SpillFileClosedError("spill catalog is closed")
                if self._spill_file is None:
                    self._spill_file = SpillFile(self._spill_dir)
                f = self._spill_file
        return f

    def _note_lock_wait(self, t0_ns: int) -> None:
        """First statement inside a public entry point's ``with
        self._lock:`` — the elapsed time since ``t0_ns`` (taken just
        before the ``with``) is dominated by the acquisition wait, which
        is exactly what spillLockWaitNs exists to expose: under the old
        synchronous design this was the convoy (threads queued behind a
        lock held across device copies); under the async engine it should
        stay near zero, because the lock now brackets only bookkeeping."""
        self.metrics["spill_lock_wait_ns"] += time.perf_counter_ns() - t0_ns

    def _entry_cond(self, entry: _Entry):
        if entry.cond is None:
            entry.cond = lockdep.condition_on(self._lock)
        return entry.cond

    # -- registration -------------------------------------------------------
    def register_batch(self, batch: ColumnarBatch,
                       priority: int = ACTIVE_BATCHING_PRIORITY,
                       owner: Optional[QosTag] = None) -> int:
        """Track a device batch as spillable; may spill lower-priority
        buffers (QoS order, module doc) to stay within the device budget.
        Returns with the budget satisfied, but the copies ran off-lock on
        the spill-IO lane — concurrent registrations overlap."""
        size = batch.device_size_bytes
        meta = TableMeta(batch.schema, batch.capacity, size)
        t0 = time.perf_counter_ns()
        with self._lock:
            self._note_lock_wait(t0)
            bid = self._next_id
            self._next_id += 1
            self._entries[bid] = _Entry(bid, priority, meta,
                                        StorageTier.DEVICE,
                                        device_batch=batch, owner=owner)
            self.device_bytes += size
        self._enforce_budgets(requester=owner)
        return bid

    # -- access -------------------------------------------------------------
    def acquire_batch(self, buffer_id: int) -> ColumnarBatch:
        """Return the batch on device, unspilling through the tiers if
        needed (RapidsBufferStore.getDeviceMemoryBuffer's tier climb).
        The restore copy runs off-lock; a buffer mid-transition is waited
        out on ITS OWN condition (the wait releases the catalog lock, so
        other threads proceed)."""
        while True:
            reserved = False
            t0 = time.perf_counter_ns()
            with self._lock:
                self._note_lock_wait(t0)
                entry = self._entries[buffer_id]
                assert not entry.freed, f"buffer {buffer_id} already freed"
                tier = entry.tier
                if tier == StorageTier.DEVICE:
                    return entry.device_batch
                if tier in TRANSITIONAL_TIERS:
                    # Wait out the in-flight transition on the BUFFER's
                    # condition — the wait releases the catalog lock, so
                    # threads touching other buffers proceed. A closed
                    # catalog also ends the wait: the stand-down publish
                    # paths never settle the tier, so a waiter would
                    # otherwise tick here forever (the re-entered loop
                    # then raises KeyError on the cleared _entries).
                    cond = self._entry_cond(entry)
                    while entry.tier in TRANSITIONAL_TIERS \
                            and not entry.freed and not self._closed:
                        cond.wait(timeout=_WAIT_TICK_S)
                else:
                    # settled off-device: reserve the restore
                    src = tier  # HOST or DISK
                    entry.tier = StorageTier.RESTORING
                    entry.moving_from = src
                    self._entry_cond(entry)
                    host_rb = entry.host_batch
                    reserved = True
            if reserved:
                return self._restore_entry(entry, src, host_rb)

    def _release_freed_restore_range(self, entry: _Entry, src: str) -> bool:
        """Deferred ``free_range`` for a freed-while-RESTORING disk entry
        (caller holds the lock): free() popped the entry and left the
        range to the restore worker, which may still have been reading
        it. Generation-guarded — a compaction since free() (gen moved, or
        a claimed rewrite running) already dropped/relocated the bytes,
        so these offsets are stale. Returns whether this thread claimed
        the follow-up compaction."""
        if src == StorageTier.DISK \
                and entry.disk_range is not None \
                and self._spill_file is not None \
                and not self._compacting \
                and entry.freed_gen == self._compact_gen:
            self._spill_file.free_range(*entry.disk_range)
            entry.disk_range = None
            return self._claim_compact()
        return False

    def _restore_entry(self, entry: _Entry, src: str,
                       host_rb) -> ColumnarBatch:
        """Off-lock restore of a RESTORING-reserved entry: disk read +
        IPC decode + host->device upload, then publish under the lock."""
        size = entry.meta.size_bytes
        t0 = time.perf_counter_ns()
        try:
            if src == StorageTier.DISK:
                payload = self._read_disk_payload(entry)
                host_rb = _ipc_deserialize(payload)
            with trace_range("spill.reload_to_device"):
                batch = ColumnarBatch.from_arrow(
                    host_rb, capacity=entry.meta.capacity)
        # Revert-and-re-raise: classification-neutral (the exception
        # reaches the retry taxonomy verbatim at the acquiring site).
        except BaseException:  # tpu-lint: ignore
            compact_ready = False
            with self._lock:
                if entry.freed:
                    # free() raced the restore and deferred the disk
                    # range to this worker — the same contract as the
                    # successful-publish freed path below.
                    compact_ready = \
                        self._release_freed_restore_range(entry, src)
                else:
                    entry.tier = src  # revert the reservation
                    entry.moving_from = ""
                entry.cond.notify_all()
            if compact_ready:
                try:
                    self._compact_now()
                except Exception:  # tpu-lint: ignore - the ORIGINAL
                    # restore error is the one the retry taxonomy must
                    # classify (the classification-neutral contract
                    # above); a failed opportunistic rewrite must not
                    # replace it.
                    import logging
                    logging.getLogger(__name__).warning(
                        "spill-file compaction failed during restore "
                        "revert; deferring reclaim", exc_info=True)
            raise
        io_ns = time.perf_counter_ns() - t0
        compact_ready = False
        closed = False
        with self._lock:
            self.metrics["spill_io_ns"] += io_ns
            self.metrics["spill_io_bytes"] += size
            freed = entry.freed
            if not freed and self._closed:
                # close() raced this restore (restores run on the
                # acquiring thread, outside close()'s IO drain): the
                # catalog is cleared and its spill file gone — hand the
                # restored batch to the acquirer without resurrecting
                # byte accounting, tier state, or disk bookkeeping.
                closed = True
                entry.cond.notify_all()
            elif freed:
                # free() deferred the disk range to this worker (the
                # read may have been in flight then): release it NOW or
                # the dead bytes sit in the shared spill file — invisible
                # to freed_fraction, so compaction might never trigger.
                compact_ready = self._release_freed_restore_range(entry,
                                                                  src)
                entry.cond.notify_all()
            else:
                if src == StorageTier.DISK:
                    # While a claimed rewrite runs the offsets are about
                    # to be remapped; disk_range=None makes the install
                    # loop free the relocated bytes instead. When no
                    # rewrite is in flight, disk_range is current (the
                    # install loop keeps live entries' ranges fresh).
                    if entry.disk_range is not None \
                            and self._spill_file is not None \
                            and not self._compacting:
                        self._spill_file.free_range(*entry.disk_range)
                    entry.disk_range = None
                    self.metrics["reloaded_from_disk"] += 1
                    compact_ready = self._claim_compact()
                else:
                    self.host_bytes -= size
                entry.host_batch = None
                entry.device_batch = batch
                entry.tier = StorageTier.DEVICE
                entry.moving_from = ""
                self.device_bytes += size
                self.metrics["reloaded_from_host"] += 1
                self.metrics["disk_spill_file_bytes"] = \
                    self._spill_file.live_bytes if self._spill_file else 0
                entry.cond.notify_all()
        if compact_ready:
            self._compact_now()
        if freed:
            raise KeyError(entry.buffer_id)
        if closed:
            return batch  # no budget pass against the closed catalog
        self._enforce_budgets(requester=entry.owner,
                              exclude=entry.buffer_id)
        return batch

    def _read_disk_payload(self, entry: _Entry) -> bytes:
        """Read one RESTORING entry's disk payload off the catalog lock,
        safely against concurrent compaction: readers stand aside while a
        compaction is claimed, the SpillFile read itself is atomic under
        the file's own lock, and the range is re-validated afterward — a
        relocated range simply retries with the installed offsets."""
        while True:
            with self._lock:
                while self._compacting:
                    self._state_cond.wait(timeout=_WAIT_TICK_S)
                rng = entry.disk_range
            payload = self._disk().read(*rng)
            with self._lock:
                if not self._compacting and entry.disk_range == rng:
                    return payload

    def tier_of(self, buffer_id: int) -> str:
        with self._lock:
            return self._entries[buffer_id].tier

    def free(self, buffer_id: int):
        compact_ready = False
        t0 = time.perf_counter_ns()
        with self._lock:
            self._note_lock_wait(t0)
            entry = self._entries.pop(buffer_id, None)
            self._pinned.discard(buffer_id)
            if entry is None or entry.freed:
                return
            entry.freed = True
            size = entry.meta.size_bytes
            tier = entry.tier
            if tier == StorageTier.DEVICE:
                self.device_bytes -= size
                entry.device_batch = None
            elif tier == StorageTier.HOST:
                self.host_bytes -= size
                entry.host_batch = None
            elif tier == StorageTier.DISK:
                if entry.disk_range is not None \
                        and self._spill_file is not None \
                        and not self._compacting:
                    # While a claimed rewrite runs, the offsets are about
                    # to be remapped — the install loop frees the
                    # relocated bytes of popped entries instead.
                    self._spill_file.free_range(*entry.disk_range)
                    entry.disk_range = None
                    compact_ready = self._claim_compact()
            elif tier == StorageTier.SPILLING:
                # The IO-lane worker owns the payload refs; account the
                # source tier now, the worker skips it on publish.
                if entry.moving_from == StorageTier.DEVICE:
                    self.device_bytes -= size
                else:
                    self.host_bytes -= size
            elif tier == StorageTier.RESTORING:
                # device_bytes was never re-added; release the source
                # side the worker is copying FROM (the disk range is
                # freed by the worker — it may still be reading it;
                # freed_gen lets it detect a compaction intervening
                # before its publish, which makes the offsets stale).
                entry.freed_gen = self._compact_gen
                if entry.moving_from == StorageTier.HOST:
                    self.host_bytes -= size
            if entry.cond is not None:
                entry.cond.notify_all()
        if compact_ready:
            self._compact_now()

    def pin(self, buffer_id: int):
        """Exclude a buffer from spilling while an operator actively uses it
        (the reference's on-deck priority bump)."""
        with self._lock:
            self._pinned.add(buffer_id)

    def unpin(self, buffer_id: int):
        with self._lock:
            self._pinned.discard(buffer_id)

    def leak_report(self) -> list:
        """Buffers registered but never freed — the cudf ref-count
        leak-warning role (SURVEY.md §5 race/leak tracking; reference
        `noWarnLeakExpected`). Returns [(buffer_id, tier, bytes)]."""
        with self._lock:
            return [(bid, e.tier, e.meta.size_bytes)
                    for bid, e in self._entries.items() if not e.freed]

    def close(self):
        with self._lock:
            # Drain in-flight IO first: a worker publishing into a
            # cleared catalog would resurrect accounting. Bounded — the
            # lane's units are short, and public callers drain their own
            # futures before returning.
            deadline = time.monotonic() + _CLOSE_DRAIN_DEADLINE_S
            while self._io_pending > 0 and time.monotonic() < deadline:
                self._state_cond.wait(timeout=_WAIT_TICK_S)
            # Even if the drain timed out, mark closed FIRST: any lane
            # worker still running sees the flag at publish time and
            # stands down instead of touching the cleared catalog or
            # lazily recreating the spill file (stray temp dir).
            self._closed = True
            # Wake every per-buffer waiter: stand-down publishes never
            # settle the tier, so a waiter mid acquire_batch would
            # otherwise tick against SPILLING/RESTORING forever (its
            # wait loop also checks _closed).
            for e in self._entries.values():
                if e.cond is not None:
                    e.cond.notify_all()
            import logging
            if self._io_pending > 0:
                logging.getLogger(__name__).warning(
                    "spill catalog closed with %d IO unit(s) still in "
                    "flight after the drain deadline; they will stand "
                    "down at publish time", self._io_pending)
            leaks = self.leak_report()
            if leaks:
                total = sum(b for _, _, b in leaks)
                logging.getLogger(__name__).warning(
                    "spill catalog closed with %d leaked buffer(s), "
                    "%d bytes: %s", len(leaks), total,
                    [(bid, t) for bid, t, _ in leaks[:8]])
            self._entries.clear()
            self._pinned.clear()
            if self._spill_file is not None:
                self._spill_file.close()
                self._spill_file = None

    # -- spilling -----------------------------------------------------------
    def synchronous_spill(self, target_device_bytes: int,
                          requester: Optional[QosTag] = None):
        """Spill device buffers (QoS victim order) until usage <= target
        (RapidsBufferStore.synchronousSpill:137-149). Returns once the
        copies have landed; they ran off-lock, overlapped on the lane."""
        jobs = self._reserve_for_target(target_device_bytes, requester)
        self._run_spill_jobs(jobs, requester)

    def spill_below(self, priority_ceiling: int,
                    requester: Optional[QosTag] = None) -> int:
        """Spill every unpinned device buffer whose priority is below
        ``priority_ceiling`` off the device (cascading to disk via the
        host budget) — the OOM-retry drain (memory/retry.py): everything
        except on-deck buffers leaves the device before the attempt
        re-runs. Victims drain in QoS order (``requester``'s own buffers
        first — an OOM ladder must not evict its neighbors' hot tables
        while its own spillable state suffices). Returns device bytes
        moved. Concurrent drains are safe without any outer lock: the
        state machine reserves each victim exactly once."""
        t0 = time.perf_counter_ns()
        with self._lock:
            self._note_lock_wait(t0)
            jobs = self._reserve_device_victims(
                target=0, requester=requester, ceiling=priority_ceiling)
        moved = sum(e.meta.size_bytes for e in jobs)
        self._run_spill_jobs(jobs, requester)
        return moved

    def _tenant_device_bytes_locked(self, tenant: str) -> int:
        """Settled DEVICE bytes owned by ``tenant`` (caller holds the
        lock) — the ONE tenant-residency meter shared by the public
        accessor and the budget victim reservation."""
        return sum(e.meta.size_bytes for e in self._entries.values()
                   if e.tier == StorageTier.DEVICE and not e.freed
                   and e.owner is not None
                   and e.owner.tenant == tenant)

    def tenant_device_bytes(self, tenant: str) -> int:
        """Settled DEVICE bytes owned by ``tenant``'s queries (QosTag
        owners stamped at registration) — the usage the serving layer's
        per-tenant memory budget meters (docs/serving.md)."""
        with self._lock:
            return self._tenant_device_bytes_locked(tenant)

    def spill_tenant_over_budget(self, tenant: str, budget: int,
                                 requester: Optional[QosTag] = None) -> int:
        """Spill ``tenant``'s own device buffers (QoS victim order among
        them) until its device residency fits ``budget`` — the serving
        layer's budget enforcement (docs/serving.md): an over-budget
        tenant pays with its OWN spillable residency before its next
        query runs; neighbors' buffers are never candidates, so
        enforcement can neither crash nor starve them. Returns device
        bytes moved."""
        t0 = time.perf_counter_ns()
        with self._lock:
            self._note_lock_wait(t0)
            jobs = self._reserve_device_victims(target=int(budget),
                                                requester=requester,
                                                tenant=tenant)
        moved = sum(e.meta.size_bytes for e in jobs)
        self._run_spill_jobs(jobs, requester)
        return moved

    def _reserve_for_target(self, target: int,
                            requester: Optional[QosTag]) -> List[_Entry]:
        t0 = time.perf_counter_ns()
        with self._lock:
            self._note_lock_wait(t0)
            return self._reserve_device_victims(target=target,
                                                requester=requester)

    def _enforce_budgets(self, requester: Optional[QosTag] = None,
                         exclude: Optional[int] = None) -> None:
        """Bring device AND host usage back under budget: reserve victims
        under the lock, copy off-lock on the lane, wait for the publishes
        (with no lock held). The upload memo's device bytes count against
        the budget too; as a pure cache it is the cheapest thing to evict
        (LRU) before any real buffer spills."""
        budget = self.device_budget  # resolves the lazy callable off-lock
        from ..data import upload_cache
        over = self.device_bytes + upload_cache.cache_bytes() - budget
        if over > 0:
            upload_cache.shrink_by(over)
        t0 = time.perf_counter_ns()
        with self._lock:
            self._note_lock_wait(t0)
            jobs = self._reserve_device_victims(
                target=budget, requester=requester, exclude=exclude)
            jobs += self._reserve_host_victims(requester)
        self._run_spill_jobs(jobs, requester)

    def _victim_key(self, entry: _Entry, requester: Optional[QosTag]):
        """QoS victim order (module doc). Spill PRIORITY stays the
        primary band — shuffle outputs are refetchable and always go
        before anyone's active batches, and on-deck buffers go last no
        matter who owns them (the reference's semantics, preserved).
        WITHIN a band: the requester's own query first (its OOM ladder
        drains its own state before touching a neighbor), then its
        tenant, then other tenants by DESCENDING deadline slack (a query
        far from its deadline can afford the reload round trip), then
        descending size (fewest evictions relieve the most pressure),
        then registration order (deterministic tie-break)."""
        owner = entry.owner
        if requester is None:
            return (entry.priority, 0, 0.0, 0, entry.buffer_id)
        if owner is requester:
            cls = 0
        elif owner is not None and owner.tenant == requester.tenant:
            cls = 1
        else:
            cls = 2
        slack = owner.slack() if owner is not None else math.inf
        return (entry.priority, cls, -slack, -entry.meta.size_bytes,
                entry.buffer_id)

    def _reserve_device_victims(self, target: int,
                                requester: Optional[QosTag],
                                exclude: Optional[int] = None,
                                ceiling: Optional[int] = None,
                                tenant: Optional[str] = None
                                ) -> List[_Entry]:
        """Reserve DEVICE->SPILLING transitions (caller holds the lock)
        until settled-plus-inflight device usage fits ``target``.
        ``ceiling`` bounds eligible priorities (spill_below); ``tenant``
        restricts BOTH the usage meter and the candidates to buffers
        owned by that tenant (the serving layer's per-tenant memory
        budget — neighbors' buffers are never candidates)."""
        if tenant is None:
            usage = self.device_bytes - self._spilling_device_bytes
        else:
            usage = self._tenant_device_bytes_locked(tenant)
        if usage <= target:
            return []
        cands = [e for e in self._entries.values()
                 if e.tier == StorageTier.DEVICE and not e.freed
                 and e.buffer_id != exclude
                 and e.buffer_id not in self._pinned
                 and (ceiling is None or e.priority < ceiling)
                 and (tenant is None or (e.owner is not None
                                         and e.owner.tenant == tenant))]
        cands.sort(key=lambda e: self._victim_key(e, requester))
        jobs: List[_Entry] = []
        for e in cands:
            if usage <= target:
                break
            e.tier = StorageTier.SPILLING
            e.moving_from = StorageTier.DEVICE
            self._entry_cond(e)
            self._spilling_device_bytes += e.meta.size_bytes
            usage -= e.meta.size_bytes
            jobs.append(e)
        return jobs

    def _reserve_host_victims(self, requester: Optional[QosTag]
                              ) -> List[_Entry]:
        """Reserve HOST->SPILLING (to disk) transitions (caller holds the
        lock) until settled-plus-inflight host usage fits the budget."""
        if self.host_bytes - self._spilling_host_bytes <= self.host_budget:
            return []
        cands = [e for e in self._entries.values()
                 if e.tier == StorageTier.HOST and not e.freed
                 and e.buffer_id not in self._pinned]
        cands.sort(key=lambda e: self._victim_key(e, requester))
        jobs: List[_Entry] = []
        for e in cands:
            if self.host_bytes - self._spilling_host_bytes \
                    <= self.host_budget:
                break
            e.tier = StorageTier.SPILLING
            e.moving_from = StorageTier.HOST
            self._entry_cond(e)
            self._spilling_host_bytes += e.meta.size_bytes
            jobs.append(e)
        return jobs

    # -- the spill-IO lane --------------------------------------------------
    def _run_spill_jobs(self, jobs: List[_Entry],
                        requester: Optional[QosTag]) -> None:
        """Run reserved spill transitions off-lock: on the lane when
        ioThreads > 0 (overlapped; bounded by the slot semaphore inside
        each unit), inline otherwise. Always waits for every publish —
        the public API's synchronous contract — but with NO lock held, so
        waiters of other buffers and other registrations proceed."""
        if not jobs:
            return
        if self._io_slots is None or len(jobs) == 1:
            # Inline path (ioThreads=0, or a single job): same collect-
            # and-re-raise contract as the submitted path below — every
            # reservation must settle (publish or revert) before the
            # first failure propagates; aborting mid-list would leave
            # the rest SPILLING forever.
            err0: Optional[BaseException] = None
            for e in jobs:
                try:
                    self._spill_job(e, requester)
                except BaseException as exc:  # tpu-lint: ignore
                    err0 = err0 or exc
            if err0 is not None:
                raise err0
            return
        from ..exec import pipeline
        submitted = []
        for e in jobs:
            with self._lock:
                self._io_pending += 1
                if self._io_pending > self.metrics["spill_queue_peak"]:
                    self.metrics["spill_queue_peak"] = self._io_pending
            f = pipeline.submit_spill_io(self._io_task, e, requester)
            if f is None:  # pool torn down: run inline
                self._io_finished()
                self._spill_job(e, requester)
            else:
                submitted.append((f, e))
        from ..metrics import trace as _tracing
        err: Optional[BaseException] = None
        for f, e in submitted:
            try:
                with _tracing.span(
                        requester.trace if requester is not None else None,
                        "spill.io_wait", cat="spill"), \
                        lockdep.blocking("spill.io_wait"):
                    f.result()
            except BaseException as exc:  # tpu-lint: ignore - collect-
                # re-raise: every job must settle (publish or revert)
                # before the first failure propagates to the retry
                # taxonomy; a cancelled unit (pool shutdown race) runs
                # inline so the reservation never leaks.
                if _is_cancelled(exc):
                    # _io_task never started, so its finally never
                    # decremented the pending count — undo it here or
                    # every later close() spins its full drain deadline.
                    self._io_finished()
                    self._spill_job(e, requester)
                else:
                    err = err or exc
        if err is not None:
            raise err

    def _io_task(self, entry: _Entry, requester: Optional[QosTag]) -> None:
        """One lane unit: bounded by the ioThreads slot semaphore."""
        with self._io_slots:
            try:
                self._spill_job(entry, requester)
            finally:
                self._io_finished()

    def _io_finished(self) -> None:
        with self._lock:
            self._io_pending -= 1
            self._state_cond.notify_all()

    def _spill_job(self, entry: _Entry,
                   requester: Optional[QosTag]) -> None:
        """Run one reserved SPILLING transition to completion (off-lock
        copy + locked publish), cascading host->disk pressure on the same
        worker so a waiter observes full settlement. Tracks simultaneous
        spill I/O — spill_concurrent_peak >= 2 is the machine-checkable
        proof that spills overlap instead of convoying (the spill-storm
        test asserts it)."""
        with self._lock:
            self._io_running += 1
            if self._io_running > self.metrics["spill_concurrent_peak"]:
                self.metrics["spill_concurrent_peak"] = self._io_running
        # Lane-transition span (ISSUE 13): runs on the IO-lane worker, so
        # it parents under the requesting query's trace root — concurrent
        # lane units show as overlapping spans, the proof the PR-11
        # off-lock engine actually overlaps.
        from ..metrics import trace as _tracing
        try:
            with _tracing.span(
                    requester.trace if requester is not None else None,
                    "spill.io", cat="spill",
                    tier=entry.moving_from or entry.tier,
                    bytes=entry.meta.size_bytes):
                if entry.moving_from == StorageTier.DEVICE:
                    self._spill_device_job(entry, requester)
                else:
                    self._spill_host_job(entry)
        finally:
            with self._lock:
                self._io_running -= 1

    def _spill_device_job(self, entry: _Entry,
                          requester: Optional[QosTag]) -> None:
        size = entry.meta.size_bytes
        t0 = time.perf_counter_ns()
        try:
            with trace_range("spill.device_to_host"):
                rb = entry.device_batch.to_arrow()
        # Revert-and-re-raise: classification-neutral (the waiter's
        # retry site classifies the propagated exception).
        except BaseException:  # tpu-lint: ignore
            with self._lock:
                self._spilling_device_bytes -= size
                if not entry.freed:
                    entry.tier = StorageTier.DEVICE  # revert
                    entry.moving_from = ""
                entry.cond.notify_all()
            raise
        io_ns = time.perf_counter_ns() - t0
        cascade: List[_Entry] = []
        with self._lock:
            self._spilling_device_bytes -= size
            self.metrics["spill_io_ns"] += io_ns
            self.metrics["spill_io_bytes"] += size
            if self._closed:
                # Late publish after close() gave up its drain: the
                # catalog (and byte accounting) is gone — drop the refs
                # and stand down; no host-budget cascade either.
                entry.device_batch = None
                entry.host_batch = None
                entry.cond.notify_all()
                self._state_cond.notify_all()
                return
            if entry.freed:
                entry.device_batch = None
                entry.cond.notify_all()
            else:
                entry.host_batch = rb
                entry.device_batch = None
                entry.tier = StorageTier.HOST
                entry.moving_from = ""
                self.device_bytes -= size
                self.host_bytes += size
                self.metrics["spilled_to_host"] += 1
                self.metrics["spill_bytes_to_host"] += size
                entry.cond.notify_all()
                cascade = self._reserve_host_victims(requester)
        # Host-budget cascade runs on THIS worker (sequential, still
        # off-lock): the submitter's wait then covers the whole chain.
        # Collect-and-re-raise (same contract as _run_spill_jobs): every
        # reserved victim must settle — publish or revert — before the
        # first failure propagates, or the survivors sit SPILLING forever
        # with _spilling_host_bytes inflated and any later acquire of
        # them hangs.
        err: Optional[BaseException] = None
        for victim in cascade:
            try:
                self._spill_host_job(victim)
            except BaseException as exc:  # tpu-lint: ignore
                err = err or exc
        if err is not None:
            raise err

    def _spill_host_job(self, entry: _Entry) -> None:
        size = entry.meta.size_bytes
        t0 = time.perf_counter_ns()
        # Appends exclude compaction both ways: stand aside while a
        # claimed rewrite runs (it would os.replace the file under us),
        # and hold _disk_appends so no claim's live snapshot can miss the
        # appended-but-not-yet-published range (the rewrite would drop
        # those bytes and this publish would install a stale offset —
        # permanent data loss on a later restore).
        with self._lock:
            while self._compacting:
                self._state_cond.wait(timeout=_WAIT_TICK_S)
            if self._closed:
                # close() gave up its IO drain and already removed the
                # spill file: abandon the transition (the catalog is
                # gone; appending would resurrect a fresh SpillFile).
                self._spilling_host_bytes -= size
                entry.host_batch = None
                if entry.cond is not None:
                    entry.cond.notify_all()
                return
            self._disk_appends += 1
        try:
            with trace_range("spill.host_to_disk"):
                payload = _ipc_serialize(entry.host_batch)
                rng = self._disk().append(payload)
        except SpillFileClosedError:
            # close() raced between the pre-gate and the append (the
            # closed-aware SpillFile refused rather than re-create the
            # removed path via open('ab')): settle as the closed
            # stand-down — reverting to HOST would resurrect tier state
            # in the cleared catalog.
            with self._lock:
                self._disk_appends -= 1
                self._spilling_host_bytes -= size
                entry.host_batch = None
                entry.cond.notify_all()
                self._state_cond.notify_all()
            return
        # Revert-and-re-raise: classification-neutral (see above).
        except BaseException:  # tpu-lint: ignore
            with self._lock:
                self._disk_appends -= 1
                self._spilling_host_bytes -= size
                if not entry.freed:
                    entry.tier = StorageTier.HOST  # revert
                    entry.moving_from = ""
                entry.cond.notify_all()
            raise
        io_ns = time.perf_counter_ns() - t0
        compact_ready = False
        with self._lock:
            self._disk_appends -= 1
            self._spilling_host_bytes -= size
            self.metrics["spill_io_ns"] += io_ns
            self.metrics["spill_io_bytes"] += len(payload)
            if self._closed:
                # close() gave up its IO drain while the append was in
                # flight and already removed the spill file (the range
                # died with it): settle without touching _disk() — it
                # must not resurrect a fresh file post-close.
                entry.host_batch = None
                entry.cond.notify_all()
                self._state_cond.notify_all()
                return
            if entry.freed:
                self._disk().free_range(*rng)
                entry.host_batch = None
                compact_ready = self._claim_compact()
            else:
                entry.disk_range = rng
                entry.host_batch = None
                entry.tier = StorageTier.DISK
                entry.moving_from = ""
                self.host_bytes -= size
                self.metrics["spilled_to_disk"] += 1
                self.metrics["spill_bytes_to_disk"] += len(payload)
                # Pick up a compaction our in-flight append deferred.
                compact_ready = self._claim_compact()
            self.metrics["disk_spill_file_bytes"] = self._disk().live_bytes
            entry.cond.notify_all()
        if compact_ready:
            self._compact_now()

    # -- disk compaction ----------------------------------------------------
    def _claim_compact(self) -> bool:
        """True when the shared spill file crossed DISK_COMPACT_FRACTION
        dead bytes AND this caller claimed the (single) compaction slot
        (caller holds the lock; must then call :meth:`_compact_now` after
        releasing it). The claim excludes disk readers until cleared."""
        f = self._spill_file
        if f is None or self._compacting or self._disk_appends > 0:
            # _disk_appends > 0: an appended-but-unpublished range would
            # be invisible to the live snapshot — the rewrite would drop
            # its bytes. The appender's publish re-claims if still due.
            if f is not None:
                self.metrics["disk_spill_file_bytes"] = f.live_bytes
            return False
        if f.freed_bytes == 0 \
                or f.freed_fraction() < DISK_COMPACT_FRACTION:
            self.metrics["disk_spill_file_bytes"] = f.live_bytes
            return False
        self._compacting = True
        return True

    def _compact_now(self) -> None:
        """Rewrite the spill file keeping only live ranges — OFF the
        catalog lock (the PR-9 debt had this under it): the live-range
        snapshot and the new-range install bracket the rewrite under the
        lock, the rewrite itself holds only the file's own io_ok lock,
        and disk readers stand aside on the claimed ``_compacting`` flag
        (re-validating their range after every read)."""
        f = self._spill_file
        with self._lock:
            if self._closed or f is None:
                # close() raced the claimed rewrite (an inline job's
                # claim runs outside close()'s IO drain): the file and
                # every range died with it — release the claim and
                # stand down instead of dereferencing the nulled file.
                self._compacting = False
                self._state_cond.notify_all()
                return
            live = {bid: e.disk_range for bid, e in self._entries.items()
                    if e.disk_range is not None and not e.freed}
        try:
            with trace_range("spill.compact_disk"):
                new_ranges = f.compact(live)
        except SpillFileClosedError:
            # close() landed between the snapshot and the rewrite (the
            # closed-aware SpillFile refused): same stand-down — an
            # opportunistic reclaim of a dead file is not an error.
            with self._lock:
                self._compacting = False
                self._state_cond.notify_all()
            return
        # Release the claim and re-raise: classification-neutral.
        except BaseException:  # tpu-lint: ignore
            with self._lock:
                self._compacting = False
                self._state_cond.notify_all()
            raise
        with self._lock:
            for bid, rng in new_ranges.items():
                e = self._entries.get(bid)
                if e is None or e.freed or e.disk_range is None:
                    # freed (or restored) while the rewrite ran: release
                    # the relocated bytes instead of resurrecting them
                    f.free_range(*rng)
                else:
                    e.disk_range = rng
            self._compacting = False
            self._compact_gen += 1
            self.metrics["disk_spill_file_compactions"] += 1
            self.metrics["disk_spill_file_bytes"] = f.live_bytes
            self._state_cond.notify_all()


def _is_cancelled(exc: BaseException) -> bool:
    from concurrent.futures import CancelledError
    return isinstance(exc, CancelledError)
