"""Spillable buffer framework — device -> host -> disk tiers.

Architectural port of the reference's spill subsystem (SURVEY.md §2.1):
``RapidsBufferCatalog`` (RapidsBufferCatalog.scala:30) maps buffer ids to
tiered buffers; ``RapidsBufferStore`` (RapidsBufferStore.scala:40) owns one
tier and spills to the next via ``synchronousSpill:137-149`` in
spill-priority order (SpillPriorities.scala:26); the device store's pressure
callback is ``DeviceMemoryEventHandler.onAllocFailure:35-59``.

TPU-native differences: XLA owns the HBM allocator and exposes no
alloc-failure callback, so the device store enforces a *byte budget*
(fraction of HBM, GpuDeviceManager-style) and spills synchronously when a
registration would exceed it — pressure is handled before allocation rather
than on allocation failure. Host interchange is Arrow IPC (the reference
uses JCudfSerialization host buffers); the disk tier appends IPC-serialized
batches to a shared spill file, like the reference's disk block manager
files.
"""

from __future__ import annotations

import dataclasses
import heapq
import io
import os
import tempfile
from typing import Dict, Optional, Tuple

import pyarrow as pa

from .. import types as T
from ..data.batch import ColumnarBatch
from ..utils import lockdep
from ..utils.tracing import trace_range


# ---------------------------------------------------------------------------
# Spill priorities (SpillPriorities.scala:26): LOWER values spill FIRST.
# ---------------------------------------------------------------------------

#: Shuffle outputs spill before anything else: they are re-fetchable and
#: typically long-lived.
OUTPUT_FOR_SHUFFLE_PRIORITY = -10_000_000
#: Buffers parked by operators between batches (coalesce accumulation).
ACTIVE_BATCHING_PRIORITY = 0
#: Buffers an operator is actively using; spill only under extreme pressure.
ACTIVE_ON_DECK_PRIORITY = 10_000_000


class StorageTier:
    DEVICE = "device"
    HOST = "host"
    DISK = "disk"


@dataclasses.dataclass
class TableMeta:
    """What's needed to faithfully restore a batch on device (the flatbuffer
    TableMeta analog, MetaUtils.scala:41)."""

    schema: T.Schema
    capacity: int
    size_bytes: int


@dataclasses.dataclass
class _Entry:
    buffer_id: int
    priority: int
    meta: TableMeta
    tier: str
    device_batch: Optional[ColumnarBatch] = None
    host_batch: Optional[pa.RecordBatch] = None
    disk_range: Optional[Tuple[int, int]] = None  # (offset, length)
    freed: bool = False


#: Compact the shared spill file once this fraction of its bytes is dead
#: (freed ranges of a still-open catalog previously leaked until close).
DISK_COMPACT_FRACTION = 0.5


class SpillFile:
    """Shared spill file (RapidsDiskStore's block-manager file): appends
    serialized payloads, tracks freed ranges, and compacts itself when the
    owner asks — so freed disk space reclaims during the catalog's
    lifetime instead of leaking until close.

    Durability (ISSUE 7): every appended range records its CRC32C and
    every read verifies it, so disk bit rot (or a concurrent writer
    scribbling over the file) surfaces as a typed
    :class:`~..utils.checksum.ChecksumError` — classified transient by
    the retry taxonomy — instead of deserializing garbage into a query
    answer."""

    def __init__(self, spill_dir: Optional[str] = None,
                 verify: bool = True):
        self._owns_dir = spill_dir is None
        self.dir = spill_dir or tempfile.mkdtemp(prefix="tpu_spill_")
        os.makedirs(self.dir, exist_ok=True)
        # Unique per catalog so concurrent catalogs (or a reused spillDir
        # from a previous process) never interleave offsets.
        fd, self.path = tempfile.mkstemp(prefix="spill_", suffix=".bin",
                                         dir=self.dir)
        os.close(fd)
        self._offset = 0
        self._freed = 0
        #: offset -> (length, crc32c) of every live appended range
        self._crcs: Dict[int, Tuple[int, int]] = {}
        #: False = record checksums but skip verification (the shuffle
        #: catalog threads spark.rapids.tpu.shuffle.checksum.enabled here
        #: so the kill switch covers its disk tier too)
        self.verify = verify
        self._lock = lockdep.lock("SpillFile._lock", io_ok=True)

    def close(self):
        import shutil
        try:
            os.remove(self.path)
        except OSError:
            pass
        if self._owns_dir:
            shutil.rmtree(self.dir, ignore_errors=True)

    def append(self, payload: bytes) -> Tuple[int, int]:
        from ..utils import checksum as CK
        crc = CK.crc32c(payload)
        with self._lock:
            offset = self._offset
            with open(self.path, "ab") as f:
                f.write(payload)
            self._offset += len(payload)
            self._crcs[offset] = (len(payload), crc)
            return offset, len(payload)

    def read_with_crc(self, offset: int, length: int
                      ) -> Tuple[bytes, Optional[int]]:
        """(payload, recorded crc32c or None) WITHOUT verification — for
        callers that must verify outside their own wider lock (the
        shuffle catalog's disk tier). None when the range has no
        recorded checksum or verification is disabled."""
        # Under the lock: compact() may be rewriting offsets concurrently.
        with self._lock:
            with open(self.path, "rb") as f:
                f.seek(offset)
                payload = f.read(length)
            rec = self._crcs.get(offset)
        if self.verify and rec is not None and rec[0] == length:
            return payload, rec[1]
        return payload, None

    def read(self, offset: int, length: int) -> bytes:
        from ..utils import checksum as CK
        # Verification runs OUTSIDE the lock — the payload is a private
        # copy, and a full-payload CRC pass must not serialize readers.
        payload, crc = self.read_with_crc(offset, length)
        if crc is not None:
            CK.verify(payload, crc,
                      f"spill range [{offset}:{offset + length}) of "
                      f"{self.path}")
        return payload

    # -- space reclaim ------------------------------------------------------
    @property
    def size_bytes(self) -> int:
        with self._lock:
            return self._offset

    @property
    def freed_bytes(self) -> int:
        with self._lock:
            return self._freed

    @property
    def live_bytes(self) -> int:
        """Bytes still referenced by live ranges (file size minus freed
        ranges not yet reclaimed by compact()) — what the
        diskSpillFileBytes metric reports."""
        with self._lock:
            return self._offset - self._freed

    def free_range(self, offset: int, length: int) -> None:
        """Mark [offset, offset+length) dead; the space reclaims at the
        owner's next :meth:`compact` call."""
        with self._lock:
            self._freed += length
            rec = self._crcs.get(offset)
            if rec is not None and rec[0] == length:
                del self._crcs[offset]

    def freed_fraction(self) -> float:
        with self._lock:
            return self._freed / self._offset if self._offset else 0.0

    def compact(self, live_ranges: Dict) -> Dict:
        """Rewrite the file keeping only ``live_ranges`` ({key: (offset,
        length)}); returns the keys' new ranges. The owner must hold its
        own entry bookkeeping consistent (it passes every live range and
        installs every returned one)."""
        from ..utils import checksum as CK
        with self._lock:
            fd, tmp = tempfile.mkstemp(prefix="spill_compact_",
                                       suffix=".bin", dir=self.dir)
            new_ranges: Dict = {}
            new_crcs: Dict[int, Tuple[int, int]] = {}
            pos = 0
            with os.fdopen(fd, "wb") as out, open(self.path, "rb") as src:
                for key, (offset, length) in sorted(
                        live_ranges.items(), key=lambda kv: kv[1][0]):
                    src.seek(offset)
                    payload = src.read(length)
                    # Verify while relocating: compaction must not launder
                    # rotted bytes into a fresh file with a fresh crc.
                    rec = self._crcs.get(offset)
                    if not self.verify:
                        new_crcs[pos] = rec if rec is not None \
                            and rec[0] == length \
                            else (length, CK.crc32c(payload))
                    elif rec is not None and rec[0] == length:
                        CK.verify(payload, rec[1],
                                  f"spill range [{offset}:"
                                  f"{offset + length}) of {self.path} "
                                  "during compaction")
                        new_crcs[pos] = (length, rec[1])
                    else:
                        new_crcs[pos] = (length, CK.crc32c(payload))
                    out.write(payload)
                    new_ranges[key] = (pos, length)
                    pos += length
            os.replace(tmp, self.path)
            self._offset = pos
            self._freed = 0
            self._crcs = new_crcs
            return new_ranges


def _ipc_serialize(rb: pa.RecordBatch) -> bytes:
    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, rb.schema) as w:
        w.write_batch(rb)
    return sink.getvalue()


def _ipc_deserialize(payload: bytes) -> pa.RecordBatch:
    with pa.ipc.open_stream(io.BytesIO(payload)) as r:
        return next(iter(r))


class BufferCatalog:
    """id -> tiered buffer, with budget-driven synchronous spill.

    The three tiers live inside one catalog (the reference splits catalog and
    three store objects; the chain wiring is identical —
    GpuShuffleEnv.initStorage, GpuShuffleEnv.scala:52-69)."""

    def __init__(self, device_budget_bytes,
                 host_budget_bytes: int,
                 spill_dir: Optional[str] = None):
        # int, or a 0-arg callable resolved on first budget check (lets the
        # device manager defer accelerator-backend init until device buffers
        # actually exist — see DeviceManager).
        self._device_budget = device_budget_bytes
        self.host_budget = host_budget_bytes
        self._entries: Dict[int, _Entry] = {}
        self._device_heap = []  # (priority, buffer_id)
        self._host_heap = []
        self.device_bytes = 0
        self.host_bytes = 0
        self._next_id = 0
        self._lock = lockdep.rlock("BufferCatalog._lock")
        self._spill_dir = spill_dir
        self._spill_file: Optional[SpillFile] = None  # lazy: first disk spill
        self._pinned: set = set()
        self.metrics = {"spilled_to_host": 0, "spilled_to_disk": 0,
                        "reloaded_from_host": 0, "reloaded_from_disk": 0,
                        # byte counters feed the query profile's spillBytes
                        # (metrics/profile.py takes per-query deltas)
                        "spill_bytes_to_host": 0, "spill_bytes_to_disk": 0,
                        # live size of the shared disk spill file (the
                        # diskSpillFileBytes profile metric) + compactions
                        "disk_spill_file_bytes": 0,
                        "disk_spill_file_compactions": 0}

    @property
    def device_budget(self) -> int:
        if callable(self._device_budget):
            self._device_budget = self._device_budget()
        return self._device_budget

    @device_budget.setter
    def device_budget(self, value: int):
        self._device_budget = value

    def _disk(self) -> SpillFile:
        if self._spill_file is None:
            self._spill_file = SpillFile(self._spill_dir)
        return self._spill_file

    # -- registration -------------------------------------------------------
    def register_batch(self, batch: ColumnarBatch,
                       priority: int = ACTIVE_BATCHING_PRIORITY) -> int:
        """Track a device batch as spillable; may synchronously spill lower-
        priority buffers to stay within the device budget."""
        size = batch.device_size_bytes
        meta = TableMeta(batch.schema, batch.capacity, size)
        with self._lock:
            bid = self._next_id
            self._next_id += 1
            entry = _Entry(bid, priority, meta, StorageTier.DEVICE,
                           device_batch=batch)
            self._entries[bid] = entry
            self.device_bytes += size
            heapq.heappush(self._device_heap, (priority, bid))
            self._ensure_device_budget()
            return bid

    # -- access -------------------------------------------------------------
    def acquire_batch(self, buffer_id: int) -> ColumnarBatch:
        """Return the batch on device, unspilling through the tiers if needed
        (RapidsBufferStore.getDeviceMemoryBuffer's tier climb)."""
        with self._lock:
            entry = self._entries[buffer_id]
            assert not entry.freed, f"buffer {buffer_id} already freed"
            if entry.tier == StorageTier.DEVICE:
                return entry.device_batch
            if entry.tier == StorageTier.DISK:
                disk = self._disk()
                payload = disk.read(*entry.disk_range)
                entry.host_batch = _ipc_deserialize(payload)
                disk.free_range(*entry.disk_range)
                entry.disk_range = None
                entry.tier = StorageTier.HOST
                self.host_bytes += entry.meta.size_bytes
                heapq.heappush(self._host_heap, (entry.priority, buffer_id))
                self.metrics["reloaded_from_disk"] += 1
                self._maybe_compact_disk()
            # HOST -> DEVICE
            with trace_range("spill.reload_to_device"):
                batch = ColumnarBatch.from_arrow(entry.host_batch,
                                                 capacity=entry.meta.capacity)
            self._remove_host(entry)
            entry.device_batch = batch
            entry.tier = StorageTier.DEVICE
            self.device_bytes += entry.meta.size_bytes
            heapq.heappush(self._device_heap, (entry.priority, buffer_id))
            self.metrics["reloaded_from_host"] += 1
            self._ensure_device_budget(exclude=buffer_id)
            return batch

    def tier_of(self, buffer_id: int) -> str:
        with self._lock:
            return self._entries[buffer_id].tier

    def free(self, buffer_id: int):
        with self._lock:
            entry = self._entries.pop(buffer_id, None)
            self._pinned.discard(buffer_id)
            if entry is None or entry.freed:
                return
            entry.freed = True
            if entry.tier == StorageTier.DEVICE:
                self.device_bytes -= entry.meta.size_bytes
                entry.device_batch = None
            elif entry.tier == StorageTier.HOST:
                self.host_bytes -= entry.meta.size_bytes
                entry.host_batch = None
            elif entry.tier == StorageTier.DISK \
                    and entry.disk_range is not None \
                    and self._spill_file is not None:
                self._spill_file.free_range(*entry.disk_range)
                entry.disk_range = None
                self._maybe_compact_disk()

    def pin(self, buffer_id: int):
        """Exclude a buffer from spilling while an operator actively uses it
        (the reference's on-deck priority bump)."""
        with self._lock:
            self._pinned.add(buffer_id)

    def unpin(self, buffer_id: int):
        with self._lock:
            self._pinned.discard(buffer_id)

    def leak_report(self) -> list:
        """Buffers registered but never freed — the cudf ref-count
        leak-warning role (SURVEY.md §5 race/leak tracking; reference
        `noWarnLeakExpected`). Returns [(buffer_id, tier, bytes)]."""
        with self._lock:
            return [(bid, e.tier, e.meta.size_bytes)
                    for bid, e in self._entries.items() if not e.freed]

    def close(self):
        with self._lock:
            leaks = self.leak_report()
            if leaks:
                import logging
                total = sum(b for _, _, b in leaks)
                logging.getLogger(__name__).warning(
                    "spill catalog closed with %d leaked buffer(s), "
                    "%d bytes: %s", len(leaks), total,
                    [(bid, t) for bid, t, _ in leaks[:8]])
            self._entries.clear()
            self._device_heap.clear()
            self._host_heap.clear()
            self._pinned.clear()
            if self._spill_file is not None:
                self._spill_file.close()
                self._spill_file = None

    # -- spilling -----------------------------------------------------------
    def synchronous_spill(self, target_device_bytes: int):
        """Spill device buffers (lowest priority first) until usage <= target
        (RapidsBufferStore.synchronousSpill:137-149)."""
        with self._lock:
            while self.device_bytes > target_device_bytes:
                entry = self._pop_spillable(self._device_heap,
                                            StorageTier.DEVICE)
                if entry is None:
                    break  # nothing spillable
                self._spill_device_entry(entry)

    def _ensure_device_budget(self, exclude: Optional[int] = None):
        # The upload memo's device bytes count against the budget too;
        # as a pure cache it is the cheapest thing to evict (LRU) before
        # any real buffer spills.
        from ..data import upload_cache
        over = self.device_bytes + upload_cache.cache_bytes() \
            - self.device_budget
        if over > 0:
            upload_cache.shrink_by(over)
        while self.device_bytes > self.device_budget:
            entry = self._pop_spillable(self._device_heap, StorageTier.DEVICE,
                                        exclude=exclude)
            if entry is None:
                break
            self._spill_device_entry(entry)
        while self.host_bytes > self.host_budget:
            entry = self._pop_spillable(self._host_heap, StorageTier.HOST)
            if entry is None:
                break
            self._spill_host_entry(entry)

    def spill_below(self, priority_ceiling: int) -> int:
        """Synchronously spill every unpinned device buffer whose priority
        is below ``priority_ceiling`` to the host tier (cascading to disk
        via the host budget) — the OOM-retry drain (memory/retry.py):
        everything except on-deck buffers leaves the device before the
        attempt re-runs. Returns device bytes moved."""
        moved = 0
        with self._lock:
            while True:
                entry = self._pop_spillable(self._device_heap,
                                            StorageTier.DEVICE,
                                            max_priority=priority_ceiling)
                if entry is None:
                    break
                moved += entry.meta.size_bytes
                self._spill_device_entry(entry)
        return moved

    def _pop_spillable(self, heap, tier: str,
                       exclude: Optional[int] = None,
                       max_priority: Optional[int] = None
                       ) -> Optional[_Entry]:
        """Pop the lowest-priority live entry still on ``tier``; stale heap
        records (moved/freed buffers) are discarded lazily. With
        ``max_priority``, entries at or above it stay put (the heap pops
        lowest-first, so the scan stops at the first such entry)."""
        skipped = []
        found = None
        while heap:
            priority, bid = heapq.heappop(heap)
            entry = self._entries.get(bid)
            if entry is None or entry.freed or entry.tier != tier:
                continue  # stale record
            if max_priority is not None and priority >= max_priority:
                skipped.append((priority, bid))
                break
            if bid == exclude or bid in self._pinned:
                skipped.append((priority, bid))
                continue
            found = entry
            break
        for item in skipped:
            heapq.heappush(heap, item)
        return found

    def _spill_device_entry(self, entry: _Entry):
        with trace_range("spill.device_to_host"):
            entry.host_batch = entry.device_batch.to_arrow()
        entry.device_batch = None
        entry.tier = StorageTier.HOST
        self.device_bytes -= entry.meta.size_bytes
        self.host_bytes += entry.meta.size_bytes
        heapq.heappush(self._host_heap, (entry.priority, entry.buffer_id))
        self.metrics["spilled_to_host"] += 1
        self.metrics["spill_bytes_to_host"] += entry.meta.size_bytes
        while self.host_bytes > self.host_budget:
            victim = self._pop_spillable(self._host_heap, StorageTier.HOST)
            if victim is None:
                break
            self._spill_host_entry(victim)

    def _spill_host_entry(self, entry: _Entry):
        with trace_range("spill.host_to_disk"):
            payload = _ipc_serialize(entry.host_batch)
            entry.disk_range = self._disk().append(payload)
        entry.host_batch = None
        entry.tier = StorageTier.DISK
        self.host_bytes -= entry.meta.size_bytes
        self.metrics["spilled_to_disk"] += 1
        self.metrics["spill_bytes_to_disk"] += len(payload)
        self.metrics["disk_spill_file_bytes"] = self._disk().live_bytes

    def _maybe_compact_disk(self):
        """Compact the shared spill file once DISK_COMPACT_FRACTION of it
        is dead (caller holds the catalog lock): live disk entries rewrite
        contiguously and their ranges update in place, so long-lived
        catalogs stop leaking freed disk space until close."""
        f = self._spill_file
        if f is None:
            return
        if f.freed_bytes == 0 or f.freed_fraction() < DISK_COMPACT_FRACTION:
            self.metrics["disk_spill_file_bytes"] = f.live_bytes
            return
        live = {bid: e.disk_range for bid, e in self._entries.items()
                if e.tier == StorageTier.DISK and not e.freed
                and e.disk_range is not None}
        with trace_range("spill.compact_disk"):
            new_ranges = f.compact(live)
        for bid, rng in new_ranges.items():
            self._entries[bid].disk_range = rng
        self.metrics["disk_spill_file_compactions"] += 1
        self.metrics["disk_spill_file_bytes"] = f.live_bytes

    def _remove_host(self, entry: _Entry):
        entry.host_batch = None
        self.host_bytes -= entry.meta.size_bytes
