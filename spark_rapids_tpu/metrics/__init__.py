"""Query-profile layer: typed leveled metrics, device-time attribution,
structured trace events, and metric-annotated EXPLAIN.

Three pieces (docs/monitoring.md):

* :mod:`.registry` — ``TpuMetric``/``MetricsRegistry``: the GpuMetric
  analog; NANO_TIMING/SUM/PEAK/AVERAGE kinds, ESSENTIAL/MODERATE/DEBUG
  levels gated by ``spark.rapids.tpu.metrics.level``, and the standard
  taxonomy every layer reports into.
* :mod:`.profile` — ``QueryProfile``/``QueryProfiler``: the per-query
  operator-tree snapshot with engine counters folded in, rendered by
  ``df.explain(metrics=True)`` and diffed by
  ``tools/profile_bench.py --compare``.
* :mod:`.eventlog` — crash-safe JSON-lines event log
  (``spark.rapids.tpu.metrics.eventLog.dir``), one line per query, with
  size-capped rotation for long-lived serving processes.
* :mod:`.trace` — per-query distributed tracing (ISSUE 13,
  ``spark.rapids.tpu.trace.enabled``): the span-tree engine, Chrome
  trace-event export, wire-propagated trace context, and the
  flight-recorder ring. Not re-exported here (call sites import the
  module directly — its disabled path is one None check);
  ``tools/trace_report.py`` is the analyzer.
"""

from .eventlog import EventLog
from .profile import (QueryProfile, QueryProfiler, compare_profiles,
                      dump_profiles, load_profiles)
from .registry import (DEBUG, ESSENTIAL, MODERATE, NONE, TAXONOMY,
                       MetricKind, MetricsRegistry, MetricSpec, TpuMetric,
                       level_name, parse_level, taxonomy_markdown)

__all__ = [
    "DEBUG", "ESSENTIAL", "MODERATE", "NONE", "TAXONOMY", "MetricKind",
    "MetricsRegistry", "MetricSpec", "TpuMetric", "level_name",
    "parse_level", "taxonomy_markdown", "QueryProfile", "QueryProfiler",
    "compare_profiles", "dump_profiles", "load_profiles", "EventLog",
]
