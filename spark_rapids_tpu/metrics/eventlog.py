"""Structured query-event log — one JSON line per executed query.

The Spark analog is the SQL event log the history server replays; here a
:class:`QueryProfile` (operator tree + metrics + engine counters) appends as
one line to ``query_profiles.jsonl`` under
``spark.rapids.tpu.metrics.eventLog.dir``. Append is crash-safe in the same
spirit as the compile manifest (compile/persist.py): each record is a single
``write()`` of one full line, failures never fail the query, and the reader
skips torn/corrupt lines (a crash mid-append loses at most the last line).
"""

from __future__ import annotations

import json
import os
from typing import List, Optional

from ..utils import lockdep

FILENAME = "query_profiles.jsonl"


class EventLog:
    """Append-only JSON-lines writer for query profiles.

    ``max_bytes`` (``spark.rapids.tpu.metrics.eventLog.maxBytes``) caps
    growth in a long-lived serving process: an append that would push
    the file past the cap first rotates it to ``<name>.1`` via
    ``os.replace`` — atomic, so a crash mid-rotation leaves either the
    old or the new generation intact, never a torn hybrid — and keeps
    exactly one prior generation. Torn-line tolerance is unchanged: both
    generations are read with the same skip-corrupt-lines reader."""

    def __init__(self, directory: str, max_bytes: int = 0):
        self.dir = directory
        self.path = os.path.join(directory, FILENAME)
        self.max_bytes = int(max_bytes)
        self._lock = lockdep.lock("EventLog._lock", io_ok=True)

    def _rotate_if_needed(self, incoming: int) -> None:
        """Rotate under the lock when the NEXT append would cross the
        cap (a single record larger than the cap still appends — the
        cap bounds the file, not the record)."""
        if self.max_bytes <= 0:
            return
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return
        if size > 0 and size + incoming > self.max_bytes:
            os.replace(self.path, self.path + ".1")

    def append(self, profile) -> bool:
        """Append one profile (QueryProfile or plain dict); returns False
        (and logs nothing) on any IO failure — the event log is an
        observability aid, never a correctness dependency."""
        record = profile if isinstance(profile, dict) else profile.to_dict()
        try:
            payload = (json.dumps(record, separators=(",", ":"),
                                  default=_jsonable) + "\n").encode("utf-8")
        except (TypeError, ValueError):
            return False
        with self._lock:
            try:
                os.makedirs(self.dir, exist_ok=True)
                self._rotate_if_needed(len(payload))
                # A previous writer may have crashed mid-append, leaving a
                # torn line with no trailing newline; start this record on
                # a fresh line so the torn one stays isolated (and skipped
                # by read()) instead of corrupting ours too.
                needs_nl = False
                try:
                    if os.path.getsize(self.path) > 0:
                        with open(self.path, "rb") as r:
                            r.seek(-1, os.SEEK_END)
                            needs_nl = r.read(1) != b"\n"
                except OSError:
                    pass
                with open(self.path, "ab") as f:
                    f.write((b"\n" if needs_nl else b"")
                            + payload)  # one write per record
                    f.flush()
            except OSError:
                return False
        return True


def _jsonable(v):
    """numpy scalars and other numerics that reach a profile dict."""
    if hasattr(v, "item"):
        return v.item()
    return str(v)


def read(path: str) -> List[dict]:
    """Load every intact profile line; torn or corrupt lines are skipped
    (crash-safety contract: a partial trailing line must not poison the
    log)."""
    out: List[dict] = []
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    out.append(rec)
    except OSError:
        return []
    return out


def read_all(directory: str) -> List[dict]:
    """Every intact profile across the rotated generation (``.1``, older)
    and the current file, in append order."""
    path = os.path.join(directory, FILENAME)
    return read(path + ".1") + read(path)


def log_path(directory: Optional[str]) -> Optional[str]:
    return os.path.join(directory, FILENAME) if directory else None
