"""Per-query profiles: the operator tree annotated with its metrics.

A :class:`QueryProfile` snapshots, at query end:

* the physical operator tree (node names + describe strings) with each
  node's metrics from the query's :class:`~.registry.MetricsRegistry`;
* "extra" metric nodes that are not plan operators (WholeStageFusion,
  TpuSemaphore) — work the plan tree cannot attribute;
* engine-level counters folded in from the other subsystems: spill-catalog
  byte deltas (memory/spill.py), semaphore wait, HBM watermarks
  (memory/device_manager.py), and the compile-once layer's counters
  (utils/kernel_cache.py, compile/executables.py, compile/warmup.py) — the
  PR-2 counters now reporting through the same profile instead of their own
  side channels.

Profiles serialize to one JSON line in the event log
(:mod:`.eventlog`), render as a metric-annotated EXPLAIN tree
(``df.explain(metrics=True)`` / ``TpuSession.last_query_profile()``), and
diff against an earlier run (:func:`compare_profiles`) — the regression
ratchet ``tools/profile_bench.py --compare`` runs on.

Metrics are keyed by node_name(), so two instances of the same exec type in
one plan share accumulators; the render marks repeated names with ``*``.
"""

from __future__ import annotations

import dataclasses
import hashlib
import time
from typing import Dict, List, Optional

from .registry import NONE, MetricsRegistry, level_name, parse_level

#: Profile schema version (bump on incompatible event-log layout changes).
VERSION = 1

#: Durability counters (ISSUE 7) summed across nodes into the engine
#: section — ALSO the list TpuSession harvests from attempts discarded by
#: the join-sizing re-run ladder (one list, or a new counter silently
#: stops surviving dispatch retries).
DURABILITY_COUNTERS = ("checksumFailures", "shuffleBlocksRefetched",
                       "mapTasksRecomputed", "deadlineCancels",
                       "peersBlacklisted", "hedgedFetches", "hedgeWins",
                       "replicaReads", "meshFailovers")

#: The subset of DURABILITY_COUNTERS the profile reads from process-wide
#: stats deltas instead of the per-query registry (they span discarded
#: dispatch attempts natively, so the session must NOT also carry them).
PROCESS_DELTA_COUNTERS = ("checksumFailures",)


def plan_profile_hash(plan_sig: tuple) -> str:
    """Short stable hash of a structural plan signature
    (utils.kernel_cache.plan_signature output) — lets explain(metrics=True)
    check that the last profile belongs to THIS query shape."""
    return hashlib.sha256(repr(plan_sig).encode()).hexdigest()[:16]


@dataclasses.dataclass
class QueryProfile:
    """One executed query's observability record."""

    query_id: int
    plan_hash: str
    wall_ns: int
    level: str
    #: nested {"name", "describe", "metrics": {..}, "children": [..]}
    tree: dict
    #: metric nodes with no plan operator: {node: {name: value}}
    extras: Dict[str, dict]
    #: engine counters: spill/semaphore/hbm/compile sections
    engine: dict
    timestamp: str = ""
    version: int = VERSION
    #: the executing session's ``spark.rapids.tpu.tenantId`` (ISSUE 12):
    #: stamped into the header AND therefore into every event-log record,
    #: so per-tenant attribution (tools/serve_bench.py) groups profiles
    #: directly instead of joining against a side channel.
    tenant: str = ""

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "QueryProfile":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})

    # -- rendering ----------------------------------------------------------
    def render(self) -> str:
        """The metric-annotated EXPLAIN tree."""
        counts: Dict[str, int] = {}
        _count_names(self.tree, counts)
        tenant = f", tenant={self.tenant}" if self.tenant else ""
        lines = [f"== Query Profile #{self.query_id} "
                 f"(level={self.level}, wall={_fmt_ns(self.wall_ns)}"
                 f"{tenant}) =="]
        _render_node(self.tree, 0, counts, lines)
        shared = sorted(n for n, c in counts.items() if c > 1)
        if shared:
            lines.append("(* metrics are keyed by node name and shared by "
                         f"repeated operators: {', '.join(shared)})")
        for node in sorted(self.extras):
            lines.append(f"+ {node}  {_fmt_metrics(self.extras[node])}")
        eng = {k: v for k, v in self.engine.items() if not isinstance(v, dict)}
        if eng:
            lines.append(f"+ engine  {_fmt_metrics(eng)}")
        comp = self.engine.get("compile")
        if comp:
            lines.append(f"+ compile  {_fmt_metrics(comp)}")
        dur = self.engine.get("durability")
        if dur:
            lines.append(f"+ durability  {_fmt_metrics(dur)}")
        mlsec = self.engine.get("ml")
        if mlsec and any(v for v in mlsec.values()
                         if isinstance(v, (int, float))):
            lines.append(f"+ ml  {_fmt_metrics(mlsec)}")
        pal = self.engine.get("pallas")
        if pal and (pal.get("enabled") or pal.get("kernels")):
            kparts = [f"{k}={m.get('staged', 0)}"
                      for k, m in sorted(pal.get("kernels", {}).items())]
            lines.append("+ pallas  [enabled="
                         f"{pal.get('enabled')}"
                         + (", " + ", ".join(kparts) if kparts else "")
                         + "]")
        return "\n".join(lines) + "\n"


def _count_names(node: dict, counts: Dict[str, int]) -> None:
    counts[node["name"]] = counts.get(node["name"], 0) + 1
    for c in node["children"]:
        _count_names(c, counts)


def _render_node(node: dict, indent: int, counts, lines: List[str]) -> None:
    star = "*" if counts.get(node["name"], 0) > 1 and node["metrics"] else ""
    tail = f"  {_fmt_metrics(node['metrics'])}{star}" if node["metrics"] \
        else ""
    lines.append("  " * indent + node["describe"] + tail)
    for c in node["children"]:
        _render_node(c, indent + 1, counts, lines)


def _fmt_ns(v) -> str:
    return f"{v / 1e6:.1f}ms"


def _fmt_metrics(metrics: dict) -> str:
    parts = []
    for name in sorted(metrics):
        v = metrics[name]
        if isinstance(v, dict):
            continue
        if (name.endswith("Ns") or name.endswith("Time")) \
                and isinstance(v, (int, float)):
            parts.append(f"{name}={_fmt_ns(v)}")
        elif isinstance(v, float):
            parts.append(f"{name}={v:.2f}")
        else:
            parts.append(f"{name}={v}")
    return "[" + ", ".join(parts) + "]"


# ---------------------------------------------------------------------------
# Collection
# ---------------------------------------------------------------------------


class QueryProfiler:
    """Brackets one query execution: captures engine-counter baselines at
    start, snapshots the registry + deltas at finish. Created only when the
    metrics level is above NONE — at NONE nothing is measured at all."""

    def __init__(self, session):
        self._session = session
        self._t0 = time.perf_counter_ns()
        from ..compile import executables as _exe
        from ..compile import warmup as _warmup
        from ..ops.kernels import pallas as _pallas
        from ..utils import checksum as _ck
        from ..utils import kernel_cache as _kc
        self._kc0 = _kc.cache_stats()
        self._exe0 = _exe.stats()
        self._warm0 = _warmup.stats()
        self._ck0 = _ck.stats()
        self._pallas0 = _pallas.stats()
        self._pallas_keys0 = _pallas.snapshot_program_keys()
        dm = session.device_manager
        self._spill0 = dict(dm.catalog.metrics)
        self._sem0 = dm.semaphore.wait_ns

    @classmethod
    def maybe(cls, session) -> Optional["QueryProfiler"]:
        if parse_level(session.conf.metrics_level) == NONE:
            return None
        return cls(session)

    def finish(self, physical, ctx, plan_sig: tuple,
               query_id: int) -> QueryProfile:
        import datetime

        from ..compile import executables as _exe
        from ..compile import warmup as _warmup
        from ..ops.kernels import pallas as _pallas
        from ..utils import checksum as _ck
        from ..utils import kernel_cache as _kc
        wall_ns = time.perf_counter_ns() - self._t0
        registry: MetricsRegistry = ctx.registry
        tree = _tree_of(physical, registry)
        tree_names: set = set()
        _collect_names(tree, tree_names)
        extras = {node: registry.node_metrics(node)
                  for node in registry.node_names()
                  if node not in tree_names}

        dm = self._session.device_manager
        spill = dm.catalog.metrics
        kc = _kc.cache_stats()
        exe = _exe.stats()
        warm = _warmup.stats()
        ck = _ck.stats()
        engine = {
            "semaphoreWaitNs": dm.semaphore.wait_ns - self._sem0,
            "spillBytes":
                _delta(spill, self._spill0, "spill_bytes_to_host")
                + _delta(spill, self._spill0, "spill_bytes_to_disk"),
            "spillBytesToHost":
                _delta(spill, self._spill0, "spill_bytes_to_host"),
            "spillBytesToDisk":
                _delta(spill, self._spill0, "spill_bytes_to_disk"),
            # Live size of the shared disk spill file (compaction keeps it
            # from leaking freed ranges — memory/spill.py).
            "diskSpillFileBytes": int(spill.get("disk_spill_file_bytes", 0)),
            # Async spill engine (ISSUE 11, docs/monitoring.md):
            # bytes-per-second through the off-lock spill-IO lane this
            # query (copies + restores; 0 when nothing spilled), the
            # process watermark of queued-not-finished lane units, and ns
            # this query's threads spent WAITING for the catalog lock —
            # the convoy detector that the old synchronous design kept
            # pegged during any spill.
            "spillThroughputBytesPerSec": _rate_per_sec(
                _delta(spill, self._spill0, "spill_io_bytes"),
                _delta(spill, self._spill0, "spill_io_ns")),
            "spillQueueDepth": int(spill.get("spill_queue_peak", 0)),
            "spillLockWaitNs": _delta(spill, self._spill0,
                                      "spill_lock_wait_ns"),
            "deviceStoreBytes": dm.catalog.device_bytes,
            **dm.hbm_watermarks(),
            "compile": {
                "compileNs": _delta(kc, self._kc0, "build_ns"),
                "kernelCompiles": _delta(kc, self._kc0, "misses"),
                "kernelHits": _delta(kc, self._kc0, "hits"),
                "fusedPrograms": exe.get("programs", 0),
                "aotExecutables": exe.get("aot_executables", 0),
                "aotHits": _delta(exe, self._exe0, "aot_hits"),
                "jitCalls": _delta(exe, self._exe0, "jit_calls"),
                # Polymorphic-tier counters (ISSUE 6): fused executables
                # actually compiled this query vs dispatches an existing
                # executable served (the cross-rung reuse the tier
                # padding buys), and the compile seconds paid.
                "fusedCompiles": _delta(exe, self._exe0, "jit_compiles"),
                "fusedCompileSeconds": round(
                    float(exe.get("compile_seconds", 0.0))
                    - float(self._exe0.get("compile_seconds", 0.0)), 3),
                "executablesReused":
                    _delta(exe, self._exe0, "aot_hits")
                    + _delta(exe, self._exe0, "jit_calls")
                    - _delta(exe, self._exe0, "jit_compiles"),
                "warmupCompiled": _delta(warm, self._warm0, "compiled"),
                "warmupSkippedCovered": _delta(warm, self._warm0,
                                               "skipped_covered"),
            },
            # Pallas kernel attribution (ISSUE 8, docs/monitoring.md):
            # per-kernel stagings (each staging is one launch per dispatch
            # of the program it was traced into), newly-compiled pallas
            # program signatures, and the fallback reasons where a kernel
            # was requested but the jnp oracle ran. Empty when the gate is
            # off — the section itself proves which kernels served the
            # query.
            "pallas": _pallas_section(self._session, self._pallas0,
                                      _pallas.stats(),
                                      registry.device_timing,
                                      self._pallas_keys0),
            # ML scenario attribution (ISSUE 14, docs/monitoring.md):
            # rows exported to trainers, rows scored by ModelScore
            # operators (one deferred device read of the traced per-batch
            # counts — the hot path never synced), trainer wall seconds,
            # and registered-model HBM bytes — so serving/event-log
            # attribution covers ML work like every other subsystem.
            "ml": _ml_section(ctx),
            # Distributed-durability counters (ISSUE 7,
            # docs/fault-tolerance.md): a clean run reads all zeros; after
            # an injected or real fault the non-zero counters PROVE the
            # recovery machinery ran (bench.py surfaces them as the
            # per-query `faults` section).
            "durability": {
                # checksumFailures comes from the process-wide stats
                # delta (it spans discarded attempts natively); the rest
                # sum the per-query registry.
                "checksumVerified": _delta(ck, self._ck0, "verified"),
                **{name: (_delta(ck, self._ck0, "failures")
                          if name == "checksumFailures"
                          else _registry_total(registry, name))
                   for name in DURABILITY_COUNTERS},
            },
        }
        from ..config import TENANT_ID
        try:
            tenant = str(self._session.conf.get(TENANT_ID) or "")
        except Exception:  # noqa: BLE001 - attribution is an aid
            tenant = ""
        return QueryProfile(
            query_id=query_id,
            plan_hash=plan_profile_hash(plan_sig),
            wall_ns=wall_ns,
            level=level_name(registry.level),
            tree=tree,
            extras=extras,
            engine=engine,
            timestamp=datetime.datetime.now(datetime.timezone.utc)
            .isoformat(timespec="seconds"),
            tenant=tenant,
        )


def _delta(now: dict, base: dict, key: str) -> int:
    return int(now.get(key, 0)) - int(base.get(key, 0))


def _rate_per_sec(amount: int, ns: int) -> int:
    """amount / (ns as seconds), 0 when nothing was measured."""
    return int(amount * 1e9 / ns) if ns > 0 else 0


def _pallas_section(session, base: dict, now: dict,
                    device_timing: bool = False,
                    base_keys: dict = None) -> dict:
    """The ``engine.pallas`` section: gate state + per-kernel deltas of
    staged launches / compiled programs / fallback reasons over this
    query (process-wide stats deltas, like checksumFailures — Pallas
    wrappers run at trace time, below the per-query registry).

    Under ``spark.rapids.tpu.metrics.deviceTiming`` each kernel that
    staged this query also gets ``deviceTimeNs``: a fenced zero-input
    replay of its staged program signatures (a traced pallas_call
    inlines into the fused XLA program, so its share of the fused
    dispatch cannot be split out; the replay measures the same program
    in isolation — same opt-in, fence-free default as the fused
    deviceTime)."""
    from ..ops.kernels import pallas as PAL
    enabled = PAL.from_conf(session.conf).enabled
    probe = PAL.probe_device_times(base_keys or {}) \
        if device_timing and enabled else {}
    kernels = {}
    for name in sorted(now):
        cur, old = now[name], base.get(name, {})
        staged = cur["staged"] - old.get("staged", 0)
        programs = cur["programs"] - old.get("programs", 0)
        fb0 = old.get("fallbacks", {})
        fallbacks = {r: n - fb0.get(r, 0)
                     for r, n in cur["fallbacks"].items()
                     if n - fb0.get(r, 0)}
        if staged or programs or fallbacks:
            kernels[name] = {"staged": staged, "programsCompiled": programs,
                             **({"fallbacks": fallbacks} if fallbacks
                                else {}),
                             **({"deviceTimeNs": probe[name]}
                                if name in probe else {})}
    return {"enabled": enabled, "kernels": kernels}


def _ml_section(ctx) -> dict:
    """The ``engine.ml`` section. ``scoreRows`` is PER QUERY (this
    query's ModelScore output, from the context's deferred traced
    counts — one device read here, zero syncs on the hot path). The
    export/train/model counters are process-CUMULATIVE: that work runs
    BETWEEN queries (the ETL→train handoff), so a per-query delta would
    always read zero — consecutive event-log records diff to attribute
    it, the same way a metrics scraper reads any monotonic counter."""
    from ..ml import registry as _mlreg
    now = _mlreg.stats()
    score_rows = 0
    vals = getattr(ctx, "ml_score_rows", None)
    if vals:
        try:
            import jax
            score_rows = int(sum(int(v) for v in jax.device_get(list(vals))))
        except Exception:  # noqa: BLE001 - attribution is an aid
            score_rows = 0
    return {
        "exportRows": int(now.get("export_rows", 0)),
        "scoreRows": score_rows,
        "trainSeconds": round(float(now.get("train_seconds", 0.0)), 3),
        "modelBytes": int(now.get("model_bytes", 0)),
        "modelsRegistered": int(now.get("models_registered", 0)),
    }


def _registry_total(registry: MetricsRegistry, name: str) -> int:
    """Sum one metric name across every node of a per-query registry
    (the durability counters are recorded under whichever operator hit
    the fault; the engine section wants the query total)."""
    total = 0
    for node in registry.node_names():
        v = registry.node_metrics(node).get(name)
        if isinstance(v, (int, float)):
            total += int(v)
    return total


def _tree_of(plan, registry: MetricsRegistry) -> dict:
    return {
        "name": plan.node_name(),
        "describe": plan.describe(),
        "metrics": registry.node_metrics(plan.node_name()),
        "children": [_tree_of(c, registry) for c in plan.children],
    }


def _collect_names(node: dict, out: set) -> None:
    out.add(node["name"])
    for c in node["children"]:
        _collect_names(c, out)


# ---------------------------------------------------------------------------
# Comparison (tools/profile_bench.py --compare)
# ---------------------------------------------------------------------------


def _flatten(node: dict, _path: str, out: Dict[str, dict]) -> None:
    # Keyed by node NAME, not tree position: metrics are shared by
    # node_name() across repeated operators (registry.py), so positional
    # keys would report the same shared accumulator once per duplicate and
    # inflate the regression count.
    out[node["name"]] = node["metrics"]
    for c in node["children"]:
        _flatten(c, _path, out)


def compare_profiles(old: dict, new: dict, threshold: float = 0.20,
                     min_ns: int = 1_000_000) -> List[dict]:
    """Per-operator regression diff of two profile dicts.

    Flags timing metrics (``*Time``/``*Ns``) that grew by more than
    ``threshold`` (default 20%) AND by more than ``min_ns`` (noise floor,
    default 1ms). Returns [{path, metric, old, new, ratio}] sorted by
    severity."""
    o_ops: Dict[str, dict] = {}
    n_ops: Dict[str, dict] = {}
    _flatten(old["tree"], "", o_ops)
    _flatten(new["tree"], "", n_ops)
    o_ops["<extras>"] = {k: v for m in old.get("extras", {}).values()
                         for k, v in m.items()}
    n_ops["<extras>"] = {k: v for m in new.get("extras", {}).values()
                         for k, v in m.items()}
    out: List[dict] = []
    for path, n_metrics in n_ops.items():
        o_metrics = o_ops.get(path)
        if o_metrics is None:
            continue
        for name, nv in n_metrics.items():
            if not (name.endswith("Time") or name.endswith("Ns")):
                continue
            ov = o_metrics.get(name)
            if not isinstance(ov, (int, float)) \
                    or not isinstance(nv, (int, float)) or ov <= 0:
                continue
            if nv - ov > min_ns and nv > ov * (1.0 + threshold):
                out.append({"path": path, "metric": name,
                            "old": ov, "new": nv,
                            "ratio": round(nv / ov, 3)})
    return sorted(out, key=lambda r: -r["ratio"])


def dump_profiles(path: str, profiles: Dict[str, QueryProfile]) -> None:
    """Write a {query name: profile dict} bundle (bench.py /
    tools/profile_bench.py emit these next to BENCH_*.json)."""
    import json
    data = {name: (p.to_dict() if isinstance(p, QueryProfile) else p)
            for name, p in profiles.items() if p is not None}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1, default=str)
        f.write("\n")


def load_profiles(path: str) -> Dict[str, dict]:
    import json
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if isinstance(data, dict) and "tree" in data:
        return {"query": data}  # a single bare profile
    return data
