"""Typed, leveled operator metrics — the ``GpuMetric`` analog.

The reference attaches leveled SQL metrics to every operator
(``GpuMetric.scala``: ESSENTIAL/MODERATE/DEBUG levels gated by
``spark.rapids.sql.metrics.level``; NANO_TIMING/SUM/PEAK/AVERAGE kinds) and
couples timing metrics with profiler ranges (``NvtxWithMetrics.scala`` —
SURVEY.md §5). This module is the TPU port: a per-query
:class:`MetricsRegistry` holding :class:`TpuMetric` accumulators keyed by
(node name, metric name), with a standard taxonomy (:data:`TAXONOMY`) shared
by every layer of the engine — exec, shuffle, io, memory, compile — so one
``QueryProfile`` (:mod:`.profile`) can read them all coherently.

Level gating happens at record time: a metric above the configured level
(``spark.rapids.tpu.metrics.level``) is dropped without allocation, and at
level NONE the registry is inert — ``ExecContext.metric`` becomes a no-op
and no timing fences are ever inserted (asserted by tests/test_metrics.py).

Timing metrics are NANO_TIMING kind, implemented on
:class:`..utils.tracing.NanoTimer` so every timed span doubles as an
XProf/TraceAnnotation range (the NvtxWithMetrics coupling).
"""

from __future__ import annotations

from typing import Dict, Optional

from ..utils import lockdep

# ---------------------------------------------------------------------------
# Levels (GpuMetric.scala: ESSENTIAL/MODERATE/DEBUG) and kinds.
# ---------------------------------------------------------------------------

NONE = 0
ESSENTIAL = 1
MODERATE = 2
DEBUG = 3

_LEVEL_NAMES = {"NONE": NONE, "ESSENTIAL": ESSENTIAL,
                "MODERATE": MODERATE, "DEBUG": DEBUG}
_LEVEL_STRS = {v: k for k, v in _LEVEL_NAMES.items()}


def parse_level(s: Optional[str]) -> int:
    """Parse a metrics level name; unknown values default to MODERATE (the
    reference's default for spark.rapids.sql.metrics.level)."""
    return _LEVEL_NAMES.get(str(s or "").strip().upper(), MODERATE)


def level_name(level: int) -> str:
    return _LEVEL_STRS.get(level, "MODERATE")


class MetricKind:
    SUM = "SUM"
    NANO_TIMING = "NANO_TIMING"
    PEAK = "PEAK"
    AVERAGE = "AVERAGE"


class MetricSpec:
    """Static description of one metric name: kind + level + doc. Frozen
    (shared across registries); accumulation state lives in TpuMetric."""

    __slots__ = ("name", "kind", "level", "doc")

    def __init__(self, name: str, kind: str, level: int, doc: str):
        self.name = name
        self.kind = kind
        self.level = level
        self.doc = doc


def _spec(name, kind, level, doc):
    return MetricSpec(name, kind, level, doc)


#: The standard metric taxonomy — the names every instrumented layer uses,
#: so profiles are comparable across operators and across runs. The table in
#: docs/monitoring.md is generated from this dict (taxonomy_markdown()).
TAXONOMY: Dict[str, MetricSpec] = {s.name: s for s in [
    _spec("opTime", MetricKind.NANO_TIMING, ESSENTIAL,
          "Host-side wall time spent in the operator's dispatch path "
          "(device execution is async; see deviceTime for fenced time)."),
    _spec("deviceTime", MetricKind.NANO_TIMING, ESSENTIAL,
          "Dispatch-to-ready device time, measured with an explicit "
          "block-until-ready fence. Only recorded under "
          "spark.rapids.tpu.metrics.deviceTiming=true — the fence "
          "serializes the pipeline, so it never runs on the default path."),
    _spec("uploadBytes", MetricKind.SUM, ESSENTIAL,
          "Host->device bytes transferred (Arrow buffer footprint at the "
          "HostToDevice boundary)."),
    _spec("downloadBytes", MetricKind.SUM, ESSENTIAL,
          "Device->host bytes transferred (result downloads, including the "
          "fused head transfer)."),
    _spec("numOutputRows", MetricKind.SUM, ESSENTIAL,
          "Rows produced, recorded only where the count is host-known "
          "(downloads, scans) — never via an extra device sync."),
    _spec("numOutputBatches", MetricKind.SUM, ESSENTIAL,
          "Batches produced by the operator."),
    _spec("numInputRows", MetricKind.SUM, MODERATE,
          "Rows consumed, where host-known."),
    _spec("numInputBatches", MetricKind.SUM, MODERATE,
          "Batches consumed."),
    _spec("spillBytes", MetricKind.SUM, ESSENTIAL,
          "Bytes pushed out of the device tier by the spill framework "
          "during the query (host + disk)."),
    _spec("semaphoreWaitNs", MetricKind.NANO_TIMING, MODERATE,
          "Time blocked acquiring the task-admission semaphore "
          "(spark.rapids.sql.concurrentTpuTasks)."),
    _spec("compileNs", MetricKind.NANO_TIMING, MODERATE,
          "Host time spent building/tracing kernels this query "
          "(kernel-cache misses; XLA backend compile time is async and "
          "shows up in deviceTime on first dispatch)."),
    _spec("shuffleBytesWritten", MetricKind.SUM, ESSENTIAL,
          "Serialized shuffle bytes written to the block catalog."),
    _spec("shuffleBytesRead", MetricKind.SUM, ESSENTIAL,
          "Serialized shuffle bytes read back on the reduce side."),
    _spec("buildTime", MetricKind.NANO_TIMING, MODERATE,
          "Join build-side accumulation wall time."),
    _spec("sortTime", MetricKind.NANO_TIMING, MODERATE,
          "Sort/top-k dispatch wall time."),
    _spec("concatTime", MetricKind.NANO_TIMING, DEBUG,
          "Batch-coalesce concat dispatch wall time."),
    _spec("serializationTime", MetricKind.NANO_TIMING, DEBUG,
          "Shuffle block serialization wall time."),
    _spec("deserializationTime", MetricKind.NANO_TIMING, DEBUG,
          "Shuffle block deserialization wall time."),
    _spec("writeTime", MetricKind.NANO_TIMING, MODERATE,
          "File-writer wall time (encode + filesystem)."),
    _spec("bytesWritten", MetricKind.SUM, ESSENTIAL,
          "Bytes written by the file writer."),
    _spec("numFiles", MetricKind.SUM, MODERATE,
          "Files produced by the file writer."),
    _spec("peakDeviceBytes", MetricKind.PEAK, MODERATE,
          "Peak device bytes observed (HBM watermark where the backend "
          "reports it)."),
    _spec("avgBatchRows", MetricKind.AVERAGE, DEBUG,
          "Average host-known rows per batch."),
    _spec("retryCount", MetricKind.SUM, ESSENTIAL,
          "Attempts re-run at the operator's retry sites after a "
          "classified OOM or transient fault (memory/retry.py; "
          "docs/fault-tolerance.md). Zero on a healthy run."),
    _spec("splitAndRetryCount", MetricKind.SUM, ESSENTIAL,
          "Input batches split in half by rows because retries alone "
          "could not fit the operator in device memory (the reference's "
          "splitSpillableInHalfByRows escalation)."),
    _spec("retryBlockTimeNs", MetricKind.NANO_TIMING, MODERATE,
          "Wall time spent blocked in retry backoff sleeps "
          "(spark.rapids.tpu.retry.backoffBaseMs ladder)."),
    _spec("retryWastedComputeNs", MetricKind.NANO_TIMING, MODERATE,
          "Wall time of failed attempts whose work was thrown away and "
          "re-run — the price of surviving the fault."),
    _spec("prefetchProducerStallNs", MetricKind.NANO_TIMING, ESSENTIAL,
          "Pipeline occupancy: time producers (prefetch workers, decode "
          "tasks) spent blocked on a full bounded prefetch queue — the "
          "consumer side is the bottleneck "
          "(spark.rapids.tpu.pipeline.prefetchDepth)."),
    _spec("prefetchConsumerStallNs", MetricKind.NANO_TIMING, ESSENTIAL,
          "Pipeline occupancy: time consumers spent blocked waiting for "
          "a prefetched batch or in-flight decode result — the producer "
          "side is the bottleneck."),
    _spec("decodeThreadBusyNs", MetricKind.NANO_TIMING, ESSENTIAL,
          "Total busy time of shared-pool decode tasks (file/row-group "
          "decode the pipeline layer overlapped with device work)."),
    _spec("boundaryOverlapNs", MetricKind.NANO_TIMING, ESSENTIAL,
          "Wall time saved by materializing independent fusion-boundary "
          "subtrees concurrently: the sum of per-boundary times minus "
          "elapsed time (spark.rapids.tpu.pipeline.boundaryParallelism)."),
    _spec("checksumFailures", MetricKind.SUM, ESSENTIAL,
          "Shuffle-block / spill-range CRC32C verifications that FAILED "
          "(utils/checksum.py; docs/fault-tolerance.md). Every failure "
          "was recovered by refetch or map recompute, or surfaced as a "
          "typed error — never as data. Zero on a healthy run."),
    _spec("shuffleBlocksRefetched", MetricKind.SUM, ESSENTIAL,
          "Shuffle blocks fetched again after a transport failure or "
          "checksum mismatch (only blocks not yet yielded re-fetch; "
          "shuffle/net.py). Zero on a healthy run."),
    _spec("mapTasksRecomputed", MetricKind.SUM, ESSENTIAL,
          "Map tasks deterministically re-executed from lineage because "
          "their shuffle blocks were lost or corrupt past refetch (the "
          "Spark stage-retry analog; shuffle/exchange.py "
          "MapOutputTracker). Zero on a healthy run."),
    _spec("deadlineCancels", MetricKind.SUM, ESSENTIAL,
          "Cooperative cancellations raised by the query deadline "
          "(spark.rapids.tpu.query.deadlineSecs): in-flight fetches, "
          "pipeline waits, and retry loops that observed an expired "
          "deadline and raised QueryDeadlineExceeded."),
    _spec("peersBlacklisted", MetricKind.SUM, ESSENTIAL,
          "Shuffle peers excluded for the session after repeated fetch "
          "failures (spark.rapids.tpu.shuffle.net.maxPeerFailures)."),
    _spec("hedgedFetches", MetricKind.SUM, ESSENTIAL,
          "Shuffle block fetches that exceeded the straggler threshold "
          "(spark.rapids.tpu.shuffle.hedge.quantileFactor x the peer's "
          "observed p50) and launched a duplicate request against a "
          "replica or the local recompute closure (shuffle/net.py). "
          "Zero on a healthy run."),
    _spec("hedgeWins", MetricKind.SUM, ESSENTIAL,
          "Hedged fetches where the DUPLICATE delivered first — the "
          "straggling primary was cancelled and the partition was "
          "served without waiting out its stall. Always <= "
          "hedgedFetches; the difference is hedge losses (wasted "
          "duplicate work)."),
    _spec("replicaReads", MetricKind.SUM, ESSENTIAL,
          "Shuffle blocks served by a replica "
          "(spark.rapids.tpu.shuffle.replication.factor) because the "
          "primary was dead, stalled, or blacklisted — each one a "
          "lineage recompute avoided. Zero on a healthy run."),
    _spec("meshFailovers", MetricKind.SUM, ESSENTIAL,
          "Mesh SPMD dispatches abandoned to the single-chip path after "
          "a device/host loss (MeshDegradedError) or a failed health "
          "probe (spark.rapids.tpu.mesh.health.probeEnabled): the query "
          "re-ran degraded instead of failing (exec/mesh.py, "
          "session.py). Zero on a healthy run."),
]}

#: Metrics recorded under names outside the taxonomy (operator-specific
#: counters like aqeOutputPartitions, stripeHostFallback) default to
#: SUM/MODERATE.
_AD_HOC_LEVEL = MODERATE


def taxonomy_markdown() -> str:
    """The docs/monitoring.md taxonomy table (kept in sync by
    tests/test_metrics.py)."""
    lines = ["Name | Kind | Level | Description",
             "-----|------|-------|------------"]
    for name in sorted(TAXONOMY):
        s = TAXONOMY[name]
        lines.append(f"`{name}`|{s.kind}|{level_name(s.level)}|{s.doc}")
    return "\n".join(lines) + "\n"


class TpuMetric:
    """One accumulator (the GpuMetric analog). Kind decides the merge:
    SUM/NANO_TIMING add, PEAK keeps the max, AVERAGE tracks (sum, count).
    Mutation is guarded by the owning registry's lock."""

    __slots__ = ("spec", "_sum", "_count", "_peak")

    def __init__(self, spec: MetricSpec):
        self.spec = spec
        self._sum = 0
        self._count = 0
        self._peak = 0

    def update(self, value) -> None:
        value = int(value) if not isinstance(value, float) else value
        if self.spec.kind == MetricKind.PEAK:
            self._peak = max(self._peak, value)
        elif self.spec.kind == MetricKind.AVERAGE:
            self._sum += value
            self._count += 1
        else:
            self._sum += value

    def set(self, value) -> None:
        """Overwrite (legacy direct-dict-assignment semantics)."""
        if self.spec.kind == MetricKind.PEAK:
            self._peak = value
        else:
            self._sum = value
            self._count = 1

    @property
    def value(self):
        if self.spec.kind == MetricKind.PEAK:
            return self._peak
        if self.spec.kind == MetricKind.AVERAGE:
            return self._sum / self._count if self._count else 0
        return self._sum


class _NoopTimer:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP_TIMER = _NoopTimer()


class MetricsRegistry:
    """Per-query metric store: (node name, metric name) -> TpuMetric.

    Thread-safe — warm-up workers and shuffle transport threads report
    concurrently (tests/test_metrics.py hammer test). Node keying follows
    the engine's existing convention: metrics are keyed by the exec's
    node_name(), so two instances of the same exec type in one plan share
    accumulators (noted in docs/monitoring.md)."""

    def __init__(self, level: int = MODERATE, device_timing: bool = False):
        self.level = level
        self.device_timing = device_timing and level > NONE
        self._lock = lockdep.lock("MetricsRegistry._lock")
        self._nodes: Dict[str, Dict[str, TpuMetric]] = {}

    @classmethod
    def for_conf(cls, conf) -> "MetricsRegistry":
        """Build from a TpuConf (duck-typed: anything with the metrics
        properties; bare test contexts without them get the defaults)."""
        level = parse_level(getattr(conf, "metrics_level", None))
        return cls(level, bool(getattr(conf, "metrics_device_timing", False)))

    @property
    def enabled(self) -> bool:
        return self.level > NONE

    def _spec_for(self, name: str) -> MetricSpec:
        spec = TAXONOMY.get(name)
        if spec is None:
            spec = MetricSpec(name, MetricKind.SUM, _AD_HOC_LEVEL,
                              "operator-specific counter")
        return spec

    def records(self, name: str) -> bool:
        """Would a metric of this name be recorded at the current level?"""
        return self.level >= self._spec_for(name).level

    def _metric_locked(self, node: str, name: str) -> Optional[TpuMetric]:
        """The accumulator for (node, name), or None when gated. Caller
        holds the lock (one critical section per observation — this is the
        per-batch hot path)."""
        spec = self._spec_for(name)
        if self.level < spec.level:
            return None
        metrics = self._nodes.setdefault(node, {})
        m = metrics.get(name)
        if m is None:
            m = metrics[name] = TpuMetric(spec)
        return m

    def add(self, node: str, name: str, value) -> None:
        with self._lock:
            m = self._metric_locked(node, name)
            if m is not None:
                m.update(value)

    def set_value(self, node: str, name: str, value) -> None:
        with self._lock:
            m = self._metric_locked(node, name)
            if m is not None:
                m.set(value)

    def timer(self, node: str, name: str, trace: Optional[str] = None):
        """Exception-safe NANO_TIMING context manager, coupled with an
        XProf trace range (the NvtxWithMetrics analog). The trace span is
        emitted regardless of the metrics level — profiler visibility must
        not depend on metric gating — but the clock reads and accumulation
        are skipped when the metric is gated."""
        if not self.records(name):
            if trace is None:
                return _NOOP_TIMER
            from ..utils.tracing import trace_range
            return trace_range(trace)
        from ..utils.tracing import NanoTimer
        return NanoTimer(trace or f"{node}.{name}",
                         _NodeSink(self, node), name)()

    # -- read side ----------------------------------------------------------
    def node_metrics(self, node: str) -> Dict[str, object]:
        with self._lock:
            return {n: m.value for n, m in self._nodes.get(node, {}).items()}

    def node_names(self):
        with self._lock:
            return list(self._nodes)

    def snapshot(self) -> Dict[str, Dict[str, object]]:
        with self._lock:
            return {node: {n: m.value for n, m in metrics.items()}
                    for node, metrics in self._nodes.items()}

    def legacy_view(self) -> "_LegacyMetricsView":
        return _LegacyMetricsView(self)


class _NodeSink:
    """Dict-shaped adapter binding NanoTimer (and other legacy dict
    writers) to one node of a registry."""

    __slots__ = ("_registry", "_node")

    def __init__(self, registry: MetricsRegistry, node: str):
        self._registry = registry
        self._node = node

    def get(self, key, default=0):
        return self._registry.node_metrics(self._node).get(key, default)

    def __setitem__(self, key, value):
        self._registry.set_value(self._node, key, value)

    def add(self, key, value):
        self._registry.add(self._node, key, value)


def _deprecated(what: str) -> None:
    import warnings
    warnings.warn(
        f"direct mutation of ExecContext.metrics ({what}) is deprecated; "
        "use ExecContext.metric(node, name, value) or "
        "ExecContext.registry — the dict shim is kept for one release",
        DeprecationWarning, stacklevel=3)


class _LegacyNodeView:
    """Read/write shim for one node's metrics: reads return plain numbers
    (what the old ad-hoc dict held); writes warn and route into the
    registry."""

    def __init__(self, registry: MetricsRegistry, node: str):
        self._registry = registry
        self._node = node

    def _values(self):
        return self._registry.node_metrics(self._node)

    def __getitem__(self, name):
        return self._values()[name]

    def get(self, name, default=None):
        return self._values().get(name, default)

    def __contains__(self, name):
        return name in self._values()

    def __iter__(self):
        return iter(self._values())

    def __len__(self):
        return len(self._values())

    def items(self):
        return self._values().items()

    def keys(self):
        return self._values().keys()

    def values(self):
        return self._values().values()

    def __setitem__(self, name, value):
        _deprecated(f"metrics[{self._node!r}][{name!r}] = ...")
        self._registry.set_value(self._node, name, value)

    def setdefault(self, name, default=0):
        cur = self._values().get(name)
        if cur is not None:
            return cur
        _deprecated(f"metrics[{self._node!r}].setdefault({name!r})")
        self._registry.set_value(self._node, name, default)
        return default

    def __repr__(self):
        return repr(self._values())

    def __eq__(self, other):
        return self._values() == other


class _LegacyMetricsView:
    """The ``ExecContext.metrics`` dict shim: node -> name -> value, backed
    by the registry. Reads are silent (tests and diagnostics iterate it);
    mutation warns with DeprecationWarning and keeps working for one
    release."""

    def __init__(self, registry: MetricsRegistry):
        self._registry = registry

    def __getitem__(self, node):
        return _LegacyNodeView(self._registry, node)

    def get(self, node, default=None):
        if node not in self._registry.node_names():
            return default
        return _LegacyNodeView(self._registry, node)

    def setdefault(self, node, default=None):
        return _LegacyNodeView(self._registry, node)

    def __contains__(self, node):
        return node in self._registry.node_names()

    def __iter__(self):
        return iter(self._registry.node_names())

    def __len__(self):
        return len(self._registry.node_names())

    def items(self):
        return [(n, _LegacyNodeView(self._registry, n))
                for n in self._registry.node_names()]

    def keys(self):
        return self._registry.node_names()

    def values(self):
        return [_LegacyNodeView(self._registry, n)
                for n in self._registry.node_names()]

    def __repr__(self):
        return repr(self._registry.snapshot())
