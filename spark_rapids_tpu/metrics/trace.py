"""Per-query distributed tracing — the span-tree engine (ISSUE 13).

The profile layer (metrics/profile.py) answers "how much"; this module
answers "WHEN": one :class:`Tracer` per query collects a tree of timed
spans across every layer the query touches — serve admission and queue
wait, session dispatch and the PR-4 retry ladder, the PR-5 pipeline
workers, the PR-11 spill-IO lane, compile/warmup events, and shuffle
map/fetch/recompute — and exports it as Chrome trace-event JSON
(Perfetto-loadable) beside the structured event log.

Design rules, in the lockdep mold (utils/lockdep.py):

* **Zero-cost default.** Tracing is off unless
  ``spark.rapids.tpu.trace.enabled`` is set; disabled call sites pay one
  ``None`` check and receive the shared :data:`NOOP_SPAN` context
  manager — no allocation, no fences, bit-identical results (asserted by
  tests/test_trace.py).
* **Named internals.** The tracer's own lock routes through the lockdep
  factories; span bookkeeping never blocks on I/O.
* **Thread stitching.** Each tracer keeps a per-thread stack of open
  spans, so nested ``with span(...)`` calls parent naturally. Work that
  hops threads (pipeline boundary workers, decode tasks, the spill-IO
  lane) either carries a :class:`SpanCtx` fork (the
  ``ExecContext.fork_for_boundary`` idiom) or falls back to parenting
  under the trace root, so worker spans always land inside the tree.
* **Wire propagation.** A trace context travels over BOTH wire planes:
  the serve frontend's ``SRTQS`` protocol carries it as a request field
  and the shuffle wire (shuffle/net.py protocol v4) carries a
  ``(trace64, span64)`` header on every request, so a fetch served by a
  peer stitches into the requesting query's trace — in-process peers
  join the SAME tracer through the live-trace registry; cross-process
  peers open a sibling tracer under the same trace id (standard
  distributed-tracing stitching by id).
* **Flight recorder.** A bounded process-wide ring buffer keeps the most
  recent finished spans and engine events (compile, warm-up, quarantine,
  crash) regardless of which query produced them;
  :func:`flight_dump` writes it to ``artifacts/`` on
  ``QueryDeadlineExceeded``, circuit-breaker quarantine trips,
  ``SessionCrashError``, and SIGTERM — the post-mortem "what was the
  engine doing" artifact.

``tools/trace_report.py`` is the reader: critical path, top self-time
spans, overlap efficiency, per-tenant queue-vs-execute. See
docs/monitoring.md#distributed-tracing.
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time
import weakref
import zlib
from collections import deque
from typing import Dict, List, Optional, Tuple

from ..utils import lockdep

#: Trace-file schema version (Chrome trace-event JSON "otherData").
VERSION = 1


# ---------------------------------------------------------------------------
# Process-wide configuration (the lockdep configure idiom)
# ---------------------------------------------------------------------------

_STATE_LOCK = lockdep.lock("trace._STATE_LOCK")
_ENABLED = False
_TRACE_DIR: Optional[str] = None
_FLIGHT_DIR = "artifacts"
#: exported trace_*.json kept per directory (oldest pruned past this)
_MAX_FILES = 256
#: bounded ring of recent span/event dicts (flight recorder)
_RING: deque = deque(maxlen=4096)
#: dumps written this process, per reason (bounded so a crash loop
#: cannot flood the artifacts directory)
_DUMPS: Dict[str, int] = {}
_MAX_DUMPS_PER_REASON = 8
_DUMP_SEQ = [0]
#: live tracers by trace id AND by wire hash (weakrefs: an abandoned
#: query's tracer must not be pinned by the registry)
_LIVE: "weakref.WeakValueDictionary[object, Tracer]" = \
    weakref.WeakValueDictionary()
_TRACE_SEQ = [0]
_SIGTERM_INSTALLED = [False]


def configure(conf) -> None:
    """Snapshot the ``spark.rapids.tpu.trace.*`` keys into process state
    (TpuSession / QueryService init — the compile-layer configure idiom).
    ENABLE-only, like ``lockdep.enable``: a session with tracing OFF
    leaves the process state alone (per-session gating in
    :func:`maybe_tracer` already keeps it untraced), so an untraced
    session can never un-configure a traced sibling mid-query. Near-free
    and idempotent; never raises on bare test confs. Disable with
    :func:`reset_for_tests`."""
    global _ENABLED, _TRACE_DIR, _FLIGHT_DIR, _RING, _MAX_FILES
    from ..config import (TRACE_DIR, TRACE_ENABLED, TRACE_FLIGHT_DIR,
                          TRACE_FLIGHT_SPANS, TRACE_MAX_FILES)
    try:
        enabled = bool(conf.get(TRACE_ENABLED))
        tdir = conf.get(TRACE_DIR)
        fdir = conf.get(TRACE_FLIGHT_DIR)
        ring = int(conf.get(TRACE_FLIGHT_SPANS))
        max_files = int(conf.get(TRACE_MAX_FILES))
    except (AttributeError, TypeError, ValueError):
        return
    if not enabled:
        return
    with _STATE_LOCK:
        _ENABLED = True
        _TRACE_DIR = tdir or None
        _FLIGHT_DIR = fdir or "artifacts"
        _MAX_FILES = max_files
        if ring > 0 and _RING.maxlen != ring:
            _RING = deque(_RING, maxlen=ring)
    _install_sigterm_dump()


def enabled() -> bool:
    return _ENABLED


def next_trace_seq() -> int:
    with _STATE_LOCK:
        _TRACE_SEQ[0] += 1
        return _TRACE_SEQ[0]


def wire_hash(trace_id: str) -> int:
    """Stable non-zero u64 of a trace id — the shuffle wire encoding
    (0 is reserved for "no trace context")."""
    h = (zlib.crc32(trace_id.encode()) << 32) \
        | zlib.crc32(trace_id[::-1].encode())
    return (h & 0xFFFFFFFFFFFFFFFF) or 1


# ---------------------------------------------------------------------------
# Spans
# ---------------------------------------------------------------------------


class _NoopSpan:
    """Shared do-nothing span: the disabled path's context manager.
    One module-level instance, reused — entering it allocates nothing."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


NOOP_SPAN = _NoopSpan()


class _Span:
    """One open span handle (context manager). Closed spans are stored as
    plain dicts on the tracer; the handle itself is transient."""

    __slots__ = ("tracer", "name", "cat", "span_id", "parent_id",
                 "t0_ns", "args")

    def __init__(self, tracer: "Tracer", name: str, cat: str,
                 span_id: int, parent_id: int, args: Optional[dict]):
        self.tracer = tracer
        self.name = name
        self.cat = cat
        self.span_id = span_id
        self.parent_id = parent_id
        self.t0_ns = 0
        self.args = args

    def __enter__(self):
        self.t0_ns = time.perf_counter_ns()
        self.tracer._push(self)
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc is not None:
            # Error spans keep their timing and are tagged — a failed
            # fetch/dispatch must stay visible on the timeline.
            a = dict(self.args or {})
            a["error"] = type(exc).__name__
            self.args = a
        self.tracer._pop(self, time.perf_counter_ns())
        return False

    def annotate(self, **kv) -> None:
        """Attach args to an already-open span (e.g. compile observed
        mid-dispatch)."""
        a = dict(self.args or {})
        a.update(kv)
        self.args = a


class SpanCtx:
    """A forked span context: (tracer, parent span id) captured on one
    thread and adopted on another — the cross-thread (and cross-process,
    via :func:`wire_context`) parenting handle."""

    __slots__ = ("tracer", "parent_id")

    def __init__(self, tracer: "Tracer", parent_id: int):
        self.tracer = tracer
        self.parent_id = parent_id


class Tracer:
    """One query's span tree. Thread-safe: pipeline workers, the spill-IO
    lane, and the dispatching thread all record concurrently. Bounded:
    past ``max_spans`` spans the tracer records only a drop counter
    (observability must not hold the query's memory hostage)."""

    def __init__(self, trace_id: str, tenant: str = "",
                 max_spans: int = 100_000):
        self.trace_id = trace_id
        self.tenant = tenant
        self.query_id: Optional[int] = None
        self.max_spans = max_spans
        self.t0_ns = time.perf_counter_ns()
        self.spans: List[dict] = []
        self.dropped = 0
        self._seq = 0
        self._root_id = 0
        #: nonzero on an adopted cross-process sibling tracer: the wire
        #: parent's span id, valid as a parent even though no local span
        #: carries it (assert_balanced honors it)
        self._remote_root = 0
        self._open: Dict[int, _Span] = {}
        self._lock = lockdep.lock("Tracer._lock")
        self._tls = threading.local()
        with _STATE_LOCK:
            _LIVE[trace_id] = self
            _LIVE[wire_hash(trace_id)] = self

    # -- recording ----------------------------------------------------------
    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def span(self, name: str, cat: str = "engine",
             fallback_parent: Optional[int] = None, **args):
        """Open a span (context manager). Parent = this thread's
        innermost open span, else ``fallback_parent`` (a fork's captured
        parent), else the trace root."""
        st = self._stack()
        if st:
            parent = st[-1].span_id
        elif fallback_parent is not None:
            parent = fallback_parent
        else:
            parent = self._root_id
        with self._lock:
            self._seq += 1
            sid = self._seq
            if self._root_id == 0:
                self._root_id = sid
        return _Span(self, name, cat, sid, 0 if sid == parent else parent,
                     args or None)

    def _push(self, s: _Span) -> None:
        self._stack().append(s)
        with self._lock:
            self._open[s.span_id] = s

    def _pop(self, s: _Span, t1_ns: int) -> None:
        st = self._stack()
        for i in range(len(st) - 1, -1, -1):
            if st[i] is s:
                del st[i]
                break
        rec = {"name": s.name, "cat": s.cat, "id": s.span_id,
               "parent": s.parent_id, "t0_ns": s.t0_ns, "t1_ns": t1_ns,
               "tid": threading.current_thread().name}
        if s.args:
            rec["args"] = s.args
        with self._lock:
            self._open.pop(s.span_id, None)
            if len(self.spans) < self.max_spans:
                self.spans.append(rec)
            else:
                self.dropped += 1
        _ring_append({"kind": "span", "trace_id": self.trace_id, **rec})

    # -- forking / wire -----------------------------------------------------
    def fork(self) -> SpanCtx:
        """Capture this thread's current span as the parent for work that
        will record from another thread (the boundary-fork idiom)."""
        st = self._stack()
        return SpanCtx(self, st[-1].span_id if st else self._root_id)

    def wire_context(self) -> Tuple[int, int]:
        """(trace64, span64) to stamp on an outgoing wire request."""
        st = self._stack()
        return (wire_hash(self.trace_id),
                st[-1].span_id if st else self._root_id)

    # -- introspection ------------------------------------------------------
    def current_span_name(self) -> Optional[str]:
        """The most recently opened still-open span's name, any thread —
        the serve ``health`` view's "where is this query right now"."""
        with self._lock:
            if not self._open:
                return None
            return self._open[max(self._open)].name

    def open_spans(self) -> List[str]:
        with self._lock:
            return [s.name for _, s in sorted(self._open.items())]

    def assert_balanced(self) -> None:
        """Every opened span closed; every parent id valid (0/root or a
        recorded or still-open span). The chaos/fault-matrix tests run
        this after every injected failure."""
        with self._lock:
            if self._open:
                raise AssertionError(
                    f"trace {self.trace_id}: {len(self._open)} span(s) "
                    f"left open: {[s.name for s in self._open.values()]}")
            ids = {s["id"] for s in self.spans}
            if self._remote_root:
                ids.add(self._remote_root)
            for s in self.spans:
                if s["parent"] and s["parent"] not in ids:
                    raise AssertionError(
                        f"trace {self.trace_id}: span {s['name']!r} has "
                        f"unknown parent {s['parent']}")
                if s["t1_ns"] < s["t0_ns"]:
                    raise AssertionError(
                        f"trace {self.trace_id}: span {s['name']!r} ends "
                        "before it starts")

    # -- export -------------------------------------------------------------
    def to_chrome(self) -> dict:
        """Chrome trace-event JSON (Perfetto-loadable): complete ``X``
        events in microseconds, one thread lane per recording thread,
        span args preserved; trace metadata in ``otherData``."""
        with self._lock:
            spans = list(self.spans)
            dropped = self.dropped
        tids: Dict[str, int] = {}
        events: List[dict] = []
        for s in sorted(spans, key=lambda r: r["t0_ns"]):
            tid = tids.setdefault(s["tid"], len(tids) + 1)
            ev = {"name": s["name"], "cat": s["cat"], "ph": "X",
                  "ts": (s["t0_ns"] - self.t0_ns) / 1e3,
                  "dur": (s["t1_ns"] - s["t0_ns"]) / 1e3,
                  "pid": os.getpid(), "tid": tid,
                  "args": {"id": s["id"], "parent": s["parent"],
                           **(s.get("args") or {})}}
            events.append(ev)
        for name, tid in tids.items():
            events.append({"ph": "M", "name": "thread_name",
                           "pid": os.getpid(), "tid": tid,
                           "args": {"name": name}})
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {"trace_id": self.trace_id, "tenant": self.tenant,
                          "query_id": self.query_id, "version": VERSION,
                          "dropped_spans": dropped},
        }


# ---------------------------------------------------------------------------
# Call-site helpers (the one-liner every instrumented layer uses)
# ---------------------------------------------------------------------------


def span(owner, name: str, cat: str = "engine", **args):
    """THE instrumentation one-liner: ``with trace.span(ctx.trace,
    "fusion.dispatch"):``. ``owner`` is None (disabled — returns the
    shared no-op), a :class:`Tracer`, or a :class:`SpanCtx` fork."""
    if owner is None:
        return NOOP_SPAN
    if isinstance(owner, SpanCtx):
        return owner.tracer.span(name, cat,
                                 fallback_parent=owner.parent_id, **args)
    return owner.span(name, cat, **args)


def fork(owner) -> Optional[SpanCtx]:
    """Fork the current span context for another thread; None stays
    None (disabled path)."""
    if owner is None:
        return None
    if isinstance(owner, SpanCtx):
        return owner
    return owner.fork()


def tracer_of(owner) -> Optional[Tracer]:
    if isinstance(owner, SpanCtx):
        return owner.tracer
    return owner if isinstance(owner, Tracer) else None


def maybe_tracer(conf, tenant: str = "") -> Optional[Tracer]:
    """A fresh per-query tracer when THIS conf sets
    ``spark.rapids.tpu.trace.enabled``, else None — the one lookup the
    default path pays. Gating is per session: a traced session never
    turns tracing on for an untraced sibling (the Pallas per-session
    gate stance)."""
    from ..config import TRACE_ENABLED
    try:
        if not conf.get(TRACE_ENABLED):
            return None
    except (AttributeError, TypeError):
        return None
    if not _ENABLED:
        configure(conf)
        if not _ENABLED:
            return None
    tid = f"{tenant or 'default'}-{os.getpid()}-{next_trace_seq()}"
    return Tracer(tid, tenant)


def adopt(trace_id: str, parent_span_id: int = 0,
          tenant: str = "") -> Optional[Tracer]:
    """Join an incoming wire trace context (the SRTQS ``trace`` request
    field): the LIVE tracer when this process owns it (loopback peers
    stitch into one tree), else a sibling tracer under the same trace id
    (cross-process; stitched by id at analysis time). None when tracing
    is disabled here."""
    if not _ENABLED:
        return None
    with _STATE_LOCK:
        live = _LIVE.get(trace_id)
    if live is not None:
        return live
    t = Tracer(trace_id, tenant)
    t._root_id = parent_span_id or 0
    t._remote_root = parent_span_id or 0
    # Local span ids start ABOVE the remote parent id: the sibling's
    # sids share a number space with the origin's, and a collision
    # would both trip the self-parent guard and make parents ambiguous
    # when the two halves are stitched by id at analysis time.
    t._seq = max(t._seq, parent_span_id or 0)
    return t


def live_tracer(key) -> Optional[Tracer]:
    """Live-trace registry lookup by trace id or wire hash (the shuffle
    server's stitch path for in-process peers)."""
    with _STATE_LOCK:
        return _LIVE.get(key)


def parse_wire(s: Optional[str]) -> Tuple[Optional[str], int]:
    """Parse the SRTQS ``trace`` field ``"<trace_id>/<parent_span>"``."""
    if not s or not isinstance(s, str):
        return None, 0
    tid, _, parent = s.partition("/")
    try:
        return (tid or None), int(parent or 0)
    except ValueError:
        return (tid or None), 0


def format_wire(tracer: Optional[Tracer]) -> Optional[str]:
    """The SRTQS ``trace`` request-field encoding of a tracer's current
    context."""
    if tracer is None:
        return None
    st = tracer._stack()
    parent = st[-1].span_id if st else tracer._root_id
    return f"{tracer.trace_id}/{parent}"


# ---------------------------------------------------------------------------
# Export
# ---------------------------------------------------------------------------


def export_dir(conf=None) -> Optional[str]:
    """Where trace files land: the CALLER's ``spark.rapids.tpu.trace.dir``
    (per-session, so two traced sessions can export to different
    places), else the process snapshot, else the caller's event-log dir
    (traces sit beside the event log), else None."""
    if conf is not None:
        from ..config import TRACE_DIR
        try:
            d = conf.get(TRACE_DIR)
            if d:
                return d
        except (AttributeError, TypeError):
            pass
    if _TRACE_DIR:
        return _TRACE_DIR
    try:
        return conf.metrics_event_log_dir if conf is not None else None
    except AttributeError:
        return None


def export_chrome(tracer: Tracer, directory: Optional[str]) -> Optional[str]:
    """Write one query's Chrome trace-event JSON as
    ``trace_<trace_id>.json`` under ``directory`` — an adopted
    cross-process sibling adds a ``.peer<pid>`` discriminator, so the
    two halves of a stitched trace exported to one shared directory
    never clobber each other. The directory is retention-bounded
    (``spark.rapids.tpu.trace.maxFiles``: oldest pruned). Best-effort:
    tracing is an aid, never a failure path — any error returns None."""
    if directory is None:
        return None
    safe = "".join(c if c.isalnum() or c in "-_." else "_"
                   for c in tracer.trace_id)
    if tracer._remote_root:
        safe = f"{safe}.peer{os.getpid()}"
    path = os.path.join(directory, f"trace_{safe}.json")
    try:
        os.makedirs(directory, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(tracer.to_chrome(), f, separators=(",", ":"),
                      default=str)
            f.write("\n")
        os.replace(tmp, path)  # atomic: a reader never sees a torn file
        _prune_trace_dir(directory)
        return path
    except (OSError, TypeError, ValueError):
        return None


def _prune_trace_dir(directory: str) -> None:
    """Drop the oldest ``trace_*.json`` past the retention cap — the
    serving process exports one file per query forever, and traces must
    not become the disk-filler the event log's maxBytes rotation already
    guards against."""
    cap = _MAX_FILES
    if cap <= 0:
        return
    try:
        entries = [(e.stat().st_mtime, e.path)
                   for e in os.scandir(directory)
                   if e.name.startswith("trace_")
                   and e.name.endswith(".json")]
    except OSError:
        return
    if len(entries) <= cap:
        return
    for _, victim in sorted(entries)[:len(entries) - cap]:
        try:
            os.remove(victim)
        except OSError:
            pass  # concurrent exporter pruned it first


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------


def _ring_append(rec: dict) -> None:
    if _ENABLED:
        _RING.append(rec)  # deque append is atomic; maxlen bounds it


def record_event(name: str, **args) -> None:
    """Record one engine event (compile, warm-up, quarantine, crash)
    into the flight-recorder ring. Near-free when tracing is off."""
    if not _ENABLED:
        return
    _RING.append({"kind": "event", "name": name,
                  "ts_ns": time.perf_counter_ns(),
                  "thread": threading.current_thread().name,
                  **({"args": args} if args else {})})


def flight_dump(reason: str, _signal_safe: bool = False,
                **context) -> Optional[str]:
    """Dump the flight-recorder ring to
    ``<flightDir>/flight_<reason>_<pid>_<n>.json``. Called on
    QueryDeadlineExceeded, quarantine trips, SessionCrashError, and
    SIGTERM; bounded per reason so a crash loop cannot flood the
    directory. Best-effort, never raises.

    ``_signal_safe`` is set ONLY by the SIGTERM handler: a signal lands
    between bytecodes on the main thread, which may already hold
    ``_STATE_LOCK`` (every tracer construction takes it) — acquiring it
    from the handler would self-deadlock the shutdown path. The
    signal-safe variant reads the state unsynchronized instead
    (GIL-atomic container ops; a raced counter at process death is
    acceptable, a hung SIGTERM is not)."""
    if not _ENABLED:
        return None
    if _signal_safe:
        # Deliberately lock-free (see docstring): runs only inside the
        # SIGTERM handler on the main thread, where taking _STATE_LOCK
        # could self-deadlock. A torn counter at process death is fine.
        n = _DUMPS.get(reason, 0)
        if n >= _MAX_DUMPS_PER_REASON:
            return None
        _DUMPS[reason] = n + 1  # concurrency: ignore
        _DUMP_SEQ[0] += 1  # concurrency: ignore
        seq = _DUMP_SEQ[0]
        directory = _FLIGHT_DIR
        ring = list(_RING)
    else:
        with _STATE_LOCK:
            n = _DUMPS.get(reason, 0)
            if n >= _MAX_DUMPS_PER_REASON:
                return None
            _DUMPS[reason] = n + 1
            _DUMP_SEQ[0] += 1
            seq = _DUMP_SEQ[0]
            directory = _FLIGHT_DIR
            ring = list(_RING)
    payload = {
        "reason": reason,
        "context": {k: str(v) for k, v in context.items()},
        "pid": os.getpid(),
        "ts_ns": time.perf_counter_ns(),
        "version": VERSION,
        "recent": ring,
    }
    path = os.path.join(directory, f"flight_{reason}_{os.getpid()}_{seq}.json")
    try:
        os.makedirs(directory, exist_ok=True)
        tmp = f"{path}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f, separators=(",", ":"), default=str)
            f.write("\n")
        os.replace(tmp, path)
        return path
    except (OSError, TypeError, ValueError):
        return None


def _install_sigterm_dump() -> None:
    """Chain a SIGTERM handler that dumps the flight recorder before the
    previous disposition runs (main thread only; best-effort)."""
    with _STATE_LOCK:
        if _SIGTERM_INSTALLED[0]:
            return
        _SIGTERM_INSTALLED[0] = True
    try:
        prev = signal.getsignal(signal.SIGTERM)

        def _on_term(signum, frame):
            flight_dump("sigterm", _signal_safe=True)
            if callable(prev):
                prev(signum, frame)
            elif prev == signal.SIG_DFL:
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                os.kill(os.getpid(), signal.SIGTERM)
        signal.signal(signal.SIGTERM, _on_term)
    except (ValueError, OSError, RuntimeError):
        # Not the main thread, or signals unavailable: the other dump
        # triggers still fire.
        with _STATE_LOCK:
            _SIGTERM_INSTALLED[0] = False


def ring_snapshot() -> List[dict]:
    """Current flight-recorder contents (tests/diagnostics)."""
    return list(_RING)


def reset_for_tests() -> None:
    """Clear process trace state (test isolation): ring, dump budgets,
    and the enabled flag (configure() re-arms it)."""
    global _ENABLED, _TRACE_DIR
    with _STATE_LOCK:
        _ENABLED = False
        _TRACE_DIR = None
        _RING.clear()
        _DUMPS.clear()
