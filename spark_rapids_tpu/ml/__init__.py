"""ML integration: zero-copy export of query results to JAX trainers
(the ml-integration / ColumnarRdd surface of the reference)."""

from .export import (feature_matrix, predict_gbt, predict_logistic,
                     train_gbt, train_logistic_regression)

__all__ = ["feature_matrix", "train_logistic_regression",
           "predict_logistic", "train_gbt", "predict_gbt"]
