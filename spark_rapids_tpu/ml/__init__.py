"""ML integration: zero-copy export of query results to JAX trainers,
sharded data-parallel training over the mesh, and a session-scoped model
registry feeding model scoring as a plan operator
(``df.with_model_score``) — the ml-integration / ColumnarRdd surface of
the reference grown into a subsystem (docs/ml-integration.md)."""

from .export import (feature_matrix, predict_gbt, predict_logistic,
                     sharded_feature_matrix, train_gbt, train_gbt_sharded,
                     train_logistic_regression,
                     train_logistic_regression_sharded)
from .registry import ModelMeta, ModelRegistry

__all__ = ["feature_matrix", "sharded_feature_matrix",
           "train_logistic_regression",
           "train_logistic_regression_sharded", "predict_logistic",
           "train_gbt", "train_gbt_sharded", "predict_gbt",
           "ModelRegistry", "ModelMeta"]
