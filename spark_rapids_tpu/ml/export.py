"""Zero-copy ML handoff — the ``ColumnarRdd`` analog.

The reference exports the GPU-resident columnar output of a query directly
to ML frameworks (XGBoost) with no host round trip
(``ColumnarRdd.scala:41-49``, ``InternalColumnarRddConverter.scala``; gated
by ``spark.rapids.sql.exportColumnarRdd``, RapidsConf.scala:329). The TPU
analog is stronger: a query's result batches are already ``jax.Array``
columns in HBM, so the handoff to a JAX trainer is literally passing
pytrees — :func:`feature_matrix` packs them into the dense ``[n, d]``
matrix an ML loop wants via one traced kernel, and
:func:`train_logistic_regression` is a reference consumer that never
leaves the device.

``DataFrame.to_device_batches()`` (plan/logical.py) is the entry point;
it requires ``spark.rapids.sql.exportColumnarRdd`` like the reference.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .. import types as T
from ..data.batch import ColumnarBatch
from ..exec.execs import _coalesce_device
from ..utils.kernel_cache import cached_kernel, kernel_key


def feature_matrix(batches: Sequence[ColumnarBatch],
                   feature_cols: Sequence[str],
                   label_col: Optional[str] = None,
                   dtype=jnp.float32):
    """Pack device batches into ``(X[cap, d], y[cap], row_mask[cap])``.

    Entirely on-device: one capacity-sized concat plus a stacking kernel —
    no host transfer anywhere (the zero-copy contract of the reference's
    ColumnarRdd). Rows with a null in any used column are masked out, the
    standard ML semantic. The row count stays traced; consumers use
    ``row_mask`` (static shapes) instead of slicing."""
    batches = list(batches)
    if not batches:
        raise ValueError("no batches to export")
    batch = _coalesce_device(batches)
    schema = batch.schema
    f_idx = tuple(schema.index_of(c) for c in feature_cols)
    l_idx = schema.index_of(label_col) if label_col is not None else None

    def build():
        def pack(b: ColumnarBatch):
            live = b.row_mask()
            cols = []
            valid = live
            for i in f_idx:
                c = b.columns[i]
                cols.append(c.data.astype(dtype))
                valid = valid & c.validity
            x = jnp.stack(cols, axis=1)
            if l_idx is not None:
                lc = b.columns[l_idx]
                y = lc.data.astype(dtype)
                valid = valid & lc.validity
            else:
                y = jnp.zeros(b.capacity, dtype)
            return x, y, valid
        return pack
    pack = cached_kernel("ml_feature_matrix",
                         kernel_key(schema, f_idx, l_idx, str(dtype)),
                         build)
    return pack(batch)


def train_logistic_regression(x, y, mask, steps: int = 100, lr: float = 0.1):
    """Reference on-device consumer: masked logistic regression by full-batch
    gradient descent, one jitted training loop (the BASELINE.md config-4
    "query output -> JAX trainer" shape). Returns the fitted model dict
    for :func:`predict_logistic`."""
    d = x.shape[1]
    m = mask.astype(x.dtype)
    n = jnp.maximum(jnp.sum(m), 1.0)
    # Feature standardization keeps GD well-conditioned for raw SQL outputs.
    mean = jnp.sum(x * m[:, None], axis=0) / n
    var = jnp.sum(((x - mean) ** 2) * m[:, None], axis=0) / n
    xs = (x - mean) / jnp.sqrt(var + 1e-6)

    def loss_fn(params):
        w, b = params
        z = xs @ w + b
        p = jax.nn.sigmoid(z)
        eps = 1e-7
        bce = -(y * jnp.log(p + eps) + (1 - y) * jnp.log(1 - p + eps))
        return jnp.sum(bce * m) / n

    @jax.jit
    def fit():
        params = (jnp.zeros(d, x.dtype), jnp.zeros((), x.dtype))

        def step(_, params):
            g = jax.grad(loss_fn)(params)
            return jax.tree_util.tree_map(lambda p, gg: p - lr * gg,
                                          params, g)
        return jax.lax.fori_loop(0, steps, step, params)

    w, b = fit()
    return {"w": w, "b": b, "mean": mean,
            "scale": jnp.sqrt(var + 1e-6)}


def predict_logistic(model, x):
    xs = (x - model["mean"]) / model["scale"]
    return jax.nn.sigmoid(xs @ model["w"] + model["b"])


# ---------------------------------------------------------------------------
# Gradient-boosted trees (the XGBoost-on-Spark handoff, BASELINE config 4)
# ---------------------------------------------------------------------------


def train_gbt(x, y, mask, *, n_trees: int = 20, max_depth: int = 4,
              n_bins: int = 32, learning_rate: float = 0.3,
              reg_lambda: float = 1.0, objective: str = "binary"):
    """Histogram-based gradient-boosted trees trained ENTIRELY on device —
    the consumer the reference hands query output to via XGBoost-on-Spark
    (docs/ml-integration.md; ColumnarRdd.scala:41-49 -> here a jax pytree).

    XLA-shaped like the reference's GPU hist algorithm: features quantize
    to ``n_bins`` once; every level builds (node, feature, bin)
    gradient/hessian histograms with one ``segment_sum`` scatter, split
    gains come from bin cumsums, and trees grow level-wise to a STATIC
    ``max_depth`` — no
    data-dependent control flow, one compiled program for the whole
    boosting loop. Masked rows carry zero gradients.

    objective: "binary" (logistic) or "regression" (squared error).
    Returns a model dict for :func:`predict_gbt`.
    """
    n, d = x.shape
    xf = x.astype(jnp.float32)
    m = mask.astype(jnp.float32)

    # -- quantile binning (once) -------------------------------------------
    xm = jnp.where(mask[:, None], xf, jnp.nan)
    qs = jnp.linspace(0.0, 1.0, n_bins + 1)[1:-1]
    edges = jnp.nanquantile(xm, qs, axis=0)          # [n_bins-1, d]
    edges = jnp.where(jnp.isnan(edges), jnp.inf, edges)
    bins = jax.vmap(jnp.searchsorted, in_axes=(1, 1))(
        edges, xf).astype(jnp.int32).T               # [n, d] in 0..n_bins-1

    max_w = 1 << (max_depth - 1)
    yf = y.astype(jnp.float32)

    def fit_tree(g, h):
        node = jnp.zeros(n, jnp.int32)
        feats = jnp.zeros((max_depth, max_w), jnp.int32)
        ths = jnp.zeros((max_depth, max_w), jnp.int32)
        fidx = jnp.arange(d, dtype=jnp.int32)
        rows = jnp.arange(n, dtype=jnp.int32)
        for depth in range(max_depth):
            n_nodes = 1 << depth
            flat = ((node[:, None] * d + fidx[None, :]) * n_bins
                    + bins)                          # [n, d]
            segs = n_nodes * d * n_bins
            G = jax.ops.segment_sum(
                jnp.broadcast_to(g[:, None], (n, d)).reshape(-1),
                flat.reshape(-1), num_segments=segs
            ).reshape(n_nodes, d, n_bins)
            H = jax.ops.segment_sum(
                jnp.broadcast_to(h[:, None], (n, d)).reshape(-1),
                flat.reshape(-1), num_segments=segs
            ).reshape(n_nodes, d, n_bins)
            Gc = jnp.cumsum(G, axis=2)[:, :, :-1]    # left sums per split
            Hc = jnp.cumsum(H, axis=2)[:, :, :-1]
            Gt = jnp.sum(G, axis=2)[:, :, None]
            Ht = jnp.sum(H, axis=2)[:, :, None]
            GR, HR = Gt - Gc, Ht - Hc
            gain = (Gc ** 2 / (Hc + reg_lambda)
                    + GR ** 2 / (HR + reg_lambda)
                    - Gt ** 2 / (Ht + reg_lambda))
            gain_f = gain.reshape(n_nodes, d * (n_bins - 1))
            best = jnp.argmax(gain_f, axis=1)
            bf = (best // (n_bins - 1)).astype(jnp.int32)
            bt = (best % (n_bins - 1)).astype(jnp.int32)
            feats = feats.at[depth, :n_nodes].set(bf)
            ths = ths.at[depth, :n_nodes].set(bt)
            go_right = bins[rows, bf[node]] > bt[node]
            node = node * 2 + go_right.astype(jnp.int32)
        n_leaves = 1 << max_depth
        Gl = jax.ops.segment_sum(g, node, num_segments=n_leaves)
        Hl = jax.ops.segment_sum(h, node, num_segments=n_leaves)
        leaf = -Gl / (Hl + reg_lambda)
        return feats, ths, leaf, leaf[node]

    def boost():
        F0 = jnp.zeros(n, jnp.float32)

        def step(carry, _):
            F, = carry
            if objective == "binary":
                p = jax.nn.sigmoid(F)
                g = (p - yf) * m
                h = jnp.maximum(p * (1 - p), 1e-6) * m
            else:
                g = (F - yf) * m
                h = m
            feats, ths, leaf, pred = fit_tree(g, h)
            return (F + learning_rate * pred,), (feats, ths, leaf)

        (_,), trees = jax.lax.scan(step, (F0,), None, length=n_trees)
        return trees

    feats, ths, leaves = jax.jit(boost)()
    return {"edges": edges, "feats": feats, "ths": ths, "leaves": leaves,
            "lr": learning_rate, "max_depth": max_depth,
            "objective": objective}


def predict_gbt(model, x):
    """Apply a :func:`train_gbt` model on device: re-bin, walk every
    tree's level arrays by gathers, sum leaf values."""
    xf = x.astype(jnp.float32)
    n = xf.shape[0]
    bins = jax.vmap(jnp.searchsorted, in_axes=(1, 1))(
        model["edges"], xf).astype(jnp.int32).T
    rows = jnp.arange(n, dtype=jnp.int32)
    max_depth = model["max_depth"]

    def one_tree(carry, tree):
        feats, ths, leaf = tree
        node = jnp.zeros(n, jnp.int32)
        for depth in range(max_depth):
            bf = feats[depth][node]
            bt = ths[depth][node]
            go_right = bins[rows, bf] > bt
            node = node * 2 + go_right.astype(jnp.int32)
        return carry + model["lr"] * leaf[node], None

    F, _ = jax.lax.scan(one_tree, jnp.zeros(n, jnp.float32),
                        (model["feats"], model["ths"], model["leaves"]))
    if model["objective"] == "binary":
        return jax.nn.sigmoid(F)
    return F
