"""Zero-copy ML handoff — the ``ColumnarRdd`` analog.

The reference exports the GPU-resident columnar output of a query directly
to ML frameworks (XGBoost) with no host round trip
(``ColumnarRdd.scala:41-49``, ``InternalColumnarRddConverter.scala``; gated
by ``spark.rapids.sql.exportColumnarRdd``, RapidsConf.scala:329). The TPU
analog is stronger: a query's result batches are already ``jax.Array``
columns in HBM, so the handoff to a JAX trainer is literally passing
pytrees — :func:`feature_matrix` packs them into the dense ``[n, d]``
matrix an ML loop wants via one traced kernel, and
:func:`train_logistic_regression` is a reference consumer that never
leaves the device.

``DataFrame.to_device_batches()`` (plan/logical.py) is the entry point;
it requires ``spark.rapids.sql.exportColumnarRdd`` like the reference.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .. import types as T
from ..data.batch import ColumnarBatch
from ..exec.execs import _coalesce_device
from ..utils.kernel_cache import cached_kernel, kernel_key


def feature_matrix(batches: Sequence[ColumnarBatch],
                   feature_cols: Sequence[str],
                   label_col: Optional[str] = None,
                   dtype=jnp.float32):
    """Pack device batches into ``(X[cap, d], y[cap], row_mask[cap])``.

    Entirely on-device: one capacity-sized concat plus a stacking kernel —
    no host transfer anywhere (the zero-copy contract of the reference's
    ColumnarRdd). Rows with a null in any used column are masked out, the
    standard ML semantic. The row count stays traced; consumers use
    ``row_mask`` (static shapes) instead of slicing."""
    batches = list(batches)
    if not batches:
        raise ValueError("no batches to export")
    batch = _coalesce_device(batches)
    schema = batch.schema
    f_idx = tuple(schema.index_of(c) for c in feature_cols)
    l_idx = schema.index_of(label_col) if label_col is not None else None

    def build():
        def pack(b: ColumnarBatch):
            live = b.row_mask()
            cols = []
            valid = live
            for i in f_idx:
                c = b.columns[i]
                cols.append(c.data.astype(dtype))
                valid = valid & c.validity
            x = jnp.stack(cols, axis=1)
            if l_idx is not None:
                lc = b.columns[l_idx]
                y = lc.data.astype(dtype)
                valid = valid & lc.validity
            else:
                y = jnp.zeros(b.capacity, dtype)
            return x, y, valid
        return pack
    pack = cached_kernel("ml_feature_matrix",
                         kernel_key(schema, f_idx, l_idx, str(dtype)),
                         build)
    return pack(batch)


def train_logistic_regression(x, y, mask, steps: int = 100, lr: float = 0.1):
    """Reference on-device consumer: masked logistic regression by full-batch
    gradient descent, one jitted training loop (the BASELINE.md config-4
    "query output -> JAX trainer" shape). Returns the fitted model dict
    for :func:`predict_logistic`."""
    d = x.shape[1]
    m = mask.astype(x.dtype)
    n = jnp.maximum(jnp.sum(m), 1.0)
    # Feature standardization keeps GD well-conditioned for raw SQL outputs.
    mean = jnp.sum(x * m[:, None], axis=0) / n
    var = jnp.sum(((x - mean) ** 2) * m[:, None], axis=0) / n
    xs = (x - mean) / jnp.sqrt(var + 1e-6)

    def loss_fn(params):
        w, b = params
        z = xs @ w + b
        p = jax.nn.sigmoid(z)
        eps = 1e-7
        bce = -(y * jnp.log(p + eps) + (1 - y) * jnp.log(1 - p + eps))
        return jnp.sum(bce * m) / n

    @jax.jit
    def fit():
        params = (jnp.zeros(d, x.dtype), jnp.zeros((), x.dtype))

        def step(_, params):
            g = jax.grad(loss_fn)(params)
            return jax.tree_util.tree_map(lambda p, gg: p - lr * gg,
                                          params, g)
        return jax.lax.fori_loop(0, steps, step, params)

    w, b = fit()
    return {"w": w, "b": b, "mean": mean,
            "scale": jnp.sqrt(var + 1e-6)}


def predict_logistic(model, x):
    xs = (x - model["mean"]) / model["scale"]
    return jax.nn.sigmoid(xs @ model["w"] + model["b"])
