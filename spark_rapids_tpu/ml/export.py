"""Zero-copy ML handoff — the ``ColumnarRdd`` analog.

The reference exports the GPU-resident columnar output of a query directly
to ML frameworks (XGBoost) with no host round trip
(``ColumnarRdd.scala:41-49``, ``InternalColumnarRddConverter.scala``; gated
by ``spark.rapids.sql.exportColumnarRdd``, RapidsConf.scala:329). The TPU
analog is stronger: a query's result batches are already ``jax.Array``
columns in HBM, so the handoff to a JAX trainer is literally passing
pytrees — :func:`feature_matrix` packs them into the dense ``[n, d]``
matrix an ML loop wants via one traced kernel, and the trainers below
never leave the device.

``DataFrame.to_device_batches()`` (plan/logical.py) is the entry point;
it requires ``spark.rapids.sql.exportColumnarRdd`` like the reference.

Compile discipline (ISSUE 14 satellite): every trainer routes its jit
through :func:`~..utils.kernel_cache.cached_kernel` keyed on the static
hyperparameters — re-training the same shape NEVER re-traces (visible to
the PR-2/PR-6 compile-once counters via ``compile_status()``), and each
build is noted in the compile manifest (compile/persist.py) when the
persistent cache is on.

Scaling (tentpole piece 2): :func:`sharded_feature_matrix` places the
exported ``(X, y, mask)`` across the device mesh (``parallel/mesh.py``
``shard_map`` idiom) and :func:`train_gbt_sharded` /
:func:`train_logistic_regression_sharded` fit data-parallel — per-shard
gradient/histogram partial sums combined with ``lax.psum`` over the
``part`` axis — so training scales past one chip's HBM while staying
numerically equivalent to the single-chip fit (tolerance of the float
reduction-order difference; exact on a one-device mesh).

Fault seams: ``ml.featureMatrix`` / ``ml.train`` register with the
deterministic fault injector (``spark.rapids.tpu.test.faultInjection.*``
``sites=ml.`` matches them all), so the ETL→train→score pipeline runs
under the same injected-OOM matrices as the rest of the engine.
"""

from __future__ import annotations

import functools
import time
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from .. import types as T
from ..data.batch import ColumnarBatch
from ..exec.execs import _coalesce_device
from ..parallel.mesh import PART_AXIS, make_mesh, partitioned, shard_map
from ..utils.fault_injection import maybe_inject
from ..utils.kernel_cache import cached_kernel, kernel_key
from . import registry as _reg


def _note_manifest(kind: str, key: tuple, shape) -> None:
    """Record a trainer build in the compile manifest (when the
    persistent cache is on) so restarted processes see which trainer
    (hyperparams, input shape) pairs this one compiled — the PR-2
    manifest discipline extended to the ML layer. Foreign entries are
    inert to warm-up (it replays only its own fused-program hashes)."""
    from ..compile import persist
    m = persist.manifest()
    if m is None:
        return
    try:
        m.record(persist.plan_hash((kind, key)),
                 tuple(int(s) for s in shape))
    except (OSError, TypeError, ValueError):
        pass  # manifest is an aid, never a gate


def _mesh_token(mesh) -> tuple:
    """Cache-key identity of a mesh: the ordered device ids (two meshes
    of the SAME size over different devices must not share a cached
    shard_map kernel — the build closure captures the mesh object)."""
    return tuple(int(getattr(d, "id", i))
                 for i, d in enumerate(mesh.devices.flat))


def feature_matrix(batches: Sequence[ColumnarBatch],
                   feature_cols: Sequence[str],
                   label_col: Optional[str] = None,
                   dtype=jnp.float32, ctx=None):
    """Pack device batches into ``(X[cap, d], y[cap], row_mask[cap])``.

    Entirely on-device: one capacity-sized concat plus a stacking kernel —
    no host transfer anywhere (the zero-copy contract of the reference's
    ColumnarRdd; the only host traffic is one scalar sync counting the
    exported rows for the ``engine.ml`` profile section). Rows with a
    null in any used column are masked out, the standard ML semantic. The
    row count stays traced; consumers use ``row_mask`` (static shapes)
    instead of slicing.

    A query that legitimately returns ZERO batches yields a SHAPED empty
    ``(X[0, d], y[0], mask[0])`` instead of crashing the handoff — the
    downstream trainer/scorer sees an ordinary (empty) matrix."""
    batches = list(batches)
    feature_cols = list(feature_cols)
    if not feature_cols:
        raise ValueError("feature_matrix needs at least one feature column")
    maybe_inject(ctx, "ml.featureMatrix")
    d = len(feature_cols)
    if not batches:
        return (jnp.zeros((0, d), dtype), jnp.zeros((0,), dtype),
                jnp.zeros((0,), jnp.bool_))
    batch = _coalesce_device(batches)
    schema = batch.schema
    f_idx = tuple(schema.index_of(c) for c in feature_cols)
    l_idx = schema.index_of(label_col) if label_col is not None else None

    def build():
        def pack(b: ColumnarBatch):
            live = b.row_mask()
            cols = []
            valid = live
            for i in f_idx:
                c = b.columns[i]
                cols.append(c.data.astype(dtype))
                valid = valid & c.validity
            x = jnp.stack(cols, axis=1)
            if l_idx is not None:
                lc = b.columns[l_idx]
                y = lc.data.astype(dtype)
                valid = valid & lc.validity
            else:
                y = jnp.zeros(b.capacity, dtype)
            return x, y, valid
        return pack
    pack = cached_kernel("ml_feature_matrix",
                         kernel_key(schema, f_idx, l_idx, str(dtype)),
                         build)
    x, y, mask = pack(batch)
    _reg.note("export_rows", int(jax.device_get(jnp.sum(mask))))
    return x, y, mask


def sharded_feature_matrix(batches: Sequence[ColumnarBatch],
                           feature_cols: Sequence[str],
                           label_col: Optional[str] = None,
                           dtype=jnp.float32, mesh=None, ctx=None):
    """:func:`feature_matrix` placed ACROSS the device mesh for
    data-parallel training: the leading (row) dimension of ``X``/``y``/
    ``mask`` shards over the canonical ``part`` axis
    (``parallel/mesh.py``), padded so every shard is equal-sized (padding
    lanes are dead by the mask invariant). Returns
    ``(x, y, mask, mesh)`` — feed to the ``*_sharded`` trainers."""
    mesh = mesh or make_mesh()
    x, y, mask = feature_matrix(batches, feature_cols, label_col, dtype,
                                ctx=ctx)
    n_parts = int(mesh.devices.size)
    pad = (-x.shape[0]) % n_parts
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
        y = jnp.pad(y, (0, pad))
        mask = jnp.pad(mask, (0, pad))
    shard = partitioned(mesh)
    return (jax.device_put(x, shard), jax.device_put(y, shard),
            jax.device_put(mask, shard), mesh)


# ---------------------------------------------------------------------------
# Logistic regression
# ---------------------------------------------------------------------------


def _logreg_fit_fn(steps: int, lr: float):
    """Single-chip masked logistic-regression fit (full-batch GD with
    feature standardization); returns (w, b, mean, scale)."""
    def fit(x, y, mask):
        d = x.shape[1]
        m = mask.astype(x.dtype)
        n = jnp.maximum(jnp.sum(m), 1.0)
        mean = jnp.sum(x * m[:, None], axis=0) / n
        var = jnp.sum(((x - mean) ** 2) * m[:, None], axis=0) / n
        xs = (x - mean) / jnp.sqrt(var + 1e-6)

        def loss_fn(params):
            w, b = params
            z = xs @ w + b
            p = jax.nn.sigmoid(z)
            eps = 1e-7
            bce = -(y * jnp.log(p + eps) + (1 - y) * jnp.log(1 - p + eps))
            return jnp.sum(bce * m) / n

        def step(_, params):
            g = jax.grad(loss_fn)(params)
            return jax.tree_util.tree_map(lambda p, gg: p - lr * gg,
                                          params, g)
        w, b = jax.lax.fori_loop(
            0, steps, step, (jnp.zeros(d, x.dtype), jnp.zeros((), x.dtype)))
        return w, b, mean, jnp.sqrt(var + 1e-6)
    return fit


def _finish_train(kind: str, key: tuple, x, out, t0: float):
    """Shared trainer epilogue: fence for an honest wall-clock, feed the
    engine.ml counters, note the build in the compile manifest."""
    jax.block_until_ready(out)
    _reg.note("train_seconds", time.perf_counter() - t0)
    _note_manifest(kind, key, x.shape)
    return out


def train_logistic_regression(x, y, mask, steps: int = 100, lr: float = 0.1,
                              ctx=None):
    """Reference on-device consumer: masked logistic regression by
    full-batch gradient descent, one cached jitted training loop (the
    BASELINE.md config-4 "query output -> JAX trainer" shape). Returns
    the fitted model dict for :func:`predict_logistic`."""
    maybe_inject(ctx, "ml.train")
    key = kernel_key("logreg", int(steps), float(lr))
    fit = cached_kernel("ml_train_logreg", key,
                        lambda: _logreg_fit_fn(int(steps), float(lr)))
    t0 = time.perf_counter()
    w, b, mean, scale = _finish_train("ml_train_logreg", key, x,
                                      fit(x, y, mask), t0)
    return {"w": w, "b": b, "mean": mean, "scale": scale}


def train_logistic_regression_sharded(x, y, mask, steps: int = 100,
                                      lr: float = 0.1, mesh=None, ctx=None):
    """Data-parallel :func:`train_logistic_regression` over the mesh:
    per-shard moment/gradient partial sums combined with ``lax.psum``
    over the ``part`` axis each step (the shard_map idiom of
    parallel/distributed.py), so the full matrix never needs to fit one
    chip. Numerically equivalent to the single-chip fit up to float
    reduction order (exact on a one-device mesh)."""
    mesh = mesh or make_mesh()
    maybe_inject(ctx, "ml.train")
    steps, lr = int(steps), float(lr)
    key = kernel_key("logreg_sharded", steps, lr, _mesh_token(mesh))

    def build():
        from jax.sharding import PartitionSpec
        spec = PartitionSpec(PART_AXIS)
        rep = PartitionSpec()

        @functools.partial(
            shard_map, mesh=mesh, in_specs=(spec, spec, spec),
            out_specs=(rep, rep, rep, rep), check_rep=False)
        def fit(xs, ys, ms):
            def psum(a):
                return jax.lax.psum(a, PART_AXIS)
            d = xs.shape[1]
            m = ms.astype(xs.dtype)
            n = jnp.maximum(psum(jnp.sum(m)), 1.0)
            mean = psum(jnp.sum(xs * m[:, None], axis=0)) / n
            var = psum(jnp.sum(((xs - mean) ** 2) * m[:, None], axis=0)) / n
            xstd = (xs - mean) / jnp.sqrt(var + 1e-6)

            def loss_sum(params):
                # LOCAL unnormalized loss; its grad psums below, and the
                # shared 1/n rescale reproduces the single-chip gradient.
                w, b = params
                p = jax.nn.sigmoid(xstd @ w + b)
                eps = 1e-7
                bce = -(ys * jnp.log(p + eps)
                        + (1 - ys) * jnp.log(1 - p + eps))
                return jnp.sum(bce * m)

            def step(_, params):
                g = jax.tree_util.tree_map(psum,
                                           jax.grad(loss_sum)(params))
                return jax.tree_util.tree_map(
                    lambda p, gg: p - lr * gg / n, params, g)
            w, b = jax.lax.fori_loop(
                0, steps, step,
                (jnp.zeros(d, xs.dtype), jnp.zeros((), xs.dtype)))
            return w, b, mean, jnp.sqrt(var + 1e-6)
        return fit
    fit = cached_kernel("ml_train_logreg_sharded", key, build)
    t0 = time.perf_counter()
    w, b, mean, scale = _finish_train("ml_train_logreg_sharded", key, x,
                                      fit(x, y, mask), t0)
    return {"w": w, "b": b, "mean": mean, "scale": scale}


def predict_logistic(model, x):
    xs = (x - model["mean"]) / model["scale"]
    return jax.nn.sigmoid(xs @ model["w"] + model["b"])


# ---------------------------------------------------------------------------
# Gradient-boosted trees (the XGBoost-on-Spark handoff, BASELINE config 4)
# ---------------------------------------------------------------------------


def _quantile_edges(xf, mask, n_bins: int):
    """Per-feature quantile bin edges over the masked matrix (global
    semantics — under GSPMD on a sharded matrix XLA computes the same
    global quantiles, so sharded and single-chip fits bin identically)."""
    xm = jnp.where(mask[:, None], xf, jnp.nan)
    qs = jnp.linspace(0.0, 1.0, n_bins + 1)[1:-1]
    edges = jnp.nanquantile(xm, qs, axis=0)          # [n_bins-1, d]
    return jnp.where(jnp.isnan(edges), jnp.inf, edges)


def _bin_features(edges, xf):
    return jax.vmap(jnp.searchsorted, in_axes=(1, 1))(
        edges, xf).astype(jnp.int32).T               # [n, d] in 0..n_bins-1


def _grad_hess(F, yf, m, objective: str):
    if objective == "binary":
        p = jax.nn.sigmoid(F)
        g = (p - yf) * m
        h = jnp.maximum(p * (1 - p), 1e-6) * m
    else:
        g = (F - yf) * m
        h = m
    return g, h


def _fit_tree(bins, g, h, n_bins: int, max_depth: int, reg_lambda: float,
              reduce):
    """One level-wise tree over pre-binned features. ``reduce`` combines
    histogram/leaf partial sums across data shards: identity on a single
    chip, ``lax.psum`` over the part axis in the sharded fit — split
    decisions are then computed REPLICATED from the global histograms
    while row→node assignment stays local."""
    n, d = bins.shape
    max_w = 1 << (max_depth - 1)
    node = jnp.zeros(n, jnp.int32)
    feats = jnp.zeros((max_depth, max_w), jnp.int32)
    ths = jnp.zeros((max_depth, max_w), jnp.int32)
    fidx = jnp.arange(d, dtype=jnp.int32)
    rows = jnp.arange(n, dtype=jnp.int32)
    for depth in range(max_depth):
        n_nodes = 1 << depth
        flat = ((node[:, None] * d + fidx[None, :]) * n_bins
                + bins)                          # [n, d]
        segs = n_nodes * d * n_bins
        G = reduce(jax.ops.segment_sum(
            jnp.broadcast_to(g[:, None], (n, d)).reshape(-1),
            flat.reshape(-1), num_segments=segs
        ).reshape(n_nodes, d, n_bins))
        H = reduce(jax.ops.segment_sum(
            jnp.broadcast_to(h[:, None], (n, d)).reshape(-1),
            flat.reshape(-1), num_segments=segs
        ).reshape(n_nodes, d, n_bins))
        Gc = jnp.cumsum(G, axis=2)[:, :, :-1]    # left sums per split
        Hc = jnp.cumsum(H, axis=2)[:, :, :-1]
        Gt = jnp.sum(G, axis=2)[:, :, None]
        Ht = jnp.sum(H, axis=2)[:, :, None]
        GR, HR = Gt - Gc, Ht - Hc
        gain = (Gc ** 2 / (Hc + reg_lambda)
                + GR ** 2 / (HR + reg_lambda)
                - Gt ** 2 / (Ht + reg_lambda))
        gain_f = gain.reshape(n_nodes, d * (n_bins - 1))
        best = jnp.argmax(gain_f, axis=1)
        bf = (best // (n_bins - 1)).astype(jnp.int32)
        bt = (best % (n_bins - 1)).astype(jnp.int32)
        feats = feats.at[depth, :n_nodes].set(bf)
        ths = ths.at[depth, :n_nodes].set(bt)
        go_right = bins[rows, bf[node]] > bt[node]
        node = node * 2 + go_right.astype(jnp.int32)
    n_leaves = 1 << max_depth
    Gl = reduce(jax.ops.segment_sum(g, node, num_segments=n_leaves))
    Hl = reduce(jax.ops.segment_sum(h, node, num_segments=n_leaves))
    leaf = -Gl / (Hl + reg_lambda)
    return feats, ths, leaf, leaf[node]


def _boost(bins, yf, m, n_trees: int, max_depth: int, n_bins: int,
           learning_rate: float, reg_lambda: float, objective: str, reduce):
    n = bins.shape[0]
    F0 = jnp.zeros(n, jnp.float32)

    def step(carry, _):
        F, = carry
        g, h = _grad_hess(F, yf, m, objective)
        feats, ths, leaf, pred = _fit_tree(bins, g, h, n_bins, max_depth,
                                           reg_lambda, reduce)
        return (F + learning_rate * pred,), (feats, ths, leaf)

    (_,), trees = jax.lax.scan(step, (F0,), None, length=n_trees)
    return trees


def train_gbt(x, y, mask, *, n_trees: int = 20, max_depth: int = 4,
              n_bins: int = 32, learning_rate: float = 0.3,
              reg_lambda: float = 1.0, objective: str = "binary", ctx=None):
    """Histogram-based gradient-boosted trees trained ENTIRELY on device —
    the consumer the reference hands query output to via XGBoost-on-Spark
    (docs/ml-integration.md; ColumnarRdd.scala:41-49 -> here a jax pytree).

    XLA-shaped like the reference's GPU hist algorithm: features quantize
    to ``n_bins`` once; every level builds (node, feature, bin)
    gradient/hessian histograms with one ``segment_sum`` scatter, split
    gains come from bin cumsums, and trees grow level-wise to a STATIC
    ``max_depth`` — no data-dependent control flow, one compiled program
    for the whole boosting loop, cached per hyperparameter signature
    (re-training the same shape never re-traces).

    objective: "binary" (logistic) or "regression" (squared error).
    Returns a model dict for :func:`predict_gbt`.
    """
    maybe_inject(ctx, "ml.train")
    hyper = (int(n_trees), int(max_depth), int(n_bins), float(learning_rate),
             float(reg_lambda), str(objective))
    key = kernel_key("gbt", *hyper)

    def build():
        nt, md, nb, lr, rl, obj = hyper

        def fit(x, y, mask):
            xf = x.astype(jnp.float32)
            m = mask.astype(jnp.float32)
            yf = y.astype(jnp.float32)
            edges = _quantile_edges(xf, mask, nb)
            bins = _bin_features(edges, xf)
            feats, ths, leaves = _boost(bins, yf, m, nt, md, nb, lr, rl,
                                        obj, lambda a: a)
            return edges, feats, ths, leaves
        return fit
    fit = cached_kernel("ml_train_gbt", key, build)
    t0 = time.perf_counter()
    edges, feats, ths, leaves = _finish_train("ml_train_gbt", key, x,
                                              fit(x, y, mask), t0)
    return {"edges": edges, "feats": feats, "ths": ths, "leaves": leaves,
            "lr": float(learning_rate), "max_depth": int(max_depth),
            "objective": str(objective)}


def train_gbt_sharded(x, y, mask, *, mesh=None, n_trees: int = 20,
                      max_depth: int = 4, n_bins: int = 32,
                      learning_rate: float = 0.3, reg_lambda: float = 1.0,
                      objective: str = "binary", ctx=None):
    """Data-parallel :func:`train_gbt` over the mesh: bin edges come from
    the GLOBAL quantiles of the sharded matrix (GSPMD — identical to the
    single-chip edges), then each boosting level builds per-shard
    (node, feature, bin) histograms and ``lax.psum``-combines them over
    the ``part`` axis, so split decisions replicate while rows never
    leave their shard (the shard_map idiom of parallel/distributed.py).
    Equivalent to the single-chip fit up to float reduction order (exact
    trees on a one-device mesh)."""
    mesh = mesh or make_mesh()
    maybe_inject(ctx, "ml.train")
    hyper = (int(n_trees), int(max_depth), int(n_bins), float(learning_rate),
             float(reg_lambda), str(objective))
    key = kernel_key("gbt_sharded", *hyper, _mesh_token(mesh))

    def build():
        from jax.sharding import PartitionSpec
        nt, md, nb, lr, rl, obj = hyper
        spec = PartitionSpec(PART_AXIS)
        rep = PartitionSpec()

        def fit(x, y, mask):
            xf = x.astype(jnp.float32)
            edges = _quantile_edges(xf, mask, nb)

            @functools.partial(
                shard_map, mesh=mesh, in_specs=(spec, spec, spec, rep),
                out_specs=(rep, rep, rep), check_rep=False)
            def boost_shards(xs, ys, ms, edges_):
                def psum(a):
                    return jax.lax.psum(a, PART_AXIS)
                bins = _bin_features(edges_, xs.astype(jnp.float32))
                return _boost(bins, ys.astype(jnp.float32),
                              ms.astype(jnp.float32), nt, md, nb, lr, rl,
                              obj, psum)
            feats, ths, leaves = boost_shards(xf, y, mask, edges)
            return edges, feats, ths, leaves
        return fit
    fit = cached_kernel("ml_train_gbt_sharded", key, build)
    t0 = time.perf_counter()
    edges, feats, ths, leaves = _finish_train("ml_train_gbt_sharded", key,
                                              x, fit(x, y, mask), t0)
    return {"edges": edges, "feats": feats, "ths": ths, "leaves": leaves,
            "lr": float(learning_rate), "max_depth": int(max_depth),
            "objective": str(objective)}


def predict_gbt(model, x):
    """Apply a :func:`train_gbt` model on device: re-bin, walk every
    tree's level arrays by gathers, sum leaf values."""
    xf = x.astype(jnp.float32)
    n = xf.shape[0]
    bins = jax.vmap(jnp.searchsorted, in_axes=(1, 1))(
        model["edges"], xf).astype(jnp.int32).T
    rows = jnp.arange(n, dtype=jnp.int32)
    max_depth = model["max_depth"]

    def one_tree(carry, tree):
        feats, ths, leaf = tree
        node = jnp.zeros(n, jnp.int32)
        for depth in range(max_depth):
            bf = feats[depth][node]
            bt = ths[depth][node]
            go_right = bins[rows, bf] > bt
            node = node * 2 + go_right.astype(jnp.int32)
        return carry + model["lr"] * leaf[node], None

    F, _ = jax.lax.scan(one_tree, jnp.zeros(n, jnp.float32),
                        (model["feats"], model["ths"], model["leaves"]))
    if model["objective"] == "binary":
        return jax.nn.sigmoid(F)
    return F
