"""Session-scoped ML model registry — trained models as first-class,
SPILLABLE engine citizens (the ml-integration tentpole, pieces 1 and 3).

A registered model is not a Python object floating beside the engine: its
array leaves are packed into one byte-exact device buffer and registered
in the session's :class:`~..memory.spill.BufferCatalog` with a QoS-stamped
owner (``spark.rapids.tpu.tenantId``), exactly like a query's build table.
That buys the whole memory discipline for free:

* a concurrent query's OOM-retry ladder (memory/retry.py) can evict a
  cold model to host/disk through the PR-11 spill state machine, in QoS
  victim order — training/model residency that "steals" HBM resolves
  through spill + retry instead of crashing either side;
* ``spill_tenant_over_budget`` (the serving layer's budget enforcement)
  sees model bytes as the owning tenant's residency;
* scoring a spilled model restores it through ``acquire_batch``'s tier
  climb, wrapped in the retry taxonomy (site ``ml.modelAcquire``).

The registry also carries the **feature-schema contract**: every model
records how many features it consumes (``n_features``), and both the
DataFrame API (`with_model_score`) and the plan-lint pass
(analysis/plan_lint.py) verify the operator's feature list against it —
a mismatched handoff fails at plan time, not as a shape error mid-query.

Training sets (the ``(X, y, mask)`` pytree from
:func:`~.export.feature_matrix`) get the same treatment via
:meth:`ModelRegistry.put_training` / :meth:`ModelRegistry.take_training`,
so exported matrices awaiting a trainer are spillable too.

Packing is byte-exact: every array leaf is bitcast to an ``int8`` lane
(``jax.lax.bitcast_convert_type``), concatenated, and padded onto a
bucket-ladder capacity — spill/restore round-trips reproduce the model
bit for bit (asserted by tests/test_ml_pipeline.py).

Observability: module-wide counters (export rows, train seconds, model
bytes, registrations) feed the ``engine.ml`` section of every
QueryProfile (metrics/profile.py, docs/monitoring.md).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..data.batch import ColumnarBatch
from ..data.column import DeviceColumn, bucket_capacity
from ..utils import lockdep

# ---------------------------------------------------------------------------
# Process-wide ML stats (engine.ml QueryProfile section reads deltas)
# ---------------------------------------------------------------------------

_STATS_LOCK = lockdep.lock("ml_registry._STATS_LOCK")
_STATS = {"export_rows": 0, "train_seconds": 0.0, "model_bytes": 0,
          "models_registered": 0}


def stats() -> dict:
    """Snapshot of the process-wide ML counters (deltas become the
    ``engine.ml`` QueryProfile section — the pallas-stats idiom)."""
    with _STATS_LOCK:
        return dict(_STATS)


def note(name: str, amount) -> None:
    with _STATS_LOCK:
        _STATS[name] = _STATS.get(name, 0) + amount


# ---------------------------------------------------------------------------
# Byte-exact pytree packing (one int8 lane per model / training set)
# ---------------------------------------------------------------------------

_PACK_SCHEMA = T.Schema([T.StructField("ml_bytes", T.BYTE, False)])

#: model kinds the score operator understands; each names its predict twin
#: in ml/export.py.
KINDS = ("gbt", "logistic")


def infer_kind(model: dict) -> str:
    if "feats" in model and "leaves" in model:
        return "gbt"
    if "w" in model and "b" in model:
        return "logistic"
    raise ValueError(
        "cannot infer model kind: expected a train_gbt dict (feats/leaves) "
        "or a train_logistic_regression dict (w/b)")


def _is_array(v) -> bool:
    return isinstance(v, (jax.Array, np.ndarray)) or (
        hasattr(v, "shape") and hasattr(v, "dtype"))


def pack_arrays(arrays: Dict[str, jax.Array]
                ) -> Tuple[ColumnarBatch, tuple, int]:
    """Pack named array leaves into ONE int8 device column (byte-exact
    bitcast), padded to a bucket-ladder capacity. Returns
    ``(batch, leaf_meta, payload_bytes)`` where ``leaf_meta`` is the
    static recipe :func:`unpack_arrays` rebuilds the pytree from."""
    metas, parts, total = [], [], 0
    for key in sorted(arrays):
        a = jnp.asarray(arrays[key])
        orig_dtype = str(a.dtype)
        if a.dtype == jnp.bool_:
            a = a.astype(jnp.int8)
        flat = a.reshape(-1)
        itemsize = np.dtype(a.dtype).itemsize
        nbytes = int(flat.size) * itemsize
        metas.append((key, tuple(int(s) for s in np.shape(arrays[key])),
                      orig_dtype, nbytes))
        if nbytes == 0:
            continue
        b = flat.astype(jnp.int8) if itemsize == 1 else \
            jax.lax.bitcast_convert_type(flat, jnp.int8).reshape(-1)
        parts.append(b)
        total += nbytes
    cap = bucket_capacity(max(total, 1))
    data = jnp.zeros(cap, jnp.int8)
    if parts:
        flat_all = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
        data = data.at[:total].set(flat_all)
    validity = jnp.arange(cap, dtype=jnp.int32) < total
    batch = ColumnarBatch(
        (DeviceColumn(data=data, validity=validity, dtype=T.BYTE),),
        jnp.asarray(total, jnp.int32), _PACK_SCHEMA)
    return batch, tuple(metas), total


def unpack_arrays(batch: ColumnarBatch, leaf_meta: tuple
                  ) -> Dict[str, jax.Array]:
    """Rebuild the named leaves from a packed batch (bit-exact inverse of
    :func:`pack_arrays`; survives any number of spill/restore trips)."""
    flat = batch.columns[0].data
    out: Dict[str, jax.Array] = {}
    off = 0
    for key, shape, dtype_s, nbytes in leaf_meta:
        want_bool = dtype_s == "bool"
        dt = np.dtype("int8" if want_bool else dtype_s)
        if nbytes == 0:
            arr = jnp.zeros(shape, jnp.bool_ if want_bool else dt)
            out[key] = arr
            continue
        seg = jax.lax.slice(flat, (off,), (off + nbytes,))
        if dt.itemsize == 1:
            arr = seg.astype(dt)
        else:
            arr = jax.lax.bitcast_convert_type(
                seg.reshape(-1, dt.itemsize), dt)
        if want_bool:
            arr = arr.astype(jnp.bool_)
        out[key] = arr.reshape(shape)
        off += nbytes
    return out


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ModelMeta:
    """Static contract of one registered model: everything the score
    operator and the plan-lint pass need WITHOUT touching the device."""

    name: str
    kind: str                   # "gbt" | "logistic"
    version: int                # bumps on every re-register of the name
    n_features: int             # the feature-schema contract
    static: tuple               # sorted (key, value) non-array model fields
    leaves: tuple               # pack_arrays leaf_meta
    payload_bytes: int          # exact packed bytes (pre-padding)
    device_bytes: int           # HBM footprint of the padded buffer
    buffer_id: int              # BufferCatalog id


def _n_features(kind: str, arrays: Dict[str, jax.Array]) -> int:
    if kind == "gbt":
        return int(arrays["edges"].shape[1])
    return int(arrays["w"].shape[0])


class ModelRegistry:
    """Session-scoped registry of trained models + parked training sets
    (see module doc). Shared by ``with_conf``-derived sessions, so a
    traced or differently-gated twin scores the same models."""

    def __init__(self, session):
        self._session = session
        self._catalog = session.device_manager.catalog
        self._lock = lockdep.lock("ModelRegistry._lock")
        self._models: Dict[str, ModelMeta] = {}
        self._versions: Dict[str, int] = {}
        #: name -> (buffer_id, leaf_meta) of parked training pytrees
        self._training: Dict[str, Tuple[int, tuple]] = {}
        from ..config import TPU_ML_MAX_MODELS
        self._max_models = int(session.conf.get(TPU_ML_MAX_MODELS))

    # -- helpers ------------------------------------------------------------
    def _owner(self, ctx=None):
        """QoS identity stamped on every registry buffer: the running
        query's tag when available, else a tag for the session tenant —
        either way the catalog's victim selection sees model/training
        bytes as THIS tenant's residency (docs/fault-tolerance.md)."""
        qos = getattr(ctx, "qos", None)
        if qos is not None:
            return qos
        from ..config import TENANT_ID
        from ..memory.spill import QosTag
        try:
            tenant = self._session.conf.get(TENANT_ID) or ""
        except (AttributeError, TypeError):
            tenant = ""
        return QosTag(tenant=tenant)

    def _acquire_ctx(self, ctx):
        """A context the retry combinator can drive spill/backoff
        through; callers outside a query (train scripts) get a bare one
        over the session conf + catalog."""
        if ctx is not None:
            return ctx
        from ..plan.physical import ExecContext
        return ExecContext(self._session.conf, catalog=self._catalog)

    def _acquire_packed(self, buffer_id: int, site: str, ctx) -> ColumnarBatch:
        """Unspill a registry buffer through the retry taxonomy: an OOM
        during the tier-climb restore spills lower-priority buffers and
        retries (PR-4 ladder over the PR-11 state machine)."""
        from ..memory import retry as R
        actx = self._acquire_ctx(ctx)
        [batch] = R.with_retry(
            actx, site, buffer_id,
            lambda bid: self._catalog.acquire_batch(bid),
            split=None, node="ModelRegistry")
        return batch

    # -- models -------------------------------------------------------------
    def register(self, name: str, model: dict, kind: Optional[str] = None,
                 ctx=None) -> ModelMeta:
        """Register (or replace) ``name``. The model's array leaves move
        into one spillable catalog buffer; non-array fields (lr, depth,
        objective) become static metadata. Returns the new meta."""
        from ..metrics import trace as TR
        from ..utils.fault_injection import maybe_inject
        maybe_inject(ctx, "ml.registerModel")
        kind = kind or infer_kind(model)
        if kind not in KINDS:
            raise ValueError(f"unknown model kind {kind!r}; one of {KINDS}")
        arrays = {k: v for k, v in model.items() if _is_array(v)}
        static = {k: v for k, v in model.items() if not _is_array(v)}
        for k, v in static.items():
            if not isinstance(v, (str, int, float, bool, type(None))):
                raise TypeError(
                    f"model field {k!r} is neither an array leaf nor a "
                    f"primitive ({type(v).__name__}); registry models are "
                    "pytrees of arrays plus scalar hyperparameters")
        # Bound pre-check BEFORE any device work: a refused register must
        # be free and side-effect-less (packing + register_batch can spill
        # a neighbor's buffers to make room). Re-checked after the insert
        # races below.
        with self._lock:
            self._check_bound_locked(name)
        batch, leaf_meta, payload = pack_arrays(arrays)
        device_bytes = batch.device_size_bytes
        bid = self._catalog.register_batch(batch, owner=self._owner(ctx))
        old = None
        meta = None
        with self._lock:
            if name in self._models \
                    or len(self._models) < self._max_models:
                version = self._versions.get(name, 0) + 1
                self._versions[name] = version
                old = self._models.get(name)
                meta = ModelMeta(
                    name=name, kind=kind, version=version,
                    n_features=_n_features(kind, arrays),
                    static=tuple(sorted(static.items())), leaves=leaf_meta,
                    payload_bytes=payload, device_bytes=device_bytes,
                    buffer_id=bid)
                self._models[name] = meta
        if meta is None:
            # Lost the pre-check race (a concurrent register filled the
            # registry while we packed): release the just-registered
            # buffer before surfacing — no leaked catalog entries.
            self._catalog.free(bid)
            raise ValueError(
                f"model registry is full ({self._max_models} models); "
                "drop one or raise "
                "spark.rapids.tpu.ml.maxRegisteredModels")
        if old is not None:
            self._catalog.free(old.buffer_id)
        note("model_bytes", device_bytes - (old.device_bytes if old else 0))
        note("models_registered", 1)
        TR.record_event("ml.registerModel", model=name, kind=kind,
                        bytes=device_bytes)
        return meta

    def _check_bound_locked(self, name: str) -> None:
        if name not in self._models \
                and len(self._models) >= self._max_models:
            raise ValueError(
                f"model registry is full ({self._max_models} models); "
                "drop one or raise "
                "spark.rapids.tpu.ml.maxRegisteredModels")

    def meta_maybe(self, name: str) -> Optional[ModelMeta]:
        with self._lock:
            return self._models.get(name)

    def meta(self, name: str) -> ModelMeta:
        m = self.meta_maybe(name)
        if m is None:
            raise KeyError(
                f"model {name!r} is not registered on this session "
                f"(registered: {self.names()}); call "
                "session.ml_models.register(name, model) first")
        return m

    def names(self) -> list:
        with self._lock:
            return sorted(self._models)

    def drop(self, name: str) -> None:
        with self._lock:
            meta = self._models.pop(name, None)
        if meta is not None:
            self._catalog.free(meta.buffer_id)
            note("model_bytes", -meta.device_bytes)

    def acquire(self, name: str, ctx=None) -> Tuple[ModelMeta, dict]:
        """The model's pytree, device-resident (unspilled if needed via
        the retry ladder; site ``ml.modelAcquire``). The returned leaves
        are independent slices — the catalog buffer may spill again
        immediately without affecting them.

        Safe against a CONCURRENT re-register of the same name: that
        frees the version we read between the meta lookup and the
        catalog acquire, which surfaces as a gone-buffer error — the
        loop re-reads and scores the CURRENT version (the same
        latest-wins semantic the planner's plan-time version resolution
        gives). A dropped name surfaces as :meth:`meta`'s KeyError."""
        for _ in range(8):
            meta = self.meta(name)
            try:
                batch = self._acquire_packed(meta.buffer_id,
                                             "ml.modelAcquire", ctx)
            except (KeyError, AssertionError):
                cur = self.meta_maybe(name)
                if cur is None:
                    # Concurrent drop(): surface the friendly model-name
                    # KeyError, not the catalog's internal buffer-id one.
                    self.meta(name)
                if cur is not None and cur.buffer_id != meta.buffer_id:
                    continue  # re-registered mid-acquire: retry on latest
                raise
            model = dict(unpack_arrays(batch, meta.leaves))
            model.update(dict(meta.static))
            return meta, model
        raise RuntimeError(
            f"model {name!r} was re-registered continuously during "
            "acquire (8 attempts)")

    # -- training sets ------------------------------------------------------
    def put_training(self, name: str, arrays: tuple, ctx=None) -> int:
        """Park an exported training pytree (X, y, mask, ...) as ONE
        spillable catalog buffer so matrices awaiting a trainer are
        memory-QoS citizens too. Returns the device byte footprint."""
        from ..utils.fault_injection import maybe_inject
        maybe_inject(ctx, "ml.putTraining")
        named = {f"a{i}": a for i, a in enumerate(arrays)}
        batch, leaf_meta, _payload = pack_arrays(named)
        bid = self._catalog.register_batch(batch, owner=self._owner(ctx))
        with self._lock:
            old = self._training.pop(name, None)
            self._training[name] = (bid, leaf_meta)
        if old is not None:
            self._catalog.free(old[0])
        return batch.device_size_bytes

    def take_training(self, name: str, ctx=None) -> tuple:
        """Reclaim a parked training pytree (restoring through the retry
        ladder; site ``ml.takeTraining``) and release its buffer."""
        with self._lock:
            entry = self._training.pop(name, None)
            parked = sorted(self._training)
        if entry is None:
            raise KeyError(f"no training set {name!r} parked "
                           f"(parked: {parked})")
        bid, leaf_meta = entry
        batch = self._acquire_packed(bid, "ml.takeTraining", ctx)
        out = unpack_arrays(batch, leaf_meta)
        self._catalog.free(bid)
        return tuple(out[f"a{i}"] for i in range(len(out)))
