"""Native host runtime loader.

Compiles the C++ sources in this directory into one shared library on first
import (g++ is part of the toolchain; ~1s, cached by source mtime) and
exposes it through ctypes. Callers use :func:`lib` and must fall back to
their pure-Python path when it returns None — the engine never hard-requires
the native build (same stance as the reference, whose JNI layer is a
packaged dependency, SURVEY.md §2.10).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

from ..utils import lockdep

_DIR = os.path.dirname(os.path.abspath(__file__))
_SOURCES = ["hostkern.cpp", "arena.cpp"]
_SO = os.path.join(_DIR, "_build", "libsrtpu_host.so")

_lock = lockdep.lock("native._lock", io_ok=True)
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _needs_build() -> bool:
    if not os.path.exists(_SO):
        return True
    so_mtime = os.path.getmtime(_SO)
    return any(os.path.getmtime(os.path.join(_DIR, s)) > so_mtime
               for s in _SOURCES)


def _build() -> bool:
    os.makedirs(os.path.dirname(_SO), exist_ok=True)
    srcs = [os.path.join(_DIR, s) for s in _SOURCES]
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
           "-o", _SO] + srcs
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (subprocess.SubprocessError, FileNotFoundError):
        return False


def _declare(lib: ctypes.CDLL) -> ctypes.CDLL:
    i64, u32p, u8p = ctypes.c_int64, \
        ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_uint8)
    for name, args in [
        ("sr_hash_col_i32", [ctypes.c_void_p, u8p, i64, u32p]),
        ("sr_hash_col_i64", [ctypes.c_void_p, u8p, i64, u32p]),
        ("sr_hash_col_f32", [ctypes.c_void_p, u8p, i64, u32p]),
        ("sr_hash_col_f64", [ctypes.c_void_p, u8p, i64, u32p]),
        ("sr_hash_col_str", [ctypes.c_void_p, ctypes.c_void_p, u8p, i64,
                             u32p]),
        ("sr_arena_write", [ctypes.c_void_p, i64, ctypes.c_void_p, i64]),
        ("sr_arena_read", [ctypes.c_void_p, i64, ctypes.c_void_p, i64]),
    ]:
        fn = getattr(lib, name)
        fn.argtypes = args
        fn.restype = None
    lib.sr_arena_create.argtypes = [i64]
    lib.sr_arena_create.restype = ctypes.c_void_p
    lib.sr_arena_destroy.argtypes = [ctypes.c_void_p]
    lib.sr_arena_destroy.restype = None
    lib.sr_arena_base.argtypes = [ctypes.c_void_p]
    lib.sr_arena_base.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.sr_arena_alloc.argtypes = [ctypes.c_void_p, i64]
    lib.sr_arena_alloc.restype = i64
    lib.sr_arena_free.argtypes = [ctypes.c_void_p, i64]
    lib.sr_arena_free.restype = ctypes.c_int
    lib.sr_arena_in_use.argtypes = [ctypes.c_void_p]
    lib.sr_arena_in_use.restype = i64
    return lib


def lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, or None when unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("SPARK_RAPIDS_TPU_NO_NATIVE"):
            return None
        try:
            if _needs_build() and not _build():
                return None
            _lib = _declare(ctypes.CDLL(_SO))
        except OSError:
            _lib = None
    return _lib
