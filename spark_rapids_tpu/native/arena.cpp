// Host arena allocator — the RMM / AddressSpaceAllocator analog.
//
// The reference backs device memory with RMM's pooled allocator
// (GpuDeviceManager.scala:209) and slices host memory through a best-fit
// address-space sub-allocator (AddressSpaceAllocator.scala:22). On TPU the
// device pool belongs to XLA, but the HOST tier of the spill/shuffle chain
// still wants one: thousands of serialized shuffle blocks as individual
// Python bytes objects fragment the heap and double-copy on every spill.
// This arena carves offsets out of ONE contiguous region with a best-fit
// free list and neighbor coalescing.
//
// C ABI for ctypes; no dependencies.

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <map>

namespace {

struct Arena {
  uint8_t* base;
  int64_t capacity;
  // free blocks: offset -> size (ordered, so neighbor coalescing is a
  // map lookup); allocated blocks: offset -> size.
  std::map<int64_t, int64_t> free_blocks;
  std::map<int64_t, int64_t> used;
  int64_t in_use;
};

}  // namespace

extern "C" {

void* sr_arena_create(int64_t capacity) {
  auto* a = new (std::nothrow) Arena();
  if (!a) return nullptr;
  a->base = static_cast<uint8_t*>(std::malloc(capacity));
  if (!a->base) {
    delete a;
    return nullptr;
  }
  a->capacity = capacity;
  a->free_blocks[0] = capacity;
  a->in_use = 0;
  return a;
}

void sr_arena_destroy(void* handle) {
  auto* a = static_cast<Arena*>(handle);
  std::free(a->base);
  delete a;
}

uint8_t* sr_arena_base(void* handle) {
  return static_cast<Arena*>(handle)->base;
}

int64_t sr_arena_in_use(void* handle) {
  return static_cast<Arena*>(handle)->in_use;
}

// Best-fit allocate; returns offset or -1 when no block fits.
int64_t sr_arena_alloc(void* handle, int64_t size) {
  auto* a = static_cast<Arena*>(handle);
  if (size <= 0) size = 1;
  auto best = a->free_blocks.end();
  for (auto it = a->free_blocks.begin(); it != a->free_blocks.end(); ++it) {
    if (it->second >= size &&
        (best == a->free_blocks.end() || it->second < best->second)) {
      best = it;
    }
  }
  if (best == a->free_blocks.end()) return -1;
  int64_t offset = best->first;
  int64_t block = best->second;
  a->free_blocks.erase(best);
  if (block > size) a->free_blocks[offset + size] = block - size;
  a->used[offset] = size;
  a->in_use += size;
  return offset;
}

// Free + coalesce with adjacent free neighbors. Returns 0 ok, -1 unknown.
int sr_arena_free(void* handle, int64_t offset) {
  auto* a = static_cast<Arena*>(handle);
  auto it = a->used.find(offset);
  if (it == a->used.end()) return -1;
  int64_t size = it->second;
  a->used.erase(it);
  a->in_use -= size;
  // merge with successor
  auto next = a->free_blocks.find(offset + size);
  if (next != a->free_blocks.end()) {
    size += next->second;
    a->free_blocks.erase(next);
  }
  // merge with predecessor
  auto prev = a->free_blocks.lower_bound(offset);
  if (prev != a->free_blocks.begin()) {
    --prev;
    if (prev->first + prev->second == offset) {
      prev->second += size;
      return 0;
    }
  }
  a->free_blocks[offset] = size;
  return 0;
}

void sr_arena_write(void* handle, int64_t offset, const uint8_t* src,
                    int64_t len) {
  std::memcpy(static_cast<Arena*>(handle)->base + offset, src, len);
}

void sr_arena_read(void* handle, int64_t offset, uint8_t* dst, int64_t len) {
  std::memcpy(dst, static_cast<Arena*>(handle)->base + offset, len);
}

}  // extern "C"
