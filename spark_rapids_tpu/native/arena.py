"""HostArena — Python face of the native best-fit arena (arena.cpp).

Backs the host tier of the shuffle block catalog: serialized blocks live at
offsets inside one contiguous native region instead of thousands of Python
bytes objects (the AddressSpaceAllocator/host-store role from the
reference's spill chain). Falls back transparently when the native library
is unavailable — callers check :attr:`available`.
"""

from __future__ import annotations

import ctypes
from typing import Optional

from . import lib


class HostArena:
    def __init__(self, capacity_bytes: int):
        self._lib = lib()
        self._capacity = int(capacity_bytes)
        self._handle = None
        self._closed = False

    def _ensure(self) -> bool:
        """Lazy creation: the region mallocs on FIRST put, so idle
        catalogs (one exists per query context) cost nothing."""
        if self._handle is None and not self._closed \
                and self._lib is not None:
            self._handle = self._lib.sr_arena_create(self._capacity)
        return self._handle is not None

    @property
    def available(self) -> bool:
        return self._lib is not None and not self._closed

    @property
    def in_use(self) -> int:
        if self._handle is None:
            return 0
        return int(self._lib.sr_arena_in_use(self._handle))

    def put(self, payload: bytes) -> Optional[int]:
        """Store payload; returns its offset or None when the arena is full
        (caller falls back to its own storage)."""
        if not self._ensure():
            return None
        off = self._lib.sr_arena_alloc(self._handle, len(payload))
        if off < 0:
            return None
        self._lib.sr_arena_write(
            self._handle, off,
            ctypes.cast(ctypes.c_char_p(payload), ctypes.c_void_p),
            len(payload))
        return int(off)

    def get(self, offset: int, length: int) -> bytes:
        if self._handle is None:
            raise RuntimeError("arena is closed or unavailable")
        buf = ctypes.create_string_buffer(length)
        self._lib.sr_arena_read(self._handle, offset,
                                ctypes.cast(buf, ctypes.c_void_p), length)
        return buf.raw

    def free(self, offset: int) -> None:
        if self._handle is not None:
            self._lib.sr_arena_free(self._handle, offset)

    def close(self) -> None:
        self._closed = True
        if self._handle is not None:
            self._lib.sr_arena_destroy(self._handle)
            self._handle = None

    def __del__(self):  # pragma: no cover - gc safety net
        try:
            self.close()
        except Exception:
            pass
