// Host columnar kernels — the native side of the host runtime.
//
// The reference's host hot paths live in C++ behind JNI (libcudf host code,
// JCudfSerialization buffer assembly); this library plays that role for the
// TPU engine's host paths. First resident: Spark Murmur3 row hashing
// (bit-for-bit the semantics of shuffle/partitioning.py's numpy/jnp
// implementation, itself matching Spark's Murmur3_x86_32) — used by the CPU
// oracle exchange and any host-side partition placement, where the Python
// per-row string loop was the cost.
//
// Build: g++ -O3 -shared -fPIC (see native/__init__.py); pure C ABI for
// ctypes. No dependencies.

#include <cstdint>
#include <cstring>

namespace {

constexpr uint32_t C1 = 0xCC9E2D51u;
constexpr uint32_t C2 = 0x1B873593u;

inline uint32_t rotl32(uint32_t x, int r) {
  return (x << r) | (x >> (32 - r));
}

inline uint32_t mix_k1(uint32_t k1) {
  k1 *= C1;
  k1 = rotl32(k1, 15);
  return k1 * C2;
}

inline uint32_t mix_h1(uint32_t h1, uint32_t k1) {
  h1 ^= k1;
  h1 = rotl32(h1, 13);
  return h1 * 5u + 0xE6546B64u;
}

inline uint32_t fmix(uint32_t h1, uint32_t length) {
  h1 ^= length;
  h1 ^= h1 >> 16;
  h1 *= 0x85EBCA6Bu;
  h1 ^= h1 >> 13;
  h1 *= 0xC2B2AE35u;
  return h1 ^ (h1 >> 16);
}

inline uint32_t hash_int(uint32_t v, uint32_t seed) {
  return fmix(mix_h1(seed, mix_k1(v)), 4);
}

inline uint32_t hash_long(uint64_t v, uint32_t seed) {
  uint32_t h1 = mix_h1(seed, mix_k1(static_cast<uint32_t>(v)));
  h1 = mix_h1(h1, mix_k1(static_cast<uint32_t>(v >> 32)));
  return fmix(h1, 8);
}

// Spark Murmur3_x86_32.hashUnsafeBytes: 4-byte little-endian blocks through
// the full mix, then the 1-3 trailing bytes one at a time as SIGNED ints.
inline uint32_t hash_bytes(const uint8_t* data, int32_t len, uint32_t seed) {
  uint32_t h1 = seed;
  int32_t aligned = (len / 4) * 4;
  for (int32_t i = 0; i < aligned; i += 4) {
    uint32_t block;
    std::memcpy(&block, data + i, 4);  // little-endian hosts only
    h1 = mix_h1(h1, mix_k1(block));
  }
  for (int32_t i = aligned; i < len; i++) {
    int32_t signed_byte = static_cast<int8_t>(data[i]);
    h1 = mix_h1(h1, mix_k1(static_cast<uint32_t>(signed_byte)));
  }
  return fmix(h1, static_cast<uint32_t>(len));
}

}  // namespace

extern "C" {

// Fold one int-width column into the running row hashes h[n]; invalid rows
// keep their hash (Spark skips null columns per row).
void sr_hash_col_i32(const int32_t* vals, const uint8_t* valid, int64_t n,
                     uint32_t* h) {
  for (int64_t i = 0; i < n; i++) {
    if (valid[i]) h[i] = hash_int(static_cast<uint32_t>(vals[i]), h[i]);
  }
}

void sr_hash_col_i64(const int64_t* vals, const uint8_t* valid, int64_t n,
                     uint32_t* h) {
  for (int64_t i = 0; i < n; i++) {
    if (valid[i]) h[i] = hash_long(static_cast<uint64_t>(vals[i]), h[i]);
  }
}

// Floats hash their IEEE bits with NaN canonicalized and -0.0 -> 0.0
// (Spark Murmur3Hash semantics).
void sr_hash_col_f32(const float* vals, const uint8_t* valid, int64_t n,
                     uint32_t* h) {
  for (int64_t i = 0; i < n; i++) {
    if (!valid[i]) continue;
    float v = vals[i];
    uint32_t bits;
    if (v != v) {
      bits = 0x7FC00000u;
    } else if (v == 0.0f) {
      bits = 0;
    } else {
      std::memcpy(&bits, &v, 4);
    }
    h[i] = hash_int(bits, h[i]);
  }
}

void sr_hash_col_f64(const double* vals, const uint8_t* valid, int64_t n,
                     uint32_t* h) {
  for (int64_t i = 0; i < n; i++) {
    if (!valid[i]) continue;
    double v = vals[i];
    uint64_t bits;
    if (v != v) {
      bits = 0x7FF8000000000000ull;
    } else if (v == 0.0) {
      bits = 0;
    } else {
      std::memcpy(&bits, &v, 8);
    }
    h[i] = hash_long(bits, h[i]);
  }
}

// Arrow string layout: offsets[n+1] into payload; per-row hashUnsafeBytes.
void sr_hash_col_str(const int32_t* offsets, const uint8_t* payload,
                     const uint8_t* valid, int64_t n, uint32_t* h) {
  for (int64_t i = 0; i < n; i++) {
    if (!valid[i]) continue;
    int32_t start = offsets[i];
    h[i] = hash_bytes(payload + start, offsets[i + 1] - start, h[i]);
  }
}

}  // extern "C"
