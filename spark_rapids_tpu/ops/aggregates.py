"""Aggregate functions — the ``CudfAggregate``/``GpuDeclarativeAggregate`` analog.

The reference declares each aggregate as buffer columns + cudf update/merge
ops + a final projection (``AggregateFunctions.scala:69,252`` —
GpuMin/Max/Sum/Count/Average at ``:276-361``, First/Last in shims). We keep
exactly that declarative structure, but the ops name **segment-reduction
kernels** (:mod:`..ops.kernels.groupby`) instead of cudf ops, so the same
declaration drives partial mode, merge mode, and reduction (no-key) mode:

* ``update_ops`` — per-buffer (kernel_op, buffer_dtype) applied to the input
  column in partial aggregation;
* ``merge_ops`` — kernel ops combining partial buffers in final aggregation;
* ``evaluate`` — expression over the merged buffers producing the result.

Host-side (oracle/fallback) evaluation maps to pyarrow group_by aggregation
names, deliberately an independent implementation.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

from .. import types as T
from .arithmetic import Divide
from .cast import Cast
from .expression import BoundReference, Expression


@dataclasses.dataclass(frozen=True)
class BufferSpec:
    """One partial-aggregation buffer column."""
    suffix: str
    update_op: str  # kernel op producing it from the input
    merge_op: str   # kernel op merging partials
    dtype: T.DataType
    #: count buffers are non-null; value buffers are null when count==0
    from_count: bool = False


class AggregateFunction(Expression):
    """Declarative aggregate. ``children`` holds the input expression."""

    def __init__(self, child: Optional[Expression] = None):
        self.children = [child] if child is not None else []

    @property
    def child(self) -> Optional[Expression]:
        return self.children[0] if self.children else None

    def with_children(self, children):
        return type(self)(children[0]) if children else type(self)()

    # -- declarative surface -------------------------------------------------
    def buffers(self) -> List[BufferSpec]:
        raise NotImplementedError

    def evaluate(self, buffer_refs: List[Expression]) -> Expression:
        """Final projection over merged buffers (identity for simple aggs)."""
        return buffer_refs[0]

    #: pyarrow group_by aggregation name for the host oracle.
    pa_agg: str = ""

    @property
    def nullable(self) -> bool:
        return True


class Min(AggregateFunction):
    pa_agg = "min"

    @property
    def data_type(self) -> T.DataType:
        return self.child.data_type

    def buffers(self):
        return [BufferSpec("min", "min", "min", self.data_type)]


class Max(AggregateFunction):
    pa_agg = "max"

    @property
    def data_type(self) -> T.DataType:
        return self.child.data_type

    def buffers(self):
        return [BufferSpec("max", "max", "max", self.data_type)]


class Sum(AggregateFunction):
    """Spark widens integral sums to bigint, float sums to double."""

    pa_agg = "sum"

    @property
    def data_type(self) -> T.DataType:
        return T.DOUBLE if self.child.data_type.is_floating else T.LONG

    def buffers(self):
        return [BufferSpec("sum", "sum", "sum", self.data_type)]


class Count(AggregateFunction):
    """count(expr) — non-null count; count(*) when child is None."""

    pa_agg = "count"

    @property
    def data_type(self) -> T.DataType:
        return T.LONG

    @property
    def nullable(self) -> bool:
        return False

    def buffers(self):
        return [BufferSpec("count", "count", "sum", T.LONG, from_count=True)]


class Average(AggregateFunction):
    """avg = sum/count carried as two buffers (reference GpuAverage:361)."""

    pa_agg = "mean"

    @property
    def data_type(self) -> T.DataType:
        return T.DOUBLE

    def buffers(self):
        return [BufferSpec("sum", "sum", "sum", T.DOUBLE),
                BufferSpec("count", "count", "sum", T.LONG, from_count=True)]

    def evaluate(self, buffer_refs):
        # Divide yields null on zero count, matching Spark's empty-group avg.
        return Divide(buffer_refs[0], Cast(buffer_refs[1], T.DOUBLE))


class First(AggregateFunction):
    """first(expr, ignoreNulls) — reference keeps First/Last in shims
    (shims/spark300/.../GpuFirst.scala:51)."""

    pa_agg = "first"

    def __init__(self, child: Optional[Expression] = None,
                 ignore_nulls: bool = True):
        super().__init__(child)
        self.ignore_nulls = ignore_nulls

    def with_children(self, children):
        return First(children[0], self.ignore_nulls)

    @property
    def data_type(self) -> T.DataType:
        return self.child.data_type

    def buffers(self):
        return [BufferSpec("first", "first", "first", self.data_type)]


class Last(AggregateFunction):
    pa_agg = "last"

    def __init__(self, child: Optional[Expression] = None,
                 ignore_nulls: bool = True):
        super().__init__(child)
        self.ignore_nulls = ignore_nulls

    def with_children(self, children):
        return Last(children[0], self.ignore_nulls)

    @property
    def data_type(self) -> T.DataType:
        return self.child.data_type

    def buffers(self):
        return [BufferSpec("last", "last", "last", self.data_type)]


@dataclasses.dataclass
class AggregateExpression:
    """A named aggregate in an Aggregate node (GpuAggregateExpression analog)."""
    func: AggregateFunction
    name: str

    def bind(self, schema) -> "AggregateExpression":
        return AggregateExpression(self.func.bind(schema), self.name)
