"""Arithmetic expressions — Spark non-ANSI semantics on device.

Mirrors the reference's arithmetic family (reference:
``sql-plugin/src/main/scala/org/apache/spark/sql/rapids/arithmetic.scala``):
Add/Subtract/Multiply/Divide/IntegralDivide/Remainder/Pmod/UnaryMinus/Abs.

Spark (non-ANSI) semantics implemented here:
* integral add/sub/mul wrap (Java two's-complement), floats follow IEEE;
* ``Divide`` always produces double and yields null on divisor 0;
* ``IntegralDivide``/``Remainder``/``Pmod`` yield null on divisor 0.

Host kernels use numpy (wrapping by construction); device kernels use jnp.
Type coercion (promoting both sides to a common type) is inserted as explicit
casts by :func:`spark_rapids_tpu.ops.coercion.coerce`.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
import pyarrow as pa

from .. import types as T
from .expression import BinaryExpression, UnaryExpression


def _np_of(arr: pa.Array):
    """pa.Array -> (zero-filled numpy values, validity numpy bool)."""
    validity = np.asarray(arr.is_valid()) if arr.null_count else None
    if arr.null_count:
        zero = False if pa.types.is_boolean(arr.type) else 0
        arr = arr.fill_null(zero)
    return arr.to_numpy(zero_copy_only=False), validity


def _to_pa(values: np.ndarray, validity, dtype: T.DataType) -> pa.Array:
    return pa.array(values.astype(dtype.np_dtype, copy=False),
                    type=T.to_arrow_type(dtype),
                    mask=None if validity is None else ~validity)


def _and_validity(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return a & b


class BinaryArithmetic(BinaryExpression):
    """Shared plumbing: numpy host kernel with explicit validity math."""

    @property
    def data_type(self) -> T.DataType:
        return T.numeric_promote(self.left.data_type, self.right.data_type)

    def do_host(self, l: pa.Array, r: pa.Array) -> pa.Array:
        lv, lval = _np_of(l)
        rv, rval = _np_of(r)
        validity = _and_validity(lval, rval)
        with np.errstate(all="ignore"):
            out, extra_null = self.np_kernel(
                lv.astype(self.data_type.np_dtype, copy=False),
                rv.astype(self.data_type.np_dtype, copy=False))
        if extra_null is not None:
            validity = _and_validity(validity, ~extra_null)
        if validity is not None:
            out = np.where(validity, out, np.zeros((), out.dtype))
        return _to_pa(out, validity, self.data_type)

    def do_device(self, l: jnp.ndarray, r: jnp.ndarray):
        np_dt = self.data_type.np_dtype
        return self.jnp_kernel(l.astype(np_dt), r.astype(np_dt))

    def np_kernel(self, l, r):
        raise NotImplementedError

    def jnp_kernel(self, l, r):
        raise NotImplementedError


class Add(BinaryArithmetic):
    def np_kernel(self, l, r):
        return l + r, None

    def jnp_kernel(self, l, r):
        return l + r, None


class Subtract(BinaryArithmetic):
    def np_kernel(self, l, r):
        return l - r, None

    def jnp_kernel(self, l, r):
        return l - r, None


class Multiply(BinaryArithmetic):
    def np_kernel(self, l, r):
        return l * r, None

    def jnp_kernel(self, l, r):
        return l * r, None


class Divide(BinaryArithmetic):
    """Double division; divisor 0 -> null (Spark non-ANSI)."""

    @property
    def data_type(self) -> T.DataType:
        return T.DOUBLE

    def np_kernel(self, l, r):
        zero = r == 0
        return np.divide(l, np.where(zero, 1, r)), zero

    def jnp_kernel(self, l, r):
        zero = r == 0
        return l / jnp.where(zero, 1.0, r), zero


class IntegralDivide(BinaryArithmetic):
    """``div`` — long division truncating toward zero; /0 -> null."""

    @property
    def data_type(self) -> T.DataType:
        return T.LONG

    def np_kernel(self, l, r):
        zero = r == 0
        safe = np.where(zero, 1, r)
        # numpy // floors; Spark/Java truncates toward zero.
        return _trunc_div_int(l, safe), zero

    def jnp_kernel(self, l, r):
        zero = r == 0
        safe = jnp.where(zero, 1, r)
        q = l // safe
        rem = l - q * safe
        # Adjust floor -> trunc when signs differ and remainder nonzero.
        adjust = (rem != 0) & ((l < 0) != (safe < 0))
        return q + adjust.astype(q.dtype), zero


def _trunc_div_int(l: np.ndarray, r: np.ndarray) -> np.ndarray:
    q = l // r
    rem = l - q * r
    adjust = (rem != 0) & ((l < 0) != (r < 0))
    return q + adjust.astype(q.dtype)


class Remainder(BinaryArithmetic):
    """Java % semantics (sign of dividend); /0 -> null."""

    def np_kernel(self, l, r):
        zero = r == 0
        safe = np.where(zero, 1, r)
        if self.data_type.is_floating:
            return np.fmod(l, safe), zero
        return l - _trunc_div_int(l, safe) * safe, zero

    def jnp_kernel(self, l, r):
        zero = r == 0
        one = jnp.ones((), dtype=r.dtype)
        safe = jnp.where(zero, one, r)
        if self.data_type.is_floating:
            return _jnp_fmod(l, safe), zero
        q = l // safe
        rem = l - q * safe
        adjust = (rem != 0) & ((l < 0) != (safe < 0))
        q = q + adjust.astype(q.dtype)
        return l - q * safe, zero


def _jnp_fmod(l, r):
    return l - jnp.trunc(l / r) * r


class Pmod(BinaryArithmetic):
    """Positive modulus; /0 -> null."""

    def np_kernel(self, l, r):
        zero = r == 0
        safe = np.where(zero, 1, r)
        if self.data_type.is_floating:
            m = np.fmod(l, safe)
            m = np.where((m != 0) & ((m < 0) != (safe < 0)), m + safe, m)
            return m, zero
        m = l - _trunc_div_int(l, safe) * safe
        m = np.where((m != 0) & ((m < 0) != (safe < 0)), m + safe, m)
        return m, zero

    def jnp_kernel(self, l, r):
        zero = r == 0
        one = jnp.ones((), dtype=r.dtype)
        safe = jnp.where(zero, one, r)
        if self.data_type.is_floating:
            m = _jnp_fmod(l, safe)
        else:
            q = l // safe
            rem = l - q * safe
            adjust = (rem != 0) & ((l < 0) != (safe < 0))
            m = l - (q + adjust.astype(q.dtype)) * safe
        m = jnp.where((m != 0) & ((m < 0) != (safe < 0)), m + safe, m)
        return m, zero


class UnaryMinus(UnaryExpression):
    @property
    def data_type(self) -> T.DataType:
        return self.child.data_type

    def do_host(self, v: pa.Array) -> pa.Array:
        vv, val = _np_of(v)
        with np.errstate(all="ignore"):
            out = (-vv).astype(self.data_type.np_dtype)
        return _to_pa(out, val, self.data_type)

    def do_device(self, data: jnp.ndarray):
        return -data, None


class Abs(UnaryExpression):
    @property
    def data_type(self) -> T.DataType:
        return self.child.data_type

    def do_host(self, v: pa.Array) -> pa.Array:
        vv, val = _np_of(v)
        with np.errstate(all="ignore"):
            out = np.abs(vv).astype(self.data_type.np_dtype)
        return _to_pa(out, val, self.data_type)

    def do_device(self, data: jnp.ndarray):
        return jnp.abs(data), None
