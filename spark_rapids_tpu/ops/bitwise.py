"""Bitwise expression family — the ``bitwise.scala`` analog (145 LoC,
SURVEY.md §2.4): And/Or/Xor/Not/ShiftLeft/ShiftRight/ShiftRightUnsigned.

Java shift semantics: the shift amount is masked to the operand width
(n & 31 for int, n & 63 for long)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from .. import types as T
from .arithmetic import _np_of, _to_pa
from .expression import BinaryExpression, UnaryExpression


class _BitBinary(BinaryExpression):
    @property
    def data_type(self) -> T.DataType:
        return T.numeric_promote(self.left.data_type, self.right.data_type)

    def do_host(self, l, r):
        lv, lval = _np_of(l)
        rv, rval = _np_of(r)
        validity = lval if rval is None else (
            rval if lval is None else lval & rval)
        np_dt = self.data_type.np_dtype
        out = self.np_op(lv.astype(np_dt), rv.astype(np_dt))
        return _to_pa(out, validity, self.data_type)

    def do_device(self, l, r):
        np_dt = self.data_type.np_dtype
        return self.np_op(l.astype(np_dt), r.astype(np_dt)), None


class BitwiseAnd(_BitBinary):
    @staticmethod
    def np_op(l, r):
        return l & r


class BitwiseOr(_BitBinary):
    @staticmethod
    def np_op(l, r):
        return l | r


class BitwiseXor(_BitBinary):
    @staticmethod
    def np_op(l, r):
        return l ^ r


class BitwiseNot(UnaryExpression):
    @property
    def data_type(self) -> T.DataType:
        return self.child.data_type

    def do_host(self, v):
        vv, validity = _np_of(v)
        return _to_pa(~vv, validity, self.data_type)

    def do_device(self, data):
        return ~data, None


class _Shift(BinaryExpression):
    """Shift amount is an int; masked to the value's bit width (Java)."""

    @property
    def data_type(self) -> T.DataType:
        return self.left.data_type

    def _mask(self):
        return 63 if self.data_type is T.LONG else 31

    def do_host(self, l, r):
        lv, lval = _np_of(l)
        rv, rval = _np_of(r)
        validity = lval if rval is None else (
            rval if lval is None else lval & rval)
        sh = rv.astype(np.int64) & self._mask()
        out = self.np_op(lv.astype(self.data_type.np_dtype), sh)
        return _to_pa(out, validity, self.data_type)

    def do_device(self, l, r):
        sh = r.astype(jnp.int64) & self._mask()
        return self.np_op(l.astype(self.data_type.np_dtype), sh), None


class ShiftLeft(_Shift):
    @staticmethod
    def np_op(v, sh):
        return (v << sh).astype(v.dtype)


class ShiftRight(_Shift):
    @staticmethod
    def np_op(v, sh):
        return (v >> sh).astype(v.dtype)


class ShiftRightUnsigned(_Shift):
    def np_op(self, v, sh):
        if self.data_type is T.LONG:
            xp = jnp if not isinstance(v, np.ndarray) else np
            u = v.astype(xp.uint64) >> sh.astype(xp.uint64)
            return u.astype(xp.int64)
        xp = jnp if not isinstance(v, np.ndarray) else np
        u = v.astype(xp.uint32) >> sh.astype(xp.uint32)
        return u.astype(xp.int32)
