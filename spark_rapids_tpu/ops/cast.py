"""Cast — Java/Spark narrowing semantics on both paths.

The reference's ``GpuCast`` covers every numeric/string/date/timestamp cast
with conf gates on the inexact float<->string paths (reference:
``GpuCast.scala:79,181``; gates ``RapidsConf.scala:395-425``). Semantics
implemented here (Spark non-ANSI = Java conversions):

* integral -> narrower integral: two's-complement bit truncation (wraps);
* float/double -> integral: NaN -> 0, +/-inf and out-of-range clamp to
  MIN/MAX (JLS 5.1.3);
* numeric -> boolean: ``x != 0``; boolean -> numeric: 1/0;
* date -> timestamp: midnight UTC; timestamp -> date: floor to day.

String casts are separate expressions in :mod:`strings` (conf-gated like the
reference).
"""

from __future__ import annotations

import datetime as _dt

import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from .. import types as T
from ..data.column import DeviceColumn
from .arithmetic import _np_of, _to_pa
from .expression import Expression, UnaryExpression


def _host_from_string(v: pa.Array, to: T.DataType) -> pa.Array:
    """CPU-oracle string parsing with Spark non-ANSI semantics (invalid ->
    null), mirroring the device kernels in cast_string.py."""
    vals = v.to_pylist()
    out = []
    for s in vals:
        if s is None:
            out.append(None)
            continue
        s = s.strip()
        try:
            # Python int()/float() accept '_' separators and non-ASCII
            # digits; Spark and the device kernels do not.
            if to.is_integral or to.name in ("float", "double"):
                if "_" in s or not s.isascii():
                    out.append(None)
                    continue
            if to.is_integral:
                x = int(s)
                lo, hi = _INT_BOUNDS[to.name]
                out.append(x if lo <= x <= hi else None)
            elif to.name in ("float", "double"):
                low = s.lower()
                if low in ("nan", "infinity", "inf", "-infinity", "-inf",
                           "+infinity", "+inf"):
                    out.append(None)  # device kernel rejects word forms
                else:
                    out.append(float(s))
            elif to is T.BOOLEAN:
                low = s.lower()
                if low in ("true", "t", "yes", "y", "1"):
                    out.append(True)
                elif low in ("false", "f", "no", "n", "0"):
                    out.append(False)
                else:
                    out.append(None)
            elif to is T.DATE:
                out.append(_dt.date.fromisoformat(_pad_iso_date(s)))
            elif to is T.TIMESTAMP:
                out.append(_parse_ts_host(s))
            else:
                raise NotImplementedError(str(to))
        except (ValueError, OverflowError):
            out.append(None)
    return pa.array(out, type=T.to_arrow_type(to))


import re as _re

_DATE_RE = _re.compile(r"^(\d{4})-(\d{1,2})-(\d{1,2})$")


def _pad_iso_date(s: str) -> str:
    m = _DATE_RE.match(s)
    if not m:
        # Python 3.11+ fromisoformat accepts compact "yyyymmdd"; Spark's
        # cast does not — force a parse failure.
        raise ValueError(f"not a yyyy-MM-dd date: {s!r}")
    return f"{m.group(1)}-{int(m.group(2)):02d}-{int(m.group(3)):02d}"


def _parse_ts_host(s: str):
    if " " in s or "T" in s:
        sep = " " if " " in s else "T"
        d, t = s.split(sep, 1)
        return _dt.datetime.fromisoformat(_pad_iso_date(d) + "T" + t)
    return _dt.datetime.combine(_dt.date.fromisoformat(_pad_iso_date(s)),
                                _dt.time())


def _host_to_string(v: pa.Array, src: T.DataType) -> pa.Array:
    vals = v.to_pylist()
    out = []
    for x in vals:
        if x is None:
            out.append(None)
        elif src is T.BOOLEAN:
            out.append("true" if x else "false")
        elif src is T.DATE:
            out.append(x.isoformat())
        elif src is T.TIMESTAMP:
            s = x.strftime("%Y-%m-%d %H:%M:%S")
            if x.microsecond:
                s += (".%06d" % x.microsecond).rstrip("0")
            out.append(s)
        else:
            out.append(str(x))
    return pa.array(out, type=pa.string())

_INT_BOUNDS = {
    "tinyint": (-(2 ** 7), 2 ** 7 - 1),
    "smallint": (-(2 ** 15), 2 ** 15 - 1),
    "int": (-(2 ** 31), 2 ** 31 - 1),
    "bigint": (-(2 ** 63), 2 ** 63 - 1),
}

_US_PER_DAY = 86_400_000_000


class Cast(UnaryExpression):
    def __init__(self, child: Expression, to: T.DataType):
        super().__init__(child)
        self.to = to

    @property
    def data_type(self) -> T.DataType:
        return self.to

    def with_children(self, children):
        return Cast(children[0], self.to)

    def eval_host(self, batch):
        src = self.child.data_type
        if src is T.STRING and self.to is not T.STRING:
            from .expression import host_to_array
            v = host_to_array(self.child.eval_host(batch), batch.num_rows)
            return _host_from_string(v, self.to)
        if self.to is T.STRING and src is not T.STRING:
            from .expression import host_to_array
            v = host_to_array(self.child.eval_host(batch), batch.num_rows)
            return _host_to_string(v, src)
        return super().eval_host(batch)

    def eval_device(self, batch):
        src = self.child.data_type
        if src is T.STRING and self.to is not T.STRING:
            from . import cast_string as CS
            from .expression import make_column
            from .strings_util import char_matrix
            c = self.child.eval_device(batch)
            parse = {
                "bigint": CS.parse_long_matrix, "int": CS.parse_long_matrix,
                "smallint": CS.parse_long_matrix,
                "tinyint": CS.parse_long_matrix,
                "float": CS.parse_double_matrix,
                "double": CS.parse_double_matrix,
                "date": CS.parse_date_matrix,
                "timestamp": CS.parse_timestamp_matrix,
                "boolean": CS.parse_bool_matrix,
            }.get(self.to.name)
            if parse is None:
                raise NotImplementedError(f"cast string->{self.to}")
            if c.is_dict:
                # Parse the small dictionary once, gather by code.
                dm = char_matrix(DeviceColumn(
                    data=c.data, validity=jnp.ones(c.dict_size, jnp.bool_),
                    dtype=T.STRING, offsets=c.offsets,
                    max_bytes=c.max_bytes))
                vals_d, ok_d = parse(dm)
                safe = jnp.clip(c.codes, 0, c.dict_size - 1)
                vals, ok = vals_d[safe], ok_d[safe]
            else:
                vals, ok = parse(char_matrix(c))
            if self.to.is_integral and self.to is not T.LONG:
                # Spark parses string->integral at target width: out of
                # range -> null (not the numeric cast's Java wrap).
                lo, hi = _INT_BOUNDS[self.to.name]
                ok = ok & (vals >= lo) & (vals <= hi)
                vals = _jnp_cast(vals, T.LONG, self.to)
            elif self.to is T.FLOAT:
                vals = vals.astype(jnp.float32)
            elif self.to.name in ("float", "double") \
                    and vals.dtype != self.to.np_dtype:
                vals = vals.astype(self.to.np_dtype)
            validity = c.validity & ok
            data = jnp.where(validity, vals.astype(self.to.np_dtype),
                             jnp.zeros((), self.to.np_dtype))
            return make_column(data, validity, self.to)
        if self.to is T.STRING and src is not T.STRING:
            from . import cast_string as CS
            from .kernels.rowops import strings_from_matrix
            from .strings_util import PAD
            c = self.child.eval_device(batch)
            if src is T.BOOLEAN:
                # Two-entry dictionary: O(1) payload.
                import numpy as _np
                payload = _np.frombuffer(b"falsetrue", dtype=_np.uint8)
                buf = _np.zeros(16, _np.uint8)
                buf[:9] = payload
                return DeviceColumn(
                    data=jnp.asarray(buf), validity=c.validity,
                    dtype=T.STRING,
                    offsets=jnp.asarray(_np.array([0, 5, 9], _np.int32)),
                    max_bytes=8,
                    codes=jnp.where(c.validity, c.data.astype(jnp.int32), 0),
                    dict_sorted=True)
            if src.is_integral:
                m = CS.format_long_matrix(c.data.astype(jnp.int64))
            elif src is T.DATE:
                m = CS.format_date_matrix(c.data)
            elif src is T.TIMESTAMP:
                m = CS.format_timestamp_matrix(c.data)
            else:
                raise NotImplementedError(f"cast {src}->string")
            m = jnp.where(c.validity[:, None], m, PAD)
            return strings_from_matrix(m, c.validity, m.shape[1])
        return super().eval_device(batch)

    def do_host(self, v: pa.Array) -> pa.Array:
        src = T.from_arrow_type(v.type)
        if src.name == self.to.name:
            return v
        vals, validity = _np_of(v)
        if vals.dtype.kind == "M":
            unit = "D" if src is T.DATE else "us"
            vals = vals.astype(f"datetime64[{unit}]").view(np.int64)
        out = _np_cast(vals, src, self.to)
        return _to_pa(out, validity, self.to)

    def do_device(self, data: jnp.ndarray):
        src = self.child.data_type
        if src.name == self.to.name:
            return data, None
        return _jnp_cast(data, src, self.to), None

    def __str__(self) -> str:
        return f"cast({self.children[0]} as {self.to})"


def _np_cast(vals: np.ndarray, src: T.DataType, to: T.DataType) -> np.ndarray:
    if to is T.BOOLEAN:
        return vals != 0
    if src is T.BOOLEAN:
        return vals.astype(to.np_dtype)
    if src is T.DATE and to is T.TIMESTAMP:
        return vals.astype(np.int64) * _US_PER_DAY
    if src is T.TIMESTAMP and to is T.DATE:
        return np.floor_divide(vals, _US_PER_DAY).astype(np.int32)
    if src.is_floating and to.is_integral:
        lo, hi = _INT_BOUNDS[to.name]
        with np.errstate(invalid="ignore"):
            t = np.trunc(vals.astype(np.float64))
            nan = np.isnan(t)
            # Compare in float64; hi rounds up to 2**63 for bigint, so values
            # at/above the rounded bound route to the clamp and the residual
            # cast below only ever sees exactly-representable in-range values.
            over = ~nan & (t >= np.float64(hi))
            under = ~nan & (t <= np.float64(lo))
            safe = np.where(nan | over | under, 0.0, t)
        out = safe.astype(to.np_dtype)
        out[over] = hi
        out[under] = lo
        return out
    # integral narrowing wraps via astype; widening and float casts are exact.
    with np.errstate(all="ignore"):
        return vals.astype(to.np_dtype)


def _jnp_cast(data: jnp.ndarray, src: T.DataType, to: T.DataType) -> jnp.ndarray:
    if to is T.BOOLEAN:
        return data != 0
    if src is T.BOOLEAN:
        return data.astype(to.np_dtype)
    if src is T.DATE and to is T.TIMESTAMP:
        return data.astype(jnp.int64) * _US_PER_DAY
    if src is T.TIMESTAMP and to is T.DATE:
        return jnp.floor_divide(data, _US_PER_DAY).astype(jnp.int32)
    if src.is_floating and to.is_integral:
        lo, hi = _INT_BOUNDS[to.name]
        t = jnp.trunc(data.astype(jnp.float64))
        nan = jnp.isnan(t)
        over = ~nan & (t >= np.float64(hi))
        under = ~nan & (t <= np.float64(lo))
        safe = jnp.where(nan | over | under, 0.0, t).astype(to.np_dtype)
        out = jnp.where(over, jnp.asarray(hi, to.np_dtype), safe)
        return jnp.where(under, jnp.asarray(lo, to.np_dtype), out)
    return data.astype(to.np_dtype)


def coerce_binary(left: Expression, right: Expression):
    """Insert casts promoting both sides to a common numeric type — the
    analyzer-side type coercion Spark does before the plugin sees the plan."""
    lt, rt = left.data_type, right.data_type
    if lt.name == rt.name:
        return left, right
    if lt is T.NULL or rt is T.NULL:
        # Null literals adopt the other side's type at eval; compiled-UDF
        # loop state (udf/loops.py) types itself lazily after binding —
        # either way there is nothing sound to cast yet.
        return left, right
    common = T.numeric_promote(lt, rt)
    if lt.name != common.name:
        left = Cast(left, common)
    if rt.name != common.name:
        right = Cast(right, common)
    return left, right
