"""Cast — Java/Spark narrowing semantics on both paths.

The reference's ``GpuCast`` covers every numeric/string/date/timestamp cast
with conf gates on the inexact float<->string paths (reference:
``GpuCast.scala:79,181``; gates ``RapidsConf.scala:395-425``). Semantics
implemented here (Spark non-ANSI = Java conversions):

* integral -> narrower integral: two's-complement bit truncation (wraps);
* float/double -> integral: NaN -> 0, +/-inf and out-of-range clamp to
  MIN/MAX (JLS 5.1.3);
* numeric -> boolean: ``x != 0``; boolean -> numeric: 1/0;
* date -> timestamp: midnight UTC; timestamp -> date: floor to day.

String casts are separate expressions in :mod:`strings` (conf-gated like the
reference).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from .. import types as T
from .arithmetic import _np_of, _to_pa
from .expression import Expression, UnaryExpression

_INT_BOUNDS = {
    "tinyint": (-(2 ** 7), 2 ** 7 - 1),
    "smallint": (-(2 ** 15), 2 ** 15 - 1),
    "int": (-(2 ** 31), 2 ** 31 - 1),
    "bigint": (-(2 ** 63), 2 ** 63 - 1),
}

_US_PER_DAY = 86_400_000_000


class Cast(UnaryExpression):
    def __init__(self, child: Expression, to: T.DataType):
        super().__init__(child)
        self.to = to

    @property
    def data_type(self) -> T.DataType:
        return self.to

    def with_children(self, children):
        return Cast(children[0], self.to)

    def do_host(self, v: pa.Array) -> pa.Array:
        src = T.from_arrow_type(v.type)
        if src.name == self.to.name:
            return v
        vals, validity = _np_of(v)
        if vals.dtype.kind == "M":
            unit = "D" if src is T.DATE else "us"
            vals = vals.astype(f"datetime64[{unit}]").view(np.int64)
        out = _np_cast(vals, src, self.to)
        return _to_pa(out, validity, self.to)

    def do_device(self, data: jnp.ndarray):
        src = self.child.data_type
        if src.name == self.to.name:
            return data, None
        return _jnp_cast(data, src, self.to), None

    def __str__(self) -> str:
        return f"cast({self.children[0]} as {self.to})"


def _np_cast(vals: np.ndarray, src: T.DataType, to: T.DataType) -> np.ndarray:
    if to is T.BOOLEAN:
        return vals != 0
    if src is T.BOOLEAN:
        return vals.astype(to.np_dtype)
    if src is T.DATE and to is T.TIMESTAMP:
        return vals.astype(np.int64) * _US_PER_DAY
    if src is T.TIMESTAMP and to is T.DATE:
        return np.floor_divide(vals, _US_PER_DAY).astype(np.int32)
    if src.is_floating and to.is_integral:
        lo, hi = _INT_BOUNDS[to.name]
        with np.errstate(invalid="ignore"):
            t = np.trunc(vals.astype(np.float64))
            nan = np.isnan(t)
            # Compare in float64; hi rounds up to 2**63 for bigint, so values
            # at/above the rounded bound route to the clamp and the residual
            # cast below only ever sees exactly-representable in-range values.
            over = ~nan & (t >= np.float64(hi))
            under = ~nan & (t <= np.float64(lo))
            safe = np.where(nan | over | under, 0.0, t)
        out = safe.astype(to.np_dtype)
        out[over] = hi
        out[under] = lo
        return out
    # integral narrowing wraps via astype; widening and float casts are exact.
    with np.errstate(all="ignore"):
        return vals.astype(to.np_dtype)


def _jnp_cast(data: jnp.ndarray, src: T.DataType, to: T.DataType) -> jnp.ndarray:
    if to is T.BOOLEAN:
        return data != 0
    if src is T.BOOLEAN:
        return data.astype(to.np_dtype)
    if src is T.DATE and to is T.TIMESTAMP:
        return data.astype(jnp.int64) * _US_PER_DAY
    if src is T.TIMESTAMP and to is T.DATE:
        return jnp.floor_divide(data, _US_PER_DAY).astype(jnp.int32)
    if src.is_floating and to.is_integral:
        lo, hi = _INT_BOUNDS[to.name]
        t = jnp.trunc(data.astype(jnp.float64))
        nan = jnp.isnan(t)
        over = ~nan & (t >= np.float64(hi))
        under = ~nan & (t <= np.float64(lo))
        safe = jnp.where(nan | over | under, 0.0, t).astype(to.np_dtype)
        out = jnp.where(over, jnp.asarray(hi, to.np_dtype), safe)
        return jnp.where(under, jnp.asarray(lo, to.np_dtype), out)
    return data.astype(to.np_dtype)


def coerce_binary(left: Expression, right: Expression):
    """Insert casts promoting both sides to a common numeric type — the
    analyzer-side type coercion Spark does before the plugin sees the plan."""
    lt, rt = left.data_type, right.data_type
    if lt.name == rt.name:
        return left, right
    common = T.numeric_promote(lt, rt)
    if lt.name != common.name:
        left = Cast(left, common)
    if rt.name != common.name:
        right = Cast(right, common)
    return left, right
