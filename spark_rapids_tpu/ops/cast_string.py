"""String <-> numeric/date/timestamp/boolean casts — the rest of GpuCast.

The reference's cast matrix (``GpuCast.scala:79,181``) covers these with
conf gates on the inexact paths (``RapidsConf.scala:395-425``); the same
gates exist here (castFloatToString / castStringToFloat /
castStringToTimestamp, config.py). Device kernels parse/format through the
char-matrix representation; DICTIONARY-encoded inputs evaluate on the
small dictionary and gather by code, so a 1M-row cast costs O(dict).

Semantics are Spark non-ANSI: invalid input -> null, integral overflow ->
null for string sources. Digits parse/format with static per-width loops
(W is the column's static max_bytes bound), which XLA unrolls into pure
vector code.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..data.column import DeviceColumn, bucket_capacity
from .strings_util import PAD, char_matrix, lengths

_LONG_MAX_F = 9.223372036854775e18


def _digit(m, j):
    c = m[:, j]
    return (c >= ord("0")) & (c <= ord("9")), (c - ord("0")).astype(jnp.int64)


def _trimmed(m: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Spark's cast trims whitespace: return (matrix', lengths') with
    leading/trailing ASCII whitespace replaced by PAD and content shifted
    to column 0."""
    ws = (m == ord(" ")) | (m == ord("\t")) | (m == ord("\n")) \
        | (m == ord("\r"))
    content = (m != PAD) & ~ws
    n, w = m.shape
    idx = jnp.arange(w, dtype=jnp.int32)[None, :]
    first = jnp.min(jnp.where(content, idx, w), axis=1)
    last = jnp.max(jnp.where(content, idx, -1), axis=1)
    shift = first[:, None]
    src = jnp.clip(idx + shift, 0, w - 1)
    shifted = jnp.take_along_axis(m, src, axis=1)
    new_len = jnp.maximum(last - first + 1, 0)
    keep = idx < new_len[:, None]
    return jnp.where(keep, shifted, PAD), new_len.astype(jnp.int32)


_I64_MAX_DIGITS = [int(c) for c in "9223372036854775807"]
_I64_MIN_DIGITS = [int(c) for c in "9223372036854775808"]


def parse_long_matrix(m: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """[N] int64 values + [N] bool validity from trimmed char rows.

    Overflow -> null (Spark non-ANSI), decided EXACTLY: <=18 significant
    digits always fit; 19 compare lexicographically against the int64
    bound (sign-dependent); >=20 overflow. The wrapped int64 accumulator
    is correct for every accepted value including INT64_MIN."""
    m, ln = _trimmed(m)
    n, w = m.shape
    neg = m[:, 0] == ord("-")
    plus = m[:, 0] == ord("+")
    start = (neg | plus).astype(jnp.int32)
    n_digits = ln - start
    acc = jnp.zeros(n, jnp.int64)
    all_digits = jnp.ones(n, jnp.bool_)
    for j in range(w):
        in_num = (j >= start) & (j < ln)
        is_d, d = _digit(m, j)
        all_digits = all_digits & (~in_num | is_d)
        acc = jnp.where(in_num & is_d, acc * 10 + d, acc)
    valid = (n_digits >= 1) & all_digits & (ln > 0)
    # significant digits: from the first nonzero digit
    idxw = jnp.arange(w, dtype=jnp.int32)[None, :]
    in_num_m = (idxw >= start[:, None]) & (idxw < ln[:, None])
    nonzero = in_num_m & (m != ord("0"))
    fs = jnp.min(jnp.where(nonzero, idxw, w), axis=1)
    has_nz = fs < w
    sig = jnp.where(has_nz, ln - fs, 1)
    decided = jnp.zeros(n, jnp.bool_)
    le19 = jnp.ones(n, jnp.bool_)
    for k in range(19):
        pos = jnp.clip(fs + k, 0, w - 1)[:, None]
        ck = (jnp.take_along_axis(m, pos, axis=1)[:, 0]
              - ord("0")).astype(jnp.int32)
        bk = jnp.where(neg, _I64_MIN_DIGITS[k], _I64_MAX_DIGITS[k])
        lt = ~decided & (ck < bk)
        gt = ~decided & (ck > bk)
        le19 = jnp.where(gt, False, le19)
        decided = decided | lt | gt
    valid = valid & ((sig <= 18) | ((sig == 19) & le19))
    out = jnp.where(neg, -acc, acc)
    return out, valid


def parse_double_matrix(m: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Decimal/exponent float parse: [sign] D* [. D*] [eE [sign] D+], at
    least one mantissa digit ("Infinity"/"NaN" words are not accepted).
    Returns ([N] float64, [N] bool)."""
    m, ln = _trimmed(m)
    n, w = m.shape
    neg = m[:, 0] == ord("-")
    plus = m[:, 0] == ord("+")
    start = (neg | plus).astype(jnp.int32)
    idx = jnp.arange(w, dtype=jnp.int32)[None, :]
    in_row = idx < ln[:, None]
    is_dot = (m == ord(".")) & in_row
    is_e = ((m == ord("e")) | (m == ord("E"))) & in_row
    dot_pos = jnp.min(jnp.where(is_dot, idx, w), axis=1)
    e_pos = jnp.min(jnp.where(is_e, idx, w), axis=1)
    n_dots = jnp.sum(is_dot.astype(jnp.int32), axis=1)
    n_es = jnp.sum(is_e.astype(jnp.int32), axis=1)
    ok = (n_dots <= 1) & (n_es <= 1) & ((n_dots == 0) | (dot_pos < e_pos))
    has_e = e_pos < w
    e_sign_col = jnp.clip(e_pos + 1, 0, w - 1)[:, None]
    e_sign_c = jnp.take_along_axis(m, e_sign_col, axis=1)[:, 0]
    e_neg = has_e & (e_sign_c == ord("-"))
    e_plus = has_e & (e_sign_c == ord("+"))
    exp_start = e_pos + 1 + (e_neg | e_plus).astype(jnp.int32)
    mant = jnp.zeros(n, jnp.float64)
    frac_scale = jnp.ones(n, jnp.float64)
    mant_digits = jnp.zeros(n, jnp.int32)
    exp_acc = jnp.zeros(n, jnp.int64)
    exp_digits = jnp.zeros(n, jnp.int32)
    for j in range(w):
        jj = jnp.full(n, j, jnp.int32)
        in_num = (jj >= start) & (jj < ln)
        is_d, d = _digit(m, j)
        df = d.astype(jnp.float64)
        in_int = in_num & (jj < dot_pos) & (jj < e_pos)
        in_frac = in_num & (jj > dot_pos) & (jj < e_pos)
        in_exp = in_num & (jj >= exp_start) & has_e
        mant = jnp.where(in_int & is_d, mant * 10 + df, mant)
        frac_scale = jnp.where(in_frac & is_d, frac_scale * 10, frac_scale)
        mant = jnp.where(in_frac & is_d, mant + df / frac_scale, mant)
        mant_digits = mant_digits + ((in_int | in_frac) & is_d)
        exp_acc = jnp.where(in_exp & is_d, exp_acc * 10 + d, exp_acc)
        exp_digits = exp_digits + (in_exp & is_d)
        legal = is_d | (jj == dot_pos) | (jj == e_pos) \
            | ((jj == e_pos + 1) & (e_neg | e_plus))
        ok = ok & (~in_num | legal)
    valid = ok & (mant_digits >= 1) & (~has_e | (exp_digits >= 1)) & (ln > 0)
    exp = jnp.where(e_neg, -exp_acc, exp_acc)
    exp = jnp.clip(exp, -400, 400).astype(jnp.float64)
    out = jnp.where(neg, -mant, mant) * jnp.power(10.0, exp)
    return out, valid


def format_long_matrix(v: jnp.ndarray) -> jnp.ndarray:
    """int64 -> char matrix [N, 20], left-aligned, PAD-terminated."""
    n = v.shape[0]
    w = 20
    neg = v < 0
    # abs in uint-safe form: int64 min magnitude fits when accumulated in
    # float for digit count, exact via per-digit divmod on the negative.
    mag = jnp.where(neg, -v, v)  # int64 min wraps; handled below via digits
    digits = []
    rest = mag
    for _ in range(w - 1):
        digits.append((rest % 10).astype(jnp.int16))
        rest = rest // 10
    dm = jnp.stack(digits[::-1], axis=1)  # [N, 19] most-significant first
    nz = dm != 0
    idx = jnp.arange(w - 1, dtype=jnp.int32)[None, :]
    first_nz = jnp.min(jnp.where(nz, idx, w - 1), axis=1)
    ndig = (w - 1) - first_nz
    ndig = jnp.maximum(ndig, 1)  # "0"
    chars = (dm + ord("0")).astype(jnp.int16)
    # left-align: row i starts at first digit (or sign)
    total = ndig + neg.astype(jnp.int32)
    out_idx = jnp.arange(w, dtype=jnp.int32)[None, :]
    src = out_idx - neg.astype(jnp.int32)[:, None] + first_nz[:, None]
    src_c = jnp.take_along_axis(chars, jnp.clip(src, 0, w - 2), axis=1)
    out = jnp.where(out_idx == 0, jnp.where(neg[:, None], ord("-"), src_c),
                    src_c).astype(jnp.int16)
    out = jnp.where(out_idx < total[:, None], out, PAD)
    # INT64_MIN: -v wraps, so the digit loop extracted garbage — overwrite
    # those rows with the constant representation.
    i64_min = jnp.int64(-9223372036854775807 - 1)
    min_row = np.full(w, PAD, np.int16)
    min_txt = b"-9223372036854775808"
    min_row[: len(min_txt)] = np.frombuffer(min_txt, np.uint8)
    return jnp.where((v == i64_min)[:, None], jnp.asarray(min_row)[None, :],
                     out)


def format_date_matrix(days: jnp.ndarray) -> jnp.ndarray:
    """date32 -> 'yyyy-MM-dd' char matrix [N, 10]."""
    from .datetime import _civil_from_days
    y, mo, d = _civil_from_days(days.astype(jnp.int64))

    def dig(x, p):
        return ((x // p) % 10 + ord("0")).astype(jnp.int16)
    cols = [dig(y, 1000), dig(y, 100), dig(y, 10), dig(y, 1),
            jnp.full_like(y, ord("-")).astype(jnp.int16),
            dig(mo, 10), dig(mo, 1),
            jnp.full_like(y, ord("-")).astype(jnp.int16),
            dig(d, 10), dig(d, 1)]
    return jnp.stack(cols, axis=1)


def parse_date_matrix(m: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """'yyyy-MM-dd' / 'yyyy-M-d' -> (days int32, valid)."""
    from .datetime import _days_from_civil
    m, ln = _trimmed(m)
    y, mo, d, pos_after, ok = _parse_ymd(m, ln)
    valid = ok & (pos_after == ln)
    days = _days_from_civil(y, mo, d)
    return days.astype(jnp.int32), valid


def _parse_int_run(m, start, max_digits):
    """Parse up to max_digits digits from per-row ``start``: returns
    (value int64, n_digits, next_pos)."""
    n, w = m.shape
    acc = jnp.zeros(n, jnp.int64)
    cnt = jnp.zeros(n, jnp.int32)
    for k in range(max_digits):
        pos = jnp.clip(start + k, 0, w - 1)
        c = jnp.take_along_axis(m, pos[:, None], axis=1)[:, 0]
        is_d = (c >= ord("0")) & (c <= ord("9")) & (start + k < w) \
            & (cnt == k)
        acc = jnp.where(is_d, acc * 10 + (c - ord("0")).astype(jnp.int64),
                        acc)
        cnt = jnp.where(is_d, cnt + 1, cnt)
    return acc, cnt, start + cnt


def _expect_char(m, pos, ch):
    c = jnp.take_along_axis(m, jnp.clip(pos, 0, m.shape[1] - 1)[:, None],
                            axis=1)[:, 0]
    return c == ord(ch)


def _parse_ymd(m, ln):
    y, yd, p = _parse_int_run(m, jnp.zeros(m.shape[0], jnp.int32), 4)
    ok = (yd == 4) & _expect_char(m, p, "-")
    mo, md, p2 = _parse_int_run(m, p + 1, 2)
    ok = ok & (md >= 1) & _expect_char(m, p2, "-")
    d, dd, p3 = _parse_int_run(m, p2 + 1, 2)
    ok = ok & (dd >= 1) & (mo >= 1) & (mo <= 12) & (d >= 1)
    # Calendar-exact day bound (Feb 29 only in leap years, Apr 31 invalid,
    # ...) — the CPU oracle parses via date.fromisoformat which rejects
    # these, so the device must too.
    dim = jnp.asarray([31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31],
                      jnp.int32)
    leap = ((y % 4 == 0) & (y % 100 != 0)) | (y % 400 == 0)
    max_d = dim[jnp.clip(mo - 1, 0, 11)] + (leap & (mo == 2))
    ok = ok & (d <= max_d)
    return y, mo, d, p3, ok


def parse_timestamp_matrix(m: jnp.ndarray
                           ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """'yyyy-MM-dd[ HH:mm:ss[.f{1..6}]]' -> (micros int64, valid)."""
    from .datetime import _days_from_civil
    m, ln = _trimmed(m)
    n, w = m.shape
    y, mo, d, p, ok = _parse_ymd(m, ln)
    days = _days_from_civil(y, mo, d)
    date_only = ok & (p == ln)
    sep_ok = _expect_char(m, p, " ") | _expect_char(m, p, "T")
    hh, hd, p1 = _parse_int_run(m, p + 1, 2)
    ok_h = ok & sep_ok & (hd >= 1) & (hh < 24)
    hour_only = ok_h & (p1 == ln)
    has_min = _expect_char(m, p1, ":")
    mi, mid, p2 = _parse_int_run(m, p1 + 1, 2)
    ok_m = ok_h & has_min & (mid >= 1) & (mi < 60)
    min_only = ok_m & (p2 == ln)
    has_sec = _expect_char(m, p2, ":")
    ss, sd, p3 = _parse_int_run(m, p2 + 1, 2)
    ok_s = ok_m & has_sec & (sd >= 1) & (ss < 60)
    has_frac = _expect_char(m, p3, ".")
    fr, fd, p4 = _parse_int_run(m, p3 + 1, 6)
    # scale fraction to microseconds by digit count
    scale = jnp.power(10.0, (6 - fd).astype(jnp.float64)).astype(jnp.int64)
    micros_frac = jnp.where(has_frac, fr * scale, 0)
    end = jnp.where(has_frac, p4, p3)
    full_ok = ok_s & (end == ln) & (~has_frac | (fd >= 1))
    mi = jnp.where(ok_m, mi, 0)
    ss = jnp.where(ok_s, ss, 0)
    micros = days.astype(jnp.int64) * 86_400_000_000 \
        + hh * 3_600_000_000 + mi * 60_000_000 + ss * 1_000_000 \
        + jnp.where(full_ok, micros_frac, 0)
    date_micros = days.astype(jnp.int64) * 86_400_000_000
    valid = date_only | hour_only | min_only | full_ok
    return jnp.where(date_only, date_micros, micros), valid


def format_timestamp_matrix(us: jnp.ndarray) -> jnp.ndarray:
    """micros -> 'yyyy-MM-dd HH:mm:ss[.ffffff]' (trailing zeros trimmed),
    char matrix [N, 26]."""
    from .datetime import _civil_from_days
    days = jnp.floor_divide(us, 86_400_000_000)
    rem = us - days * 86_400_000_000
    y, mo, d = _civil_from_days(days)
    hh = rem // 3_600_000_000
    mi = (rem // 60_000_000) % 60
    ss = (rem // 1_000_000) % 60
    frac = rem % 1_000_000

    def dig(x, p):
        return ((x // p) % 10 + ord("0")).astype(jnp.int16)

    def lit(ch):
        return jnp.full(us.shape[0], ord(ch), jnp.int16)
    cols = [dig(y, 1000), dig(y, 100), dig(y, 10), dig(y, 1), lit("-"),
            dig(mo, 10), dig(mo, 1), lit("-"), dig(d, 10), dig(d, 1),
            lit(" "), dig(hh, 10), dig(hh, 1), lit(":"),
            dig(mi, 10), dig(mi, 1), lit(":"), dig(ss, 10), dig(ss, 1),
            lit("."),
            dig(frac, 100000), dig(frac, 10000), dig(frac, 1000),
            dig(frac, 100), dig(frac, 10), dig(frac, 1)]
    m = jnp.stack(cols, axis=1)
    # Trim: no frac -> length 19; else 20 + digits up to last nonzero.
    idx = jnp.arange(26, dtype=jnp.int32)[None, :]
    frac_digits = jnp.where(
        frac == 0, 0,
        6 - _trailing_zeros6(frac))
    total = jnp.where(frac == 0, 19, 20 + frac_digits)
    return jnp.where(idx < total[:, None], m, PAD)


def _trailing_zeros6(frac: jnp.ndarray) -> jnp.ndarray:
    tz = jnp.zeros(frac.shape[0], jnp.int32)
    rest = frac
    done = frac == 0
    for _ in range(6):
        is_z = (rest % 10 == 0) & ~done
        tz = tz + is_z
        done = done | ~is_z
        rest = jnp.where(is_z, rest // 10, rest)
    return tz


_TRUE_WORDS = ("true", "t", "yes", "y", "1")
_FALSE_WORDS = ("false", "f", "no", "n", "0")


def parse_bool_matrix(m: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    m, ln = _trimmed(m)
    lower = jnp.where((m >= ord("A")) & (m <= ord("Z")), m + 32, m)

    def word_eq(word: str):
        w = m.shape[1]
        if len(word) > w:
            return jnp.zeros(m.shape[0], jnp.bool_)
        row = np.full(w, PAD, np.int16)
        row[: len(word)] = [ord(c) for c in word]
        return jnp.all(lower == jnp.asarray(row)[None, :], axis=1)
    is_true = jnp.zeros(m.shape[0], jnp.bool_)
    is_false = jnp.zeros(m.shape[0], jnp.bool_)
    for wd in _TRUE_WORDS:
        is_true = is_true | word_eq(wd)
    for wd in _FALSE_WORDS:
        is_false = is_false | word_eq(wd)
    return is_true, is_true | is_false


# ---------------------------------------------------------------------------
# Fixed-width custom timestamp patterns (non-default formats)
# ---------------------------------------------------------------------------

#: Java pattern token -> (field width, strftime directive)
_TS_TOKENS = [("yyyy", 4, "%Y"), ("MM", 2, "%m"), ("dd", 2, "%d"),
              ("HH", 2, "%H"), ("mm", 2, "%M"), ("ss", 2, "%S")]


def compile_ts_pattern(fmt: str):
    """Compile a Java time pattern into fixed positions, or None when the
    pattern is outside the supported fixed-width subset.

    Supported: the yyyy/MM/dd/HH/mm/ss tokens (each at most once, year +
    month + day required) joined by non-alphabetic single-char literals —
    the same fixed-format stance the reference takes for its timestamp
    parsing (GpuUnixTimestamp; docs/compatibility.md date gates), extended
    from one hardcoded pattern to the whole fixed-width family. Returns
    (fields, total_len, strftime_fmt) with fields as (token, pos, width) /
    ('lit', pos, char).
    """
    fields, i, strf = [], 0, []
    seen = set()
    while i < len(fmt):
        for tok, width, directive in _TS_TOKENS:
            if fmt.startswith(tok, i):
                if tok in seen:
                    return None
                seen.add(tok)
                fields.append((tok, i, width))
                strf.append(directive)
                i += width
                break
        else:
            ch = fmt[i]
            if ch.isalpha():
                return None
            fields.append(("lit", i, ch))
            strf.append(ch)
            i += 1
    if not {"yyyy", "MM", "dd"} <= seen:
        return None
    return fields, len(fmt), "".join(strf)


def parse_timestamp_pattern(m: jnp.ndarray, fmt: str
                            ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Strict fixed-width parse of ``fmt`` -> (micros int64, valid).

    Every field must have exactly its width in digits, every literal must
    match, and the (trimmed) string length must equal the pattern length.
    Calendar validity is exact: the parsed (y, m, d) must round-trip
    through the epoch-day conversion."""
    from .datetime import _civil_from_days, _days_from_civil
    spec = compile_ts_pattern(fmt)
    assert spec is not None, f"unsupported timestamp pattern {fmt!r}"
    fields, total, _ = spec
    m, ln = _trimmed(m)
    n = m.shape[0]
    ok = ln == total
    vals = {"yyyy": None, "MM": None, "dd": None,
            "HH": 0, "mm": 0, "ss": 0}
    for tok, pos, width in fields:
        if tok == "lit":
            ok = ok & _expect_char(m, jnp.full(n, pos, jnp.int32), width)
            continue
        v, nd, _ = _parse_int_run(m, jnp.full(n, pos, jnp.int32), width)
        ok = ok & (nd == width)
        vals[tok] = v
    y, mo, d = vals["yyyy"], vals["MM"], vals["dd"]
    hh, mi, ss = vals["HH"], vals["mm"], vals["ss"]
    days = _days_from_civil(y, mo, d)
    y2, m2, d2 = _civil_from_days(days)
    ok = ok & (y2 == y) & (m2 == mo) & (d2 == d)
    for v, hi in ((hh, 24), (mi, 60), (ss, 60)):
        if not isinstance(v, int):
            ok = ok & (v >= 0) & (v < hi)
    def _us(v, mult):
        return (v if isinstance(v, int) else v) * mult
    micros = days.astype(jnp.int64) * 86_400_000_000 \
        + _us(hh, 3_600_000_000) + _us(mi, 60_000_000) \
        + _us(ss, 1_000_000)
    return jnp.where(ok, micros, 0), ok


def format_timestamp_pattern(us: jnp.ndarray, fmt: str) -> jnp.ndarray:
    """micros -> fixed-width ``fmt`` char matrix [N, len(fmt)]."""
    from .datetime import _civil_from_days
    spec = compile_ts_pattern(fmt)
    assert spec is not None, f"unsupported timestamp pattern {fmt!r}"
    fields, total, _ = spec
    days = jnp.floor_divide(us, 86_400_000_000)
    rem = us - days * 86_400_000_000
    y, mo, d = _civil_from_days(days)
    parts = {"yyyy": y, "MM": mo, "dd": d,
             "HH": rem // 3_600_000_000,
             "mm": (rem // 60_000_000) % 60,
             "ss": (rem // 1_000_000) % 60}

    def dig(x, p):
        return ((x // p) % 10 + ord("0")).astype(jnp.int16)

    cols = [None] * total
    for tok, pos, width in fields:
        if tok == "lit":
            cols[pos] = jnp.full(us.shape[0], ord(width), jnp.int16)
            continue
        v = parts[tok]
        for k in range(width):
            cols[pos + k] = dig(v, 10 ** (width - 1 - k))
    return jnp.stack(cols, axis=1)
